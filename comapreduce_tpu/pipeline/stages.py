"""Pipeline stages: the ``PipelineFunction`` contract over the TPU ops.

Each stage is a small dataclass with the reference protocol
(``Analysis/Running.py:51-80``): ``__call__(data, level2) -> STATE``,
``groups`` (output HDF5 groups, drive the ``contains``/``overwrite``
resume), and ``save_data`` (``(datasets, attributes)`` deposited into the
Level-2 store by ``COMAPLevel2.update``). The heavy math lives in
:mod:`comapreduce_tpu.ops`; stages do host-side orchestration only (lazy
HDF5 reads, shape bookkeeping), so everything device-side stays jitted.

Registered stages (name -> reference counterpart):

- ``CheckLevel1File``      — ``Level1Averaging.py:324-356``
- ``AssignLevel1Data``     — ``Level2Data.py:26-68``
- ``MeasureSystemTemperature`` — ``VaneCalibration.py:21-198``
- ``SkyDip``               — ``Level1Averaging.py:48-155``
- ``AtmosphereRemoval``    — ``Level1Averaging.py:188-234``
- ``Level1AveragingGainCorrection`` — ``Level1Averaging.py:499-943``
- ``Spikes``               — ``Statistics.py:30-104``
- ``Level2FitPowerSpectrum`` / ``NoiseStatistics``
                           — ``Level2Data.py:246-329`` / ``Statistics.py:106-224``
- ``WriteLevel2Data``      — ``Level2Data.py:113-139``
- ``Level2Timelines``      — ``Level2Data.py:142-223``
"""

from __future__ import annotations

import functools
import logging
import os
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from comapreduce_tpu.ops import power as power_ops
from comapreduce_tpu.ops import vane as vane_ops
from comapreduce_tpu.ops.atmosphere import fit_atmosphere_segments
from comapreduce_tpu.ops.average import edge_channel_mask, frequency_bin
from comapreduce_tpu.ops.reduce import (ReduceConfig, ShapeBuckets,
                                        pad_scan_geometry, pad_time_axis,
                                        plan_reduce_memory,
                                        scan_starts_lengths,
                                        stage_feed_batches)
from comapreduce_tpu.ops.spikes import spike_mask
from comapreduce_tpu.ops.stats import auto_rms
from comapreduce_tpu.data.scan_edges import segment_ids_from_edges
from comapreduce_tpu.pipeline.registry import register

__all__ = ["CheckLevel1File", "AssignLevel1Data", "UseLevel2Pointing",
           "MeasureSystemTemperature", "SkyDip", "AtmosphereRemoval",
           "Level1Averaging", "Level1AveragingGainCorrection", "Spikes",
           "Level2FitPowerSpectrum", "NoiseStatistics", "WriteLevel2Data",
           "Level2Timelines", "mean_vane_tsys_gain", "bucket_scan_lengths",
           "first_fitted_scan"]

logger = logging.getLogger("comapreduce_tpu")


@dataclass
class _StageBase:
    """Shared stage state: outputs staged for ``COMAPLevel2.update``."""

    overwrite: bool = False
    STATE: bool = True
    groups: tuple = ()
    # campaign shape-canonicalisation policy (ops.reduce.ShapeBuckets |
    # dict | None = off). Set by the Runner from the [campaign] table:
    # stages that launch shape-specialised device programs pad each
    # observation up to its campaign bucket (masked tails, zero-length
    # scans) so a whole filelist shares one compiled program set per
    # bucket instead of recompiling per file (docs/OPERATIONS.md §9)
    shape_buckets: object = None
    # end-to-end precision policy (ops.precision.PrecisionPolicy |
    # None = identity). Set by the Runner from the [precision] table.
    # Stages need no per-dtype code: a bf16 TOD payload device_puts as
    # jnp.bfloat16 and the fused reduce chains widen to f32 at first
    # arithmetic touch (docs/OPERATIONS.md §15); the knob is carried
    # here so stage code CAN consult it (e.g. to size feed batches by
    # the narrowed payload bytes)
    precision: object = None
    _data: dict = field(default_factory=dict, repr=False)
    _attrs: dict = field(default_factory=dict, repr=False)

    @property
    def name(self) -> str:
        return type(self).__name__

    @property
    def save_data(self):
        return self._data, self._attrs

    def pre_init(self, data) -> None:  # hook parity (Running.py:141)
        pass

    def clear_outputs(self) -> None:
        """Drop staged outputs; the runner calls this before each file so a
        failing stage can never deposit the previous file's results."""
        self._data = {}
        self._attrs = {}

    def __str__(self) -> str:
        return self.name


@register(backend="any")
@dataclass
class CheckLevel1File(_StageBase):
    """Gate: reject too-short files and operator-flagged observations.

    Parity: ``CheckLevel1File`` (``Level1Averaging.py:324-356``) — files
    under ``min_duration_seconds`` or whose comment marks a sky dip/test
    abort the stage chain (falsy STATE). Always runs (stateless)."""

    min_duration_seconds: float = 300.0
    bad_keywords: tuple = ("sky dip", "skydip", "sky nod", "test")
    overwrite: bool = True

    def __call__(self, data, level2) -> bool:
        import re

        mjd = data.mjd
        duration = float(mjd[-1] - mjd[0]) * 86400.0
        comment = data.comment.lower()
        # word-boundary match: 'test' must not fire on 'latest'
        bad = next((k for k in self.bad_keywords
                    if re.search(rf"\b{re.escape(k)}\b", comment)), None)
        self.STATE = True
        if duration < self.min_duration_seconds:
            logger.info("CheckLevel1File: obs %s too short (%.0f s)",
                        data.obsid, duration)
            self.STATE = False
        elif bad is not None:
            logger.info("CheckLevel1File: obs %s flagged (%r in comment)",
                        data.obsid, bad)
            self.STATE = False
        return self.STATE


@register(backend="any")
@dataclass
class AssignLevel1Data(_StageBase):
    """Copy pointing and metadata from Level-1 into the Level-2 store
    (parity: ``AssignLevel1Data``, ``Level2Data.py:26-68``)."""

    groups: tuple = ("spectrometer",)

    def __call__(self, data, level2) -> bool:
        self._data = {
            "spectrometer/MJD": data.mjd,
            "spectrometer/feeds": data.feeds,
            "spectrometer/features": data.materialise("spectrometer/features"),
            "spectrometer/frequency": data.frequency,
            "spectrometer/pixel_pointing/pixel_ra": np.asarray(data.ra),
            "spectrometer/pixel_pointing/pixel_dec": np.asarray(data.dec),
            "spectrometer/pixel_pointing/pixel_az": np.asarray(data.az),
            "spectrometer/pixel_pointing/pixel_el": np.asarray(data.el),
        }
        self._attrs = {"comap": {
            "obsid": data.obsid,
            "source": data.attrs("comap", "source"),
            "comment": data.comment,
        }}
        self.STATE = True
        return True


@register(backend="any")
@dataclass
class UseLevel2Pointing(_StageBase):
    """Re-read pointing from an existing Level-2 file into both the Level-1
    view and the Level-2 store (parity: ``UseLevel2Pointing``,
    ``Level2Data.py:71-110`` — used when pointing was re-solved offline and
    written back to Level-2). Acts only when ``overwrite`` is set AND the
    Level-2 file already exists (reference behavior); otherwise a no-op."""

    overwrite: bool = False

    def __call__(self, data, level2) -> bool:
        self.STATE = True
        if not self.overwrite:
            return True
        if not os.path.exists(level2.filename):
            return True
        import h5py

        with h5py.File(level2.filename, "r") as h:
            base = "spectrometer/pixel_pointing"
            if f"{base}/pixel_ra" not in h:
                logger.warning("UseLevel2Pointing: %s has no pointing",
                               level2.filename)
                return True
            ra = h[f"{base}/pixel_ra"][...]
            dec = h[f"{base}/pixel_dec"][...]
            az = h[f"{base}/pixel_az"][...]
            el = h[f"{base}/pixel_el"][...]
        for store in (data, level2):
            store.ra = ra
            store.dec = dec
            store.az = az
            store.el = el
        # ordering check the reference lacks: products derived from the
        # OLD pointing (airmass fits, the reduction) already sit in the
        # checkpointed store — re-solving the pointing without re-running
        # them silently mixes epochs. The per-stage resume makes the fix
        # one overwrite flag away, so say so loudly.
        stale = [g for g in ("skydip", "atmosphere", "averaged_tod")
                 if level2.contains_groups((g,))]
        if stale:
            logger.warning(
                "UseLevel2Pointing: %s in %s were computed from the "
                "PREVIOUS pointing; re-run those stages with "
                "overwrite=True to refresh them", ", ".join(stale),
                os.path.basename(level2.filename))
        return True


@register()
@dataclass
class MeasureSystemTemperature(_StageBase):
    """Vane calibration: per-channel system temperature and gain per vane
    event (parity: ``VaneCalibration.py:21-198``). Writes
    ``vane/system_temperature`` and ``vane/system_gain``, each
    ``(n_events, F, B, C)``."""

    groups: tuple = ("vane",)
    pad: int = 50
    figure_dir: str = ""

    def __call__(self, data, level2) -> bool:
        tod = data["spectrometer/tod"]

        def reader(s, e):
            return tod[..., s:e]

        tsys, gain = vane_ops.measure_system_temperature(
            reader, data.vane_flag, data.vane_temperature, pad=self.pad)
        if tsys is None:
            logger.warning("MeasureSystemTemperature: obs %s has no vane "
                           "events", data.obsid)
            self.STATE = False
            return False
        self._data = {
            "vane/system_temperature": np.asarray(tsys),
            "vane/system_gain": np.asarray(gain),
        }
        if self.figure_dir:
            self._plot(data, reader, np.asarray(tsys))
        self.STATE = True
        return True

    def _plot(self, data, reader, tsys):
        """First vane event, feed 0: hot/cold selection + Tsys
        (``VaneCalibration.py:173-190``)."""
        from comapreduce_tpu import diagnostics

        events = vane_ops.find_vane_events(data.vane_flag)
        if not len(events):
            return
        n = len(data.vane_flag)
        s = max(0, int(events[0][0]) - self.pad)
        e = min(n, int(events[0][1]) + self.pad)
        ev = np.asarray(reader(s, e), dtype=np.float32)[0]  # (B, C, t)
        band_avg = ev.mean(axis=1)
        hot, cold = vane_ops.hot_cold_masks(band_avg)
        diagnostics.plot_vane_event(
            diagnostics.figure_path(self.figure_dir, data.obsid,
                                    "vane_feed00_event00"),
            band_avg, np.asarray(hot), np.asarray(cold), tsys[0, 0],
            feed=0)


def _stage_buckets(stage) -> ShapeBuckets:
    """The stage's campaign shape policy (identity when unset)."""
    return ShapeBuckets.coerce(getattr(stage, "shape_buckets", None))


def _stage_donate(argnums: tuple) -> tuple:
    """Donate the raw-counts buffer on accelerator backends only: CPU
    jit ignores donation and warns once per compile — pytest noise for
    zero benefit. On device, donation lets XLA reuse the 2.2 GB/feed
    input allocation in place (the NaN-filled copy aliases the raw
    counts instead of doubling residency)."""
    return argnums if jax.default_backend() != "cpu" else ()


def _warm_compile(name: str, fn, *args, **kwargs):
    """AOT lower+compile one warmup program AND feed the compiled
    executable's cost/memory analysis to the program registry
    (``telemetry.programs`` — flops, bytes accessed, HBM footprint per
    program x shape bucket, docs/OPERATIONS.md §17). The warmup already
    pays the compile; the registry just stops discarding the result."""
    compiled = fn.lower(*args, **kwargs).compile()
    try:
        from comapreduce_tpu.telemetry.programs import (PROGRAMS,
                                                        shape_bucket)

        PROGRAMS.record(name, compiled,
                        shape_bucket=shape_bucket(*args, **kwargs))
    except Exception:   # the registry observes; it never breaks warmup
        pass
    return compiled


@functools.lru_cache(maxsize=32)
def _batched_atmosphere_fit(n_scans: int):
    """Cached jitted whole-batch atmosphere fit (one compile per scan
    count, not one per file): ONE dispatch per feed chunk, feeds
    streamed by ``lax.map`` so the working set stays one feed's blocks
    while the planner-sized chunk's raw counts are resident (donated —
    see ``_stage_donate``). Takes NaN-carrying raw counts and a
    per-feed time mask (f32[n_feeds, T], or [n_feeds, 1] for all-on);
    validity is derived on device so the host never builds or ships a
    dense (B, C, T) mask."""
    def fit_all(raw, airmass, seg, tmask):
        def one(args):
            r, a, tm = args
            mask = jnp.isfinite(r).astype(jnp.float32) * tm
            return fit_atmosphere_segments(jnp.nan_to_num(r), a, seg,
                                           mask, n_scans=n_scans)

        return jax.lax.map(one, (raw, airmass, tmask))

    return jax.jit(fit_all, donate_argnums=_stage_donate((0,)))


def apply_fleet_channel_mask(tsys, db_file: str, obsid: int):
    """ONE home for the stages' fleet-mask hook: zero fleet-masked
    channels' Tsys (== zero weight) when ``db_file`` is configured;
    no-op on the empty default."""
    if not db_file:
        return tsys
    from comapreduce_tpu.database.normalised_mask import apply_mask_to_tsys

    return apply_mask_to_tsys(tsys, db_file, obsid)


def mean_vane_tsys_gain(level2):
    """Event-averaged (tsys, gain), each f32[F, B, C]; zeros stay zero.

    Channels a vane event failed to calibrate hold 0; averaging counts only
    the valid events per channel (the reference indexes a single event
    instead, ``Level1Averaging.py:592-599``)."""
    tsys = np.asarray(level2.system_temperature, dtype=np.float32)
    gain = np.asarray(level2.system_gain, dtype=np.float32)
    ok_t = (tsys > 0).sum(axis=0)
    ok_g = (gain > 0).sum(axis=0)
    tsys_m = tsys.sum(axis=0) / np.maximum(ok_t, 1)
    gain_m = gain.sum(axis=0) / np.maximum(ok_g, 1)
    return tsys_m, gain_m


@register()
@dataclass
class SkyDip(_StageBase):
    """Per-channel linear fit of TOD against airmass -> ``skydip/fits``
    (F, B, 2, C): [offset, slope-vs-airmass].

    Two modes (parity: ``SkyDip``, ``Level1Averaging.py:48-155``):

    - default: fit the CURRENT file's elevation coverage (useful for CES
      scans with an elevation swing);
    - ``sky_nod_obsid`` >= 0 or ``sky_nod_file`` set: the reference's
      actual sky-dip workflow — fit the PRIOR observation's sky-nod.
      ``sky_nod_obsid=0`` means "the observation before this one"
      (the reference's hardwired ``obsid - 1`` lookup); a positive value
      or an explicit file pins it. The sky-nod TOD is divided by the
      current vane gain and restricted to the reference's elevation
      window before the per-channel airmass regression. A missing or
      non-sky-nod prior file is a logged no-op, like the reference.
    """

    groups: tuple = ("skydip",)
    # feeds per device batch: 0 = auto — the HBM planner
    # (ops.reduce.plan_stage_feed_batch) picks the largest chunk that
    # fits, so the whole observation is ONE dispatch wherever the raw
    # counts fit device memory; a positive value is an upper bound
    feed_batch: int = 0
    # prior-observation sky-nod mode (-1 = off -> fit the current file)
    sky_nod_obsid: int = -1
    sky_nod_file: str = ""
    # elevation window of the sky-nod fit (Level1Averaging.py:124)
    el_min: float = 40.0
    el_max: float = 55.0
    figure_dir: str = ""

    def __call__(self, data, level2) -> bool:
        self.STATE = True
        if self.sky_nod_file or self.sky_nod_obsid >= 0:
            return self._fit_sky_nod(data, level2)
        fits = self._fit_file(data, gain=None,
                              tmask=~np.asarray(data.vane_flag))
        self._data = {"skydip/fits": fits}  # (F, B, 2, C)
        self._plot(data, fits)
        return True

    def _plot(self, data, fits: np.ndarray) -> None:
        """Feed-0 offset/slope vs frequency (the reference's per-feed
        sky-dip figure, ``Level1Averaging.py:137-155``)."""
        if not self.figure_dir:
            return
        from comapreduce_tpu import diagnostics

        diagnostics.plot_skydip_fit(
            diagnostics.figure_path(self.figure_dir, data.obsid,
                                    "skydip_feed00"),
            np.asarray(data.frequency), fits[0], feed=0)

    def _fit_file(self, data, gain, tmask) -> np.ndarray:
        """Per-channel (offset, slope-vs-airmass) over ``tmask``-selected
        samples of ``data``; ``gain`` (F, B, C) divides the counts into
        kelvin when given (the sky-nod mode)."""
        F, B, C, T = (int(x) for x in data.tod_shape)
        # campaign bucket: the padded tail ships as NaN (zero validity)
        # with a zero time mask, so the fit is unchanged while every
        # same-bucket file reuses ONE compiled program
        Tb = _stage_buckets(self).round_T(T)
        tmask = np.broadcast_to(np.asarray(tmask), (F, T))
        seg = np.zeros(Tb, np.int32)  # one global segment; masking via
        seg_j = jnp.asarray(seg)      # the per-feed time mask
        airmass_all = np.asarray(data.airmass).astype(np.float32)
        fit = _batched_atmosphere_fit(1)
        fits = np.zeros((F, B, 2, C), np.float32)
        for idx in stage_feed_batches(F, B, C, Tb, self.feed_batch):
            raw = np.stack([np.asarray(data.read_tod_feed(j),
                                       dtype=np.float32) for j in idx])
            if gain is not None:
                g = gain[idx][..., None]
                raw = np.where(g > 0, raw / np.where(g > 0, g, 1.0), np.nan)
            off, slope = fit(jnp.asarray(pad_time_axis(raw, Tb)),
                             jnp.asarray(pad_time_axis(
                                 airmass_all[idx], Tb, fill="edge")),
                             seg_j,
                             jnp.asarray(pad_time_axis(
                                 tmask[idx].astype(np.float32), Tb,
                                 fill="zero")))
            fits[idx] = np.stack([np.asarray(off)[..., 0],
                                  np.asarray(slope)[..., 0]], axis=-2)
        return fits

    def warm_programs(self, F, B, C, T, S, L, calibrator=False):
        """AOT-compile this stage's device programs for one campaign
        bucket (the ``pipeline.campaign.Warmup`` hook): the lax.map
        atmosphere fit at the canonical padded time axis, one compile
        per distinct feed-chunk size. Reaches the run through the
        persistent compile cache (docs/OPERATIONS.md §9)."""
        del S, L, calibrator   # the sky-dip fit is one global segment
        F, B, C = int(F), int(B), int(C)
        Tb = _stage_buckets(self).round_T(int(T))
        fit = _batched_atmosphere_fit(1)
        f32, i32 = jnp.float32, jnp.int32
        for f in sorted({len(idx) for idx in
                         stage_feed_batches(F, B, C, Tb,
                                            self.feed_batch)}):
            _warm_compile("skydip.atmosphere_fit", fit,
                          jax.ShapeDtypeStruct((f, B, C, Tb), f32),
                          jax.ShapeDtypeStruct((f, Tb), f32),
                          jax.ShapeDtypeStruct((Tb,), i32),
                          jax.ShapeDtypeStruct((f, Tb), f32))

    def _fit_sky_nod(self, data, level2) -> bool:
        from comapreduce_tpu.data.level import (COMAPLevel1,
                                                find_level1_by_obsid)

        path = self.sky_nod_file
        if not path:
            target = (data.obsid - 1 if self.sky_nod_obsid == 0
                      else self.sky_nod_obsid)
            path = find_level1_by_obsid(
                os.path.dirname(data.source_filename) or ".", target)
            if path is None:
                logger.info("SkyDip: no file for obsid %s; skipping",
                            target)
                return True
        prev = COMAPLevel1()
        try:
            prev.read(path)
        except OSError as exc:
            # an unreadable/missing prior file is a logged no-op, like the
            # reference's silent return — it must not kill a field run
            logger.warning("SkyDip: cannot read sky-nod %s (%s); skipping",
                           path, exc)
            return True
        comment = prev.comment.lower()
        if "sky nod" not in comment and "sky dip" not in comment:
            logger.info("SkyDip: %s is not a sky-nod (comment %r); "
                        "skipping", path, prev.comment)
            return True
        try:
            _, gain = mean_vane_tsys_gain(level2)
        except KeyError:
            logger.warning("SkyDip: obs %s has no vane calibration",
                           data.obsid)
            self.STATE = False
            return False
        if tuple(prev.tod_shape[:3]) != gain.shape:
            # the current vane gain can only normalise a sky-nod recorded
            # with the same (feeds, bands, channels) layout
            logger.warning("SkyDip: sky-nod %s shape %s does not match "
                           "the current gain %s; skipping", path,
                           tuple(prev.tod_shape[:3]), gain.shape)
            self.STATE = False
            return False
        el = np.asarray(prev.el, dtype=np.float32)  # (F, T)
        tmask = (el > self.el_min) & (el < self.el_max) \
            & ~np.asarray(prev.vane_flag)[None, :]
        if not tmask.any():
            logger.warning("SkyDip: sky-nod %s has no samples in the "
                           "%.0f-%.0f deg window", path, self.el_min,
                           self.el_max)
            self.STATE = False
            return False
        fits = self._fit_file(prev, gain=gain, tmask=tmask)
        self._data = {"skydip/fits": fits}
        self._attrs = {"skydip": {"sky_nod_obsid": prev.obsid,
                                  "sky_nod_file": os.path.basename(path)}}
        self._plot(prev, fits)
        return True


@register()
@dataclass
class AtmosphereRemoval(_StageBase):
    """Per-(scan, feed, band, channel) regression of the TOD against
    airmass; stores coefficients only (subtraction happens in the
    reduction). Parity: ``AtmosphereRemoval`` (``Level1Averaging.py:
    188-234``), which stores ``atmosphere/fit_values`` (S, F, B, 2, C)."""

    groups: tuple = ("atmosphere",)
    # feeds per device batch: 0 = auto via the HBM planner (see SkyDip)
    feed_batch: int = 0

    def __call__(self, data, level2) -> bool:
        edges = data.scan_edges
        if len(edges) == 0:
            logger.warning("AtmosphereRemoval: obs %s has no scans",
                           data.obsid)
            self.STATE = False
            return False
        S = len(edges)
        T = int(data.tod_shape[-1])
        # campaign bucket: pad T (NaN tail -> zero validity, segment id
        # 0 with zero weight) and S (segments S..Sb-1 own no samples;
        # their fit rows are sliced off) so same-bucket files share one
        # compiled program
        bk = _stage_buckets(self)
        Tb, Sb = bk.round_T(T), bk.round_S(S)
        seg = segment_ids_from_edges(edges, T).astype(np.int32)
        seg_j = jnp.asarray(pad_time_axis(seg, Tb, fill="zero"))
        F, B, C, _ = data.tod_shape
        airmass_all = np.asarray(data.airmass).astype(np.float32)
        fit = _batched_atmosphere_fit(Sb)
        out = np.zeros((S, F, B, 2, C), np.float32)
        for idx in stage_feed_batches(F, B, C, Tb, self.feed_batch):
            raw = np.stack([np.asarray(data.read_tod_feed(j),
                                       dtype=np.float32) for j in idx])
            off, atm = fit(jnp.asarray(pad_time_axis(raw, Tb)),
                           jnp.asarray(pad_time_axis(
                               airmass_all[idx], Tb, fill="edge")),
                           seg_j,
                           jnp.ones((len(idx), 1), jnp.float32))
            # (f, B, C, Sb) pair -> (Sb, f, B, 2, C) -> first S scans
            blk = np.stack([np.asarray(off), np.asarray(atm)], axis=0)
            out[:, idx] = np.transpose(blk, (4, 1, 2, 0, 3))[:S]
        self._data = {"atmosphere/fit_values": out}
        self.STATE = True
        return True

    def warm_programs(self, F, B, C, T, S, L, calibrator=False):
        """AOT-compile the per-scan atmosphere fit for one campaign
        bucket (see ``SkyDip.warm_programs``)."""
        del L, calibrator
        if int(S) == 0:
            return
        F, B, C = int(F), int(B), int(C)
        bk = _stage_buckets(self)
        Tb, Sb = bk.round_T(int(T)), bk.round_S(int(S))
        fit = _batched_atmosphere_fit(Sb)
        f32, i32 = jnp.float32, jnp.int32
        for f in sorted({len(idx) for idx in
                         stage_feed_batches(F, B, C, Tb,
                                            self.feed_batch)}):
            _warm_compile("atmosphere.scan_fit", fit,
                          jax.ShapeDtypeStruct((f, B, C, Tb), f32),
                          jax.ShapeDtypeStruct((f, Tb), f32),
                          jax.ShapeDtypeStruct((Tb,), i32),
                          jax.ShapeDtypeStruct((f, 1), f32))


@functools.lru_cache(maxsize=8)
def _batched_frequency_bin(bin_size: int):
    """Cached jitted whole-batch frequency binner: counts / gain, then
    the weighted in-bin mean + stddev (one compile per bin size), feeds
    streamed by ``lax.map`` with the raw counts donated (ONE dispatch
    per planner-sized feed chunk — see ``_batched_atmosphere_fit``).
    NaN-flagged raw samples carry ZERO weight into the bin average (the
    ``mask=None`` ingest policy) rather than averaging in as zeros —
    validity stays a bool operand so no raw-sized f32 weight tensor is
    ever resident (see ``frequency_bin``)."""
    def bin_all(raw, gain, weights):
        def one(args):
            r, g, w = args
            valid = jnp.isfinite(r)
            tod = r / jnp.where(g > 0, g, 1.0)[..., None]
            return frequency_bin(tod, w, bin_size, valid=valid)

        return jax.lax.map(one, (raw, gain, weights))

    return jax.jit(bin_all, donate_argnums=_stage_donate((0,)))


@register()
@dataclass
class Level1Averaging(_StageBase):
    """Plain frequency-binning reduction — NO gain-fluctuation
    correction (parity: ``Level1Averaging.average_tod``,
    ``Level1Averaging.py:292-321``): counts / vane gain, 1/Tsys^2
    weights with the reference's edge + band-centre channels cut, then a
    weighted mean and in-bin standard deviation over
    ``frequency_bin_size``-channel groups.

    Writes ``frequency_binned/{tod, tod_stddev}`` (F, B, C//bin, T) —
    its own group (the reference overwrites the Level-2 copy of
    ``spectrometer/tod`` in place, which would break this runner's
    group-based resume test against ``AssignLevel1Data``)."""

    groups: tuple = ("frequency_binned",)
    frequency_bin_size: int = 512
    # feeds per device batch: 0 = auto via the HBM planner (see SkyDip)
    feed_batch: int = 0
    # obsdb file with fleet date-range channel masks (empty = no fleet
    # cut); masked channels get tsys=0 == zero weight
    normalised_mask_db: str = ""

    def __call__(self, data, level2) -> bool:
        try:
            tsys, gain = mean_vane_tsys_gain(level2)
        except KeyError:
            logger.warning("Level1Averaging: obs %s has no vane "
                           "calibration", data.obsid)
            self.STATE = False
            return False
        tsys = apply_fleet_channel_mask(tsys, self.normalised_mask_db,
                                        data.obsid)
        F, B, C, T = (int(x) for x in data.tod_shape)
        bin_size = min(self.frequency_bin_size, C)
        # the reference's frequency mask: 10 edge channels each end plus
        # the 3 band-centre channels [511:514] (Level1Averaging.py:267-271),
        # scaled with C like the other channel cuts
        def s(n):
            return max(int(round(n * C / 1024.0)), 1)
        chan_mask = np.asarray(edge_channel_mask(C, s(10), s(1), s(2)))
        w = np.where(tsys > 0, 1.0 / np.maximum(tsys, 1e-10) ** 2, 0.0)
        w = (w * chan_mask).astype(np.float32)          # (F, B, C)
        fit = _batched_frequency_bin(bin_size)
        nb = C // bin_size
        # campaign bucket: NaN time tail -> zero bin weight; outputs
        # sliced back to the file's own T
        Tb = _stage_buckets(self).round_T(T)
        tod_out = np.zeros((F, B, nb, T), np.float32)
        std_out = np.zeros((F, B, nb, T), np.float32)
        for idx in stage_feed_batches(F, B, C, Tb, self.feed_batch):
            raw = np.stack([np.asarray(data.read_tod_feed(j),
                                       dtype=np.float32) for j in idx])
            avg, std = fit(jnp.asarray(pad_time_axis(raw, Tb)),
                           jnp.asarray(gain[idx]), jnp.asarray(w[idx]))
            tod_out[idx] = np.asarray(avg)[..., :T]
            std_out[idx] = np.asarray(std)[..., :T]
        self._data = {
            "frequency_binned/tod": tod_out,
            "frequency_binned/tod_stddev": std_out,
            # the plain product must be mappable standalone: the
            # destriper reads scan edges from the Level-2 store (the
            # gain chain writes averaged_tod/scan_edges likewise)
            "frequency_binned/scan_edges": np.asarray(data.scan_edges),
        }
        self.STATE = True
        return True

    def warm_programs(self, F, B, C, T, S, L, calibrator=False):
        """AOT-compile the frequency binner for one campaign bucket
        (see ``SkyDip.warm_programs``)."""
        del S, L, calibrator
        F, B, C = int(F), int(B), int(C)
        Tb = _stage_buckets(self).round_T(int(T))
        bin_size = min(self.frequency_bin_size, C)
        fit = _batched_frequency_bin(bin_size)
        f32 = jnp.float32
        for f in sorted({len(idx) for idx in
                         stage_feed_batches(F, B, C, Tb,
                                            self.feed_batch)}):
            _warm_compile("level1.frequency_bin", fit,
                          jax.ShapeDtypeStruct((f, B, C, Tb), f32),
                          jax.ShapeDtypeStruct((f, B, C), f32),
                          jax.ShapeDtypeStruct((f, B, C), f32))


@register()
@dataclass
class Level1AveragingGainCorrection(_StageBase):
    """The flagship reduction: Level-1 -> Level-2 averaged TOD.

    Feeds are processed in device BATCHES through the fused multi-feed
    program (:func:`~comapreduce_tpu.parallel.sharded.reduce_feeds_sharded`
    — vmap over feeds, feed-sharded over every local device), with the
    next batch's lazy HDF5 read prefetched on a worker thread while the
    device reduces the current one (SURVEY hard part 4: overlap host
    ingest with device compute). The chain per feed: NaN fill, atmosphere
    subtraction, radiometer normalisation, median-filter high-pass,
    gain-fluctuation solve, Tsys-weighted band average. Parity:
    ``Level1AveragingGainCorrection.average_tod``
    (``Level1Averaging.py:792-872``, which loops feeds serially on host).
    Writes ``averaged_tod/{tod, tod_original, weights, scan_edges}``."""

    groups: tuple = ("averaged_tod",)
    medfilt_window: int = 6000
    # None = two-level block-median filter beyond 512-sample windows (fast
    # path, quantified in tests/test_medfilt_parity.py); 1 = exact filter
    medfilt_stride: int | None = None
    pad_to: int = 128
    # feeds per device batch (0 = all feeds in one program). The default
    # fits a 16 GB chip at production shape (F=19, B=4, C=1024, T~135k:
    # ~2.2 GB of raw counts per feed) with scan streaming auto-selected;
    # every config is re-checked against the device HBM budget before
    # dispatch (ops.reduce.plan_reduce_memory), which raises with a
    # suggested feed_batch instead of letting the device OOM.
    feed_batch: int = 2
    # scans streamed per chunk inside the reduction (None = auto: all at
    # once when it fits the HBM budget, else the largest fitting chunk)
    scan_batch: int | None = None
    prefetch: bool = True
    figure_dir: str = ""
    # obsdb file with fleet date-range channel masks (empty = no fleet
    # cut); masked channels get tsys=0 == zero weight in the reduction
    normalised_mask_db: str = ""

    def __call__(self, data, level2) -> bool:
        from comapreduce_tpu.parallel.mesh import feed_time_mesh
        from comapreduce_tpu.parallel.sharded import reduce_feeds_sharded

        edges = np.asarray(data.scan_edges)
        if len(edges) == 0:
            logger.warning("Level1AveragingGainCorrection: obs %s has no "
                           "scans", data.obsid)
            self.STATE = False
            return False
        try:
            tsys, sys_gain = mean_vane_tsys_gain(level2)
        except KeyError:
            logger.warning("Level1AveragingGainCorrection: obs %s has no "
                           "vane calibration", data.obsid)
            self.STATE = False
            return False
        tsys = apply_fleet_channel_mask(tsys, self.normalised_mask_db,
                                        data.obsid)

        F, B, C, T = data.tod_shape
        T = int(T)
        starts, lengths, L = scan_starts_lengths(edges, pad_to=self.pad_to)
        # campaign bucket (docs/OPERATIONS.md §9): T padded with a NaN
        # tail (the mask=None path derives zero validity on device), S
        # padded with zero-length scans (all-masked; the scatter drops
        # every one of their samples), L rounded up on the pad_to grid
        # (masked-tail extract semantics carry any L >= the longest
        # scan). The medfilt window is clamped against the UNPADDED L:
        # padding must never change the filter the real samples see.
        bk = _stage_buckets(self)
        Tb = bk.round_T(T)
        L_raw = L
        L = bk.round_L(L)
        Sb = bk.round_S(len(edges))
        starts, lengths = pad_scan_geometry(starts, lengths, Sb)
        freq = data.frequency.astype(np.float32)  # (B, C) GHz
        f0 = freq.mean(axis=1, keepdims=True)
        freq_scaled = ((freq - f0) / f0).astype(np.float32)
        airmass_all = np.asarray(data.airmass).astype(np.float32)  # (F, T)

        # feed batches padded to a multiple of the local feed-mesh size so
        # every batch shards evenly and compiles once. LOCAL devices only:
        # multi-host runs are data parallel over files (each process has
        # different data), so a global mesh would deadlock its collectives
        local = jax.local_devices()
        mesh = feed_time_mesh(local, n_feed=len(local))
        n_dev = mesh.shape["feed"]
        fb = -(-min(self.feed_batch or F, F) // n_dev) * n_dev
        # HBM budget check on the PER-DEVICE footprint (each device of the
        # feed mesh holds fb/n_dev feeds); auto-picks scan streaming, or
        # raises naming a feed_batch that fits — before the device OOMs
        scan_batch = plan_reduce_memory(fb // n_dev, B, C, Tb, Sb,
                                        L, self.scan_batch,
                                        suggest_scale=n_dev)
        if scan_batch != self.scan_batch:
            logger.info("Level1AveragingGainCorrection: streaming %s "
                        "scans per chunk to fit device memory", scan_batch)
        cfg = ReduceConfig(C,
                           medfilt_window=min(self.medfilt_window, L_raw),
                           is_calibrator=data.is_calibrator,
                           medfilt_stride=self.medfilt_stride,
                           scan_batch=scan_batch)
        batches = [list(range(i, min(i + fb, F))) for i in range(0, F, fb)]

        def load(idx):
            """Read one feed batch from the lazy store (worker thread).

            NaNs ride along: the reduction derives validity on device
            (``mask=None`` path) so neither a dense mask nor a NaN-filled
            copy is built on host."""
            raws = [np.asarray(data.read_tod_feed(i), dtype=np.float32)
                    for i in idx]
            raws += [raws[0]] * (fb - len(idx))        # pad: results dropped
            raw = pad_time_axis(np.stack(raws), Tb)    # NaN bucket tail
            am = pad_time_axis(
                airmass_all[idx + [idx[0]] * (fb - len(idx))], Tb,
                fill="edge")
            return raw, am

        def pad_cal(x, idx):
            sel = x[idx]
            return np.concatenate([sel, np.repeat(sel[:1], fb - len(idx),
                                                  axis=0)])

        tod_out = np.zeros((F, B, T), np.float32)
        orig_out = np.zeros((F, B, T), np.float32)
        wei_out = np.zeros((F, B, T), np.float32)
        starts_j = starts.astype(np.int32)
        lengths_j = lengths.astype(np.int32)

        from concurrent.futures import ThreadPoolExecutor

        dg0 = None
        with ThreadPoolExecutor(max_workers=1) as ex:
            fut = ex.submit(load, batches[0])
            for bi, idx in enumerate(batches):
                raw, am = fut.result()
                if self.prefetch and bi + 1 < len(batches):
                    fut = ex.submit(load, batches[bi + 1])
                res = reduce_feeds_sharded(
                    mesh, raw, None, am, starts_j, lengths_j,
                    pad_cal(tsys, idx), pad_cal(sys_gain, idx),
                    freq_scaled, cfg, L=L,
                    # under a campaign bucket the filter must reflect at
                    # the UNPADDED block length (a dynamic operand, so
                    # every file of the bucket shares one compile) —
                    # windows near a scan's end would otherwise mirror
                    # different samples at different bucket sizes
                    fold_len=L_raw if bk.enabled else None)
                # device -> host copy blocks here while the worker thread
                # reads the next batch from HDF5 (the bucketed tail
                # [T:Tb) holds no scan samples; slice it off)
                tod_out[idx] = np.asarray(res["tod"])[:len(idx), :, :T]
                orig_out[idx] = np.asarray(
                    res["tod_original"])[:len(idx), :, :T]
                wei_out[idx] = np.asarray(res["weights"])[:len(idx), :, :T]
                if bi == 0 and self.figure_dir:
                    dg0 = np.asarray(res["dg"])[0]  # (S, L), feed 0
                if not self.prefetch and bi + 1 < len(batches):
                    fut = ex.submit(load, batches[bi + 1])
        if self.figure_dir and dg0 is not None and len(edges):
            from comapreduce_tpu import diagnostics

            s0, e0 = int(edges[0][0]), int(edges[0][1])
            diagnostics.plot_gain_solution(
                diagnostics.figure_path(self.figure_dir, data.obsid,
                                        "gain_feed00_scan00"),
                tod_out[0, 0, s0:e0], dg0[0][:e0 - s0], feed=0, scan=0)
        self._data = {
            "averaged_tod/tod": tod_out,
            "averaged_tod/tod_original": orig_out,
            "averaged_tod/weights": wei_out,
            "averaged_tod/scan_edges": edges,
        }
        self.STATE = True
        return True

    def warm_programs(self, F, B, C, T, S, L, calibrator=False):
        """AOT-compile the fused reduction for one campaign bucket.

        Mirrors ``__call__``'s planning EXACTLY — same mesh, same
        rounded feed batch, same HBM-planned scan streaming, same
        ``ReduceConfig`` — and lowers the same cached
        ``_reduce_feeds_fn`` jit (NaN-carrying ``mask=None`` variant)
        with the same input shardings, so the persistent compile cache
        entry it writes is the one the batch loop's call will hit."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from comapreduce_tpu.parallel.mesh import feed_time_mesh
        from comapreduce_tpu.parallel.sharded import _reduce_feeds_fn

        if int(S) == 0:
            return
        F, B, C, T = int(F), int(B), int(C), int(T)
        bk = _stage_buckets(self)
        Tb = bk.round_T(T)
        L_raw = int(L)
        Lb = bk.round_L(L_raw)
        Sb = bk.round_S(int(S))
        local = jax.local_devices()
        mesh = feed_time_mesh(local, n_feed=len(local))
        n_dev = mesh.shape["feed"]
        fb = -(-min(self.feed_batch or F, F) // n_dev) * n_dev
        scan_batch = plan_reduce_memory(fb // n_dev, B, C, Tb, Sb, Lb,
                                        self.scan_batch,
                                        suggest_scale=n_dev)
        cfg = ReduceConfig(C,
                           medfilt_window=min(self.medfilt_window, L_raw),
                           is_calibrator=bool(calibrator),
                           medfilt_stride=self.medfilt_stride,
                           scan_batch=scan_batch)
        fn = _reduce_feeds_fn(cfg, Sb, Lb, with_mask=False,
                              donate_tod=True, with_fold=bk.enabled)
        feed_sh = NamedSharding(mesh, P("feed"))
        repl = NamedSharding(mesh, P())
        SDS, f32, i32 = jax.ShapeDtypeStruct, jnp.float32, jnp.int32
        fold = (SDS((), i32, sharding=repl),) if bk.enabled else ()
        with mesh:
            _warm_compile("level1.reduce_feeds", fn,
                          SDS((fb, B, C, Tb), f32, sharding=feed_sh),
                          SDS((fb, Tb), f32, sharding=feed_sh),
                          SDS((Sb,), i32, sharding=repl),
                          SDS((Sb,), i32, sharding=repl),
                          SDS((fb, B, C), f32, sharding=feed_sh),
                          SDS((fb, B, C), f32, sharding=feed_sh),
                          SDS((B, C), f32, sharding=repl),
                          *fold)


@register()
@dataclass
class Spikes(_StageBase):
    """Spike flagging of the averaged TOD -> ``spikes/spike_mask``
    (F, B, T) uint8 (parity: ``Statistics.py:30-104``)."""

    groups: tuple = ("spikes",)
    window: int = 501
    threshold: float = 10.0
    pad: int = 100

    def __call__(self, data, level2) -> bool:
        tod = np.asarray(level2.tod, dtype=np.float32)
        # validity comes from the reduction's real per-sample weights
        # (zero outside scans / for dead channels) — a genuine zero-valued
        # TOD sample stays valid. Fall back to the tod != 0 sentinel only
        # for stores that predate the weights dataset.
        if "averaged_tod/weights" in level2:
            valid = (np.asarray(level2["averaged_tod/weights"],
                                dtype=np.float32) > 0).astype(np.float32)
        else:
            valid = (tod != 0).astype(np.float32)
        T = tod.shape[-1]
        mask = spike_mask(tod, window=min(self.window, max(3, T // 2 * 2 - 1)),
                          threshold=self.threshold, pad=self.pad, valid=valid)
        self._data = {"spikes/spike_mask":
                      np.asarray(mask).astype(np.uint8)}
        self.STATE = True
        return True


def bucket_scan_lengths(edges: np.ndarray, quantum: int,
                        max_buckets: int = 0) -> dict:
    """Group scan indices by quantised fit length: {length: [scan ids]}.

    Scans are fitted at their own length rounded DOWN to the ``quantum``
    grid (scans shorter than the quantum round to an even length);
    anything under 16 samples is unfittable and dropped. Shared by the
    device and numpy noise stages so a per-stage backend switch fits
    identical blocks; ``quantum=1`` reproduces the reference's exact
    full-length fits (``Level2Data.py:288-329``).

    ``max_buckets > 0`` caps the number of DISTINCT buckets — each
    distinct length is its own XLA compile, and an adversarial filelist
    with many distinct scan lengths would otherwise compile one kernel
    per scan. Over-cap bucket sets are merged directly: the sorted
    distinct lengths are split into ``max_buckets`` contiguous groups
    and every group fits at its MINIMUM length (round-down stays safe
    for every scan in the group); the worst extra trim is logged."""
    q = max(int(quantum), 1)
    buckets: dict[int, list[int]] = {}
    for si, (s, e) in enumerate(np.asarray(edges)):
        ln = int(e - s)
        lq = (ln // q) * q if ln >= q else ln // 2 * 2
        if lq >= 16:
            buckets.setdefault(lq, []).append(si)

    if max_buckets > 0 and len(buckets) > max_buckets:
        n0 = len(buckets)
        groups = np.array_split(np.asarray(sorted(buckets)), max_buckets)
        merged: dict[int, list[int]] = {}
        worst = 0
        for g in groups:
            if not g.size:
                continue
            tgt = int(g[0])                 # ascending: g[0] is the min
            worst = max(worst, int(g[-1]) - tgt)
            for ln in g:
                merged.setdefault(tgt, []).extend(buckets[int(ln)])
        buckets = {ln: sorted(v) for ln, v in merged.items()}
        logger.warning(
            "bucket_scan_lengths: %d distinct fit lengths exceed the "
            "%d-compile cap; merged to %d buckets (up to %d extra "
            "samples trimmed per scan)", n0, max_buckets, len(buckets),
            worst)
    return buckets


def first_fitted_scan(buckets: dict, edges: np.ndarray):
    """(scan id, fit length, start) of the first fitted scan — the QA
    figure target, shared by both noise-stage backends."""
    si0 = min(min(v) for v in buckets.values())
    lq0 = next(lq for lq, v in sorted(buckets.items()) if si0 in v)
    return si0, lq0, int(np.asarray(edges)[si0, 0])


@register()
@dataclass
class Level2FitPowerSpectrum(_StageBase):
    """Per-(feed, band, scan) noise power-spectrum fit of the averaged TOD.

    Red-noise model ``sigma_w^2 + sigma_r^2 |nu|^alpha``
    (``Level2Data.py:246-329``, which fits each scan at its own full
    length). Each scan is fitted at its OWN length, rounded down to the
    ``length_quantum`` grid: scans of like length share one compiled
    kernel (one jit per distinct bucket, not per scan), and a single
    short stub no longer destroys the low-frequency leverage of every
    full-length scan the way a truncate-to-shortest scheme would. Writes
    ``fnoise_fits/{fnoise_fit_parameters (F,B,S,3), auto_rms (F,B,S)}``;
    scans too short to fit (< 16 samples) hold NaN — downstream medians
    (``database/obsdb.py`` fleet stats) are nan-aware, and zeros would
    silently drag them."""

    groups: tuple = ("fnoise_fits",)
    nbins: int = 30
    sample_rate: float = 50.0
    model_name: str = "red_noise"
    out_group: str = "fnoise_fits"
    # exclude resonance spikes >100x the white level from the binned PSD
    # before fitting (Level2Data.py:288-298)
    mask_peaks: bool = True
    # scans are fitted at their length rounded DOWN to this grid (<1% of
    # a production 13.5k-sample scan); 1 = every distinct (even) length
    # compiles its own kernel
    length_quantum: int = 128
    # cap on distinct compile buckets per observation (0 = uncapped);
    # an adversarial filelist cannot force one XLA compile per scan
    max_length_buckets: int = 16
    figure_dir: str = ""

    def _bucket_scans(self, edges: np.ndarray) -> dict[int, list[int]]:
        return bucket_scan_lengths(edges, self.length_quantum,
                                   self.max_length_buckets)

    def __call__(self, data, level2) -> bool:
        tod = np.asarray(level2.tod, dtype=np.float32)  # (F, B, T)
        edges = np.asarray(level2.scan_edges)
        if len(edges) == 0:
            self.STATE = False
            return False
        buckets = self._bucket_scans(edges)
        if not buckets:
            self.STATE = False
            return False
        F, B = tod.shape[:2]
        S = len(edges)
        params = np.full((F, B, S, 3), np.nan, np.float32)
        rms = np.full((F, B, S), np.nan, np.float32)
        for lq, sids in sorted(buckets.items()):
            blocks = np.stack(
                [tod[..., edges[si, 0]:edges[si, 0] + lq] for si in sids],
                axis=2)  # (F, B, s, lq)
            fit = power_ops.fit_observation_noise(
                jnp.asarray(blocks), sample_rate=self.sample_rate,
                nbins=self.nbins, model_name=self.model_name,
                mask_peaks=self.mask_peaks)
            params[:, :, sids] = np.asarray(fit)
            rms[:, :, sids] = np.asarray(auto_rms(jnp.asarray(blocks)))
        if self.figure_dir:
            from comapreduce_tpu import diagnostics

            si0, lq0, s0 = first_fitted_scan(buckets, edges)
            freqs, ps = power_ops.psd(jnp.asarray(tod[0, 0, s0:s0 + lq0]),
                                      self.sample_rate)
            nu, pb, _ = power_ops.log_bin_psd(freqs, ps, nbins=self.nbins)
            model = (power_ops.red_noise_model
                     if self.model_name == "red_noise"
                     else power_ops.knee_model)
            diagnostics.plot_power_spectrum_fit(
                diagnostics.figure_path(
                    self.figure_dir, data.obsid,
                    f"{self.out_group}_feed00_band00_scan{si0:02d}"),
                np.asarray(nu), np.asarray(pb), params[0, 0, si0], model)
        self._data = {
            f"{self.out_group}/fnoise_fit_parameters": params,
            f"{self.out_group}/auto_rms": rms,
        }
        self.STATE = True
        return True


@register()
@dataclass
class NoiseStatistics(Level2FitPowerSpectrum):
    """Knee-model variant writing ``noise_statistics/fnoise``
    (parity: ``Statistics.py:106-224``)."""

    groups: tuple = ("noise_statistics",)
    model_name: str = "knee"
    out_group: str = "noise_statistics"


@register(backend="any")
@dataclass
class WriteLevel2Data(_StageBase):
    """Write the Level-2 store to its target file (parity:
    ``WriteLevel2Data``, ``Level2Data.py:113-139``). The runner already
    checkpoints after every stage; this stage exists for chain parity and
    for explicit final placement via ``output_dir``."""

    overwrite: bool = True
    output_dir: str = ""

    def __call__(self, data, level2) -> bool:
        path = level2.filename
        if self.output_dir:
            os.makedirs(self.output_dir, exist_ok=True)
            path = os.path.join(self.output_dir, os.path.basename(path))
            level2.filename = path
        level2.write(path)
        self.STATE = True
        return True


@register(backend="any")
@dataclass
class Level2Timelines(_StageBase):
    """Fleet timelines product from a Level-2 filelist (parity:
    ``Level2Timelines``, ``Level2Data.py:142-223``, which equally takes
    its own filelist kwarg inside the per-file protocol).

    Builds the Tsys/gain/noise timelines over ``filelist`` and writes the
    ``gains.hd5``-style product to ``output_path`` ONCE per runner pass
    (the reference recomputes per target file; rebuilding an identical
    fleet product for every file is pure waste). ``filelist`` empty means
    "the runner's own Level-2 output": the current file's path is
    accumulated and the product (re)written after each file, so the
    timelines stay complete however many files the run covers.
    """

    overwrite: bool = True
    filelist: str = ""
    output_path: str = "gains.hd5"

    def _out_path(self) -> str:
        """Accumulate mode under a multi-process launch: ranks own
        disjoint filelist shards, so sharing one path would leave a
        last-writer-wins partial product (and risk concurrent-write
        corruption) — each rank writes a ``_rank{r}`` suffix. Single
        -process runs keep the plain name."""
        from comapreduce_tpu.parallel.multihost import rank_info

        rank, n_ranks = rank_info()
        if n_ranks <= 1:
            return self.output_path
        base, ext = os.path.splitext(self.output_path)
        return f"{base}_rank{rank}{ext}"

    def __call__(self, data, level2) -> bool:
        from comapreduce_tpu.summary import (assemble_timelines,
                                             timeline_row, write_gains)

        if self.filelist:
            # explicit filelist = the FULL fleet: every rank would build
            # an identical product, so rank 0 alone writes the plain
            # output_path and the others no-op
            from comapreduce_tpu.parallel.multihost import rank_info

            rank, _ = rank_info()
            # once-per-pass memo keyed on the filelist IDENTITY (path +
            # mtime + size), not a sticky instance flag: a runner reused
            # for a second pass over an UPDATED filelist rebuilds the
            # product instead of silently skipping
            try:
                st = os.stat(self.filelist)
                done_key = (self.filelist, st.st_mtime_ns, st.st_size)
            except OSError:
                done_key = (self.filelist, None, None)
            if rank != 0 or getattr(self, "_done_key", None) == done_key:
                self.STATE = True
                return True
            from comapreduce_tpu.pipeline.config import read_filelist

            rows = [r for r in map(timeline_row,
                                   read_filelist(self.filelist))
                    if r is not None]
            write_gains(self.output_path, assemble_timelines(rows))
            self._done_key = done_key   # only after a successful write
            self.STATE = True
            return True
        else:
            # the runner's own output: the runner has already checkpointed
            # this file's store (atomic write after every stage), so only
            # the NEW file needs reading; earlier rows are cached
            cache = getattr(self, "_rows", {})
            if level2.filename not in cache:
                cache[level2.filename] = timeline_row(level2.filename)
            self._rows = cache
            rows = [r for r in cache.values() if r is not None]
        write_gains(self._out_path(), assemble_timelines(rows))
        self.STATE = True
        return True
