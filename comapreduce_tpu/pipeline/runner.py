"""The pipeline executor: per-file stage loop with resume + checkpointing.

Parity target: ``Analysis/Running.py`` — ``Runner.run_tod`` (:120-153):
per-file loop, skip-if-``contains`` unless ``overwrite``, falsy-``STATE``
abort, Level-2 write after every stage (the Level-2 file *is* the
checkpoint); ``set_logging`` (:30-49): per-rank logfile named
``{base}_{time}_{host}_PID{pid}_rank{rank}.log`` plus an excepthook that
routes uncaught errors into the log.

Differences by design: no ``time.sleep(rank*15)`` NFS stagger (TPU hosts
read their own shards), per-stage wall/compile timing is recorded in
``Runner.timings``, and the stage list can be built straight from a TOML
or legacy-INI config through the registry.
"""

from __future__ import annotations

import logging
import os
import socket
import sys
import time
from dataclasses import dataclass, field

from comapreduce_tpu.data.level import COMAPLevel1, COMAPLevel2
from comapreduce_tpu.pipeline import config as cfg_mod
from comapreduce_tpu.pipeline.registry import resolve

__all__ = ["Runner", "set_logging", "level2_path"]

logger = logging.getLogger("comapreduce_tpu")


def set_logging(base: str = "pipeline", log_dir: str = ".",
                rank: int = 0, level: str = "INFO") -> str:
    """Per-rank logfile + excepthook (``Running.py:30-49``). Returns path."""
    os.makedirs(log_dir, exist_ok=True)
    stamp = time.strftime("%Y%m%d-%H%M%S")
    host = socket.gethostname()
    path = os.path.join(
        log_dir, f"{base}_{stamp}_{host}_PID{os.getpid()}_rank{rank}.log")
    for h in list(logger.handlers):
        if isinstance(h, logging.FileHandler):
            logger.removeHandler(h)
            h.close()
    handler = logging.FileHandler(path)
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    logger.addHandler(handler)
    logger.setLevel(getattr(logging, level.upper(), logging.INFO))

    def excepthook(exc_type, exc, tb):
        logger.error("uncaught exception", exc_info=(exc_type, exc, tb))
        sys.__excepthook__(exc_type, exc, tb)

    sys.excepthook = excepthook
    return path


def level2_path(output_dir: str, level1_filename: str,
                prefix: str = "Level2") -> str:
    base = os.path.basename(level1_filename)
    return os.path.join(output_dir, f"{prefix}_{base}")


@dataclass
class Runner:
    """Run a stage chain over a filelist.

    ``processes`` are instantiated stages (see :mod:`stages`); build them
    from config with :meth:`from_config`. ``rank``/``n_ranks`` implement
    the reference's static filelist shard (``run_average.py:38-39``) for
    multi-host runs — rank r takes files ``i`` with ``i % n_ranks == r``.
    """

    processes: list = field(default_factory=list)
    output_dir: str = "."
    prefix: str = "Level2"
    rank: int = 0
    n_ranks: int = 1
    timings: dict = field(default_factory=dict)
    # when set, each file's stage chain runs under jax.profiler.trace
    # writing TensorBoard-readable traces here (the reference has no
    # profiler at all — SURVEY.md §5 'Tracing/profiling: none')
    profile_dir: str = ""

    def shard(self, filelist: list[str]) -> list[str]:
        return [f for i, f in enumerate(filelist)
                if i % self.n_ranks == self.rank]

    def run_tod(self, filelist: list[str]) -> list[COMAPLevel2]:
        """The TOD-reduction loop (``Running.py:120-153``)."""
        os.makedirs(self.output_dir, exist_ok=True)
        results = []
        for filename in self.shard(list(filelist)):
            logger.info("rank %d: processing %s", self.rank, filename)
            try:
                results.append(self.run_file(filename))
            except Exception:
                # per-file fault tolerance: a bad file never kills the run
                # (reference: broad try/except + "BAD FILE" logging,
                # COMAPData.py:169-173)
                logger.exception("BAD FILE %s", filename)
                results.append(None)
        return results

    def run_file(self, filename: str) -> COMAPLevel2:
        if self.profile_dir:
            import contextlib

            import jax

            os.makedirs(self.profile_dir, exist_ok=True)
            try:
                ctx = jax.profiler.trace(self.profile_dir)
            except Exception:  # profiler unsupported on this backend
                logger.warning("jax.profiler.trace unavailable; "
                               "running unprofiled")
                ctx = contextlib.nullcontext()
            with ctx:
                return self._run_file(filename)
        return self._run_file(filename)

    def _run_file(self, filename: str) -> COMAPLevel2:
        data = COMAPLevel1()
        data.read(filename)
        lvl2 = COMAPLevel2(
            filename=level2_path(self.output_dir, filename, self.prefix))
        for process in self.processes:
            pname = getattr(process, "name", type(process).__name__)
            process.pre_init(data)
            if lvl2.contains(process) and not process.overwrite:
                logger.info("%s: contained, skipping", pname)
                continue
            if hasattr(process, "clear_outputs"):
                process.clear_outputs()  # no stale outputs across files
            t0 = time.perf_counter()
            state = process(data, lvl2)
            dt = time.perf_counter() - t0
            self.timings.setdefault(pname, []).append(dt)
            logger.info("%s: %.3f s (STATE=%s)", pname, dt, bool(state))
            if not state:
                logger.info("%s returned falsy STATE; aborting %s",
                            pname, filename)
                break
            lvl2.update(process)
            # checkpoint after EVERY stage; atomic so a kill mid-write
            # can't strand a half-written group that resume would skip
            lvl2.write(lvl2.filename, atomic=True)
        return lvl2

    def run_astro_cal(self, filelist: list[str],
                      calibrator_level2: list[str],
                      cache_path: str = "") -> list[COMAPLevel2]:
        """Apply astronomical calibration factors to target files
        (``Running.run_astro_cal``, ``Running.py:156-173``): factors are
        harvested from the calibrator Level-2 files, the nearest-in-MJD
        factor is written into each target's Level-2 store."""
        from comapreduce_tpu.calibration.apply_cal import ApplyCalibration

        stage = ApplyCalibration(
            calibrator_filelist=tuple(calibrator_level2),
            cache_path=cache_path)
        sub = Runner(processes=[stage], output_dir=self.output_dir,
                     prefix=self.prefix, rank=self.rank,
                     n_ranks=self.n_ranks, timings=self.timings)
        return sub.run_tod(filelist)

    # -- config-driven construction ----------------------------------------
    @classmethod
    def from_config(cls, config: dict | str, rank: int = 0,
                    n_ranks: int = 1) -> "Runner":
        """Build from a TOML config (path or parsed dict).

        Layout (mirrors ``configuration.toml``): ``[Global]`` has
        ``processes`` (stage-name list), ``output_dir``, optional
        ``backend``; each ``[StageName]`` section holds that stage's
        kwargs (including per-stage ``backend``/``overwrite``)."""
        if isinstance(config, str):
            config = cfg_mod.load_toml(config)
        glob = config.get("Global", {})
        backend = glob.get("backend")
        processes = []
        for name in glob.get("processes", []):
            kwargs = dict(config.get(name, {}))
            kwargs.setdefault("backend", backend)
            processes.append(resolve(name, **kwargs))
        return cls(processes=processes,
                   output_dir=glob.get("output_dir", "."),
                   prefix=glob.get("prefix", "Level2"),
                   rank=rank, n_ranks=n_ranks)

    @classmethod
    def from_legacy_config(cls, ini_path: str, rank: int = 0,
                           n_ranks: int = 1) -> "Runner":
        """Build from a legacy INI (``Module.Class(variant)`` registry,
        ``Tools/Parser.py:44-96``)."""
        ini = cfg_mod.IniConfig(ini_path)
        processes = [resolve(name, **kwargs)
                     for name, kwargs in ini.pipeline_jobs()]
        out = ini.get("Inputs", {}).get("output_dir", ".")
        return cls(processes=processes, output_dir=out,
                   rank=rank, n_ranks=n_ranks)
