"""The pipeline executor: per-file stage loop with resume + checkpointing.

Parity target: ``Analysis/Running.py`` — ``Runner.run_tod`` (:120-153):
per-file loop, skip-if-``contains`` unless ``overwrite``, falsy-``STATE``
abort, Level-2 write after every stage (the Level-2 file *is* the
checkpoint); ``set_logging`` (:30-49): per-rank logfile named
``{base}_{time}_{host}_PID{pid}_rank{rank}.log`` plus an excepthook that
routes uncaught errors into the log.

Differences by design: no ``time.sleep(rank*15)`` NFS stagger (TPU hosts
read their own shards), per-stage wall/compile timing is recorded in
``Runner.timings``, and the stage list can be built straight from a TOML
or legacy-INI config through the registry.
"""

from __future__ import annotations

import contextlib
import logging
import os
import socket
import sys
import time
from dataclasses import dataclass, field

from comapreduce_tpu.data.level import COMAPLevel1, COMAPLevel2
from comapreduce_tpu.pipeline import config as cfg_mod
from comapreduce_tpu.pipeline.registry import resolve
from comapreduce_tpu.telemetry import (TELEMETRY, StageTimings,
                                       TelemetryConfig)

__all__ = ["Runner", "set_logging", "level2_path"]

logger = logging.getLogger("comapreduce_tpu")


def set_logging(base: str = "pipeline", log_dir: str = ".",
                rank: int = 0, level: str = "INFO") -> str:
    """Per-rank logfile + excepthook (``Running.py:30-49``). Returns path."""
    os.makedirs(log_dir, exist_ok=True)
    stamp = time.strftime("%Y%m%d-%H%M%S")
    host = socket.gethostname()
    path = os.path.join(
        log_dir, f"{base}_{stamp}_{host}_PID{os.getpid()}_rank{rank}.log")
    for h in list(logger.handlers):
        if isinstance(h, logging.FileHandler):
            logger.removeHandler(h)
            h.close()
    handler = logging.FileHandler(path)
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    logger.addHandler(handler)
    logger.setLevel(getattr(logging, level.upper(), logging.INFO))

    # CHAIN to whatever hook was installed before us (a debugger, a
    # crash reporter, an earlier set_logging) instead of clobbering it
    # — but when the previous hook is one of ours, chain to ITS parent
    # so repeated set_logging calls never stack an unbounded chain.
    prev_hook = sys.excepthook
    if getattr(prev_hook, "_comap_excepthook", False):
        prev_hook = prev_hook._comap_prev

    def excepthook(exc_type, exc, tb):
        logger.error("rank %d: uncaught exception", rank,
                     exc_info=(exc_type, exc, tb))
        prev_hook(exc_type, exc, tb)

    excepthook._comap_excepthook = True
    excepthook._comap_prev = prev_hook
    sys.excepthook = excepthook
    return path


def level2_path(output_dir: str, level1_filename: str,
                prefix: str = "Level2") -> str:
    base = os.path.basename(level1_filename)
    return os.path.join(output_dir, f"{prefix}_{base}")


def _record_timing(timings, name: str, seconds: float, **kw) -> None:
    """Append into ``timings`` through the spans-backed adapter when
    present; a caller-supplied plain dict still works (and simply has
    no skip tracking or span emission)."""
    rec = getattr(timings, "record", None)
    if rec is not None:
        rec(name, seconds, **kw)
    else:
        timings.setdefault(name, []).append(float(seconds))


@dataclass
class Runner:
    """Run a stage chain over a filelist.

    ``processes`` are instantiated stages (see :mod:`stages`); build them
    from config with :meth:`from_config`. ``rank``/``n_ranks`` implement
    the reference's static filelist shard (``run_average.py:38-39``) for
    multi-host runs — rank r takes files ``i`` with ``i % n_ranks == r``.
    """

    processes: list = field(default_factory=list)
    output_dir: str = "."
    prefix: str = "Level2"
    rank: int = 0
    n_ranks: int = 1
    # per-stage wall times; a StageTimings (telemetry/core.py): a real
    # dict[str, list[float]] — every historic consumer keeps working —
    # that also publishes spans and excludes skip-path placeholders
    # from the watchdog's adaptive percentile via .samples()
    timings: dict = field(default_factory=StageTimings)
    # when set, each file's stage chain runs under jax.profiler.trace
    # writing TensorBoard-readable traces here (the reference has no
    # profiler at all — SURVEY.md §5 'Tracing/profiling: none')
    profile_dir: str = ""
    # streaming-ingest knob: IngestConfig | {"prefetch": N, "cache_mb":
    # M, ...} | None. prefetch=0 (default) is the serial path.
    ingest: object = None
    # resilience knob: ResilienceConfig | {"quarantine": ..., ...} |
    # None. The default config quarantines failures into
    # <output_dir>/quarantine.jsonl and retries transient reads;
    # {"quarantine": "off"} restores the bare BAD-FILE-log behaviour.
    resilience: object = None
    # run-state directory (heartbeats, lease files, queue manifest):
    # '' keeps state next to the science outputs (historic behaviour);
    # the CLIs pass [Global] log_dir so <log_dir> holds ALL run state
    # and <output_dir> holds only data products
    state_dir: str = ""
    # campaign-throughput knob (TOML [campaign] / INI [Campaign]):
    # CampaignConfig | {"t_quantum": ..., "warm_compile": ...} | None.
    # Shape canonicalisation pads each observation up to its campaign
    # bucket so the fused programs compile once per bucket, not once
    # per file; warm_compile AOT-compiles the bucket set on a
    # background thread (needs [ingest] compile_cache_dir). All off by
    # default (docs/OPERATIONS.md §9).
    campaign: object = None
    # observability knob (TOML [telemetry]): TelemetryConfig |
    # {"enabled": ..., "flush_s": ..., "jax_profiler": ...} | None.
    # enabled=True streams spans/counters to <state_dir>/
    # events.rank{r}.jsonl for tools/campaign_report.py; off by
    # default (docs/OPERATIONS.md §13)
    telemetry: object = None
    # precision knob (TOML [precision] / INI [Precision]):
    # PrecisionPolicy | {"tod_dtype": "bf16", "cg_dot": "compensated"}
    # | None. tod_dtype narrows streamed/cached TOD payloads (weights/
    # masks stay f32; the fused reduce widens at first touch); cg_dot
    # selects the destriper's CG inner product. Default None is the
    # identity policy — byte-identical behaviour
    # (docs/OPERATIONS.md §15).
    precision: object = None
    # data-quality ledger knob (TOML [quality] / INI [Quality]):
    # QualityConfig | {"enabled": ...} | None. Enabled (the default)
    # assembles one quality record per (file, feed, band) — vane
    # Tsys/gain, white sigma + 1/f knee/alpha, spike count, masked
    # fraction — after each file's stage chain, appended to
    # <state_dir>/quality.rank{r}.jsonl (docs/OPERATIONS.md §16)
    quality: object = None
    # SLO thresholds over quality records (TOML [slo] / INI [Slo]):
    # SloConfig | mapping | None. Violations flag the record and fire
    # a quality.alert telemetry counter; run_destriper can exclude
    # flagged files behind [slo] exclude_flagged (default off)
    slo: object = None
    # control-plane knob (TOML [control] / INI [Control]):
    # ControlConfig | mapping | None. admission=True gates the elastic
    # scheduler's claims behind the SLO-driven shed/defer loop;
    # autoscale/solver_policy are consumed by the supervisor sidecar
    # and the destriper CLI respectively. Default None = every loop
    # off, byte-for-byte the uncontrolled pipeline
    # (docs/OPERATIONS.md §19)
    control: object = None
    # shape-bucket autotuner knob (TOML [tuning] / INI [Tuning]):
    # TuningConfig | {"enabled": ..., "device_hbm_mb": ...} | None.
    # Enabled, the HBM auto-sizers (stage feed_batch, plan pair_batch)
    # consult measured winners from <state_dir>/tuning.jsonl; absent
    # table = byte-identical untuned pipeline
    # (docs/OPERATIONS.md §21)
    tuning: object = None
    # cumulative async-writeback stats ({"writes", "write_s",
    # "flush_wait_s", ...}) across this Runner's run_tod calls — the
    # bench's write-overlap observable
    writeback_stats: dict = field(default_factory=dict)
    # the BlockCache lives on the Runner, not the run_tod call: a
    # reduction pass followed by run_astro_cal (run_average's flow) or
    # a second run_tod re-reads the same Level-1 files, and a per-call
    # cache could never hit
    _ingest_cache: object = field(default=None, repr=False)
    # the built Resilience runtime (ledger/retry/chaos) — Runner-lifetime
    # for the same reason as the cache: run_astro_cal and repeated
    # run_tod calls must consult ONE ledger
    _resilience: object = field(default=None, repr=False)
    # the live async writer during a run_tod call (None = synchronous
    # checkpoint writes, the default)
    _writeback: object = field(default=None, repr=False)
    # the live elastic scheduler during a run_tod call (None = static
    # shard, the default; see [resilience] lease_ttl_s)
    _scheduler: object = field(default=None, repr=False)
    # last run_tod's final scheduler stats dict ({} = static shard):
    # claim/steal/commit/fence accounting for post-run audits
    scheduler_stats: dict = field(default_factory=dict, repr=False)

    def shard_iter(self, filelist):
        """Lazy round-robin shard: rank r takes files ``i % n_ranks == r``.
        Both the serial loop and the prefetcher consume this one
        iterator, so the sharding rule cannot drift between paths."""
        for i, f in enumerate(filelist):
            if i % self.n_ranks == self.rank:
                yield f

    def shard(self, filelist: list[str]) -> list[str]:
        return list(self.shard_iter(filelist))

    def run_tod(self, filelist: list[str]) -> list[COMAPLevel2]:
        """The TOD-reduction loop (``Running.py:120-153``).

        With ``ingest.prefetch >= 1`` a background thread reads ahead
        over this rank's shard while the current file's stage chain
        computes (``ingest/``); per-file read/compute wall times land in
        ``timings['ingest.read']`` / ``timings['ingest.compute']`` on
        both paths, so the overlap is observable. A file whose *read*
        fails takes the same per-file "BAD FILE" -> ``None`` slot as a
        file whose stage chain fails — a bad file never kills the queue
        or the run.

        Failures also land in the quarantine ledger
        (``<output_dir>/quarantine.jsonl`` by default, ``resilience``
        knob): transient read errors are retried with backoff first;
        files the ledger already quarantines are skipped WITHOUT a read
        (no result slot) until ``retry_quarantined`` re-admits them.
        """
        from comapreduce_tpu.ingest import IngestConfig, level1_stream
        from comapreduce_tpu.pipeline.campaign import CampaignConfig

        from comapreduce_tpu.ops.precision import PrecisionPolicy

        os.makedirs(self.output_dir, exist_ok=True)
        from comapreduce_tpu.telemetry.quality import (QualityConfig,
                                                       SloConfig)

        cfg = IngestConfig.coerce(self.ingest)
        camp = CampaignConfig.coerce(self.campaign)
        tcfg = TelemetryConfig.coerce(self.telemetry)
        prec = PrecisionPolicy.coerce(self.precision)
        # validate [quality]/[slo] up front so a typo'd knob raises at
        # run start, not inside the per-file best-effort ledger path
        QualityConfig.coerce(self.quality)
        SloConfig.coerce(self.slo)
        from comapreduce_tpu.tuning.cache import TUNING, TuningConfig

        tun = TuningConfig.coerce(self.tuning)
        if tun.enabled and not TUNING.enabled:
            # like the telemetry registry, the winners cache is
            # process-wide: the first enabled Runner opens it; every
            # later auto-sized plan in the process consults it
            TUNING.configure(self.state_dir or self.output_dir, tun)
        if tcfg.enabled and not TELEMETRY.enabled:
            # the registry is process-wide: the first enabled Runner
            # opens this rank's stream; sub-runs (run_astro_cal) and
            # later run_tod calls append to the same stream
            TELEMETRY.configure(self.state_dir or self.output_dir,
                                rank=self.rank, flush_s=tcfg.flush_s,
                                jax_profiler=tcfg.jax_profiler)
        buckets = camp.shape_buckets()
        if buckets.enabled:
            # campaign shape canonicalisation (docs/OPERATIONS.md §9):
            # stages pad each observation up to its bucket so the fused
            # programs compile once per bucket, not once per file
            for p in self.processes:
                if hasattr(p, "shape_buckets"):
                    p.shape_buckets = buckets
        if prec.enabled:
            # precision policy (docs/OPERATIONS.md §15), threaded like
            # the shape buckets: stages that expose the knob receive
            # the whole policy (the reduce stage widens bf16 TOD at
            # first touch; a destriper stage would read cg_dot)
            for p in self.processes:
                if hasattr(p, "precision"):
                    p.precision = prec
        if cfg.compile_cache_dir:
            from comapreduce_tpu.pipeline.campaign import \
                enable_compile_cache

            enable_compile_cache(cfg.compile_cache_dir)
        if self._ingest_cache is None:
            self._ingest_cache = cfg.make_cache()
        cache = self._ingest_cache
        if (prec.tod_dtype != "f32" and cache is None
                and not (cfg.eager_tod and cfg.prefetch >= 1)):
            # the narrowing happens in the eager loader, before the
            # cache/prefetch queue; a lazy h5py handle is returned
            # as-is (loaders.load_level1), so on the serial lazy path
            # the knob is inert — say so instead of silently doing
            # nothing (docs/OPERATIONS.md §15)
            logger.warning(
                "[precision] tod_dtype = %s has no effect on the lazy "
                "serial ingest path (prefetch = 0, no cache): the "
                "narrowing runs in the eager loader. Set [ingest] "
                "prefetch >= 1 (or cache_mb > 0) to stream narrowed "
                "TOD.", prec.tod_dtype)
        res = self._resilience_runtime()
        if camp.warm_compile:
            # AOT warm-up of the campaign's bucket set, overlapped with
            # the first file's prefetch (a daemon thread: probe every
            # file's geometry, lower+compile each stage's programs once
            # per bucket). AOT results reach the run only through the
            # persistent compile cache, so it is a hard prerequisite.
            if not cfg.compile_cache_dir:
                logger.warning(
                    "campaign warm_compile needs [ingest] "
                    "compile_cache_dir (AOT compiles reach the run only "
                    "through the persistent cache); skipping warm-up")
            elif not isinstance(filelist, (list, tuple)):
                logger.warning(
                    "campaign warm_compile needs a concrete filelist "
                    "(got a one-shot iterable); skipping warm-up")
            else:
                from comapreduce_tpu.pipeline.campaign import \
                    start_warmup

                start_warmup(self.processes, self.shard(list(filelist)),
                             buckets=buckets)
        wb = None
        if cfg.writeback >= 1:
            # async Level-2 writeback (docs/OPERATIONS.md §9): stage
            # checkpoints snapshot to host and commit on an ordered
            # background writer; the per-file flush barrier in
            # _run_file keeps resume/quarantine/kill semantics
            # byte-identical to the synchronous path
            from comapreduce_tpu.data.writeback import Writeback

            wb = Writeback(
                depth=cfg.writeback, watchdog=res.watchdog,
                chaos=res.chaos,
                on_hang=lambda f: res.record_hang(
                    f, stage="writeback.write",
                    message="checkpoint write never returned; "
                            "writer abandoned"))
        self._writeback = wb
        if res.heartbeat is not None:
            # liveness for the whole loop: the ticker keeps beating
            # even while one file wedges inside a stage, which is
            # exactly when sibling ranks (and operators reading
            # tools/watchdog_report.py) need the signal most — and in
            # elastic mode a live heartbeat is what keeps this rank's
            # leases from being stolen, so it must start BEFORE the
            # first claim
            res.heartbeat.start()
        sched = None
        if res.lease_ttl_s > 0:
            # elastic campaign: replace the static rank::n_ranks shard
            # with lease-based claiming — dead ranks' files are stolen
            # by survivors, fresh ranks just start claiming
            # (docs/OPERATIONS.md §11)
            from comapreduce_tpu.pipeline.scheduler import Scheduler

            sched = Scheduler(
                list(filelist), state_dir=res.state_dir or
                self.state_dir or self.output_dir,
                rank=self.rank, n_ranks=self.n_ranks,
                lease_ttl_s=res.lease_ttl_s,
                steal_after_s=res.steal_after_s,
                ledger=res.ledger, chaos=res.chaos,
                heartbeat=res.heartbeat,
                admission=self._admission_gate(res))
            source = sched.claim_iter()
        else:
            if self._admission_gate(res) is not None:
                logger.warning(
                    "[control] admission is on but the shard is STATIC "
                    "([resilience] lease_ttl_s = 0): a static shard "
                    "has no claim/defer cycle, so admission control "
                    "is inert for this run")
            source = self.shard_iter(filelist)
        self._scheduler = sched
        results = []
        stream = level1_stream(self._admitted(source, res),
                               prefetch=cfg.prefetch, cache=cache,
                               eager_tod=cfg.eager_tod,
                               eager_for=self._needs_tod,
                               tod_dtype=prec.tod_dtype,
                               retry=res.retry, chaos=res.chaos,
                               watchdog=res.watchdog,
                               on_hang=lambda f: res.record_hang(
                                   f, stage="ingest.close",
                                   message="loader never returned; "
                                           "prefetcher abandoned"))
        try:
            self._consume_stream(stream, results, res)
        finally:
            if sched is not None:
                self._scheduler = None
                n = sched.release_held()
                if n:
                    logger.warning("rank %d: released %d unprocessed "
                                   "claim(s) on shutdown", self.rank, n)
                logger.info("scheduler rank %d: %s", self.rank,
                            sched.stats)
                # the run's final claim/commit accounting, kept for
                # callers (the synthetic scale drill's exactly-once
                # audit reads it after run_tod returns)
                self.scheduler_stats = dict(sched.stats)
            # deterministic shutdown even when a stage raises something
            # the per-file net does not catch and the caller keeps the
            # traceback alive: closing the generator stops the worker
            stream.close()
            if wb is not None:
                self._writeback = None
                try:
                    wb.close()
                finally:
                    for k, v in wb.stats.items():
                        self.writeback_stats[k] = \
                            self.writeback_stats.get(k, 0) + v
            if res.heartbeat is not None:
                res.heartbeat.stop(final_stage="run_tod.done")
        if res.ledger is not None and res.ledger.entries:
            logger.info("quarantine ledger %s: %s", res.ledger.path,
                        res.ledger.summary())
        return results

    def _resilience_runtime(self):
        """The Runner-lifetime Resilience bundle (built on first use).
        Multi-rank runs get per-rank ledger files (single-writer JSONL;
        the shard split is stable, so each rank's skip set is its own)."""
        from comapreduce_tpu.resilience import ResilienceConfig

        if self._resilience is None:
            cfg = ResilienceConfig.coerce(self.resilience)
            self._resilience = cfg.make_runtime(
                self.output_dir, rank=self.rank, n_ranks=self.n_ranks,
                state_dir=self.state_dir)
            if self._resilience.watchdog is not None:
                # the Runner's own per-stage timings feed the adaptive
                # deadlines (hard = p95 x scale of prior same-stage
                # durations, floored by config)
                self._resilience.watchdog.timings = self.timings
        return self._resilience

    def _admission_gate(self, res):
        """The SLO-driven admission controller for this rank's elastic
        scheduler, or None when ``[control] admission`` is off — None
        keeps the scheduler byte-for-byte on its uncontrolled path
        (docs/OPERATIONS.md §19)."""
        from comapreduce_tpu.control.config import ControlConfig

        ccfg = ControlConfig.coerce(self.control)
        if not ccfg.admission:
            return None
        from comapreduce_tpu.control.admission import AdmissionController

        return AdmissionController(
            ccfg, res.state_dir or self.state_dir or self.output_dir,
            rank=self.rank)

    def _admitted(self, source, res):
        """``source`` (this rank's static shard, or its elastic claim
        stream) minus currently-quarantined files (the cheap resume
        skip — no read, no decode, one log line). A claimed-but-
        quarantined unit is committed immediately: durably skipping IS
        handling it, and leaving its lease open would make every other
        rank steal, re-skip and re-stall on it forever."""
        for f in source:
            if res.admit(f):
                yield f
            else:
                logger.warning("rank %d: %s is quarantined — skipping "
                               "(re-admit with --retry-quarantined)",
                               self.rank, f)
                self._commit_unit(f)

    def _commit_unit(self, filename: str) -> None:
        """Elastic mode: publish this unit done through the lease
        generation fence (no-op on the static shard). A rejected
        commit means the unit was stolen and redone while this rank
        worked — the thief's result stands, ours is superseded."""
        sched = self._scheduler
        if sched is None:
            return
        if not sched.commit(filename):
            logger.warning(
                "rank %d: commit of %s REJECTED at the lease "
                "generation fence — the unit was stolen and redone "
                "elsewhere; this rank's late result is superseded",
                self.rank, filename)

    def _consume_stream(self, stream, results: list, res=None) -> None:
        if res is None:  # direct callers/tests without a runtime
            res = self._resilience_runtime()
        hb, wd = res.heartbeat, res.watchdog
        n_ok = 0
        for item in stream:
            logger.info("rank %d: processing %s", self.rank, item.filename)
            if hb is not None:
                hb.note(stage="stage_chain", unit=item.filename)
            # errored reads record a SKIPPED 0.0, keeping the per-file
            # lists index-aligned WITHOUT feeding failure durations
            # into the adaptive deadline percentile (timings backs
            # watchdog.deadline_for): a hang-cancelled read lasts
            # ~attempts x hard deadline, and letting that into the p95
            # would grow the very budget that cancelled it — each
            # generation of hangs inflating the next's, unbounded.
            # emit=False: the read's TRUE interval was already
            # published as a span by the prefetch/serial loader
            _record_timing(self.timings, "ingest.read",
                           item.read_s if item.error is None else 0.0,
                           skipped=item.error is not None,
                           unit=item.filename, emit=False)
            t0 = time.perf_counter()
            if item.error is not None:
                # per-file fault tolerance: a bad file never kills the
                # run (reference: broad try/except + "BAD FILE" logging,
                # COMAPData.py:169-173); prefetch-worker failures are
                # re-raised here, per file, never queue-fatal. The
                # ledger entry carries what the log line loses: the
                # failure class, retry count and traceback digest.
                logger.error("BAD FILE %s", item.filename,
                             exc_info=item.error)
                res.record_failure(item.filename, item.error,
                                   stage="ingest.read")
                results.append(None)
                # keep the read/compute lists index-aligned per file;
                # skipped=True keeps this placeholder out of the
                # adaptive percentile (a mostly-failed or mostly-
                # resumed campaign must not drag deadline budgets
                # toward zero) — the telemetry span carries the
                # skipped attribute instead
                _record_timing(self.timings, "ingest.compute", 0.0,
                               skipped=True, unit=item.filename)
                if hb is not None:
                    hb.advance(files_failed=1)
                # a failed read is still a HANDLED unit (ledgered): in
                # elastic mode commit it so no survivor re-reads a file
                # this rank already triaged
                self._commit_unit(item.filename)
                continue
            # a retry-saved read is bookkeeping only, never skipped
            res.record_recovered(item.filename, item.retries,
                                 stage="ingest.read")
            # [telemetry] jax_profiler: bracket exactly ONE steady-
            # state file (the second success — the first paid compile)
            # so the XLA device trace lines up with the host spans
            prof = TELEMETRY.maybe_jax_profile(steady=n_ok == 1)
            try:
                with prof or contextlib.nullcontext(), \
                        TELEMETRY.span("ingest.compute",
                                       unit=item.filename):
                    if wd is not None:
                        # soft/hard monitoring only: a stage chain
                        # drives jitted device compute and cannot be
                        # cancelled in place — a blown hard deadline is
                        # flagged (event + heartbeat + log), never
                        # killed mid-solve
                        with wd.watch("pipeline.stage_chain",
                                      unit=item.filename):
                            value = self._run_file_with_retry(item, res)
                    else:
                        value = self._run_file_with_retry(item, res)
                results.append(value)
                n_ok += 1
                if hb is not None:
                    hb.advance(files_done=1)
                self._ledger_quality(item.filename, value, res)
            except Exception as exc:
                logger.exception("BAD FILE %s", item.filename)
                # never quarantine the INPUT over a stage-chain error:
                # it may indict the output side (a full disk during the
                # checkpoint write), and skipping the input durably
                # would turn an environment outage into missing data
                res.record_failure(item.filename, exc,
                                   stage="stage_chain",
                                   may_quarantine=False)
                results.append(None)
                if hb is not None:
                    hb.advance(files_failed=1)
            finally:
                # emit=False: the compute span above already carries
                # the true interval (including the error attr on a
                # failed chain); this is the list-alignment record
                _record_timing(self.timings, "ingest.compute",
                               time.perf_counter() - t0, emit=False)
                self._commit_unit(item.filename)

    def _run_file_with_retry(self, item, res):
        """The per-file stage loop under the retry policy: a transient
        failure (an NFS flake mid-checkpoint-write) re-runs the chain —
        resume-safe, since completed stages skip off the checkpoint.
        Permanent (shape/validation) errors propagate immediately."""
        from comapreduce_tpu.resilience.retry import retry_call

        value, retries = retry_call(
            lambda: self.run_file(item.filename, data=item.payload),
            res.retry, key=item.filename,
            label=f"stage chain {item.filename}")
        res.record_recovered(item.filename, retries, stage="stage_chain")
        return value

    def _ledger_quality(self, filename: str, value, res) -> None:
        """Assemble + ledger the per-(feed, band) quality records for
        one finished file (docs/OPERATIONS.md §16). Strictly
        best-effort: quality bookkeeping must never fail a file whose
        science chain just succeeded, so every exception is logged and
        swallowed."""
        try:
            from comapreduce_tpu.ops.precision import PrecisionPolicy
            from comapreduce_tpu.telemetry import quality as q

            qcfg = q.QualityConfig.coerce(self.quality)
            if not qcfg.enabled or value is None:
                return
            slo = q.SloConfig.coerce(self.slo)
            prec = PrecisionPolicy.coerce(self.precision)
            records = q.assemble_quality_records(
                value, filename, rank=self.rank,
                precision_id=f"tod={prec.tod_dtype}|cgdot={prec.cg_dot}",
                masked=q.masked_from_ledger(res.ledger, filename))
            for rec in records:
                rec["flags"] = q.evaluate_record(rec, slo)
                rec["flagged"] = bool(rec["flags"])
            q.append_quality(
                q.quality_path(self.state_dir or self.output_dir,
                               self.rank), records)
            q.emit_alerts(records)
        except Exception:
            logger.exception("quality ledger failed for %s", filename)

    def _needs_tod(self, filename: str) -> bool:
        """False when every OUTPUT-producing stage of this file's chain
        will resume-skip — then the prefetch worker must not
        materialise its multi-GB TOD just for the chain to drop it (the
        serial path's lazy read cost near zero on fully-resumed files;
        prefetch must match). Group-less gate stages (CheckLevel1File:
        ``overwrite=True``, ``groups=()``) always run but are metadata
        checks a lazy handle serves; lazy is always *correct*, eager is
        only the read-ahead optimisation, so mispredicting here can
        never change results. The probe opens the checkpoint and lists
        its top-level groups only — decoding the whole (potentially
        hundreds of MB) Level-2 store here would compete with the very
        read-ahead this hook optimises."""
        from comapreduce_tpu.data.hdf5io import safe_hdf5_open

        l2path = level2_path(self.output_dir, filename, self.prefix)
        if not os.path.exists(l2path):
            return True  # checkpoint missing: normal first-run state
        try:
            # verify against the commit-time sha256 sidecar BEFORE
            # trusting the group listing: HDF5 can parse a bit-rotted
            # file whose data blocks are damaged, and resume-skipping
            # on it would fold the damage into the map
            from comapreduce_tpu.resilience.integrity import verify_file

            verify_file(l2path, kind="checkpoint")
            with safe_hdf5_open(l2path, "r") as f:
                have = set(f.keys())
        except Exception as exc:
            # checkpoint PRESENT but unreadable — that is never normal
            # (a partial copy, bit rot, a foreign file squatting on the
            # checkpoint name): say so and ledger the stale Level-2 file
            # instead of silently re-reading as if nothing happened.
            # Returning True re-runs the chain, whose atomic checkpoint
            # write replaces the corrupt file.
            logger.warning("corrupt/unreadable Level-2 checkpoint %s "
                           "(%s: %s); re-reducing %s from Level-1",
                           l2path, type(exc).__name__, exc, filename)
            self._quarantine_checkpoint(l2path, filename, exc)
            return True

        def contained(p) -> bool:
            return all(g.split("/")[0] in have
                       for g in getattr(p, "groups", ()))

        return any(
            getattr(p, "groups", ()) and
            (not contained(p) or getattr(p, "overwrite", False))
            for p in self.processes)

    def _quarantine_checkpoint(self, l2path: str, filename: str,
                               exc: BaseException) -> None:
        """Ledger a corrupt Level-2 checkpoint (shared by the resume
        probe and ``_run_file``): quarantined until the re-reduction
        rewrites it, so a destriper run in between never maps stale
        data. Idempotent (a checkpoint that stays corrupt across runs
        appends one entry, not one per probe), and lock contention is
        exempt — another rank mid-write is not a corrupt file."""
        from comapreduce_tpu.resilience.retry import (classify_error,
                                                      is_lock_error)

        res = self._resilience_runtime()
        if res.ledger is None or is_lock_error(exc) \
                or res.ledger.is_quarantined(l2path):
            return
        fclass = classify_error(exc)
        # checksum-proven damage gets the first-class ``corrupt``
        # disposition (skipped like quarantined, lifted by the same
        # ``recovered`` once the re-reduction rewrites the file) with
        # the digest evidence in the message
        res.ledger.record(l2path, error=exc,
                          failure_class=fclass,
                          disposition=("corrupt" if fclass == "corrupt"
                                       else "quarantined"),
                          stage="resume.checkpoint",
                          message=f"unreadable checkpoint for "
                                  f"{filename}: {exc}")

    def run_file(self, filename: str, data=None) -> COMAPLevel2:
        if self.profile_dir:
            import contextlib

            import jax

            os.makedirs(self.profile_dir, exist_ok=True)
            try:
                ctx = jax.profiler.trace(self.profile_dir)
            except Exception:  # profiler unsupported on this backend
                logger.warning("jax.profiler.trace unavailable; "
                               "running unprofiled")
                ctx = contextlib.nullcontext()
            with ctx:
                return self._run_file(filename, data)
        return self._run_file(filename, data)

    def _run_file(self, filename: str, data=None) -> COMAPLevel2:
        if data is None:
            data = COMAPLevel1()
            data.read(filename)
        l2path = level2_path(self.output_dir, filename, self.prefix)
        try:
            lvl2 = COMAPLevel2(filename=l2path)
        except Exception as exc:
            from comapreduce_tpu.resilience.retry import is_lock_error

            if is_lock_error(exc):
                # a WRITER holds the checkpoint (contention, not
                # corruption): never unlink a live file — let the
                # per-file retry policy re-attempt the chain
                raise
            # checkpoint present but unreadable (partial copy, bit rot):
            # start from a FRESH store under the same name — the first
            # stage's atomic write replaces the corrupt file whole. The
            # old behaviour let the open error bubble into the per-file
            # net, burning the whole observation on a stale checkpoint.
            logger.warning("unreadable Level-2 checkpoint %s (%s: %s); "
                           "starting fresh", l2path, type(exc).__name__,
                           exc)
            self._quarantine_checkpoint(l2path, filename, exc)
            try:
                # the corrupt file must go NOW: the atomic checkpoint
                # write copies an existing target before appending, and
                # appending into garbage raises. A kill between unlink
                # and first write just means a clean re-reduce on resume.
                os.unlink(l2path)
            except OSError:
                pass
            from comapreduce_tpu.resilience.integrity import drop_sidecar

            drop_sidecar(l2path)
            lvl2 = COMAPLevel2(filename="")
            lvl2.filename = l2path
        wrote = False
        for process in self.processes:
            pname = getattr(process, "name", type(process).__name__)
            process.pre_init(data)
            if lvl2.contains(process) and not process.overwrite:
                logger.info("%s: contained, skipping", pname)
                continue
            if hasattr(process, "clear_outputs"):
                process.clear_outputs()  # no stale outputs across files
            t0 = time.perf_counter()
            with TELEMETRY.span(pname,
                                unit=os.path.basename(filename)):
                state = process(data, lvl2)
            dt = time.perf_counter() - t0
            _record_timing(self.timings, pname, dt, emit=False)
            logger.info("%s: %.3f s (STATE=%s)", pname, dt, bool(state))
            if not state:
                logger.info("%s returned falsy STATE; aborting %s",
                            pname, filename)
                break
            lvl2.update(process)
            # checkpoint after EVERY stage; atomic so a kill mid-write
            # can't strand a half-written group that resume would skip
            # (async under [ingest] writeback: the snapshot queues on
            # the ordered background writer and the NEXT stage's device
            # compute overlaps this write)
            self._checkpoint(lvl2)
            wrote = True
        if self._writeback is not None:
            # per-file flush barrier: every queued checkpoint for this
            # file commits (durably) before the file's result exists.
            # A failed/hung async write surfaces HERE — inside the same
            # per-file retry/quarantine net a synchronous write error
            # would have hit — so resume, quarantine and kill-mid-write
            # semantics are byte-identical to the synchronous path.
            self._writeback.flush(lvl2.filename)
        res = self._resilience_runtime()
        if wrote and res.ledger is not None and \
                res.ledger.is_quarantined(lvl2.filename):
            # a checkpoint _needs_tod flagged as corrupt has now been
            # rewritten whole: lift its quarantine so downstream
            # (destriper filelists) sees it live again. Gated on an
            # ACTUAL write — a chain that aborted on falsy STATE before
            # writing must not record a recovery that never happened.
            res.ledger.record(lvl2.filename, disposition="recovered",
                              stage="resume.checkpoint",
                              message="checkpoint rewritten by "
                                      "re-reduction")
        return lvl2

    def _checkpoint(self, lvl2) -> None:
        """One stage checkpoint: synchronous atomic write, or — with
        ``[ingest] writeback >= 1`` — a host snapshot queued on the
        ordered background writer (``data/writeback.py``)."""
        wb = self._writeback
        if wb is None:
            lvl2.write(lvl2.filename, atomic=True)
            res = self._resilience_runtime()
            if res.chaos is not None:
                # bit_rot drills damage the COMMITTED checkpoint —
                # after the atomic write sealed its sidecar, so the
                # injected rot is detectable rot (the async path gets
                # the same shot inside Writeback's commit)
                res.chaos.maybe_bit_rot(lvl2.filename)
            return
        from comapreduce_tpu.data.writeback import snapshot_store

        wb.submit_store(lvl2.filename, snapshot_store(lvl2))

    def run_astro_cal(self, filelist: list[str],
                      calibrator_level2: list[str],
                      cache_path: str = "") -> list[COMAPLevel2]:
        """Apply astronomical calibration factors to target files
        (``Running.run_astro_cal``, ``Running.py:156-173``): factors are
        harvested from the calibrator Level-2 files, the nearest-in-MJD
        factor is written into each target's Level-2 store."""
        from comapreduce_tpu.calibration.apply_cal import ApplyCalibration

        stage = ApplyCalibration(
            calibrator_filelist=tuple(calibrator_level2),
            cache_path=cache_path)
        # the apply pass re-walks the SAME filelist whose reduction
        # leases are already committed in state_dir — under elastic
        # claiming a sub-run scheduler would see every unit "done
        # elsewhere" and apply calibration to nothing, so this pass
        # always uses the static rank::n_ranks shard (the Level-2
        # stores exist for every file regardless of which rank reduced
        # it); the ledger/heartbeat/chaos objects stay shared in-place
        res = self._resilience_runtime()
        if res.lease_ttl_s > 0:
            import dataclasses

            res = dataclasses.replace(res, lease_ttl_s=0.0)
        sub = Runner(processes=[stage], output_dir=self.output_dir,
                     prefix=self.prefix, rank=self.rank,
                     n_ranks=self.n_ranks, timings=self.timings,
                     ingest=self.ingest, resilience=self.resilience,
                     telemetry=self.telemetry,
                     quality=self.quality, slo=self.slo,
                     state_dir=self.state_dir,
                     _ingest_cache=self._ingest_cache,
                     _resilience=res)
        results = sub.run_tod(filelist)
        self._ingest_cache = sub._ingest_cache  # share warm cache back
        return results

    # -- config-driven construction ----------------------------------------
    @classmethod
    def from_config(cls, config: dict | str, rank: int = 0,
                    n_ranks: int = 1) -> "Runner":
        """Build from a TOML config (path or parsed dict).

        Layout (mirrors ``configuration.toml``): ``[Global]`` has
        ``processes`` (stage-name list), ``output_dir``, optional
        ``backend``; each ``[StageName]`` section holds that stage's
        kwargs (including per-stage ``backend``/``overwrite``). An
        optional ``[ingest]`` table (``prefetch``, ``cache_mb``,
        ``spill_dir``, ``compile_cache_dir``, ``writeback``) turns on
        streaming ingest / the persistent compile cache / async
        writeback (docs/ingest.md, docs/OPERATIONS.md §9); an
        optional ``[resilience]`` table (``quarantine``,
        ``max_retries``, ``inject``, ...) tunes the quarantine/retry/
        chaos layer (docs/OPERATIONS.md §7); an optional ``[campaign]``
        table (``t_quantum``, ``scan_quantum``, ``l_quantum``,
        ``warm_compile``) turns on the campaign shape policy and
        compile warm-up (docs/OPERATIONS.md §9); an optional
        ``[precision]`` table (``tod_dtype``, ``cg_dot``) sets the
        end-to-end precision policy — a typo'd key raises here, at
        load (docs/OPERATIONS.md §15)."""
        from comapreduce_tpu.control.config import ControlConfig
        from comapreduce_tpu.ingest import IngestConfig
        from comapreduce_tpu.ops.precision import PrecisionPolicy
        from comapreduce_tpu.pipeline.campaign import CampaignConfig
        from comapreduce_tpu.resilience import ResilienceConfig
        from comapreduce_tpu.telemetry.quality import (QualityConfig,
                                                       SloConfig)
        from comapreduce_tpu.tuning.cache import TuningConfig

        if isinstance(config, str):
            config = cfg_mod.load_toml(config)
        glob = config.get("Global", {})
        backend = glob.get("backend")
        processes = []
        for name in glob.get("processes", []):
            kwargs = dict(config.get(name, {}))
            kwargs.setdefault("backend", backend)
            processes.append(resolve(name, **kwargs))
        output_dir = glob.get("output_dir", ".")
        return cls(processes=processes,
                   output_dir=output_dir,
                   prefix=glob.get("prefix", "Level2"),
                   # run state (heartbeats/leases/queue) lives with the
                   # logs, not the science products (OPERATIONS.md §11)
                   state_dir=str(glob.get("log_dir", "") or
                                 os.path.join(output_dir, "logs")),
                   rank=rank, n_ranks=n_ranks,
                   ingest=IngestConfig.coerce(config.get("ingest")),
                   # campaign surface: elastic claiming is the DEFAULT
                   # here — [resilience] lease_ttl_s = 0 opts back into
                   # the static rank::n_ranks shard (OPERATIONS.md §11)
                   resilience=ResilienceConfig.coerce_campaign(
                       config.get("resilience")),
                   campaign=CampaignConfig.coerce(
                       config.get("campaign")),
                   # [telemetry] enabled/flush_s/jax_profiler: spans +
                   # counters to <log_dir>/events.rank{r}.jsonl
                   # (docs/OPERATIONS.md §13)
                   telemetry=TelemetryConfig.coerce(
                       config.get("telemetry")),
                   # [precision] tod_dtype/cg_dot: the end-to-end
                   # precision policy (docs/OPERATIONS.md §15)
                   precision=PrecisionPolicy.coerce(
                       config.get("precision")),
                   # [quality]/[slo]: the data-quality ledger and its
                   # declarative thresholds (docs/OPERATIONS.md §16)
                   quality=QualityConfig.coerce(config.get("quality")),
                   slo=SloConfig.coerce(config.get("slo")),
                   # [control]: supervisor/admission/solver-policy
                   # loops — absent table = every loop off
                   # (docs/OPERATIONS.md §19)
                   control=ControlConfig.coerce(config.get("control")),
                   # [tuning]: shape-bucket autotuner winners cache —
                   # absent table = untuned (docs/OPERATIONS.md §21)
                   tuning=TuningConfig.coerce(config.get("tuning")))

    @classmethod
    def from_legacy_config(cls, ini_path: str, rank: int = 0,
                           n_ranks: int = 1) -> "Runner":
        """Build from a legacy INI (``Module.Class(variant)`` registry,
        ``Tools/Parser.py:44-96``). Resilience knobs live in a
        ``[Resilience]`` section, campaign knobs in a ``[Campaign]``
        section (same names as the TOML tables)."""
        from comapreduce_tpu.control.config import ControlConfig
        from comapreduce_tpu.ingest import IngestConfig
        from comapreduce_tpu.pipeline.campaign import CampaignConfig
        from comapreduce_tpu.resilience import ResilienceConfig
        from comapreduce_tpu.telemetry.quality import (QualityConfig,
                                                       SloConfig)
        from comapreduce_tpu.tuning.cache import TuningConfig

        ini = cfg_mod.IniConfig(ini_path)
        processes = [resolve(name, **kwargs)
                     for name, kwargs in ini.pipeline_jobs()]
        inputs = ini.get("Inputs", {})
        output_dir = inputs.get("output_dir", ".")
        return cls(processes=processes,
                   output_dir=output_dir,
                   state_dir=str(inputs.get("log_dir", "") or
                                 os.path.join(output_dir, "logs")),
                   rank=rank, n_ranks=n_ranks,
                   ingest=IngestConfig.from_mapping(inputs),
                   # coerce, not from_mapping: [Resilience]/[Campaign]
                   # are DEDICATED sections, so a typo'd knob must
                   # raise instead of silently running with the
                   # default; campaign surface, so elastic claiming
                   # defaults ON (lease_ttl_s = 0 opts out)
                   resilience=ResilienceConfig.coerce_campaign(
                       dict(ini.get("Resilience", {}))),
                   campaign=CampaignConfig.coerce(
                       dict(ini.get("Campaign", {}))),
                   telemetry=TelemetryConfig.coerce(
                       dict(ini.get("Telemetry", {})) or None),
                   quality=QualityConfig.coerce(
                       dict(ini.get("Quality", {})) or None),
                   slo=SloConfig.coerce(
                       dict(ini.get("Slo", {})) or None),
                   control=ControlConfig.coerce(
                       dict(ini.get("Control", {})) or None),
                   tuning=TuningConfig.coerce(
                       dict(ini.get("Tuning", {})) or None))
