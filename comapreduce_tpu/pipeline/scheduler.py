"""Elastic campaign scheduler: a filesystem work queue over lease files.

Replaces the static ``files[rank::n_ranks]`` shard with dynamic
claiming: every rank runs the same two-phase loop over the SAME full
filelist, and the lease board (``resilience/lease.py``) arbitrates who
reduces what.

- **Phase 1 (claim pass)** walks the filelist in this rank's shard
  order first (``rank::n_ranks`` rotation — ranks start spread out
  instead of stampeding the same file) and claims every unit whose
  lease name is free. A rank that joins mid-campaign simply starts
  here: whatever is unclaimed is its to take — there is no membership
  list to update.
- **Phase 2 (steal loop)** polls the leftovers: units finished by
  other ranks drop out as ``done``; units whose owner's heartbeat went
  stale past ``lease_ttl_s`` are STOLEN (generation bumped) and
  re-reduced here. A rank that left — crashed, preempted, or paused
  zombie — needs no goodbye: its leases expire and the survivors
  drain them.

The caller must :meth:`commit` each yielded file after reducing it;
commit goes through the board's generation fence, so a zombie's late
commit of a stolen-and-redone unit returns False (counted in
``stats["fence_rejects"]``) and its output must be discarded. Steals
and stolen-unit recoveries are ledgered (``stolen`` / ``recovered``
dispositions) so the operator report can show exactly which units
moved ranks.

No services, no sockets: every rank only ever touches files in one
state directory, with the same durability discipline as
``data/durable.py``.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Iterator

from comapreduce_tpu.data.durable import durable_replace
from comapreduce_tpu.resilience.lease import Lease, LeaseBoard
from comapreduce_tpu.telemetry import TELEMETRY

__all__ = ["Scheduler", "QUEUE_MANIFEST"]

logger = logging.getLogger("comapreduce_tpu")

QUEUE_MANIFEST = "queue.json"


class Scheduler:
    """Per-rank view of one campaign's work queue.

    Parameters mirror the knobs in ``[resilience]``: ``lease_ttl_s``
    is the owner-heartbeat age beyond which a lease is stealable and
    ``steal_after_s`` the minimum lease-file age (0 = the TTL).
    ``ledger``/``chaos``/``heartbeat`` are the rank's
    :class:`~comapreduce_tpu.resilience.config.Resilience` members —
    the chaos hooks (``rank_kill`` / ``rank_pause``) fire at claim
    time, which is exactly where a preemption or a zombie hurts most.
    """

    def __init__(self, filelist, state_dir: str, rank: int = 0,
                 n_ranks: int = 1, heartbeat_dir: str | None = None,
                 lease_ttl_s: float = 60.0, steal_after_s: float = 0.0,
                 poll_s: float = 0.25, stall_timeout_s: float = 0.0,
                 ledger=None, chaos=None, heartbeat=None,
                 clock=time.monotonic, sleep=time.sleep):
        self.files = list(filelist)
        self.state_dir = state_dir or "."
        self.rank = int(rank)
        self.n_ranks = max(int(n_ranks), 1)
        self.board = LeaseBoard(self.state_dir, rank=self.rank,
                                heartbeat_dir=heartbeat_dir,
                                lease_ttl_s=lease_ttl_s,
                                steal_after_s=steal_after_s)
        self.poll_s = float(poll_s)
        # no unit going done/stolen for this long means the queue is
        # wedged (e.g. a survivor-less campaign): bail out instead of
        # spinning forever — generous default of several TTLs
        self.stall_timeout_s = (float(stall_timeout_s)
                                or 4.0 * self.board.lease_ttl_s + 30.0)
        self.ledger = ledger
        self.chaos = chaos
        self.heartbeat = heartbeat
        self.clock = clock
        self.sleep = sleep
        self._held: dict[str, Lease] = {}
        self.stats = {"claimed": 0, "stolen": 0, "committed": 0,
                      "recovered": 0, "fence_rejects": 0,
                      "done_elsewhere": 0, "abandoned": 0}
        self._write_manifest()

    def _bump(self, key: str, n: int = 1) -> None:
        """Stats bump mirrored into the telemetry counter stream —
        claim/steal/fence-reject rates become cross-rank counter
        tracks in campaign_report's merged timeline."""
        self.stats[key] += n
        TELEMETRY.counter("scheduler." + key, n)

    # -- the queue ----------------------------------------------------------
    def claim_iter(self) -> Iterator[str]:
        """Yield every file this rank gets to reduce; returns when the
        whole campaign's queue has drained (every unit done somewhere,
        or abandoned after ``stall_timeout_s`` of no progress)."""
        order = (self.files[self.rank % self.n_ranks::self.n_ranks]
                 + [f for r in range(self.n_ranks)
                    if r != self.rank % self.n_ranks
                    for f in self.files[r::self.n_ranks]])
        pending = []  # held by other ranks: revisit in the steal loop
        for f in order:
            if self.board.is_done(f):
                self._bump("done_elsewhere")
                continue
            lease = self.board.claim(f)
            if lease is None:
                pending.append(f)
                continue
            yield self._grant(f, lease)
        # steal loop: wait out the other ranks' units
        last_progress = self.clock()
        while pending:
            still = []
            progressed = False
            for f in pending:
                if self.board.is_done(f):
                    self._bump("done_elsewhere")
                    progressed = True
                    continue
                lease = self.board.claim(f)  # released or fence-gap
                if lease is None and self.board.expired(f):
                    lease = self.board.steal(f)
                    if lease is not None:
                        self._bump("stolen")
                        self._ledger_steal(f, lease)
                if lease is None:
                    still.append(f)
                    continue
                progressed = True
                yield self._grant(f, lease)
            pending = still
            if progressed:
                last_progress = self.clock()
            elif self.clock() - last_progress > self.stall_timeout_s:
                self._abandon(pending)
                return
            if pending:
                self.sleep(self.poll_s)

    def commit(self, filename: str) -> bool:
        """Publish ``filename`` done through the generation fence.
        False = the unit was stolen while we worked (we are the
        zombie): the caller MUST discard its result for this unit."""
        lease = self._held.pop(filename, None)
        if lease is None:
            return False
        ok = self.board.commit(lease)
        if ok:
            self._bump("committed")
            if lease.stolen_from is not None:
                self._bump("recovered")
                self._ledger_recovered(filename, lease)
            # wake any map server tailing this campaign (best effort —
            # the done lease is the durable fact, this is only latency)
            try:
                from comapreduce_tpu.serving.watcher import announce_commit

                announce_commit(self.state_dir, filename)
            except Exception:  # pragma: no cover - advisory only
                pass
        else:
            self._bump("fence_rejects")
        return ok

    def release_held(self) -> int:
        """Give back any claims yielded but never committed (clean
        shutdown mid-queue); returns how many were released."""
        n = 0
        for f, lease in list(self._held.items()):
            if self.board.release(lease):
                n += 1
            self._held.pop(f, None)
        return n

    # -- internals ----------------------------------------------------------
    def _grant(self, filename: str, lease: Lease) -> str:
        self._held[filename] = lease
        self._bump("claimed")
        if self.chaos is not None:
            # rank_kill: SIGKILL self mid-lease (the preempted rank);
            # rank_pause: freeze the heartbeat but keep working (the
            # zombie whose late commit the fence must reject)
            self.chaos.maybe_kill(filename)
            if self.chaos.maybe_pause(filename) and \
                    self.heartbeat is not None:
                self.heartbeat.pause()
        return filename

    def _abandon(self, pending) -> None:
        self._bump("abandoned", len(pending))
        logger.error(
            "scheduler rank %d: queue stalled for %.0f s with %d "
            "unit(s) still leased elsewhere and not expiring — "
            "abandoning them (see the ledger)", self.rank,
            self.stall_timeout_s, len(pending))
        if self.ledger is None:
            return
        for f in pending:
            st = self.board.state(f) or {}
            self.ledger.record(
                f, error=None, failure_class="hang",
                disposition="rejected", stage="scheduler.queue",
                message=f"queue stalled: lease held by rank "
                        f"{st.get('owner')} gen {st.get('generation')} "
                        f"never completed nor expired")

    def _ledger_steal(self, filename: str, lease: Lease) -> None:
        if self.ledger is None:
            return
        self.ledger.record(
            filename, error=None, failure_class="hang",
            disposition="stolen", stage="scheduler.steal",
            message=f"lease stolen from rank {lease.stolen_from} "
                    f"(heartbeat stale past "
                    f"{self.board.lease_ttl_s:g} s); redoing here as "
                    f"gen {lease.generation}")

    def _ledger_recovered(self, filename: str, lease: Lease) -> None:
        if self.ledger is None:
            return
        self.ledger.record(
            filename, error=None, failure_class="hang",
            disposition="recovered", stage="scheduler.steal",
            message=f"stolen unit re-reduced and committed by rank "
                    f"{self.rank} at gen {lease.generation}")

    def _write_manifest(self) -> None:
        """Durably publish the campaign's file set once (first rank
        wins; later ranks verify they agree). The manifest is what
        lets ``tools/watchdog_report.py`` count pending units."""
        path = os.path.join(self.state_dir, QUEUE_MANIFEST)
        names = [os.path.basename(f) for f in self.files]
        try:
            with open(path, "r", encoding="utf-8") as f:
                have = json.load(f)
            if sorted(have.get("files", [])) != sorted(names):
                logger.warning(
                    "scheduler rank %d: %s lists %d unit(s) but this "
                    "rank was given %d — ranks should share one "
                    "filelist", self.rank, QUEUE_MANIFEST,
                    len(have.get("files", [])), len(names))
            return
        except (OSError, ValueError):
            pass
        os.makedirs(self.state_dir, exist_ok=True)
        tmp = os.path.join(self.state_dir,
                           f".{QUEUE_MANIFEST}.{os.getpid()}.tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"schema": 1, "n": len(names), "files": names,
                       "t_wall": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                               time.gmtime())}, f)
        durable_replace(tmp, path)
