"""Elastic campaign scheduler: a filesystem work queue over lease files.

Replaces the static ``files[rank::n_ranks]`` shard with dynamic
claiming: every rank runs the same two-phase loop over the SAME full
filelist, and the lease board (``resilience/lease.py``) arbitrates who
reduces what.

- **Phase 1 (claim pass)** walks the filelist in this rank's shard
  order first (``rank::n_ranks`` rotation — ranks start spread out
  instead of stampeding the same file) and claims every unit whose
  lease name is free. A rank that joins mid-campaign simply starts
  here: whatever is unclaimed is its to take — there is no membership
  list to update.
- **Phase 2 (steal loop)** polls the leftovers: units finished by
  other ranks drop out as ``done``; units whose owner's heartbeat went
  stale past ``lease_ttl_s`` are STOLEN (generation bumped) and
  re-reduced here. A rank that left — crashed, preempted, or paused
  zombie — needs no goodbye: its leases expire and the survivors
  drain them.

The caller must :meth:`commit` each yielded file after reducing it;
commit goes through the board's generation fence, so a zombie's late
commit of a stolen-and-redone unit returns False (counted in
``stats["fence_rejects"]``) and its output must be discarded. Steals
and stolen-unit recoveries are ledgered (``stolen`` / ``recovered``
dispositions) so the operator report can show exactly which units
moved ranks.

No services, no sockets: every rank only ever touches files in one
state directory, with the same durability discipline as
``data/durable.py``.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Iterator

from comapreduce_tpu.data.durable import durable_replace
from comapreduce_tpu.resilience.integrity import check_json, seal_json
from comapreduce_tpu.resilience.lease import Lease, LeaseBoard
from comapreduce_tpu.telemetry import TELEMETRY

__all__ = ["Scheduler", "QUEUE_MANIFEST", "extend_manifest",
           "read_manifest"]

logger = logging.getLogger("comapreduce_tpu")

QUEUE_MANIFEST = "queue.json"


def read_manifest(state_dir: str) -> dict | None:
    """Parse the shared queue manifest; None when missing/torn — or
    when the manifest parses but fails its embedded ``_sha256`` seal
    (rotted in place: a wrong file census silently shrinking the
    campaign is the failure mode this rejects)."""
    try:
        with open(os.path.join(state_dir or ".", QUEUE_MANIFEST), "r",
                  encoding="utf-8") as f:
            man = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(man, dict):
        return None
    man, verdict = check_json(man)
    if verdict is False:
        logger.warning("queue manifest in %s fails its _sha256 seal; "
                       "ignoring it (run tools/campaign_fsck.py)",
                       state_dir)
        return None
    return man


def extend_manifest(state_dir: str, new_files) -> list:
    """Append late-arriving units to the shared ``queue.json`` — a
    chaos ``load_spike``, or an operator dropping a fresh observing
    session into a live campaign. Returns the full paths actually
    added (units already queued, by basename, are skipped).

    The manifest keeps listing basenames in ``files`` (what the
    operator report counts); full paths for the additions ride in
    ``added_paths`` so sibling ranks re-polling the manifest
    (``Scheduler`` steal loop) can claim units their own filelist
    never mentioned. Durable replace, same discipline as the first
    write — the burst either landed whole or not at all."""
    man = read_manifest(state_dir) or {"schema": 1, "files": []}
    have = set(man.get("files", []))
    added = [f for f in new_files if os.path.basename(f) not in have]
    if not added:
        return []
    man["files"] = list(man.get("files", [])) + \
        [os.path.basename(f) for f in added]
    man["n"] = len(man["files"])
    paths = dict(man.get("added_paths", {}))
    paths.update({os.path.basename(f): f for f in added})
    man["added_paths"] = paths
    man["t_wall"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    os.makedirs(state_dir or ".", exist_ok=True)
    tmp = os.path.join(state_dir or ".",
                       f".{QUEUE_MANIFEST}.{os.getpid()}.ext.tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(seal_json(man), f)
    durable_replace(tmp, os.path.join(state_dir or ".", QUEUE_MANIFEST))
    logger.warning("queue manifest %s: %d late unit(s) appended "
                   "(%d total)", state_dir, len(added), man["n"])
    return added


class Scheduler:
    """Per-rank view of one campaign's work queue.

    Parameters mirror the knobs in ``[resilience]``: ``lease_ttl_s``
    is the owner-heartbeat age beyond which a lease is stealable and
    ``steal_after_s`` the minimum lease-file age (0 = the TTL).
    ``ledger``/``chaos``/``heartbeat`` are the rank's
    :class:`~comapreduce_tpu.resilience.config.Resilience` members —
    the chaos hooks (``rank_kill`` / ``rank_pause``) fire at claim
    time, which is exactly where a preemption or a zombie hurts most.

    ``admission`` is the optional control-plane gate (duck-typed as
    :class:`~comapreduce_tpu.control.admission.AdmissionController`):
    consulted on every just-claimed unit, it may answer with a defer
    reason, in which case the claim is released, the unit is ledgered
    ``deferred``, and it re-enters the queue when pressure clears —
    shed, never dropped. ``None`` (the default) admits everything,
    byte-for-byte the pre-control behavior.
    """

    def __init__(self, filelist, state_dir: str, rank: int = 0,
                 n_ranks: int = 1, heartbeat_dir: str | None = None,
                 lease_ttl_s: float = 60.0, steal_after_s: float = 0.0,
                 poll_s: float = 0.25, stall_timeout_s: float = 0.0,
                 ledger=None, chaos=None, heartbeat=None,
                 admission=None, clock=time.monotonic,
                 sleep=time.sleep):
        self.files = list(filelist)
        self.state_dir = state_dir or "."
        self.rank = int(rank)
        self.n_ranks = max(int(n_ranks), 1)
        self.board = LeaseBoard(self.state_dir, rank=self.rank,
                                heartbeat_dir=heartbeat_dir,
                                lease_ttl_s=lease_ttl_s,
                                steal_after_s=steal_after_s)
        self.poll_s = float(poll_s)
        # no unit going done/stolen for this long means the queue is
        # wedged (e.g. a survivor-less campaign): bail out instead of
        # spinning forever — generous default of several TTLs
        self.stall_timeout_s = (float(stall_timeout_s)
                                or 4.0 * self.board.lease_ttl_s + 30.0)
        self.ledger = ledger
        self.chaos = chaos
        self.heartbeat = heartbeat
        self.admission = admission
        self.clock = clock
        self.sleep = sleep
        self._held: dict[str, Lease] = {}
        self._deferred: list[str] = []
        self._outstanding: set[str] = set(self.files)
        self.stats = {"claimed": 0, "stolen": 0, "committed": 0,
                      "recovered": 0, "fence_rejects": 0,
                      "done_elsewhere": 0, "abandoned": 0,
                      "deferred": 0, "readmitted": 0, "spiked": 0}
        self._write_manifest()

    def _bump(self, key: str, n: int = 1) -> None:
        """Stats bump mirrored into the telemetry counter stream —
        claim/steal/fence-reject rates become cross-rank counter
        tracks in campaign_report's merged timeline."""
        self.stats[key] += n
        TELEMETRY.counter("scheduler." + key, n)

    # -- the queue ----------------------------------------------------------
    def claim_iter(self) -> Iterator[str]:
        """Yield every file this rank gets to reduce; returns when the
        whole campaign's queue has drained (every unit done somewhere,
        or abandoned after ``stall_timeout_s`` of no progress)."""
        order = (self.files[self.rank % self.n_ranks::self.n_ranks]
                 + [f for r in range(self.n_ranks)
                    if r != self.rank % self.n_ranks
                    for f in self.files[r::self.n_ranks]])
        pending = []  # held by other ranks: revisit in the steal loop
        for f in order:
            if self.board.is_done(f):
                self._bump("done_elsewhere")
                self._outstanding.discard(f)
                continue
            lease = self.board.claim(f)
            if lease is None:
                pending.append(f)
                continue
            if self._shed(f, lease):
                continue
            yield self._grant(f, lease)
        # steal loop: wait out the other ranks' units
        pending.extend(self._poll_manifest())
        last_progress = self.clock()
        while pending or self._deferred:
            still = []
            progressed = False
            for f in pending:
                if self.board.is_done(f):
                    self._bump("done_elsewhere")
                    self._outstanding.discard(f)
                    progressed = True
                    continue
                lease = self.board.claim(f)  # released or fence-gap
                if lease is None and self.board.expired(f):
                    lease = self.board.steal(f)
                    if lease is not None:
                        self._bump("stolen")
                        self._ledger_steal(f, lease)
                if lease is None:
                    still.append(f)
                    continue
                progressed = True
                if self._shed(f, lease):
                    continue
                yield self._grant(f, lease)
            pending = still
            # late arrivals: a load_spike burst or an operator append
            # lands in the shared manifest mid-campaign
            new = self._poll_manifest()
            if new:
                pending.extend(new)
                progressed = True
            # re-admission pass: shed units return when admission
            # pressure clears, when they finished elsewhere, or when
            # nothing but deferred work remains — a shed unit is
            # never silently dropped
            if self._deferred:
                clear = (self.admission is None or
                         self.admission.pressure_cleared(self.backlog()))
                for f in list(self._deferred):
                    if self.board.is_done(f):
                        self._deferred.remove(f)
                        self._outstanding.discard(f)
                        self._bump("done_elsewhere")
                        progressed = True
                        continue
                    if not clear and pending:
                        continue
                    lease = self.board.claim(f)
                    if lease is None and self.board.expired(f):
                        lease = self.board.steal(f)
                        if lease is not None:
                            self._bump("stolen")
                            self._ledger_steal(f, lease)
                    if lease is None:
                        continue
                    self._deferred.remove(f)
                    self._bump("readmitted")
                    self._ledger_readmitted(f)
                    progressed = True
                    yield self._grant(f, lease)
            if progressed:
                last_progress = self.clock()
            elif self.clock() - last_progress > self.stall_timeout_s:
                self._abandon(pending)
                return
            if pending or self._deferred:
                self.sleep(self.poll_s)

    def backlog(self) -> int:
        """Units this rank still sees as not done anywhere, excluding
        ones it shed (``deferred``) or currently holds — the
        admission-control pressure signal."""
        return max(len(self._outstanding) - len(self._deferred)
                   - len(self._held), 0)

    def commit(self, filename: str) -> bool:
        """Publish ``filename`` done through the generation fence.
        False = the unit was stolen while we worked (we are the
        zombie): the caller MUST discard its result for this unit."""
        lease = self._held.pop(filename, None)
        if lease is None:
            return False
        ok = self.board.commit(lease)
        if ok:
            self._bump("committed")
            self._outstanding.discard(filename)
            if lease.stolen_from is not None:
                self._bump("recovered")
                self._ledger_recovered(filename, lease)
            # wake any map server tailing this campaign (best effort —
            # the done lease is the durable fact, this is only latency)
            try:
                from comapreduce_tpu.serving.watcher import announce_commit

                announce_commit(self.state_dir, filename)
            except Exception:  # pragma: no cover - advisory only
                pass
            if self.chaos is not None:
                # load_spike: a burst of extra units arrives at commit
                # time — published to the shared manifest so EVERY
                # rank's steal loop (including ours) picks them up
                burst = self.chaos.maybe_spike(filename)
                if burst:
                    added = extend_manifest(self.state_dir, burst)
                    if added:
                        self._bump("spiked", len(added))
        else:
            self._bump("fence_rejects")
        return ok

    def release_held(self) -> int:
        """Give back any claims yielded but never committed (clean
        shutdown mid-queue); returns how many were released."""
        n = 0
        for f, lease in list(self._held.items()):
            if self.board.release(lease):
                n += 1
            self._held.pop(f, None)
        return n

    # -- internals ----------------------------------------------------------
    def _grant(self, filename: str, lease: Lease) -> str:
        self._held[filename] = lease
        self._bump("claimed")
        if self.chaos is not None:
            # rank_kill: SIGKILL self mid-lease (the preempted rank);
            # rank_pause: freeze the heartbeat but keep working (the
            # zombie whose late commit the fence must reject)
            self.chaos.maybe_kill(filename)
            if self.chaos.maybe_pause(filename) and \
                    self.heartbeat is not None:
                self.heartbeat.pause()
        return filename

    def _shed(self, filename: str, lease: Lease) -> bool:
        """Admission-control gate on a just-claimed unit. True = the
        unit was shed: claim released, ledgered ``deferred``, queued
        locally for re-admission when pressure clears."""
        if self.admission is None:
            return False
        reason = self.admission.should_defer(filename, self.backlog())
        if not reason:
            return False
        self.board.release(lease)
        self._deferred.append(filename)
        self._bump("deferred")
        logger.warning("scheduler rank %d: unit %s deferred under "
                       "admission pressure (%s)", self.rank,
                       os.path.basename(filename), reason)
        if self.ledger is not None:
            self.ledger.record(
                filename, error=None, failure_class="quality",
                disposition="deferred", stage="control.admission",
                message=reason)
        return True

    def _poll_manifest(self) -> list:
        """Pick up units appended to the shared manifest after this
        rank built its queue (:func:`extend_manifest` — a load_spike
        burst or an operator append); returns their full paths."""
        man = read_manifest(self.state_dir)
        if man is None:
            return []
        known = {os.path.basename(f) for f in self.files}
        paths = man.get("added_paths", {})
        home = os.path.dirname(self.files[0]) if self.files else ""
        new = []
        for name in man.get("files", []):
            if name in known:
                continue
            new.append(paths.get(name) or
                       (os.path.join(home, name) if home else name))
        if new:
            self.files.extend(new)
            self._outstanding.update(new)
            logger.info("scheduler rank %d: %d late unit(s) joined "
                        "the queue", self.rank, len(new))
        return new

    def _abandon(self, pending) -> None:
        self._bump("abandoned", len(pending))
        for f in pending:
            self._outstanding.discard(f)
        logger.error(
            "scheduler rank %d: queue stalled for %.0f s with %d "
            "unit(s) still leased elsewhere and not expiring — "
            "abandoning them (see the ledger)", self.rank,
            self.stall_timeout_s, len(pending))
        if self.ledger is None:
            return
        for f in pending:
            st = self.board.state(f) or {}
            self.ledger.record(
                f, error=None, failure_class="hang",
                disposition="rejected", stage="scheduler.queue",
                message=f"queue stalled: lease held by rank "
                        f"{st.get('owner')} gen {st.get('generation')} "
                        f"never completed nor expired")

    def _ledger_steal(self, filename: str, lease: Lease) -> None:
        if self.ledger is None:
            return
        self.ledger.record(
            filename, error=None, failure_class="hang",
            disposition="stolen", stage="scheduler.steal",
            message=f"lease stolen from rank {lease.stolen_from} "
                    f"(heartbeat stale past "
                    f"{self.board.lease_ttl_s:g} s); redoing here as "
                    f"gen {lease.generation}")

    def _ledger_readmitted(self, filename: str) -> None:
        if self.ledger is None:
            return
        self.ledger.record(
            filename, error=None, failure_class="quality",
            disposition="readmitted", stage="control.admission",
            message=f"admission pressure cleared; unit re-admitted "
                    f"on rank {self.rank}")

    def _ledger_recovered(self, filename: str, lease: Lease) -> None:
        if self.ledger is None:
            return
        self.ledger.record(
            filename, error=None, failure_class="hang",
            disposition="recovered", stage="scheduler.steal",
            message=f"stolen unit re-reduced and committed by rank "
                    f"{self.rank} at gen {lease.generation}")

    def _write_manifest(self) -> None:
        """Durably publish the campaign's file set once (first rank
        wins; later ranks verify they agree). The manifest is what
        lets ``tools/watchdog_report.py`` count pending units."""
        path = os.path.join(self.state_dir, QUEUE_MANIFEST)
        names = [os.path.basename(f) for f in self.files]
        try:
            with open(path, "r", encoding="utf-8") as f:
                have = json.load(f)
            if sorted(have.get("files", [])) != sorted(names):
                logger.warning(
                    "scheduler rank %d: %s lists %d unit(s) but this "
                    "rank was given %d — ranks should share one "
                    "filelist", self.rank, QUEUE_MANIFEST,
                    len(have.get("files", [])), len(names))
            return
        except (OSError, ValueError):
            pass
        os.makedirs(self.state_dir, exist_ok=True)
        tmp = os.path.join(self.state_dir,
                           f".{QUEUE_MANIFEST}.{os.getpid()}.tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(seal_json(
                {"schema": 1, "n": len(names), "files": names,
                 "t_wall": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime())}), f)
        durable_replace(tmp, path)
