"""Campaign executor support: shape buckets, compile warm-up, counters.

PR 4 made ONE observation nearly roofline-optimal; a production
campaign is hundreds of Level-1 files, and today every distinct
``(T, S, L)`` geometry recompiles the flagship programs on the
critical path. This module moves the unit of optimisation from "one
observation" to "the filelist" (ISSUE 5 tentpole):

- :class:`CampaignConfig` — the ``[campaign]`` TOML table / ``[Campaign]``
  INI section: the :class:`~comapreduce_tpu.ops.reduce.ShapeBuckets`
  quanta plus the ``warm_compile`` switch. All defaults off: zero
  behaviour change for existing configs.
- :func:`enable_compile_cache` — turns on JAX's persistent compilation
  cache (the ``[ingest] compile_cache_dir`` knob): compiled programs
  are keyed by HLO and reused across *processes*, so a steady-state
  campaign run never XLA-compiles on the critical path.
- :func:`start_warmup` / :class:`Warmup` — AOT-compiles
  (``jit(...).lower().compile()``) the campaign's bucket set on a
  background thread, overlapped with the first file's prefetch. AOT
  compiles do NOT prime a jit's in-process dispatch cache (measured:
  the next call still triggers a backend compile request), but with the
  persistent cache enabled that request is a disk HIT — which is why
  warm-up requires ``compile_cache_dir`` and is skipped (loudly)
  without it.
- :class:`CompileCounter` — compile observability through
  ``jax.monitoring`` event hooks: backend-compile requests and
  persistent-cache hits/misses. ``bench.py`` reports them
  (``compile_count`` / ``cache_hit_count``) and
  ``tools/check_perf.py`` gates steady-state recompiles against the
  bucket count.
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass

import numpy as np

from comapreduce_tpu.ops.reduce import ShapeBuckets, scan_starts_lengths

__all__ = ["CampaignConfig", "CompileCounter", "enable_compile_cache",
           "probe_observation", "campaign_bucket_set", "Warmup",
           "start_warmup"]

logger = logging.getLogger("comapreduce_tpu")


@dataclass(frozen=True)
class CampaignConfig:
    """Knobs for the campaign-throughput layer.

    t_quantum / scan_quantum / l_quantum:
        :class:`~comapreduce_tpu.ops.reduce.ShapeBuckets` quanta — each
        axis of an observation's ``(T, S, L)`` geometry is rounded UP
        to its quantum so a whole filelist shares a small set of
        compiled program shapes (0 = that axis stays per-file exact).
        Padded samples are masked (NaN tail -> zero validity;
        zero-length scans dropped by the scatter), so bucketed outputs
        match the unpadded path (pinned by ``tests/test_campaign.py``).
        Worst-case padding overhead per axis is ``quantum - 1``
        samples; keep quanta a few percent of the axis (e.g.
        ``t_quantum = 4096`` against a 135k-sample production T).
    warm_compile:
        AOT-compile the campaign's bucket set on a background thread
        overlapped with the first file's prefetch. Requires
        ``[ingest] compile_cache_dir`` (AOT results reach the steady
        state only through the persistent cache); without it the
        warm-up is skipped with a warning.
    """

    t_quantum: int = 0
    scan_quantum: int = 0
    l_quantum: int = 0
    warm_compile: bool = False

    def __post_init__(self):
        object.__setattr__(self, "t_quantum",
                           max(int(self.t_quantum or 0), 0))
        object.__setattr__(self, "scan_quantum",
                           max(int(self.scan_quantum or 0), 0))
        object.__setattr__(self, "l_quantum",
                           max(int(self.l_quantum or 0), 0))
        object.__setattr__(self, "warm_compile",
                           bool(self.warm_compile))

    KNOBS = ("t_quantum", "scan_quantum", "l_quantum", "warm_compile")

    @classmethod
    def coerce(cls, value) -> "CampaignConfig":
        """Build from None / dict / CampaignConfig. A dedicated
        ``[campaign]`` table rejects unknown keys (typo'd knobs raise
        at config load, the ResilienceConfig contract)."""
        if value is None:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            known = {k: value[k] for k in cls.KNOBS if k in value}
            unknown = set(value) - set(known)
            if unknown:
                raise ValueError(
                    f"unknown campaign keys: {sorted(unknown)}")
            return cls(**known)
        raise TypeError(f"cannot build CampaignConfig from {type(value)}")

    def shape_buckets(self) -> ShapeBuckets:
        return ShapeBuckets(t_quantum=self.t_quantum,
                            scan_quantum=self.scan_quantum,
                            l_quantum=self.l_quantum)


# --------------------------------------------------------------------------
# Persistent compilation cache
# --------------------------------------------------------------------------

_CACHE_DIR_ENABLED: str | None = None


def enable_compile_cache(cache_dir: str) -> bool:
    """Enable JAX's persistent compilation cache at ``cache_dir``
    (idempotent; returns True when active). Thresholds are dropped to
    zero so even the quick CI shapes cache — the default floors would
    silently skip small programs and the no-recompile gate could never
    observe a hit."""
    global _CACHE_DIR_ENABLED
    if not cache_dir:
        return False
    if _CACHE_DIR_ENABLED == cache_dir:
        return True
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    for knob, value in (("jax_persistent_cache_min_entry_size_bytes", -1),
                        ("jax_persistent_cache_min_compile_time_secs", 0)):
        try:
            jax.config.update(knob, value)
        except Exception:  # older jax: thresholds unknown — cache still on
            logger.info("compile cache: %s unsupported on this jax", knob)
    try:
        # jax latches its is-cache-used decision at the FIRST backend
        # compile of the process; any jit call before this knob was set
        # would have frozen "no cache" for the process lifetime. Reset
        # the latch (and the in-memory cache object) so enabling
        # mid-process takes effect — the campaign CLI path sets the knob
        # before the first file, but library users may not.
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # pragma: no cover - private API moved
        logger.warning("compile cache: could not reset jax's cache "
                       "latch; a pre-existing compile may have pinned "
                       "the cache off for this process")
    _CACHE_DIR_ENABLED = cache_dir
    logger.info("persistent compilation cache enabled at %s", cache_dir)
    return True


# --------------------------------------------------------------------------
# Compile-event observability
# --------------------------------------------------------------------------

_ACTIVE_COUNTERS: list = []
_HOOKS_INSTALLED = False
_HOOK_LOCK = threading.Lock()


def _on_event(event: str, **kwargs) -> None:
    from comapreduce_tpu.telemetry import TELEMETRY

    if event == "/jax/compilation_cache/cache_hits":
        for c in list(_ACTIVE_COUNTERS):
            c._bump("cache_hits")
        TELEMETRY.counter("jax.compile_cache.hits")
    elif event == "/jax/compilation_cache/cache_misses":
        for c in list(_ACTIVE_COUNTERS):
            c._bump("cache_misses")
        TELEMETRY.counter("jax.compile_cache.misses")


def _on_duration(event: str, duration_secs: float, **kwargs) -> None:
    if event.endswith("backend_compile_duration"):
        for c in list(_ACTIVE_COUNTERS):
            c._bump("backend_compiles", duration_secs)
        # every backend compile becomes a span: a steady-state
        # campaign segment must show ZERO of these — the recompile
        # gate campaign_report and check_perf read
        from comapreduce_tpu.telemetry import TELEMETRY

        TELEMETRY.event_span("jax.compile", duration_secs,
                             event=event)


def _install_hooks() -> None:
    global _HOOKS_INSTALLED
    with _HOOK_LOCK:
        if _HOOKS_INSTALLED:
            return
        import jax

        # jax.monitoring has no per-listener removal, so ONE pair of
        # module-level dispatchers is registered for the process
        # lifetime and counters attach/detach from _ACTIVE_COUNTERS
        jax.monitoring.register_event_listener(_on_event)
        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _HOOKS_INSTALLED = True


class CompileCounter:
    """Counts XLA compile activity through ``jax.monitoring`` hooks.

    - ``backend_compiles``: compile REQUESTS that reached the backend
      (in-process jit-cache misses). With the persistent cache enabled
      a request can still be a fast disk hit — split by
      ``cache_hits`` / ``cache_misses``. In a steady-state campaign
      (shapes canonicalised, programs in the in-process caches) this
      stays at zero per file, which is what the no-recompile gate
      measures.
    - ``compile_s``: wall seconds spent in backend compiles.

    Use :meth:`install` / :meth:`remove` (or as a context manager);
    :meth:`snapshot` returns a plain dict copy for deltas.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.counts = {"backend_compiles": 0, "cache_hits": 0,
                       "cache_misses": 0, "compile_s": 0.0}

    def _bump(self, key: str, duration: float = 0.0) -> None:
        with self._lock:
            self.counts[key] += 1
            if duration:
                self.counts["compile_s"] += float(duration)

    def install(self) -> "CompileCounter":
        _install_hooks()
        if self not in _ACTIVE_COUNTERS:
            _ACTIVE_COUNTERS.append(self)
        return self

    def remove(self) -> None:
        try:
            _ACTIVE_COUNTERS.remove(self)
        except ValueError:
            pass

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self.counts)

    def __enter__(self) -> "CompileCounter":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.remove()


# --------------------------------------------------------------------------
# Bucket probing + AOT warm-up
# --------------------------------------------------------------------------

def probe_observation(path: str, pad_to: int = 128) -> dict:
    """Header-only geometry probe of one Level-1 file: ``{F, B, C, T,
    S, L, calibrator}``. Reads the TOD *shape* and the (small) feature/
    housekeeping streams; the multi-GB TOD itself stays on disk — cheap
    enough to probe a whole campaign on the warm-up thread."""
    from comapreduce_tpu.data.level import COMAPLevel1

    if path.startswith("synth://"):
        # virtual scenario member: geometry is arithmetic on the
        # scenario — no TOD generation on the warm-up thread
        from comapreduce_tpu.synthetic.memsource import probe_virtual

        return probe_virtual(path, pad_to=pad_to)
    data = COMAPLevel1()
    data.read(path)
    try:
        F, B, C, T = (int(x) for x in data.tod_shape)
        edges = np.asarray(data.scan_edges)
        calibrator = bool(data.is_calibrator)
    finally:
        data.close()
    if len(edges):
        _, _, L = scan_starts_lengths(edges, pad_to=pad_to)
    else:
        L = int(pad_to)
    return {"F": F, "B": B, "C": C, "T": T, "S": int(len(edges)),
            "L": int(L), "calibrator": calibrator}


def campaign_bucket_set(shapes, buckets: ShapeBuckets) -> set:
    """Distinct canonical buckets of a probed shape list:
    ``{(F, B, C, Tb, Sb, Lb, calibrator)}`` — the campaign's compile
    budget (one program set per member)."""
    out = set()
    for s in shapes:
        Tb, Sb, Lb = buckets.canonical(s["T"], s["S"], s["L"])
        out.add((s["F"], s["B"], s["C"], Tb, Sb, Lb,
                 bool(s.get("calibrator", False))))
    return out


class Warmup:
    """Background AOT warm-up of the campaign's bucket set.

    Probes every file's geometry, canonicalises it, and calls each
    stage's ``warm_programs(**shape)`` hook once per distinct bucket —
    the stages AOT-compile (``lower().compile()``) exactly the programs
    their ``__call__`` will launch, at exactly the canonical shapes, so
    the persistent cache is hot before the first file's stage chain
    runs. Failures are logged, never fatal: warm-up is an optimisation,
    the inline compile path remains correct.
    """

    def __init__(self, stages, files, pad_to: int = 128,
                 buckets: ShapeBuckets | None = None):
        self._stages = [s for s in stages
                        if callable(getattr(s, "warm_programs", None))]
        self._files = list(files)
        self._pad_to = int(pad_to)
        self._buckets = buckets
        self.warmed: list[dict] = []
        self.errors: list[str] = []
        self.shapes: list[dict] = []
        self._thread = threading.Thread(target=self._run,
                                        name="campaign-warmup",
                                        daemon=True)

    def start(self) -> "Warmup":
        self._thread.start()
        return self

    def join(self, timeout: float | None = None) -> None:
        self._thread.join(timeout=timeout)

    @property
    def done(self) -> bool:
        return not self._thread.is_alive()

    def _run(self) -> None:
        seen: set = set()
        for path in self._files:
            try:
                shape = probe_observation(path, pad_to=self._pad_to)
            except Exception as exc:  # noqa: BLE001 — probe-only
                self.errors.append(f"probe {path}: {exc!r}")
                continue
            self.shapes.append(shape)
            # dedup on the CANONICAL bucket when the campaign policy is
            # known (a jittered 500-file campaign must warm ~bucket-set
            # programs, not ~500x; each stage still applies its own
            # policy inside warm_programs — the same one its __call__
            # uses — so warm and run can never disagree on shapes).
            # Without a policy, dedup on the raw geometry. Warm-up is
            # best-effort either way: a rare same-bucket program
            # variant (e.g. a file whose unpadded L undercuts a stage's
            # filter window) just compiles inline on first use.
            if self._buckets is not None:
                key = ((shape["F"], shape["B"], shape["C"])
                       + self._buckets.canonical(shape["T"], shape["S"],
                                                 shape["L"])
                       + (bool(shape.get("calibrator", False)),))
            else:
                key = tuple(sorted(shape.items()))
            if key in seen:
                continue
            seen.add(key)
            for stage in self._stages:
                try:
                    stage.warm_programs(**shape)
                    self.warmed.append(
                        {"stage": getattr(stage, "name",
                                          type(stage).__name__), **shape})
                except Exception as exc:  # noqa: BLE001 — best effort
                    self.errors.append(
                        f"{type(stage).__name__} @ {shape}: {exc!r}")
                    logger.warning(
                        "campaign warm-up: %s failed for %s: %s",
                        type(stage).__name__, shape, exc)


def start_warmup(stages, files, pad_to: int = 128,
                 buckets: ShapeBuckets | None = None) -> Warmup:
    """Start (and return) a daemon :class:`Warmup` over ``files``."""
    return Warmup(stages, files, pad_to=pad_to, buckets=buckets).start()
