"""Configuration loading: TOML (current) and legacy INI (ParserClass).

The reference drives the current pipeline from a TOML file
(``run_average.py:104-106``) and the legacy pipeline from hand-rolled INI
files parsed by ``Tools/ParserClass.py:4-101`` (``:``/``=`` delimiters,
automatic bool/int/float/list coercion) with ``Module.Class(variant)``
section names enabling multiple configurations of one stage class
(``ClassParameters.ini:110``, ``Tools/Parser.py:26-41``). Both mechanisms
are supported here; both feed the same registry (:mod:`registry`).
"""

from __future__ import annotations

import re

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: tomli is the same parser
    try:
        import tomli as tomllib
    except ModuleNotFoundError:  # neither: gate to load_toml call time
        tomllib = None

__all__ = ["load_toml", "IniConfig", "parse_stage_name", "coerce",
           "read_filelist"]


def read_filelist(path: str) -> list[str]:
    """Paths from a filelist text file: one per line, blank lines and
    ``#`` comments (leading whitespace allowed) skipped. The single
    shared parser for every filelist consumer."""
    with open(path) as f:
        return [ln.strip() for ln in f
                if ln.strip() and not ln.strip().startswith("#")]

_STAGE_NAME_RE = re.compile(
    r"^(?:(?P<module>[A-Za-z_]\w*)\.)?(?P<cls>[A-Za-z_]\w*)"
    r"(?:\((?P<variant>[^)]*)\))?$")


def load_toml(path: str) -> dict:
    """Load a TOML pipeline configuration (``run_average.py:104``)."""
    if tomllib is None:  # pragma: no cover - env without tomllib/tomli
        raise ModuleNotFoundError(
            "TOML configs need tomllib (Python >= 3.11) or tomli")
    with open(path, "rb") as f:
        return tomllib.load(f)


def parse_stage_name(name: str):
    """Split ``"Module.Class(variant)"`` into ``(module, cls, variant)``.

    ``module`` and ``variant`` may be ``None``; bare class names are allowed
    (the TOML path uses bare names, ``run_average.py:44-46``). Raises
    ``ValueError`` on malformed names — the reference's ``getClass`` would
    crash opaquely instead (``Tools/Parser.py:26-41``).
    """
    m = _STAGE_NAME_RE.match(name.strip())
    if not m:
        raise ValueError(f"malformed stage name: {name!r}")
    return m.group("module"), m.group("cls"), m.group("variant")


def coerce(value: str):
    """Coerce an INI value string the way ``ParserClass.ReadLines`` does:
    bools, ints, floats, comma lists (recursively coerced), else str."""
    s = value.strip()
    if "," in s:
        items = [coerce(v) for v in s.split(",") if v.strip() != ""]
        return items
    low = s.lower()
    if low in ("true", "yes"):
        return True
    if low in ("false", "no"):
        return False
    if low in ("none", ""):
        return None
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    return s


class IniConfig(dict):
    """Nested-dict INI parser with the legacy coercion rules.

    ``IniConfig(filename)`` or ``IniConfig.from_text(text)``. Sections map to
    dicts; ``key : value`` and ``key = value`` are both accepted; ``#`` and
    ``;`` start comments. Unlike the reference parser this one keeps the
    raw section-name string as the key (including ``Class(variant)``),
    which :func:`parse_stage_name` decodes.
    """

    def __init__(self, filename: str | None = None):
        super().__init__()
        if filename is not None:
            with open(filename) as f:
                self._parse(f.read())

    @classmethod
    def from_text(cls, text: str) -> "IniConfig":
        cfg = cls()
        cfg._parse(text)
        return cfg

    def _parse(self, text: str) -> None:
        section = None
        for raw in text.splitlines():
            line = raw.split("#", 1)[0].split(";", 1)[0].strip()
            if not line:
                continue
            if line.startswith("[") and line.endswith("]"):
                section = line[1:-1].strip()
                self.setdefault(section, {})
                continue
            # first delimiter by position, so '=' values containing ':'
            # (paths, times) split at the right place
            positions = [(line.index(d), d) for d in (":", "=") if d in line]
            if not positions:
                continue
            _, delim = min(positions)
            key, value = line.split(delim, 1)
            target = self.setdefault(section, {}) if section else self
            target[key.strip()] = coerce(value)

    def pipeline_jobs(self) -> list[tuple[str, dict]]:
        """Legacy job list: the ``[Inputs] pipeline`` stage names, each with
        its own section's kwargs (``Tools/Parser.py:44-96``)."""
        inputs = self.get("Inputs", {})
        pipeline = inputs.get("pipeline", [])
        if isinstance(pipeline, str):
            pipeline = [pipeline]
        jobs = []
        for name in pipeline:
            kwargs = dict(self.get(name, {}))
            jobs.append((name, kwargs))
        return jobs
