"""Host (numpy, f64) backend for the core chain.

The BASELINE north star keeps the legacy registry's per-stage ``backend``
switch (``Tools/Parser.py:26-41``); this package provides the ``numpy``
side: double-precision host implementations of the vane calibration, the
Level-1 -> Level-2 reduction, and the destriper. They serve three roles —
tiny jobs without an accelerator, the f64 parity oracles SURVEY §7 calls
for (exercised by ``tests/test_numpy_backend.py``), and reference-free
documentation of each kernel's math.

Importing this package registers the numpy stages.
"""

from comapreduce_tpu.backends import stages_numpy  # noqa: F401
from comapreduce_tpu.backends.numpy_ops import (destripe_np,
                                                measure_system_temperature_np,
                                                reduce_feed_scans_np)

__all__ = ["destripe_np", "measure_system_temperature_np",
           "reduce_feed_scans_np"]
