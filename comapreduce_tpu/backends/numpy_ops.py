"""Double-precision numpy implementations of the core-chain kernels.

Independent f64 mirrors of the jitted ops (``ops/vane.py``,
``ops/reduce.py``, ``mapmaking/destriper.py``) with the same observable
semantics: masked statistics instead of NaNs, edge-replicated scan padding,
symmetric median-filter boundaries, closed-form gain solve, CG with the
singular-system breakdown guard. Used as the ``numpy`` pipeline backend and
as the parity oracles (SURVEY §7 hard part 5: f64-on-host oracles against
the f32 device path).
"""

from __future__ import annotations

import numpy as np

from comapreduce_tpu.ops.reduce import ReduceConfig, scan_starts_lengths
from comapreduce_tpu.ops.vane import (GRADIENT_LIMIT, SIGMA_FACTOR,
                                      VANE_COLD_TEMP, find_vane_events)

__all__ = ["measure_system_temperature_np", "reduce_feed_scans_np",
           "destripe_np", "rolling_median_np"]


# -- shared helpers ---------------------------------------------------------

def _masked_median(x, m, axis=-1):
    """Mean of the lower and upper median over ``axis`` counting only
    ``m > 0`` samples (same definition as ``ops.stats.masked_median``)."""
    x = np.moveaxis(np.asarray(x, np.float64), axis, -1)
    m = np.moveaxis(np.asarray(m), axis, -1) > 0
    big = np.finfo(np.float64).max
    xs = np.sort(np.where(m, x, big), axis=-1)
    cnt = m.sum(axis=-1)
    n = x.shape[-1]
    lo = np.clip((np.maximum(cnt, 1) - 1) // 2, 0, n - 1)
    hi = np.clip(np.maximum(cnt, 1) // 2, 0, n - 1)
    vlo = np.take_along_axis(xs, lo[..., None], axis=-1)[..., 0]
    vhi = np.take_along_axis(xs, hi[..., None], axis=-1)[..., 0]
    return np.where(cnt > 0, 0.5 * (vlo + vhi), 0.0)


def _masked_mean(x, m, axis=-1):
    m = np.asarray(m, np.float64)
    return (x * m).sum(axis=axis) / np.maximum(m.sum(axis=axis), 1.0)


def _auto_rms(x, axis=-1):
    """Adjacent-pair rms (``Tools/stats.py:58-71`` capability)."""
    x = np.moveaxis(x, axis, -1)
    n2 = x.shape[-1] // 2 * 2
    d = x[..., 1:n2:2] - x[..., 0:n2:2]
    return d.std(axis=-1) / np.sqrt(2.0)


def rolling_median_np(x: np.ndarray, window: int, pad_mode="symmetric",
                      chunk: int = 2048) -> np.ndarray:
    """Exact centered rolling median along the last axis.

    Same alignment as ``ops.median_filter.rolling_median``: output[i] is
    the median of ``x[i-(w-1)//2 : i+w//2+1]`` with boundary handling by
    ``pad_mode``. Chunked ``sliding_window_view`` + ``np.median`` so peak
    memory stays ~``chunk * window`` f64.
    """
    if window <= 1:
        return np.asarray(x, np.float64).copy()
    x = np.asarray(x, np.float64)
    T = x.shape[-1]
    left = (window - 1) // 2
    right = window - 1 - left
    pad = [(0, 0)] * (x.ndim - 1) + [(left, right)]
    padded = np.pad(x, pad, mode=pad_mode)
    out = np.empty_like(x)
    from numpy.lib.stride_tricks import sliding_window_view

    win = sliding_window_view(padded, window, axis=-1)  # (..., T, window)
    for s in range(0, T, chunk):
        e = min(s + chunk, T)
        out[..., s:e] = np.median(win[..., s:e, :], axis=-1)
    return out


# -- vane calibration -------------------------------------------------------

def _hot_cold_masks_np(band_avg: np.ndarray):
    """f64 mirror of ``ops.vane.hot_cold_masks`` over (..., t)."""
    x = np.asarray(band_avg, np.float64)
    rms = _auto_rms(x)[..., None]
    rng = np.maximum(x.max(-1) - x.min(-1), 1e-30)[..., None]
    xn = x / rng
    rms_n = rms / rng
    mid = ((xn.max(-1) + xn.min(-1)) / 2.0)[..., None]
    flat = np.abs(np.gradient(xn, axis=-1)) < GRADIENT_LIMIT
    hot = ((xn - mid) > SIGMA_FACTOR * rms_n) & flat
    cold = ((xn - mid) < SIGMA_FACTOR * rms_n) & flat
    t = np.arange(x.shape[-1])
    last_hot = np.max(np.where(hot, t, -1), axis=-1, keepdims=True)
    cold = cold & (t > last_hot)
    has_both = (hot.any(-1) & cold.any(-1))[..., None]
    return hot & has_both, cold & has_both


def measure_system_temperature_np(tod_reader, vane_flag, vane_temperature,
                                  pad: int = 50):
    """f64 mirror of ``ops.vane.measure_system_temperature``:
    ``(tsys, gain)`` each (n_events, F, B, C), or (None, None)."""
    events = find_vane_events(vane_flag)
    n = len(vane_flag)
    out_t, out_g = [], []
    for start, end in events:
        s, e = max(0, int(start) - pad), min(n, int(end) + pad)
        tod = np.asarray(tod_reader(s, e), np.float64)  # (F, B, C, t)
        hot, cold = _hot_cold_masks_np(tod.mean(axis=2))
        p_hot = _masked_mean(tod, hot[..., None, :])
        p_cold = _masked_mean(tod, cold[..., None, :])
        gain = (p_hot - p_cold) / (vane_temperature - VANE_COLD_TEMP)
        ok = (hot.sum(-1) > 0) & (cold.sum(-1) > 0)
        ok = ok[..., None] & (gain > 0)
        gain = np.where(ok, gain, 0.0)
        tsys = np.where(ok, p_cold / np.where(ok, gain, 1.0), 0.0)
        out_t.append(tsys)
        out_g.append(gain)
    if not out_t:
        return None, None
    return np.stack(out_t), np.stack(out_g)


# -- Level-1 -> Level-2 reduction ------------------------------------------

def reduce_feed_scans_np(tod, mask, airmass, edges, tsys, sys_gain,
                         freq_scaled, cfg: ReduceConfig,
                         pad_to: int = 128):
    """f64 mirror of ``ops.reduce.reduce_feed_scans`` for one feed.

    Same chain and masks: NaN fill with the stride-4 masked median,
    centered airmass regression (or median removal for calibrators),
    radiometer normalisation, EXACT rolling-median high-pass with affine
    regression, closed-form gain solve, Tsys^2-weighted band average.
    Returns the same dict of (B, T) arrays (f64).
    """
    tod = np.asarray(tod, np.float64)
    mask = np.asarray(mask, np.float64)
    airmass = np.asarray(airmass, np.float64)
    tsys = np.asarray(tsys, np.float64)
    sys_gain = np.asarray(sys_gain, np.float64)
    B, C, T = tod.shape
    starts, lengths, L = scan_starts_lengths(np.asarray(edges),
                                             pad_to=pad_to)
    out = {k: np.zeros((B, T)) for k in ("tod", "tod_original", "weights")}
    m_med = np.asarray(cfg.mask_medfilt, np.float64)
    m_tmpl = np.asarray(cfg.mask_templates, np.float64)
    m_w = (np.asarray(cfg.mask_weights, np.float64)
           * np.asarray(cfg.mask_band_avg, np.float64))
    dgs, atms = [], []

    for start, length in zip(starts, lengths):
        start, length = int(start), int(length)
        # edge-replicated padded block (extract_scan_blocks semantics)
        idx = np.minimum(np.arange(L) + start, start + max(length, 1) - 1)
        idx = np.clip(idx, 0, T - 1)
        d = tod[..., idx]
        tv = (np.arange(L) < length).astype(np.float64)
        m = mask[..., idx] * tv
        a = airmass[idx]

        # NaN fill: stride-4 masked median, masked-mean fallback
        med = _masked_median(d[..., ::4], m[..., ::4])
        sub_cnt = m[..., ::4].sum(-1)
        mean = _masked_mean(d, m)
        fill = np.where(sub_cnt > 0, med, mean)[..., None]
        d = np.where(m > 0, d, fill)

        if cfg.is_calibrator:
            med_c = _masked_median(d, m)[..., None]
            clean = d - med_c
            atm = np.concatenate([med_c[..., 0][:, None, :],
                                  np.zeros((B, 1, C))], axis=1)
        else:
            cnt = m.sum(-1)
            s1 = np.maximum(cnt, 1.0)
            a_mean = (m * a).sum(-1) / s1
            d_mean = (m * d).sum(-1) / s1
            da = a - a_mean[..., None]
            dd = d - d_mean[..., None]
            saa = (m * da * da).sum(-1)
            sad = (m * da * dd).sum(-1)
            ok = (cnt >= 2.0) & (saa > 1e-12)
            slope = np.where(ok, sad / np.maximum(saa, 1e-12), 0.0)
            off = d_mean - slope * a_mean
            clean = d - (off[..., None] + slope[..., None] * a)
            atm = np.stack([off, slope], axis=1)

        # radiometer normalisation (stride-4 pair differences)
        n4 = L // 4 * 4
        diff = clean[..., 0:n4:4] - clean[..., 2:n4:4]
        pm = m[..., 0:n4:4] * m[..., 2:n4:4]
        dmean = _masked_mean(diff, pm)
        var = _masked_mean((diff - dmean[..., None]) ** 2, pm)
        norm = (np.sqrt(np.maximum(var, 0.0)) / np.sqrt(2.0)
                * np.sqrt(cfg.bandwidth * cfg.tau))[..., None]
        clean = np.where(norm > 0, clean / np.maximum(norm, 1e-30), 0.0)

        # median-filter high-pass: band mean -> exact rolling median ->
        # per-channel affine regression (time-masked)
        cm = m_med[None, :, None]
        nch = np.maximum(m_med.sum(), 1.0)
        mean_tod = (clean * cm).sum(axis=1) / nch            # (B, L)
        medf = rolling_median_np(mean_tod, int(cfg.medfilt_window))
        n_t = np.maximum(tv.sum(), 1.0)
        mf_mean = (medf * tv).sum(-1) / n_t
        d_mean2 = (clean * tv).sum(-1) / n_t
        dm = (medf - mf_mean[..., None]) * tv
        smm = (dm * dm).sum(-1)
        smd = np.einsum("bt,bct->bc", dm, clean)
        safe = np.where(smm > 1e-20, smm, 1.0)
        bcoef = np.where(smm[..., None] > 1e-20, smd / safe[..., None], 0.0)
        acoef = d_mean2 - bcoef * mf_mean[..., None]
        model = acoef[..., None] + bcoef[..., None] * medf[:, None, :]
        filtered = (clean - model) * cm[..., 0][..., None]

        # closed-form gain solve ((P^T Z P) g = P^T Z y, diagonal system)
        ok_t = (tsys > 0) & (m_tmpl[None, :] > 0) & np.isfinite(tsys)
        inv_t = np.where(ok_t, 1.0 / np.where(ok_t, tsys, 1.0), 0.0)
        T2 = np.stack([inv_t.reshape(-1),
                       (freq_scaled * inv_t).reshape(-1)], axis=-1)
        p = ok_t.astype(np.float64).reshape(-1)
        G = T2.T @ T2
        det = G[0, 0] * G[1, 1] - G[0, 1] * G[1, 0]
        G = G if abs(det) > 1e-30 else np.eye(2)
        zp = p - T2 @ (np.linalg.inv(G) @ (T2.T @ p))
        zpp = p @ zp
        y = (filtered * m).reshape(B * C, L)
        if cfg.is_calibrator:
            dg = np.zeros(L)
        else:
            dg = (zp @ y) / max(zpp, 1e-20) * tv
        sub = filtered - p.reshape(B, C)[..., None] * dg[None, None, :]

        # back to kelvin, band average
        w_tsys = np.where(tsys > 0, 1.0 / np.maximum(tsys, 1e-10) ** 2, 0.0)
        w = w_tsys * m_w[None, :]
        safe_gain = np.where(sys_gain > 0, sys_gain, 1.0)
        residual = sub * norm / safe_gain[..., None]
        den = np.maximum(w.sum(-1), 1e-30)[..., None]
        tod_clean = np.einsum("bct,bc->bt", residual, w) / den
        in_kelvin = filtered * norm / safe_gain[..., None]
        tod_orig = np.einsum("bct,bc->bt", in_kelvin, w) / den

        n2 = L // 2 * 2
        dpair = tod_clean[..., 1:n2:2] - tod_clean[..., 0:n2:2]
        pm2 = tv[1:n2:2] * tv[0:n2:2]
        var2 = (dpair * dpair * pm2).sum(-1) / np.maximum(pm2.sum(), 1.0)
        rms2 = var2 / 2.0
        w_t = np.where(rms2 > 0, 1.0 / np.maximum(rms2, 1e-30), 0.0)

        sl = slice(start, start + length)
        keep = slice(0, length)
        out["tod"][:, sl] = (tod_clean * tv)[:, keep]
        out["tod_original"][:, sl] = (tod_orig * tv)[:, keep]
        out["weights"][:, sl] = (np.broadcast_to(w_t[:, None], (B, L))
                                 * tv)[:, keep]
        dgs.append(dg)
        atms.append(atm)
    out["dg"] = np.stack(dgs) if dgs else np.zeros((0, L))
    out["atmos_fits"] = np.stack(atms) if atms else np.zeros((0, B, 2, C))
    return out


# -- destriper --------------------------------------------------------------

def destripe_np(tod, pixels, weights, npix: int, offset_length: int = 50,
                n_iter: int = 100, threshold: float = 1e-6):
    """f64 mirror of ``mapmaking.destriper.destripe`` (no ground template).

    Same normal equations and CG (with the singular-system breakdown
    guard); binning via ``np.bincount``. Returns a dict with ``offsets``,
    ``destriped_map``, ``naive_map``, ``weight_map``, ``hit_map``,
    ``n_iter``, ``residual``.
    """
    tod = np.asarray(tod, np.float64)
    w = np.asarray(weights, np.float64)
    pix = np.asarray(pixels, np.int64)
    n = tod.size
    n_off = n // offset_length
    pix = np.where((pix < 0) | (pix >= npix), npix, pix)
    valid = pix < npix

    def bins(v):
        return np.bincount(pix, weights=v, minlength=npix + 1)[:npix]

    sum_w = bins(w)

    def zmap(d):
        m = np.where(sum_w > 0, bins(w * d) / np.maximum(sum_w, 1e-30), 0.0)
        return w * (d - np.where(valid, m[np.minimum(pix, npix - 1)], 0.0))

    def reduce_off(v):
        return v.reshape(n_off, offset_length).sum(axis=1)

    def matvec(a):
        return reduce_off(zmap(np.repeat(a, offset_length)))

    b = reduce_off(zmap(tod))
    b_norm = float(b @ b)
    x = np.zeros(n_off)
    r = b.copy()
    p = b.copy()
    rz = b_norm
    k = 0
    while k < n_iter and rz > threshold**2 * max(b_norm, 1e-30):
        q = matvec(p)
        pq = float(p @ q)
        if not np.isfinite(pq) or pq <= 0:
            break
        alpha = rz / pq
        x = x + alpha * p
        r = r - alpha * q
        rz_new = float(r @ r)
        if not np.isfinite(rz_new):
            break
        p = r + (rz_new / max(rz, 1e-30)) * p
        rz = rz_new
        k += 1

    template = np.repeat(x, offset_length)
    naive = np.where(sum_w > 0, bins(w * tod) / np.maximum(sum_w, 1e-30), 0)
    destriped = np.where(sum_w > 0, bins(w * (tod - template))
                         / np.maximum(sum_w, 1e-30), 0.0)
    hits = bins(np.ones_like(w))
    return {"offsets": x, "destriped_map": destriped, "naive_map": naive,
            "weight_map": sum_w, "hit_map": hits, "n_iter": k,
            "residual": float(np.sqrt(rz / max(b_norm, 1e-30)))}


# -- noise statistics (f64 oracles for ops/power.py + ops/spikes.py) --------

def spike_mask_np(tod, window: int = 501, threshold: float = 10.0,
                  pad: int = 100, valid=None) -> np.ndarray:
    """Spike mask of the averaged TOD, f64 (``Statistics.py:30-104``):
    median-filter high-pass, flag ``|hp| > threshold * auto_rms(hp)``,
    dilate each flag by ``+-pad`` samples. 1 = spike.

    Same rms definition as the device ``ops.spikes.spike_mask``: masked
    adjacent-pair rms of the HIGH-PASSED stream — a pair counts only when
    both samples are valid, so invalid runs neither inflate the threshold
    with boundary jumps nor deflate it with zero-difference pairs."""
    from scipy.ndimage import maximum_filter1d

    tod = np.asarray(tod, np.float64)
    if valid is None:
        valid = (tod != 0)
    valid = np.asarray(valid) > 0
    hp = tod - rolling_median_np(tod, window)
    n2 = hp.shape[-1] // 2 * 2
    d = hp[..., 1:n2:2] - hp[..., 0:n2:2]
    pm = valid[..., 1:n2:2] & valid[..., 0:n2:2]
    mu = _masked_mean(d, pm)[..., None]
    var = _masked_mean((d - mu) ** 2, pm)
    rms = (np.sqrt(np.maximum(var, 0.0)) / np.sqrt(2.0))[..., None]
    hit = (np.abs(hp) > threshold * np.maximum(rms, 1e-30)) & valid
    return maximum_filter1d(hit.astype(np.uint8), size=2 * pad + 1,
                            axis=-1, mode="constant")


def _psd_peak_mask_np(freqs, ps, auto_rms2, threshold=100.0, min_freq=0.5):
    """Reference-faithful spike masking of a PSD row: iterative
    ``find_peaks``/``peak_widths`` above ``threshold * auto_rms^2``
    (``Level2Data.py:288-298``), f64. Returns 1 = keep."""
    from scipy.signal import find_peaks, peak_widths

    keep = np.ones(ps.shape, bool)
    flat = ps.reshape(-1, ps.shape[-1])
    kflat = keep.reshape(flat.shape)
    a2 = np.asarray(auto_rms2, np.float64).reshape(-1)
    for r in range(flat.shape[0]):
        row = flat[r].copy()
        for _ in range(10):  # the reference iterates until clean
            pk, _ = find_peaks(row, height=threshold * a2[r])
            pk = pk[freqs[pk] > min_freq]
            if pk.size == 0:
                break
            widths = peak_widths(row, pk, rel_height=0.85)[0]
            for p, w in zip(pk, widths):
                lo = max(int(p - w), 0)
                hi = min(int(p + w) + 1, row.size)
                kflat[r, lo:hi] = False
                row[lo:hi] = 0.0
    return keep


def fit_observation_noise_np(blocks, sample_rate: float = 50.0,
                             nbins: int = 30, model_name: str = "red_noise",
                             mask_peaks: bool = True) -> np.ndarray:
    """Whole-observation noise fits in f64: PSD -> peak mask -> log bin
    -> L-BFGS-B on the log-chi^2 (the reference's actual minimiser,
    ``PowerSpectra.py:137-159``). Same outputs as
    ``ops.power.fit_observation_noise``: f64[..., 3]."""
    from scipy.optimize import minimize

    blocks = np.asarray(blocks, np.float64)
    n = blocks.shape[-1]
    ps = np.abs(np.fft.rfft(blocks, axis=-1)) ** 2 / n
    freqs = np.fft.rfftfreq(n, d=1.0 / sample_rate)
    if mask_peaks:
        d = np.diff(blocks, axis=-1)
        auto_rms2 = d.var(axis=-1) / 2.0
        smask = _psd_peak_mask_np(freqs, ps, auto_rms2)
    else:
        smask = np.ones(ps.shape, bool)
    # log-spaced bins, identical layout to ops.power.log_bin_psd
    edges = np.logspace(np.log10(freqs[1]), np.log10(freqs[-1]), nbins + 1)
    ids = np.clip(np.searchsorted(edges, freqs, side="right") - 1,
                  0, nbins - 1)
    valid = freqs >= freqs[1]
    fsum = np.bincount(ids, weights=freqs * valid, minlength=nbins)
    vcnt = np.bincount(ids, weights=valid.astype(float), minlength=nbins)
    nu = fsum / np.maximum(vcnt, 1.0)

    flat = ps.reshape(-1, ps.shape[-1])
    mflat = (smask.reshape(flat.shape) & valid)
    out = np.zeros((flat.shape[0], 3))
    for r in range(flat.shape[0]):
        w = mflat[r].astype(float)
        cnt = np.bincount(ids, weights=w, minlength=nbins)
        pb = np.bincount(ids, weights=flat[r] * w, minlength=nbins) \
            / np.maximum(cnt, 1.0)
        good = (cnt > 0) & (pb > 0) & (nu > 0)
        hi = nu > 0.5 * nu.max()
        sig2 = max(pb[good & hi].mean() if (good & hi).any() else 0.0,
                   1e-20)
        p_low = max(pb[1], sig2 * 1.01)
        nu_low = max(nu[1], 1e-3)
        alpha0 = -1.5
        if model_name == "red_noise":
            p1 = max((p_low - sig2) * nu_low ** (-alpha0), sig2 * 1e-3)

            def model(p, x):
                return p[0] + p[1] * np.abs(x) ** p[2]
        else:
            excess = max(p_low / sig2 - 1.0, 1e-3)
            p1 = np.clip(nu_low * excess ** (-1.0 / alpha0),
                         nu_low, 0.5 * sample_rate)

            def model(p, x):
                return p[0] * (1.0 + np.abs(x / p[1]) ** p[2])
        wgt = np.sqrt(np.maximum(cnt, 0.0)) * good

        def loss(q):
            p = (np.exp(q[0]), np.exp(q[1]), q[2])
            m = model(p, np.maximum(nu, 1e-6))
            resid = (np.where(good, np.log(np.maximum(pb, 1e-30)), 0.0)
                     - np.log(np.maximum(m, 1e-30))) * wgt
            return float(np.sum(resid * resid))

        res = minimize(loss, [np.log(sig2), np.log(p1), alpha0],
                       method="L-BFGS-B",
                       bounds=[(-60, 60), (-60, 60), (-5.0, 0.0)])
        out[r] = [np.exp(res.x[0]), np.exp(res.x[1]), res.x[2]]
    return out.reshape(blocks.shape[:-1] + (3,))
