"""NumPy-backend pipeline stages (``backend = "numpy"`` in the config).

Same stage names and Level-2 outputs as the device stages
(``pipeline/stages.py``); the math runs through the f64 host kernels in
:mod:`comapreduce_tpu.backends.numpy_ops`. Capability parity target: the
legacy registry's per-stage backend switch (``Tools/Parser.py:26-41``,
BASELINE.json north star).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

import numpy as np

from comapreduce_tpu.backends import numpy_ops
from comapreduce_tpu.ops.reduce import ReduceConfig, scan_starts_lengths
from comapreduce_tpu.pipeline.registry import register
from comapreduce_tpu.pipeline.stages import _StageBase, mean_vane_tsys_gain

__all__ = ["MeasureSystemTemperatureNumpy",
           "Level1AveragingGainCorrectionNumpy"]

logger = logging.getLogger("comapreduce_tpu")


@register("MeasureSystemTemperature", backend="numpy")
@dataclass
class MeasureSystemTemperatureNumpy(_StageBase):
    """Vane calibration on host in f64 (oracle for the device stage)."""

    groups: tuple = ("vane",)
    pad: int = 50

    def __call__(self, data, level2) -> bool:
        tod = data["spectrometer/tod"]

        def reader(s, e):
            return tod[..., s:e]

        tsys, gain = numpy_ops.measure_system_temperature_np(
            reader, data.vane_flag, data.vane_temperature, pad=self.pad)
        if tsys is None:
            logger.warning("MeasureSystemTemperature[numpy]: obs %s has no "
                           "vane events", data.obsid)
            self.STATE = False
            return False
        self._data = {
            "vane/system_temperature": np.asarray(tsys, np.float32),
            "vane/system_gain": np.asarray(gain, np.float32),
        }
        self.STATE = True
        return True


@register("Level1AveragingGainCorrection", backend="numpy")
@dataclass
class Level1AveragingGainCorrectionNumpy(_StageBase):
    """Level-1 -> Level-2 reduction on host in f64 (oracle / tiny jobs).

    Exact rolling median at any window (no two-level approximation), f64
    throughout; otherwise the same chain and outputs as the device stage.
    """

    groups: tuple = ("averaged_tod",)
    medfilt_window: int = 6000
    pad_to: int = 128

    def __call__(self, data, level2) -> bool:
        edges = np.asarray(data.scan_edges)
        if len(edges) == 0:
            logger.warning("Level1AveragingGainCorrection[numpy]: obs %s "
                           "has no scans", data.obsid)
            self.STATE = False
            return False
        try:
            tsys, sys_gain = mean_vane_tsys_gain(level2)
        except KeyError:
            logger.warning("Level1AveragingGainCorrection[numpy]: obs %s "
                           "has no vane calibration", data.obsid)
            self.STATE = False
            return False

        F, B, C, T = data.tod_shape
        _, _, L = scan_starts_lengths(edges, pad_to=self.pad_to)
        # clamp to the padded scan length like the device stage, so both
        # backends run the same filter on short scans
        cfg = ReduceConfig(C, medfilt_window=min(self.medfilt_window, L),
                           is_calibrator=data.is_calibrator)
        freq = np.asarray(data.frequency, np.float64)
        f0 = freq.mean(axis=1, keepdims=True)
        freq_scaled = (freq - f0) / f0
        airmass_all = np.asarray(data.airmass, np.float64)

        tod_out = np.zeros((F, B, T), np.float32)
        orig_out = np.zeros((F, B, T), np.float32)
        wei_out = np.zeros((F, B, T), np.float32)
        for ifeed in range(F):
            raw = np.asarray(data.read_tod_feed(ifeed), np.float64)
            mask = np.isfinite(raw).astype(np.float64)
            res = numpy_ops.reduce_feed_scans_np(
                np.nan_to_num(raw), mask, airmass_all[ifeed], edges,
                tsys[ifeed], sys_gain[ifeed], freq_scaled, cfg,
                pad_to=self.pad_to)
            tod_out[ifeed] = res["tod"]
            orig_out[ifeed] = res["tod_original"]
            wei_out[ifeed] = res["weights"]
        self._data = {
            "averaged_tod/tod": tod_out,
            "averaged_tod/tod_original": orig_out,
            "averaged_tod/weights": wei_out,
            "averaged_tod/scan_edges": edges,
        }
        self.STATE = True
        return True
