"""NumPy-backend pipeline stages (``backend = "numpy"`` in the config).

Same stage names and Level-2 outputs as the device stages
(``pipeline/stages.py``); the math runs through the f64 host kernels in
:mod:`comapreduce_tpu.backends.numpy_ops`. Capability parity target: the
legacy registry's per-stage backend switch (``Tools/Parser.py:26-41``,
BASELINE.json north star).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

import numpy as np

from comapreduce_tpu.backends import numpy_ops
from comapreduce_tpu.ops.reduce import ReduceConfig, scan_starts_lengths
from comapreduce_tpu.pipeline.registry import register
from comapreduce_tpu.pipeline.stages import (_StageBase,
                                             apply_fleet_channel_mask,
                                             mean_vane_tsys_gain)

__all__ = ["MeasureSystemTemperatureNumpy", "Level1AveragingNumpy",
           "Level1AveragingGainCorrectionNumpy",
           "SpikesNumpy", "Level2FitPowerSpectrumNumpy",
           "NoiseStatisticsNumpy"]

logger = logging.getLogger("comapreduce_tpu")


@register("MeasureSystemTemperature", backend="numpy")
@dataclass
class MeasureSystemTemperatureNumpy(_StageBase):
    """Vane calibration on host in f64 (oracle for the device stage)."""

    groups: tuple = ("vane",)
    pad: int = 50

    def __call__(self, data, level2) -> bool:
        tod = data["spectrometer/tod"]

        def reader(s, e):
            return tod[..., s:e]

        tsys, gain = numpy_ops.measure_system_temperature_np(
            reader, data.vane_flag, data.vane_temperature, pad=self.pad)
        if tsys is None:
            logger.warning("MeasureSystemTemperature[numpy]: obs %s has no "
                           "vane events", data.obsid)
            self.STATE = False
            return False
        self._data = {
            "vane/system_temperature": np.asarray(tsys, np.float32),
            "vane/system_gain": np.asarray(gain, np.float32),
        }
        self.STATE = True
        return True


@register("Level1Averaging", backend="numpy")
@dataclass
class Level1AveragingNumpy(_StageBase):
    """Plain frequency-binning reduction on host in f64 (oracle for the
    device ``Level1Averaging``; ref ``Level1Averaging.py:292-321``)."""

    groups: tuple = ("frequency_binned",)
    frequency_bin_size: int = 512
    feed_batch: int = 4   # config parity; the host path streams per feed
    normalised_mask_db: str = ""

    def __call__(self, data, level2) -> bool:
        from comapreduce_tpu.ops.average import edge_channel_mask
        from comapreduce_tpu.pipeline.stages import mean_vane_tsys_gain

        try:
            tsys, gain = mean_vane_tsys_gain(level2)
        except KeyError:
            logger.warning("Level1Averaging[numpy]: obs %s has no vane "
                           "calibration", data.obsid)
            self.STATE = False
            return False
        tsys = apply_fleet_channel_mask(tsys, self.normalised_mask_db,
                                        data.obsid)
        F, B, C, T = (int(x) for x in data.tod_shape)
        bin_size = min(self.frequency_bin_size, C)
        nb = C // bin_size

        def s(n):
            return max(int(round(n * C / 1024.0)), 1)
        chan_mask = np.asarray(edge_channel_mask(C, s(10), s(1), s(2)),
                               np.float64)
        tsys = np.asarray(tsys, np.float64)
        gain = np.asarray(gain, np.float64)
        w = np.where(tsys > 0, 1.0 / np.maximum(tsys, 1e-10) ** 2, 0.0)
        w = w * chan_mask                                 # (F, B, C)
        tod_out = np.zeros((F, B, nb, T), np.float32)
        std_out = np.zeros((F, B, nb, T), np.float32)
        for ifeed in range(F):
            raw = np.asarray(data.read_tod_feed(ifeed), np.float64)
            # NaN-flagged samples carry zero weight into the bin average
            # (the mask=None ingest policy) — NOT zero counts at full
            # weight, which would drag the binned TOD toward zero.
            # einsum contractions keep the per-sample weight product out
            # of memory (the f64 (B, C, T) tensor would double the
            # oracle's working set)
            valid = np.isfinite(raw)
            g = np.where(gain[ifeed] > 0, gain[ifeed], 1.0)[..., None]
            tod = np.where(valid, raw, 0.0) / g
            wr = w[ifeed][:, :nb * bin_size].reshape(B, nb, bin_size)
            x = tod[:, :nb * bin_size].reshape(B, nb, bin_size, T)
            v = valid[:, :nb * bin_size].reshape(B, nb, bin_size, T)
            den = np.maximum(
                np.einsum("bkst,bks->bkt", v, wr), 1e-30)
            avg = np.einsum("bkst,bks->bkt", x, wr) / den
            d = np.where(v, x - avg[:, :, None, :], 0.0)
            var = np.einsum("bkst,bkst,bks->bkt", d, d, wr) / den
            tod_out[ifeed] = avg
            std_out[ifeed] = np.sqrt(np.maximum(var, 0.0))
        self._data = {
            "frequency_binned/tod": tod_out,
            "frequency_binned/tod_stddev": std_out,
            "frequency_binned/scan_edges": np.asarray(data.scan_edges),
        }
        self.STATE = True
        return True


@register("Level1AveragingGainCorrection", backend="numpy")
@dataclass
class Level1AveragingGainCorrectionNumpy(_StageBase):
    """Level-1 -> Level-2 reduction on host in f64 (oracle / tiny jobs).

    Exact rolling median at any window (no two-level approximation), f64
    throughout; otherwise the same chain and outputs as the device stage.
    """

    groups: tuple = ("averaged_tod",)
    medfilt_window: int = 6000
    pad_to: int = 128
    normalised_mask_db: str = ""

    def __call__(self, data, level2) -> bool:
        edges = np.asarray(data.scan_edges)
        if len(edges) == 0:
            logger.warning("Level1AveragingGainCorrection[numpy]: obs %s "
                           "has no scans", data.obsid)
            self.STATE = False
            return False
        try:
            tsys, sys_gain = mean_vane_tsys_gain(level2)
        except KeyError:
            logger.warning("Level1AveragingGainCorrection[numpy]: obs %s "
                           "has no vane calibration", data.obsid)
            self.STATE = False
            return False
        tsys = apply_fleet_channel_mask(tsys, self.normalised_mask_db,
                                        data.obsid)

        F, B, C, T = data.tod_shape
        _, _, L = scan_starts_lengths(edges, pad_to=self.pad_to)
        # clamp to the padded scan length like the device stage, so both
        # backends run the same filter on short scans
        cfg = ReduceConfig(C, medfilt_window=min(self.medfilt_window, L),
                           is_calibrator=data.is_calibrator)
        freq = np.asarray(data.frequency, np.float64)
        f0 = freq.mean(axis=1, keepdims=True)
        freq_scaled = (freq - f0) / f0
        airmass_all = np.asarray(data.airmass, np.float64)

        tod_out = np.zeros((F, B, T), np.float32)
        orig_out = np.zeros((F, B, T), np.float32)
        wei_out = np.zeros((F, B, T), np.float32)
        for ifeed in range(F):
            raw = np.asarray(data.read_tod_feed(ifeed), np.float64)
            mask = np.isfinite(raw).astype(np.float64)
            res = numpy_ops.reduce_feed_scans_np(
                np.nan_to_num(raw), mask, airmass_all[ifeed], edges,
                tsys[ifeed], sys_gain[ifeed], freq_scaled, cfg,
                pad_to=self.pad_to)
            tod_out[ifeed] = res["tod"]
            orig_out[ifeed] = res["tod_original"]
            wei_out[ifeed] = res["weights"]
        self._data = {
            "averaged_tod/tod": tod_out,
            "averaged_tod/tod_original": orig_out,
            "averaged_tod/weights": wei_out,
            "averaged_tod/scan_edges": edges,
        }
        self.STATE = True
        return True


@register("Spikes", backend="numpy")
@dataclass
class SpikesNumpy(_StageBase):
    """Spike flagging on host in f64 (oracle for the device stage;
    ``Statistics.py:30-104``)."""

    groups: tuple = ("spikes",)
    window: int = 501
    threshold: float = 10.0
    pad: int = 100

    def __call__(self, data, level2) -> bool:
        tod = np.asarray(level2.tod, np.float64)
        T = tod.shape[-1]
        # real validity from the reduction's weights (a genuine zero TOD
        # sample stays valid); sentinel fallback for pre-weights stores
        valid = (np.asarray(level2["averaged_tod/weights"]) > 0) \
            if "averaged_tod/weights" in level2 else None
        mask = numpy_ops.spike_mask_np(
            tod, window=min(self.window, max(3, T // 2 * 2 - 1)),
            threshold=self.threshold, pad=self.pad, valid=valid)
        self._data = {"spikes/spike_mask": mask.astype(np.uint8)}
        self.STATE = True
        return True


@register("Level2FitPowerSpectrum", backend="numpy")
@dataclass
class Level2FitPowerSpectrumNumpy(_StageBase):
    """Per-(feed, band, scan) noise fits on host in f64, using the
    reference's own machinery — iterative scipy ``find_peaks`` masking
    (``Level2Data.py:288-298``) and L-BFGS-B on the log-chi^2
    (``PowerSpectra.py:137-159``). Oracle for the device stage."""

    groups: tuple = ("fnoise_fits",)
    nbins: int = 30
    sample_rate: float = 50.0
    model_name: str = "red_noise"
    out_group: str = "fnoise_fits"
    mask_peaks: bool = True
    # same quantised per-scan-length buckets as the device stage (a
    # backend switch must fit identical blocks); 1 = the reference's
    # exact full-length per-scan fits (free on host — no compile cost)
    length_quantum: int = 128
    # same cap as the device stage (identical blocks after a backend
    # switch — on host it only bounds the loop count, not compiles)
    max_length_buckets: int = 16
    figure_dir: str = ""   # same knob as the device stage: a config
    #                        section must survive a backend switch

    def __call__(self, data, level2) -> bool:
        from comapreduce_tpu.pipeline.stages import bucket_scan_lengths

        tod = np.asarray(level2.tod, np.float64)
        edges = np.asarray(level2.scan_edges)
        if len(edges) == 0:
            self.STATE = False
            return False
        buckets = bucket_scan_lengths(edges, self.length_quantum,
                                      self.max_length_buckets)
        if not buckets:
            self.STATE = False
            return False
        F, B = tod.shape[:2]
        S = len(edges)
        # NaN, not 0, for unfittable stubs: fleet stats take nanmedians
        params = np.full((F, B, S, 3), np.nan, np.float64)
        rms = np.full((F, B, S), np.nan, np.float64)
        for lq, sids in sorted(buckets.items()):
            for si in sids:   # host path: no batching pressure
                s = int(edges[si, 0])
                blk = tod[..., s:s + lq][:, :, None, :]
                params[:, :, si] = numpy_ops.fit_observation_noise_np(
                    blk, sample_rate=self.sample_rate, nbins=self.nbins,
                    model_name=self.model_name,
                    mask_peaks=self.mask_peaks)[:, :, 0]
                rms[:, :, si] = numpy_ops._auto_rms(blk)[:, :, 0]
        if self.figure_dir:
            from comapreduce_tpu.pipeline.stages import first_fitted_scan

            si0, lq0, s0 = first_fitted_scan(buckets, edges)
            self._plot_first_fit(tod[0, 0, s0:s0 + lq0], params[0, 0, si0],
                                 data.obsid, si0)
        self._data = {
            f"{self.out_group}/fnoise_fit_parameters":
                params.astype(np.float32),
            f"{self.out_group}/auto_rms": rms.astype(np.float32),
        }
        self.STATE = True
        return True

    def _plot_first_fit(self, block, params, obsid, si0: int = 0) -> None:
        """Same QA figure as the device stage (feed 0, band 0, first
        fitted scan)."""
        from comapreduce_tpu import diagnostics

        n = block.size
        ps = np.abs(np.fft.rfft(block)) ** 2 / n
        freqs = np.fft.rfftfreq(n, d=1.0 / self.sample_rate)
        e = np.logspace(np.log10(freqs[1]), np.log10(freqs[-1]),
                        self.nbins + 1)
        ids = np.clip(np.searchsorted(e, freqs, side="right") - 1,
                      0, self.nbins - 1)
        v = (freqs >= freqs[1]).astype(float)
        cnt = np.maximum(np.bincount(ids, weights=v,
                                     minlength=self.nbins), 1.0)
        nu = np.bincount(ids, weights=freqs * v,
                         minlength=self.nbins) / cnt
        pb = np.bincount(ids, weights=ps * v, minlength=self.nbins) / cnt
        if self.model_name == "red_noise":
            model = lambda p, x: p[0] + p[1] * np.abs(x) ** p[2]  # noqa: E731
        else:
            model = lambda p, x: p[0] * (1 + np.abs(x / p[1]) ** p[2])  # noqa: E731
        diagnostics.plot_power_spectrum_fit(
            diagnostics.figure_path(
                self.figure_dir, obsid,
                f"{self.out_group}_feed00_band00_scan{si0:02d}"),
            nu, pb, params, model)


@register("NoiseStatistics", backend="numpy")
@dataclass
class NoiseStatisticsNumpy(Level2FitPowerSpectrumNumpy):
    """Knee-model variant (``Statistics.py:106-224``)."""

    groups: tuple = ("noise_statistics",)
    model_name: str = "knee"
    out_group: str = "noise_statistics"
