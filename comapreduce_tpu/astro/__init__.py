"""Astrometry: the framework's SLALIB-equivalent (host-side).

The reference drives all pointing through vendored Fortran SLALIB
(``Tools/sla.f`` + ``Tools/pysla.f90`` f2py wrappers) called from
``Tools/Coordinates.py``. Here the same capability is a small astrometry
library with two interchangeable backends:

- :mod:`core` — vectorised NumPy (always available; the parity oracle);
- the native C++ library in ``csrc/astrometry.cpp`` loaded through
  :mod:`native` (ctypes), built on demand with ``g++`` — the production
  path for long pointing streams.

High-level COMAP-specific API (site constants, calibrator catalogue,
apparent-place chains, relative-coordinate rotations) is in
:mod:`coordinates`. Pointing is precomputed per observation on host
(the reference already 50x-downsamples + interpolates,
``Tools/Coordinates.py:302-304``), so none of this is a device hot loop.
"""

from comapreduce_tpu.astro import core  # noqa: F401
from comapreduce_tpu.astro.coordinates import (COMAP_LATITUDE,
                                               COMAP_LONGITUDE,
                                               CALIBRATORS, e2g, g2e,
                                               e2h_full, h2e_full, pa,
                                               precess, rotate, unrotate,
                                               sex2deg, source_position)

__all__ = ["core", "COMAP_LONGITUDE", "COMAP_LATITUDE", "CALIBRATORS",
           "h2e_full", "e2h_full", "precess", "pa", "e2g", "g2e",
           "rotate", "unrotate", "sex2deg", "source_position"]
