"""UT1-UTC (dUT1) lookup for the astrometry chain.

The reference pulls dUT1 from astropy's live IERS table
(``Tools/Coordinates.py:279-342``); this framework is air-gapped, so it
ships a coarse bundled table and accepts a user-supplied IERS one.

Resolution order for :func:`dut1_at`:

1. a table loaded explicitly with :func:`load_table`;
2. the file named by ``COMAP_DUT1_TABLE`` (two whitespace-separated
   columns ``mjd  ut1_utc_seconds``, ``#`` comments — trivially produced
   from IERS ``finals2000A`` with awk, docs/OPERATIONS.md);
3. the bundled coarse table below.

**Pointing-error budget.** Neglected dUT1 rotates the hour angle by
15 arcsec per second of dUT1. |dUT1| stays below 0.9 s (leap seconds), so
ignoring it entirely costs up to ~13 arcsec — invisible next to COMAP's
4.5 arcmin beam but not to the README's arcsecond-class astrometry
claim. The bundled table is semiannual Bulletin-D-grade (+-0.1 s
between nodes in the worst case) -> residual error under ~1.5 arcsec;
a user-supplied IERS finals table (+-1 ms) retires the term completely
(~0.015 arcsec).
"""

from __future__ import annotations

import logging
import os

import numpy as np

__all__ = ["dut1_at", "load_table", "bundled_table"]

logger = logging.getLogger("comapreduce_tpu")

# Coarse semiannual UT1-UTC anchors (seconds), Bulletin-D grade.
# MJD of Jan 1 / Jul 1; values rounded to 0.01 s. Outside the range the
# nearest node is held (extrapolating Earth rotation is meaningless).
_BUNDLED = np.array([
    (57754.0, 0.40),   # 2017-01-01 (after the 2016-12-31 leap second)
    (57935.0, 0.35),   # 2017-07-01
    (58119.0, 0.22),   # 2018-01-01
    (58300.0, 0.10),   # 2018-07-01
    (58484.0, -0.01),  # 2019-01-01
    (58665.0, -0.10),  # 2019-07-01
    (58849.0, -0.18),  # 2020-01-01
    (59031.0, -0.24),  # 2020-07-01
    (59215.0, -0.17),  # 2021-01-01
    (59396.0, -0.11),  # 2021-07-01
    (59580.0, -0.11),  # 2022-01-01
    (59761.0, -0.07),  # 2022-07-01
    (59945.0, -0.02),  # 2023-01-01
    (60126.0, -0.01),  # 2023-07-01
    (60310.0, 0.00),   # 2024-01-01
])

_loaded: np.ndarray | None = None
# ((path, mtime_ns, size), parsed table | None on failure) — keyed on the
# file's identity AND stat so editing the table in place takes effect
_env_cache: tuple = (("", 0, 0), None)


def bundled_table() -> np.ndarray:
    """The coarse built-in (mjd, ut1_utc) table, (N, 2) float64."""
    return _BUNDLED.copy()


def _parse_table(path: str) -> np.ndarray:
    rows = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            if len(parts) < 2:
                raise ValueError(f"dUT1 table {path}: line {line!r} "
                                 "needs two columns (mjd ut1_utc)")
            rows.append((float(parts[0]), float(parts[1])))
    if len(rows) < 1:
        raise ValueError(f"dUT1 table {path} has no rows")
    tab = np.asarray(sorted(rows), np.float64)
    if np.abs(tab[:, 1]).max() >= 0.9:
        raise ValueError(f"dUT1 table {path}: |UT1-UTC| must stay "
                         "below 0.9 s — wrong column?")
    return tab


def load_table(path: str) -> np.ndarray:
    """Load and activate a user dUT1 table: two columns ``mjd  seconds``
    (``#`` comments ignored). Returns the active (N, 2) table."""
    global _loaded
    _loaded = _parse_table(path)
    return _loaded


def _active_table() -> np.ndarray:
    global _env_cache
    if _loaded is not None:
        return _loaded
    env = os.environ.get("COMAP_DUT1_TABLE", "")
    if not env:
        return _BUNDLED
    # re-resolved every call (setting the env var OR editing the file
    # mid-process must take effect); the parse itself is cached per
    # (path, mtime, size) so an in-place fix invalidates a failed parse
    try:
        st = os.stat(env)
        key = (env, st.st_mtime_ns, st.st_size)
    except OSError:
        key = (env, 0, 0)
    if _env_cache[0] != key:
        try:
            tab = _parse_table(env)
        except (OSError, ValueError) as exc:
            logger.warning("COMAP_DUT1_TABLE %s unusable (%s); using "
                           "the bundled coarse table", env, exc)
            tab = None
        _env_cache = (key, tab)
    return _env_cache[1] if _env_cache[1] is not None else _BUNDLED


def dut1_at(mjd) -> float:
    """UT1-UTC [s] at ``mjd`` (scalar or array -> mean epoch): linear
    interpolation on the active table, nearest node held outside it.
    dUT1 drifts ~1 ms/day, so one value per observation is exact to
    ~0.1 ms over an hour-long file."""
    t = float(np.mean(np.asarray(mjd, np.float64)))
    tab = _active_table()
    return float(np.interp(t, tab[:, 0], tab[:, 1]))
