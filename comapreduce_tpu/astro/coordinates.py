"""COMAP-level coordinate API (degrees in, degrees out).

Re-design of the reference ``Tools/Coordinates.py``: observatory site,
calibrator catalogue, apparent-place chains ``h2e_full``/``e2h_full``
(``Tools/Coordinates.py:279-342``, which 50x-downsamples + interpolates —
kept here as ``downsample_factor``), precession, parallactic angle,
galactic conversion, planet ephemerides and the source-relative rotation
used by the calibrator fitting (``Rotate``/``UnRotate``,
``Coordinates.py:77-116``).

Backend: :mod:`comapreduce_tpu.astro.native` (C++ via ctypes) when the
shared library is available, :mod:`comapreduce_tpu.astro.core` (NumPy)
otherwise. Both are exact peers; tests assert parity.
"""

from __future__ import annotations

import numpy as np

from comapreduce_tpu.astro import core

__all__ = ["COMAP_LONGITUDE", "COMAP_LATITUDE", "CALIBRATORS", "sex2deg",
           "h2e_full", "e2h_full", "precess", "pa", "e2g", "g2e",
           "rotate", "unrotate", "source_position", "planet_distance_au"]

# OVRO 10.4-m site (reference Tools/Coordinates.py:16-17).
COMAP_LONGITUDE = -118.2941  # deg east
COMAP_LATITUDE = 37.2314     # deg

# J2000 positions of the point-source calibrators
# (reference Tools/Coordinates.py:7-15 CalibratorList).
CALIBRATORS = {
    "TauA": (83.6331, 22.0145),
    "CasA": (350.8500, 58.8150),
    "CygA": (299.8682, 40.7339),
}

_PLANET_NAMES = ("sun", "moon", "mercury", "venus", "mars", "jupiter",
                 "saturn", "uranus", "neptune")


def sex2deg(text: str, hours: bool = False) -> float:
    """``'dd:mm:ss.s'`` (or ``'hh:mm:ss.s'``) -> degrees
    (``Coordinates.py sex2deg`` role)."""
    parts = [float(p) for p in str(text).split(":")]
    while len(parts) < 3:
        parts.append(0.0)
    sign = -1.0 if str(text).strip().startswith("-") else 1.0
    deg = abs(parts[0]) + parts[1] / 60.0 + parts[2] / 3600.0
    deg *= sign
    return deg * 15.0 if hours else deg


def _slow_terms(mjd, longitude, dut1, downsample_factor):
    """The expensive, slowly-varying pieces of the apparent-place chain —
    local apparent sidereal time, the combined nutation@precession matrix,
    and the aberration velocity — evaluated on a ``downsample_factor``-
    subsampled time grid and linearly interpolated back (the reference
    computes the whole transform 50x-downsampled and interpolates the
    output angles, ``Coordinates.py:302-304``; interpolating the *slow
    terms* instead keeps the fast az/el spherical trig exact per sample).
    """
    mjd = np.atleast_1d(np.asarray(mjd, np.float64))
    n = mjd.size
    f = max(int(downsample_factor), 1)
    if f <= 1 or n <= 2 * f:
        sub = np.arange(n)
    else:
        sub = np.unique(np.r_[np.arange(0, n, f), n - 1])
    t_sub = mjd.ravel()[sub]
    lst_sub = np.unwrap(core.last(t_sub, np.radians(longitude), dut1))
    m_sub = core.nutation_matrix(t_sub) @ core.precession_matrix(t_sub)
    beta_sub = core._earth_velocity(t_sub) / core._C_AU_PER_DAY
    if len(sub) == n:
        return lst_sub, m_sub, beta_sub
    x = np.arange(n, dtype=np.float64)
    lst = np.interp(x, x[sub], lst_sub)
    m = np.empty((n, 3, 3))
    beta = np.empty((n, 3))
    for i in range(3):
        beta[:, i] = np.interp(x, x[sub], beta_sub[:, i])
        for j in range(3):
            m[:, i, j] = np.interp(x, x[sub], m_sub[:, i, j])
    return lst, m, beta


def h2e_full(az_deg, el_deg, mjd, longitude: float = COMAP_LONGITUDE,
             latitude: float = COMAP_LATITUDE, dut1: float | None = None,
             apply_refraction: bool = True, downsample_factor: int = 50,
             backend: str = "auto"):
    """Observed azimuth/elevation -> mean J2000 RA/Dec [deg].

    The ``sla_oap``+``sla_amp`` chain of the reference ``h2e_full``
    (``pysla.f90``): un-refract, horizontal -> apparent (ha, dec) at the
    local apparent sidereal time, then apparent -> J2000. The slow terms
    (LAST, nutation x precession, aberration) are evaluated on a
    ``downsample_factor`` subgrid; the per-sample trig is exact.
    ``backend``: 'auto' uses the C++ library when it loads, 'native'
    requires it, 'numpy' forces the oracle. ``dut1=None`` (default)
    resolves UT1-UTC from the active dUT1 table at the mean epoch —
    the reference's live-IERS behavior (``Tools/Coordinates.py:279-342``)
    with an air-gapped table (:mod:`comapreduce_tpu.astro.dut1`, error
    budget documented there); pass an explicit float to pin it."""
    if dut1 is None:
        from comapreduce_tpu.astro.dut1 import dut1_at

        dut1 = dut1_at(mjd)
    az = np.atleast_1d(np.asarray(az_deg, np.float64))
    el = np.atleast_1d(np.asarray(el_deg, np.float64))
    mjd_b = np.broadcast_to(np.atleast_1d(np.asarray(mjd, np.float64)),
                            az.shape)
    if az.ndim > 1:
        # per-feed streams: each row is its own time series — the slow-term
        # subsampling must never interpolate across a feed boundary
        # np.empty (not empty_like): the output must be C-contiguous so the
        # row views written below alias the returned array
        ra = np.empty(az.shape)
        dec = np.empty(az.shape)
        flat_a = az.reshape(-1, az.shape[-1])
        flat_e = el.reshape(-1, az.shape[-1])
        flat_m = mjd_b.reshape(-1, az.shape[-1])
        fr = ra.reshape(-1, az.shape[-1])
        fd = dec.reshape(-1, az.shape[-1])
        for i in range(flat_a.shape[0]):
            fr[i], fd[i] = h2e_full(
                flat_a[i], flat_e[i], flat_m[i], longitude, latitude, dut1,
                apply_refraction, downsample_factor, backend)
        return ra, dec
    if backend in ("auto", "native"):
        from comapreduce_tpu.astro import native
        if native.available():
            ra, dec = native.h2e_full(
                np.radians(az), np.radians(el), mjd_b,
                np.radians(longitude), np.radians(latitude), dut1,
                apply_refraction, stride=max(int(downsample_factor), 1))
            return np.degrees(ra) % 360.0, np.degrees(dec)
        if backend == "native":
            raise RuntimeError("native astrometry library unavailable")
    lst, m, beta = _slow_terms(mjd_b.ravel(), longitude, dut1,
                               downsample_factor)

    azr, elr = np.radians(az), np.radians(el)
    if apply_refraction:
        elr = elr - core.refraction_bennett(elr)
    ha, dec = core.azel_to_hadec(azr, elr, np.radians(latitude))
    ra_app = (lst - ha) % (2 * np.pi)
    v = core.equatorial_to_cartesian(ra_app, dec)
    v = core._apply(np.swapaxes(m, -1, -2), v)
    v = v - beta
    v = v / np.linalg.norm(v, axis=-1, keepdims=True)
    ra, dec = core.cartesian_to_equatorial(v)
    return np.degrees(ra) % 360.0, np.degrees(dec)


def e2h_full(ra_deg, dec_deg, mjd, longitude: float = COMAP_LONGITUDE,
             latitude: float = COMAP_LATITUDE, dut1: float | None = None,
             apply_refraction: bool = True, downsample_factor: int = 50,
             backend: str = "auto"):
    """Mean J2000 RA/Dec -> observed azimuth/elevation [deg]
    (``sla_map``+``sla_aop`` chain of the reference ``e2h_full``).
    ``dut1=None`` resolves from the dUT1 table (see :func:`h2e_full`)."""
    if dut1 is None:
        from comapreduce_tpu.astro.dut1 import dut1_at

        dut1 = dut1_at(mjd)
    ra = np.atleast_1d(np.asarray(ra_deg, np.float64))
    dec = np.atleast_1d(np.asarray(dec_deg, np.float64))
    mjd_b = np.broadcast_to(np.atleast_1d(np.asarray(mjd, np.float64)),
                            ra.shape)
    if ra.ndim > 1:
        az = np.empty(ra.shape)
        el = np.empty(ra.shape)
        fa = az.reshape(-1, ra.shape[-1])
        fe = el.reshape(-1, ra.shape[-1])
        flat_r = ra.reshape(-1, ra.shape[-1])
        flat_d = dec.reshape(-1, ra.shape[-1])
        flat_m = mjd_b.reshape(-1, ra.shape[-1])
        for i in range(flat_r.shape[0]):
            fa[i], fe[i] = e2h_full(
                flat_r[i], flat_d[i], flat_m[i], longitude, latitude, dut1,
                apply_refraction, downsample_factor, backend)
        return az, el
    if backend in ("auto", "native"):
        from comapreduce_tpu.astro import native
        if native.available():
            az, el = native.e2h_full(
                np.radians(ra), np.radians(dec), mjd_b,
                np.radians(longitude), np.radians(latitude), dut1,
                apply_refraction)
            return np.degrees(az) % 360.0, np.degrees(el)
        if backend == "native":
            raise RuntimeError("native astrometry library unavailable")
    lst, m, beta = _slow_terms(mjd_b.ravel(), longitude, dut1,
                               downsample_factor)

    v = core.equatorial_to_cartesian(np.radians(ra.ravel()),
                                     np.radians(dec.ravel()))
    v = v + beta
    v = v / np.linalg.norm(v, axis=-1, keepdims=True)
    v = core._apply(m, v)
    ra_app, dec_app = core.cartesian_to_equatorial(v)
    ha = (lst - ra_app + np.pi) % (2 * np.pi) - np.pi
    az, el = core.hadec_to_azel(ha, dec_app, np.radians(latitude))
    if apply_refraction:
        el = el + core.refraction_bennett(el)
    return (np.degrees(az).reshape(ra.shape) % 360.0,
            np.degrees(el).reshape(ra.shape))


def precess(ra_deg, dec_deg, mjd, reverse: bool = False):
    """J2000 <-> mean-of-date precession [deg] (``sla_preces`` role)."""
    v = core.equatorial_to_cartesian(np.radians(ra_deg), np.radians(dec_deg))
    m = core.precession_matrix(mjd)
    if reverse:
        m = np.swapaxes(m, -1, -2)
    ra, dec = core.cartesian_to_equatorial(core._apply(m, v))
    return np.degrees(ra) % 360.0, np.degrees(dec)


def pa(ra_deg, dec_deg, mjd, longitude: float = COMAP_LONGITUDE,
       latitude: float = COMAP_LATITUDE) -> np.ndarray:
    """Parallactic angle [deg] of a J2000 position at time ``mjd``
    (``Coordinates.py pa`` role)."""
    lst = core.last(np.asarray(mjd, np.float64), np.radians(longitude))
    ha = lst - np.radians(np.asarray(ra_deg, np.float64))
    return np.degrees(core.parallactic_angle(
        ha, np.radians(np.asarray(dec_deg, np.float64)),
        np.radians(latitude)))


def e2g(ra_deg, dec_deg):
    """J2000 -> galactic [deg] (``Coordinates.py e2g``)."""
    gl, gb = core.equ_to_gal(np.radians(ra_deg), np.radians(dec_deg))
    return np.degrees(gl) % 360.0, np.degrees(gb)


def g2e(gl_deg, gb_deg):
    ra, dec = core.gal_to_equ(np.radians(gl_deg), np.radians(gb_deg))
    return np.degrees(ra) % 360.0, np.degrees(dec)


def _relative_matrix(lon0_deg: float, lat0_deg: float, angle_deg: float):
    return (core._rx(np.radians(angle_deg))
            @ core._ry(-np.radians(lat0_deg))
            @ core._rz(np.radians(lon0_deg)))


def rotate(lon_deg, lat_deg, lon0_deg, lat0_deg, angle_deg=0.0):
    """Source-relative coordinates: rotate so (lon0, lat0) is the origin,
    then roll by ``angle_deg`` (parallactic-angle rotation of the
    calibrator maps). Returns (dlon, dlat) [deg], dlon in (-180, 180].
    Parity: ``Coordinates.Rotate`` (``Coordinates.py:77-116``)."""
    v = core.equatorial_to_cartesian(np.radians(lon_deg),
                                     np.radians(lat_deg))
    m = _relative_matrix(lon0_deg, lat0_deg, angle_deg)
    dlon, dlat = core.cartesian_to_equatorial(core._apply(m, v))
    dlon = np.degrees(dlon)
    dlon = (dlon + 180.0) % 360.0 - 180.0
    return dlon, np.degrees(dlat)


def unrotate(dlon_deg, dlat_deg, lon0_deg, lat0_deg, angle_deg=0.0):
    """Inverse of :func:`rotate` (``Coordinates.UnRotate``)."""
    v = core.equatorial_to_cartesian(np.radians(dlon_deg),
                                     np.radians(dlat_deg))
    m = _relative_matrix(lon0_deg, lat0_deg, angle_deg)
    lon, lat = core.cartesian_to_equatorial(
        core._apply(np.swapaxes(m, -1, -2), v))
    return np.degrees(lon) % 360.0, np.degrees(lat)


def source_position(name: str, mjd):
    """(ra_deg, dec_deg, distance_au) of a named source at ``mjd``.

    Fixed calibrators return their catalogue J2000 position with distance
    0; solar-system bodies come from the ephemerides
    (``Coordinates.sourcePosition``, ``Coordinates.py:225-253``)."""
    if name in CALIBRATORS:
        ra, dec = CALIBRATORS[name]
        shape = np.shape(mjd)
        return (np.broadcast_to(ra, shape).copy() if shape else ra,
                np.broadcast_to(dec, shape).copy() if shape else dec,
                np.zeros(shape) if shape else 0.0)
    lname = name.lower()
    if lname not in _PLANET_NAMES:
        raise KeyError(f"unknown source {name!r} (calibrators: "
                       f"{sorted(CALIBRATORS)}; planets: {_PLANET_NAMES})")
    ra, dec, dist = core.planet_position(lname, mjd)
    return np.degrees(ra) % 360.0, np.degrees(dec), dist


def planet_distance_au(name: str, mjd):
    """Geocentric distance [AU] (Jupiter flux-model scaling input)."""
    return source_position(name, mjd)[2]
