"""Vectorised NumPy astrometry kernels (the SLALIB-subset oracle).

Everything the reference uses from SLALIB (``Tools/pysla.f90``:
``h2e``/``e2h`` GMST chains, ``h2e_full``/``e2h_full`` apparent-place
chains, ``precess``, ``pa``, ``e2g``/``g2e``, ``rdplan``/``planet``,
``refro``) re-derived from the standard published algorithms:

- GMST: IAU 1982 polynomial (Meeus ch. 12).
- Precession: IAU 1976 zeta/z/theta rotation (Meeus 21.2).
- Nutation: IAU 1980 series truncated to the 13 largest terms
  (|dpsi| error < 0.1 arcsec — the acceptance level of the reference's
  own round-trip test, ``pysla.f90 test_oap_aop``).
- Annual aberration: Earth velocity by central difference of the solar
  position (equivalent to the classical kappa formulation to < 0.01").
- Solar position: Meeus ch. 25 low precision (~1").
- Lunar position: truncated ELP series (Meeus ch. 47, ~0.01 deg).
- Planets: Standish (1992) approximate Keplerian elements, 1800-2050
  (~1 arcmin for Jupiter; the COMAP beam is 4.5 arcmin).
- Refraction: Bennett (1982) with pressure/temperature scaling.

All angles radians unless a function name says ``_deg``. Times are MJD
(UTC); TT-UTC is applied internally where precession/nutation/ephemerides
need it.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "mjd_to_jd", "julian_centuries_tt", "gmst", "last",
    "mean_obliquity", "nutation", "precession_matrix",
    "equatorial_to_cartesian", "cartesian_to_equatorial",
    "apparent_from_j2000", "j2000_from_apparent",
    "hadec_to_azel", "azel_to_hadec", "parallactic_angle",
    "equ_to_gal", "gal_to_equ", "refraction_bennett",
    "sun_position", "moon_position", "planet_position", "PLANETS",
]

TT_MINUS_UTC_DAYS = 69.184 / 86400.0  # TAI-UTC(37s) + 32.184s, post-2017
ARCSEC = np.pi / (180.0 * 3600.0)
J2000_MJD = 51544.5


# -- time scales ------------------------------------------------------------

def mjd_to_jd(mjd):
    return np.asarray(mjd, dtype=np.float64) + 2400000.5


def julian_centuries_tt(mjd):
    """Julian centuries of TT since J2000.0 from a UTC MJD."""
    return (np.asarray(mjd, dtype=np.float64) + TT_MINUS_UTC_DAYS
            - J2000_MJD) / 36525.0


def gmst(mjd, dut1: float = 0.0):
    """Greenwich mean sidereal time [rad] from UTC MJD (IAU 1982)."""
    d = np.asarray(mjd, dtype=np.float64) + dut1 / 86400.0 - J2000_MJD
    t = d / 36525.0
    deg = (280.46061837 + 360.98564736629 * d
           + 0.000387933 * t * t - t * t * t / 38710000.0)
    return np.radians(deg % 360.0)


def last(mjd, longitude, dut1: float = 0.0):
    """Local apparent sidereal time [rad]; ``longitude`` rad east-positive."""
    dpsi, _, eps = nutation(mjd)
    return (gmst(mjd, dut1) + longitude + dpsi * np.cos(eps)) % (2 * np.pi)


# -- precession / nutation --------------------------------------------------

def mean_obliquity(mjd):
    """Mean obliquity of the ecliptic [rad] (IAU 1980)."""
    t = julian_centuries_tt(mjd)
    sec = 84381.448 - 46.8150 * t - 0.00059 * t**2 + 0.001813 * t**3
    return sec * ARCSEC


# IAU 1980 nutation, 13 largest terms (Meeus Table 22.A).
# Columns: D, M, M', F, Omega multipliers; psi_sin, psi_sin_t;
# eps_cos, eps_cos_t (units 0.0001 arcsec).
_NUTATION_TERMS = np.array([
    [0, 0, 0, 0, 1, -171996.0, -174.2, 92025.0, 8.9],
    [-2, 0, 0, 2, 2, -13187.0, -1.6, 5736.0, -3.1],
    [0, 0, 0, 2, 2, -2274.0, -0.2, 977.0, -0.5],
    [0, 0, 0, 0, 2, 2062.0, 0.2, -895.0, 0.5],
    [0, 1, 0, 0, 0, 1426.0, -3.4, 54.0, -0.1],
    [0, 0, 1, 0, 0, 712.0, 0.1, -7.0, 0.0],
    [-2, 1, 0, 2, 2, -517.0, 1.2, 224.0, -0.6],
    [0, 0, 0, 2, 1, -386.0, -0.4, 200.0, 0.0],
    [0, 0, 1, 2, 2, -301.0, 0.0, 129.0, -0.1],
    [-2, -1, 0, 2, 2, 217.0, -0.5, -95.0, 0.3],
    [-2, 0, 1, 0, 0, -158.0, 0.0, 0.0, 0.0],
    [-2, 0, 0, 2, 1, 129.0, 0.1, -70.0, 0.0],
    [0, 0, -1, 2, 2, 123.0, 0.0, -53.0, 0.0],
])


def _fundamental_arguments(t):
    """Delaunay arguments [rad] (Meeus ch. 22)."""
    D = (297.85036 + 445267.111480 * t - 0.0019142 * t**2 + t**3 / 189474.0)
    M = (357.52772 + 35999.050340 * t - 0.0001603 * t**2 - t**3 / 300000.0)
    Mp = (134.96298 + 477198.867398 * t + 0.0086972 * t**2 + t**3 / 56250.0)
    F = (93.27191 + 483202.017538 * t - 0.0036825 * t**2 + t**3 / 327270.0)
    Om = (125.04452 - 1934.136261 * t + 0.0020708 * t**2 + t**3 / 450000.0)
    return tuple(np.radians(np.mod(x, 360.0)) for x in (D, M, Mp, F, Om))


def nutation(mjd):
    """Nutation (dpsi, deps) and TRUE obliquity eps [rad]."""
    t = np.asarray(julian_centuries_tt(mjd), dtype=np.float64)
    D, M, Mp, F, Om = _fundamental_arguments(t)
    args = np.stack([D, M, Mp, F, Om], axis=-1)  # (..., 5)
    mult = _NUTATION_TERMS[:, :5]                # (13, 5)
    phase = np.tensordot(args, mult.T, axes=([-1], [0]))  # (..., 13)
    psi = (_NUTATION_TERMS[:, 5] + _NUTATION_TERMS[:, 6] * t[..., None])
    eps = (_NUTATION_TERMS[:, 7] + _NUTATION_TERMS[:, 8] * t[..., None])
    dpsi = np.sum(psi * np.sin(phase), axis=-1) * 1e-4 * ARCSEC
    deps = np.sum(eps * np.cos(phase), axis=-1) * 1e-4 * ARCSEC
    eps_true = mean_obliquity(mjd) + deps
    return dpsi, deps, eps_true


def _rx(a):
    c, s = np.cos(a), np.sin(a)
    z, o = np.zeros_like(c), np.ones_like(c)
    return np.stack([np.stack([o, z, z], -1),
                     np.stack([z, c, s], -1),
                     np.stack([z, -s, c], -1)], -2)


def _ry(a):
    c, s = np.cos(a), np.sin(a)
    z, o = np.zeros_like(c), np.ones_like(c)
    return np.stack([np.stack([c, z, -s], -1),
                     np.stack([z, o, z], -1),
                     np.stack([s, z, c], -1)], -2)


def _rz(a):
    c, s = np.cos(a), np.sin(a)
    z, o = np.zeros_like(c), np.ones_like(c)
    return np.stack([np.stack([c, s, z], -1),
                     np.stack([-s, c, z], -1),
                     np.stack([z, z, o], -1)], -2)


def precession_matrix(mjd):
    """IAU 1976 precession matrix J2000 -> mean of date (Meeus 21.2).

    Returns (..., 3, 3); apply to a J2000 cartesian vector.
    """
    t = np.asarray(julian_centuries_tt(mjd), dtype=np.float64)
    zeta = (2306.2181 * t + 0.30188 * t**2 + 0.017998 * t**3) * ARCSEC
    z = (2306.2181 * t + 1.09468 * t**2 + 0.018203 * t**3) * ARCSEC
    theta = (2004.3109 * t - 0.42665 * t**2 - 0.041833 * t**3) * ARCSEC
    return _rz(-z) @ _ry(theta) @ _rz(-zeta)


def nutation_matrix(mjd):
    """Nutation matrix mean-of-date -> true-of-date."""
    dpsi, deps, eps_true = nutation(mjd)
    eps0 = mean_obliquity(mjd)
    return _rx(-(eps0 + deps)) @ _rz(-dpsi) @ _rx(eps0)


# -- vectors ----------------------------------------------------------------

def equatorial_to_cartesian(ra, dec):
    ra = np.asarray(ra, dtype=np.float64)
    dec = np.asarray(dec, dtype=np.float64)
    return np.stack([np.cos(dec) * np.cos(ra),
                     np.cos(dec) * np.sin(ra),
                     np.sin(dec)], axis=-1)


def cartesian_to_equatorial(v):
    v = np.asarray(v, dtype=np.float64)
    ra = np.arctan2(v[..., 1], v[..., 0]) % (2 * np.pi)
    dec = np.arcsin(np.clip(v[..., 2] / np.linalg.norm(v, axis=-1), -1, 1))
    return ra, dec


def _apply(m, v):
    return np.einsum("...ij,...j->...i", m, v)


# -- aberration -------------------------------------------------------------

_C_AU_PER_DAY = 173.144632674  # speed of light [AU/day]


def _earth_velocity(mjd):
    """Earth barycentric-ish velocity [AU/day] by central difference of the
    geocentric solar position (annual aberration only, < 0.01" error)."""
    dt = 0.05
    r1 = _sun_vector(np.asarray(mjd, dtype=np.float64) - dt)
    r2 = _sun_vector(np.asarray(mjd, dtype=np.float64) + dt)
    # geocentric sun moves opposite to heliocentric earth
    return (r2 - r1) / (2 * dt)


def aberrate(v, mjd):
    """Apply annual aberration to unit vector(s) ``v`` (true direction ->
    apparent direction)."""
    beta = _earth_velocity(mjd) / _C_AU_PER_DAY
    out = v + beta
    return out / np.linalg.norm(out, axis=-1, keepdims=True)


def unaberrate(v, mjd):
    beta = _earth_velocity(mjd) / _C_AU_PER_DAY
    out = v - beta
    return out / np.linalg.norm(out, axis=-1, keepdims=True)


# -- apparent place chain ---------------------------------------------------

def apparent_from_j2000(ra, dec, mjd):
    """Mean J2000 RA/Dec -> apparent (true-of-date) RA/Dec [rad].

    Chain: aberration -> precession -> nutation (the reference's
    ``sla_map`` role; proper motion/parallax are zero for COMAP targets).
    """
    v = equatorial_to_cartesian(ra, dec)
    v = aberrate(v, mjd)
    m = nutation_matrix(mjd) @ precession_matrix(mjd)
    return cartesian_to_equatorial(_apply(m, v))


def j2000_from_apparent(ra, dec, mjd):
    """Apparent RA/Dec of date -> mean J2000 (the ``sla_amp`` role)."""
    v = equatorial_to_cartesian(ra, dec)
    m = nutation_matrix(mjd) @ precession_matrix(mjd)
    v = _apply(np.swapaxes(m, -1, -2), v)
    return cartesian_to_equatorial(unaberrate(v, mjd))


# -- horizontal <-> equatorial ----------------------------------------------

def hadec_to_azel(ha, dec, lat):
    """Hour angle/declination -> azimuth (N=0, E=90deg)/elevation [rad]."""
    ha, dec = np.asarray(ha, np.float64), np.asarray(dec, np.float64)
    sl, cl = np.sin(lat), np.cos(lat)
    se = sl * np.sin(dec) + cl * np.cos(dec) * np.cos(ha)
    el = np.arcsin(np.clip(se, -1, 1))
    az = np.arctan2(-np.cos(dec) * np.sin(ha),
                    np.sin(dec) * cl - np.cos(dec) * np.cos(ha) * sl)
    return az % (2 * np.pi), el


def azel_to_hadec(az, el, lat):
    az, el = np.asarray(az, np.float64), np.asarray(el, np.float64)
    sl, cl = np.sin(lat), np.cos(lat)
    sd = sl * np.sin(el) + cl * np.cos(el) * np.cos(az)
    dec = np.arcsin(np.clip(sd, -1, 1))
    ha = np.arctan2(-np.cos(el) * np.sin(az),
                    np.sin(el) * cl - np.cos(el) * np.cos(az) * sl)
    return ha, dec


def parallactic_angle(ha, dec, lat):
    """Parallactic angle [rad] (the ``sla_pa`` role)."""
    ha, dec = np.asarray(ha, np.float64), np.asarray(dec, np.float64)
    return np.arctan2(np.sin(ha),
                      np.tan(lat) * np.cos(dec) - np.sin(dec) * np.cos(ha))


# -- galactic ---------------------------------------------------------------

# J2000 equatorial -> galactic rotation matrix (IAU 1958 pole at J2000:
# NGP RA 192.85948 deg, Dec 27.12825 deg, l of NCP 122.93192 deg). The
# standard matrix (e.g. Hipparcos vol. 1 eq. 1.5.11), not re-derived.
_EQ2GAL = np.array([
    [-0.0548755604, -0.8734370902, -0.4838350155],
    [0.4941094279, -0.4448296300, 0.7469822445],
    [-0.8676661490, -0.1980763734, 0.4559837762],
])
# orthonormalise the 10-digit literal so round trips are exact
_u, _, _vt = np.linalg.svd(_EQ2GAL)
_EQ2GAL = _u @ _vt


def equ_to_gal(ra, dec):
    """J2000 RA/Dec -> galactic l, b [rad] (``sla_eqgal`` role)."""
    v = equatorial_to_cartesian(ra, dec)
    return cartesian_to_equatorial(_apply(_EQ2GAL, v))


def gal_to_equ(gl, gb):
    v = equatorial_to_cartesian(gl, gb)
    return cartesian_to_equatorial(_apply(_EQ2GAL.T, v))


# -- refraction -------------------------------------------------------------

def refraction_bennett(el, pressure_mb: float = 870.0,
                       temperature_c: float = 0.0):
    """Atmospheric refraction [rad] to ADD to the true elevation
    (Bennett 1982 with P/T scaling; ~1000 m site default). The reference
    uses ``sla_refro``; at el > 30 deg (COMAP's observing range) the two
    agree to ~1 arcsec."""
    h = np.degrees(np.asarray(el, dtype=np.float64))
    r_arcmin = 1.02 / np.tan(np.radians(h + 10.3 / (h + 5.11)))
    scale = (pressure_mb / 1010.0) * (283.0 / (273.0 + temperature_c))
    return np.radians(np.maximum(r_arcmin, 0.0) * scale / 60.0)


# -- solar / lunar / planetary ephemerides ----------------------------------

def _sun_ecliptic(mjd):
    """Geometric solar ecliptic longitude [rad] and distance [AU]
    (Meeus ch. 25)."""
    t = julian_centuries_tt(mjd)
    L0 = 280.46646 + 36000.76983 * t + 0.0003032 * t**2
    M = np.radians((357.52911 + 35999.05029 * t - 0.0001537 * t**2) % 360.0)
    e = 0.016708634 - 0.000042037 * t
    C = ((1.914602 - 0.004817 * t - 0.000014 * t**2) * np.sin(M)
         + (0.019993 - 0.000101 * t) * np.sin(2 * M)
         + 0.000289 * np.sin(3 * M))
    lon = np.radians((L0 + C) % 360.0)
    nu = M + np.radians(C)
    r = 1.000001018 * (1 - e**2) / (1 + e * np.cos(nu))
    return lon, r


def _sun_vector(mjd):
    """Geocentric solar position vector [AU], mean equator/equinox of date
    approximated with the J2000 obliquity (aberration use only)."""
    lon, r = _sun_ecliptic(mjd)
    eps = mean_obliquity(mjd)
    x = r * np.cos(lon)
    y = r * np.sin(lon) * np.cos(eps)
    z = r * np.sin(lon) * np.sin(eps)
    return np.stack([x, y, z], axis=-1)


def sun_position(mjd):
    """Apparent geocentric RA/Dec [rad] and distance [AU] of the Sun."""
    lon, r = _sun_ecliptic(mjd)
    t = julian_centuries_tt(mjd)
    om = np.radians(125.04 - 1934.136 * t)
    lam = lon - np.radians(0.00569 + 0.00478 * np.sin(om))
    eps = mean_obliquity(mjd) + np.radians(0.00256) * np.cos(om)
    ra = np.arctan2(np.cos(eps) * np.sin(lam), np.cos(lam)) % (2 * np.pi)
    dec = np.arcsin(np.clip(np.sin(eps) * np.sin(lam), -1, 1))
    return ra, dec, r


# Truncated lunar series (Meeus ch. 47, largest terms).
def moon_position(mjd):
    """Geocentric apparent RA/Dec [rad] and distance [AU] of the Moon
    (truncated ELP: ~0.01 deg, fine vs the 0.5 deg lunar disc)."""
    t = julian_centuries_tt(mjd)
    Lp = np.radians((218.3164477 + 481267.88123421 * t
                     - 0.0015786 * t**2) % 360.0)
    D = np.radians((297.8501921 + 445267.1114034 * t
                    - 0.0018819 * t**2) % 360.0)
    M = np.radians((357.5291092 + 35999.0502909 * t) % 360.0)
    Mp = np.radians((134.9633964 + 477198.8675055 * t
                     + 0.0087414 * t**2) % 360.0)
    F = np.radians((93.2720950 + 483202.0175233 * t
                    - 0.0036539 * t**2) % 360.0)
    # eccentricity damping of solar-anomaly terms (Meeus 47.6)
    E = 1.0 - 0.002516 * t - 0.0000074 * t**2
    # longitude terms (1e-6 deg; Meeus Table 47.A, |coeff| > 3500)
    dlon = (6288774 * np.sin(Mp) + 1274027 * np.sin(2 * D - Mp)
            + 658314 * np.sin(2 * D) + 213618 * np.sin(2 * Mp)
            - 185116 * E * np.sin(M) - 114332 * np.sin(2 * F)
            + 58793 * np.sin(2 * D - 2 * Mp)
            + 57066 * E * np.sin(2 * D - M - Mp)
            + 53322 * np.sin(2 * D + Mp)
            + 45758 * E * np.sin(2 * D - M)
            - 40923 * E * np.sin(M - Mp) - 34720 * np.sin(D)
            - 30383 * E * np.sin(M + Mp) + 15327 * np.sin(2 * D - 2 * F)
            - 12528 * np.sin(Mp + 2 * F) + 10980 * np.sin(Mp - 2 * F)
            + 10675 * np.sin(4 * D - Mp) + 10034 * np.sin(3 * Mp)
            + 8548 * np.sin(4 * D - 2 * Mp)
            - 7888 * E * np.sin(2 * D + M - Mp)
            - 6766 * E * np.sin(2 * D + M) - 5163 * np.sin(D - Mp)
            + 4987 * E * np.sin(D + M)
            + 4036 * E * np.sin(2 * D - M + Mp)
            + 3994 * np.sin(2 * D + 2 * Mp) + 3861 * np.sin(4 * D)
            + 3665 * np.sin(2 * D - 3 * Mp)) * 1e-6
    # latitude terms (Meeus Table 47.B, |coeff| > 4000)
    dlat = (5128122 * np.sin(F) + 280602 * np.sin(Mp + F)
            + 277693 * np.sin(Mp - F) + 173237 * np.sin(2 * D - F)
            + 55413 * np.sin(2 * D - Mp + F)
            + 46271 * np.sin(2 * D - Mp - F)
            + 32573 * np.sin(2 * D + F) + 17198 * np.sin(2 * Mp + F)
            + 9266 * np.sin(2 * D + Mp - F) + 8822 * np.sin(2 * Mp - F)
            + 8216 * E * np.sin(2 * D - M - F)
            + 4324 * np.sin(2 * D - 2 * Mp - F)
            + 4200 * np.sin(2 * D + Mp + F)) * 1e-6
    dr = (-20905355 * np.cos(Mp) - 3699111 * np.cos(2 * D - Mp)
          - 2955968 * np.cos(2 * D) - 569925 * np.cos(2 * Mp)
          + 48888 * E * np.cos(M) - 3149 * np.cos(2 * F)
          + 246158 * np.cos(2 * D - 2 * Mp)
          - 152138 * E * np.cos(2 * D - M - Mp)
          - 170733 * np.cos(2 * D + Mp)
          - 204586 * E * np.cos(2 * D - M)
          - 129620 * E * np.cos(M - Mp) + 108743 * np.cos(D)
          + 104755 * E * np.cos(M + Mp) + 10321 * np.cos(2 * D - 2 * F)
          + 79661 * np.cos(Mp - 2 * F)) * 1e-3
    lon = Lp + np.radians(dlon)
    lat = np.radians(dlat)
    dist_km = 385000.56 + dr
    eps = mean_obliquity(mjd)
    sl, cl = np.sin(lon), np.cos(lon)
    sb, cb = np.sin(lat), np.cos(lat)
    x = cb * cl
    y = cb * sl * np.cos(eps) - sb * np.sin(eps)
    z = cb * sl * np.sin(eps) + sb * np.cos(eps)
    ra = np.arctan2(y, x) % (2 * np.pi)
    dec = np.arcsin(np.clip(z, -1, 1))
    return ra, dec, dist_km / 149597870.7


# Standish (1992) approximate Keplerian elements, J2000 ecliptic, valid
# 1800-2050. Per planet: a[AU], e, I[deg], L[deg], varpi[deg], Omega[deg]
# and their per-century rates.
PLANETS = {
    "mercury": ((0.38709927, 0.20563593, 7.00497902, 252.25032350,
                 77.45779628, 48.33076593),
                (0.00000037, 0.00001906, -0.00594749, 149472.67411175,
                 0.16047689, -0.12534081)),
    "venus": ((0.72333566, 0.00677672, 3.39467605, 181.97909950,
               131.60246718, 76.67984255),
              (0.00000390, -0.00004107, -0.00078890, 58517.81538729,
               0.00268329, -0.27769418)),
    "earth": ((1.00000261, 0.01671123, -0.00001531, 100.46457166,
               102.93768193, 0.0),
              (0.00000562, -0.00004392, -0.01294668, 35999.37244981,
               0.32327364, 0.0)),
    "mars": ((1.52371034, 0.09339410, 1.84969142, -4.55343205,
              -23.94362959, 49.55953891),
             (0.00001847, 0.00007882, -0.00813131, 19140.30268499,
              0.44441088, -0.29257343)),
    "jupiter": ((5.20288700, 0.04838624, 1.30439695, 34.39644051,
                 14.72847983, 100.47390909),
                (-0.00011607, -0.00013253, -0.00183714, 3034.74612775,
                 0.21252668, 0.20469106)),
    "saturn": ((9.53667594, 0.05386179, 2.48599187, 49.95424423,
                92.59887831, 113.66242448),
               (-0.00125060, -0.00050991, 0.00193609, 1222.49362201,
                -0.41897216, -0.28867794)),
    "uranus": ((19.18916464, 0.04725744, 0.77263783, 313.23810451,
                170.95427630, 74.01692503),
               (-0.00196176, -0.00004397, -0.00242939, 428.48202785,
                0.40805281, 0.04240589)),
    "neptune": ((30.06992276, 0.00859048, 1.77004347, -55.12002969,
                 44.96476227, 131.78422574),
                (0.00026291, 0.00005105, 0.00035372, 218.45945325,
                 -0.32241464, -0.00508664)),
}


def _heliocentric_ecliptic(name, mjd):
    """Heliocentric J2000-ecliptic position [AU] from Standish elements."""
    el0, rate = PLANETS[name]
    t = julian_centuries_tt(mjd)
    a, e, inc, L, varpi, Om = (np.asarray(el0[i] + rate[i] * t)
                               for i in range(6))
    inc, L, varpi, Om = (np.radians(x) for x in (inc, L, varpi, Om))
    w = varpi - Om                      # argument of perihelion
    M = np.mod(L - varpi, 2 * np.pi)    # mean anomaly
    # Kepler solve (Newton, e < 0.21 for all planets: 6 iters ~ 1e-14)
    E = M + e * np.sin(M)
    for _ in range(6):
        E = E - (E - e * np.sin(E) - M) / (1 - e * np.cos(E))
    xp = a * (np.cos(E) - e)            # orbital plane
    yp = a * np.sqrt(1 - e * e) * np.sin(E)
    cw, sw = np.cos(w), np.sin(w)
    cO, sO = np.cos(Om), np.sin(Om)
    ci, si = np.cos(inc), np.sin(inc)
    x = (cw * cO - sw * sO * ci) * xp + (-sw * cO - cw * sO * ci) * yp
    y = (cw * sO + sw * cO * ci) * xp + (-sw * sO + cw * cO * ci) * yp
    z = (sw * si) * xp + (cw * si) * yp
    return np.stack([x, y, z], axis=-1)


_ECL2EQU_J2000 = _rx(-np.radians(23.43928))  # J2000 obliquity


def planet_position(name: str, mjd):
    """Geocentric astrometric J2000 RA/Dec [rad] and distance [AU] of a
    planet (the ``sla_rdplan``/``planet`` role; also accepts 'sun'/'moon').

    Light-time is not iterated (< 20 arcsec for Jupiter motion over the
    ~40 min light travel — below the Standish element accuracy)."""
    name = name.lower()
    if name == "sun":
        return sun_position(mjd)
    if name == "moon":
        return moon_position(mjd)
    p = _heliocentric_ecliptic(name, mjd)
    e = _heliocentric_ecliptic("earth", mjd)
    geo = _apply(_ECL2EQU_J2000, p - e)
    ra, dec = cartesian_to_equatorial(geo)
    return ra, dec, np.linalg.norm(geo, axis=-1)
