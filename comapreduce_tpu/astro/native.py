"""ctypes bindings for the native astrometry library (csrc/astrometry.cpp).

The shared library is built on demand with ``g++ -O3 -shared -fPIC`` into
the package directory (pybind11 is not available in this image; the C ABI
+ ctypes keeps the binding dependency-free). If no compiler is available
the NumPy oracle in :mod:`core` serves alone — ``available()`` gates all
callers.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

import numpy as np

__all__ = ["available", "load", "h2e_full", "e2h_full", "gmst", "last",
           "nutation", "apparent_from_j2000", "j2000_from_apparent",
           "planet_position"]

logger = logging.getLogger("comapreduce_tpu")

_HERE = os.path.dirname(os.path.abspath(__file__))
# repo layout first (csrc/ beside the package), then the copy installed
# as package data by setup.py (non-editable installs have no csrc/)
_SRC_CANDIDATES = (
    os.path.join(os.path.dirname(os.path.dirname(_HERE)), "csrc",
                 "astrometry.cpp"),
    os.path.join(_HERE, "astrometry.cpp"),
)
_SRC = next((p for p in _SRC_CANDIDATES if os.path.exists(p)),
            _SRC_CANDIDATES[0])
_LIB_PATH = os.path.join(_HERE, "_astrometry.so")

_lib = None
_tried = False

_D = np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS")


def _build() -> bool:
    if not os.path.exists(_SRC):
        return False
    # build to a per-PID path and rename: concurrent ranks must never
    # dlopen a half-written .so
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB_PATH)
        return True
    except (OSError, subprocess.SubprocessError) as exc:
        logger.info("native astrometry build failed (%s); using NumPy", exc)
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return False


def load():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB_PATH) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_LIB_PATH)):
        if not _build():
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError as exc:
        logger.info("native astrometry load failed (%s)", exc)
        return None
    lib.cr_gmst.argtypes = [_D, ctypes.c_long, ctypes.c_double, _D]
    lib.cr_last.argtypes = [_D, ctypes.c_long, ctypes.c_double,
                            ctypes.c_double, _D]
    lib.cr_nutation.argtypes = [_D, ctypes.c_long, _D, _D, _D]
    lib.cr_precession_matrix.argtypes = [_D, ctypes.c_long, _D]
    lib.cr_apparent_from_j2000.argtypes = [_D, _D, _D, ctypes.c_long, _D, _D]
    lib.cr_j2000_from_apparent.argtypes = [_D, _D, _D, ctypes.c_long, _D, _D]
    lib.cr_h2e_full.argtypes = [_D, _D, _D, ctypes.c_long, ctypes.c_double,
                                ctypes.c_double, ctypes.c_double,
                                ctypes.c_int, ctypes.c_long, _D, _D]
    lib.cr_e2h_full.argtypes = [_D, _D, _D, ctypes.c_long, ctypes.c_double,
                                ctypes.c_double, ctypes.c_double,
                                ctypes.c_int, ctypes.c_long, _D, _D]
    lib.cr_planet.argtypes = [ctypes.c_char_p, _D, ctypes.c_long, _D, _D, _D]
    lib.cr_planet.restype = ctypes.c_int
    _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


def _as1d(x):
    return np.ascontiguousarray(np.atleast_1d(x), dtype=np.float64)


def gmst(mjd, dut1: float = 0.0):
    lib = load()
    m = _as1d(mjd)
    out = np.empty_like(m)
    lib.cr_gmst(m, m.size, dut1, out)
    return out


def last(mjd, longitude, dut1: float = 0.0):
    lib = load()
    m = _as1d(mjd)
    out = np.empty_like(m)
    lib.cr_last(m, m.size, float(longitude), dut1, out)
    return out


def nutation(mjd):
    lib = load()
    m = _as1d(mjd)
    dpsi = np.empty_like(m)
    deps = np.empty_like(m)
    eps = np.empty_like(m)
    lib.cr_nutation(m, m.size, dpsi, deps, eps)
    return dpsi, deps, eps


def apparent_from_j2000(ra, dec, mjd):
    lib = load()
    r, d = _as1d(ra), _as1d(dec)
    m = np.ascontiguousarray(np.broadcast_to(_as1d(mjd), r.shape))
    ra_o = np.empty_like(r)
    dec_o = np.empty_like(r)
    lib.cr_apparent_from_j2000(r, d, m, r.size, ra_o, dec_o)
    return ra_o, dec_o


def j2000_from_apparent(ra, dec, mjd):
    lib = load()
    r, d = _as1d(ra), _as1d(dec)
    m = np.ascontiguousarray(np.broadcast_to(_as1d(mjd), r.shape))
    ra_o = np.empty_like(r)
    dec_o = np.empty_like(r)
    lib.cr_j2000_from_apparent(r, d, m, r.size, ra_o, dec_o)
    return ra_o, dec_o


def h2e_full(az_rad, el_rad, mjd, longitude_rad, latitude_rad,
             dut1: float = 0.0, apply_refraction: bool = True,
             stride: int = 50):
    """Radian-domain batch h2e chain (see coordinates.h2e_full)."""
    lib = load()
    a, e = _as1d(az_rad), _as1d(el_rad)
    m = np.ascontiguousarray(np.broadcast_to(_as1d(mjd), a.shape))
    ra = np.empty_like(a)
    dec = np.empty_like(a)
    lib.cr_h2e_full(a, e, m, a.size, float(longitude_rad),
                    float(latitude_rad), dut1, int(apply_refraction),
                    int(stride), ra, dec)
    return ra, dec


def e2h_full(ra_rad, dec_rad, mjd, longitude_rad, latitude_rad,
             dut1: float = 0.0, apply_refraction: bool = True):
    lib = load()
    r, d = _as1d(ra_rad), _as1d(dec_rad)
    m = np.ascontiguousarray(np.broadcast_to(_as1d(mjd), r.shape))
    az = np.empty_like(r)
    el = np.empty_like(r)
    lib.cr_e2h_full(r, d, m, r.size, float(longitude_rad),
                    float(latitude_rad), dut1, int(apply_refraction), 1,
                    az, el)
    return az, el


def planet_position(name: str, mjd):
    if name.lower() in ("sun", "moon"):
        # backend parity with core.planet_position: sun/moon use the
        # Meeus series, which live only in the NumPy oracle (they are not
        # per-sample hot paths)
        from comapreduce_tpu.astro import core
        return core.planet_position(name, mjd)
    lib = load()
    m = _as1d(mjd)
    ra = np.empty_like(m)
    dec = np.empty_like(m)
    dist = np.empty_like(m)
    rc = lib.cr_planet(name.lower().encode(), m, m.size, ra, dec, dist)
    if rc != 0:
        raise KeyError(f"unknown planet {name!r}")
    return ra, dec, dist
