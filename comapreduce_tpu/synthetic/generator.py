"""Scenario -> per-file observation parameters -> Level-1 files.

One deterministic mapping, used by every consumer: the disk writer
(:func:`write_campaign`), the in-memory ingest source
(``synthetic/memsource.py``), the transfer-function workload and the
scale drill all call :func:`file_params`, so a campaign's bytes are
identical however it is materialised.

Per-file variation is a pure function of ``(scenario, index)``:

- obsid/MJD step linearly;
- ``shape_jitter`` perturbs ``scan_samples`` on a fixed pseudo-random
  lattice (``(index * 29) % 97`` — the bench's shape-bucket exercise),
  so a jittered campaign covers many TOD shapes with a bounded bucket
  census;
- ``weather_drift`` ramps the zenith atmosphere linearly across the
  campaign (file 0 coldest, file N-1 wettest);
- the per-file RNG seed is ``seed * 1_000_003 + index`` — distinct
  streams per file, reproducible forever.
"""

from __future__ import annotations

import os

from comapreduce_tpu.synthetic.scenario import ScenarioConfig

__all__ = ["file_basename", "file_params", "campaign_params",
           "campaign_truth", "virtual_filelist", "write_campaign",
           "SCHEME"]

# virtual-path scheme for in-memory campaigns (see memsource.py)
SCHEME = "synth://"


def _jitter(cfg: ScenarioConfig, index: int) -> int:
    """Deterministic scan_samples jitter in [-shape_jitter, +shape_jitter]."""
    if cfg.shape_jitter <= 0:
        return 0
    lattice = ((index * 29) % 97) - 48  # in [-48, 48]
    return int(round(cfg.shape_jitter * lattice / 48.0))


def file_basename(cfg: ScenarioConfig, index: int) -> str:
    """Campaign-unique Level-1 basename (COMAP naming scheme)."""
    return f"comap-{cfg.obsid_start + index:07d}-{cfg.name}.hd5"


def file_params(cfg: ScenarioConfig, index: int):
    """The ``SyntheticObsParams`` for file ``index`` of the scenario."""
    from comapreduce_tpu.data.synthetic import SyntheticObsParams

    if not 0 <= index < cfg.n_files:
        raise IndexError(f"file index {index} outside scenario "
                         f"[0, {cfg.n_files})")
    frac = index / max(cfg.n_files - 1, 1)
    scan_samples = max(cfg.scan_samples + _jitter(cfg, index), 0)
    return SyntheticObsParams(
        obsid=cfg.obsid_start + index,
        source=cfg.source,
        n_feeds=cfg.n_feeds,
        n_bands=cfg.n_bands,
        n_channels=cfg.n_channels,
        n_scans=cfg.n_scans,
        scan_samples=scan_samples,
        vane_samples=cfg.vane_samples,
        gap_samples=cfg.gap_samples,
        mjd_start=cfg.mjd_start + index * cfg.mjd_step,
        elevation=cfg.elevation,
        el_sweep=cfg.el_sweep,
        az_throw=cfg.az_throw,
        ra0=cfg.ra0,
        dec0=cfg.dec0,
        t_atm_zenith=(cfg.t_atm_zenith
                      + cfg.weather_drift * (frac - 0.5)),
        sigma_g=cfg.sigma_g,
        fknee=cfg.fknee,
        alpha=cfg.alpha,
        t_atm_sigma=cfg.t_atm_sigma,
        t_atm_fknee=cfg.t_atm_fknee,
        t_atm_alpha=cfg.t_atm_alpha,
        spike_rate=cfg.spike_rate,
        nan_rate=cfg.nan_rate,
        sky_model=cfg.sky_model(),
        seed=cfg.seed * 1_000_003 + index,
        comment=f"scenario={cfg.name} index={index}",
    )


def campaign_params(cfg: ScenarioConfig) -> list:
    """``file_params`` for every file of the scenario, in order."""
    return [file_params(cfg, i) for i in range(cfg.n_files)]


def campaign_truth(cfg: ScenarioConfig) -> dict:
    """JSON-serialisable ground truth of the campaign: per-file identity
    plus the injected noise/sky parameters recovery is checked against
    (docs/OPERATIONS.md §18)."""
    files = []
    for i in range(cfg.n_files):
        frac = i / max(cfg.n_files - 1, 1)
        files.append({
            "index": i,
            "basename": file_basename(cfg, i),
            "obsid": cfg.obsid_start + i,
            "seed": cfg.seed * 1_000_003 + i,
            "scan_samples": max(cfg.scan_samples + _jitter(cfg, i), 0),
            "t_atm_zenith": cfg.t_atm_zenith
            + cfg.weather_drift * (frac - 0.5),
        })
    return {
        "scenario": cfg.name,
        "seed": cfg.seed,
        "n_files": cfg.n_files,
        "noise": {"sigma_g": cfg.sigma_g, "fknee": cfg.fknee,
                  "alpha": cfg.alpha,
                  "t_atm_sigma": cfg.t_atm_sigma,
                  "t_atm_fknee": cfg.t_atm_fknee,
                  "t_atm_alpha": cfg.t_atm_alpha},
        "faults": {"spike_rate": cfg.spike_rate, "nan_rate": cfg.nan_rate},
        "sky": {"amplitude_k": cfg.sky_amplitude_k,
                "fwhm_deg": cfg.sky_fwhm_deg, "index": cfg.sky_index,
                "ra0": cfg.ra0, "dec0": cfg.dec0},
        "files": files,
    }


def virtual_filelist(cfg: ScenarioConfig) -> list:
    """``synth://`` paths for the whole campaign — serve them through
    the ingest loaders with zero disk (``memsource.register_scenario``
    first)."""
    return [f"{SCHEME}{cfg.name}/{i:05d}/{file_basename(cfg, i)}"
            for i in range(cfg.n_files)]


def write_campaign(cfg: ScenarioConfig, out_dir: str,
                   indices=None) -> list:
    """Stream the campaign to ``out_dir`` as real Level-1 HDF5 files;
    returns the written paths (same bytes as the in-memory source)."""
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for i in (range(cfg.n_files) if indices is None else indices):
        from comapreduce_tpu.data.synthetic import generate_level1_file

        path = os.path.join(out_dir, file_basename(cfg, i))
        generate_level1_file(path, file_params(cfg, i))
        paths.append(path)
    return paths
