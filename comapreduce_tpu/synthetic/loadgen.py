"""The scale drill: a generated file queue against the whole serving
stack at once.

``run_synthetic_drill`` points an N-file virtual campaign
(``synth://`` members, zero bytes of Level-1 on disk) at every moving
part the repo ships, simultaneously:

- **elastic reduce**: three real worker processes (``python -m
  comapreduce_tpu.synthetic.loadgen --worker``) share one lease-file
  queue and run the REAL stage chain (``Runner.from_config``) over the
  virtual members; each worker re-registers the scenario from its TOML
  on the command line — the determinism contract is what makes a
  late-joining process serve identical bytes;
- **ranks leaving and joining**: rank 1 is SIGKILLed the moment it
  holds a live lease (the leaked lease must be stolen by a survivor),
  then a NEW process rejoins as rank 1 mid-run and drains queue tail —
  its fresh heartbeat is also what returns ``/healthz`` to 200;
- **publish pressure**: a ``serving.MapServer`` (with a tile root
  attached) folds committed files into versioned epochs WHILE the
  queue is still draining — one mid-run epoch under load, one final
  epoch over the full census;
- **live observability**: a ``telemetry.live.LiveServer`` sidecar is
  scraped throughout — ``/healthz`` must flip 503 within one TTL of
  the kill and recover after the rejoin, and the final ``/metrics``
  commit counters must match the per-rank scheduler accounting
  exactly for ranks whose telemetry stream was drained cleanly.

Every gate is machine-independent (counts, lease states, census
equality — never wall time), so ``tools/check_resilience.py
--synthetic-only`` behaves identically on a laptop and in CI.
"""

from __future__ import annotations

import glob as _glob
import json
import logging
import os
import time

__all__ = ["SCALE_SCENARIO", "scale_scenario", "write_scenario_toml",
           "run_synthetic_drill"]

logger = logging.getLogger("comapreduce_tpu")

# Per-file shape is deliberately tiny (one feed, one band, 16 channels,
# two ~256-sample scans): the drill's subject is the QUEUE — hundreds
# of files through claim/reduce/commit/fold — not per-file science.
# shape_jitter exercises the shape-bucket compile reuse across the
# campaign; the small spike/NaN rates keep the numerical tripwires in
# the hot path; TauA routes the calibrator reduce chain (cheapest).
SCALE_SCENARIO = dict(
    name="scale",
    source="TauA",
    n_feeds=1,
    n_bands=1,
    n_channels=16,
    n_scans=2,
    scan_samples=256,
    vane_samples=64,
    # gap must exceed MeasureSystemTemperature's window pad (30 in
    # _reduce_config) or the padded vane windows swallow faulted scan
    # samples and the Tsys solve zeroes out — see _reduce_config.
    gap_samples=40,
    shape_jitter=16,
    az_throw=0.25,
    t_atm_sigma=0.01,
    t_atm_fknee=1.0,
    t_atm_alpha=1.5,
    spike_rate=0.002,
    nan_rate=0.001,
)

_N_RANKS = 3
# the drill's MeasureSystemTemperature window pad — ONE constant shared
# by _reduce_config and the load-time pad-vs-gap fault trap
# (ScenarioConfig.validate_vane_pad), so the stage chain and the
# validation can never drift apart
_VANE_PAD = 30
MAP_SHAPE = (64, 64)
CDELT = (1.0 / 60.0, 1.0 / 60.0)


def scale_scenario(seed: int = 0, n_files: int = 200, **overrides):
    from comapreduce_tpu.synthetic.scenario import ScenarioConfig

    knobs = dict(SCALE_SCENARIO)
    knobs.update(overrides)
    knobs["seed"] = int(seed)
    knobs["n_files"] = int(n_files)
    return ScenarioConfig.coerce(knobs)


def write_scenario_toml(cfg, path: str) -> str:
    """Serialise ``cfg`` as a loadable ``[scenario]`` TOML file — the
    hand-off a subprocess worker (or another host) re-registers from."""
    lines = ["[scenario]"]
    for key in type(cfg).KNOBS:
        v = getattr(cfg, key)
        if isinstance(v, str):
            lines.append(f'{key} = "{v}"')
        elif isinstance(v, bool):
            lines.append(f"{key} = {str(v).lower()}")
        else:
            lines.append(f"{key} = {v!r}")
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    os.replace(tmp, path)
    return path


def _reduce_config(out_dir: str, state_dir: str, ttl_s: float) -> dict:
    """The workers' stage chain: the standard calibration front half
    (enough to produce a servable Level-2), elastic claiming on."""
    return {
        "Global": {
            "processes": ["CheckLevel1File", "AssignLevel1Data",
                          "MeasureSystemTemperature", "AtmosphereRemoval",
                          "Level1AveragingGainCorrection"],
            "output_dir": out_dir,
            "log_dir": state_dir,
        },
        "CheckLevel1File": {"min_duration_seconds": 5.0},
        # pad must stay below gap_samples: the stage widens each vane
        # window by `pad` to catch post-retraction sky samples, and at
        # the default 50 it reaches past the 40-sample gap into the
        # scan cells where the scenario's spike/NaN faults live — one
        # fault inside the window NaNs the range normalisation and
        # zeroes the whole event's Tsys (hence every Level-2 weight).
        "MeasureSystemTemperature": {"pad": _VANE_PAD},
        "Level1AveragingGainCorrection": {"feed_batch": 1},
        "resilience": {"lease_ttl_s": ttl_s,
                       "heartbeat_s": max(ttl_s / 5.0, 0.05)},
    }


def _worker_main(argv=None) -> int:
    """One elastic reduce rank over a virtual campaign (the
    ``python -m comapreduce_tpu.synthetic.loadgen --worker`` entry).

    The scenario TOML on the command line is the ONLY data hand-off:
    the worker re-registers it, derives the same ``synth://`` filelist
    every sibling derives, and claims from the shared lease queue."""
    import argparse

    from comapreduce_tpu.pipeline.runner import Runner
    from comapreduce_tpu.synthetic.generator import virtual_filelist
    from comapreduce_tpu.synthetic.memsource import register_scenario_file

    p = argparse.ArgumentParser(prog="loadgen-worker")
    p.add_argument("--scenario", required=True)
    p.add_argument("--state-dir", required=True)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--rank", type=int, required=True)
    p.add_argument("--n-ranks", type=int, default=_N_RANKS)
    p.add_argument("--ttl", type=float, default=2.0)
    p.add_argument("--telemetry", action="store_true")
    a = p.parse_args(argv)
    if a.telemetry:
        from comapreduce_tpu.telemetry import TELEMETRY

        TELEMETRY.configure(a.state_dir, rank=a.rank, flush_s=0.2)
    # vane_pad threads the chain's window pad into the load-time
    # pad-vs-gap fault trap: a scenario whose gap the padded vane
    # windows would overrun fails HERE, not as silently-zero weights
    cfg = register_scenario_file(a.scenario, vane_pad=_VANE_PAD)
    files = virtual_filelist(cfg)
    runner = Runner.from_config(
        _reduce_config(a.output_dir, a.state_dir, a.ttl),
        rank=a.rank, n_ranks=a.n_ranks)
    results = runner.run_tod(files)
    out = {"rank": a.rank, "n_results": len(results),
           "stats": dict(runner.scheduler_stats or {})}
    tmp = os.path.join(a.state_dir, f".result.rank{a.rank}.tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(out, f)
    os.replace(tmp, os.path.join(a.state_dir,
                                 f"result.rank{a.rank}.json"))
    if a.telemetry:
        from comapreduce_tpu.telemetry import TELEMETRY

        TELEMETRY.close()
    return 0


def _scan_leases(state_dir: str) -> dict:
    """``{basename: lease dict}`` for every lease file in the queue."""
    from comapreduce_tpu.resilience.lease import read_lease

    out = {}
    for p in _glob.glob(os.path.join(state_dir, "lease.*.json")):
        st = read_lease(p)
        if st is not None:
            out[os.path.basename(str(st.get("file", p)))] = st
    return out


def run_synthetic_drill(workdir: str, seed: int = 0, n_files: int = 200,
                        ttl_s: float = 2.0,
                        timeout_s: float = 600.0) -> dict:
    """The scale drill; returns the evidence dict, raises
    ``AssertionError`` with a named criterion on any broken promise."""
    import subprocess
    import sys
    from urllib.error import URLError
    from urllib.request import urlopen

    from comapreduce_tpu.mapmaking.wcs import WCS
    from comapreduce_tpu.resilience.drill import _child_env
    from comapreduce_tpu.serving.epochs import EpochStore
    from comapreduce_tpu.serving.ledger import ServedLedger
    from comapreduce_tpu.serving.server import MapServer
    from comapreduce_tpu.synthetic.generator import virtual_filelist
    from comapreduce_tpu.synthetic.memsource import register_scenario
    from comapreduce_tpu.telemetry.live import LiveServer
    from comapreduce_tpu.tiles.tiler import TileSet

    t0 = time.perf_counter()
    dirs = {k: os.path.join(workdir, k)
            for k in ("state", "level2", "epochs", "tiles")}
    for d in dirs.values():
        os.makedirs(d, exist_ok=True)

    # the same trap the workers run at registration — fired before any
    # process spawns, so a pad-vs-gap override fails in one stack trace
    cfg = scale_scenario(seed, n_files).validate_vane_pad(_VANE_PAD)
    register_scenario(cfg)
    scenario_toml = write_scenario_toml(
        cfg, os.path.join(workdir, "scenario.toml"))
    files = virtual_filelist(cfg)
    names = sorted(os.path.basename(f) for f in files)
    env = _child_env()
    srv = LiveServer(dirs["state"], port=0, stale_s=ttl_s,
                     n_ranks=_N_RANKS).start()

    def spawn(rank: int):
        cmd = [sys.executable, "-m", "comapreduce_tpu.synthetic.loadgen",
               "--worker", f"--scenario={scenario_toml}",
               f"--state-dir={dirs['state']}",
               f"--output-dir={dirs['level2']}", f"--rank={rank}",
               f"--n-ranks={_N_RANKS}", f"--ttl={ttl_s}", "--telemetry"]
        return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)

    def wait(pr):
        try:
            stdout, _ = pr.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            pr.kill()
            stdout, _ = pr.communicate()
        return pr.returncode, (stdout or b"").decode(errors="replace")

    def probe() -> int:
        try:
            with urlopen(f"http://{srv.host}:{srv.port}/healthz",
                         timeout=10) as r:
                return r.status
        except URLError as exc:
            code = getattr(exc, "code", None)
            if code is not None:
                return int(code)
            raise

    def poll_until(pred, deadline_s: float, what: str):
        t_start = time.monotonic()
        while True:
            got = pred()
            if got:
                return got
            if time.monotonic() - t_start > deadline_s:
                raise AssertionError(
                    f"scale drill: {what} never happened within "
                    f"{deadline_s:.0f} s")
            time.sleep(0.05)

    server = MapServer(
        dirs["state"], dirs["epochs"], wcs=WCS.from_field(
            (cfg.ra0, cfg.dec0), CDELT, MAP_SHAPE),
        band=0, level2_dir=dirs["level2"], offset_length=50, n_iter=50,
        threshold=1e-5, medfilt_window=51, use_calibration=False,
        warm_start=True, tiles_root=dirs["tiles"], tile_px=16)
    store = EpochStore(dirs["epochs"])

    procs = {r: spawn(r) for r in range(_N_RANKS)}
    rc, out = {}, {}
    try:
        # -- rank 1 leaves: SIGKILL while it HOLDS a live lease --------
        def rank1_held():
            return [n for n, st in _scan_leases(dirs["state"]).items()
                    if st.get("state") == "claimed"
                    and int(st.get("owner", -1)) == 1]

        leaked = poll_until(rank1_held, 120.0,
                            "rank 1 claiming its first lease")
        procs[1].kill()
        rc[1], out[1] = wait(procs[1])
        t_kill = time.monotonic()
        assert rc[1] == -9, \
            f"scale drill: killed rank exited {rc[1]}, expected " \
            f"SIGKILL (-9):\n{out[1]}"
        # the dead rank's heartbeat freezes: /healthz must flip within
        # one lease TTL (plus poll slack)
        poll_until(lambda: probe() == 503, ttl_s + 3.0,
                   "/healthz flipping 503 after the SIGKILL")
        t_503 = time.monotonic() - t_kill

        # -- a fresh process rejoins as rank 1 mid-run ----------------
        # The rejoin's heartbeat shadows its dead predecessor's file,
        # but the lease layer keys claim liveness on the claimant's
        # PID, not the rank alone (lease.LeaseBoard.expired) — so the
        # leaked unit stays stealable by any rank, including the
        # rejoined one, and the rejoin can enter the live queue
        # immediately instead of waiting out the survivors' drain.
        rejoin = spawn(1)

        # -- publish pressure: fold an epoch while the queue drains ---
        def done_count():
            return sum(1 for st in _scan_leases(dirs["state"]).values()
                       if st.get("state") == "done")

        mid_target = max(3, n_files // 4)
        poll_until(lambda: done_count() >= mid_target, timeout_s,
                   f"{mid_target} commits for the mid-run epoch")
        n_mid = server.poll_once(force=True)
        mid_epoch = store.current()
        mid_census = len(store.census(mid_epoch)) if mid_epoch else 0
        mid_healthz = probe()

        # -- drain ----------------------------------------------------
        for r in (0, 2):
            rc[r], out[r] = wait(procs[r])
        rc["rejoin"], out["rejoin"] = wait(rejoin)
        for r in (0, 2, "rejoin"):
            assert rc[r] == 0, \
                f"scale drill: rank {r} failed ({rc[r]}):\n{out[r]}"
        # the rejoined rank 1's fresh heartbeat (clean .done) is what
        # returns the campaign to healthy
        poll_until(lambda: probe() == 200, 10.0,
                   "/healthz recovering after the rejoin drained")
    finally:
        for pr in list(procs.values()):
            if pr.poll() is None:
                pr.kill()

    # -- exactly-once at the lease layer -------------------------------
    leases = _scan_leases(dirs["state"])
    not_done = sorted(n for n, st in leases.items()
                      if st.get("state") != "done")
    assert sorted(leases) == names and not not_done, \
        f"scale drill: {len(not_done)}/{len(names)} units not done " \
        f"({not_done[:5]}...) — the queue did not drain exactly-once"
    l2 = sorted(_glob.glob(os.path.join(dirs["level2"], "Level2_*.hd5")))
    assert len(l2) == n_files, \
        f"scale drill: {len(l2)} Level-2 products for {n_files} units"

    results = {}
    for r in range(_N_RANKS):
        with open(os.path.join(dirs["state"], f"result.rank{r}.json"),
                  encoding="utf-8") as f:
            results[r] = json.load(f)
    committed_results = sum(r["stats"].get("committed", 0)
                            for r in results.values())
    # the killed process committed its pre-kill units but wrote no
    # result file; the gap is exactly its share
    dead_commits = n_files - committed_results
    assert dead_commits >= 0, \
        f"scale drill: survivor commit counters ({committed_results}) " \
        f"exceed the filelist ({n_files}) — a unit committed twice"
    stolen = sum(r["stats"].get("stolen", 0) for r in results.values())
    assert stolen >= 1, \
        f"scale drill: rank 1 died holding {leaked} but no survivor " \
        f"ledgered a steal (stats: { {r: v['stats'] for r, v in results.items()} })"
    for n in leaked:
        assert leases[n].get("state") == "done", \
            f"scale drill: leaked unit {n} never recovered"
    rejoin_committed = results[1]["stats"].get("committed", 0)
    if n_files >= 100:
        assert rejoin_committed >= 1, \
            "scale drill: the late-joining rank committed nothing — " \
            "it never actually joined the live queue"

    # -- epochs + tiles: fresh, exactly-once folding --------------------
    n_final = server.poll_once(force=True)
    epochs = store.list_epochs()
    final = store.current()
    assert final == store.latest() and store.census(final) == set(names), \
        f"scale drill: final epoch census {len(store.census(final))} " \
        f"!= campaign {n_files}"
    if n_files >= 48:
        assert len(epochs) >= 2 and mid_census < n_files, \
            f"scale drill: no mid-run epoch under load (epochs " \
            f"{epochs}, mid census {mid_census}/{n_files})"
    folded = []
    for n in epochs:
        folded += list(store.manifest(n).get("new_files", []))
    assert sorted(folded) == names, \
        f"scale drill: epochs folded {len(folded)} files, expected " \
        f"each of {n_files} exactly once"
    led = ServedLedger(os.path.join(dirs["epochs"], "served.jsonl"))
    assert sorted(led.files) == names and len(led) == len(names), \
        "scale drill: admission ledger is not exactly the census"
    ts = TileSet(dirs["tiles"])
    man = ts.manifest(final)
    assert ts.current() == final and man and man["n_tiles"] > 1, \
        f"scale drill: tile tier not current (tiles CURRENT=" \
        f"{ts.current()}, epoch {final})"

    # -- /metrics: the live counters match the scheduler exactly -------
    with urlopen(f"http://{srv.host}:{srv.port}/metrics",
                 timeout=10) as r:
        prom = r.read().decode("utf-8")
    srv.stop()
    per_rank = {}
    for ln in prom.splitlines():
        if ln.startswith("comap_scheduler_committed_total{"):
            label, val = ln.rsplit(" ", 1)
            rk = label.split('rank="')[1].split('"')[0]
            per_rank[int(rk)] = per_rank.get(int(rk), 0.0) + float(val)
    # ranks 0 and 2 drained their telemetry stream cleanly: their live
    # counter must equal their scheduler accounting EXACTLY. rank 1's
    # lane mixes the killed process (buffer lost at SIGKILL) with the
    # rejoined one, so it is bounded, not equal.
    for r in (0, 2):
        want = float(results[r]["stats"].get("committed", 0))
        assert per_rank.get(r) == want, \
            f"scale drill: /metrics committed for rank {r} is " \
            f"{per_rank.get(r)}, scheduler says {want}"
    assert sum(per_rank.values()) <= n_files, \
        f"scale drill: /metrics total {sum(per_rank.values())} " \
        f"exceeds the filelist — a commit double-counted"
    assert "comap_live_healthy 1" in prom, \
        "scale drill: /metrics lacks comap_live_healthy 1 at the end"

    return {
        "n_files": n_files,
        "seed": seed,
        "returncodes": {str(k): v for k, v in rc.items()},
        "t_503_after_kill_s": round(t_503, 3),
        "leaked_units": leaked,
        "stolen": stolen,
        "dead_rank_commits": dead_commits,
        "rejoin_commits": rejoin_committed,
        "commits_by_rank": {r: v["stats"].get("committed", 0)
                            for r, v in results.items()},
        "mid_epoch_census": mid_census,
        "mid_epoch_published": n_mid,
        "mid_healthz": mid_healthz,
        "final_epoch": final,
        "final_published": n_final,
        "epochs": epochs,
        "n_tiles": man["n_tiles"],
        "metrics_committed": per_rank,
        "wall_s": round(time.perf_counter() - t0, 3),
    }


if __name__ == "__main__":
    import sys as _sys

    _argv = _sys.argv[1:]
    if "--worker" in _argv:
        _argv.remove("--worker")
        raise SystemExit(_worker_main(_argv))
    raise SystemExit("usage: python -m comapreduce_tpu.synthetic.loadgen "
                     "--worker ... (the drill entry is "
                     "tools/check_resilience.py --synthetic-only)")
