"""Transfer-function workload: injected sky through the REAL pipeline.

The scenario describes a campaign with a known Gaussian sky and known
1/f noise. This module generates the campaign *without* the sky,
injects the sky into the written Level-1 files through
``simulations.skymodel.inject_level1`` (the production injection path,
using the generator's truth gains), reduces the files with the
standard stage chain (``Runner``), destripes and maps each band
(``read_comap_data`` + ``solve_band`` — the same read/solve the drill
and the map server use), and compares the recovered map against the
injected truth per (band, radial-k bin):

    T_b(k) = sum_k Re[conj(F{truth}) F{recovered}] / sum_k |F{truth}|^2

Two closures come out of one run:

- the **map transfer function** per band — how much injected sky the
  reduce + destripe chain returns at each angular scale (the medfilt
  high-pass and the offset subtraction both eat large scales, and the
  artifact quantifies exactly how much);
- the **quality-ledger noise closure** — the ledgered (white_sigma,
  fknee, alpha) must agree with what the scenario's known
  ``(t_atm_sigma, t_atm_fknee, t_atm_alpha)`` predict for the
  band-averaged TOD. The atmospheric stream is common-mode across a
  band's channels, so band averaging leaves sigma_atm intact while the
  radiometer white level drops by sqrt(C); the knee the ledger's fit
  sees is the *effective* knee of white + atm:

      fknee_eff = t_atm_fknee * (sigma_atm^2 / white_sigma_fit^2)^(1/alpha)

Everything is deterministic in the scenario seed — ``check_transfer``
gates on physics ratios, never on wall time, so the gate is
machine-independent (tools/check_perf.py --transfer-gate).
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import json
import logging
import os

import numpy as np

from comapreduce_tpu.synthetic.generator import file_basename, file_params
from comapreduce_tpu.synthetic.scenario import ScenarioConfig

__all__ = ["TRANSFER_SCENARIO", "transfer_scenario", "run_transfer",
           "check_transfer", "transfer_curve"]

logger = logging.getLogger("comapreduce_tpu")

# The gate scenario: small enough for CI (two files, one band, ~3k
# samples each), hot enough that every closure has signal. C=64 keeps
# the gain-fluctuation solve conditioned (at C=16 the two gain
# templates are nearly degenerate over ~14 usable channels and the
# solve's white amplification swamps everything); the atm 1/f at
# 0.08 K dominates the band-averaged radiometer noise (~0.006 K), so
# the ledger's knee fit recovers (t_atm_sigma, fknee, alpha) directly.
# The 0.5 K / 0.12 deg sky is compact against the medfilt high-pass
# (a 3 s window crosses the source in ~0.5 s of az sweep).
TRANSFER_SCENARIO = dict(
    name="transfer",
    source="TauA",           # calibrator path: median removal, no gain
                             # solve — a bright injected source would be
                             # partially absorbed by the (deliberately
                             # ill-conditioned) field gain estimator,
                             # exactly why the reference routes bright
                             # sources through the calibrator chain
    n_files=2,
    n_feeds=2,
    n_bands=1,
    n_channels=64,
    n_scans=4,
    scan_samples=600,
    vane_samples=120,
    gap_samples=40,
    az_throw=0.25,           # keeps the RA sweep inside the 64' field
    t_atm_sigma=0.08,        # K; dominates band-avg white -> clean knee
    t_atm_fknee=2.0,
    t_atm_alpha=1.5,
    sky_amplitude_k=0.5,
    sky_fwhm_deg=0.12,       # ~7 px: truth power spans the low-k bins
    sky_index=0.0,
)

MAP_SHAPE = (64, 64)         # 64' x 64' at 1'/px, centred on (ra0, dec0)
CDELT = (1.0 / 60.0, 1.0 / 60.0)


def transfer_scenario(seed: int = 0, **overrides) -> ScenarioConfig:
    """The gate scenario at ``seed`` (overrides must be known knobs)."""
    knobs = dict(TRANSFER_SCENARIO)
    knobs.update(overrides)
    knobs["seed"] = int(seed)
    return ScenarioConfig.coerce(knobs)


def _reduce_config(out_dir: str) -> dict:
    """The standard reduce chain (examples/configs/configuration.toml)
    sized for the gate scenario's 600-sample scans; single-rank static
    shard (no lease files — the scale drill owns the elastic path)."""
    return {
        "Global": {
            "processes": ["CheckLevel1File", "AssignLevel1Data",
                          "MeasureSystemTemperature", "AtmosphereRemoval",
                          "Level1AveragingGainCorrection", "Spikes",
                          "Level2FitPowerSpectrum", "NoiseStatistics"],
            "output_dir": out_dir,
            "log_dir": os.path.join(out_dir, "logs"),
        },
        "CheckLevel1File": {"min_duration_seconds": 30.0},
        # medfilt_window clamps to the scan length (600): the high-pass
        # removes only the slowest per-scan structure, so the injected
        # sky and the atm 1/f both reach the fits and the destriper
        "Level1AveragingGainCorrection": {"feed_batch": 2},
        "Spikes": {"window": 101, "pad": 10},
        "Level2FitPowerSpectrum": {"nbins": 12},
        "NoiseStatistics": {"nbins": 12},
        "resilience": {"lease_ttl_s": 0},
    }


def transfer_curve(truth, recovered, n_bins: int = 6):
    """Radial-k transfer bins between two maps on the same grid.

    Pixels the pipeline never hit (NaN in ``recovered``) are excluded
    from BOTH maps (mean removed over the common hit set, unhit set to
    zero) so coverage gaps bias truth and recovery identically. Returns
    ``(k_centres, transfer, n_modes)`` with k in cycles/pixel.
    """
    truth = np.asarray(truth, np.float64)
    recovered = np.asarray(recovered, np.float64)
    if truth.shape != recovered.shape or truth.ndim != 2:
        raise ValueError(f"map shape mismatch: {truth.shape} vs "
                         f"{recovered.shape}")
    hit = np.isfinite(recovered)
    if not hit.any():
        raise ValueError("recovered map has no hit pixels")
    t = np.where(hit, truth - truth[hit].mean(), 0.0)
    r = np.where(hit, recovered - recovered[hit].mean(), 0.0)
    tf = np.fft.fft2(t)
    rf = np.fft.fft2(r)
    ky = np.fft.fftfreq(truth.shape[0])[:, None]
    kx = np.fft.fftfreq(truth.shape[1])[None, :]
    k = np.hypot(ky, kx)
    cross = (np.conj(tf) * rf).real
    auto = (tf.real ** 2 + tf.imag ** 2)
    edges = np.linspace(0.0, 0.5, n_bins + 1)
    centres, transfer, n_modes = [], [], []
    for lo, hi in zip(edges[:-1], edges[1:]):
        sel = (k >= lo) & (k < hi) & (k > 0)
        centres.append(0.5 * (lo + hi))
        n_modes.append(int(sel.sum()))
        denom = float(auto[sel].sum()) if sel.any() else 0.0
        transfer.append(float(cross[sel].sum() / denom)
                        if denom > 0 else float("nan"))
    return np.asarray(centres), np.asarray(transfer), np.asarray(n_modes)


def _truth_map(model, wcs, freq_ghz: float) -> np.ndarray:
    """The injected sky evaluated on the map grid at one frequency."""
    lon, lat = wcs.pixel_centers()
    vals = np.asarray(model(lon, lat, np.asarray([float(freq_ghz)])))
    return vals[..., 0] if vals.ndim == 3 else vals


def _quality_closure(state_dir: str, cfg: ScenarioConfig,
                     file_base: str | None = None) -> dict:
    """Ledgered noise fits vs the scenario's known injection.

    ``file_base`` restricts the closure to one Level-1 file — the
    blind noise-reference file, whose fits see only the scenario's
    known noise (the injected source adds sweep-synchronous power
    that would bias the knee on the injected files).
    """
    from comapreduce_tpu.telemetry.quality import read_quality

    records = read_quality(state_dir)
    if file_base is not None:
        records = [r for r in records
                   if os.path.basename(str(r.get("file", ""))) == file_base]
    alphas = [r["alpha"] for r in records
              if r.get("alpha") is not None]
    fknees = [r["fknee_hz"] for r in records
              if r.get("fknee_hz") is not None]
    whites = [r["white_sigma"] for r in records
              if r.get("white_sigma") is not None]
    out = {"n_records": len(records),
           "n_fitted": len(alphas),
           "alpha_expected": -cfg.t_atm_alpha,
           "alpha_median": (float(np.median(alphas)) if alphas else None),
           "white_sigma_median": (float(np.median(whites))
                                  if whites else None),
           "fknee_median": (float(np.median(fknees)) if fknees else None),
           "fknee_expected": None}
    if whites and cfg.t_atm_sigma > 0 and cfg.t_atm_alpha > 0:
        # knee fit of white + atm: sig2_fit = sig_w^2 + sig_atm^2, and
        # the effective knee satisfies
        # sig_atm^2 (fk/f)^a = sig2_fit (fk_eff/f)^a
        w2 = float(np.median(whites)) ** 2
        ratio = min(cfg.t_atm_sigma ** 2 / w2, 1.0)
        out["fknee_expected"] = float(
            cfg.t_atm_fknee * ratio ** (1.0 / cfg.t_atm_alpha))
    return out


def run_transfer(workdir: str, seed: int = 0, n_bins: int = 6,
                 overrides: dict | None = None) -> dict:
    """Generate -> inject -> reduce -> destripe -> compare; returns the
    transfer artifact (also written to ``<workdir>/transfer.json``).

    The campaign is generated with ``sky_amplitude_k = 0`` and the sky
    is injected afterwards through ``skymodel.inject_level1`` with the
    generator's truth gains — the production injection path, so the
    artifact measures the pipeline, not a generator shortcut.
    """
    from comapreduce_tpu.cli.run_destriper import solve_band
    from comapreduce_tpu.data.synthetic import generate_level1_file
    from comapreduce_tpu.mapmaking.leveldata import read_comap_data
    from comapreduce_tpu.mapmaking.wcs import WCS
    from comapreduce_tpu.pipeline.runner import Runner
    from comapreduce_tpu.simulations.skymodel import inject_level1

    cfg = transfer_scenario(seed, **(overrides or {}))
    model = cfg.sky_model()
    if model is None:
        raise ValueError("transfer scenario needs sky_amplitude_k > 0")
    blind = dataclasses.replace(cfg, sky_amplitude_k=0.0)

    level1_dir = os.path.join(workdir, "level1")
    out_dir = os.path.join(workdir, "level2")
    os.makedirs(level1_dir, exist_ok=True)

    # -- generate (no sky) then inject (production path, truth gains) --
    files = []
    for i in range(cfg.n_files):
        path = os.path.join(level1_dir, file_basename(cfg, i))
        p = generate_level1_file(path, file_params(blind, i))
        inject_level1(path, model, gain_estimate=p.truth["gain"])
        files.append(path)
    # one extra BLIND file: the noise reference for the ledger closure
    # (on the injected files the source's sweep-synchronous power
    # inflates the fitted knee — a physics effect, not a pipeline bug)
    ref_cfg = dataclasses.replace(blind, n_files=cfg.n_files + 1)
    ref_base = file_basename(ref_cfg, cfg.n_files)
    ref_path = os.path.join(level1_dir, ref_base)
    generate_level1_file(ref_path, file_params(ref_cfg, cfg.n_files))

    # -- reduce with the standard chain ---------------------------------
    runner = Runner.from_config(_reduce_config(out_dir))
    runner.run_tod(files + [ref_path])

    all_l2 = sorted(_glob.glob(
        os.path.join(out_dir, f"{runner.prefix}_*.hd5")))
    if len(all_l2) != len(files) + 1:
        raise RuntimeError(f"reduce produced {len(all_l2)} Level-2 "
                           f"files for {len(files) + 1} inputs")
    # the map uses only the injected files (matched by obsid)
    obsids = [f"{cfg.obsid_start + i:07d}" for i in range(cfg.n_files)]
    l2files = [p for p in all_l2
               if any(o in os.path.basename(p) for o in obsids)]
    if len(l2files) != len(files):
        raise RuntimeError(f"could not match Level-2 outputs to the "
                           f"{len(files)} injected files: {all_l2}")

    # -- destripe + map each band, compare to the injected truth --------
    wcs = WCS.from_field((cfg.ra0, cfg.dec0), CDELT, MAP_SHAPE)
    from comapreduce_tpu.data.synthetic import _band_frequencies

    nu_c = _band_frequencies(cfg.n_bands, cfg.n_channels).mean(axis=1)
    bands = []
    for band in range(cfg.n_bands):
        data = read_comap_data(l2files, band=band, wcs=wcs,
                               offset_length=50, medfilt_window=401,
                               use_calibration=False)
        result = solve_band(data, offset_length=50, n_iter=100,
                            threshold=1e-6)
        recovered = np.asarray(result.destriped_map,
                               np.float64).reshape(MAP_SHAPE)
        hits = np.asarray(result.hit_map, np.float64).reshape(MAP_SHAPE)
        recovered = np.where(hits > 0, recovered, np.nan)
        truth = _truth_map(model, wcs, nu_c[band])
        k, tr, n_modes = transfer_curve(truth, recovered, n_bins=n_bins)
        hit = np.isfinite(recovered)
        # map gain: least-squares coefficient of truth in the recovered
        # map over the hit pixels (both mean-subtracted). A single
        # scale-free scalar — the map-domain analogue of the k=0+
        # transfer bin, robust to the source filling the field
        t_c = truth[hit] - truth[hit].mean()
        r_c = recovered[hit] - recovered[hit].mean()
        denom = float((t_c * t_c).sum())
        map_gain = (float((t_c * r_c).sum() / denom)
                    if denom > 0 else None)
        bands.append({
            "band": band,
            "freq_ghz": float(nu_c[band]),
            "k_bins": [float(v) for v in k],
            "transfer": [float(v) for v in tr],
            "n_modes": [int(v) for v in n_modes],
            "hit_fraction": float(hit.mean()),
            "map_gain": map_gain,
        })

    artifact = {
        "schema": 1,
        "scenario": cfg.name,
        "seed": int(seed),
        "n_files": cfg.n_files,
        "sky": {"amplitude_k": cfg.sky_amplitude_k,
                "fwhm_deg": cfg.sky_fwhm_deg, "index": cfg.sky_index},
        "bands": bands,
        "quality": _quality_closure(runner.state_dir, cfg, ref_base),
    }
    path = os.path.join(workdir, "transfer.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
    artifact["artifact_path"] = path
    return artifact


def check_transfer(artifact: dict) -> None:
    """Machine-independent closure gate over one transfer artifact.

    Raises ``AssertionError`` with a named criterion. Thresholds are
    physics ratios calibrated on seeds 0-4 of the gate scenario (see
    docs/OPERATIONS.md §18) with ~2x headroom over the observed
    scatter — loose enough to survive BLAS/FFT differences across
    hosts, tight enough that a broken stage (lost gain correction,
    destriper regression, ledger drift) fails immediately.
    """
    bands = artifact.get("bands") or []
    assert bands, "transfer: no bands in artifact"
    for b in bands:
        tr = np.asarray(b["transfer"], np.float64)
        assert np.isfinite(tr).all(), \
            f"transfer: non-finite transfer bins (band {b['band']}): {tr}"
        # the sky is beam-scale (FWHM ~7 px): the truth's power lives in
        # the first two k bins; higher bins divide noise by ~zero truth
        # power, so only the signal-carrying bins are gated.
        low = tr[:2]
        assert low.min() > 0.30, \
            f"transfer: low-k transfer collapsed (band {b['band']}): {tr}"
        assert low.max() < 1.30, \
            f"transfer: low-k transfer > 1.3 — injected power " \
            f"amplified (band {b['band']}): {tr}"
        assert b["hit_fraction"] > 0.10, \
            f"transfer: map coverage {b['hit_fraction']:.3f} too small"
        g = b["map_gain"]
        assert g is not None and 0.45 < g < 1.30, \
            f"transfer: map gain {g} outside [0.45, 1.30]"
    q = artifact.get("quality") or {}
    assert q.get("n_fitted", 0) > 0, \
        "transfer: quality ledger has no noise fits"
    a_med, a_exp = q.get("alpha_median"), q.get("alpha_expected")
    assert a_med is not None and abs(a_med - a_exp) < 0.7, \
        f"transfer: ledger alpha {a_med} != expected {a_exp} +- 0.7"
    fk_med, fk_exp = q.get("fknee_median"), q.get("fknee_expected")
    assert fk_med is not None and fk_exp is not None \
        and 0.4 < fk_med / fk_exp < 2.5, \
        f"transfer: ledger fknee {fk_med} vs expected {fk_exp} " \
        f"outside [0.4, 2.5]x"
