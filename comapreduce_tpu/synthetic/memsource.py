"""``synth://`` virtual paths: serve scenario files straight from memory.

A 1000-file campaign should not need 1000 files of disk. Scenario
members get virtual paths::

    synth://<scenario-name>/<index>/<basename>

A process-global registry maps scenario names to their configs;
``ingest.loaders.load_level1`` consults :func:`is_virtual` /
:func:`load_virtual` before touching the filesystem, so the whole
pipeline — prefetcher, cache, retry net, Runner, scheduler — sees
virtual members through the exact code path a disk file takes. Content
is a pure function of the path (the determinism contract), which is
what makes the cache key ``(path, 0)`` sound and lets every worker
process regenerate identical bytes after re-registering the scenario
(``register_scenario_file`` — subprocess workers pass the scenario TOML
on their command line).
"""

from __future__ import annotations

import threading

from comapreduce_tpu.synthetic.generator import SCHEME, file_basename
from comapreduce_tpu.synthetic.scenario import ScenarioConfig

__all__ = ["is_virtual", "parse_virtual", "register_scenario",
           "register_scenario_file", "registered", "clear_registry",
           "load_virtual", "probe_virtual", "virtual_store"]

_LOCK = threading.Lock()
_REGISTRY: dict = {}


def is_virtual(path: str) -> bool:
    """True for ``synth://`` scenario-member paths."""
    return isinstance(path, str) and path.startswith(SCHEME)


def register_scenario(cfg: ScenarioConfig) -> ScenarioConfig:
    """Make ``cfg``'s members resolvable in this process; returns it.

    Re-registering the same name with an identical config is a no-op;
    a *different* config under the same name raises — two scenarios
    sharing a name would make path -> bytes ambiguous.
    """
    cfg = ScenarioConfig.coerce(cfg)
    with _LOCK:
        held = _REGISTRY.get(cfg.name)
        if held is not None and held != cfg:
            raise ValueError(
                f"scenario {cfg.name!r} already registered with a "
                "different config")
        _REGISTRY[cfg.name] = cfg
    return cfg


def register_scenario_file(path: str, vane_pad=None) -> ScenarioConfig:
    """Load + register a scenario TOML (subprocess worker entry).
    ``vane_pad`` threads the consumer's vane-window pad into the
    load-time pad-vs-gap fault trap (``load_scenario``)."""
    from comapreduce_tpu.synthetic.scenario import load_scenario

    return register_scenario(load_scenario(path, vane_pad=vane_pad))


def registered(name: str) -> ScenarioConfig | None:
    with _LOCK:
        return _REGISTRY.get(name)


def clear_registry() -> None:
    """Drop all registrations (test isolation)."""
    with _LOCK:
        _REGISTRY.clear()


def parse_virtual(path: str) -> tuple:
    """``synth://name/00042/basename.hd5 -> (config, 42)``.

    Raises ``FileNotFoundError`` (the error class a missing disk file
    would produce, so the per-file fault net triages it identically)
    when the scenario is unregistered or the member is out of range or
    misnamed.
    """
    if not is_virtual(path):
        raise ValueError(f"not a synth:// path: {path}")
    parts = path[len(SCHEME):].split("/")
    if len(parts) != 3:
        raise FileNotFoundError(
            f"malformed virtual path (want synth://name/index/file): "
            f"{path}")
    name, idx_s, base = parts
    cfg = registered(name)
    if cfg is None:
        raise FileNotFoundError(
            f"scenario {name!r} not registered in this process "
            f"(synthetic.memsource.register_scenario): {path}")
    try:
        index = int(idx_s)
    except ValueError:
        raise FileNotFoundError(f"bad member index in {path}") from None
    if not 0 <= index < cfg.n_files or base != file_basename(cfg, index):
        raise FileNotFoundError(f"no such scenario member: {path}")
    return cfg, index


def virtual_store(path: str):
    """Generate the member's Level-1 content: ``(params, HDF5Store)``."""
    from comapreduce_tpu.synthetic.generator import file_params
    from comapreduce_tpu.data.synthetic import generate_level1_store

    cfg, index = parse_virtual(path)
    return generate_level1_store(file_params(cfg, index))


def load_virtual(path: str):
    """The member as a :class:`COMAPLevel1` (fully materialised — there
    is no file handle to keep lazy)."""
    from comapreduce_tpu.data.level import COMAPLevel1

    _, store = virtual_store(path)
    payload = store.export_payload()
    payload["source"] = path
    data = COMAPLevel1()
    data.adopt_payload(payload)
    return data


def probe_virtual(path: str, pad_to: int = 128) -> dict:
    """Shape metadata for campaign warm-up (``probe_observation``
    parity) WITHOUT generating the TOD: pure arithmetic on the scenario.

    ``L`` is the scan length padded as ``ops.reduce.scan_starts_lengths``
    pads it; the feature-derived edges the pipeline later recovers may
    trim a sample or two, but ``ShapeBuckets.canonical`` collapses that
    to the same bucket (a mismatch costs one extra compile, never an
    error)."""
    from comapreduce_tpu.data.level import CALIBRATOR_NAMES
    from comapreduce_tpu.synthetic.generator import file_params

    cfg, index = parse_virtual(path)
    p = file_params(cfg, index)
    L = p.scan_samples if p.n_scans and p.scan_samples else pad_to
    L = -(-L // pad_to) * pad_to
    return {
        "F": p.n_feeds, "B": p.n_bands, "C": p.n_channels,
        "T": p.n_samples, "S": p.n_scans, "L": int(L),
        "calibrator": p.source in CALIBRATOR_NAMES,
    }
