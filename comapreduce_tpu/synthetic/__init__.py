"""Synthetic campaign engine: scenario-driven Level-1 generation, known
signal/noise injection, transfer-function measurement, and a scale-drill
load generator.

The validation workloads the reference stack proves itself on (COMAP
Early Science III transfer functions, arXiv 2111.05929; MAPPRAISER-style
synthetic campaigns, arXiv 2112.03370) live here:

``scenario``
    Declarative ``[scenario]`` config (TOML) describing an N-file
    campaign — shape jitter, scan geometry, weather drift, per-feed 1/f
    noise with *known* (sigma, fknee, alpha), fault mix, injected sky —
    fail-at-load on unknown sections/keys, deterministic by seed.
``generator``
    Turns a scenario into per-file ``SyntheticObsParams``, written to
    disk or served in memory (same bytes either way).
``memsource``
    ``synth://`` virtual paths: a process-global scenario registry that
    the ingest loaders consult, so 1000-file campaigns need no disk.
``transfer``
    Inject a known sky, run reduce -> destripe -> map, measure the
    pipeline transfer function per (band, pixel-scale bin), and check
    the quality ledger recovers the injected noise parameters.
``loadgen``
    The >=200-file scale drill: elastic scheduler + map server + tile
    tier under publish pressure with mid-run rank kill/join
    (``tools/check_resilience.py --synthetic-only``).

See docs/OPERATIONS.md §18 for the runbook.
"""

from comapreduce_tpu.synthetic.scenario import ScenarioConfig, load_scenario
from comapreduce_tpu.synthetic.generator import (campaign_params,
                                                 campaign_truth,
                                                 file_params,
                                                 virtual_filelist,
                                                 write_campaign)

__all__ = ["ScenarioConfig", "load_scenario", "file_params",
           "campaign_params", "campaign_truth", "virtual_filelist",
           "write_campaign"]
