"""Scenario configuration: the declarative ``[scenario]`` campaign table.

A scenario file is a TOML document with exactly one table::

    [scenario]
    name = "smoke"
    n_files = 8
    seed = 0
    t_atm_sigma = 0.02      # additive 1/f, known (sigma, fknee, alpha)
    sky_amplitude_k = 0.5   # injected Gaussian sky, known truth
    ...

Loading is strict both ways: unknown *sections* (a stray ``[Destriper]``
pasted from a pipeline config) and unknown *keys* inside ``[scenario]``
raise ``ValueError`` at load, never at file 738 of a campaign. The knob
names live once, in :attr:`ScenarioConfig.KNOBS`, following the
``IngestConfig`` idiom so the coercion rules cannot drift between entry
points (CLI, bench, drill, tests).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

__all__ = ["ScenarioConfig", "load_scenario"]


@dataclass(frozen=True)
class ScenarioConfig:
    """Knobs for one synthetic campaign.

    Geometry/shape knobs mirror ``SyntheticObsParams`` (they are per-file
    seeds for it); campaign-level knobs add file count, shape jitter,
    weather drift across the campaign, and the injected-sky description.

    Determinism contract: every byte of every generated file is a pure
    function of ``(the scenario, file index)``. Same scenario => byte
    identical Level-1 files, whether streamed to disk or served from
    memory, on any host (docs/OPERATIONS.md §18).
    """

    name: str = "scenario"
    n_files: int = 8
    seed: int = 0
    obsid_start: int = 9_000_001
    source: str = "co2"
    # per-file observation shape (SyntheticObsParams units)
    n_feeds: int = 2
    n_bands: int = 1
    n_channels: int = 16
    n_scans: int = 3
    scan_samples: int = 400
    vane_samples: int = 120
    gap_samples: int = 40
    # +- peak scan_samples jitter across files (deterministic triangle
    # wave in the file index — exercises shape-bucket reuse, see §9)
    shape_jitter: int = 0
    mjd_start: float = 59620.0
    mjd_step: float = 0.02          # days between file starts
    # scan geometry
    elevation: float = 55.0
    el_sweep: float = 0.0
    az_throw: float = 4.0
    ra0: float = 170.25
    dec0: float = 52.25
    # weather: zenith atmosphere ramps linearly across the campaign by
    # +- weather_drift/2 around t_atm_zenith
    t_atm_zenith: float = 10.0
    weather_drift: float = 0.0
    # per-feed 1/f gain fluctuations with known parameters
    sigma_g: float = 5.0e-4
    fknee: float = 1.0
    alpha: float = 1.5
    # additive per-feed atmospheric 1/f (the injection the quality
    # ledger's noise fits must recover — survives gain correction)
    t_atm_sigma: float = 0.0
    t_atm_fknee: float = 0.1
    t_atm_alpha: float = 1.5
    # fault mix (fraction of scan cells)
    spike_rate: float = 0.0
    nan_rate: float = 0.0
    # injected sky: a GaussianComponent SkyModel at (ra0, dec0) with an
    # optional power-law spectral index across bands
    sky_amplitude_k: float = 0.0
    sky_fwhm_deg: float = 0.45
    sky_index: float = 0.0

    def __post_init__(self):
        object.__setattr__(self, "name", str(self.name or "scenario"))
        object.__setattr__(self, "source", str(self.source or "co2"))
        for key in ("n_files", "seed", "obsid_start", "n_feeds", "n_bands",
                    "n_channels", "n_scans", "scan_samples", "vane_samples",
                    "gap_samples", "shape_jitter"):
            object.__setattr__(self, key, int(getattr(self, key) or 0))
        for key in ("mjd_start", "mjd_step", "elevation", "el_sweep",
                    "az_throw", "ra0", "dec0", "t_atm_zenith",
                    "weather_drift", "sigma_g", "fknee", "alpha",
                    "t_atm_sigma", "t_atm_fknee", "t_atm_alpha",
                    "spike_rate", "nan_rate", "sky_amplitude_k",
                    "sky_fwhm_deg", "sky_index"):
            object.__setattr__(self, key, float(getattr(self, key) or 0.0))
        if self.n_files < 1:
            raise ValueError(f"scenario needs n_files >= 1, got "
                             f"{self.n_files}")
        if self.n_feeds < 1 or self.n_bands < 1 or self.n_channels < 1:
            raise ValueError("scenario needs n_feeds/n_bands/n_channels "
                             ">= 1")
        if self.scan_samples < 0 or self.n_scans < 0:
            raise ValueError("scenario scan_samples/n_scans must be >= 0")

    KNOBS = ("name", "n_files", "seed", "obsid_start", "source",
             "n_feeds", "n_bands", "n_channels", "n_scans", "scan_samples",
             "vane_samples", "gap_samples", "shape_jitter",
             "mjd_start", "mjd_step",
             "elevation", "el_sweep", "az_throw", "ra0", "dec0",
             "t_atm_zenith", "weather_drift",
             "sigma_g", "fknee", "alpha",
             "t_atm_sigma", "t_atm_fknee", "t_atm_alpha",
             "spike_rate", "nan_rate",
             "sky_amplitude_k", "sky_fwhm_deg", "sky_index")

    @classmethod
    def from_mapping(cls, mapping) -> "ScenarioConfig":
        """Pick the scenario knobs out of a wider mapping, ignoring
        unrelated keys (for embedding in a pipeline TOML)."""
        return cls(**{k: mapping[k] for k in cls.KNOBS if k in mapping})

    @classmethod
    def coerce(cls, value) -> "ScenarioConfig":
        """Build from None / dict / ScenarioConfig; unknown keys raise."""
        if value is None:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            known = {k: value[k] for k in cls.KNOBS if k in value}
            unknown = set(value) - set(known)
            if unknown:
                raise ValueError(
                    f"unknown scenario keys: {sorted(unknown)}")
            return cls(**known)
        raise TypeError(f"cannot build ScenarioConfig from {type(value)}")

    def validate_vane_pad(self, pad) -> "ScenarioConfig":
        """Fail FAST when a vane-measurement window pad would swallow
        faulted scan samples (ISSUE 19 bugfix).

        ``MeasureSystemTemperature`` widens each vane window by ``pad``
        samples to catch post-retraction sky; when ``pad >=
        gap_samples`` the widened window reaches past the gap into the
        scan cells. On a fault-injecting scenario (``spike_rate`` /
        ``nan_rate`` > 0) one NaN inside the window breaks the range
        normalisation and zeroes the whole event's Tsys — every
        Level-2 weight silently becomes zero, file after file. Raise
        at scenario load instead, naming both knobs.

        Fault-free scenarios pass: with no spikes/NaNs in the scan
        cells the widened window only averages clean sky (the transfer
        scenario runs gap=40 under the stage default pad=50 by
        design). Returns ``self`` so call sites can chain."""
        pad = int(pad)
        if (self.vane_samples > 0 and self.gap_samples <= pad
                and (self.spike_rate > 0 or self.nan_rate > 0)):
            raise ValueError(
                f"scenario {self.name!r}: vane window pad {pad} >= "
                f"gap_samples {self.gap_samples} on a fault-injecting "
                f"scenario (spike_rate={self.spike_rate}, "
                f"nan_rate={self.nan_rate}) — the widened vane windows "
                "would swallow faulted scan samples and zero every "
                "Level-2 weight; raise gap_samples or lower the "
                "MeasureSystemTemperature pad")
        return self

    def sky_model(self):
        """The injected-sky ``SkyModel`` (None when no sky is injected)."""
        if self.sky_amplitude_k <= 0:
            return None
        from comapreduce_tpu.simulations import (GaussianComponent,
                                                 SkyModel, power_law)

        law = None
        if self.sky_index:
            index = self.sky_index

            def law(freq_ghz, _index=index):
                return power_law(freq_ghz, freq0_ghz=30.0, index=_index)

        comp = (GaussianComponent(self.ra0, self.dec0, self.sky_amplitude_k,
                                  self.sky_fwhm_deg, freq_law=law)
                if law is not None else
                GaussianComponent(self.ra0, self.dec0, self.sky_amplitude_k,
                                  self.sky_fwhm_deg))
        return SkyModel([comp])


def load_scenario(path: str, vane_pad=None) -> ScenarioConfig:
    """Parse a scenario TOML file, strictly.

    The document must contain a ``[scenario]`` table; any *other*
    top-level section (``[Destriper]``, ``[Global]``, ...) and any
    unknown key inside ``[scenario]`` is a ``ValueError`` — a typo'd
    campaign config fails at load, not 20 minutes into generation.

    ``vane_pad`` is the consuming stage chain's
    ``MeasureSystemTemperature`` window pad, when the caller knows it:
    the pad-vs-gap fault trap (:meth:`ScenarioConfig.validate_vane_pad`)
    then fires HERE, at load, instead of zeroing every Level-2 weight
    mid-campaign.
    """
    from comapreduce_tpu.pipeline.config import load_toml

    if not os.path.exists(path):
        raise FileNotFoundError(f"scenario file not found: {path}")
    doc = load_toml(path)
    if "scenario" not in doc:
        raise ValueError(f"{path}: missing required [scenario] section")
    extra_sections = sorted(set(doc) - {"scenario"})
    if extra_sections:
        raise ValueError(
            f"{path}: unknown sections {extra_sections} — a scenario "
            f"file holds exactly one [scenario] table")
    try:
        cfg = ScenarioConfig.coerce(dict(doc["scenario"]))
        if vane_pad is not None:
            cfg.validate_vane_pad(vane_pad)
        return cfg
    except (TypeError, ValueError) as exc:
        raise ValueError(f"{path}: {exc}") from None
