"""Radio recombination line (RRL) analysis.

Capability parity with the reference ``RRLs/`` package (legacy, broken
at upstream HEAD — ``RRLFuncs.py:14`` imports the removed BaseClasses):
hydrogen-alpha line frequencies in the COMAP band, velocity-grid spectral
stacking across lines (a ``segment_sum`` on device), Gaussian line fits,
and the line-to-continuum electron-temperature relation
(``RRLs/RRLequations.py:3-50``).
"""

from __future__ import annotations

import numpy as np

__all__ = ["hydrogen_alpha_frequency", "lines_in_band", "channel_velocity",
           "stack_spectra", "electron_temperature", "fit_line"]

_RYDBERG_HZ = 3.2898419603e15  # R_H * c for hydrogen
C_KMS = 299792.458


def hydrogen_alpha_frequency(n: int) -> float:
    """Rest frequency [GHz] of the H(n)alpha transition n+1 -> n."""
    nu = _RYDBERG_HZ * (1.0 / n**2 - 1.0 / (n + 1) ** 2)
    return nu / 1e9


def lines_in_band(fmin_ghz: float = 26.0, fmax_ghz: float = 34.0):
    """{n: freq_ghz} of the Hnalpha lines inside [fmin, fmax] (the COMAP
    band holds H58a-H62a)."""
    out = {}
    for n in range(40, 120):
        f = hydrogen_alpha_frequency(n)
        if fmin_ghz <= f <= fmax_ghz:
            out[n] = f
    return out


def channel_velocity(freq_ghz, line_freq_ghz: float):
    """Radio-convention velocity [km/s] of each channel relative to a
    line: ``v = c (nu0 - nu) / nu0``."""
    nu = np.asarray(freq_ghz, np.float64)
    return C_KMS * (line_freq_ghz - nu) / line_freq_ghz


def stack_spectra(spectra, freq_ghz, line_freqs, v_grid,
                  weights=None):
    """Stack spectra from several lines onto one velocity grid.

    ``spectra``/``freq_ghz``: f32[..., C] per-channel brightness and
    frequency; ``line_freqs``: list of rest frequencies [GHz]; ``v_grid``:
    bin edges [km/s] (length nbins+1). Returns ``(stacked[..., nbins],
    hits[..., nbins])`` — a ``segment_sum`` over velocity-bin ids, the
    device analogue of the reference's per-line loop (``RRLFuncs.py``
    ``read_data``/stacking)."""
    import jax
    import jax.numpy as jnp

    spectra = jnp.asarray(spectra)
    w = jnp.ones_like(spectra) if weights is None else jnp.asarray(weights)
    nbins = len(v_grid) - 1
    v_grid = np.asarray(v_grid, np.float64)
    total = None
    hits = None
    for f0 in line_freqs:
        v = channel_velocity(np.asarray(freq_ghz, np.float64), float(f0))
        ids = np.searchsorted(v_grid, v, side="right") - 1
        valid = (ids >= 0) & (ids < nbins)
        ids = np.where(valid, ids, nbins)
        ids_j = jnp.asarray(ids.reshape(-1), jnp.int32)
        flat_s = (spectra * w).reshape(-1, spectra.shape[-1])
        flat_w = (w * jnp.asarray(valid, w.dtype)).reshape(
            -1, spectra.shape[-1])

        def bin_rows(rows):
            return jax.vmap(lambda r: jax.ops.segment_sum(
                r, ids_j, num_segments=nbins + 1)[:nbins])(rows)

        s = bin_rows(flat_s * jnp.asarray(valid, flat_s.dtype))
        h = bin_rows(flat_w)
        total = s if total is None else total + s
        hits = h if hits is None else hits + h
    shape = spectra.shape[:-1] + (nbins,)
    stacked = jnp.where(hits > 0, total / jnp.maximum(hits, 1e-30), 0.0)
    return stacked.reshape(shape), hits.reshape(shape)


def electron_temperature(line_peak_k, continuum_k, delta_v_kms,
                         freq_ghz, helium_fraction: float = 0.08):
    """LTE electron temperature [K] from the line-to-continuum ratio
    (``RRLequations.py:3-50``):

    ``T_e = (7103.3 nu_GHz^1.1 / ((T_L/T_C) dv (1 + y+)))^0.87``
    """
    ratio = np.asarray(line_peak_k, np.float64) \
        / np.maximum(np.asarray(continuum_k, np.float64), 1e-30)
    x = (7103.3 * np.asarray(freq_ghz, np.float64) ** 1.1
         / np.maximum(ratio * np.asarray(delta_v_kms, np.float64)
                      * (1.0 + helium_fraction), 1e-30))
    return x ** 0.87


def fit_line(v_kms, spectrum, weights=None):
    """Gaussian line fit on a stacked velocity spectrum: returns
    ``(amplitude, v0, fwhm_kms, offset)`` via the shared LM solver."""
    import jax.numpy as jnp

    from comapreduce_tpu.calibration import fitting

    v = jnp.asarray(v_kms, jnp.float32)
    s = jnp.asarray(spectrum, jnp.float32)
    w = jnp.ones_like(s) if weights is None else jnp.asarray(weights,
                                                             jnp.float32)

    def model(p, x, y):
        amp, v0, sig, off = p
        return amp * jnp.exp(-0.5 * ((x - v0) / sig) ** 2) + off

    i = int(jnp.argmax(s))
    p0 = jnp.asarray([float(s[i]) - float(jnp.median(s)), float(v[i]),
                      20.0, float(jnp.median(s))], jnp.float32)
    p, err, chi2 = fitting.fit_gauss2d(
        s, v, jnp.zeros_like(v), w, p0, model=model)
    amp, v0, sig, off = (float(x) for x in p)
    return amp, v0, abs(sig) * 2.355, off
