"""Radio recombination line (RRL) analysis.

Capability parity with the reference ``RRLs/`` package (legacy, broken
at upstream HEAD — ``RRLFuncs.py:14`` imports the removed BaseClasses):
hydrogen-alpha line frequencies in the COMAP band, velocity-grid spectral
stacking across lines (a ``segment_sum`` on device), Gaussian line fits,
and the line-to-continuum electron-temperature relation
(``RRLs/RRLequations.py:3-50``).
"""

from __future__ import annotations

import numpy as np

__all__ = ["hydrogen_alpha_frequency", "lines_in_band", "channel_velocity",
           "stack_spectra", "electron_temperature", "fit_line"]

# Hydrogen's reduced-mass Rydberg frequency R_H*c = R_inf*c / (1 + m_e/m_p).
# Using the infinite-nuclear-mass R_inf*c (3.28984e15) would put every
# Hn-alpha line ~18 MHz (~170 km/s) high — e.g. H58a at 32.870 instead of
# the published 32.852 GHz.
_RYDBERG_HZ = 3.2880513e15
C_KMS = 299792.458


def hydrogen_alpha_frequency(n: int) -> float:
    """Rest frequency [GHz] of the H(n)alpha transition n+1 -> n."""
    nu = _RYDBERG_HZ * (1.0 / n**2 - 1.0 / (n + 1) ** 2)
    return nu / 1e9


def lines_in_band(fmin_ghz: float = 26.0, fmax_ghz: float = 34.0):
    """{n: freq_ghz} of the Hnalpha lines inside [fmin, fmax] (the COMAP
    band holds H58a-H62a)."""
    out = {}
    for n in range(40, 120):
        f = hydrogen_alpha_frequency(n)
        if fmin_ghz <= f <= fmax_ghz:
            out[n] = f
    return out


def channel_velocity(freq_ghz, line_freq_ghz: float):
    """Radio-convention velocity [km/s] of each channel relative to a
    line: ``v = c (nu0 - nu) / nu0``."""
    nu = np.asarray(freq_ghz, np.float64)
    return C_KMS * (line_freq_ghz - nu) / line_freq_ghz


def stack_spectra(spectra, freq_ghz, line_freqs, v_grid,
                  weights=None):
    """Stack spectra from several lines onto one velocity grid.

    ``spectra``/``freq_ghz``: f32[..., C] per-channel brightness and
    frequency; ``line_freqs``: list of rest frequencies [GHz]; ``v_grid``:
    bin edges [km/s] (length nbins+1). Returns ``(stacked[..., nbins],
    hits[..., nbins])`` — a ``segment_sum`` over velocity-bin ids, the
    device analogue of the reference's per-line loop (``RRLFuncs.py``
    ``read_data``/stacking)."""
    import jax
    import jax.numpy as jnp

    spectra = jnp.asarray(spectra)
    w = (jnp.ones_like(spectra) if weights is None
         else jnp.broadcast_to(jnp.asarray(weights, spectra.dtype),
                               spectra.shape))
    C = spectra.shape[-1]
    nbins = len(v_grid) - 1
    v_grid = np.asarray(v_grid, np.float64)
    # Velocity-bin ids are per ROW: freq_ghz broadcasts against the full
    # spectra shape, so a multi-row stack (different feeds with different
    # frequency grids) bins each row on its own grid. Ids are computed at
    # freq_ghz's natural shape and only the (cheap, int) ids broadcast —
    # a shared 1-D grid does one searchsorted pass, not one per row.
    freq = np.asarray(freq_ghz, np.float64)
    flat_s = (spectra * w).reshape(-1, C)
    flat_w = w.reshape(-1, C)

    def bin_row(sr, wr, idr):
        s = jax.ops.segment_sum(sr, idr, num_segments=nbins + 1)[:nbins]
        h = jax.ops.segment_sum(wr, idr, num_segments=nbins + 1)[:nbins]
        return s, h

    total = jnp.zeros((flat_s.shape[0], nbins), spectra.dtype)
    hits = jnp.zeros_like(total)
    for f0 in line_freqs:
        v = channel_velocity(freq, float(f0))
        ids = np.searchsorted(v_grid, v, side="right") - 1
        valid = (ids >= 0) & (ids < nbins)
        ids = np.where(valid, ids, nbins)
        ids_flat = np.broadcast_to(ids, spectra.shape).reshape(-1, C)
        valid_flat = np.broadcast_to(valid, spectra.shape).reshape(-1, C)
        ids_j = jnp.asarray(ids_flat, jnp.int32)
        valid_j = jnp.asarray(valid_flat, spectra.dtype)
        s, h = jax.vmap(bin_row)(flat_s * valid_j, flat_w * valid_j, ids_j)
        total = total + s
        hits = hits + h
    shape = spectra.shape[:-1] + (nbins,)
    stacked = jnp.where(hits > 0, total / jnp.maximum(hits, 1e-30), 0.0)
    return stacked.reshape(shape), hits.reshape(shape)


def electron_temperature(line_peak_k, continuum_k, delta_v_kms,
                         freq_ghz, helium_fraction: float = 0.08):
    """LTE electron temperature [K] from the line-to-continuum ratio
    (``RRLequations.py:3-50``):

    ``T_e = (7103.3 nu_GHz^1.1 / ((T_L/T_C) dv (1 + y+)))^0.87``
    """
    ratio = np.asarray(line_peak_k, np.float64) \
        / np.maximum(np.asarray(continuum_k, np.float64), 1e-30)
    x = (7103.3 * np.asarray(freq_ghz, np.float64) ** 1.1
         / np.maximum(ratio * np.asarray(delta_v_kms, np.float64)
                      * (1.0 + helium_fraction), 1e-30))
    return x ** 0.87


def fit_line(v_kms, spectrum, weights=None):
    """Gaussian line fit on a stacked velocity spectrum: returns
    ``(amplitude, v0, fwhm_kms, offset)`` via the shared LM solver.

    ``weights`` should be the ``hits`` array from :func:`stack_spectra`
    (or any per-bin inverse-variance weight): when channel spacing exceeds
    the velocity-bin width the stack zero-fills empty bins, and fitting
    those as real zeros drags the fit away from the line. Zero-weight bins
    are excluded from both the initial guess and the solve.
    """
    import jax.numpy as jnp

    from comapreduce_tpu.calibration import fitting

    v_np = np.asarray(v_kms, np.float64)
    s_np = np.asarray(spectrum, np.float64)
    w_np = (np.ones_like(s_np) if weights is None
            else np.asarray(weights, np.float64))
    valid = w_np > 0
    if not valid.any():
        raise ValueError("fit_line: all bins have zero weight")

    def model(p, x, y):
        amp, v0, sig, off = p
        return amp * jnp.exp(-0.5 * ((x - v0) / sig) ** 2) + off

    i = int(np.argmax(np.where(valid, s_np, -np.inf)))
    med = float(np.median(s_np[valid]))
    # moment-based initial width/centre from the positive excess
    excess = np.where(valid, np.maximum(s_np - med, 0.0), 0.0)
    norm = excess.sum()
    if norm > 0:
        v0_0 = float((excess * v_np).sum() / norm)
        sig0 = float(np.sqrt((excess * (v_np - v0_0) ** 2).sum() / norm))
        sig0 = max(sig0, 1e-3)
    else:
        v0_0, sig0 = float(v_np[i]), 20.0
    p0 = jnp.asarray([s_np[i] - med, v0_0, sig0, med], jnp.float32)
    p, err, chi2 = fitting.fit_gauss2d(
        jnp.asarray(s_np, jnp.float32), jnp.asarray(v_np, jnp.float32),
        jnp.zeros(s_np.shape, jnp.float32), jnp.asarray(w_np, jnp.float32),
        p0, model=model)
    amp, v0, sig, off = (float(x) for x in p)
    return amp, v0, abs(sig) * 2.355, off
