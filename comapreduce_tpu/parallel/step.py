"""The flagship end-to-end program: one observation -> destriped map.

``ObservationStep`` fuses the whole pipeline into ONE jitted SPMD program
over a ``('feed', 'time')`` mesh:

  vane Tsys/gain  ->  Level-1 -> Level-2 reduction  ->  destriper CG map

- the vane kernel and the reduction are data parallel over feeds (sharded
  ``'feed'``; the reference's rank-per-file MPI split);
- the destriper shards the flattened (feed, band, time) axis over EVERY
  device; maps and CG scalars are ``psum``-reduced (the reference's
  Allreduce/Gather+Bcast, ``Destriper.py:61-75,183-204``).

This is the program the driver compile-checks (``__graft_entry__.py``) and
the benchmark times (``bench.py``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from comapreduce_tpu.mapmaking.destriper import DestriperResult, destripe
from comapreduce_tpu.ops.reduce import (ReduceConfig, reduce_feed_scans,
                                        scan_starts_lengths)
from comapreduce_tpu.ops.vane import _event_kernel
from comapreduce_tpu.parallel.sharded import _shard_map, pad_for_shards

__all__ = ["ObservationStep", "make_example_inputs"]


class ObservationStep:
    """Compile-once runner of the full observation pipeline on a mesh.

    Static geometry (scan edges, map size, offset length) is fixed at
    construction; ``__call__`` takes the per-observation arrays. All shapes
    must match the construction-time geometry — the pipeline pads ragged
    observations into these static blocks (``ops/reduce.py``).
    """

    def __init__(self, mesh: Mesh, scan_edges: np.ndarray, n_samples: int,
                 npix: int, offset_length: int = 50, n_iter: int = 100,
                 threshold: float = 1e-6, n_channels: int = 64,
                 medfilt_window: int = 500, vane_temperature: float = 290.0):
        self.mesh = mesh
        self.axes = tuple(mesh.axis_names)
        self.n_shards = int(np.prod([mesh.shape[a] for a in self.axes]))
        starts, lengths, L = scan_starts_lengths(np.asarray(scan_edges))
        self.starts = jnp.asarray(starts, jnp.int32)
        self.lengths = jnp.asarray(lengths, jnp.int32)
        self.n_scans, self.L = len(starts), L
        self.n_samples = n_samples
        self.npix = npix
        self.offset_length = offset_length
        self.n_iter = n_iter
        self.threshold = threshold
        self.vane_temperature = vane_temperature
        self.cfg = ReduceConfig(n_channels, medfilt_window=medfilt_window)
        self._fns = {}  # (F, B, T) -> compiled step

    def _build(self, F: int, B: int, T: int):
        axes, mesh = self.axes, self.mesh
        cfg, n_scans, L = self.cfg, self.n_scans, self.L
        npix, oflen = self.npix, self.offset_length
        # Offsets must never straddle (feed, band) row boundaries — one
        # offset amplitude models ONE detector's 1/f over L contiguous
        # samples. Pad each row to a whole number of offsets (zero weight,
        # drop pixel), the analogue of the reference truncating scans to
        # offset multiples (countDataSize, COMAPData.py:163-187).
        t_row_pad = (-T) % oflen

        def step(tod, mask, vane_tod, airmass, pixels, freq_scaled,
                 starts, lengths):
            # ---- vane calibration, vmapped over feeds (dp) --------------
            tsys, sys_gain = _event_kernel(
                vane_tod, jnp.float32(self.vane_temperature))

            # ---- Level-1 -> Level-2 reduction, vmapped over feeds (dp) --
            red = jax.vmap(
                functools.partial(reduce_feed_scans, cfg=cfg,
                                  n_scans=n_scans, L=L),
                in_axes=(0, 0, 0, None, None, 0, 0, None))(
                tod, mask, airmass, starts, lengths, tsys, sys_gain,
                freq_scaled)

            # ---- flatten to the destriper's time axis (sp) --------------
            row_pad = [(0, 0), (0, 0), (0, t_row_pad)]
            flat_tod = jnp.pad(red["tod"], row_pad).reshape(-1)
            flat_w = jnp.pad(red["weights"], row_pad).reshape(-1)
            pix3 = jnp.broadcast_to(pixels[:, None, :], (F, B, T))
            flat_pix = jnp.pad(pix3, row_pad,
                               constant_values=npix).reshape(-1)
            flat_tod, flat_pix, flat_w = pad_for_shards(
                flat_tod, flat_pix, flat_w, self.n_shards, oflen, npix)
            spec = P(axes)
            shard_sharding = NamedSharding(mesh, spec)
            flat_tod = jax.lax.with_sharding_constraint(flat_tod,
                                                        shard_sharding)
            flat_w = jax.lax.with_sharding_constraint(flat_w, shard_sharding)
            flat_pix = jax.lax.with_sharding_constraint(flat_pix,
                                                        shard_sharding)

            out_specs = DestriperResult(
                offsets=spec, ground=P(), destriped_map=P(), naive_map=P(),
                weight_map=P(), hit_map=P(), n_iter=P(), residual=P(),
                diverged=P())
            result = _shard_map(
                lambda t, p, w: destripe(
                    t, p, w, npix, offset_length=oflen, n_iter=self.n_iter,
                    threshold=self.threshold, axis_name=axes),
                mesh=mesh, in_specs=(spec, spec, spec),
                out_specs=out_specs, check_vma=False)(
                flat_tod, flat_pix, flat_w)
            return red, result

        feed = NamedSharding(mesh, P("feed"))
        repl = NamedSharding(mesh, P())
        in_shardings = (feed, feed, feed, feed, feed, repl, repl, repl)
        return jax.jit(step, in_shardings=in_shardings)

    def __call__(self, tod, mask, vane_tod, airmass, pixels, freq_scaled):
        """Run the full step.

        tod, mask:   f32[F, B, C, T] science samples (vane samples masked).
        vane_tod:    f32[F, B, C, t_vane] one vane event window.
        airmass:     f32[F, T].
        pixels:      i32[F, T] map pixel per sample (npix = invalid).
        freq_scaled: f32[B, C].

        Returns ``(level2_dict, DestriperResult)``.
        """
        F, B, C, T = tod.shape
        key = (F, B, T)
        if key not in self._fns:
            self._fns[key] = self._build(F, B, T)
        return self._fns[key](jnp.asarray(tod), jnp.asarray(mask),
                        jnp.asarray(vane_tod), jnp.asarray(airmass),
                        jnp.asarray(pixels), jnp.asarray(freq_scaled),
                        self.starts, self.lengths)

    def input_shardings(self) -> dict:
        """Per-input NamedShardings of :meth:`__call__`'s array kwargs —
        the placement the ingest double-buffer must land blocks in so
        the compiled step starts without a reshard."""
        feed = NamedSharding(self.mesh, P("feed"))
        repl = NamedSharding(self.mesh, P())
        return dict(tod=feed, mask=feed, vane_tod=feed, airmass=feed,
                    pixels=feed, freq_scaled=repl)

    def run_stream(self, observations, buffer_size: int = 2,
                   watchdog=None):
        """Stream observations through the compiled step with
        host→device double-buffering: observation ``i+1``'s arrays
        transfer (``jax.device_put`` is async) while observation ``i``
        computes (``ingest.prefetch_to_device``; docs/ingest.md).

        ``observations`` yields dicts with :meth:`__call__`'s array
        kwargs (host numpy, e.g. built from a prefetched
        ``level1_stream``). Yields one ``(level2_dict,
        DestriperResult)`` per observation, in order. ``watchdog`` (a
        ``resilience.Watchdog``, e.g. ``Resilience.watchdog``) puts
        each H2D issue under the ``ingest.h2d`` deadline — a wedged
        transfer backend blocks at issue time once the queue fills,
        and the soft deadline surfaces it (monitor-only; see
        ``prefetch_to_device``).
        """
        from comapreduce_tpu.ingest.device_buffer import prefetch_to_device

        shardings = self.input_shardings()
        for block in prefetch_to_device(
                observations, size=buffer_size,
                sharding=lambda b: {k: shardings[k] for k in b},
                watchdog=watchdog):
            yield self(**block)


def make_example_inputs(rng: np.random.Generator, n_feeds: int = 2,
                        n_bands: int = 2, n_channels: int = 16,
                        n_scans: int = 2, scan_samples: int = 400,
                        vane_samples: int = 128, npix: int = 64):
    """Tiny physically-shaped inputs for compile checks and smoke tests.

    Returns ``(kwargs_for_ObservationStep, arrays)`` — a raw-counts TOD with
    gain structure, a vane window, and a sweep pixel pattern, all numpy.
    """
    F, B, C = n_feeds, n_bands, n_channels
    gap = 32
    edges, t = [], gap
    for _ in range(n_scans):
        edges.append((t, t + scan_samples))
        t += scan_samples + gap
    T = t
    edges = np.asarray(edges, dtype=np.int64)

    gain = 1e6 * (1.0 + 0.1 * rng.normal(size=(F, B, C)))
    tsys = 45.0 * (1.0 + 0.2 * rng.random(size=(F, B, C)))
    tod = gain[..., None] * tsys[..., None] * (
        1.0 + 0.01 * rng.normal(size=(F, B, C, T)))
    mask = np.zeros((F, B, C, T), np.float32)
    for s, e in edges:
        mask[..., s:e] = 1.0
    vane_tod = gain[..., None] * (
        tsys[..., None] + np.where(np.arange(vane_samples) < vane_samples // 2,
                                   290.0, 0.0))
    vane_tod = vane_tod * (1.0 + 1e-3 * rng.normal(size=(F, B, C,
                                                         vane_samples)))
    airmass = np.full((F, T), 1.2, np.float32)
    sweep = (np.arange(T) * 7) % npix
    pixels = np.broadcast_to(sweep, (F, T)).astype(np.int32).copy()
    freq = np.linspace(-0.1, 0.1, C, dtype=np.float32)
    freq_scaled = np.broadcast_to(freq, (B, C)).astype(np.float32).copy()

    step_kwargs = dict(scan_edges=edges, n_samples=T, npix=npix,
                       offset_length=50, n_iter=20, n_channels=C,
                       medfilt_window=101)
    arrays = dict(tod=tod.astype(np.float32), mask=mask,
                  vane_tod=vane_tod.astype(np.float32), airmass=airmass,
                  pixels=pixels, freq_scaled=freq_scaled)
    return step_kwargs, arrays
