"""Dataset axis roles -> mesh shardings (``Tools/Types.py`` parity).

The reference annotates every COMAP dataset path with the physical role
of each axis (``_HORNS_/_SIDEBANDS_/_FREQUENCY_/_TIME_``,
``Types.py:33-44``) and derives MPI split structures from them
(``getSplitStructure``/``getSelectStructure`` :52-94). The TPU-native
counterpart maps those roles onto mesh axes and produces
``PartitionSpec``s: feeds shard over the ``'feed'`` axis, time over
``'time'``, bands/channels stay local (they ride the VPU lanes).
"""

from __future__ import annotations

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["AXIS_ROLES", "DATASET_AXES", "partition_spec", "sharding_for",
           "split_slices"]

# physical axis roles
HORNS = "horns"          # feeds (<= 20)
SIDEBANDS = "sidebands"  # bands (4)
FREQUENCY = "frequency"  # channels (1024)
TIME = "time"            # samples

AXIS_ROLES = (HORNS, SIDEBANDS, FREQUENCY, TIME)

# per-dataset axis roles (the reference's _COMAPDATA_, Types.py:33-44)
DATASET_AXES = {
    "spectrometer/tod": (HORNS, SIDEBANDS, FREQUENCY, TIME),
    "spectrometer/MJD": (TIME,),
    "spectrometer/features": (TIME,),
    "spectrometer/frequency": (SIDEBANDS, FREQUENCY),
    "spectrometer/feeds": (HORNS,),
    "spectrometer/bands": (SIDEBANDS,),
    "spectrometer/pixel_pointing/pixel_ra": (HORNS, TIME),
    "spectrometer/pixel_pointing/pixel_dec": (HORNS, TIME),
    "spectrometer/pixel_pointing/pixel_az": (HORNS, TIME),
    "spectrometer/pixel_pointing/pixel_el": (HORNS, TIME),
    "averaged_tod/tod": (HORNS, SIDEBANDS, TIME),
    "averaged_tod/tod_original": (HORNS, SIDEBANDS, TIME),
    "averaged_tod/weights": (HORNS, SIDEBANDS, TIME),
    "spikes/spike_mask": (HORNS, SIDEBANDS, TIME),
    "vane/system_temperature": (None, HORNS, SIDEBANDS, FREQUENCY),
    "vane/system_gain": (None, HORNS, SIDEBANDS, FREQUENCY),
}

# which mesh axis (if any) each physical role shards over
_ROLE_TO_MESH = {HORNS: "feed", TIME: "time",
                 SIDEBANDS: None, FREQUENCY: None, None: None}


def partition_spec(dataset: str, mesh_axes=("feed", "time")) -> P:
    """PartitionSpec for a dataset path on a mesh with ``mesh_axes``.

    Roles whose mesh axis is absent from ``mesh_axes`` stay replicated
    (the reference's select-vs-split distinction, ``Types.py:71-94``).
    """
    roles = DATASET_AXES.get(dataset)
    if roles is None:
        return P()
    spec = []
    for role in roles:
        m = _ROLE_TO_MESH.get(role)
        spec.append(m if m in mesh_axes else None)
    return P(*spec)


def sharding_for(dataset: str, mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, partition_spec(dataset,
                                              tuple(mesh.axis_names)))


def split_slices(n: int, n_parts: int, part: int) -> slice:
    """Contiguous block split of an axis (the reference's ``hi/lo``
    ``getDataRange``, ``DOCS/main.tex:258-269``)."""
    step = -(-n // n_parts)
    lo = min(step * part, n)
    return slice(lo, min(lo + step, n))
