"""Distributed execution: device meshes, sharded reduction, sharded destriping.

The reference parallelises with mpi4py: static file sharding for the TOD
stages (``run_average.py:38-39``) and Allreduce/Gather+Bcast collectives
inside the destriper CG (``Destriper.py:61-75,183-204``) — SURVEY.md §2.5.
The TPU-native design replaces every MPI pattern with XLA collectives over a
``jax.sharding.Mesh``:

- **dp (data parallel)** — feeds/files shard over the ``'feed'`` mesh axis
  (the reference's rank-per-file decomposition);
- **sp (sequence parallel)** — the concatenated TOD time axis shards over
  the ``'time'`` mesh axis in the destriper; each shard owns whole offsets,
  the map and CG scalars are ``psum``-reduced over ICI (the reference's
  rank-owns-samples decomposition, ``Destriper.py:217-263``);
- multi-host scales the same program over DCN: same mesh, more devices.

No point-to-point communication exists anywhere — every reference pattern is
all-reduce-shaped (SURVEY.md §2.5), so ``psum`` is the only collective.
"""

from comapreduce_tpu.parallel.mesh import (  # noqa: F401
    feed_time_mesh,
    flat_axis_size,
    local_mesh,
)
from comapreduce_tpu.parallel.sharded import (  # noqa: F401
    destripe_sharded,
    reduce_feeds_sharded,
)
from comapreduce_tpu.parallel.step import ObservationStep  # noqa: F401
