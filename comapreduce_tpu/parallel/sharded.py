"""Sharded versions of the two flagship programs.

- :func:`reduce_feeds_sharded`: the Level-1 -> Level-2 reduction, data
  parallel over feeds (reference: one MPI rank per file,
  ``run_average.py:38-39``). Pure SPMD — no collectives; XLA partitions the
  ``vmap``-over-feeds program from the input shardings alone.
- :func:`destripe_sharded`: the destriper CG with the concatenated TOD time
  axis sharded over every device. Each shard owns whole offsets; the map
  accumulation and CG dot products are ``psum`` over the mesh (reference:
  ``share_map`` Gather+Bcast and Allreduce scalars,
  ``Destriper.py:61-75,183-204``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from comapreduce_tpu.mapmaking.destriper import (DestriperResult,
                                                 _check_precond, destripe,
                                                 destripe_planned)
from comapreduce_tpu.mapmaking.pixel_space import resolve_npix
from comapreduce_tpu.mapmaking.pointing_plan import PointingPlan
from comapreduce_tpu.ops.reduce import (ReduceConfig, reduce_feed_scans,
                                        scan_starts_lengths)

try:  # jax >= 0.4.35 exports shard_map at top level
    from jax import shard_map as _jax_shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _jax_shard_map

# the replication-check kwarg was renamed check_rep -> check_vma; probe
# the installed signature once and translate (callers use check_vma)
import inspect as _inspect

_CHECK_KW = ("check_vma" if "check_vma"
             in _inspect.signature(_jax_shard_map).parameters
             else "check_rep")


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    return _jax_shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **{_CHECK_KW: check_vma})

__all__ = ["reduce_feeds_sharded", "destripe_sharded",
           "destripe_sharded_planned", "make_destripe_sharded_planned",
           "pad_for_shards"]


@functools.lru_cache(maxsize=32)
def _reduce_feeds_fn(cfg: ReduceConfig, n_scans: int, L: int,
                     with_mask: bool = True, donate_tod: bool = True,
                     with_fold: bool = False):
    """Cached jitted vmap-over-feeds reduction (one compile per geometry,
    not one per call — a filelist run calls this once per batch).

    ``with_mask=False`` is the NaN-carrying ingest path: the per-feed mask
    is derived on device (``reduce_feed_scans`` with ``mask=None``).
    ``donate_tod=False`` builds the non-donating variant for callers whose
    ``tod`` buffer must survive the call (see ``reduce_feeds_sharded``).
    ``with_fold=True`` appends a trailing DYNAMIC ``fold_len`` i32 scalar
    operand (the per-file scan-block length the median filter reflects
    at) — the campaign shape policy's one value-dynamic knob, so every
    file of a bucket shares this single compile."""
    fold_axis = (None,) if with_fold else ()
    if with_mask:
        # keyword-bind cfg/n_scans/L through a wrapper: appending the
        # fold tracer POSITIONALLY to a partial would land it on the
        # static ``cfg`` parameter and fail at trace time
        def one(tod, mask, airmass, starts, lengths, tsys, sys_gain,
                freq, *fold):
            return reduce_feed_scans(tod, mask, airmass, starts, lengths,
                                     tsys, sys_gain, freq, cfg=cfg,
                                     n_scans=n_scans, L=L,
                                     fold_len=fold[0] if fold else None)
        fn = jax.vmap(one, in_axes=(0, 0, 0, None, None, 0, 0, None)
                      + fold_axis)
    else:
        def one(tod, airmass, starts, lengths, tsys, sys_gain, freq,
                *fold):
            return reduce_feed_scans(tod, None, airmass, starts, lengths,
                                     tsys, sys_gain, freq, cfg=cfg,
                                     n_scans=n_scans, L=L,
                                     fold_len=fold[0] if fold else None)
        fn = jax.vmap(one, in_axes=(0, 0, None, None, 0, 0, None)
                      + fold_axis)
    # donate the raw counts (ISSUE 4 tentpole 1): the stage ships a fresh
    # batch every call, so XLA may reuse the ~2.2 GB/feed input
    # allocation for the scan blocks instead of doubling residency.
    # Accelerator backends only — CPU jit ignores donation and warns.
    donate = (0,) if donate_tod and jax.default_backend() != "cpu" else ()
    return jax.jit(fn, donate_argnums=donate)


def reduce_feeds_sharded(mesh: Mesh, tod, mask, airmass, starts, lengths,
                         tsys, sys_gain, freq_scaled, cfg: ReduceConfig,
                         L: int | None = None,
                         fold_len: int | None = None):
    """Run :func:`reduce_feed_scans` for every feed, feeds sharded over the
    ``'feed'`` mesh axis.

    Arrays carry a leading feed axis: ``tod``/``mask`` f32[F, B, C, T],
    ``airmass`` f32[F, T], ``tsys``/``sys_gain`` f32[F, B, C]. Scan geometry
    (``starts``/``lengths``) and ``freq_scaled`` f32[B, C] are shared by all
    feeds (replicated). Returns the dict of :func:`reduce_feed_scans` with a
    leading feed axis, feed-sharded. ``mask=None`` ships NaN-carrying
    counts and derives validity on device (half the host->device bytes).

    On accelerator backends the ``tod`` buffer is DONATED (XLA reuses
    the ~2.2 GB/feed raw-counts allocation in place): treat the passed
    array as consumed. Exception: a ``jax.Array`` already carrying the
    feed sharding is NOT donated — ``device_put`` would hand the jit the
    caller's own buffer, and donation must never invalidate an input the
    caller still owns.
    """
    n_scans = int(starts.shape[0])
    if L is None:
        # L is static inside reduce_feed_scans; recover it the same way
        # the single-feed path does (scan blocks are padded to this
        # length). A caller running a campaign shape policy passes its
        # canonical L explicitly instead — the masked-tail extract
        # semantics carry any L >= the longest scan.
        _, _, L = scan_starts_lengths(
            np.stack([np.asarray(starts),
                      np.asarray(starts) + np.asarray(lengths)],
                     axis=1))
    L = int(L)

    feed_sharded = NamedSharding(mesh, P("feed"))
    repl = NamedSharding(mesh, P())

    # the raw-counts buffer is DONATED on accelerator backends — safe for
    # host-shipped batches (device_put creates a fresh buffer), but a
    # caller that pre-placed tod with the feed sharding would get the
    # SAME buffer back from device_put and donation would invalidate
    # their copy; use the non-donating program for that case
    donate_tod = not (isinstance(tod, jax.Array)
                      and getattr(tod, "sharding", None) == feed_sharded)
    tod = jax.device_put(tod, feed_sharded)
    if mask is not None:
        mask = jax.device_put(mask, feed_sharded)
    airmass = jax.device_put(airmass, feed_sharded)
    tsys = jax.device_put(tsys, feed_sharded)
    sys_gain = jax.device_put(sys_gain, feed_sharded)
    starts = jax.device_put(jnp.asarray(starts), repl)
    lengths = jax.device_put(jnp.asarray(lengths), repl)
    freq_scaled = jax.device_put(freq_scaled, repl)
    # the campaign policy's one value-dynamic operand: the per-file
    # block length the median filter reflects at (see reduce_feed_scans)
    fold = () if fold_len is None else (
        jax.device_put(jnp.asarray(int(fold_len), jnp.int32), repl),)

    fn = _reduce_feeds_fn(cfg, n_scans, L, with_mask=mask is not None,
                          donate_tod=donate_tod,
                          with_fold=fold_len is not None)
    with mesh:
        if mask is None:
            return fn(tod, airmass, starts, lengths, tsys, sys_gain,
                      freq_scaled, *fold)
        return fn(tod, mask, airmass, starts, lengths, tsys,
                  sys_gain, freq_scaled, *fold)


def pad_for_shards(tod, pixels, weights, n_shards: int, offset_length: int,
                   npix: int):
    """Pad flat destriper vectors so every shard gets whole offsets.

    Padding samples carry zero weight and the drop pixel ``npix``, so they
    change nothing (the reference instead truncates scans to offset
    multiples, ``COMAPData.py:163-187``; padding wastes nothing on TPU where
    shapes are static anyway). ``npix`` may be a ``PixelSpace`` — the
    sentinel is then the compacted space's ``n_solve``.
    """
    npix = resolve_npix(npix)
    n = tod.shape[0]
    quantum = n_shards * offset_length
    n_pad = (-n) % quantum
    if n_pad:
        tod = jnp.concatenate([tod, jnp.zeros(n_pad, tod.dtype)])
        pixels = jnp.concatenate(
            [pixels, jnp.full(n_pad, npix, pixels.dtype)])
        weights = jnp.concatenate([weights, jnp.zeros(n_pad, weights.dtype)])
    return tod, pixels, weights


def destripe_sharded(mesh: Mesh, tod, pixels, weights, npix: int,
                     offset_length: int = 50, n_iter: int = 100,
                     threshold: float = 1e-6,
                     ground_ids=None, az=None, n_groups: int = 0,
                     precond: str = "jacobi",
                     cg_dot: str = "f32") -> DestriperResult:
    """Destripe with the flat time axis sharded over the whole mesh.

    ``tod``/``weights`` f32[N], ``pixels`` i32[N]; N is padded here to a
    multiple of ``n_devices * offset_length``. The returned ``offsets``
    vector is the concatenation over shards (global offset order); maps and
    CG scalars come back replicated. ``npix`` may be a compacted
    ``PixelSpace`` (pixels already remapped to solver ids): every
    psum'd map vector is then ``n_compact``-sized — the whole-mesh
    reduction never materialises the sky.
    """
    npix = resolve_npix(npix)
    axes = tuple(mesh.axis_names)
    n_shards = int(np.prod([mesh.shape[a] for a in axes]))
    tod, pixels, weights = pad_for_shards(
        tod, pixels, weights, n_shards, offset_length, npix)
    with_ground = ground_ids is not None
    if with_ground:
        n = tod.shape[0]
        pad = n - ground_ids.shape[0]
        if pad:
            ground_ids = jnp.concatenate(
                [ground_ids, jnp.zeros(pad, ground_ids.dtype)])
            az = jnp.concatenate([az, jnp.zeros(pad, az.dtype)])

    shard = P(axes)
    repl = P()

    def local(tod_l, pixels_l, weights_l, ground_l, az_l):
        return destripe(tod_l, pixels_l, weights_l, npix,
                        offset_length=offset_length, n_iter=n_iter,
                        threshold=threshold, axis_name=axes,
                        ground_ids=ground_l if with_ground else None,
                        az=az_l if with_ground else None, n_groups=n_groups,
                        precond=precond, cg_dot=cg_dot)

    out_specs = DestriperResult(
        offsets=shard, ground=repl, destriped_map=repl, naive_map=repl,
        weight_map=repl, hit_map=repl, n_iter=repl, residual=repl,
        diverged=repl)

    if with_ground:
        fn = _shard_map(local, mesh=mesh,
                        in_specs=(shard, shard, shard, shard, shard),
                        out_specs=out_specs, check_vma=False)
        args = (tod, pixels, weights, ground_ids, az)
    else:
        fn = _shard_map(lambda t, p, w: local(t, p, w, None, None), mesh=mesh,
                        in_specs=(shard, shard, shard),
                        out_specs=out_specs, check_vma=False)
        args = (tod, pixels, weights)

    with mesh:
        return jax.jit(fn)(*args)


_PLAN_KEYS = ("sample_perm", "sample_pair", "sample_base", "pair_rank",
              "pair_offset", "rank_base", "pair_perm_off", "off_base",
              "uniq_pixels", "rank_to_global")


def make_destripe_sharded_planned(mesh: Mesh, plans: list[PointingPlan],
                                  n_iter: int = 100,
                                  threshold: float = 1e-6,
                                  n_bands: int = 0,
                                  n_groups: int = 0,
                                  with_coarse: bool = False,
                                  with_mg: bool = False,
                                  mg_smooth: int = 1,
                                  mg_omega: float = 2.0 / 3.0,
                                  with_banded: bool = False,
                                  precond: str = "jacobi",
                                  kernels: str = "auto",
                                  cg_dot: str = "f32",
                                  trace_iters: int = 0):
    """Build a reusable sharded planned-destriper: returns
    ``run(tod, weights) -> DestriperResult``.

    The returned callable owns the uploaded per-shard index arrays and ONE
    jitted shard_map program — callers solving several RHS against the
    same pointing (e.g. the per-band loop of ``run_destriper``, whose
    pixels are band-invariant) pay the plan upload and XLA compile once.

    ``n_bands > 0`` builds the MULTI-RHS program: ``tod``/``weights`` are
    f32[n_bands, N] with the band axis replicated and the time axis
    sharded; offsets/maps/residual come back with the leading band axis
    (see ``destripe_planned``), the whole stack in one CG.

    ``n_groups > 0`` builds the joint GROUND-template program (single
    RHS): ``run(tod, weights, ground_off, az)`` with the per-offset
    group ids and per-sample azimuth sharded alongside; the ground block
    is replicated (its group sums psum over the mesh).

    ``with_coarse=True`` builds the program with the two-level
    preconditioner inputs: ``run(tod, weights, coarse=(grp, ac_inv))``
    where ``grp`` is the GLOBAL i32[n_off_total] offset->block map
    (sharded here — every shard owns whole offsets, so its slice lines
    up) and ``ac_inv`` the replicated coarse inverse
    (``destriper.build_coarse_preconditioner``; stack (nb, n_c, n_c)
    for multi-RHS). Not available on the ground program.

    ``with_mg=True`` builds the native sharded MULTIGRID program:
    ``run(tod, weights, mg=hierarchy)`` with the hierarchy from
    ``destriper.build_multigrid_hierarchy`` (or ``stack_multigrid``)
    built over the GLOBAL padded pixel/weight vectors. Level 0's
    ``grp`` is sharded like the two-level ``grp`` (whole offsets per
    shard — the slice lines up); every other leaf is replicated, the
    level-0 restriction psum-assembles the global coarse residual and
    the coarser levels run redundantly per shard (see
    ``destripe_planned``'s ``mg`` doc). ``mg_smooth``/``mg_omega``
    are static. Mutually exclusive with ``with_coarse``.

    ``with_banded=True`` adds the measured-noise banded prior inputs:
    ``run(..., banded=(c0, cs))`` from
    ``mapmaking.noise_weight.build_banded_weight`` built with
    ``n_shards`` = this mesh's device count over the PADDED global
    offset count — ``c0``/``cs`` are sharded on their offset (last)
    axis and the apply is purely local (boundary couplings are zeroed
    by the builder). Composes with any preconditioner program.

    ``trace_iters > 0`` threads the solver-trace depth: the result's
    ``trace`` histories come back replicated (the traced dots are
    psum'd), so ``telemetry.solver_trace.record_solve`` works on
    sharded solves exactly as on single-device ones.

    ``cg_dot`` threads the ``[Precision] cg_dot`` knob to every branch
    (see ``destripe_planned``): compensated per-shard dots, f32 psum of
    the per-shard partials.
    """
    if n_bands and n_groups:
        raise ValueError("ground solves are single-RHS; run per band")
    _check_precond(precond, coarse="coarse" if with_coarse else None,
                   mg="mg" if with_mg else None)
    if n_groups and (with_coarse or with_mg or with_banded):
        raise ValueError("the sharded ground program keeps Jacobi and "
                         "white weighting; with_coarse/with_mg/"
                         "with_banded apply to the plain/multi-RHS "
                         "programs")
    axes = tuple(mesh.axis_names)
    n_shards = int(np.prod([mesh.shape[a] for a in axes]))
    if len(plans) != n_shards:
        raise ValueError(f"{len(plans)} plans for {n_shards} shards")
    p0 = plans[0]
    if p0.rank_to_global is None:
        raise ValueError("plans must come from build_sharded_plans")
    stacked = {k: jnp.stack([jnp.asarray(getattr(p, k), jnp.int32)
                             for p in plans])
               for k in _PLAN_KEYS}

    shard = P(axes)
    repl = P()
    # band axis replicated, time axis sharded
    v_spec = P(None, axes) if n_bands else shard
    band_repl = P(None) if n_bands else repl

    arr_specs = {k: shard for k in stacked}
    out_specs = DestriperResult(
        offsets=v_spec, ground=repl, destriped_map=band_repl,
        naive_map=band_repl, weight_map=band_repl, hit_map=repl,
        n_iter=repl, residual=band_repl, diverged=band_repl,
        # traced histories are replicated (every traced dot is psum'd);
        # untraced solves return None there — an empty pytree node, so
        # the specs pytree matches either way
        trace=((repl, repl, repl, repl) if trace_iters else None))

    if n_groups:
        def local_g(tod_l, w_l, g_off_l, az_l, arrs):
            arrs = {k: v[0] for k, v in arrs.items()}
            return destripe_planned(tod_l, w_l, p0, n_iter=n_iter,
                                    threshold=threshold, axis_name=axes,
                                    dense_maps=False, device_arrays=arrs,
                                    ground_off=g_off_l, az=az_l,
                                    n_groups=n_groups, precond=precond,
                                    kernels=kernels, cg_dot=cg_dot,
                                    trace_iters=trace_iters)

        fn = jax.jit(_shard_map(
            local_g, mesh=mesh,
            in_specs=(shard, shard, shard, shard, arr_specs),
            out_specs=out_specs, check_vma=False))

        def run(tod, weights, ground_off, az) -> DestriperResult:
            with mesh:
                return fn(jnp.asarray(tod), jnp.asarray(weights),
                          jnp.asarray(ground_off, jnp.int32),
                          jnp.asarray(az, jnp.float32), stacked)

        return run

    # ONE local body for every non-ground program: the optional inputs
    # (two-level coarse pair, multigrid hierarchy, banded prior) ride a
    # dict whose in_specs mirror its structure — built lazily per
    # structure because the mg hierarchy's level count is a call-time
    # fact, then cached (jit dedupes recompiles by structure anyway)
    def local(tod_l, w_l, extra, arrs):
        arrs = {k: v[0] for k, v in arrs.items()}
        kw = {}
        if "coarse_grp" in extra:
            kw["coarse"] = (extra["coarse_grp"], extra["coarse_inv"])
        if "mg" in extra:
            kw["mg"] = extra["mg"]
            kw["mg_smooth"] = mg_smooth
            kw["mg_omega"] = mg_omega
        if "banded_c0" in extra:
            kw["banded"] = (extra["banded_c0"], extra["banded_cs"])
        return destripe_planned(tod_l, w_l, p0, n_iter=n_iter,
                                threshold=threshold, axis_name=axes,
                                dense_maps=False, device_arrays=arrs,
                                precond=precond, kernels=kernels,
                                cg_dot=cg_dot, trace_iters=trace_iters,
                                **kw)

    def extra_specs(extra):
        specs = {}
        for k, v in extra.items():
            if k == "coarse_grp":
                specs[k] = shard          # whole offsets per shard
            elif k == "coarse_inv":
                specs[k] = band_repl
            elif k == "mg":
                # level 0's grp is each shard's slice of the global
                # offset->block map; every other leaf (coarser stencils,
                # operator values, dense inverse) is replicated
                specs[k] = tuple(
                    {kk: (shard if (i == 0 and kk == "grp") else repl)
                     for kk in lv}
                    for i, lv in enumerate(v))
            elif k == "banded_c0":
                specs[k] = v_spec         # offset axis sharded
            elif k == "banded_cs":
                specs[k] = (P(None, None, axes) if n_bands
                            else P(None, axes))
        return specs

    compiled: dict = {}

    def get_fn(extra):
        key = jax.tree_util.tree_structure(extra)
        if key not in compiled:
            compiled[key] = jax.jit(_shard_map(
                local, mesh=mesh,
                in_specs=(v_spec, v_spec, extra_specs(extra), arr_specs),
                out_specs=out_specs, check_vma=False))
        return compiled[key]

    def run(tod, weights, coarse=None, mg=None,
            banded=None) -> DestriperResult:
        extra = {}
        if with_coarse:
            if coarse is None:
                raise ValueError("this program was built with_coarse; "
                                 "pass coarse=(grp, ac_inv)")
            grp, aci = coarse
            extra["coarse_grp"] = jnp.asarray(grp, jnp.int32)
            extra["coarse_inv"] = jnp.asarray(aci, jnp.float32)
        elif coarse is not None:
            raise ValueError("coarse passed but the program was built "
                             "without with_coarse")
        if with_mg:
            if mg is None:
                raise ValueError("this program was built with_mg; pass "
                                 "mg=build_multigrid_hierarchy(...) over "
                                 "the GLOBAL padded vectors")
            extra["mg"] = jax.tree_util.tree_map(jnp.asarray, tuple(mg))
        elif mg is not None:
            raise ValueError("mg passed but the program was built "
                             "without with_mg")
        if with_banded:
            if banded is None:
                raise ValueError("this program was built with_banded; "
                                 "pass banded=(c0, cs) from "
                                 "noise_weight.build_banded_weight")
            extra["banded_c0"] = jnp.asarray(banded[0], jnp.float32)
            extra["banded_cs"] = jnp.asarray(banded[1], jnp.float32)
        elif banded is not None:
            raise ValueError("banded passed but the program was built "
                             "without with_banded")
        fn = get_fn(extra)
        with mesh:
            return fn(jnp.asarray(tod), jnp.asarray(weights), extra,
                      stacked)

    return run


def destripe_sharded_planned(mesh: Mesh, tod, weights,
                             plans: list[PointingPlan],
                             n_iter: int = 100, threshold: float = 1e-6
                             ) -> DestriperResult:
    """Scatter-free destriping with the flat time axis sharded over the
    mesh and a SHARED compact pixel space.

    ``plans`` come from ``pointing_plan.build_sharded_plans`` (one per
    device, identical static shapes, global rank space). ``tod``/``weights``
    are the full f32[N] vectors in natural order; each shard receives its
    contiguous slice plus its own index arrays as shard_map inputs. The
    compact maps and CG scalars are ``psum``-reduced over the mesh; maps
    come back COMPACT — (n_rank_global,) over ``plans[0].uniq_global`` —
    so device memory is bounded by hit pixels, never npix (nside-4096
    scale, SURVEY hard part 3). One-shot wrapper over
    :func:`make_destripe_sharded_planned`.
    """
    return make_destripe_sharded_planned(mesh, plans, n_iter=n_iter,
                                         threshold=threshold)(tod, weights)
