"""Multi-host (multi-process) initialisation and filelist sharding.

The reference scales across nodes with MPI: every rank runs the same
driver and takes filelist slice ``i % size == rank``
(``run_average.py:13-16,38-39``; ``mpiexec -n X python run_average.py``).
The TPU-native equivalent is JAX's distributed runtime: one process per
host, ``jax.distributed.initialize`` wires them into one global device
mesh (collectives ride ICI within a slice and DCN across hosts), and the
filelist shards by ``jax.process_index()``.

Launch recipe (one command per host/process)::

    # host 0 (coordinator)
    JAX_COORDINATOR_ADDRESS=host0:7632 JAX_NUM_PROCESSES=2 \
        JAX_PROCESS_ID=0 python -m comapreduce_tpu.cli.run_average cfg.toml
    # host 1
    JAX_COORDINATOR_ADDRESS=host0:7632 JAX_NUM_PROCESSES=2 \
        JAX_PROCESS_ID=1 python -m comapreduce_tpu.cli.run_average cfg.toml

On managed clusters (Cloud TPU pods, SLURM), ``jax.distributed
.initialize()`` auto-detects all three values; when a known cluster
environment is detected the env vars are unnecessary. With no
multi-process indication at all the call is a no-op and the run stays
single-host.

IMPORTANT: this is *data-parallel* multi-host — each process takes its
own filelist shard and runs an independent program over its LOCAL
devices. Meshes for the per-file analysis/destriping must therefore be
built from ``jax.local_devices()``, never ``jax.devices()`` (which
becomes the global cross-host list after initialisation, and a
multi-controller program over divergent per-rank data would deadlock in
its collectives).
"""

from __future__ import annotations

import logging
import os
import time

__all__ = ["maybe_initialize_distributed", "rank_info",
           "straggler_barrier"]

logger = logging.getLogger("comapreduce_tpu")

_ENV_ADDR = ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS")
# presence of any of these marks a managed cluster where the no-arg
# jax.distributed.initialize() can auto-detect the topology. SLURM is
# deliberately NOT auto-detected: a single-process launch inside a
# multi-task batch allocation would block as coordinator waiting for
# tasks that never connect — SLURM users pass the explicit env triple.
_CLUSTER_ENV = ("TPU_WORKER_HOSTNAMES", "CLOUD_TPU_TASK_ID",
                "MEGASCALE_COORDINATOR_ADDRESS")


def maybe_initialize_distributed() -> bool:
    """Initialise the JAX distributed runtime when the environment
    indicates a multi-process launch; no-op otherwise.

    Indication: either the explicit triple — a coordinator address in
    ``JAX_COORDINATOR_ADDRESS`` (or ``COORDINATOR_ADDRESS``) plus
    ``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID`` — or a recognised managed
    cluster (Cloud TPU pod / SLURM), where the no-arg auto-detecting
    ``initialize()`` is used. Raises if a clearly-indicated multi-process
    launch fails to initialise — silently degrading to rank 0/1 would
    make every process run the full filelist and clobber shared outputs.
    Returns True when the distributed runtime is (now) initialised.
    """
    import jax

    if _distributed_is_initialized(jax):
        return True
    addr = next((os.environ[k] for k in _ENV_ADDR if os.environ.get(k)),
                None)
    n = os.environ.get("JAX_NUM_PROCESSES")
    pid = os.environ.get("JAX_PROCESS_ID")
    if addr and n and pid:
        # explicit indication: failure here must propagate — degrading to
        # rank 0/1 would duplicate the whole filelist on every process
        jax.distributed.initialize(coordinator_address=addr,
                                   num_processes=int(n),
                                   process_id=int(pid))
    elif any(os.environ.get(k) for k in _CLUSTER_ENV):
        # fuzzy indication (cluster-like env, e.g. a tunnelled single
        # chip also sets TPU_WORKER_HOSTNAMES): try auto-detection, fall
        # back to single-host when jax cannot resolve a topology
        try:
            jax.distributed.initialize()
        except (ValueError, RuntimeError) as err:
            logger.info("distributed auto-detect unavailable (%s); "
                        "running single-host", err)
            return False
    else:
        return False
    logger.info("distributed: process %d/%d",
                jax.process_index(), jax.process_count())
    return True


def _distributed_is_initialized(jax) -> bool:
    """``jax.distributed.is_initialized()`` exists only from jax 0.5;
    older versions expose the same fact as a non-None global client."""
    try:
        return bool(jax.distributed.is_initialized())
    except AttributeError:
        from jax._src import distributed

        return distributed.global_state.client is not None


def straggler_barrier(heartbeat_dir: str, rank: int, n_ranks: int,
                      timeout_s: float = 120.0, poll_s: float = 0.5,
                      heartbeat=None, clock=time.monotonic,
                      sleep=time.sleep) -> tuple[list, list]:
    """Pre-shard barrier over heartbeat files; returns
    ``(alive_ranks, dead_ranks)``.

    Every rank beats its own ``heartbeat.rank{r}.json`` on entering the
    barrier (``resilience.heartbeat``; pass this rank's own
    ``heartbeat``) and then polls for the siblings'. A sibling counts
    as ALIVE only when its heartbeat is observed to CHANGE during our
    polling — a new ``seq``/timestamp/mtime, or a file appearing — a
    liveness signal that no leftover can fake: a heartbeat file from a
    crashed rank (even one written seconds ago by the final beat of a
    dying process, or by a previous run a supervisor just relaunched
    over) never changes again, while an alive sibling re-beats at
    least every ticker period and on its own barrier entry. It is
    also immune to cross-host clock skew — a moving file is a moving
    file regardless of what its timestamps claim. The price is
    latency: proving a sibling alive takes until its next write, so
    ``timeout_s`` must comfortably exceed the fleet's ``heartbeat_s``
    ticker period (warned below when it does not).

    Ranks still unchanged at ``timeout_s`` are declared DEAD and the
    caller continues with its own static shard instead of deadlocking
    a collective against a rank that will never arrive (elastic
    claiming — the campaign default — makes this barrier unnecessary:
    survivors steal a dead rank's leases and finish its files in the
    same run). The barrier is advisory and read-only: it never blocks
    a healthy single-rank run (``n_ranks <= 1`` returns immediately)
    and a rank declared dead by mistake (a paused VM resuming late)
    costs nothing — the verdict is a log line, not a ledger entry.
    """
    from comapreduce_tpu.resilience.heartbeat import (HeartbeatWatch,
                                                      read_heartbeats)

    if heartbeat is not None:
        # our own barrier-entry beat doubles as the change siblings
        # polling right now are waiting to observe
        heartbeat.note(stage="multihost.barrier")
        period = getattr(heartbeat, "period_s", 0.0)
        if period and timeout_s <= 2 * period:
            logger.warning(
                "straggler barrier: timeout_s=%.0f is not comfortably "
                "above the heartbeat period (%.0f s) — healthy "
                "siblings may not beat within the window; raise "
                "straggler_timeout_s or lower heartbeat_s",
                timeout_s, period)
    if n_ranks <= 1:
        return [rank], []
    others = [r for r in range(n_ranks) if r != rank]

    # the ONE change-based liveness rule (resilience.heartbeat
    # .HeartbeatWatch, shared with the control-plane supervisor): the
    # first observe is the baseline scan — whatever is on disk NOW
    # proves nothing (it may be a dead rank's last beat); only change
    # from here on does. ttl_s = the whole barrier window, so a rank
    # proven alive once stays alive for the barrier's purposes.
    watch = HeartbeatWatch(ttl_s=max(timeout_s, 0.0), clock=clock)
    watch.observe(read_heartbeats(heartbeat_dir))
    alive: set = set()
    deadline = clock() + max(timeout_s, 0.0)
    while clock() < deadline and len(alive) < len(others):
        sleep(poll_s)
        verdicts = watch.observe(read_heartbeats(heartbeat_dir))
        alive |= {r for r in others
                  if verdicts.get(r) == HeartbeatWatch.ALIVE}
    dead = sorted(set(others) - alive)
    if dead:
        logger.warning(
            "straggler barrier: rank(s) %s missed the barrier within "
            "%.1f s (heartbeats in %s missing or stale); continuing "
            "DEGRADED — their static shards wait for the next launch "
            "(elastic claiming, the campaign default, would finish "
            "them this run)", dead, timeout_s, heartbeat_dir)
    return sorted(alive | {rank}), dead


def rank_info() -> tuple[int, int]:
    """(process_index, process_count) — the filelist-shard coordinates
    (reference ``run_average.py:38-39``).

    Resolution order: explicit ``COMAP_RANK``/``COMAP_NRANKS`` (set by
    ``cli/batchrun.py`` for coordinator-less single-node fan-out), then
    the jax distributed runtime after optional initialisation.
    Initialisation errors propagate (see
    :func:`maybe_initialize_distributed`); only a missing jax degrades to
    the single-process (0, 1)."""
    r, n = os.environ.get("COMAP_RANK"), os.environ.get("COMAP_NRANKS")
    if r and n:  # empty string == unset, like the vars above
        return int(r), int(n)
    try:
        import jax
    except ImportError:  # pragma: no cover - jax is a hard dep in practice
        return 0, 1
    maybe_initialize_distributed()
    return jax.process_index(), jax.process_count()
