"""Device-mesh construction for the framework's two parallel axes.

The canonical mesh is 2-D: ``('feed', 'time')``. The TOD reduction is data
parallel over feeds (the reference's MPI-rank-per-file split,
``run_average.py:38-39``); the destriper is sequence parallel over the
concatenated time axis (the reference's rank-owns-samples split,
``Destriper.py:217-263``). Either axis may be size 1; collapsing both gives
the single-chip program unchanged — the same code runs on one chip, a v4-8,
or a multi-host slice (DCN just extends the mesh).
"""

from __future__ import annotations

import numpy as np
from jax.sharding import Mesh

__all__ = ["feed_time_mesh", "local_mesh", "flat_axis_size"]

AXES = ("feed", "time")


def feed_time_mesh(devices=None, n_feed: int | None = None) -> Mesh:
    """Build a ``('feed', 'time')`` mesh over ``devices``.

    ``n_feed`` fixes the feed-axis size (must divide the device count);
    default splits devices as evenly as possible with feed >= time, which
    suits the common case of more feeds than destriper shards.
    """
    import jax

    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    n = devices.size
    if n_feed is None:
        n_feed = 1
        for cand in range(int(np.sqrt(n)), 0, -1):
            if n % cand == 0:
                n_feed = max(cand, n // cand)
                break
    if n % n_feed != 0:
        raise ValueError(f"n_feed={n_feed} does not divide {n} devices")
    return Mesh(devices.reshape(n_feed, n // n_feed), AXES)


def local_mesh() -> Mesh:
    """A 1x1 mesh on the first local device (single-chip path)."""
    import jax

    return Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1), AXES)


def flat_axis_size(mesh: Mesh) -> int:
    """Total devices in the mesh — the shard count when both axes gang up
    on one array axis (the destriper's flat time axis)."""
    return int(np.prod(list(mesh.shape.values())))
