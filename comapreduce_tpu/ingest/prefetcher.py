"""Background-thread read-ahead with a bounded queue.

One reader thread walks the (already rank-sharded) filelist in order,
decodes each file with the caller's ``loader`` and parks the result in
a bounded queue; the consumer iterates ready payloads while the worker
reads ahead. HDF5 access stays on a single thread — h5py serialises
library calls behind a global lock anyway, so extra reader threads buy
nothing while losing the trivial ordering guarantee.

Failure contract: a loader exception is captured into that file's
:class:`PrefetchItem` and the worker moves on — one bad file never
kills the queue or the files behind it (the consumer maps it onto the
pipeline's per-file "BAD FILE" fault tolerance). Breaking out of the
consumer loop (or ``close()``) stops the worker promptly: every
blocking queue operation polls a stop event. A worker that ignores the
stop event (a loader hung inside C code) is abandoned after the join
timeout: the prefetcher is poisoned (iterating it again raises) and
the in-flight file is reported through ``on_hang`` for the quarantine
ledger. With a ``resilience.Watchdog`` each read attempt additionally
runs under the ``ingest.read`` soft/hard deadline and a hung attempt
is cancelled (``HangError``) instead of wedging the worker at all.

:func:`iter_serial` is the same iteration contract without the thread —
the serial fallback and the prefetched path share one code path in
every consumer.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Iterator

from comapreduce_tpu.telemetry import TELEMETRY

__all__ = ["Prefetcher", "PrefetchItem", "iter_serial"]

logger = logging.getLogger("comapreduce_tpu")

_POLL_S = 0.1  # stop-event poll period for blocking queue ops


@dataclass
class PrefetchItem:
    """One file's ingest result: exactly one of ``payload``/``error``."""

    index: int
    filename: str
    payload: Any = None
    error: BaseException | None = None
    read_s: float = 0.0     # wall seconds spent decoding (0 on cache hit)
    cached: bool = False    # served from the BlockCache
    retries: int = 0        # transient-failure re-attempts burned
    # True marks a failure of the file *listing* itself, not of one
    # file: consumers must abort (the serial path's iterator raises at
    # the same point), never map it onto per-file fault tolerance
    fatal: bool = False

    def result(self):
        """Payload, re-raising the captured per-file error."""
        if self.error is not None:
            raise self.error
        return self.payload


def _load_one(index: int, filename: str, loader, cache,
              retry=None, sleep=None, watchdog=None) -> PrefetchItem:
    """Shared load step (cache probe -> loader -> cache fill) used by
    both the worker thread and :func:`iter_serial`. ``retry`` (a
    ``resilience.RetryPolicy``) re-attempts transient loader failures
    with backoff before the error is captured into the item — applied
    here so the serial and prefetched paths share one retry site.
    ``watchdog`` (a ``resilience.Watchdog``) runs each attempt under
    the ``ingest.read`` deadline INSIDE the retry net: a read cancelled
    at the hard deadline (``HangError``) is retried with a fresh budget
    like any transient, and only then captured into the item."""
    t0 = time.perf_counter()
    retries = 0
    try:
        key = None
        if cache is not None:
            payload = cache.get(filename)
            if payload is not None:
                read_s = time.perf_counter() - t0
                TELEMETRY.event_span("ingest.read", read_s,
                                     unit=filename, cached=True)
                return PrefetchItem(index, filename, payload=payload,
                                    read_s=read_s, cached=True)
            # identity BEFORE the (possibly long) decode: a file
            # rewritten mid-read must not pair its new mtime with the
            # stale content we are about to load
            from comapreduce_tpu.ingest.cache import file_key

            key = file_key(filename)
        if watchdog is not None:
            def attempt(fname=filename, _loader=loader):
                return watchdog.call(_loader, "ingest.read", unit=fname,
                                     args=(fname,))
        else:
            def attempt(fname=filename, _loader=loader):
                return _loader(fname)
        if retry is not None:
            from comapreduce_tpu.resilience.retry import retry_call

            payload, retries = retry_call(
                attempt, retry, key=filename,
                label=f"ingest.read {filename}",
                **({"sleep": sleep} if sleep is not None else {}))
        else:
            payload = attempt()
        # only decoded-payload dicts are cacheable: a live store (lazy
        # h5py handle) must never reach the pickle-based disk spill
        if cache is not None and isinstance(payload, dict):
            cache.put(filename, payload, key=key)
        read_s = time.perf_counter() - t0
        # the read's TRUE interval, emitted on the thread that did the
        # I/O — campaign_report's read/compute overlap integrates
        # these span intersections, so they must carry actual read
        # time, not the consumer-side bookkeeping moment
        TELEMETRY.event_span("ingest.read", read_s, unit=filename,
                             retries=retries)
        return PrefetchItem(index, filename, payload=payload,
                            read_s=read_s, retries=retries)
    except Exception as exc:  # noqa: BLE001 — per-file fault tolerance
        read_s = time.perf_counter() - t0
        TELEMETRY.event_span("ingest.read", read_s, unit=filename,
                             skipped=True, error=type(exc).__name__)
        return PrefetchItem(index, filename, error=exc, read_s=read_s,
                            retries=getattr(exc, "_retries", retries))


def iter_serial(filenames: Iterable[str], loader: Callable[[str], Any],
                cache=None, retry=None,
                watchdog=None) -> Iterator[PrefetchItem]:
    """The serial path: identical items, read lazily at ``next()``."""
    for i, fname in enumerate(filenames):
        yield _load_one(i, fname, loader, cache, retry,
                        watchdog=watchdog)


class Prefetcher:
    """Iterate ``PrefetchItem``s over ``filenames``, reading ahead.

    Parameters
    ----------
    filenames:
        Iterable of paths (consumed lazily, so a generator — e.g. a
        lazy rank shard — is fine).
    loader:
        ``path -> payload``; runs on the worker thread. Exceptions are
        captured per-file.
    depth:
        Queue bound: at most ``depth`` decoded payloads wait in the
        queue, plus one in the worker's hand (blocked on a full queue)
        and the one the consumer currently processes — size host
        memory for ``depth + 2`` decoded files.
    cache:
        Optional :class:`~comapreduce_tpu.ingest.cache.BlockCache`.

    Use as an iterator (it closes itself when exhausted *or* when the
    consumer breaks early) or as a context manager for explicit scope.
    ``depth_log`` records ``(t_rel_s, qsize)`` after every enqueue —
    the bench's queue-occupancy-over-time observable.
    """

    def __init__(self, filenames: Iterable[str],
                 loader: Callable[[str], Any], depth: int = 2,
                 cache=None, name: str = "ingest-prefetch",
                 retry=None, watchdog=None, on_hang=None):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self.depth = int(depth)
        self._loader = loader
        self._cache = cache
        self._retry = retry
        self._watchdog = watchdog
        # called with the in-flight filename when close() abandons a
        # worker that never returned (the resilience layer ledgers it
        # as a hang); the prefetcher is then POISONED: iterating it
        # again would consume from a half-dead queue
        self._on_hang = on_hang
        self._poisoned = False
        self._inflight: str | None = None
        self._queue: queue.Queue = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._sentinel = object()
        self.depth_log: list[tuple[float, int]] = []
        self._t0 = time.perf_counter()
        self._thread = threading.Thread(
            target=self._work, args=(iter(filenames),), name=name,
            daemon=True)
        self._thread.start()

    # -- worker ------------------------------------------------------------
    def _put(self, item) -> bool:
        """Blocking put that gives up when the consumer is gone."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=_POLL_S)
                return True
            except queue.Full:
                continue
        return False

    def _work(self, files: Iterator[str]) -> None:
        index = 0
        try:
            while not self._stop.is_set():
                try:
                    fname = next(files)
                except StopIteration:
                    break
                except Exception as exc:  # noqa: BLE001 — a broken
                    # filelist generator must surface to the consumer,
                    # not vanish with the thread; fatal: the serial
                    # path would raise out of its loop here, not skip
                    # one file
                    self._put(PrefetchItem(index, "<filelist>",
                                           error=exc, fatal=True))
                    break
                # backoff sleeps poll the stop event; wait() returning
                # True (stop set) ABORTS the retry schedule, so a
                # closing consumer is never held behind it — neither by
                # the sleeps nor by zero-delay re-attempts of a dying
                # loader
                self._inflight = fname
                item = _load_one(index, fname, self._loader, self._cache,
                                 self._retry, sleep=self._stop.wait,
                                 watchdog=self._watchdog)
                self._inflight = None
                if not self._put(item):
                    return
                depth = self._queue.qsize()
                self.depth_log.append((time.perf_counter() - self._t0,
                                       depth))
                # queue depth as a counter track: depth pinned at the
                # bound = reads are ahead (healthy); pinned at 0 = the
                # consumer is read-starved
                TELEMETRY.gauge("ingest.queue_depth", depth)
                index += 1
        except BaseException as exc:  # noqa: BLE001 — even SystemExit
            # from a loader must reach the consumer as a FATAL item:
            # sentinel-after-crash would read as a clean (truncated) end
            self._put(PrefetchItem(index, "<worker>", error=exc,
                                   fatal=True))
            raise
        finally:
            # ALWAYS mark end-of-stream (after any fatal item above) so
            # the consumer never blocks on a dead worker
            self._put(self._sentinel)

    def _close_timeout(self) -> float:
        """close()'s default join budget, derived AT CLOSE TIME: when a
        watchdog supervises ``ingest.read``, a read attempt cannot
        outlive its hard deadline, so the worker gets every attempt's
        full budget (+grace) before it is declared hung. Resolved here
        rather than at construction because adaptive extension can
        legally GROW the hard deadline mid-run — a read still inside
        its (extended) budget must never be ledgered as a hang by the
        shutdown path racing it."""
        timeout = 10.0
        if self._watchdog is not None:
            dl = self._watchdog.deadline_for("ingest.read")
            if dl is not None and dl.hard_s is not None:
                attempts = 1 + getattr(self._retry, "max_retries", 0)
                timeout = max(10.0, attempts * (
                    dl.hard_s + getattr(self._watchdog, "grace_s",
                                        0.0)))
        return timeout

    # -- consumer ----------------------------------------------------------
    def __iter__(self) -> Iterator[PrefetchItem]:
        try:
            while True:
                if self._poisoned:
                    # a previous close() abandoned a hung worker: its
                    # queue may still fill with stale results at any
                    # moment — consuming them would silently mix files
                    # from before and after the hang
                    raise RuntimeError(
                        "Prefetcher is poisoned (its worker hung and "
                        "was abandoned); build a fresh Prefetcher")
                try:
                    item = self._queue.get(timeout=_POLL_S)
                except queue.Empty:
                    if not self._thread.is_alive() and self._queue.empty():
                        if self._stop.is_set():
                            return  # closed by the consumer
                        # worker died without its sentinel: a silent
                        # clean-looking end would truncate the run (a
                        # short results list with nothing flagged) —
                        # fail loudly like the serial path would
                        raise RuntimeError(
                            "Prefetcher worker died without completing "
                            "the filelist")
                    continue
                if item is self._sentinel:
                    return
                yield item
        finally:
            self.close()

    def close(self, timeout: float | None = None) -> None:
        """Stop the worker and join it. Idempotent; safe mid-iteration
        (the early-exit path of a breaking consumer).

        When the worker does not stop within ``timeout`` (default:
        10 s, or the full per-file retry x hard-deadline budget when a
        watchdog supervises the reads — a loader stuck in HDF5/NFS C
        code ignores the stop event) it is ABANDONED: the prefetcher
        is marked poisoned — later iteration raises instead of
        consuming from the half-dead queue — and ``on_hang`` is
        invoked with the in-flight filename so the resilience layer
        can ledger the hang (``rejected``: re-attempted next run, so a
        slow-but-healthy read mis-flagged at shutdown costs one run's
        deferral, never the file)."""
        if timeout is None:
            timeout = self._close_timeout()
        self._stop.set()
        # drain so a worker blocked on a full queue sees the stop event
        # on its next put poll rather than after a timeout
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                inflight = self._inflight
                self._poisoned = True
                logger.warning(
                    "Prefetcher: worker did not stop within %.1f s "
                    "(loader stuck in C code?); abandoning it%s and "
                    "poisoning the prefetcher", timeout,
                    f" mid-read of {inflight}" if inflight else "")
                if inflight and self._on_hang is not None:
                    try:
                        self._on_hang(inflight)
                    except Exception:  # pragma: no cover - ledger I/O
                        logger.exception(
                            "Prefetcher: on_hang callback failed for %s",
                            inflight)

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
