"""File loaders + shared streams for the pipeline's two file kinds.

Loaders run on the prefetcher's worker thread and return *payloads*:
either a live store object (the lazy serial Level-1 case, which keeps
an open h5py handle) or a decoded payload dict
(:meth:`HDF5Store.export_payload`) that is cache- and pickle-friendly.
The streams rebuild a fresh store wrapper per consumption, so a cached
payload handed out twice never aliases mutable wrapper state (the
underlying numpy arrays ARE shared — consumers must not mutate them in
place, and none do: every stage computes new arrays).

``level1_stream``/``level2_stream`` are the ONE iteration code path for
serial and prefetched ingest (``prefetch=0`` selects the inline serial
read; ``>= 1`` the background reader) — consumers cannot drift apart.
"""

from __future__ import annotations

from typing import Iterator

from comapreduce_tpu.data.level import COMAPLevel1, COMAPLevel2
from comapreduce_tpu.ingest.prefetcher import (PrefetchItem, Prefetcher,
                                               iter_serial)
from comapreduce_tpu.ops.precision import cast_payload_tod

__all__ = ["load_level1", "load_level2", "level1_stream", "level2_stream"]


def load_level1(filename: str, eager_tod: bool = True,
                tod_dtype: str = "f32"):
    """Read a Level-1 file. ``eager_tod=True`` materialises the big
    ``spectrometer/tod`` dataset here — on the prefetcher's worker
    thread that IS the read being overlapped — and closes the file;
    ``False`` keeps the reference behaviour (lazy handle, open file).

    ``tod_dtype="bf16"`` narrows the exported TOD payload on the
    worker (precision policy, OPERATIONS.md §15): the ``BlockCache``
    then holds half the bytes and every downstream transfer — the
    prefetch queue, ``prefetch_to_device``'s H2D copies — ships half
    the bytes. A lazy handle (``eager_tod=False``) is returned as-is:
    it is never cached, so there is nothing to narrow.

    ``synth://`` virtual scenario members (``synthetic/memsource.py``)
    are generated in memory here, on the same worker thread a disk read
    would use — the rest of the ingest machinery (cache, retry,
    watchdog, prefetch queue) cannot tell the difference. There is no
    handle to keep lazy, so the eager/lazy split collapses: lazy
    consumers get the materialised store, eager ones its payload."""
    if filename.startswith("synth://"):
        from comapreduce_tpu.synthetic.memsource import load_virtual

        data = load_virtual(filename)
        if not eager_tod:
            return data
        return cast_payload_tod(data.export_payload(), tod_dtype)
    data = COMAPLevel1()
    data.read(filename)
    if not eager_tod:
        return data
    for path in data.lazy_paths:
        if path in data:
            data.materialise(path)
    data.close()
    return cast_payload_tod(data.export_payload(), tod_dtype)


def load_level2(filename: str, tod_dtype: str = "f32"):
    """Read a Level-2 file into a decoded payload dict (``tod_dtype``
    as in :func:`load_level1` — bf16 narrows the ``averaged_tod`` /
    ``frequency_binned`` TOD arrays, weights stay f32)."""
    lvl2 = COMAPLevel2(filename=filename)
    return cast_payload_tod(lvl2.export_payload(), tod_dtype)


def _rebuild(cls, payload, **kwargs):
    """Payload -> fresh store wrapper; live stores pass through."""
    if isinstance(payload, dict) and "data" in payload and \
            "attrs" in payload:
        store = cls(**kwargs)
        store.adopt_payload(payload)
        return store
    return payload


def _stream(filenames, loader, rebuild, prefetch: int = 0,
            cache=None, retry=None, chaos=None, watchdog=None,
            on_hang=None) -> Iterator[PrefetchItem]:
    if chaos is not None:
        # fault injection wraps the loader INSIDE the retry net — an
        # injected read error exercises the same retry/quarantine path
        # a real one would (resilience/chaos.py). The cache is disabled
        # for the drill: a poisoned payload written to a (possibly
        # disk-spilled) cache would outlive the drill and serve NaNs to
        # a later clean run as a cache hit, bypassing chaos.decide.
        # The watchdog wraps OUTSIDE the chaos loader (below, via
        # _load_one): an injected hang must be cancelled by the same
        # deadline a real one would be.
        loader = chaos.wrap_loader(loader)
        cache = None
    if prefetch >= 1:
        items = Prefetcher(filenames, loader, depth=prefetch, cache=cache,
                           retry=retry, watchdog=watchdog,
                           on_hang=on_hang)
    else:
        items = iter_serial(filenames, loader, cache, retry=retry,
                            watchdog=watchdog)
    try:
        for item in items:
            if item.fatal:
                # a broken file LISTING aborts the run on the serial
                # path (the iterator raises mid-loop); the prefetched
                # path must fail identically, not truncate the run as
                # one "bad file"
                raise item.error
            if item.error is None:
                item.payload = rebuild(item.payload)
            yield item
    finally:
        # deterministic worker shutdown: consumers call .close() on
        # this generator (or exhaust it); either way the Prefetcher
        # must not linger decoding ahead behind a kept-alive traceback
        close = getattr(items, "close", None)
        if close is not None:
            close()


def level1_stream(filenames, prefetch: int = 0, cache=None,
                  eager_tod: bool = True, eager_for=None,
                  retry=None, chaos=None, watchdog=None,
                  on_hang=None,
                  tod_dtype: str = "f32") -> Iterator[PrefetchItem]:
    """Ordered ``PrefetchItem``s of :class:`COMAPLevel1` views.

    The TOD is materialised on the worker when prefetching (that is the
    read being overlapped) or when a cache is present (a lazy handle
    cannot be cached); the plain serial cache-less path keeps it lazy,
    exactly the pre-ingest behaviour. ``eager_tod=False`` always wins:
    it keeps reads lazy even with a cache configured (Level-1 payloads
    then simply bypass the cache — the explicit RAM ceiling outranks
    cache hits).

    ``eager_for`` (``path -> bool``) vetoes materialisation per file —
    the Runner passes its resume test, so a file whose whole stage
    chain will be skipped is not read end to end just to be dropped.
    A lazily-read file is never cached (live h5py handles are neither
    shareable nor picklable).

    ``retry`` (a ``resilience.RetryPolicy``) re-attempts transient read
    failures with backoff before a file takes its error slot; ``chaos``
    (a ``resilience.ChaosMonkey``) injects faults around the loader;
    ``watchdog`` (a ``resilience.Watchdog``) runs each attempt under
    the ``ingest.read`` soft/hard deadline (a hung read is cancelled,
    retried, and only then captured); ``on_hang`` is the prefetcher's
    abandoned-worker callback (see ``Prefetcher``) — all off (None) by
    default.

    ``tod_dtype`` ("f32" default, "bf16") is the precision-policy
    storage dtype for TOD payloads (see :func:`load_level1`). The
    conversion runs in the loader, i.e. BEFORE the cache: a given
    ``BlockCache`` instance is dtype-homogeneous per run (its key is
    ``(path, mtime)`` — do not share one cache across policies).
    """
    eager = eager_tod and (prefetch >= 1 or cache is not None)

    def loader(path):
        eager_this = eager and (eager_for is None or eager_for(path))
        return load_level1(path, eager_tod=eager_this,
                           tod_dtype=tod_dtype)

    return _stream(filenames, loader,
                   lambda p: _rebuild(COMAPLevel1, p),
                   prefetch=prefetch, cache=cache, retry=retry,
                   chaos=chaos, watchdog=watchdog, on_hang=on_hang)


def level2_stream(filenames, prefetch: int = 0, cache=None,
                  retry=None, chaos=None, watchdog=None,
                  on_hang=None,
                  tod_dtype: str = "f32") -> Iterator[PrefetchItem]:
    """Ordered ``PrefetchItem``s of :class:`COMAPLevel2` views (the
    destriper's filelist reader; always fully decoded). ``retry``/
    ``chaos``/``watchdog``/``on_hang``/``tod_dtype`` as in
    :func:`level1_stream` — with a bf16 policy the shared multi-band
    cache holds half the TOD bytes, so twice the filelist fits before
    the LRU starts evicting between band passes."""
    def loader(path):
        return load_level2(path, tod_dtype=tod_dtype)

    return _stream(filenames, loader,
                   lambda p: _rebuild(COMAPLevel2, p, filename=""),
                   prefetch=prefetch, cache=cache, retry=retry,
                   chaos=chaos, watchdog=watchdog, on_hang=on_hang)
