"""LRU-by-bytes block cache for decoded HDF5 payloads.

Keys are ``(abspath, mtime_ns)``: a file rewritten in place (a Level-2
checkpoint updated by a later stage, a re-generated synthetic fixture)
gets a fresh key and the stale entry is dropped on the next lookup.
Values are arbitrary decoded payloads — typically the ``(data, attrs)``
dict pair of an :class:`~comapreduce_tpu.data.hdf5io.HDF5Store` — whose
size is accounted by :func:`payload_nbytes`.

Eviction is LRU by total bytes. With ``spill_dir`` set, evicted entries
are pickled to disk instead of discarded; a later ``get`` restores them
(and promotes them back into memory), so a multi-pass workload larger
than RAM still skips the HDF5 re-decode. Spill files self-identify
their key — a stale spill (file changed since) is ignored and deleted.

Thread-safe: the prefetcher worker thread populates the cache while the
consumer reads it.

Precision note (OPERATIONS.md §15): payload dtype is whatever the
loader produced — under a bf16 TOD policy the cached TOD arrays are
bf16 and the same ``cache_mb`` budget holds twice the filelist. The
key does NOT encode the policy, so one cache instance is
dtype-homogeneous per run; do not share a spill dir between runs with
different ``tod_dtype`` settings.
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import threading
from collections import OrderedDict

import numpy as np

from comapreduce_tpu.telemetry import TELEMETRY

__all__ = ["BlockCache", "payload_nbytes", "file_key"]

logger = logging.getLogger("comapreduce_tpu")

_SPILL_SUFFIX = ".ingest.pkl"


def payload_nbytes(payload) -> int:
    """Recursive byte estimate of a payload: ndarrays count their
    buffers, containers recurse, everything else counts a nominal 64."""
    if isinstance(payload, np.ndarray):
        return int(payload.nbytes)
    if isinstance(payload, dict):
        return sum(payload_nbytes(v) for v in payload.values())
    if isinstance(payload, (list, tuple, set, frozenset)):
        return sum(payload_nbytes(v) for v in payload)
    if isinstance(payload, (bytes, bytearray, str)):
        return len(payload)
    return 64


def file_key(path: str) -> tuple:
    """Cache key of ``path``: ``(abspath, mtime_ns)``.

    Raises ``OSError`` when the file does not exist — the caller's
    per-file fault tolerance owns that, not the cache. ``synth://``
    virtual scenario members (``synthetic/memsource.py``) have no inode
    and are immutable by construction (content is a pure function of
    the path), so the path alone is the identity.
    """
    if path.startswith("synth://"):
        return path, 0
    ap = os.path.abspath(path)
    return ap, os.stat(ap).st_mtime_ns


class BlockCache:
    """Byte-bounded LRU cache with optional on-disk spill.

    Parameters
    ----------
    max_bytes:
        In-memory budget. Entries larger than the whole budget are
        never held in memory (they go straight to spill, or are
        dropped).
    spill_dir:
        When set, evicted entries are pickled here and restored on a
        later ``get``. Created on first use.
    durable:
        fsync each spill file before its atomic rename (default).
        A spill that survives a crash is consulted by the NEXT run's
        warm start; without the fsync a power cut can commit the
        rename ahead of the data and leave a zero-length .pkl under a
        valid name (it would be dropped as unreadable — safe — but a
        torn-yet-unpicklable payload under a matching key is the kind
        of corruption ``_load_spill``'s key check cannot see).
        ``durable=False`` restores the lower-latency spill.
    """

    def __init__(self, max_bytes: int, spill_dir: str = "",
                 durable: bool = True):
        self.max_bytes = int(max_bytes)
        self.spill_dir = spill_dir
        self.durable = bool(durable)
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, tuple] = OrderedDict()
        # keys with a valid spill file on disk: content per key is
        # immutable (the key embeds mtime), so a re-evicted promoted
        # entry must not pay the multi-GB pickle again
        self._on_disk: set = set()
        self._bytes = 0
        self.stats = {"hits": 0, "misses": 0, "evictions": 0,
                      "spills": 0, "spill_hits": 0}

    # -- internals ---------------------------------------------------------
    def _spill_path(self, key: tuple) -> str:
        digest = hashlib.sha1(repr(key[0]).encode()).hexdigest()
        return os.path.join(self.spill_dir, digest + _SPILL_SUFFIX)

    def _evict_locked(self, need: int = 0) -> list:
        """Pop LRU entries until ``need`` fits; returns the victims so
        the caller can spill them AFTER releasing the lock — a multi-GB
        pickle write under the lock would stall the prefetch worker and
        the consumer against each other, serialising exactly the I/O
        and compute this subsystem overlaps."""
        victims = []
        while self._entries and self._bytes + need > self.max_bytes:
            key, (payload, nbytes) = self._entries.popitem(last=False)
            self._bytes -= nbytes
            self.stats["evictions"] += 1
            victims.append((key, payload))
        return victims

    def _spill(self, victims: list) -> None:
        """Pickle evicted entries to ``spill_dir`` (lock NOT held);
        entries whose (immutable-per-key) content is already on disk —
        a promoted spill hit being re-evicted — skip the rewrite."""
        if not self.spill_dir:
            return
        for key, payload in victims:
            with self._lock:
                if key in self._on_disk:
                    continue
            try:
                from comapreduce_tpu.resilience.integrity import (
                    committed_replace)

                os.makedirs(self.spill_dir, exist_ok=True)
                tmp = self._spill_path(key) + ".tmp"
                with open(tmp, "wb") as f:
                    pickle.dump((key, payload), f,
                                protocol=pickle.HIGHEST_PROTOCOL)
                committed_replace(tmp, self._spill_path(key),
                                  kind="spill", durable=self.durable)
                with self._lock:
                    self.stats["spills"] += 1
                    self._on_disk.add(key)
                if TELEMETRY.enabled:  # payload_nbytes walk gated
                    TELEMETRY.counter("ingest.cache.spill_bytes",
                                      payload_nbytes(payload))
            except OSError as exc:  # spill is best-effort
                logger.warning("BlockCache: spill failed for %s (%s)",
                               key[0], exc)

    def _load_spill(self, key: tuple):
        from comapreduce_tpu.resilience.integrity import (
            CorruptArtifactError, drop_sidecar, verify_file)

        path = self._spill_path(key)
        try:
            # verify BEFORE unpickling: a rotted spill entry must cost
            # one cache miss (re-read from Level-1), never feed damaged
            # bytes to pickle — and certainly never reach a solve
            verify_file(path, kind="spill")
        except CorruptArtifactError as exc:
            logger.warning("BlockCache: corrupt spill for %s dropped "
                           "(%s); re-reading from source", key[0], exc)
            try:
                os.unlink(path)
            except OSError:
                pass
            drop_sidecar(path)
            with self._lock:
                self._on_disk.discard(key)
            return None
        try:
            with open(path, "rb") as f:
                stored_key, payload = pickle.load(f)
        except (OSError, pickle.UnpicklingError, EOFError):
            return None
        if stored_key != key:  # file changed since the spill: stale
            try:
                os.unlink(path)
            except OSError:
                pass
            drop_sidecar(path)
            with self._lock:
                self._on_disk.discard(stored_key)
            return None
        with self._lock:
            self._on_disk.add(key)
        return payload

    # -- public API --------------------------------------------------------
    @property
    def current_bytes(self) -> int:
        return self._bytes

    def get(self, path: str):
        """Cached payload for ``path`` at its *current* mtime, or None."""
        try:
            key = file_key(path)
        except OSError:
            return None
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self._entries.move_to_end(key)
                self.stats["hits"] += 1
                TELEMETRY.counter("ingest.cache.hits")
                return hit[0]
            # a stale same-path entry (older mtime) is dead weight: drop
            for k in [k for k in self._entries if k[0] == key[0]]:
                _, nb = self._entries.pop(k)
                self._bytes -= nb
        if self.spill_dir:
            payload = self._load_spill(key)
            if payload is not None:
                with self._lock:
                    self.stats["hits"] += 1
                    self.stats["spill_hits"] += 1
                TELEMETRY.counter("ingest.cache.hits", spill=True)
                # promote back into memory — but an oversized payload
                # would only bounce straight back out through another
                # full pickle write; leave those on disk
                if payload_nbytes(payload) <= self.max_bytes:
                    self.put(path, payload, key=key)
                return payload
        with self._lock:
            self.stats["misses"] += 1
        TELEMETRY.counter("ingest.cache.misses")
        return None

    def put(self, path: str, payload, nbytes: int | None = None,
            key: tuple | None = None) -> None:
        """Insert ``payload`` for ``path``; evicts LRU entries over
        budget. Oversized payloads (> the whole budget) go straight to
        spill (when configured) and are never held in memory.

        ``key`` lets the caller pin the identity observed BEFORE a slow
        decode: stat'ing here would pair a file rewritten mid-read with
        its stale decoded content (see ``prefetcher._load_one``).
        """
        if key is None:
            try:
                key = file_key(path)
            except OSError:
                return
        nbytes = payload_nbytes(payload) if nbytes is None else int(nbytes)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            if nbytes > self.max_bytes:
                # never resident: spill directly without evicting the
                # (smaller, hotter) entries already in memory
                self.stats["evictions"] += 1
                victims = [(key, payload)]
            else:
                victims = self._evict_locked(need=nbytes)
                self._entries[key] = (payload, nbytes)
                self._bytes += nbytes
        self._spill(victims)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
