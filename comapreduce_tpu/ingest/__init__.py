"""Streaming ingest: overlap HDF5 I/O with TPU compute.

The reference pipeline is I/O-dominated: each Level-1 observation is a
multi-GB HDF5 file, and the per-file stage loop reads each file to
completion before any compute starts — the accelerator idles for the
whole read. This subsystem stages ingestion the way massively parallel
map-makers do (MAPPRAISER, arXiv:2112.03370):

- :class:`Prefetcher` — a background reader thread with a *bounded*
  queue that reads ahead over the rank-sharded filelist and yields
  ready payloads in filelist order. Worker exceptions are captured and
  delivered per-file (never queue-fatal), and breaking out of the
  consumer loop shuts the worker down cleanly.
- :class:`BlockCache` — an LRU-by-bytes cache of decoded payloads keyed
  on ``(path, mtime)`` with optional on-disk spill, so multi-pass
  workloads (four destriper bands over one filelist, a re-run over
  files just reduced) skip redundant HDF5 decode.
- :func:`prefetch_to_device` — host→device double-buffering:
  ``jax.device_put`` of the next block is issued while the current one
  computes (the ``flax.jax_utils.prefetch_to_device`` idiom), aware of
  mesh shardings via :mod:`comapreduce_tpu.parallel.axes`.
- :func:`level1_stream` / :func:`level2_stream` — the shared file
  iteration used by both the serial fallback and the prefetched path,
  so the two can never drift apart (``Runner.run_tod`` and
  ``mapmaking.leveldata.read_comap_data`` both consume them).

Config surface (``IngestConfig``): ``prefetch`` (queue depth; 0 keeps
the serial path), ``cache_mb`` (0 disables the cache), ``spill_dir``.
See ``docs/ingest.md`` for the design and knobs. The precision policy
(``PrecisionPolicy``, OPERATIONS.md §15) rides this subsystem: with
``tod_dtype = "bf16"`` the loaders narrow TOD payloads on the worker
thread, so cache bytes, queue bytes, and the H2D transfer the
``ingest.h2d.bytes`` counter meters all halve.
"""

from comapreduce_tpu.ingest.cache import BlockCache, payload_nbytes  # noqa: F401
from comapreduce_tpu.ingest.config import IngestConfig  # noqa: F401
from comapreduce_tpu.ingest.device_buffer import prefetch_to_device  # noqa: F401
from comapreduce_tpu.ingest.prefetcher import (  # noqa: F401
    Prefetcher,
    PrefetchItem,
    iter_serial,
)
from comapreduce_tpu.ingest.loaders import (  # noqa: F401
    level1_stream,
    level2_stream,
    load_level1,
    load_level2,
)
