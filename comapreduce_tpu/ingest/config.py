"""Ingest configuration: the ``ingest: {prefetch: N, cache_mb: M}`` knob.

One small value object shared by every ingest consumer (the pipeline
Runner's TOML ``[ingest]`` table, the destriper driver's ``[Inputs]``
keys) so the knob names cannot drift between entry points.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["IngestConfig"]


@dataclass(frozen=True)
class IngestConfig:
    """Knobs for the streaming ingest subsystem.

    prefetch:
        Read-ahead queue depth. 0 (default) keeps the serial path —
        files are read inline on the consumer thread, exactly the
        pre-ingest behaviour. ``>= 1`` starts the background reader.
    cache_mb:
        In-memory :class:`~comapreduce_tpu.ingest.cache.BlockCache`
        budget in MiB; 0 disables caching.
    spill_dir:
        Optional directory for on-disk spill of evicted cache entries.
    eager_tod:
        Prefetched Level-1 reads materialise the big
        ``spectrometer/tod`` dataset on the worker thread (that *is*
        the read being overlapped); the serial path keeps it lazy as
        before. Only consulted when ``prefetch >= 1``.
    compile_cache_dir:
        JAX persistent compilation cache directory (empty = off).
        Compiled programs are reused across processes, so steady-state
        campaign runs never XLA-compile on the critical path — and the
        ``[campaign] warm_compile`` AOT warm-up lands its results here
        (docs/OPERATIONS.md §9).
    writeback:
        Async Level-2 writeback queue depth. 0 (default) keeps the
        synchronous checkpoint write; ``>= 1`` snapshots each stage
        checkpoint to host and commits it on an ordered background
        writer (``data/writeback.py``) with a per-file flush barrier —
        resume/quarantine/kill semantics unchanged, stage compute
        overlaps the write. Size host memory for ``writeback + 1``
        Level-2 snapshots.
    """

    prefetch: int = 0
    cache_mb: float = 0.0
    spill_dir: str = ""
    eager_tod: bool = True
    compile_cache_dir: str = ""
    writeback: int = 0

    def __post_init__(self):
        # normalise once, here, instead of at every consumer: INI
        # coercion turns 'prefetch : none' (or an empty value) into
        # None, and None must mean "disabled", not a downstream
        # TypeError; negative values clamp to disabled likewise
        object.__setattr__(self, "prefetch",
                           max(int(self.prefetch or 0), 0))
        object.__setattr__(self, "cache_mb",
                           max(float(self.cache_mb or 0.0), 0.0))
        object.__setattr__(self, "spill_dir", str(self.spill_dir or ""))
        object.__setattr__(self, "eager_tod",
                           True if self.eager_tod is None
                           else bool(self.eager_tod))
        object.__setattr__(self, "compile_cache_dir",
                           str(self.compile_cache_dir or ""))
        object.__setattr__(self, "writeback",
                           max(int(self.writeback or 0), 0))

    # the knob names, once — every config entry point (TOML [ingest]
    # table, INI [Inputs] keys, CLI flags) extracts against this tuple
    KNOBS = ("prefetch", "cache_mb", "spill_dir", "eager_tod",
             "compile_cache_dir", "writeback")

    @classmethod
    def from_mapping(cls, mapping) -> "IngestConfig":
        """Pick the ingest knobs out of a wider config mapping (an INI
        ``[Inputs]`` section, say), ignoring unrelated keys."""
        return cls(**{k: mapping[k] for k in cls.KNOBS if k in mapping})

    @classmethod
    def coerce(cls, value) -> "IngestConfig":
        """Build from None / dict / IngestConfig (config-file plumbing)."""
        if value is None:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            known = {k: value[k] for k in cls.KNOBS if k in value}
            unknown = set(value) - set(known)
            if unknown:
                raise ValueError(f"unknown ingest keys: {sorted(unknown)}")
            return cls(**known)
        raise TypeError(f"cannot build IngestConfig from {type(value)}")

    def make_cache(self):
        """A configured BlockCache, or None when caching is off."""
        if self.cache_mb <= 0:
            return None
        from comapreduce_tpu.ingest.cache import BlockCache

        return BlockCache(max_bytes=int(self.cache_mb * (1 << 20)),
                          spill_dir=self.spill_dir)
