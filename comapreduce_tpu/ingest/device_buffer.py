"""Host→device double-buffering for ingest streams.

``jax.device_put`` is asynchronous: issuing the transfer of block
``i+1`` while block ``i`` computes hides the PCIe/ICI copy behind
compute (the ``flax.jax_utils.prefetch_to_device`` idiom). Unlike the
flax helper this one is mesh/sharding-aware: a ``NamedSharding`` (or a
pytree of them matching the block structure) places each block directly
into its sharded layout, and :func:`sharding_for_dataset` derives the
placement from the dataset axis-role table in
:mod:`comapreduce_tpu.parallel.axes` so ingest and compute agree on the
layout without a reshard.
"""

from __future__ import annotations

import collections
import itertools
from typing import Any, Callable, Iterable, Iterator

__all__ = ["prefetch_to_device", "sharding_for_dataset"]


def sharding_for_dataset(dataset: str, mesh=None):
    """The ingest-side sharding for a COMAP dataset path: the axis-role
    mapping of :func:`comapreduce_tpu.parallel.axes.sharding_for` on
    ``mesh`` (default: a 1-D ``('feed', 'time')`` mesh over the local
    devices via :func:`~comapreduce_tpu.parallel.mesh.feed_time_mesh`).
    """
    from comapreduce_tpu.parallel import axes as axes_mod
    from comapreduce_tpu.parallel.mesh import feed_time_mesh

    if mesh is None:
        mesh = feed_time_mesh()
    return axes_mod.sharding_for(dataset, mesh)


def prefetch_to_device(blocks: Iterable[Any], size: int = 2,
                       sharding: Any | Callable[[Any], Any] = None,
                       watchdog: Any = None,
                       cast: Callable[[Any], Any] = None) -> Iterator[Any]:
    """Yield device-resident blocks, keeping ``size`` in flight.

    Parameters
    ----------
    blocks:
        Host blocks — arrays or pytrees (``TODBlock`` works as-is).
    size:
        In-flight transfer depth. 2 = classic double-buffering: the
        next block's H2D copy overlaps the current block's compute.
        1 degenerates to plain per-block ``device_put``.
    sharding:
        ``None`` (commit to the default device), a ``Sharding`` applied
        to every leaf, a pytree of shardings matching the block
        structure, or a callable ``block -> sharding (pytree)`` for
        streams of heterogeneous blocks.

    The transfer queue drains lazily: breaking out of the consumer loop
    abandons at most ``size`` in-flight blocks (harmless — transfers
    complete in the background and are garbage-collected).

    ``watchdog`` (a ``resilience.Watchdog``) supervises each H2D issue
    under the ``ingest.h2d`` deadline. ``device_put`` is asynchronous —
    the call itself only enqueues — but a wedged transfer backend (a
    PCIe reset, a dead ICI link) blocks right here at issue time once
    the transfer queue fills, which is exactly the hang the soft
    deadline surfaces; monitoring only, no cancellation (an abandoned
    transfer would leak device buffers).

    ``cast`` (optional ``block -> block``) runs on the host BEFORE the
    transfer is issued — the precision-policy hook (OPERATIONS.md §15):
    a bf16-narrowing cast here halves the bytes that actually cross
    the bus, and the ``ingest.h2d.bytes`` counter below measures the
    POST-cast payload, so the telemetry ledger always reports what was
    shipped, not what was decoded.
    """
    import time

    import jax

    from comapreduce_tpu.telemetry import TELEMETRY

    if size < 1:
        raise ValueError(f"size must be >= 1, got {size}")

    def _issue(block, shard):
        if shard is None:
            return jax.device_put(block)
        return jax.device_put(block, shard)

    def put(block):
        if cast is not None:
            block = cast(block)
        shard = sharding(block) if callable(sharding) else sharding
        if not TELEMETRY.enabled:
            if watchdog is not None:
                with watchdog.watch("ingest.h2d"):
                    return _issue(block, shard)
            return _issue(block, shard)
        # H2D accounting: issue-time span + bytes counter (the
        # transfer itself is async; a wedged backend blocks the issue,
        # which is exactly what the span then shows). The tree walk
        # only runs with telemetry on.
        nbytes = sum(int(getattr(x, "nbytes", 0))
                     for x in jax.tree_util.tree_leaves(block))
        t0 = time.perf_counter()
        if watchdog is not None:
            with watchdog.watch("ingest.h2d"):
                out = _issue(block, shard)
        else:
            out = _issue(block, shard)
        TELEMETRY.event_span("ingest.h2d", time.perf_counter() - t0,
                             bytes=nbytes)
        TELEMETRY.counter("ingest.h2d.bytes", nbytes)
        return out

    it = iter(blocks)
    buf: collections.deque = collections.deque()
    for block in itertools.islice(it, size):
        buf.append(put(block))
    while buf:
        out = buf.popleft()
        try:
            buf.append(put(next(it)))
        except StopIteration:
            pass
        yield out
