"""Per-obsid diagnostic figures (QA plots).

The reference emits QA PNGs throughout the pipeline: vane hot/cold fits
(``VaneCalibration.py:173-190``), gain-solution examples
(``Level1Averaging.py:727-789``), power-spectrum fits
(``Level2Data.py:300-327``), and source-fit postage stamps
(``AstroCalibration.py:615-641``). These are host-side, matplotlib-based,
and entirely optional: every entry point degrades to a warning when
matplotlib is unavailable, and nothing here touches the device path.

Stages call :func:`figure_path` with their ``figure_dir`` (set by the
CLI's ``--figures`` flag or a ``figure_dir`` config key); an empty dir
disables plotting.
"""

from __future__ import annotations

import logging
import os

import numpy as np

__all__ = ["figure_path", "plot_vane_event", "plot_gain_solution",
           "plot_power_spectrum_fit", "plot_source_fit",
           "plot_sed_fit", "plot_sed_corner"]

logger = logging.getLogger("comapreduce_tpu")


def _pyplot():
    try:
        import matplotlib

        matplotlib.use("Agg", force=False)
        from matplotlib import pyplot

        return pyplot
    except Exception:  # pragma: no cover - matplotlib missing
        logger.warning("diagnostics: matplotlib unavailable, skipping plot")
        return None


def figure_path(figure_dir: str, obsid, name: str) -> str | None:
    """``{figure_dir}/{obsid}/{name}.png`` (directories created), or None
    when figures are disabled (reference pattern:
    ``VaneCalibration.py:173-176``)."""
    if not figure_dir:
        return None
    d = os.path.join(figure_dir, str(obsid))
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"{name}.png")


def plot_vane_event(path: str, band_avg, hot_mask, cold_mask, tsys,
                    feed: int = 0):
    """Vane event: band-average TOD with hot/cold samples marked, plus the
    per-channel Tsys it produced (``VaneCalibration.py:173-190``)."""
    plt = _pyplot()
    if plt is None or path is None:
        return
    band_avg = np.asarray(band_avg)
    hot = np.asarray(hot_mask) > 0
    cold = np.asarray(cold_mask) > 0
    tsys = np.asarray(tsys)
    n_bands = band_avg.shape[0]
    fig, axes = plt.subplots(2, 1, figsize=(10, 8))
    t = np.arange(band_avg.shape[-1])
    for ib in range(n_bands):
        axes[0].plot(t, band_avg[ib], lw=0.7, label=f"band {ib}")
        axes[0].plot(t[hot[ib]], band_avg[ib][hot[ib]], "r.", ms=2)
        axes[0].plot(t[cold[ib]], band_avg[ib][cold[ib]], "b.", ms=2)
    axes[0].set_xlabel("sample")
    axes[0].set_ylabel("band-average counts")
    axes[0].set_title(f"vane event, feed {feed} "
                      "(red = hot, blue = cold)")
    axes[0].legend(fontsize=8)
    for ib in range(tsys.shape[0]):
        axes[1].plot(np.where(tsys[ib] > 0, tsys[ib], np.nan), lw=0.7)
    axes[1].set_xlabel("channel")
    axes[1].set_ylabel("Tsys [K]")
    fig.tight_layout()
    fig.savefig(path, dpi=100)
    plt.close(fig)


def plot_gain_solution(path: str, avg_tod, dg, feed: int = 0,
                       scan: int = 0):
    """Scan gain solution against the band-averaged TOD
    (``Level1Averaging.py:727-789``)."""
    plt = _pyplot()
    if plt is None or path is None:
        return
    fig, ax = plt.subplots(1, 1, figsize=(10, 5))
    ax.plot(np.asarray(avg_tod), lw=0.5, label="band-averaged TOD")
    ax.plot(np.asarray(dg), lw=0.8, label="gain solution dG")
    ax.set_xlabel("sample")
    ax.set_ylabel("normalised units")
    ax.set_title(f"gain fluctuation, feed {feed} scan {scan}")
    ax.legend(fontsize=8)
    fig.tight_layout()
    fig.savefig(path, dpi=100)
    plt.close(fig)


def plot_power_spectrum_fit(path: str, nu, p_bin, params, model,
                            feed: int = 0, band: int = 0, scan: int = 0):
    """Binned PSD with the fitted noise model overlaid
    (``Level2Data.py:300-327``)."""
    plt = _pyplot()
    if plt is None or path is None:
        return
    nu = np.asarray(nu)
    pb = np.asarray(p_bin)
    good = (nu > 0) & (pb > 0)
    fig, ax = plt.subplots(1, 1, figsize=(8, 6))
    ax.loglog(nu[good], pb[good], "o", ms=3, label="binned PSD")
    m = np.asarray(model(np.asarray(params), nu[good]))
    ax.loglog(nu[good], m, "-", label="fit")
    ax.axhline(float(params[0]), color="k", ls="--", lw=0.7,
               label="white level")
    ax.set_xlabel("frequency [Hz]")
    ax.set_ylabel("power")
    ax.set_title(f"noise fit, feed {feed} band {band} scan {scan}")
    ax.legend(fontsize=8)
    fig.tight_layout()
    fig.savefig(path, dpi=100)
    plt.close(fig)


def plot_source_fit(path: str, map2d, fit_params, source: str = "",
                    feed: int = 0, band: int = 0):
    """Source postage stamp with the fitted Gaussian's centre/FWHM
    (``AstroCalibration.py:615-641``). ``fit_params``: [amp, x0, sig_x,
    y0, sig_y, ...] in pixel units as produced by the source fitter."""
    plt = _pyplot()
    if plt is None or path is None:
        return
    m = np.asarray(map2d)
    fig, ax = plt.subplots(1, 1, figsize=(6, 6))
    im = ax.imshow(m, origin="lower", cmap="viridis")
    fig.colorbar(im, ax=ax, shrink=0.8)
    p = np.asarray(fit_params, dtype=np.float64).ravel()
    if p.size >= 5 and np.isfinite(p[:5]).all():
        x0, sx, y0, sy = p[1], abs(p[2]), p[3], abs(p[4])
        th = np.linspace(0, 2 * np.pi, 100)
        k = 2.355 / 2.0  # FWHM/2 in sigma units
        ax.plot(x0 + k * sx * np.cos(th), y0 + k * sy * np.sin(th),
                "r-", lw=1.0)
        ax.plot([x0], [y0], "r+")
    ax.set_title(f"{source} feed {feed} band {band}")
    fig.tight_layout()
    fig.savefig(path, dpi=100)
    plt.close(fig)


def plot_skydip_fit(path: str, freq_ghz, fits, feed: int = 0):
    """Sky-dip fit vs frequency for one feed: offset (zero-airmass
    system temperature) and slope (sky brightness per airmass) — the
    reference's per-feed sky-dip figure (``Level1Averaging.py:137-155``).
    ``freq_ghz``: (B, C); ``fits``: (B, 2, C) [offset, slope]."""
    if path is None:
        return
    plt = _pyplot()
    if plt is None:
        return
    freq_ghz = np.asarray(freq_ghz)
    fits = np.asarray(fits)
    nu = freq_ghz.ravel()
    order = np.argsort(nu)
    fig, axes = plt.subplots(2, 1, sharex=True, figsize=(7, 5))
    axes[0].plot(nu[order], fits[:, 0, :].ravel()[order], lw=0.8)
    axes[0].set_ylabel("offset [K or counts]")
    axes[1].plot(nu[order], fits[:, 1, :].ravel()[order], lw=0.8)
    axes[1].set_ylabel("slope per airmass")
    axes[1].set_xlabel("frequency [GHz]")
    fig.suptitle(f"sky dip, feed {feed:02d}")
    fig.tight_layout()
    fig.savefig(path, dpi=100)
    plt.close(fig)


def plot_sed_fit(path: str, freqs_ghz, flux, flux_err, model_freqs,
                 model_flux, title: str = ""):
    """SED data points + fitted model curve (the ``SEDs/tools.py``
    fit-plot role). Log-log axes; None path = disabled."""
    if path is None:
        return
    plt = _pyplot()
    if plt is None:
        return
    fig, ax = plt.subplots(figsize=(5, 4))
    ax.errorbar(np.asarray(freqs_ghz), np.asarray(flux),
                yerr=np.asarray(flux_err), fmt="o", ms=4, capsize=2,
                label="data")
    ax.plot(np.asarray(model_freqs), np.asarray(model_flux), "-",
            label="model")
    ax.set_xscale("log")
    ax.set_yscale("log")
    ax.set_xlabel("frequency [GHz]")
    ax.set_ylabel("flux density")
    if title:
        ax.set_title(title)
    ax.legend()
    fig.tight_layout()
    fig.savefig(path, dpi=100)
    plt.close(fig)


def plot_sed_corner(path: str, chain, names):
    """Corner-style posterior grid from an MCMC chain (the
    ``SEDs/tools.py:859-991`` corner/walker-plot role, matplotlib-only —
    no external corner package). ``chain``: f64[n_samples, n_params] in
    the sampler's internal (possibly log) parameterisation; ``names``
    labels the columns."""
    if path is None:
        return
    plt = _pyplot()
    if plt is None:
        return
    chain = np.asarray(chain)
    n = chain.shape[1]
    fig, axes = plt.subplots(n, n, figsize=(2.0 * n, 2.0 * n))
    axes = np.atleast_2d(axes)
    for i in range(n):
        for j in range(n):
            ax = axes[i, j]
            if j > i:
                ax.axis("off")
                continue
            if i == j:
                ax.hist(chain[:, i], bins=40, histtype="step")
            else:
                ax.hist2d(chain[:, j], chain[:, i], bins=40)
            if i == n - 1:
                ax.set_xlabel(names[j])
            else:
                ax.set_xticklabels([])
            if j == 0 and i > 0:
                ax.set_ylabel(names[i])
            else:
                ax.set_yticklabels([])
    fig.tight_layout()
    fig.savefig(path, dpi=100)
    plt.close(fig)
