"""SED fitting driver (``SEDs/tools.py`` ``SED`` class parity).

Least squares is a host-side NumPy Levenberg-Marquardt with
finite-difference Jacobians (the emission models are NumPy; tracing them
through the JAX solver in :mod:`calibration.fitting` would require
rewriting the physics in jnp for fits that are tiny and never a device
hot path), plus a dependency-free Metropolis sampler standing in for the
reference's emcee MCMC (``SEDs/mcmc.py:40``, ``tools.py:333``): returns
chains, means, and covariances — everything the reference's corner/
walker plots consume.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from comapreduce_tpu.seds.emission import DEFAULT_COMPONENTS, total_model

__all__ = ["SED", "mh_sample"]

# fit parameters per component: (name, default, positive?)
_COMPONENT_PARAMS = {
    "synchrotron": (("sync_amp", 1e-3, True), ("sync_index", -3.0, False)),
    "freefree": (("em", 10.0, True),),
    "ame": (("ame_amp", 1e-3, True), ("ame_peak", 25.0, True)),
    "thermal_dust": (("tau353", 1e-6, True),),
    "cmb": (("cmb_dt", 1e-5, False),),
}


def mh_sample(log_prob, p0: np.ndarray, n_steps: int = 4000,
              step_scale: np.ndarray | float = 0.05,
              seed: int = 0, burn: int = 1000):
    """Random-walk Metropolis with a FIXED symmetric proposal.

    Step sizes are frozen from the starting point (``step_scale *
    max(|p0|, 0.05)`` per parameter) — a state-dependent scale would make
    the proposal asymmetric and bias the chain without a Hastings
    correction, and a pure relative scale freezes parameters near zero.
    Returns (chain, acceptance)."""
    rng = np.random.default_rng(seed)
    p = np.asarray(p0, np.float64).copy()
    lp = log_prob(p)
    rel = np.broadcast_to(np.asarray(step_scale, np.float64), p.shape)
    step = rel * np.maximum(np.abs(p), 0.05)
    chain = np.empty((n_steps, p.size))
    accepted = 0
    for i in range(n_steps):
        prop = p + step * rng.normal(size=p.shape)
        lp_new = log_prob(prop)
        if np.isfinite(lp_new) and np.log(rng.random()) < lp_new - lp:
            p, lp = prop, lp_new
            accepted += 1
        chain[i] = p
    return chain[burn:], accepted / n_steps


@dataclass
class SED:
    """Fit emission components to flux measurements.

    ``freq_ghz``/``flux_jy``/``flux_err_jy``: 1-D measurement vectors;
    ``omega_sr``: aperture solid angle; ``components``: subset of
    :data:`DEFAULT_COMPONENTS`.
    """

    freq_ghz: np.ndarray
    flux_jy: np.ndarray
    flux_err_jy: np.ndarray
    omega_sr: float
    components: tuple = DEFAULT_COMPONENTS
    params: dict = field(default_factory=dict)
    errors: dict = field(default_factory=dict)
    chain: np.ndarray | None = None

    @property
    def param_names(self) -> list[str]:
        """Parameter names in chain/vector column order (public API for
        corner-plot labelling)."""
        return [name for name, _, _ in self._param_spec()]

    def _param_spec(self):
        spec = []
        for c in self.components:
            spec.extend(_COMPONENT_PARAMS[c])
        return spec

    def _to_dict(self, vec):
        out = {}
        for (name, _, positive), v in zip(self._param_spec(), vec):
            out[name] = float(np.exp(v)) if positive else float(v)
        return out

    def _to_vec(self, d):
        vec = []
        for name, default, positive in self._param_spec():
            v = d.get(name, default)
            vec.append(np.log(max(v, 1e-30)) if positive else v)
        return np.asarray(vec, np.float64)

    def model(self, params: dict, freq_ghz=None):
        return total_model(params,
                           self.freq_ghz if freq_ghz is None else freq_ghz,
                           self.omega_sr, self.components)

    def chi2(self, params: dict) -> float:
        r = (self.model(params) - self.flux_jy) / self.flux_err_jy
        return float(np.sum(r * r))

    def fit(self, p0: dict | None = None, n_iter: int = 200) -> dict:
        """Levenberg-Marquardt least squares in the transformed
        (log-positive) parameter space. Host NumPy with finite-difference
        Jacobians — SED fits are tiny (N_freq x ~8 params) and never a
        device hot path (the reference runs emcee on host too)."""
        spec = self._param_spec()

        def residual(v):
            m = self.model(self._to_dict(v))
            return (m - self.flux_jy) / self.flux_err_jy

        def jacobian(v):
            r0 = residual(v)
            J = np.empty((r0.size, v.size))
            for i in range(v.size):
                h = 1e-6 * max(abs(v[i]), 1.0)
                vp = v.copy()
                vp[i] += h
                J[:, i] = (residual(vp) - r0) / h
            return J, r0

        v = self._to_vec(p0 or {})
        lam = 1e-3
        c2 = float(np.sum(residual(v) ** 2))
        for _ in range(n_iter):
            J, r = jacobian(v)
            H = J.T @ J
            g = J.T @ r
            try:
                delta = np.linalg.solve(
                    H + lam * np.diag(np.maximum(np.diag(H), 1e-12)), g)
            except np.linalg.LinAlgError:
                lam *= 10.0
                continue
            v_new = v - delta
            c2_new = float(np.sum(residual(v_new) ** 2))
            if np.isfinite(c2_new) and c2_new < c2:
                v, c2 = v_new, c2_new
                lam = max(lam * 0.3, 1e-10)
                if abs(delta).max() < 1e-10:
                    break
            else:
                lam = min(lam * 8.0, 1e8)
        J, r = jacobian(v)
        dof = max(r.size - v.size, 1)
        cov = np.linalg.pinv(J.T @ J) * c2 / dof
        err = np.sqrt(np.maximum(np.diag(cov), 0.0))
        self.params = self._to_dict(v)
        self.errors = {}
        for (name, _, positive), vi, ei in zip(spec, v, err):
            # transform log-space sigma back to natural units
            self.errors[name] = (float(np.exp(vi) * ei) if positive
                                 else float(ei))
        self.chi2_value = float(c2)
        return self.params

    def mcmc_fit(self, n_steps: int = 4000, seed: int = 0) -> dict:
        """Posterior sampling (the emcee stand-in). Seeds from the LM fit
        when available; stores the chain for corner-style analysis."""
        if not self.params:
            self.fit()
        v0 = self._to_vec(self.params)

        def log_prob(v):
            d = self._to_dict(v)
            return -0.5 * self.chi2(d)

        chain, acc = mh_sample(log_prob, v0, n_steps=n_steps, seed=seed)
        self.chain = chain
        mean = chain.mean(axis=0)
        std = chain.std(axis=0)
        spec = self._param_spec()
        self.params = self._to_dict(mean)
        self.errors = {name: (float(np.exp(m) * s) if positive
                              else float(s))
                       for (name, _, positive), m, s
                       in zip(spec, mean, std)}
        self.acceptance = acc
        return self.params
