"""SED fitting toolkit (parity with the reference ``SEDs/`` package).

Emission-component models (synchrotron, free-free, AME, thermal dust,
CMB — ``SEDs/emission.py:14-107``) and a fitting driver (``SEDs/tools.py
SED`` class). The reference fits with emcee MCMC; emcee is not in this
image, so the driver offers the batched Levenberg-Marquardt solver (the
pipeline's workhorse) plus a dependency-free Metropolis-Hastings sampler
for posterior estimates.
"""

from comapreduce_tpu.seds.emission import (ame, cmb, freefree, synchrotron,
                                           thermal_dust, total_model)
from comapreduce_tpu.seds.fit import SED, mh_sample

__all__ = ["synchrotron", "freefree", "ame", "thermal_dust", "cmb",
           "total_model", "SED", "mh_sample"]
