"""Emission-component SED models (``SEDs/emission.py:14-107`` parity).

All models return flux density [Jy] over a solid angle ``omega_sr`` at
``freq_ghz``. Parameters are in the log/natural units the fitter uses.
"""

from __future__ import annotations

import numpy as np

from comapreduce_tpu.calibration.unitconv import (blackbody, k_to_jy,
                                                  planck_correction)
from comapreduce_tpu.simulations.frequency_models import lognormal_ame

__all__ = ["synchrotron", "freefree", "ame", "thermal_dust", "cmb",
           "total_model", "DEFAULT_COMPONENTS"]


def _rj_to_jy(t_k, freq_ghz, omega_sr):
    return k_to_jy(t_k, freq_ghz, omega_sr)


def synchrotron(freq_ghz, omega_sr, amp_k, index=-3.0, freq0=30.0):
    """Power-law synchrotron: ``amp_k`` K RJ at ``freq0``."""
    t = amp_k * (np.asarray(freq_ghz, np.float64) / freq0) ** index
    return _rj_to_jy(t, freq_ghz, omega_sr)


def freefree(freq_ghz, omega_sr, em_pc_cm6, t_e=7500.0):
    """Free-free from emission measure [pc cm^-6] (Draine 2011 approx
    gaunt factor, as the reference uses)."""
    nu9 = np.asarray(freq_ghz, np.float64)
    t4 = t_e / 1e4
    g = np.log(np.exp(5.960 - np.sqrt(3.0) / np.pi
                      * np.log(nu9 * t4 ** (-1.5))) + np.e)
    tau = 5.468e-2 * t_e ** (-1.5) * nu9 ** (-2.0) * em_pc_cm6 * g
    t_ff = t_e * (1.0 - np.exp(-tau))
    return _rj_to_jy(t_ff, freq_ghz, omega_sr)


def ame(freq_ghz, omega_sr, amp_k, freq_peak=25.0, width=0.5):
    """Anomalous microwave emission: log-normal bump (the spdust-table
    stand-in; same parameterisation as Simulations)."""
    t = amp_k * lognormal_ame(freq_ghz, freq_peak, width)
    return _rj_to_jy(t, freq_ghz, omega_sr)


def thermal_dust(freq_ghz, omega_sr, tau_353, beta=1.6, t_dust=19.6):
    """Modified blackbody anchored at 353 GHz optical depth."""
    nu = np.asarray(freq_ghz, np.float64)
    tau = tau_353 * (nu / 353.0) ** beta
    i_nu = tau * blackbody(nu, t_dust)  # W m^-2 Hz^-1 sr^-1
    return i_nu * omega_sr * 1e26


def cmb(freq_ghz, omega_sr, dt_cmb_k):
    """CMB anisotropy: thermodynamic dT -> Jy (dT_RJ = dT_CMB / g)."""
    conv = 1.0 / planck_correction(freq_ghz)
    return _rj_to_jy(dt_cmb_k * conv, freq_ghz, omega_sr)


DEFAULT_COMPONENTS = ("synchrotron", "freefree", "ame", "thermal_dust",
                      "cmb")


def total_model(params: dict, freq_ghz, omega_sr,
                components=DEFAULT_COMPONENTS):
    """Sum the selected components. ``params`` keys: ``sync_amp``,
    ``sync_index``, ``em``, ``ame_amp``, ``ame_peak``, ``tau353``,
    ``dust_beta``, ``dust_temp``, ``cmb_dt`` (missing -> defaults/0)."""
    p = params
    total = np.zeros_like(np.asarray(freq_ghz, np.float64))
    if "synchrotron" in components:
        total = total + synchrotron(freq_ghz, omega_sr,
                                    p.get("sync_amp", 0.0),
                                    p.get("sync_index", -3.0))
    if "freefree" in components:
        total = total + freefree(freq_ghz, omega_sr, p.get("em", 0.0))
    if "ame" in components:
        total = total + ame(freq_ghz, omega_sr, p.get("ame_amp", 0.0),
                            p.get("ame_peak", 25.0),
                            p.get("ame_width", 0.5))
    if "thermal_dust" in components:
        total = total + thermal_dust(freq_ghz, omega_sr,
                                     p.get("tau353", 0.0),
                                     p.get("dust_beta", 1.6),
                                     p.get("dust_temp", 19.6))
    if "cmb" in components:
        total = total + cmb(freq_ghz, omega_sr, p.get("cmb_dt", 0.0))
    return total
