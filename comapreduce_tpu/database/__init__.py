"""Observation-metadata database (parity with ``COMAPDatabase/``).

Fleet-level observability store: per-obsid stats, quality flags, and
calibration factors in one HDF5 file, with the reference's tooling roles
— stats harvesting from Level-2 files, threshold-based flag assignment
(``assign_stats_flags.py``), smoothed calibration-factor assignment
(``assign_calibration_factors.py:7-60`` + the outlier-robust smoothing of
``data/Data.py:13-98``), and source-based filelist queries
(``query_source.py:31-60``). The Google-Sheets observer-flag sync is
replaced by a CSV import (no gspread in this image).
"""

from comapreduce_tpu.database.obsdb import (ObsDatabase, robust_smooth,
                                            assign_stats_flags)
from comapreduce_tpu.database.metadata import (parse_obsinfo,
                                               query_obs_metadata,
                                               obsinfo_from_database)
from comapreduce_tpu.database.normalised_mask import (
    harvest_channel_flags, build_normalised_masks, level2_channel_mask,
    apply_mask_to_tsys, read_date_cuts)

__all__ = ["ObsDatabase", "robust_smooth", "assign_stats_flags",
           "parse_obsinfo", "query_obs_metadata", "obsinfo_from_database",
           "harvest_channel_flags", "build_normalised_masks",
           "level2_channel_mask", "apply_mask_to_tsys", "read_date_cuts"]
