"""HDF5-backed observation database.

Schema (one group per obsid, ``COMAPDatabase/README`` parity)::

    <obsid>/
        attrs: source, mjd, level2_path, flag (int; 0 = good)
        stats/   noise_mk, tsys_median, fnoise_median (per band)
        calibration/ factors (F, B), good (F, B)
"""

from __future__ import annotations

import logging
import os

import numpy as np

from comapreduce_tpu.data.hdf5io import HDF5Store
from comapreduce_tpu.data.level import COMAPLevel2

__all__ = ["ObsDatabase", "robust_smooth", "assign_stats_flags"]

logger = logging.getLogger("comapreduce_tpu")

# flag bits (assign_stats_flags.py role)
FLAG_GOOD = 0
FLAG_NOISY = 1 << 0        # white level above threshold
FLAG_NO_CAL = 1 << 1       # no valid calibration factors
FLAG_OBSERVER = 1 << 2     # manual/observer flag (CSV import)
FLAG_BAD_WEATHER = 1 << 3  # high fnoise


def robust_smooth(mjds: np.ndarray, values: np.ndarray,
                  window_days: float = 30.0, n_sigma: float = 3.0):
    """Outlier-robust running median (``data/Data.py:13-98`` smoothing):
    median within ±window/2, after rejecting points > n_sigma MADs.
    Windows are found by binary search on the time-sorted series, so a
    fleet-sized series stays O(T log T), not O(T^2)."""
    mjds = np.asarray(mjds, np.float64)
    values = np.asarray(values, np.float64)
    order = np.argsort(mjds)
    ts = mjds[order]
    vs = values[order]
    med_all = np.nanmedian(vs)
    mad = np.nanmedian(np.abs(vs - med_all)) * 1.4826 + 1e-30
    keep = np.abs(vs - med_all) < n_sigma * mad
    lo = np.searchsorted(ts, ts - window_days / 2.0, side="left")
    hi = np.searchsorted(ts, ts + window_days / 2.0, side="right")
    out_sorted = np.empty_like(vs)
    for i in range(len(ts)):
        seg = vs[lo[i]:hi[i]][keep[lo[i]:hi[i]]]
        out_sorted[i] = np.nanmedian(seg) if seg.size else med_all
    out = np.empty_like(out_sorted)
    out[order] = out_sorted
    return out


class ObsDatabase:
    """Dict-of-obsid records persisted to one HDF5 file."""

    def __init__(self, filename: str):
        self.filename = filename
        self.store = HDF5Store(name="obsdb")
        if os.path.exists(filename):
            self.store.read(filename)

    # -- record access ------------------------------------------------------
    def obsids(self) -> list[int]:
        ids = {p.split("/")[0] for p in self.store.keys()}
        ids |= {p.split("/")[0] for p, _ in self.store.attr_items() if p}
        return sorted(int(i) for i in ids if i.isdigit())

    def get_attr(self, obsid: int, key: str, default=None):
        try:
            return self.store.attrs(str(obsid), key)
        except KeyError:
            return default

    def set_attr(self, obsid: int, key: str, value) -> None:
        self.store.set_attrs(str(obsid), key, value)

    def get(self, obsid: int, path: str, default=None):
        return self.store.get(f"{obsid}/{path}", default)

    def set(self, obsid: int, path: str, value) -> None:
        self.store[f"{obsid}/{path}"] = value

    def save(self) -> None:
        self.store.write(self.filename, atomic=True)

    # -- harvesting ---------------------------------------------------------
    def update_from_level2(self, filenames) -> int:
        """Harvest per-obsid stats from Level-2 files
        (the ``COMAPDatabase`` stats-collection role)."""
        from comapreduce_tpu.mapmaking.filelist import noise_level_mk

        count = 0
        for fname in filenames:
            try:
                lvl2 = COMAPLevel2(filename=fname)
                obsid = lvl2.obsid
                if obsid < 0:
                    continue
                tod = np.asarray(lvl2["averaged_tod/tod"])
                B = tod.shape[1]
                noise = np.array([noise_level_mk(lvl2, b)
                                  for b in range(B)])
                self.set(obsid, "stats/noise_mk", noise)
                if "vane/system_temperature" in lvl2:
                    tsys = np.asarray(lvl2.system_temperature)
                    ok = tsys > 0
                    med = np.where(
                        ok.any(axis=(0, 3)),
                        np.nanmedian(np.where(ok, tsys, np.nan),
                                     axis=(0, 3)), 0.0)
                    self.set(obsid, "stats/tsys_median", med)
                if "fnoise_fits/fnoise_fit_parameters" in lvl2:
                    fn = np.asarray(
                        lvl2["fnoise_fits/fnoise_fit_parameters"])
                    self.set(obsid, "stats/fnoise_median",
                             np.nanmedian(fn, axis=(0, 2)))
                if "astro_calibration/calibration_factors" in lvl2:
                    fac = np.asarray(
                        lvl2["astro_calibration/calibration_factors"])
                    self.set(obsid, "calibration/factors", fac)
                    good = lvl2.get("astro_calibration/calibration_good")
                    self.set(obsid, "calibration/good",
                             np.asarray(good) if good is not None
                             else np.ones(fac.shape, np.uint8))
                self.set_attr(obsid, "source", lvl2.source_name)
                mjd = np.asarray(lvl2.mjd)
                # mean for nearest-MJD factor assignment; start for the
                # filename convention (comap-<obsid>-<start stamp>)
                self.set_attr(obsid, "mjd", float(np.mean(mjd)))
                self.set_attr(obsid, "mjd_start", float(mjd.flat[0]))
                self.set_attr(obsid, "level2_path", os.path.abspath(fname))
                if self.get_attr(obsid, "flag") is None:
                    self.set_attr(obsid, "flag", FLAG_GOOD)
                count += 1
            except (OSError, KeyError) as exc:
                logger.warning("obsdb: BAD FILE %s (%s)", fname, exc)
        return count

    # -- flags --------------------------------------------------------------
    def import_observer_flags(self, csv_path: str) -> int:
        """CSV ``obsid,flagged`` import (the Google-Sheets sync stand-in,
        ``comap_wiki_flags.py:24-38``)."""
        n = 0
        with open(csv_path) as f:
            for line in f:
                parts = line.strip().split(",")
                if len(parts) < 2 or not parts[0].strip().isdigit():
                    continue
                obsid = int(parts[0])
                flagged = parts[1].strip().lower() in ("1", "true", "yes")
                flag = int(self.get_attr(obsid, "flag", FLAG_GOOD) or 0)
                if flagged:
                    flag |= FLAG_OBSERVER
                else:
                    flag &= ~FLAG_OBSERVER
                self.set_attr(obsid, "flag", flag)
                n += 1
        return n

    # -- queries ------------------------------------------------------------
    def query_source(self, source: str, good_only: bool = True
                     ) -> list[str]:
        """Level-2 paths of observations of ``source``
        (``query_source.py:31-60``)."""
        out = []
        for obsid in self.obsids():
            if str(self.get_attr(obsid, "source", "")) != source:
                continue
            if good_only and int(self.get_attr(obsid, "flag", 0) or 0):
                continue
            path = self.get_attr(obsid, "level2_path")
            if path is not None:
                out.append(str(path))
        return out

    def smoothed_calibration_factors(self, window_days: float = 30.0):
        """Per-(feed, band) calibration factors smoothed over time with
        the outlier-robust median (``assign_calibration_factors.py:7-60``).
        Returns (mjds, smoothed[T, F, B])."""
        recs = []
        for obsid in self.obsids():
            fac = self.get(obsid, "calibration/factors")
            mjd = self.get_attr(obsid, "mjd")
            if fac is None or mjd is None:
                continue
            recs.append((float(mjd), np.asarray(fac)))
        if not recs:
            return np.zeros(0), np.zeros((0, 0, 0))
        # one inconsistent record (different F or B) must not break the
        # fleet: keep the most common shape, skip the rest
        from collections import Counter

        shape = Counter(r[1].shape for r in recs).most_common(1)[0][0]
        dropped = [r for r in recs if r[1].shape != shape]
        if dropped:
            logger.warning("smoothed_calibration_factors: skipping %d "
                           "records with shape != %s", len(dropped), shape)
        recs = [r for r in recs if r[1].shape == shape]
        recs.sort(key=lambda r: r[0])
        mjds = np.array([r[0] for r in recs])
        fac = np.stack([r[1] for r in recs])  # (T, F, B)
        out = np.empty_like(fac)
        T, F, B = fac.shape
        for f in range(F):
            for b in range(B):
                out[:, f, b] = robust_smooth(mjds, fac[:, f, b],
                                             window_days)
        return mjds, out


def assign_stats_flags(db: ObsDatabase, noise_cut_mk: float = 4.0,
                       fnoise_red_cut: float | None = None) -> int:
    """Threshold-based quality flags (``assign_stats_flags.py`` role)."""
    n = 0
    for obsid in db.obsids():
        flag = int(db.get_attr(obsid, "flag", FLAG_GOOD) or 0)
        noise = db.get(obsid, "stats/noise_mk")
        flag &= ~(FLAG_NOISY | FLAG_BAD_WEATHER | FLAG_NO_CAL)
        if noise is not None and np.nanmedian(np.asarray(noise)) \
                > noise_cut_mk:
            flag |= FLAG_NOISY
        if fnoise_red_cut is not None:
            fn = db.get(obsid, "stats/fnoise_median")
            if fn is not None and np.nanmedian(
                    np.asarray(fn)[..., 1]) > fnoise_red_cut:
                flag |= FLAG_BAD_WEATHER
        good = db.get(obsid, "calibration/good")
        if good is not None and not np.asarray(good).any():
            flag |= FLAG_NO_CAL
        db.set_attr(obsid, "flag", flag)
        n += 1
    return n
