"""Fleet-wide "normalised" channel masks over operator date ranges.

Role parity: ``COMAPDatabase/assign_normalised_mask.py:1-60`` — channels
that misbehave in more than ``threshold`` of the observations inside an
operator-defined date (obsid) range are masked for EVERY observation in
that range, so one noisy week cannot leak a different channel set into
each map. The coarse "level-2" mask (16-channel bins, >=2 bad channels
masks the bin, +-1-bin dilation) matches the reference's ``Level2Mask``
product; it is applied at the next reduction level through the Tsys
flags (``tsys <= 0`` channels already carry zero weight in both
averaging stages — see ``apply_mask_to_tsys``).

Differences from the reference (deliberate):

- date cuts are inclusive obsid ranges, not nearest-obsid matches (the
  reference's ``argmin((obsid - start)**2)`` silently snaps a typo'd cut
  to the nearest real obs);
- per-feed cut files are optional — a single global cut list is the
  common case (the reference requires 19 ``datecuts/FeedNN_cuts.dat``
  files);
- the per-channel "bad" evidence is harvested from the Level-2 vane
  products (non-finite / non-positive Tsys, plus the vane spike mask
  when present) instead of a separate fleet pickle.
"""

from __future__ import annotations

import logging
import os

import numpy as np

from comapreduce_tpu.database.obsdb import ObsDatabase

__all__ = ["harvest_channel_flags", "build_normalised_masks",
           "level2_channel_mask", "apply_mask_to_tsys", "read_date_cuts"]

logger = logging.getLogger("comapreduce_tpu")


def read_date_cuts(path: str) -> list:
    """Two-column ``start_obsid end_obsid`` file (``#`` comments) ->
    list of (start, end) inclusive ranges (the ``datecuts/`` format)."""
    cuts = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            if len(parts) < 2:
                raise ValueError(f"date-cut file {path}: line {line!r} "
                                 "needs two columns (start end)")
            cuts.append((int(float(parts[0])), int(float(parts[1]))))
    return cuts


def harvest_channel_flags(db: ObsDatabase, filenames) -> int:
    """Record per-obs ``vane/channel_bad`` (F, B, C) uint8 evidence from
    Level-2 stores: non-finite / non-positive Tsys in any vane event,
    OR'd with the vane spike mask when present."""
    from comapreduce_tpu.data.level import COMAPLevel2

    n = 0
    for fname in filenames:
        try:
            lvl2 = COMAPLevel2(filename=fname)
            obsid = lvl2.obsid
            tsys = np.asarray(lvl2.system_temperature, np.float64)
        except (OSError, KeyError) as exc:
            logger.warning("harvest_channel_flags: BAD FILE %s (%s)",
                           fname, exc)
            continue
        # (Nvane, F, B, C) or (F, B, C): bad if bad in ANY vane event
        if tsys.ndim == 4:
            bad = (~np.isfinite(tsys) | (tsys <= 0)).any(axis=0)
        else:
            bad = ~np.isfinite(tsys) | (tsys <= 0)
        spikes = lvl2.get("vane/spike_mask")
        if spikes is not None:
            sp = np.asarray(spikes) > 0
            if sp.ndim == 4:
                sp = sp.any(axis=0)
            bad = bad | sp
        db.set(obsid, "vane/channel_bad", bad.astype(np.uint8))
        n += 1
    return n


def build_normalised_masks(db: ObsDatabase, cuts,
                           feed_cuts: dict | None = None,
                           threshold: float = 0.25,
                           coarse_bin: int = 16, min_bad: int = 2,
                           dilate: int = 1) -> int:
    """Build + store the date-range masks from the harvested evidence.

    ``cuts``: list of (start_obsid, end_obsid) inclusive ranges applied
    to every feed; ``feed_cuts`` optionally overrides the list for
    individual feed indices (the reference's per-feed
    ``datecuts/FeedNN_cuts.dat`` role). Within each range a channel is
    masked when it is bad in more than ``threshold`` of the range's
    observations (``assign_normalised_mask.py`` uses ``s > 0.25 w``).

    Writes per obs: ``vane/normalised_mask`` (F, B, C) uint8 (full-res
    fleet mask) and ``vane/level2_mask`` (F, B, C//coarse_bin) uint8
    (own-bad OR fleet mask, ``min_bad``-of-``coarse_bin`` rule, +-dilate
    bins) — the product the next reduction level applies. Returns the
    number of observations updated."""
    evid = {o: np.asarray(db.get(o, "vane/channel_bad"), bool)
            for o in db.obsids()
            if db.get(o, "vane/channel_bad") is not None}
    if not evid:
        return 0
    # mixed instrument epochs (different F or C) must not crash the
    # fleet build: keep the most common evidence shape, skip the rest
    # (same policy as obsdb.smoothed_calibration_factors)
    from collections import Counter

    shape = Counter(e.shape for e in evid.values()).most_common(1)[0][0]
    dropped = [o for o, e in evid.items() if e.shape != shape]
    if dropped:
        logger.warning("build_normalised_masks: skipping %d obs with "
                       "evidence shape != %s", len(dropped), shape)
    obsids = sorted(o for o, e in evid.items() if e.shape == shape)
    F, B, C = shape
    fleet = {o: np.zeros(shape, bool) for o in obsids}

    for ifeed in range(F):
        for start, end in (feed_cuts or {}).get(ifeed, cuts):
            inside = [o for o in obsids if start <= o <= end]
            if not inside:
                continue
            stack = np.stack([evid[o][ifeed] for o in inside])  # (n,B,C)
            frac = stack.mean(axis=0)
            mask = frac > threshold
            for o in inside:
                fleet[o][ifeed] |= mask

    nb = max(C // coarse_bin, 1)
    for o in obsids:
        db.set(o, "vane/normalised_mask", fleet[o].astype(np.uint8))
        combined = (fleet[o] | evid[o])[:, :, : nb * coarse_bin]
        counts = combined.reshape(F, B, nb, -1).sum(axis=-1)
        lvl2 = counts >= min_bad
        for d in range(1, dilate + 1):       # +-d-bin dilation, no wrap
            grown = lvl2.copy()
            grown[:, :, d:] |= lvl2[:, :, :-d]
            grown[:, :, :-d] |= lvl2[:, :, d:]
            lvl2 = grown
        db.set(o, "vane/level2_mask", lvl2.astype(np.uint8))
    return len(obsids)


def level2_channel_mask(db: ObsDatabase, obsid: int,
                        n_channels: int | None = None
                        ) -> np.ndarray | None:
    """Full-resolution (F, B, C) bool mask (True = masked) expanded from
    the stored coarse ``vane/level2_mask``; None when the observation has
    no mask (the stages then apply no fleet cut)."""
    coarse = db.get(obsid, "vane/level2_mask")
    if coarse is None:
        return None
    coarse = np.asarray(coarse, bool)
    F, B, nb = coarse.shape
    C = n_channels or nb * 16
    reps = max(C // nb, 1)
    full = np.repeat(coarse, reps, axis=-1)
    if full.shape[-1] < C:                    # C not divisible: extend
        pad = np.repeat(full[:, :, -1:], C - full.shape[-1], axis=-1)
        full = np.concatenate([full, pad], axis=-1)
    return full[:, :, :C]


# one-slot db cache keyed on (path, mtime_ns, size): a batch reduction
# calls apply_mask_to_tsys up to twice per observation and must not
# re-read the whole fleet store every time
_db_cache: tuple = (None, None)
_warned_missing: set = set()


def _cached_db(db_file: str) -> ObsDatabase:
    global _db_cache
    st = os.stat(db_file)
    key = (os.path.abspath(db_file), st.st_mtime_ns, st.st_size)
    if _db_cache[0] != key:
        _db_cache = (key, ObsDatabase(db_file))
    return _db_cache[1]


def apply_mask_to_tsys(tsys: np.ndarray, db_file: str, obsid: int
                       ) -> np.ndarray:
    """Zero the Tsys of fleet-masked channels (zero Tsys == zero channel
    weight in every averaging stage — the mask rides the existing Tsys
    flags exactly as the reference applies ``Level2Mask`` on top of its
    initial Tsys flags). Returns ``tsys`` unchanged when the database or
    mask is absent (fail-open: a missing fleet product must not block a
    reduction — but a MISSING DATABASE FILE is warned once per path,
    since an operator configured it expecting a cut)."""
    if not os.path.exists(db_file):
        if db_file not in _warned_missing:
            _warned_missing.add(db_file)
            logger.warning("normalised_mask_db %s does not exist; "
                           "reducing WITHOUT the fleet channel cut",
                           db_file)
        return tsys
    try:
        db = _cached_db(db_file)
        mask = level2_channel_mask(db, obsid, tsys.shape[-1])
    except (OSError, KeyError, ValueError) as exc:
        logger.warning("normalised mask unavailable (%s); reducing "
                       "without the fleet cut", exc)
        return tsys
    if mask is None:
        return tsys
    if mask.shape != tsys.shape:
        logger.warning("normalised mask shape %s != tsys %s; skipping",
                       mask.shape, tsys.shape)
        return tsys
    n = int(mask.sum())
    if n:
        logger.info("obs %s: masking %d fleet-flagged channels", obsid, n)
    return np.where(mask, 0.0, tsys)
