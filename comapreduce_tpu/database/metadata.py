"""Observation-metadata queries (``Tools/FileTools.py:6-27`` parity).

The reference shells out over SSH to a script on the OVRO archive host
that prints one ``obsid target day time`` line per observation, then
rebuilds the Level-2 filename from the COMAP convention
``comap-{obsid:07d}-{YYYY-mm-dd-HHMMSS}{suffix}.hd5``. Here the same
capability is split into

* :func:`parse_obsinfo` — the line-format parser (pure, testable);
* :func:`query_obs_metadata` — run a remote/local command and parse its
  output (argv list, no ``shell=True``);
* :func:`obsinfo_from_database` — answer the same query offline from a
  local :class:`~comapreduce_tpu.database.obsdb.ObsDatabase`, which is
  the TPU-cluster-native path (no SSH hop from worker hosts).
"""

from __future__ import annotations

import logging
import shlex
import subprocess
from datetime import datetime, timezone

__all__ = ["parse_obsinfo", "query_obs_metadata", "obsinfo_from_database"]

logger = logging.getLogger("comapreduce_tpu")

_FILENAME_FMT = "comap-{obsid:07d}-{stamp}{suffix}.hd5"


def parse_obsinfo(text: str, suffix: str = "_Level2Cont") -> dict[str, str]:
    """Parse ``obsid target day time`` lines into ``{filename: target}``.

    Lines that do not have exactly four whitespace-separated fields, a
    numeric obsid, or a parseable ``%Y-%m-%d %H:%M:%S[.f]`` timestamp
    are skipped (the reference silently skips malformed lines too,
    ``FileTools.py:17-18``).
    """
    obsinfo: dict[str, str] = {}
    for line in text.splitlines():
        parts = line.split()
        if len(parts) != 4:
            continue
        obsid_s, target, day, time_s = parts
        if not obsid_s.isdigit():
            continue
        stamp = None
        for fmt in ("%Y-%m-%d %H:%M:%S.%f", "%Y-%m-%d %H:%M:%S"):
            try:
                stamp = datetime.strptime(f"{day} {time_s}", fmt)
                break
            except ValueError:
                continue
        if stamp is None:
            continue
        filename = _FILENAME_FMT.format(
            obsid=int(obsid_s), stamp=stamp.strftime("%Y-%m-%d-%H%M%S"),
            suffix=suffix)
        obsinfo[filename] = target
    return obsinfo


def query_obs_metadata(server: str | None, script_argv,
                       suffix: str = "_Level2Cont",
                       timeout: float = 120.0) -> dict[str, str]:
    """Run the archive metadata script and parse its output.

    ``server=None`` runs ``script_argv`` locally; otherwise it is wrapped
    in ``ssh server ...``. ``script_argv`` may be an argv list or a
    command string (split with :func:`shlex.split`, so both paths agree
    on word boundaries). No local shell is involved, and for the ssh
    path the command is re-quoted with :func:`shlex.join` so the remote
    login shell sees exactly the given argv — embedded metacharacters
    are not reinterpreted on either side. A dead archive host raises
    instead of returning an empty dict silently.
    """
    if isinstance(script_argv, str):
        script_argv = shlex.split(script_argv)
    argv = [str(a) for a in script_argv]
    if server is not None:
        # "--" BEFORE the destination (ssh's getopt does not permute;
        # anything after the first non-option word is the remote command)
        argv = ["ssh", "--", server, shlex.join(argv)]
    out = subprocess.run(argv, capture_output=True, text=True,
                         timeout=timeout, check=True)
    info = parse_obsinfo(out.stdout, suffix=suffix)
    logger.info("query_obs_metadata: %d observations from %s",
                len(info), server or "localhost")
    return info


def obsinfo_from_database(db, suffix: str = "_Level2Cont",
                          source: str | None = None) -> dict[str, str]:
    """``{filename: target}`` from a local obs database — the offline
    equivalent of the SSH query. The filename stamp encodes the
    observation *start* time (``mjd_start`` attr, as harvested by
    ``ObsDatabase.update_from_level2``); records without it are skipped
    with a warning — a stamp fabricated from the mean MJD would yield
    keys that never match real archive filenames."""
    out: dict[str, str] = {}
    skipped = 0
    for obsid in db.obsids():
        target = db.get_attr(obsid, "source")
        if target is None:
            continue
        mjd = db.get_attr(obsid, "mjd_start")
        if mjd is None:
            skipped += 1
            continue
        target = str(target)
        if source is not None and target != source:
            continue
        # MJD 40587 = Unix epoch; render in UTC so filenames are
        # host-timezone independent
        stamp = datetime.fromtimestamp(
            (float(mjd) - 40587.0) * 86400.0,
            tz=timezone.utc).strftime("%Y-%m-%d-%H%M%S")
        out[_FILENAME_FMT.format(obsid=int(obsid), stamp=stamp,
                                 suffix=suffix)] = target
    if skipped:
        logger.warning("obsinfo_from_database: %d records lack mjd_start "
                       "(pre-upgrade harvest) — re-run update_from_level2 "
                       "to include them", skipped)
    return out
