"""Compiled-program cost/memory registry (ISSUE 15).

Every steady-state XLA program the pipeline compiles — the campaign
warmup's stage fits, the destriper's planned solvers, the bench
harness's kernels — knows its own FLOP count, bytes accessed, and HBM
footprint via ``compiled.cost_analysis()`` / ``memory_analysis()``,
but until this module those numbers were scattered across two ad-hoc
bench.py calls and hand-transcribed into ROOFLINE.md. The ``PROGRAMS``
singleton captures them at the compile sites, keyed by stable program
name x shape bucket x precision id, deduped in-process, and appended
torn-line-safe to ``programs.jsonl`` under ``[Global] log_dir`` (it
rides ``TELEMETRY.configure`` — telemetry on means the program
registry is on).

Record schema (one JSON object per line)::

    {"schema": 1, "kind": "program", "name": "destriper.multigrid",
     "shape_bucket": "f32[262144]x2", "precision_id": "tod=float32",
     "backend": "cpu", "rank": 0, "t": "2026-08-05T07:00:00Z",
     "flops": 1.2e9, "bytes_accessed": 3.4e8,
     "argument_bytes": 2097152, "output_bytes": 1048576,
     "temp_bytes": 524288, "code_bytes": 40960}

Analysis keys are best-effort per backend (CPU may lack a memory
analysis; missing keys are simply absent, never errors). The
machine-independent HBM-regression gate (``tools/check_perf.py``)
compares per-program ``temp_bytes + output_bytes`` against a committed
baseline via :func:`hbm_regressions`; ``tools/roofline_report.py``
merges the registry with measured walls.
"""

from __future__ import annotations

import glob as _glob
import json
import logging
import os
import threading
import time

__all__ = ["PROGRAMS", "ProgramRegistry", "analyze", "hbm_regressions",
           "program_key", "programs_path", "read_programs",
           "shape_bucket"]

logger = logging.getLogger("comapreduce_tpu")

PROGRAMS_SCHEMA = 1

# HBM gate slack: temp+output bytes are exact counts from XLA's buffer
# assignment (machine-independent for a fixed backend), but minor
# version-to-version layout drift should not page anyone — a quarter
# over baseline is a real regression, 2% is noise
HBM_SLACK = 1.25


def programs_path(directory: str) -> str:
    return os.path.join(directory or ".", "programs.jsonl")


def shape_bucket(*args, **kwargs) -> str:
    """A stable shape signature from example arguments (arrays or
    ShapeDtypeStructs): ``f32[4096,64]xf32[4096]`` — the same bucketing
    the campaign warmup keys programs by. Non-array leaves are skipped;
    long signatures truncate with a ``+N`` tail."""
    try:
        import jax

        leaves = jax.tree.util.tree_leaves((args, kwargs))
    except Exception:
        leaves = [a for a in args] + list(kwargs.values())
    parts = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        name = getattr(dtype, "name", str(dtype))
        short = {"float32": "f32", "float64": "f64", "bfloat16": "bf16",
                 "float16": "f16", "int32": "i32", "int64": "i64",
                 "uint32": "u32", "bool": "b1"}.get(name, name)
        parts.append(f"{short}[{','.join(str(d) for d in shape)}]")
    if len(parts) > 12:
        parts, extra = parts[:12], len(parts) - 12
        parts.append(f"+{extra}")
    return "x".join(parts)


def program_key(name: str, bucket: str = "",
                precision_id: str = "", kernels: str = "") -> str:
    """Registry identity of one compiled program.

    ``kernels`` is the RESOLVED binning/gather implementation the
    program compiled with ('xla'|'pallas'|'interpret' — never 'auto'):
    the same (name, bucket, precision) triple compiles to genuinely
    different programs per implementation, and folding them onto one
    key would let whichever ran last overwrite the other's HBM
    baseline. Appended only when non-empty, so keys from stages that
    predate the field (and every non-destriper program) stay stable."""
    key = f"{name}|{bucket}|{precision_id}"
    if kernels:
        key = f"{key}|kernels={kernels}"
    return key


def analyze(compiled) -> dict:
    """Best-effort cost + memory analysis of one compiled executable.

    ``cost_analysis()`` may return a list/tuple (one dict per
    computation — take the first, bench.py's long-standing idiom) or a
    dict; ``memory_analysis()`` exposes sizes as attributes and may be
    absent entirely on some backends. Whatever the backend won't say
    is simply missing from the result."""
    out: dict = {}
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if isinstance(cost, dict):
            if "flops" in cost:
                out["flops"] = float(cost["flops"])
            if "bytes accessed" in cost:
                out["bytes_accessed"] = float(cost["bytes accessed"])
    except Exception:
        pass
    try:
        mem = compiled.memory_analysis()
        for attr, key in (("argument_size_in_bytes", "argument_bytes"),
                          ("output_size_in_bytes", "output_bytes"),
                          ("temp_size_in_bytes", "temp_bytes"),
                          ("alias_size_in_bytes", "alias_bytes"),
                          ("generated_code_size_in_bytes",
                           "code_bytes")):
            v = getattr(mem, attr, None)
            if v is not None:
                out[key] = int(v)
    except Exception:
        pass
    return out


class ProgramRegistry:
    """Process-wide compiled-program registry (the TELEMETRY shape:
    disabled it costs one attribute check; ``configure`` rides
    ``Telemetry.configure`` so there is no second knob to forget)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._enabled = False
        self._path = ""
        self._rank = 0
        self._seen: set = set()
        self._records: list = []

    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def path(self) -> str:
        return self._path

    def configure(self, log_dir: str, rank: int = 0) -> "ProgramRegistry":
        with self._lock:
            self._path = programs_path(log_dir)
            self._rank = int(rank)
            self._enabled = True
        return self

    def close(self) -> None:
        with self._lock:
            self._enabled = False
            self._seen.clear()
            self._records.clear()

    def seen(self, name: str, bucket: str = "",
             precision_id: str = "", kernels: str = "") -> bool:
        """Dedup probe — callers about to pay an AOT lower+compile just
        to feed the registry should skip when the key is already
        recorded (``record_jit`` does)."""
        return program_key(name, bucket, precision_id,
                           kernels) in self._seen

    def snapshot(self) -> list:
        with self._lock:
            return list(self._records)

    def record(self, name: str, compiled, *, shape_bucket: str = "",
               precision_id: str = "", kernels: str = "",
               extra: dict | None = None):
        """Analyze one compiled executable and append its record.
        Duplicate (name, bucket, precision, kernels) keys are dropped —
        warmup re-runs re-compile the same programs, they don't
        re-count. ``kernels`` is the RESOLVED matvec implementation
        (see :func:`program_key`) — without it the xla and pallas
        compiles of one destriper program collide on one key and the
        last writer corrupts the HBM gate baseline."""
        if not self._enabled:
            return None
        key = program_key(name, shape_bucket, precision_id, kernels)
        with self._lock:
            if key in self._seen:
                return None
            self._seen.add(key)
        try:
            import jax

            backend = jax.default_backend()
        except Exception:
            backend = ""
        rec = {"schema": PROGRAMS_SCHEMA, "kind": "program",
               "name": str(name), "shape_bucket": shape_bucket,
               "precision_id": precision_id, "backend": backend,
               "rank": self._rank,
               "t": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
        if kernels:
            rec["kernels"] = str(kernels)
        rec.update(analyze(compiled))
        if extra:
            rec.update(extra)
        with self._lock:
            self._records.append(rec)
        self._append([rec])
        try:
            from comapreduce_tpu.telemetry.core import TELEMETRY

            TELEMETRY.counter("programs.recorded", 1, name=str(name))
        except Exception:
            pass
        return rec

    def record_jit(self, name: str, fn, *args, precision_id: str = "",
                   bucket: str | None = None, kernels: str = "",
                   **kwargs):
        """Record a ``jax.jit`` function by AOT-compiling it for the
        given example arguments. The dedup probe runs FIRST: the
        lower+compile (which does not share the jit call cache) is paid
        at most once per distinct program, and any failure is swallowed
        — the registry observes, it never breaks a solve."""
        if not self._enabled:
            return None
        if bucket is None:
            bucket = shape_bucket(*args, **kwargs)
        if self.seen(name, bucket, precision_id, kernels):
            return None
        try:
            compiled = fn.lower(*args, **kwargs).compile()
        except Exception as exc:
            logger.debug("programs: AOT compile of %s failed (%s: %s)",
                         name, type(exc).__name__, exc)
            return None
        return self.record(name, compiled, shape_bucket=bucket,
                           precision_id=precision_id, kernels=kernels)

    def _append(self, records: list) -> None:
        """The quality ledger's torn-line-safe append discipline; the
        single shared ``programs.jsonl`` is safe for multi-rank appends
        because each record lands in ONE O_APPEND write."""
        if not self._path:
            return
        try:
            os.makedirs(os.path.dirname(self._path) or ".",
                        exist_ok=True)
            needs_nl = False
            try:
                with open(self._path, "rb") as f:
                    f.seek(-1, os.SEEK_END)
                    needs_nl = f.read(1) != b"\n"
            except OSError:
                pass
            payload = "".join(
                json.dumps(r, separators=(",", ":")) + "\n"
                for r in records)
            with open(self._path, "a", encoding="utf-8") as f:
                f.write(("\n" if needs_nl else "") + payload)
                f.flush()
                os.fsync(f.fileno())
        except OSError as exc:
            logger.warning("programs registry append to %s failed "
                           "(%s: %s)", self._path,
                           type(exc).__name__, exc)


PROGRAMS = ProgramRegistry()


def read_programs(source) -> list:
    """Program records from a directory (its ``programs.jsonl``), one
    path, or a list of paths — latest-wins per (name, shape_bucket,
    precision_id), torn lines dropped."""
    if isinstance(source, (list, tuple)):
        paths = [str(p) for p in source]
    elif os.path.isdir(source):
        paths = sorted(_glob.glob(os.path.join(source,
                                               "programs*.jsonl")))
    else:
        paths = [str(source)]
    latest: dict = {}
    for path in paths:
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            continue
        for line in raw.split(b"\n"):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except Exception:
                continue
            if not isinstance(rec, dict) or rec.get("kind") != "program":
                continue
            key = program_key(rec.get("name", ""),
                              rec.get("shape_bucket", ""),
                              rec.get("precision_id", ""),
                              rec.get("kernels", ""))
            latest[key] = rec
    return [latest[k] for k in sorted(latest)]


def hbm_regressions(current: list, baseline: dict,
                    slack: float = HBM_SLACK) -> list:
    """The machine-independent HBM gate: per-program
    ``temp_bytes + output_bytes`` against a committed baseline.

    ``current`` — program records (:func:`read_programs` /
    ``PROGRAMS.snapshot()``); ``baseline`` — ``{key: hbm_bytes}`` as
    written by ``check_perf --update``. Returns failure strings (empty
    = pass). New programs and programs the bench no longer compiles are
    reported by the caller as informational, never failures — byte
    GROWTH on a program both sides know is the regression signal."""
    failures = []
    for rec in current:
        key = program_key(rec.get("name", ""),
                          rec.get("shape_bucket", ""),
                          rec.get("precision_id", ""),
                          rec.get("kernels", ""))
        hbm = (rec.get("temp_bytes") or 0) + (rec.get("output_bytes")
                                              or 0)
        base = baseline.get(key)
        if base is None or base <= 0 or hbm <= 0:
            continue
        if hbm > base * slack:
            failures.append(
                f"program HBM regression: {key} temp+output "
                f"{hbm} B > baseline {base} B x {slack:.2f}")
    return failures
