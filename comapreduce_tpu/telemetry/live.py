"""The live observability plane: tail the event streams, serve HTTP.

``tools/campaign_report.py`` is post-hoc — it merges a FINISHED run's
streams. This module watches a RUNNING campaign:

- :class:`LiveTail` incrementally tails every ``events.rank*.jsonl``
  in a state directory with per-file byte offsets, consuming only
  complete lines (a torn tail from an in-flight write — or from a
  crashed writer, later healed by the flush discipline's prepended
  newline — is simply left for the next poll; a complete-but-torn line
  is dropped like every JSONL reader here). Counters accumulate,
  gauges keep the last level, span durations feed bounded p50/p95
  windows — all without re-reading a byte twice.
- :class:`LiveServer` is a stdlib HTTP sidecar in the style of
  ``tiles/http.py``:

  ==================  ==============================================
  ``/metrics``        Prometheus text (the ``prom_snapshot`` format
                      family): counter totals, gauge levels, span
                      p50/p95 summaries — plus live heartbeat ages,
                      scheduler queue depth, serving freshness and
                      quality-ledger flag counts.
  ``/healthz``        exit-code-honest liveness: 200 when every
                      expected rank beats within ``stale_s`` and no
                      lease is expired-unreclaimed, 503 otherwise
                      (same :func:`resilience.status.report_healthy`
                      rule as ``watchdog_report``'s exit code).
  ``/v1/campaign``    the schema-2 watchdog report as JSON.
  ``/v1/quality``     quality-ledger summary (records, flags, worst
                      feeds by knee).
  ==================  ==============================================

Exposed via ``tools/campaign_watch.py`` (serve/status/check) and the
``--live-port`` flag on ``run_average`` / ``run_destriper`` /
``map_server.py serve``. Scrapes never write: the plane is a read-only
observer of the same on-disk state every other consumer uses, so it
can run inside a rank, beside one, or on another host sharing the
filesystem.
"""

from __future__ import annotations

import collections
import hashlib
import json
import logging
import math
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlsplit

from comapreduce_tpu.resilience.status import (build_report,
                                               report_healthy,
                                               resolve_state_dir)
from comapreduce_tpu.resilience.watchdog import percentile
from comapreduce_tpu.telemetry.core import RequestMetrics
from comapreduce_tpu.telemetry.quality import flag_counts, read_quality
from comapreduce_tpu.telemetry.report import _prom_name

__all__ = ["LiveServer", "LiveTail", "PROM_CONTENT_TYPE"]

logger = logging.getLogger("comapreduce_tpu")

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
_JSON = "application/json"

_RANK_RE = re.compile(r"events\.rank(\d+)\.jsonl$")

#: span-duration window per name: quantiles are over the most recent
#: samples (a live plane answers "how slow is it NOW"), while count
#: and sum stay whole-history so rates and totals are exact
SPAN_WINDOW = 2048


class LiveTail:
    """Incremental, torn-line-tolerant tail over a directory's
    ``events.rank*.jsonl`` streams. :meth:`poll` consumes whatever
    complete lines appeared since the last poll; accessors read the
    accumulated state. Not thread-safe by itself — the server
    serialises polls under a lock."""

    #: stream-identity fingerprint window: sha1 over the first bytes of
    #: a stream (its meta anchor — pid + wall0/mono0 differ per writer
    #: — plus the first real events), so a replaced file is
    #: distinguishable from a grown one. 4 KiB (not the old 64-byte raw
    #: prefix, PR 15's documented blind spot: a same-size rewrite
    #: differing only past byte 64 read as no-change) — combined with
    #: the mtime_ns + size tiebreak this catches any rewrite that
    #: touches the first page, while a metadata-only touch (equal
    #: content, new mtime) keeps its offset
    HEAD_BYTES = 4096

    def __init__(self, log_dir: str):
        self.log_dir = log_dir or "."
        # path -> {"offset", "rank", "align", "mtime", "head"}
        self._files: dict = {}
        self.counters: dict = {}  # (name, rank) -> total
        self.gauges: dict = {}    # (name, rank) -> last value
        self.span_windows: dict = {}  # name -> deque[dur]
        self.span_totals: dict = {}   # name -> [count, sum]
        self.last_event_t: dict = {}  # rank -> aligned wall seconds
        # rank -> deque[(iteration, log10_residual, threshold)] from
        # solver.log10_residual gauges: the ETA slope fit's input
        self.solver_history: dict = {}
        self.dropped_lines = 0
        self.events_consumed = 0

    def poll(self) -> int:
        """Consume new complete lines from every stream; returns the
        number of events absorbed this poll."""
        import glob as _glob

        absorbed = 0
        for path in sorted(_glob.glob(os.path.join(
                self.log_dir, "events.rank*.jsonl"))):
            absorbed += self._poll_file(path)
        return absorbed

    def _poll_file(self, path: str) -> int:
        m = _RANK_RE.search(path)
        state = self._files.get(path)
        if state is None:
            state = self._files[path] = {
                "offset": 0, "rank": int(m.group(1)) if m else 0,
                "align": 0.0, "mtime": -1, "head": None}
        try:
            st = os.stat(path)
        except OSError:
            return 0
        size = st.st_size
        if size < state["offset"]:
            state["offset"] = 0  # replaced/rotated stream: restart
        elif state["offset"] and st.st_mtime_ns != state["mtime"]:
            # a stream REPLACED at equal-or-larger size passes the size
            # checks (the equal-size rewrite was PR 14's documented
            # blind spot): when the mtime moved, re-verify the stream's
            # identity by its head-hash fingerprint and restart from
            # byte 0 on a mismatch — re-absorbing accumulates counters,
            # exactly the shrink case's semantics. A plain append (or a
            # metadata-only touch) keeps the fingerprint and the offset.
            if state["head"] is None \
                    or self._fingerprint(path,
                                         state["head"][0]) != state["head"]:
                state["offset"] = 0
        if size == state["offset"]:
            state["mtime"] = st.st_mtime_ns
            return 0
        started_at_zero = state["offset"] == 0
        try:
            with open(path, "rb") as f:
                f.seek(state["offset"])
                chunk = f.read()
        except OSError:
            return 0
        # consume only COMPLETE lines: a partial tail is an append in
        # flight (or a crashed writer's stump the next flush will
        # heal) — leave it for a later poll, never parse half a record
        cut = chunk.rfind(b"\n")
        if cut < 0:
            return 0
        state["offset"] += cut + 1
        if started_at_zero:
            head = chunk[:self.HEAD_BYTES]
            state["head"] = (len(head), hashlib.sha1(head).hexdigest())
        state["mtime"] = st.st_mtime_ns
        n = 0
        for line in chunk[:cut].split(b"\n"):
            if not line.strip():
                continue
            try:
                ev = json.loads(line)
            except Exception:
                self.dropped_lines += 1
                continue
            if not isinstance(ev, dict):
                self.dropped_lines += 1
                continue
            self._absorb(ev, state)
            n += 1
        self.events_consumed += n
        return n

    def _fingerprint(self, path: str, length: int):
        """(length, sha1) over the file's first ``length`` bytes —
        compared against the fingerprint captured when the stream was
        first consumed; None (unreadable) never matches."""
        try:
            with open(path, "rb") as f:
                head = f.read(length)
        except OSError:
            return None
        return (len(head), hashlib.sha1(head).hexdigest())

    def _absorb(self, ev: dict, state: dict) -> None:
        kind = ev.get("kind")
        if kind == "meta":
            state["rank"] = int(ev.get("rank", state["rank"]))
            state["align"] = float(ev.get("wall0", 0.0)) \
                - float(ev.get("mono0", 0.0))
            return
        rank = state["rank"]
        t = float(ev.get("mono", 0.0)) + state["align"]
        if kind == "counter":
            key = (ev.get("name", ""), rank)
            self.counters[key] = self.counters.get(key, 0.0) \
                + float(ev.get("value", 0.0))
        elif kind == "gauge":
            name = ev.get("name", "")
            value = float(ev.get("value", 0.0))
            self.gauges[(name, rank)] = value
            if name == "solver.log10_residual":
                # the solver trace stamps the iteration ON the gauge
                # (no event-ordering games): the history feeds the
                # /metrics slope-fit ETA
                attrs = ev.get("attrs") or {}
                hist = self.solver_history.get(rank)
                if hist is None:
                    hist = self.solver_history[rank] = \
                        collections.deque(maxlen=SPAN_WINDOW)
                hist.append((float(attrs.get("iteration", -1.0)), value,
                             float(attrs.get("threshold", 0.0))))
        elif kind == "span":
            attrs = ev.get("attrs") or {}
            if not attrs.get("skipped"):
                name = ev.get("name", "")
                win = self.span_windows.get(name)
                if win is None:
                    win = self.span_windows[name] = \
                        collections.deque(maxlen=SPAN_WINDOW)
                tot = self.span_totals.setdefault(name, [0, 0.0])
                dur = float(ev.get("dur", 0.0))
                win.append(dur)
                tot[0] += 1
                tot[1] += dur
                t += dur
        # 'begin' advances the liveness clock too: an open span is
        # still evidence the rank was alive at its start
        self.last_event_t[rank] = max(self.last_event_t.get(rank, 0.0),
                                      t)

    def counter_total(self, name: str) -> float:
        """One counter summed across ranks (e.g. the scheduler's
        ``scheduler.committed`` — the live file-done count)."""
        return sum(v for (n, _r), v in self.counters.items()
                   if n == name)


class _HTTPError(Exception):
    def __init__(self, status: int, msg: str):
        super().__init__(msg)
        self.status = status


class LiveServer:
    """Serve one campaign state directory's live view over HTTP.

    ``port=0`` binds an ephemeral port (tests/drills); the bound port
    is ``self.port``. ``stale_s`` is the /healthz heartbeat TTL (pass
    the campaign's ``lease_ttl_s``); ``n_ranks`` pins the expected
    rank count so a rank that never wrote a heartbeat still fails the
    probe. Run with :meth:`serve_forever` (blocking) or :meth:`start`
    (daemon thread — the sidecar mode the CLIs use).
    """

    def __init__(self, state_dir: str, host: str = "127.0.0.1",
                 port: int = 0, *, stale_s: float = 60.0,
                 n_ranks: int = 0, stats_path: str = ""):
        self.root = state_dir or "."
        self.stale_s = float(stale_s)
        self.n_ranks = int(n_ranks)
        # the map server's stats file lives in its EPOCHS root, not the
        # campaign state dir — pass it when serving beside one
        self.stats_path = str(stats_path or "")
        self._lock = threading.Lock()
        self._tail: LiveTail | None = None
        self.stats = {"t_start_unix": time.time(), "n_requests": 0,
                      "n_errors": 0, "by_route": {}}
        # per-request latency histogram + route/status counters, the
        # schema tiles/http.py shares (ISSUE 15) — the sidecar measures
        # itself on the same page it serves
        self.request_metrics = RequestMetrics("live_http")
        self.httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self.httpd.daemon_threads = True
        self.httpd.app = self
        self.host, self.port = self.httpd.server_address[:2]
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def serve_forever(self) -> None:
        logger.info("live plane on http://%s:%d/ (state %s)", self.host,
                    self.port, self.root)
        self.httpd.serve_forever(poll_interval=0.2)

    def start(self) -> "LiveServer":
        self._thread = threading.Thread(target=self.serve_forever,
                                        name="live-plane", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- shared state ------------------------------------------------------

    def _state_dir(self) -> str:
        # resolved per request: the logs/ child may not exist until
        # the campaign's first write
        return resolve_state_dir(self.root)

    def tail(self) -> LiveTail:
        """Poll-and-return the (lazily created) stream tail."""
        with self._lock:
            d = self._state_dir()
            if self._tail is None or self._tail.log_dir != d:
                self._tail = LiveTail(d)
            self._tail.poll()
            return self._tail

    def report(self) -> dict:
        return build_report(self.root, stale_s=self.stale_s,
                            n_ranks=self.n_ranks)

    # -- routing -----------------------------------------------------------

    def handle(self, path: str) -> tuple[str, int, str, bytes]:
        """``(route, status, content_type, body)`` for one request."""
        parts = [p for p in path.split("/") if p]
        if parts == ["metrics"]:
            return ("metrics", 200, PROM_CONTENT_TYPE,
                    self.prom_text().encode("utf-8"))
        if parts == ["healthz"]:
            rep = self.report()
            ok = report_healthy(rep)
            body = json.dumps(
                {"ok": ok, "n_stale": rep["n_stale"],
                 "n_expired_leases": rep["n_expired_leases"],
                 "stale_s": rep["stale_s"],
                 "ranks": [{"rank": r["rank"],
                            "stale": r["stale"],
                            "age_s": r.get("age_s")}
                           for r in rep["ranks"]]},
                sort_keys=True).encode("utf-8") + b"\n"
            return "healthz", (200 if ok else 503), _JSON, body
        if parts == ["v1", "campaign"]:
            return ("campaign", 200, _JSON,
                    json.dumps(self.report(), sort_keys=True)
                    .encode("utf-8") + b"\n")
        if parts == ["v1", "quality"]:
            return ("quality", 200, _JSON,
                    json.dumps(self.quality_summary(), sort_keys=True)
                    .encode("utf-8") + b"\n")
        raise _HTTPError(404, f"no route for {path} (want /metrics, "
                              "/healthz, /v1/campaign, /v1/quality)")

    def quality_summary(self) -> dict:
        from comapreduce_tpu.telemetry.quality import worst_feeds

        records = read_quality(self._state_dir())
        return {
            "n_records": len(records),
            "n_flagged": sum(1 for r in records if r.get("flagged")),
            "flag_counts": flag_counts(records),
            "worst_feeds": [
                {"file": r["file"], "feed": r["feed"],
                 "band": r["band"], "fknee_hz": r["fknee_hz"]}
                for r in worst_feeds(records, 5)],
        }

    # -- /metrics rendering ------------------------------------------------

    def prom_text(self) -> str:
        """The live Prometheus page: the tail's counters/gauges/span
        summaries in ``prom_snapshot``'s exact format family, then the
        campaign-state gauges only a live observer can provide."""
        tail = self.tail()
        out = []
        for (name, rank), total in sorted(tail.counters.items()):
            mname = _prom_name(name) + "_total"
            out.append(f"# TYPE {mname} counter")
            out.append(f'{mname}{{rank="{rank}"}} {total:g}')
        for (name, rank), value in sorted(tail.gauges.items()):
            mname = _prom_name(name)
            out.append(f"# TYPE {mname} gauge")
            out.append(f'{mname}{{rank="{rank}"}} {value:g}')
        for name in sorted(tail.span_windows):
            win = list(tail.span_windows[name])
            if not win:
                continue
            count, total = tail.span_totals[name]
            base = _prom_name(name) + "_seconds"
            out.append(f"# TYPE {base} summary")
            for q in (50.0, 95.0):
                out.append(f'{base}{{quantile="{q / 100:g}"}} '
                           f"{percentile(win, q):g}")
            out.append(f"{base}_sum {total:g}")
            out.append(f"{base}_count {count}")
        out.extend(self._solver_metrics(tail))
        out.extend(self._campaign_metrics())
        out.extend(self.request_metrics.prom_lines())
        out.append(f"# TYPE comap_live_dropped_lines counter")
        out.append(f"comap_live_dropped_lines {tail.dropped_lines}")
        return "\n".join(out) + "\n"

    def _solver_metrics(self, tail: LiveTail) -> list:
        """The slope-based iters-to-tolerance ETA: fit the
        log10-residual history (iteration-stamped gauge samples) per
        rank and extrapolate to the solve's threshold. -1 means
        'stalled or diverging' (non-negative slope); no line at all
        means no solver has reported yet. The raw progress gauges
        (``comap_solver_iteration`` etc.) ride the generic gauge path
        above."""
        out = []
        for rank in sorted(tail.solver_history):
            hist = [h for h in tail.solver_history[rank]
                    if h[0] >= 0.0]
            if len(hist) < 2:
                continue
            (i0, r0, _), (i1, r1, thr) = hist[0], hist[-1]
            if i1 <= i0:
                continue
            slope = (r1 - r0) / (i1 - i0)  # decades per iteration
            target = math.log10(max(thr, 1e-300)) if thr > 0 else None
            if target is None:
                continue
            if r1 <= target:
                eta = 0.0
            elif slope < 0:
                eta = (target - r1) / slope
            else:
                eta = -1.0
            out.append("# TYPE comap_solver_eta_iters gauge")
            out.append(f'comap_solver_eta_iters{{rank="{rank}"}} '
                       f"{eta:g}")
        return out

    def _campaign_metrics(self) -> list:
        rep = self.report()
        out = []

        def gauge(name, value, labels=""):
            out.append(f"# TYPE {name} gauge")
            out.append(f"{name}{labels} {value:g}")

        for r in rep["ranks"]:
            labels = f'{{rank="{r["rank"]}"}}'
            if r.get("present"):
                out.append("# TYPE comap_live_heartbeat_age_seconds "
                           "gauge")
                out.append(
                    f"comap_live_heartbeat_age_seconds{labels} "
                    f"{r['age_s']:g}")
            out.append("# TYPE comap_live_rank_stale gauge")
            out.append(f"comap_live_rank_stale{labels} "
                       f"{1 if r['stale'] else 0}")
        gauge("comap_live_ranks_stale", rep["n_stale"])
        gauge("comap_live_expired_leases", rep["n_expired_leases"])
        gauge("comap_live_healthy", 1 if report_healthy(rep) else 0)
        # integrity plane (docs/OPERATIONS.md §20): ledger-derived, so
        # corruption found by any past rank surfaces even when no live
        # rank is currently ticking comap_integrity_violations_total
        gauge("comap_integrity_corrupt_artifacts",
              rep.get("n_corrupt", 0))
        gauge("comap_integrity_corrupt_ledger_lines",
              rep.get("n_corrupt_ledger_lines", 0))
        q = rep.get("queue")
        if q:
            for k in ("n_files", "n_done", "n_claimed", "n_pending",
                      "n_torn"):
                gauge(f"comap_live_queue_{k[2:]}", q[k])
        out.extend(self._freshness_metrics())
        records = read_quality(self._state_dir())
        gauge("comap_quality_records", len(records))
        gauge("comap_quality_flagged",
              sum(1 for r in records if r.get("flagged")))
        for rule, n in sorted(flag_counts(records).items()):
            out.append("# TYPE comap_quality_flags gauge")
            out.append(f'comap_quality_flags{{rule="{rule}"}} {n}')
        return out

    def _freshness_metrics(self) -> list:
        """Serving freshness: the age of the newest committed unit
        (from the done leases), and — when a map server shares the
        state dir — its published epoch + stats-file age."""
        out = []
        now = time.time()
        d = self._state_dir()
        try:
            from comapreduce_tpu.serving.watcher import scan_committed

            done = scan_committed(d)
        except Exception:
            done = {}
        stamps = [float(p.get("t_done_unix", 0.0))
                  for p in done.values() if p.get("t_done_unix")]
        if stamps:
            out.append("# TYPE comap_live_commit_freshness_seconds "
                       "gauge")
            out.append(f"comap_live_commit_freshness_seconds "
                       f"{max(0.0, now - max(stamps)):g}")
        stats_path = self.stats_path \
            or os.path.join(d, "server.stats.json")
        try:
            with open(stats_path, "r", encoding="utf-8") as f:
                st = json.load(f)
        except (OSError, ValueError):
            return out
        if st.get("current_epoch") is not None:
            out.append("# TYPE comap_live_serving_epoch gauge")
            out.append(f"comap_live_serving_epoch "
                       f"{int(st['current_epoch'])}")
        if st.get("t_update_unix"):
            out.append("# TYPE comap_live_serving_freshness_seconds "
                       "gauge")
            out.append(
                f"comap_live_serving_freshness_seconds "
                f"{max(0.0, now - float(st['t_update_unix'])):g}")
        return out

    def _account(self, route: str, status: int,
                 dur_s: float = 0.0) -> None:
        self.request_metrics.observe(route, status, dur_s)
        with self._lock:
            self.stats["n_requests"] += 1
            if status >= 500 and route != "healthz":
                self.stats["n_errors"] += 1
            br = self.stats["by_route"]
            br[route] = br.get(route, 0) + 1


class _Handler(BaseHTTPRequestHandler):
    server_version = "comap-live/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: A003 - stdlib signature
        logger.debug("live-plane %s - %s", self.address_string(),
                     fmt % args)

    def do_GET(self):  # noqa: N802 - stdlib casing
        self._serve(send_body=True)

    def do_HEAD(self):  # noqa: N802 - stdlib casing
        self._serve(send_body=False)

    def _serve(self, send_body: bool) -> None:
        app: LiveServer = self.server.app
        url = urlsplit(self.path)
        route = "error"
        t0 = time.monotonic()
        try:
            route, status, ctype, body = app.handle(url.path)
        except _HTTPError as exc:
            status, ctype = exc.status, _JSON
            body = json.dumps({"error": str(exc)}).encode("utf-8") \
                + b"\n"
        except Exception as exc:  # a bug must 500, not kill the thread
            logger.exception("live-plane error on %s", self.path)
            status, ctype = 500, _JSON
            body = json.dumps({"error": f"internal: {exc}"}) \
                .encode("utf-8") + b"\n"
        # account BEFORE writing the response (but after rendering, so
        # a scrape never includes itself): each connection gets its own
        # handler thread, so a client that has read response N can race
        # a post-write account line and scrape N+1 without N's request
        # in it. The measured duration excludes the socket write — the
        # histogram prices rendering, which is the part we own.
        app._account(route, status, time.monotonic() - t0)
        try:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.send_header("Cache-Control", "no-cache")
            self.end_headers()
            if send_body:
                self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # reader hung up mid-write; nothing to do
