"""Per-iteration CG solver traces — the convergence plane (ISSUE 15).

The destriper's CG loop *is* the production cost model: the map-making
literature (MAPPRAISER, arXiv 2112.03370; the preconditioner surveys,
arXiv 1309.7473) evaluates entirely in iterations-to-tolerance, yet
until this module the loop reported two scalars (final iteration count
and residual) per solve. ``destriper._cg_loop`` now optionally carries
per-iteration histories of the true residual ``|r|^2``, alpha and beta
through the while-loop state (``trace_n``); the host renders them here
into ``solver.rank{r}.jsonl`` under ``[Global] log_dir`` with the
quarantine ledger's torn-line-safe append discipline, annotated with
the band, preconditioner id, precision id, and divergence/stagnation
marks, and mirrors the solve's progress onto live telemetry gauges
(``solver.band`` / ``solver.iteration`` / ``solver.log10_residual``)
so the ``/metrics`` sidecar can show a slope-based iters-to-tolerance
ETA mid-solve.

Record schema (one JSON object per line)::

    {"schema": 1, "kind": "iteration", "band": "band0", "iter": 12,
     "residual": 3.2e-4, "rr": 1.1e-7, "alpha": 0.9, "beta": 0.4,
     "precond_id": "multigrid|...", "precision_id": "tod=float32|...",
     "threshold": 1e-6, "rank": 0, "diverging": false}

    {"schema": 1, "kind": "solve", "band": "band0", "n_iter": 48,
     "residual": 8.8e-7, "converged": true, "diverged": false,
     "stalled": false, "stalled_at": null, "base": 0,
     "precond_id": "...", "precision_id": "...", "threshold": 1e-6,
     "rank": 0, "t": "2026-08-05T07:00:00Z"}

``iter`` is the GLOBAL iteration index: chunked solves
(``solve_band_checkpointed``) pass ``base=n_done`` so a resumed run's
trace continues numbering where the previous chunk stopped. Readers
(``tools/solver_report.py``, the live plane) drop unparseable lines
like every JSONL reader here.
"""

from __future__ import annotations

import glob as _glob
import json
import logging
import math
import os
import re
import time

import numpy as np

from comapreduce_tpu.telemetry.core import TELEMETRY

__all__ = ["SOLVER_SCHEMA", "STALL_SLOPE", "STALL_WINDOW",
           "append_solver", "iteration_records", "read_solver",
           "record_solve", "solve_summary", "solver_path",
           "trace_enabled"]

logger = logging.getLogger("comapreduce_tpu")

SOLVER_SCHEMA = 1

# mirrors the in-loop divergence monitor (destriper.DIVERGENCE_GROWTH):
# an iteration whose |r|^2 sits more than this factor above the best
# seen so far is annotated "diverging" in its record
_DIVERGING_GROWTH = 100.0

# stagnation: over the trailing STALL_WINDOW iterations of an
# UNCONVERGED solve, a log10-residual slope shallower (less negative)
# than -STALL_SLOPE decades/iteration marks the solve stalled — the
# preconditioner has stopped buying progress
STALL_SLOPE = 1e-3
STALL_WINDOW = 25

_SOLVER_RE = re.compile(r"solver\.rank(\d+)\.jsonl$")


def solver_path(directory: str, rank: int = 0) -> str:
    return os.path.join(directory or ".",
                        f"solver.rank{int(rank)}.jsonl")


def trace_enabled() -> bool:
    """The solver trace rides the telemetry switch: traced programs
    carry three scalar scatters per iteration (negligible next to one
    matvec) so any telemetry-on run gets the convergence plane for
    free. ``COMAP_SOLVER_TRACE=0`` is the kill switch."""
    if os.environ.get("COMAP_SOLVER_TRACE", "").strip() == "0":
        return False
    return TELEMETRY.enabled


def append_solver(path: str, records: list) -> None:
    """Torn-line-safe append — the quality ledger's exact discipline
    (heal a crashed writer's stump with a newline first, then append +
    flush + fsync). I/O failures are logged and swallowed: solver
    bookkeeping must never kill a solve."""
    if not records:
        return
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        needs_nl = False
        try:
            with open(path, "rb") as f:
                f.seek(-1, os.SEEK_END)
                needs_nl = f.read(1) != b"\n"
        except OSError:
            pass
        payload = "".join(json.dumps(r, separators=(",", ":")) + "\n"
                          for r in records)
        with open(path, "a", encoding="utf-8") as f:
            f.write(("\n" if needs_nl else "") + payload)
            f.flush()
            os.fsync(f.fileno())
    except OSError as exc:
        logger.warning("solver trace append to %s failed (%s: %s)",
                       path, type(exc).__name__, exc)


def read_solver(source) -> list:
    """All solver records from a state directory (every
    ``solver.rank*.jsonl``), one path, or a list of paths. Torn/garbage
    lines are dropped; records come back in file order (iteration
    records are append-ordered within a solve by construction)."""
    if isinstance(source, (list, tuple)):
        paths = [str(p) for p in source]
    elif os.path.isdir(source):
        paths = sorted(_glob.glob(os.path.join(source,
                                               "solver.rank*.jsonl")))
    else:
        paths = [str(source)]
    out = []
    for path in paths:
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            continue
        for line in raw.split(b"\n"):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except Exception:
                continue
            if isinstance(rec, dict) and rec.get("kind") in (
                    "iteration", "solve"):
                out.append(rec)
    return out


def _finite(v, default=None):
    v = float(v)
    return v if math.isfinite(v) else default


def iteration_records(rr_hist, alpha_hist, beta_hist, b_norm, n_ran,
                      *, band: str, precond_id: str = "",
                      precision_id: str = "", threshold: float = 0.0,
                      base: int = 0, rank: int = 0,
                      bucket: str = "") -> list:
    """Per-iteration records from one system's histories.

    ``rr_hist``/``alpha_hist``/``beta_hist`` are 1-D length >= n_ran
    (one CG system — multi-RHS callers slice their trailing system axis
    first); ``b_norm`` is that system's ``|b|^2``; ``n_ran`` how many
    iterations actually executed (``result.n_iter``). ``residual`` is
    the relative norm ``sqrt(rr / |b|^2)`` — the quantity the
    convergence criterion tests. The ``diverging`` annotation mirrors
    the in-loop monitor: |r|^2 more than 100x above the best seen.
    ``bucket`` is the solve's shape-bucket id (``"L=50|N=36864"``) the
    per-bucket solver policy groups by (ISSUE 20); empty = unstamped
    (records predating the field parse identically).
    """
    rr = np.asarray(rr_hist, dtype=np.float64).reshape(-1)
    al = np.asarray(alpha_hist, dtype=np.float64).reshape(-1)
    be = np.asarray(beta_hist, dtype=np.float64).reshape(-1)
    bn = max(float(np.asarray(b_norm)), 1e-30)
    n = int(min(int(n_ran), rr.size))
    records = []
    best = math.inf
    for k in range(n):
        rr_k = _finite(rr[k])
        res = math.sqrt(rr_k / bn) if rr_k is not None else None
        diverging = bool(rr_k is not None and best < math.inf
                         and rr_k > _DIVERGING_GROWTH * best)
        if rr_k is not None:
            best = min(best, rr_k)
        rec = {
            "schema": SOLVER_SCHEMA, "kind": "iteration",
            "band": band, "iter": int(base) + k,
            "residual": res, "rr": rr_k,
            "alpha": _finite(al[k]), "beta": _finite(be[k]),
            "precond_id": precond_id, "precision_id": precision_id,
            "threshold": float(threshold), "rank": int(rank),
            "diverging": diverging,
        }
        if bucket:
            rec["bucket"] = str(bucket)
        records.append(rec)
    return records


def _stall(records: list, threshold: float) -> tuple:
    """``(stalled, stalled_at)`` over one solve's iteration records: the
    trailing-window log10-residual slope of an unconverged solve. A
    converged solve is never 'stalled' — sitting at the floor is
    success, not stagnation."""
    resid = [(r["iter"], r["residual"]) for r in records
             if r.get("residual")]
    if len(resid) < 2:
        return False, None
    last = resid[-1][1]
    if threshold > 0 and last <= threshold:
        return False, None
    window = resid[-min(len(resid), STALL_WINDOW):]
    di = window[-1][0] - window[0][0]
    if di <= 0:
        return False, None
    slope = (math.log10(max(window[-1][1], 1e-300))
             - math.log10(max(window[0][1], 1e-300))) / di
    if slope > -STALL_SLOPE:
        return True, int(window[0][0])
    return False, None


def solve_summary(records: list, *, band: str, n_iter: int,
                  residual: float, diverged: bool,
                  precond_id: str = "", precision_id: str = "",
                  threshold: float = 0.0, base: int = 0,
                  rank: int = 0, bucket: str = "") -> dict:
    """The per-solve summary record, with divergence/stagnation
    annotations derived from the iteration records. ``bucket`` stamps
    the solve's shape bucket for the per-bucket solver policy (empty =
    unstamped, the pre-ISSUE-20 record shape)."""
    stalled, stalled_at = _stall(records, threshold)
    out = {
        "schema": SOLVER_SCHEMA, "kind": "solve", "band": band,
        "n_iter": int(n_iter), "residual": _finite(residual),
        "converged": bool(threshold > 0 and float(residual) <= threshold
                          and not diverged),
        "diverged": bool(diverged), "stalled": stalled,
        "stalled_at": stalled_at, "base": int(base),
        "precond_id": precond_id, "precision_id": precision_id,
        "threshold": float(threshold), "rank": int(rank),
        "t": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if bucket:
        out["bucket"] = str(bucket)
    return out


def _band_index(band: str) -> float:
    m = re.search(r"(\d+)", str(band))
    return float(m.group(1)) if m else -1.0


def record_solve(result, *, band: str, precond_id: str = "",
                 precision_id: str = "", threshold: float = 0.0,
                 base: int = 0, log_dir: str | None = None,
                 rank: int | None = None, bands: list | None = None,
                 path: str | None = None, bucket: str = "") -> list:
    """Render one traced ``DestriperResult`` into solver records,
    append them to ``solver.rank{r}.jsonl``, and mirror progress onto
    live gauges. Returns the records (callers cross-check the
    iteration count against ``result.n_iter``).

    Multi-RHS solves (histories with a trailing system axis) get one
    record stream per system, labelled ``bands[i]`` when given else
    ``{band}[{i}]``. A ``result`` without a trace (untraced/sharded
    path) is a silent no-op. ``bucket`` stamps every record with the
    solve's shape-bucket id so the control plane's solver policy can
    pick rungs per bucket (ISSUE 20); empty keeps the legacy record
    shape.
    """
    trace = getattr(result, "trace", None)
    if trace is None:
        return []
    rr_h, al_h, be_h, b_norm = (np.asarray(t) for t in trace)
    n_ran = int(np.asarray(result.n_iter))
    div = np.asarray(result.diverged).reshape(-1)
    if rank is None:
        rank = getattr(TELEMETRY, "_rank", 0)
    if path is None:
        directory = log_dir if log_dir is not None else TELEMETRY.log_dir
        path = solver_path(directory, rank)

    # normalise to (trace_n, n_systems)
    if rr_h.ndim == 1:
        rr_h, al_h, be_h = (a[:, None] for a in (rr_h, al_h, be_h))
        b_norm = np.asarray(b_norm).reshape(1)
    n_sys = rr_h.shape[-1]
    res_final = np.asarray(result.residual).reshape(-1)
    records = []
    for i in range(n_sys):
        label = (bands[i] if bands is not None and i < len(bands)
                 else (band if n_sys == 1 else f"{band}[{i}]"))
        iters = iteration_records(
            rr_h[:, i], al_h[:, i], be_h[:, i], b_norm[i], n_ran,
            band=label, precond_id=precond_id,
            precision_id=precision_id, threshold=threshold,
            base=base, rank=rank, bucket=bucket)
        summary = solve_summary(
            iters, band=label, n_iter=n_ran,
            residual=float(res_final[i % res_final.size]),
            diverged=bool(div[i % div.size]), precond_id=precond_id,
            precision_id=precision_id, threshold=threshold,
            base=base, rank=rank, bucket=bucket)
        records.extend(iters)
        records.append(summary)
        # live progress gauges: iteration FIRST so a reader seeing the
        # residual gauge can pair it with a current iteration; the
        # residual gauge carries the iteration as an attribute so the
        # live plane can fit a slope without event ordering games
        if iters and TELEMETRY.enabled:
            last = iters[-1]
            if last["residual"]:
                log_res = math.log10(max(last["residual"], 1e-300))
                TELEMETRY.gauge("solver.band", _band_index(label))
                TELEMETRY.gauge("solver.iteration", float(last["iter"]))
                TELEMETRY.gauge("solver.log10_residual", log_res,
                                iteration=last["iter"], band=label,
                                threshold=threshold)
    append_solver(path, records)
    return records
