"""Process-wide telemetry registry: structured spans + counters/gauges.

One ``Telemetry`` singleton (``TELEMETRY``) per process emits
structured events to an append-only per-rank ``events.rank{r}.jsonl``
stream under ``[Global] log_dir`` — the same torn-line-tolerant JSONL
discipline as the quarantine ledger (``resilience/ledger.py``): the
writer repairs a torn trailing stump with a newline before appending,
so a crash mid-write costs at most one line and never glues two
records, and the reader (``telemetry/reader.py``) drops unparseable
lines instead of dying.

Event kinds (one JSON object per line, ``mono`` = ``time.monotonic()``
seconds in the WRITER's clock domain — cross-rank alignment happens at
read time through each stream's ``meta`` anchor ``wall0``/``mono0``):

- ``meta``    stream header: schema, rank, pid, host, wall0/mono0.
- ``begin``   a span OPENED (id, name, unit, tid, mono, parent).
  A span with a ``begin`` but no matching ``span`` record was left
  open by a crash/SIGKILL; the reader renders it explicitly truncated.
- ``span``    a span CLOSED: begin fields + ``dur`` (seconds) +
  ``attrs`` (free-form, e.g. ``skipped``/``error``/``bytes``).
- ``counter`` a monotonic-count DELTA sample (``value`` adds).
- ``gauge``   a point-in-time level sample (``value`` replaces).

Overhead discipline: with telemetry disabled (the default) every
public call is one attribute check and ``span()`` returns a shared
no-op context manager — no allocation, no lock, no clock read. Enabled,
events buffer in memory and a daemon thread drains them every
``flush_s`` seconds (polling registered gauge callables on the same
beat), so the hot path never touches the filesystem.

``StageTimings`` is the spans-backed drop-in for ``Runner.timings``: a
real ``dict[str, list[float]]`` (the watchdog's ``.get(name, ())`` and
``run_average``'s ``sorted(...items())`` keep working unchanged) whose
``record()`` also emits a completed span and tracks which entries are
skip-path placeholders; ``samples(name)`` returns only the real
measurements, which is what the watchdog's adaptive percentile reads —
a campaign of mostly-resumed files no longer drags its p95 toward zero.
"""

from __future__ import annotations

import atexit
import bisect
import itertools
import json
import logging
import os
import re
import socket
import threading
import time

__all__ = ["TELEMETRY", "Telemetry", "TelemetryConfig", "StageTimings",
           "RequestMetrics", "LATENCY_BUCKETS_S",
           "serving_lane_rank", "SERVING_LANE_BASE"]

logger = logging.getLogger("comapreduce_tpu")

_SCHEMA = 1

#: first telemetry rank of the SERVING lane: reducer ranks are the
#: campaign's real ranks (0..N-1), long-lived serving processes (map
#: server, tile server) write at >= this so the streams never collide
SERVING_LANE_BASE = 1000

_RANK_STREAM_RE = re.compile(r"^events\.rank(\d+)\.jsonl$")


def serving_lane_rank(log_dir: str,
                      base: int = SERVING_LANE_BASE) -> int:
    """The next free serving-lane rank in ``log_dir``: one past the
    highest existing ``events.rank{r}.jsonl`` with ``r >= base``
    (``base`` itself when the lane is empty). Span/event ids are
    per-process, so two servers appending to one stream would
    interleave unrelated spans — every serving process (and every
    restart of one) takes a fresh stream instead; the reader merges
    them by the meta anchor like any other rank."""
    best = int(base) - 1
    try:
        names = os.listdir(log_dir or ".")
    except OSError:
        names = []
    for name in names:
        m = _RANK_STREAM_RE.match(name)
        if m and int(m.group(1)) >= int(base):
            best = max(best, int(m.group(1)))
    return best + 1


def _json_safe(obj):
    """Best-effort scalarisation for numpy/jax leaves in attrs."""
    try:
        return float(obj)
    except Exception:
        return str(obj)


class _NullSpan:
    """The disabled-path span: one shared instance, no state."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return None


_NULL_SPAN = _NullSpan()


class Span:
    """A live span (use through ``Telemetry.span`` as a context
    manager). ``begin`` is written on entry so a rank SIGKILLed
    mid-span still leaves evidence; the full record with ``dur``
    replaces it on exit. ``set(**attrs)`` attaches attributes any time
    before exit; an exception exits the span with an ``error`` attr."""

    __slots__ = ("_tele", "name", "unit", "attrs", "id", "parent", "t0")

    def __init__(self, tele: "Telemetry", name: str, unit: str,
                 attrs: dict):
        self._tele = tele
        self.name = name
        self.unit = unit
        self.attrs = attrs

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> "Span":
        tele = self._tele
        self.id = next(tele._ids)
        stack = tele._stack()
        self.parent = stack[-1] if stack else 0
        stack.append(self.id)
        self.t0 = time.monotonic()
        ev = {"kind": "begin", "id": self.id, "name": self.name,
              "mono": self.t0, "tid": threading.current_thread().name}
        if self.unit:
            ev["unit"] = self.unit
        if self.parent:
            ev["parent"] = self.parent
        tele._emit(ev)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        tele = self._tele
        dur = time.monotonic() - self.t0
        stack = tele._stack()
        if stack and stack[-1] == self.id:
            stack.pop()
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        ev = {"kind": "span", "id": self.id, "name": self.name,
              "mono": self.t0, "dur": dur,
              "tid": threading.current_thread().name}
        if self.unit:
            ev["unit"] = self.unit
        if self.parent:
            ev["parent"] = self.parent
        if self.attrs:
            ev["attrs"] = self.attrs
        tele._emit(ev)
        return False


class Telemetry:
    """The process-wide registry. Disabled until :meth:`configure`."""

    def __init__(self):
        self._enabled = False
        self._lock = threading.Lock()
        self._buf: list[dict] = []
        self._path = ""
        self._rank = 0
        self._flush_s = 2.0
        self.jax_profiler = False
        self._jax_profiled = False
        self._ids = itertools.count(1)
        self._tls = threading.local()
        self._gauges: dict = {}
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._write_failed = False

    # -- lifecycle ---------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def path(self) -> str:
        return self._path

    @property
    def log_dir(self) -> str:
        return os.path.dirname(self._path) if self._path else ""

    def configure(self, log_dir: str, rank: int = 0, *,
                  flush_s: float = 2.0,
                  jax_profiler: bool = False) -> "Telemetry":
        """Open (or re-open) the per-rank event stream and start the
        flush thread. Re-configuring into the same file appends — the
        stream is append-only by contract, like the quarantine ledger."""
        self.close()
        os.makedirs(log_dir or ".", exist_ok=True)
        self._path = os.path.join(log_dir or ".",
                                  f"events.rank{int(rank)}.jsonl")
        self._rank = int(rank)
        self._flush_s = max(float(flush_s), 0.05)
        self.jax_profiler = bool(jax_profiler)
        self._jax_profiled = False
        self._write_failed = False
        self._stop = threading.Event()
        self._enabled = True
        # the stream anchor: readers align this rank's mono clock onto
        # wall time through (wall0, mono0) — mono clocks of different
        # hosts share no epoch, so every cross-rank merge needs this
        self._emit({"kind": "meta", "schema": _SCHEMA, "rank": self._rank,
                    "pid": os.getpid(), "host": socket.gethostname(),
                    "wall0": time.time(), "mono0": time.monotonic()})
        self.flush()
        self._thread = threading.Thread(target=self._flush_loop,
                                        name="telemetry-flush",
                                        daemon=True)
        self._thread.start()
        # compile events become spans: the jax.monitoring dispatchers
        # are process-lifetime (no removal API), installed once here so
        # compile spans flow even without a CompileCounter in scope
        try:
            from comapreduce_tpu.pipeline.campaign import _install_hooks

            _install_hooks()
        except Exception:  # jax absent/odd backend: spans still work
            pass
        # the compiled-program registry rides the same switch: telemetry
        # on means every AOT compile site self-reports cost/memory into
        # <log_dir>/programs.jsonl (ISSUE 15) — no second knob to forget
        try:
            from comapreduce_tpu.telemetry.programs import PROGRAMS

            PROGRAMS.configure(log_dir, rank)
        except Exception:
            pass
        return self

    def close(self) -> None:
        """Stop the flush thread and drain the buffer. Idempotent;
        leaves the registry disabled (configure() re-enables)."""
        thread, self._thread = self._thread, None
        self._stop.set()
        if thread is not None:
            thread.join(timeout=5.0)
        if self._enabled:
            self._poll_gauges()
            self.flush()
        self._enabled = False
        self._gauges.clear()
        try:
            from comapreduce_tpu.telemetry.programs import PROGRAMS

            PROGRAMS.close()
        except Exception:
            pass

    # -- emission ----------------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _emit(self, ev: dict) -> None:
        with self._lock:
            self._buf.append(ev)

    def span(self, name: str, unit: str = "", **attrs):
        """Context manager timing a live region (writes begin + span)."""
        if not self._enabled:
            return _NULL_SPAN
        return Span(self, name, unit, attrs)

    def event_span(self, name: str, dur_s: float, unit: str = "",
                   skipped: bool = False, **attrs) -> None:
        """A completed span reported post-hoc: the common pattern for
        regions whose duration the caller already measured. Emitted
        promptly after the region ends, its ``[now-dur, now]`` interval
        is the region's true extent (what the overlap fractions in
        ``campaign_report`` integrate). ``skipped`` marks placeholder
        durations (error/resume paths) that summaries must not count."""
        if not self._enabled:
            return
        end = time.monotonic()
        dur = max(float(dur_s), 0.0)
        if skipped:
            attrs["skipped"] = True
        stack = self._stack()
        ev = {"kind": "span", "id": next(self._ids), "name": name,
              "mono": end - dur, "dur": dur,
              "tid": threading.current_thread().name}
        if unit:
            ev["unit"] = unit
        if stack:
            ev["parent"] = stack[-1]
        if attrs:
            ev["attrs"] = attrs
        self._emit(ev)

    def counter(self, name: str, value: float = 1, **attrs) -> None:
        """A monotonic-count delta (``value`` ADDS to the series)."""
        if not self._enabled:
            return
        ev = {"kind": "counter", "name": name, "mono": time.monotonic(),
              "value": value}
        if attrs:
            ev["attrs"] = attrs
        self._emit(ev)

    def gauge(self, name: str, value: float, **attrs) -> None:
        """A point-in-time level (queue depth, bytes resident)."""
        if not self._enabled:
            return
        ev = {"kind": "gauge", "name": name, "mono": time.monotonic(),
              "value": value}
        if attrs:
            ev["attrs"] = attrs
        self._emit(ev)

    def register_gauge(self, name: str, fn) -> None:
        """Register ``fn() -> number|None`` to be sampled on every
        flush beat — the zero-hot-path-cost way to track levels that
        change constantly (cache occupancy, cumulative hit counts)."""
        if not self._enabled:
            return
        with self._lock:
            self._gauges[name] = fn

    def maybe_jax_profile(self, steady: bool):
        """The opt-in ``jax.profiler.trace`` hook: returns a context
        manager bracketing exactly ONE steady-state file per configure
        (``[telemetry] jax_profiler``), writing device traces under
        ``<log_dir>/jax_trace`` so XLA timelines line up with the host
        spans. None everywhere else."""
        if not (self._enabled and self.jax_profiler and steady) \
                or self._jax_profiled:
            return None
        self._jax_profiled = True
        out = os.path.join(self.log_dir or ".", "jax_trace")
        try:
            import jax

            os.makedirs(out, exist_ok=True)
            return jax.profiler.trace(out)
        except Exception:  # profiler unsupported on this backend
            logger.warning("telemetry: jax.profiler.trace unavailable; "
                           "skipping device trace")
            return None

    # -- flushing ----------------------------------------------------------
    def _flush_loop(self) -> None:
        while not self._stop.wait(self._flush_s):
            self._poll_gauges()
            self.flush()

    def _poll_gauges(self) -> None:
        with self._lock:
            gauges = list(self._gauges.items())
        for name, fn in gauges:
            try:
                value = fn()
            except Exception:  # a closed subsystem's gauge: drop it
                with self._lock:
                    self._gauges.pop(name, None)
                continue
            if value is not None:
                self.gauge(name, value)

    def flush(self) -> None:
        """Drain the buffer to the stream (torn-line-safe append)."""
        with self._lock:
            buf, self._buf = self._buf, []
        if not buf or not self._path:
            return
        payload = "".join(
            json.dumps(ev, separators=(",", ":"), default=_json_safe)
            + "\n" for ev in buf)
        try:
            # "a+b", not "ab": the torn-tail probe READS the last byte,
            # and a write-only append handle turns that read into
            # io.UnsupportedOperation (an OSError), silently skipping
            # the heal; O_APPEND still pins every write to the end
            with open(self._path, "a+b") as f:
                # heal a torn trailing line from a previous crash with
                # a newline FIRST: the stump stays (the reader drops
                # it), but it can never glue onto this batch's first
                # record (the ledger's exact discipline)
                needs_nl = False
                try:
                    f.seek(-1, os.SEEK_END)
                    needs_nl = f.read(1) != b"\n"
                except OSError:
                    pass
                f.write((b"\n" if needs_nl else b"")
                        + payload.encode("utf-8"))
                f.flush()
                os.fsync(f.fileno())
        except OSError as exc:
            if not self._write_failed:  # warn once, never kill the run
                self._write_failed = True
                logger.warning("telemetry: cannot append to %s (%s); "
                               "events are being dropped",
                               self._path, exc)


TELEMETRY = Telemetry()
atexit.register(TELEMETRY.close)


class TelemetryConfig:
    """The ``[telemetry]`` config table as a value object.

    Knobs (all optional):

    - ``enabled``       bool, default False — the whole subsystem is
      opt-in; disabled it costs one attribute check per call site.
    - ``flush_s``       float, default 2.0 — event-buffer drain (and
      gauge sampling) period.
    - ``jax_profiler``  bool, default False — bracket one steady-state
      file per run in ``jax.profiler.trace`` (device traces under
      ``<log_dir>/jax_trace``).

    ``coerce`` accepts a TelemetryConfig (pass-through), a mapping, or
    None, and rejects unknown keys — the same contract as
    ``IngestConfig.coerce`` (a typo'd knob must raise, not silently
    run with the default).
    """

    KNOBS = ("enabled", "flush_s", "jax_profiler")

    __slots__ = KNOBS

    def __init__(self, enabled: bool = False, flush_s: float = 2.0,
                 jax_profiler: bool = False):
        self.enabled = bool(enabled)
        self.flush_s = max(float(flush_s), 0.05)
        self.jax_profiler = bool(jax_profiler)

    @classmethod
    def coerce(cls, value) -> "TelemetryConfig":
        if value is None:
            return cls()
        if isinstance(value, cls):
            return value
        unknown = set(value) - set(cls.KNOBS)
        if unknown:
            raise ValueError(
                f"unknown [telemetry] option(s) {sorted(unknown)}; "
                f"valid: {list(cls.KNOBS)}")
        return cls(**dict(value))

    def __repr__(self) -> str:  # debugging aid
        return (f"TelemetryConfig(enabled={self.enabled}, "
                f"flush_s={self.flush_s}, "
                f"jax_profiler={self.jax_profiler})")


class StageTimings(dict):
    """``Runner.timings``, spans-backed.

    A genuine ``dict[str, list[float]]`` — every existing consumer
    (``watchdog.timings.get(name, ())``, ``sorted(runner.timings.
    items())``, the benches' ``sum(timings["ingest.read"])``) works
    unchanged, and per-file index alignment across lists is preserved
    because placeholders are still appended. On top:

    - ``record(name, seconds, skipped=..., unit=..., emit=...)``
      appends AND (when telemetry is enabled and ``emit``) publishes a
      completed span; ``skipped=True`` marks error/resume placeholders.
    - ``samples(name)`` returns only the non-skipped measurements —
      the watchdog's adaptive percentile reads THIS, so placeholder
      zeros never drag deadline budgets toward zero.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._skips: dict[str, set] = {}

    def record(self, name: str, seconds: float, *,
               skipped: bool = False, unit: str = "",
               emit: bool = True, **attrs) -> None:
        vals = self.setdefault(name, [])
        vals.append(float(seconds))
        if skipped:
            self._skips.setdefault(name, set()).add(len(vals) - 1)
        if emit and TELEMETRY.enabled:
            TELEMETRY.event_span(name, seconds, unit=unit,
                                 skipped=skipped, **attrs)

    def samples(self, name: str) -> list:
        """Non-placeholder durations (the adaptive-deadline feed)."""
        vals = self.get(name)
        if not vals:
            return []
        skips = self._skips.get(name)
        if not skips:
            return list(vals)
        return [v for i, v in enumerate(vals) if i not in skips]


#: request-latency histogram bounds (seconds) shared by every HTTP
#: surface here — localhost JSON endpoints live in the 1-10 ms bins,
#: tile/cutout transfers reach the 100 ms+ bins, and the +Inf bucket
#: catches stalls. Fixed bounds keep scrapes mergeable across restarts.
LATENCY_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class RequestMetrics:
    """Per-request HTTP telemetry: a Prometheus cumulative latency
    histogram plus per-(route, status) counters, shared by the live
    sidecar (``telemetry/live.py``) and the tile server
    (``tiles/http.py``) so both /metrics surfaces speak the same
    schema (ISSUE 15).

    ``observe()`` is handler-thread-safe and costs one lock + one
    bisect; ``prom_lines()`` renders::

        comap_<name>_request_duration_seconds_bucket{le="0.005"} 4
        comap_<name>_request_duration_seconds_sum 0.012
        comap_<name>_request_duration_seconds_count 5
        comap_<name>_requests_total{route="/metrics",status="200"} 5
    """

    def __init__(self, name: str,
                 buckets: tuple = LATENCY_BUCKETS_S):
        self.name = str(name)
        self.buckets = tuple(float(b) for b in buckets)
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)  # +1: +Inf
        self._sum_s = 0.0
        self._n = 0
        self._routes: dict = {}

    def observe(self, route: str, status: int, dur_s: float) -> None:
        dur_s = max(float(dur_s), 0.0)
        i = bisect.bisect_left(self.buckets, dur_s)
        with self._lock:
            self._counts[i] += 1
            self._sum_s += dur_s
            self._n += 1
            key = (str(route), int(status))
            self._routes[key] = self._routes.get(key, 0) + 1

    def snapshot(self) -> dict:
        with self._lock:
            return {"counts": list(self._counts), "sum_s": self._sum_s,
                    "n": self._n, "routes": dict(self._routes)}

    def prom_lines(self) -> list:
        snap = self.snapshot()
        base = f"comap_{self.name}_request_duration_seconds"
        lines = [f"# TYPE {base} histogram"]
        cum = 0
        for bound, count in zip(self.buckets, snap["counts"]):
            cum += count
            lines.append(f'{base}_bucket{{le="{bound:g}"}} {cum}')
        cum += snap["counts"][-1]
        lines.append(f'{base}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{base}_sum {snap['sum_s']:.9g}")
        lines.append(f"{base}_count {snap['n']}")
        total = f"comap_{self.name}_requests_total"
        if snap["routes"]:
            lines.append(f"# TYPE {total} counter")
        for (route, status), n in sorted(snap["routes"].items()):
            lines.append(
                f'{total}{{route="{route}",status="{status}"}} {n}')
        return lines
