"""Per-(file, feed, band) data-quality ledger + declarative SLO rules.

The reference pipeline's data-selection criteria (COMAP Early Science
III: per-scan Tsys, 1/f knee/alpha, spike rates) are computed by
``ops/power.py`` / ``ops/spikes.py`` but — before this module — never
ledgered, thresholded, or trended. Here the Runner assembles one
**quality record** per (file, feed, band) after a file's stage chain
completes, appends it to ``quality.rank{r}.jsonl`` (the quarantine
ledger's torn-line-safe append discipline), and evaluates it against
the declarative ``[quality]``/``[slo]`` config tables. Records that
violate an SLO rule are *flagged*: an ``alert`` telemetry counter
fires (visible on the live ``/metrics`` plane and in
``campaign_report``), and ``run_destriper`` can exclude flagged files
like quarantines behind ``[slo] exclude_flagged`` (default OFF — the
science decision to drop data is the operator's, the pipeline only
makes it one knob away).

Record schema (one JSON object per line)::

    {"schema": 1, "file": "comap-0001.hd5", "feed": 0, "band": 1,
     "t": "2026-08-05T07:00:00Z", "rank": 0,
     "precision": "tod=bfloat16|accum=float32|cgdot=compensated",
     "tsys_k": 41.2, "gain": 0.031, "noise_model": "knee",
     "white_sigma": 0.0021, "fknee_hz": 0.9, "alpha": -1.6,
     "n_spikes": 3, "spike_fraction": 0.0002,
     "nonfinite_fraction": 0.0, "masked_fraction": 0.0,
     "n_samples": 600, "flags": [], "flagged": false}

Missing inputs are ``None`` fields, never errors — a minimal stage
chain still yields records carrying whatever science signals it
computed. ``precision`` is the run's precision-policy id
(docs/OPERATIONS.md §15) so a quality trend is attributable to a
numerics change. Reading is latest-wins per (file, feed, band) across
every rank's file, exactly like the quarantine ledger.
"""

from __future__ import annotations

import glob as _glob
import json
import logging
import os
import re
import time

import numpy as np

from comapreduce_tpu.telemetry.core import TELEMETRY

__all__ = ["QualityConfig", "SloConfig", "append_quality",
           "assemble_quality_records", "emit_alerts", "evaluate_record",
           "flag_counts", "flagged_files", "masked_from_ledger",
           "quality_path", "read_quality", "worst_feeds"]

logger = logging.getLogger("comapreduce_tpu")

QUALITY_SCHEMA = 1

_QUALITY_RE = re.compile(r"quality\.rank(\d+)\.jsonl$")


def _bool(v) -> bool:
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


class QualityConfig:
    """The ``[quality]`` config table: record assembly on/off.

    - ``enabled``  bool, default True — assembling a handful of
      reductions per file is cheap next to the stage chain, and the
      ledger is the input to every downstream SLO/trend feature, so it
      is on by default (unlike telemetry, which is opt-in).

    ``coerce`` rejects unknown keys like every other config table.
    """

    KNOBS = ("enabled",)

    __slots__ = KNOBS

    def __init__(self, enabled: bool = True):
        self.enabled = _bool(enabled)

    @classmethod
    def coerce(cls, value) -> "QualityConfig":
        if value is None:
            return cls()
        if isinstance(value, cls):
            return value
        unknown = set(value) - set(cls.KNOBS)
        if unknown:
            raise ValueError(
                f"unknown [quality] option(s) {sorted(unknown)}; "
                f"valid: {list(cls.KNOBS)}")
        return cls(**dict(value))

    def __repr__(self) -> str:
        return f"QualityConfig(enabled={self.enabled})"


class SloConfig:
    """The ``[slo]`` table: declarative thresholds over quality records.

    Every threshold is OFF at ``0`` except ``max_masked_fraction``,
    whose default (1 %) encodes the one rule that should never need
    opting into: a feed whose samples were zero-weighted (or arrived
    non-finite) beyond the percent level is reduction-damaged, not
    science. Rule names (the ``flags`` vocabulary):

    ====================  =============================================
    ``tsys_high``         mean vane Tsys above ``max_tsys_k``
    ``tsys_low``          mean vane Tsys below ``min_tsys_k``
    ``white_sigma_high``  fitted white-noise sigma above
                          ``max_white_sigma``
    ``fknee_high``        fitted 1/f knee above ``max_fknee_hz``
    ``spike_high``        spike fraction above ``max_spike_fraction``
    ``masked_high``       max(masked, non-finite) fraction above
                          ``max_masked_fraction``
    ====================  =============================================

    ``exclude_flagged`` (default False) lets ``run_destriper`` drop
    flagged files from the filelist like quarantines.
    """

    KNOBS = ("max_tsys_k", "min_tsys_k", "max_white_sigma",
             "max_fknee_hz", "max_spike_fraction",
             "max_masked_fraction", "exclude_flagged")

    __slots__ = KNOBS

    def __init__(self, max_tsys_k: float = 0.0, min_tsys_k: float = 0.0,
                 max_white_sigma: float = 0.0,
                 max_fknee_hz: float = 0.0,
                 max_spike_fraction: float = 0.0,
                 max_masked_fraction: float = 0.01,
                 exclude_flagged: bool = False):
        self.max_tsys_k = float(max_tsys_k)
        self.min_tsys_k = float(min_tsys_k)
        self.max_white_sigma = float(max_white_sigma)
        self.max_fknee_hz = float(max_fknee_hz)
        self.max_spike_fraction = float(max_spike_fraction)
        self.max_masked_fraction = float(max_masked_fraction)
        self.exclude_flagged = _bool(exclude_flagged)

    @classmethod
    def coerce(cls, value) -> "SloConfig":
        if value is None:
            return cls()
        if isinstance(value, cls):
            return value
        unknown = set(value) - set(cls.KNOBS)
        if unknown:
            raise ValueError(
                f"unknown [slo] option(s) {sorted(unknown)}; "
                f"valid: {list(cls.KNOBS)}")
        return cls(**dict(value))

    def __repr__(self) -> str:
        body = ", ".join(f"{k}={getattr(self, k)}" for k in self.KNOBS)
        return f"SloConfig({body})"


def evaluate_record(rec: dict, slo: SloConfig) -> list:
    """Rule names violated by one record (None fields never fire — an
    absent signal is not evidence of a bad one)."""
    flags = []

    def over(value, limit) -> bool:
        return limit > 0 and value is not None and value > limit

    if over(rec.get("tsys_k"), slo.max_tsys_k):
        flags.append("tsys_high")
    if slo.min_tsys_k > 0 and rec.get("tsys_k") is not None \
            and rec["tsys_k"] < slo.min_tsys_k:
        flags.append("tsys_low")
    if over(rec.get("white_sigma"), slo.max_white_sigma):
        flags.append("white_sigma_high")
    if over(rec.get("fknee_hz"), slo.max_fknee_hz):
        flags.append("fknee_high")
    if over(rec.get("spike_fraction"), slo.max_spike_fraction):
        flags.append("spike_high")
    damaged = max(rec.get("masked_fraction") or 0.0,
                  rec.get("nonfinite_fraction") or 0.0)
    if slo.max_masked_fraction > 0 and damaged > slo.max_masked_fraction:
        flags.append("masked_high")
    return flags


def masked_from_ledger(ledger, filename: str) -> dict:
    """``(feed, band) -> n_masked`` for one file from the quarantine
    ledger's ``masked`` dispositions (``record_masked``'s message is
    ``"{n} non-finite sample(s) zero-weighted"``; its unit carries
    feed/band when the scrub was per-feed). A row without feed/band
    lands under the ``None`` key and applies file-wide. Max on
    collision: re-runs re-ledger the same scrub, they don't add to it.
    """
    base = os.path.basename(filename)
    out: dict = {}
    for e in getattr(ledger, "entries", ()):
        if e.disposition != "masked":
            continue
        unit = e.unit or {}
        if os.path.basename(str(unit.get("file", ""))) != base:
            continue
        m = re.match(r"\s*(\d+)", str(e.message))
        if not m:
            continue
        n = int(m.group(1))
        feed, band = unit.get("feed"), unit.get("band")
        key = (int(feed), int(band)) \
            if feed is not None and band is not None else None
        out[key] = max(out.get(key, 0), n)
    return out


# -- assembly ----------------------------------------------------------------

def _finite_mean(a) -> float | None:
    a = np.asarray(a, dtype=np.float64)
    good = np.isfinite(a) & (a != 0.0)
    if not good.any():
        return None
    return float(a[good].mean())


def _noise_fit(level2, ifeed: int, iband: int):
    """``(model, white_sigma, fknee_hz, alpha)`` from whichever noise
    fit the stage chain wrote: ``noise_statistics`` (knee model,
    params ``[sig2, fknee, alpha]``) preferred over ``fnoise_fits``
    (red-noise model ``sig2 + red2 |nu|^alpha``, whose knee is derived
    as ``(sig2/red2)^(1/alpha)``). Scan axis is nan-mean-reduced
    (unfittable scans are NaN rows by contract)."""
    for group, model in (("noise_statistics", "knee"),
                         ("fnoise_fits", "red_noise")):
        key = f"{group}/fnoise_fit_parameters"
        if key not in level2:
            continue
        params = np.asarray(level2[key], dtype=np.float64)
        if params.ndim != 4 or ifeed >= params.shape[0] \
                or iband >= params.shape[1]:
            continue
        p = params[ifeed, iband]  # (S, 3)
        good = np.isfinite(p).all(axis=-1)
        if not good.any():
            return model, None, None, None
        sig2, p1, alpha = (float(v) for v in p[good].mean(axis=0))
        sigma = float(np.sqrt(sig2)) if sig2 >= 0 else None
        if model == "knee":
            fknee = abs(p1)
        else:
            # sig2 = red2 |fknee|^alpha at the knee
            fknee = (abs(sig2 / p1) ** (1.0 / alpha)
                     if p1 != 0 and sig2 > 0 and alpha != 0 else None)
        return model, sigma, fknee, alpha
    return None, None, None, None


def assemble_quality_records(level2, filename: str, *, rank: int = 0,
                             precision_id: str = "",
                             masked: dict | None = None) -> list:
    """One record per (feed, band) of a finished file.

    ``masked`` maps ``(feed, band) -> n_masked_samples`` from the
    scrub ledger events (``disposition == "masked"``); a ``None`` key
    applies file-wide. Signals the stage chain did not compute are
    ``None`` fields.
    """
    try:
        tod = np.asarray(level2.tod)
    except (KeyError, AttributeError):
        return []
    if tod.ndim != 3:
        return []
    F, B, T = tod.shape
    masked = masked or {}

    tsys_m = gain_m = None
    if "vane/system_temperature" in level2:
        # lazy import: pipeline.stages imports the telemetry package
        from comapreduce_tpu.pipeline.stages import mean_vane_tsys_gain

        try:
            tsys_m, gain_m = mean_vane_tsys_gain(level2)
        except (KeyError, ValueError):
            tsys_m = gain_m = None

    spikes = None
    if "spikes/spike_mask" in level2:
        spikes = np.asarray(level2["spikes/spike_mask"])

    t_iso = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    base = os.path.basename(filename)
    records = []
    for f in range(F):
        for b in range(B):
            model, sigma, fknee, alpha = _noise_fit(level2, f, b)
            n_spk = None
            if spikes is not None and f < spikes.shape[0] \
                    and b < spikes.shape[1]:
                n_spk = int(np.count_nonzero(spikes[f, b]))
            n_masked = masked.get((f, b), masked.get(None, 0))
            records.append({
                "schema": QUALITY_SCHEMA,
                "file": base, "feed": f, "band": b, "t": t_iso,
                "rank": int(rank), "precision": precision_id,
                "tsys_k": (_finite_mean(tsys_m[f, b])
                           if tsys_m is not None else None),
                "gain": (_finite_mean(gain_m[f, b])
                         if gain_m is not None else None),
                "noise_model": model, "white_sigma": sigma,
                "fknee_hz": fknee, "alpha": alpha,
                "n_spikes": n_spk,
                "spike_fraction": (n_spk / T if n_spk is not None and T
                                   else None),
                "nonfinite_fraction": float(
                    np.mean(~np.isfinite(tod[f, b]))),
                "masked_fraction": (n_masked / T if T else 0.0),
                "n_samples": T,
            })
    return records


def emit_alerts(records: list) -> int:
    """Fire one ``quality.alert`` telemetry counter (+ a log line) per
    flagged record; returns the alert count. No-op with telemetry
    disabled beyond the log lines — the ledger itself is the durable
    evidence either way."""
    n = 0
    for rec in records:
        if not rec.get("flagged"):
            continue
        n += 1
        rules = ",".join(rec.get("flags", ()))
        logger.warning(
            "QUALITY ALERT %s feed %s band %s: %s", rec.get("file"),
            rec.get("feed"), rec.get("band"), rules)
        TELEMETRY.counter("quality.alert", 1, file=rec.get("file", ""),
                          feed=rec.get("feed"), band=rec.get("band"),
                          rules=rules)
    if records:
        TELEMETRY.counter("quality.records", len(records))
    return n


# -- persistence (the quarantine ledger's append discipline) -----------------

def quality_path(directory: str, rank: int) -> str:
    return os.path.join(directory or ".",
                        f"quality.rank{int(rank)}.jsonl")


def append_quality(path: str, records: list) -> None:
    """Torn-line-safe append: heal a crashed writer's trailing stump
    with a newline first (the stump stays; the reader drops it), then
    append + flush + fsync — identical discipline to
    ``resilience/ledger.py``. I/O failures are logged and swallowed:
    quality bookkeeping must never kill the run."""
    if not records:
        return
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        needs_nl = False
        try:
            with open(path, "rb") as f:
                f.seek(-1, os.SEEK_END)
                needs_nl = f.read(1) != b"\n"
        except OSError:
            pass
        from comapreduce_tpu.resilience.integrity import seal_line

        payload = "".join(seal_line(r) + "\n" for r in records)
        with open(path, "a", encoding="utf-8") as f:
            f.write(("\n" if needs_nl else "") + payload)
            f.flush()
            os.fsync(f.fileno())
    except OSError as exc:
        logger.warning("quality ledger append to %s failed (%s: %s)",
                       path, type(exc).__name__, exc)


def read_quality(source) -> list:
    """All quality records, latest-wins per (file, feed, band).

    ``source``: a state directory (every ``quality.rank*.jsonl`` in
    it), one path, or a list of paths. Torn lines are dropped like
    every JSONL reader here; so are lines failing their embedded
    ``_sha256`` seal (a rotted flag flipping a file in or out of the
    destriper's exclusion set is a map-level corruption, not a
    bookkeeping blip) — ``tools/campaign_fsck.py --repair`` rewrites
    the file without them."""
    from comapreduce_tpu.resilience.integrity import check_line

    if isinstance(source, (list, tuple)):
        paths = [str(p) for p in source]
    elif os.path.isdir(source):
        paths = sorted(_glob.glob(os.path.join(source,
                                               "quality.rank*.jsonl")))
    else:
        paths = [str(source)]
    latest: dict = {}
    corrupt = 0
    for path in paths:
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            continue
        for line in raw.split(b"\n"):
            if not line.strip():
                continue
            try:
                text = line.decode("utf-8")
            except UnicodeDecodeError:
                continue
            rec, verdict = check_line(text)
            if rec is None:
                if verdict is False and b"_sha256" in line:
                    corrupt += 1
                continue
            if not isinstance(rec, dict) or "file" not in rec:
                continue
            key = (rec.get("file"), rec.get("feed"), rec.get("band"))
            prev = latest.get(key)
            if prev is None or str(rec.get("t", "")) >= \
                    str(prev.get("t", "")):
                latest[key] = rec
    if corrupt:
        logger.warning("read_quality: dropped %d line(s) failing "
                       "their integrity seal", corrupt)
    return sorted(latest.values(),
                  key=lambda r: (str(r.get("file")),
                                 r.get("feed") or 0, r.get("band") or 0))


def flagged_files(source) -> set:
    """Basenames whose latest record (any feed/band) is flagged — the
    destriper's exclusion set."""
    return {r["file"] for r in read_quality(source) if r.get("flagged")}


def flag_counts(records: list) -> dict:
    """``{rule: count}`` over records' ``flags``."""
    out: dict = {}
    for r in records:
        for rule in r.get("flags") or ():
            out[rule] = out.get(rule, 0) + 1
    return out


def worst_feeds(records: list, n: int = 5) -> list:
    """The N worst (file, feed, band) rows by fitted knee frequency —
    the headline data-selection ranking."""
    rows = [r for r in records if r.get("fknee_hz") is not None]
    rows.sort(key=lambda r: -float(r["fknee_hz"]))
    return rows[:n]
