"""Torn-line-tolerant reader + cross-rank merge for telemetry streams.

``read_events`` loads one ``events.rank{r}.jsonl`` stream, dropping
unparseable lines (a torn tail from a crashed writer, a stump healed
by a later append) exactly like the quarantine ledger's loader.

``merge_streams`` merges every rank's stream into one timeline:

- **Clock alignment.** Monotonic clocks of different hosts (or even
  different processes) share no epoch, so each stream's ``meta``
  anchor ``(wall0, mono0)`` maps that writer's ``mono`` values onto
  wall time: ``t = mono + (wall0 - mono0)``. Skewed mono bases between
  ranks therefore cannot shear the merged timeline.
- **Truncated spans.** A ``begin`` with no matching ``span`` record
  is a span left open by a crash/SIGKILL. It is synthesised into a
  span running to the stream's LAST observed timestamp and marked
  ``truncated`` — rendered explicitly in the Chrome trace rather than
  silently dropped (the evidence of where a rank died is the point).
- **Namespacing.** Span ids are per-process counters; the merge
  prefixes them ``r{rank}:`` so parent links never collide across
  ranks.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import re
from dataclasses import dataclass, field

__all__ = ["read_events", "merge_streams", "MergedStream"]

_RANK_RE = re.compile(r"events\.rank(\d+)\.jsonl$")


def read_events(path: str) -> tuple[list[dict], int]:
    """All parseable events of one stream + the dropped-line count."""
    with open(path, "rb") as f:
        raw = f.read()
    events, dropped = [], 0
    for line in raw.split(b"\n"):
        if not line.strip():
            continue
        try:
            ev = json.loads(line)
        except Exception:
            dropped += 1
            continue
        if isinstance(ev, dict):
            events.append(ev)
        else:
            dropped += 1
    return events, dropped


@dataclass
class MergedStream:
    """The merged cross-rank timeline.

    ``spans``/``counters``/``gauges`` are normalised events, each with
    ``t`` (aligned wall seconds), ``rank``, ``name``; spans add
    ``dur``, ``unit``, ``tid``, ``id``, ``parent``, ``attrs`` and the
    convenience flags ``skipped``/``truncated``.
    """

    spans: list = field(default_factory=list)
    counters: list = field(default_factory=list)
    gauges: list = field(default_factory=list)
    ranks: list = field(default_factory=list)
    dropped_lines: int = 0

    def spans_named(self, name: str, *, skipped: bool = False) -> list:
        """Spans called ``name`` (skip-path placeholders excluded
        unless ``skipped=True``)."""
        return [s for s in self.spans if s["name"] == name
                and (skipped or not s["skipped"])]

    def span_names(self) -> list:
        return sorted({s["name"] for s in self.spans})


def _stream_paths(source) -> list[str]:
    if isinstance(source, (list, tuple)):
        return [str(p) for p in source]
    if os.path.isdir(source):
        paths = _glob.glob(os.path.join(source, "events.rank*.jsonl"))
        return sorted(paths, key=lambda p: (
            int(m.group(1)) if (m := _RANK_RE.search(p)) else 1 << 30, p))
    return [str(source)]


def merge_streams(source) -> MergedStream:
    """Merge rank streams into one aligned timeline.

    ``source``: a run's log directory (every ``events.rank*.jsonl``
    in it), one stream path, or an explicit list of paths.
    """
    merged = MergedStream()
    for path in _stream_paths(source):
        events, dropped = read_events(path)
        merged.dropped_lines += dropped
        m = _RANK_RE.search(path)
        rank = int(m.group(1)) if m else 0
        offset = 0.0
        for ev in events:
            if ev.get("kind") == "meta":
                rank = int(ev.get("rank", rank))
                offset = float(ev.get("wall0", 0.0)) \
                    - float(ev.get("mono0", 0.0))
                break
        if rank not in merged.ranks:
            merged.ranks.append(rank)
        last_t = 0.0
        open_spans: dict = {}
        closed: set = set()
        for ev in events:
            kind = ev.get("kind")
            mono = float(ev.get("mono", 0.0))
            t = mono + offset
            if kind == "span":
                t_end = t + float(ev.get("dur", 0.0))
                last_t = max(last_t, t_end)
                closed.add(ev.get("id"))
                merged.spans.append(_norm_span(ev, rank, t))
            elif kind == "begin":
                last_t = max(last_t, t)
                open_spans[ev.get("id")] = (ev, t)
            elif kind in ("counter", "gauge"):
                last_t = max(last_t, t)
                target = merged.counters if kind == "counter" \
                    else merged.gauges
                target.append({"name": ev.get("name", ""), "rank": rank,
                               "t": t,
                               "value": float(ev.get("value", 0.0)),
                               "attrs": ev.get("attrs") or {}})
        for sid, (ev, t) in open_spans.items():
            if sid in closed:
                continue
            # the rank died (or was SIGKILLed) inside this span: render
            # it to the stream's last heartbeat of evidence, explicitly
            # truncated — never silently dropped, never passed off as a
            # clean completion
            ev = dict(ev, dur=max(last_t - t, 0.0),
                      attrs=dict(ev.get("attrs") or {}, truncated=True))
            merged.spans.append(_norm_span(ev, rank, t, truncated=True))
    merged.spans.sort(key=lambda s: s["t"])
    merged.counters.sort(key=lambda c: c["t"])
    merged.gauges.sort(key=lambda g: g["t"])
    merged.ranks.sort()
    return merged


def _norm_span(ev: dict, rank: int, t: float,
               truncated: bool = False) -> dict:
    attrs = ev.get("attrs") or {}
    sid = ev.get("id")
    parent = ev.get("parent")
    return {"name": ev.get("name", ""), "unit": ev.get("unit", ""),
            "rank": rank, "tid": str(ev.get("tid", "main")),
            "t": t, "dur": float(ev.get("dur", 0.0)),
            "id": f"r{rank}:{sid}" if sid is not None else "",
            "parent": f"r{rank}:{parent}" if parent else "",
            "attrs": attrs,
            "skipped": bool(attrs.get("skipped")),
            "truncated": truncated or bool(attrs.get("truncated"))}
