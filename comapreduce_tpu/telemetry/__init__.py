"""Unified telemetry: structured spans, counters/gauges, trace export.

The one source of truth every subsystem reports into and every
consumer (CLI summary, ``tools/campaign_report.py``, the perf gates)
reads out of — see docs/OPERATIONS.md §13.

Import surface is deliberately light (stdlib only at import time):
``TELEMETRY`` is safe to touch from any hot path.
"""

from comapreduce_tpu.telemetry.core import (SERVING_LANE_BASE, TELEMETRY,
                                            StageTimings, Telemetry,
                                            TelemetryConfig,
                                            serving_lane_rank)
from comapreduce_tpu.telemetry.reader import (MergedStream,
                                              merge_streams,
                                              read_events)

__all__ = ["TELEMETRY", "Telemetry", "TelemetryConfig", "StageTimings",
           "MergedStream", "merge_streams", "read_events",
           "serving_lane_rank", "SERVING_LANE_BASE"]
