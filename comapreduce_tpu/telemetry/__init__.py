"""Unified telemetry: structured spans, counters/gauges, trace export.

The one source of truth every subsystem reports into and every
consumer (CLI summary, ``tools/campaign_report.py``, the perf gates)
reads out of — see docs/OPERATIONS.md §13.

Import surface is deliberately light (stdlib only at import time):
``TELEMETRY`` is safe to touch from any hot path. The live
observability plane (``telemetry/live.py`` — streaming ``/metrics`` /
``/healthz`` / ``/v1/campaign`` sidecar) and the data-quality ledger
(``telemetry/quality.py``) pull in numpy/resilience and are imported
as submodules by their consumers, never here; the run registry
(``telemetry/registry.py``) is stdlib-only and re-exported.
See docs/OPERATIONS.md §16.
"""

from comapreduce_tpu.telemetry.core import (SERVING_LANE_BASE, TELEMETRY,
                                            StageTimings, Telemetry,
                                            TelemetryConfig,
                                            serving_lane_rank)
from comapreduce_tpu.telemetry.reader import (MergedStream,
                                              merge_streams,
                                              read_events)
from comapreduce_tpu.telemetry.registry import (default_registry_path,
                                                read_runs, record_run,
                                                trend)

__all__ = ["TELEMETRY", "Telemetry", "TelemetryConfig", "StageTimings",
           "MergedStream", "merge_streams", "read_events",
           "serving_lane_rank", "SERVING_LANE_BASE",
           "default_registry_path", "read_runs", "record_run", "trend"]
