"""Exports and summaries over a merged telemetry timeline.

Three views of one ``MergedStream`` (see ``telemetry/reader.py``):

- :func:`chrome_trace` — Chrome trace-event JSON (load in Perfetto or
  ``chrome://tracing``): each rank is a process, each writer thread a
  track, spans are complete (``"X"``) events, counters/gauges are
  counter (``"C"``) tracks, truncated spans carry
  ``args.truncated = true`` and a distinct colour.
- :func:`prom_snapshot` — a Prometheus textfile-exporter ``.prom``
  snapshot: counter totals, last gauge levels, span-duration
  count/sum/quantiles, ready for ``node_exporter``'s textfile
  collector.
- :func:`summarize` / :func:`format_summary` — the terminal view:
  per-stage count/p50/p95, read/compute/write overlap fractions
  integrated from span intersections, and per-rank busy-time
  imbalance.

The per-stage quantile definitions here are THE definitions: the
``run_average`` CLI prints its end-of-run table through
:func:`format_duration_table`, and ``tools/check_perf.py`` gates the
bench's own overlap measurement against :func:`summarize`'s — one
truth for CLI, report, and CI.
"""

from __future__ import annotations

import json

from comapreduce_tpu.resilience.watchdog import percentile

__all__ = ["chrome_trace", "prom_snapshot", "summarize",
           "format_summary", "span_overlap", "overlap_seconds",
           "duration_rows", "format_duration_table", "rank_label"]


def rank_label(rank) -> str:
    """Human label for a telemetry rank. Reducer ranks are the
    campaign's real ranks (``rank 0..N-1``); streams at
    ``SERVING_LANE_BASE`` and above are long-lived serving processes
    (map server, tile server — each restart takes a fresh stream), so
    the operator views name the lane instead of showing a bare
    four-digit rank number."""
    from comapreduce_tpu.telemetry.core import SERVING_LANE_BASE

    r = int(rank)
    if r >= SERVING_LANE_BASE:
        return f"serving lane {r - SERVING_LANE_BASE}"
    return f"rank {r}"


# -- interval algebra --------------------------------------------------------

def _union(intervals) -> list:
    """Merge ``(t0, t1)`` intervals into a disjoint sorted union."""
    out = []
    for t0, t1 in sorted(i for i in intervals if i[1] > i[0]):
        if out and t0 <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], t1))
        else:
            out.append((t0, t1))
    return out


def _measure(union) -> float:
    return sum(t1 - t0 for t0, t1 in union)


def _intersection(ua, ub) -> float:
    """Total overlap length of two disjoint sorted unions."""
    total, i, j = 0.0, 0, 0
    while i < len(ua) and j < len(ub):
        lo = max(ua[i][0], ub[j][0])
        hi = min(ua[i][1], ub[j][1])
        if hi > lo:
            total += hi - lo
        if ua[i][1] < ub[j][1]:
            i += 1
        else:
            j += 1
    return total


def span_overlap(merged, name_a: str, name_b: str,
                 t0: float | None = None,
                 t1: float | None = None) -> float:
    """Fraction of the SHORTER activity hidden under the other one,
    integrated from actual span intersections per rank (cross-rank
    "overlap" is meaningless — two ranks are always concurrent):
    ``sum_r |A_r ∩ B_r| / min(sum_r |A_r|, sum_r |B_r|)``.

    ``t0``/``t1`` clip to a window (e.g. the steady-state segment).
    Returns 0.0 when either side is empty.
    """
    inter = tot_a = tot_b = 0.0
    for rank in merged.ranks:
        ua = _union(_intervals(merged, name_a, rank, t0, t1))
        ub = _union(_intervals(merged, name_b, rank, t0, t1))
        inter += _intersection(ua, ub)
        tot_a += _measure(ua)
        tot_b += _measure(ub)
    floor = min(tot_a, tot_b)
    return inter / floor if floor > 0 else 0.0


def overlap_seconds(merged, name_a: str, name_b: str,
                    t0: float | None = None,
                    t1: float | None = None) -> float:
    """Raw intersection seconds of two span families, summed per rank
    (the numerator of :func:`span_overlap`). The bench normalises this
    by its own steady wall clock — a large, stable denominator — so
    the telemetry-vs-bench overlap comparison in ``check_perf`` is not
    hostage to the (often tiny) total read time."""
    inter = 0.0
    for rank in merged.ranks:
        inter += _intersection(
            _union(_intervals(merged, name_a, rank, t0, t1)),
            _union(_intervals(merged, name_b, rank, t0, t1)))
    return inter


def _intervals(merged, name, rank, t0, t1):
    for s in merged.spans_named(name):
        if s["rank"] != rank:
            continue
        a, b = s["t"], s["t"] + s["dur"]
        if t0 is not None:
            a = max(a, t0)
        if t1 is not None:
            b = min(b, t1)
        if b > a:
            yield (a, b)


# -- the shared per-stage duration table ------------------------------------

def duration_rows(timings) -> list:
    """Summary rows for a ``{name: [seconds, ...]}`` mapping (a
    ``StageTimings``, a plain dict, or span-derived lists). Skip-path
    placeholders are excluded when the mapping knows about them
    (``StageTimings.samples``); the placeholder count is reported as
    ``skipped`` so the total file count stays visible."""
    sample_fn = getattr(timings, "samples", None)
    rows = []
    for name in sorted(timings):
        vals = list(timings[name])
        kept = list(sample_fn(name)) if sample_fn is not None else vals
        rows.append({
            "name": name, "count": len(kept),
            "skipped": len(vals) - len(kept),
            "total_s": sum(kept),
            "mean_s": sum(kept) / len(kept) if kept else 0.0,
            "p50_s": percentile(kept, 50.0) if kept else 0.0,
            "p95_s": percentile(kept, 95.0) if kept else 0.0})
    return rows


def format_duration_table(timings) -> str:
    """The end-of-run stage table (used by ``run_average``): one
    definition of count/mean/p50/p95 shared with ``campaign_report``."""
    lines = []
    for r in duration_rows(timings):
        skip = f" (+{r['skipped']} skipped)" if r["skipped"] else ""
        lines.append(
            f"{r['name']}: {r['total_s']:.2f} s over {r['count']} "
            f"files{skip} | mean {r['mean_s']:.3f} p50 {r['p50_s']:.3f} "
            f"p95 {r['p95_s']:.3f}")
    return "\n".join(lines)


# -- terminal summary --------------------------------------------------------

def summarize(merged, t0: float | None = None,
              t1: float | None = None) -> dict:
    """The operator summary of a merged timeline: per-stage
    count/p50/p95, overlap fractions from span intersections, per-rank
    busy seconds + load imbalance, truncation/drop evidence."""
    stages = {}
    for name in merged.span_names():
        durs = [s["dur"] for s in merged.spans_named(name)
                if _in_window(s, t0, t1)]
        skipped = sum(1 for s in merged.spans_named(name, skipped=True)
                      if s["skipped"] and _in_window(s, t0, t1))
        if durs or skipped:
            stages[name] = {
                "count": len(durs), "skipped": skipped,
                "total_s": sum(durs),
                "p50_s": percentile(durs, 50.0) if durs else 0.0,
                "p95_s": percentile(durs, 95.0) if durs else 0.0}
    busy = {}
    for rank in merged.ranks:
        busy[rank] = _measure(_union(
            _intervals(merged, "ingest.compute", rank, t0, t1)))
    vals = [v for v in busy.values()]
    mean_busy = sum(vals) / len(vals) if vals else 0.0
    return {
        "stages": stages,
        "overlap": {
            "read_compute": span_overlap(merged, "ingest.read",
                                         "ingest.compute", t0, t1),
            "write_compute": span_overlap(merged, "writeback.write",
                                          "ingest.compute", t0, t1)},
        "ranks": {
            "busy_s": {str(r): busy[r] for r in merged.ranks},
            # max/mean busy: 1.0 = perfectly balanced; 2.0 = the
            # slowest rank carries twice the average load
            "imbalance": (max(vals) / mean_busy
                          if vals and mean_busy > 0 else 1.0)},
        "truncated_spans": sum(1 for s in merged.spans
                               if s["truncated"]),
        "dropped_lines": merged.dropped_lines}


def _in_window(s, t0, t1) -> bool:
    if t0 is not None and s["t"] + s["dur"] < t0:
        return False
    if t1 is not None and s["t"] > t1:
        return False
    return True


def format_summary(summary: dict) -> str:
    lines = ["per-stage durations:"]
    for name, st in sorted(summary["stages"].items()):
        skip = f" (+{st['skipped']} skipped)" if st["skipped"] else ""
        lines.append(
            f"  {name}: {st['total_s']:.2f} s over {st['count']} "
            f"spans{skip} | p50 {st['p50_s']:.3f} p95 {st['p95_s']:.3f}")
    ov = summary["overlap"]
    lines.append(f"overlap: read/compute {ov['read_compute']:.2f}, "
                 f"write/compute {ov['write_compute']:.2f}")
    ranks = summary["ranks"]

    def _short(r):   # serving-lane streams read as lanes, not ranks
        lbl = rank_label(r)
        return lbl.replace("serving lane ", "serving") \
            if lbl.startswith("serving") else f"r{int(r)}"

    per_rank = ", ".join(f"{_short(r)}={v:.2f}s"
                         for r, v in sorted(ranks["busy_s"].items()))
    lines.append(f"rank busy: {per_rank} "
                 f"(imbalance {ranks['imbalance']:.2f})")
    if summary["truncated_spans"]:
        lines.append(f"TRUNCATED spans (rank died mid-span): "
                     f"{summary['truncated_spans']}")
    if summary["dropped_lines"]:
        lines.append(f"dropped (torn) stream lines: "
                     f"{summary['dropped_lines']}")
    return "\n".join(lines)


# -- Chrome trace-event export ----------------------------------------------

def chrome_trace(merged) -> dict:
    """Chrome trace-event JSON (Perfetto-loadable): ranks as
    processes, writer threads as tracks, counters/gauges as counter
    tracks. Times are microseconds relative to the earliest event so
    Perfetto's viewport opens on the data."""
    events = []
    starts = ([s["t"] for s in merged.spans]
              + [c["t"] for c in merged.counters]
              + [g["t"] for g in merged.gauges])
    t_base = min(starts) if starts else 0.0
    tids: dict = {}

    def tid_of(rank, name):
        key = (rank, name)
        if key not in tids:
            tids[key] = len([k for k in tids if k[0] == rank]) + 1
            events.append({"ph": "M", "name": "thread_name",
                           "pid": rank, "tid": tids[key],
                           "args": {"name": name}})
        return tids[key]

    for rank in merged.ranks:
        events.append({"ph": "M", "name": "process_name", "pid": rank,
                       "args": {"name": rank_label(rank)}})
    for s in merged.spans:
        args = {k: v for k, v in s["attrs"].items()}
        if s["unit"]:
            args["unit"] = s["unit"]
        if s["truncated"]:
            args["truncated"] = True
        ev = {"ph": "X", "name": s["name"], "pid": s["rank"],
              "tid": tid_of(s["rank"], s["tid"]),
              "ts": (s["t"] - t_base) * 1e6,
              "dur": s["dur"] * 1e6, "args": args}
        if s["truncated"]:
            ev["cname"] = "terrible"  # renders the cut visibly
        events.append(ev)
    # counters accumulate (delta samples -> running total); gauges are
    # levels as-is — both become "C" counter tracks
    totals: dict = {}
    for c in merged.counters:
        key = (c["rank"], c["name"])
        totals[key] = totals.get(key, 0.0) + c["value"]
        events.append({"ph": "C", "name": c["name"], "pid": c["rank"],
                       "ts": (c["t"] - t_base) * 1e6,
                       "args": {"value": totals[key]}})
    for g in merged.gauges:
        events.append({"ph": "C", "name": g["name"], "pid": g["rank"],
                       "ts": (g["t"] - t_base) * 1e6,
                       "args": {"value": g["value"]}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- Prometheus textfile snapshot -------------------------------------------

def _prom_name(name: str) -> str:
    return "comap_" + "".join(
        ch if ch.isalnum() or ch == "_" else "_" for ch in name)


def prom_snapshot(merged) -> str:
    """A textfile-exporter snapshot: counter totals, final gauge
    levels, span-duration count/sum + p50/p95 quantile gauges."""
    out = []
    totals: dict = {}
    for c in merged.counters:
        key = (c["name"], c["rank"])
        totals[key] = totals.get(key, 0.0) + c["value"]
    for (name, rank), total in sorted(totals.items()):
        mname = _prom_name(name) + "_total"
        out.append(f"# TYPE {mname} counter")
        out.append(f'{mname}{{rank="{rank}"}} {total:g}')
    last: dict = {}
    for g in merged.gauges:  # time-sorted: last write wins
        last[(g["name"], g["rank"])] = g["value"]
    for (name, rank), value in sorted(last.items()):
        mname = _prom_name(name)
        out.append(f"# TYPE {mname} gauge")
        out.append(f'{mname}{{rank="{rank}"}} {value:g}')
    for name in merged.span_names():
        durs = [s["dur"] for s in merged.spans_named(name)]
        if not durs:
            continue
        base = _prom_name(name) + "_seconds"
        out.append(f"# TYPE {base} summary")
        for q in (50.0, 95.0):
            out.append(f'{base}{{quantile="{q / 100:g}"}} '
                       f"{percentile(durs, q):g}")
        out.append(f"{base}_sum {sum(durs):g}")
        out.append(f"{base}_count {len(durs)}")
    return "\n".join(out) + "\n"


def write_trace(merged, path: str) -> None:
    with open(path, "w") as f:
        json.dump(chrome_trace(merged), f)


def write_prom(merged, path: str) -> None:
    with open(path, "w") as f:
        f.write(prom_snapshot(merged))
