"""Cross-run regression registry: ``evidence/runs.jsonl``.

Every completed campaign / bench / perf-gate run appends ONE summary
record — key throughputs, CG iteration counts, gate verdicts, git sha —
so the ``evidence/BENCH_*.json`` trajectory finally has a
machine-readable time series behind it. ``tools/campaign_watch.py
trend`` compares the latest record against the trailing window and
exits nonzero on regression: a perf cliff becomes an alert, not
archaeology.

Record schema (one JSON object per line)::

    {"schema": 1, "kind": "campaign" | "bench" | "perf_gate",
     "t": "2026-08-05T07:00:00Z", "t_unix": 1785913200.0,
     "git_sha": "cc6d92b...", "host": "vm", "ok": true,
     "metrics": {"files_per_s": 3.2, "cg_iters": 41, ...}}

Metric direction is inferred from the key name (``trend``): suffixes
``_per_s`` / ``_throughput`` / ``_rate`` are higher-is-better;
``_s`` / ``_seconds`` / ``_ms`` / ``_iters`` / ``_errors`` /
``_failures`` are lower-is-better; anything else is informational and
never gates. Appends use the quarantine ledger's torn-line-safe
discipline; reads drop unparseable lines.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import subprocess
import time

__all__ = ["default_registry_path", "format_trend", "read_runs",
           "record_run", "trend"]

logger = logging.getLogger("comapreduce_tpu")

RUNS_SCHEMA = 1

_LOWER_BETTER = ("_s", "_seconds", "_ms", "_iters", "_errors",
                 "_failures")
_HIGHER_BETTER = ("_per_s", "_throughput", "_rate")


def default_registry_path() -> str:
    """``$COMAP_RUNS_REGISTRY`` when set, else ``evidence/runs.jsonl``
    next to the package checkout (the directory the BENCH_*.json
    snapshots already live in)."""
    env = os.environ.get("COMAP_RUNS_REGISTRY", "")
    if env:
        return env
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, "evidence", "runs.jsonl")


def _git_sha() -> str:
    try:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        out = subprocess.run(["git", "rev-parse", "HEAD"], cwd=repo,
                             capture_output=True, text=True, timeout=10)
        return out.stdout.strip() if out.returncode == 0 else ""
    except Exception:
        return ""


def record_run(kind: str, metrics: dict, *, ok: bool = True,
               path: str | None = None, git_sha: str | None = None,
               extra: dict | None = None) -> dict:
    """Append one run-summary record; returns it. Non-finite / non-
    numeric metric values are stringified rather than rejected (a
    crashed bench's partial summary is still evidence). I/O failures
    are logged and swallowed — the registry must never fail a run."""
    path = path or default_registry_path()
    clean = {}
    for k, v in (metrics or {}).items():
        try:
            clean[str(k)] = float(v)
        except (TypeError, ValueError):
            clean[str(k)] = str(v)
    rec = {"schema": RUNS_SCHEMA, "kind": str(kind),
           "t": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "t_unix": time.time(),
           "git_sha": _git_sha() if git_sha is None else git_sha,
           "host": socket.gethostname(), "ok": bool(ok),
           "metrics": clean}
    if extra:
        rec.update({k: v for k, v in extra.items() if k not in rec})
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        needs_nl = False
        try:
            with open(path, "rb") as f:
                f.seek(-1, os.SEEK_END)
                needs_nl = f.read(1) != b"\n"
        except OSError:
            pass
        with open(path, "a", encoding="utf-8") as f:
            f.write(("\n" if needs_nl else "")
                    + json.dumps(rec, separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
    except OSError as exc:
        logger.warning("run registry append to %s failed (%s: %s)",
                       path, type(exc).__name__, exc)
    return rec


def read_runs(path: str | None = None, *,
              kind: str | None = None) -> list:
    """All parseable run records in append (time) order, optionally
    filtered by ``kind``."""
    path = path or default_registry_path()
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return []
    runs = []
    for line in raw.split(b"\n"):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except Exception:
            continue
        if not isinstance(rec, dict) or "metrics" not in rec:
            continue
        if kind is not None and rec.get("kind") != kind:
            continue
        runs.append(rec)
    return runs


def _direction(key: str) -> int:
    """+1 higher-is-better, -1 lower-is-better, 0 informational."""
    for suf in _HIGHER_BETTER:
        if key.endswith(suf):
            return 1
    for suf in _LOWER_BETTER:
        if key.endswith(suf):
            return -1
    return 0


def trend(runs: list, *, window: int = 5,
          tolerance: float = 0.2) -> dict:
    """Compare the LATEST run against the trailing window.

    For every directional metric present in the latest record and in
    at least one baseline record, the baseline is the window median;
    a regression is the latest being worse than baseline by more than
    ``tolerance`` (fractional). A latest record with ``ok: false``
    (a failed gate) is always a regression. Returns ``{"ok", "n_runs",
    "n_baseline", "regressions": [...], "checked": [...]}`` —
    ``ok: True`` with fewer than 2 runs (nothing to compare yet).
    """
    if len(runs) < 2:
        return {"ok": True, "n_runs": len(runs), "n_baseline": 0,
                "regressions": [], "checked": []}
    latest = runs[-1]
    baseline = runs[max(0, len(runs) - 1 - window):-1]
    regressions, checked = [], []
    if latest.get("ok") is False:
        regressions.append({"metric": "ok", "latest": 0.0,
                            "baseline": 1.0, "ratio": 0.0,
                            "direction": "gate"})
    for key, value in sorted((latest.get("metrics") or {}).items()):
        d = _direction(key)
        if d == 0 or not isinstance(value, (int, float)):
            continue
        base_vals = sorted(
            r["metrics"][key] for r in baseline
            if isinstance((r.get("metrics") or {}).get(key),
                          (int, float)))
        if not base_vals:
            continue
        med = base_vals[len(base_vals) // 2]
        checked.append(key)
        if med == 0:
            continue
        ratio = float(value) / float(med)
        worse = ratio < 1.0 - tolerance if d > 0 \
            else ratio > 1.0 + tolerance
        if worse:
            regressions.append({
                "metric": key, "latest": float(value),
                "baseline": float(med), "ratio": round(ratio, 4),
                "direction": "higher_better" if d > 0
                else "lower_better"})
    return {"ok": not regressions, "n_runs": len(runs),
            "n_baseline": len(baseline), "regressions": regressions,
            "checked": checked}


def format_trend(res: dict) -> str:
    lines = [f"trend: latest vs trailing {res['n_baseline']} run(s) — "
             + ("OK" if res["ok"] else
                f"{len(res['regressions'])} REGRESSION(S)")]
    for r in res["regressions"]:
        lines.append(
            f"  {r['metric']}: {r['latest']:g} vs baseline "
            f"{r['baseline']:g} (x{r['ratio']:g}, {r['direction']})")
    if res["checked"]:
        lines.append("  checked: " + ", ".join(res["checked"]))
    return "\n".join(lines)
