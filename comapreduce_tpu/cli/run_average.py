"""TOD-reduction driver: ``python -m comapreduce_tpu.cli.run_average
configuration.toml`` (reference ``run_average.py:100-118``).

TOML layout::

    [Global]
    processes = ["CheckLevel1File", "AssignLevel1Data", ...]
    filelist = "filelist.txt"        # one Level-1 path per line
    output_dir = "level2"
    log_dir = "logs"                 # default: <output_dir>/logs
    calibrator_filelist = "cals.txt" # optional: enables run_astro_cal

    [StageName]
    # per-stage kwargs

    [precision]
    # optional precision policy (docs/OPERATIONS.md §15): flows
    # through Runner.from_config as PrecisionPolicy — e.g.
    # tod_dtype = "bf16" streams Level-1 TOD at half the HBM/H2D
    # bytes (accumulators and products stay f32)

Multi-host sharding (reference: MPI rank filelist shard,
``run_average.py:38-39``): rank/n_ranks come from ``jax.process_index``
when jax.distributed is initialised, else 0/1 (single host).
"""

from __future__ import annotations

import os
import sys

from comapreduce_tpu.pipeline import Runner, load_toml, set_logging
from comapreduce_tpu.pipeline.config import read_filelist as _read_filelist


def _rank_info():
    from comapreduce_tpu.parallel.multihost import rank_info

    return rank_info()


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    figure_dir = ""
    retry_quarantined = False
    live_port = None
    rest = []
    for a in argv:
        if a == "--figures":
            figure_dir = "figures"
        elif a.startswith("--figures="):
            figure_dir = a.split("=", 1)[1]
        elif a == "--retry-quarantined":
            # re-admit everything the quarantine ledger currently skips
            # (each re-admission is itself a ledger event; see
            # docs/OPERATIONS.md §7)
            retry_quarantined = True
        elif a.startswith("--live-port="):
            # live observability sidecar (docs/OPERATIONS.md §16):
            # /metrics, /healthz, /v1/campaign over this run's state
            live_port = int(a.split("=", 1)[1])
        else:
            rest.append(a)
    if len(rest) != 1:
        print("usage: python -m comapreduce_tpu.cli.run_average "
              "[--figures[=DIR]] [--retry-quarantined] "
              "[--live-port=N] configuration.toml", file=sys.stderr)
        return 2
    config = load_toml(rest[0])
    glob = config.get("Global", {})
    if retry_quarantined:
        config = dict(config)
        config["resilience"] = dict(config.get("resilience", {}),
                                    retry_quarantined=True)
    rank, n_ranks = _rank_info()
    # run logs default under the OUTPUT dir, never the CWD: a fleet of
    # campaign runs must not strew per-rank logfiles over whatever
    # directory the operator happened to launch from (or the repo root)
    log_dir = str(glob.get("log_dir", "") or
                  os.path.join(str(glob.get("output_dir", ".")), "logs"))
    set_logging(base="run_average", log_dir=log_dir,
                rank=rank, level=str(glob.get("log_level", "INFO")))
    runner = Runner.from_config(config, rank=rank, n_ranks=n_ranks)
    live = None
    if live_port is not None and rank == 0:
        # rank 0 only: the plane reads EVERY rank's on-disk state, so
        # one sidecar per campaign is the whole picture
        from comapreduce_tpu.telemetry.live import LiveServer

        live = LiveServer(runner.state_dir or runner.output_dir,
                          port=live_port, n_ranks=n_ranks).start()
        print(f"live plane: http://{live.host}:{live.port}/metrics")
    if n_ranks > 1:
        res = runner._resilience_runtime()
        if res.lease_ttl_s > 0:
            # elastic campaign (docs/OPERATIONS.md §11) — the DEFAULT:
            # no barrier needed; Runner claims files under leases, dead
            # ranks' leases expire and survivors steal them, and a rank
            # joining late simply starts claiming
            pass
        elif res.straggler_timeout_s > 0 and res.heartbeat is not None:
            # static shard (lease_ttl_s = 0 opt-out): the pre-shard
            # straggler barrier names ranks that are already dead —
            # advisory only; a dead rank's shard waits for the next
            # launch (elastic claiming would have finished it this run)
            from comapreduce_tpu.parallel.multihost import \
                straggler_barrier

            res.heartbeat.start()
            straggler_barrier(
                runner.state_dir or runner.output_dir, rank, n_ranks,
                timeout_s=res.straggler_timeout_s,
                heartbeat=res.heartbeat)
    figure_dir = figure_dir or str(glob.get("figure_dir", ""))
    if figure_dir:
        # per-obsid QA figures (reference: VaneCalibration.py:173-190,
        # Level1Averaging.py:727-789, Level2Data.py:300-327)
        for p in runner.processes:
            if hasattr(p, "figure_dir"):
                p.figure_dir = figure_dir
    filelist = _read_filelist(glob["filelist"])
    runner.run_tod(filelist)
    cal_list_path = glob.get("calibrator_filelist")
    if cal_list_path:
        from comapreduce_tpu.pipeline.runner import level2_path

        cal_l2 = [level2_path(runner.output_dir, f, runner.prefix)
                  for f in _read_filelist(cal_list_path)]
        runner.run_astro_cal(filelist, cal_l2,
                             cache_path=glob.get("calibration_cache", ""))
    # the end-of-run stage table goes through the telemetry summary
    # formatter — ONE definition of count/mean/p50/p95 shared with
    # tools/campaign_report.py and the bench (docs/OPERATIONS.md §13);
    # skip-path placeholders are counted separately, not averaged in
    from comapreduce_tpu.telemetry import TELEMETRY
    from comapreduce_tpu.telemetry.report import format_duration_table

    table = format_duration_table(runner.timings)
    if table:
        print(table)
    if TELEMETRY.enabled:
        TELEMETRY.close()  # drain the event buffer before exit
        print(f"telemetry: {TELEMETRY.path} "
              f"(merge with tools/campaign_report.py)")
    if live is not None:
        live.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
