"""Batch launcher: run the TOD pipeline as N parallel processes.

Reference: ``batchrun.py`` (legacy) and the PBS recipe
(``scripts/general/pbs.script``: ``mpirun -n 16 python run_average.py``) —
the operator-facing way to fan a filelist across ranks on one node. Here
the launcher spawns N ``run_average`` worker processes, each with
``COMAP_RANK``/``COMAP_NRANKS`` set (read by
``parallel.multihost.rank_info``, ahead of any distributed runtime); the
workers then take their round-robin filelist shard exactly as an
``mpiexec`` launch would::

    python -m comapreduce_tpu.cli.batchrun -n 4 configuration.toml

For multi-NODE launches use the ``jax.distributed`` recipe in
``parallel/multihost.py`` instead (one process per host).
"""

from __future__ import annotations

import os
import subprocess
import sys

__all__ = ["main"]


def _usage() -> int:
    print("usage: python -m comapreduce_tpu.cli.batchrun "
          "[-n N] configuration.toml [run_average args...]",
          file=sys.stderr)
    return 2


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    n_procs = 2
    rest = []
    it = iter(argv)
    for a in it:
        if a in ("-n", "--n-procs"):
            try:
                n_procs = int(next(it))
            except (StopIteration, ValueError):
                return _usage()
        elif a.startswith("--n-procs="):
            try:
                n_procs = int(a.split("=", 1)[1])
            except ValueError:
                return _usage()
        else:
            rest.append(a)
    if len(rest) < 1 or n_procs < 1:
        return _usage()

    procs = []
    for rank in range(n_procs):
        env = dict(os.environ)
        # the workers shard by rank without a coordinator: the pipeline
        # stages are embarrassingly parallel over files (reference ranks
        # never talk during the TOD loop either)
        env["COMAP_RANK"] = str(rank)
        env["COMAP_NRANKS"] = str(n_procs)
        # N processes cannot share one accelerator (libtpu is exclusive);
        # host fan-out is a CPU pattern — a single process drives the
        # chip(s) via the device mesh instead. Explicit JAX_PLATFORMS in
        # the environment overrides this.
        if n_procs > 1:
            env.setdefault("JAX_PLATFORMS", "cpu")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "comapreduce_tpu.cli.run_average",
             *rest], env=env))
    rcs = [p.wait() for p in procs]
    return next((r for r in rcs if r), 0)


if __name__ == "__main__":
    raise SystemExit(main())
