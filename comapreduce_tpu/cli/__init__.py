"""Command-line entry points (the reference's repo-root drivers).

- ``python -m comapreduce_tpu.cli.run_average config.toml`` — the TOD
  reduction pipeline (``run_average.py`` parity);
- ``python -m comapreduce_tpu.cli.run_destriper params.ini`` — the
  destriping map-maker (``MapMaking/run_destriper.py`` parity).
"""
