"""Destriping map-maker driver: ``python -m comapreduce_tpu.cli.
run_destriper parameters.ini`` (reference ``MapMaking/run_destriper.py``).

INI layout (legacy ``ParserClass`` syntax, ``MapMaking/parameters.ini``)::

    [Inputs]
    filelist : filelist.txt
    output_dir : maps
    prefix : co2
    bands : 0, 1, 2, 3
    offset_length : 50
    niter : 100
    threshold : 1e-6
    calibration : true

    [Pixelization]
    type : wcs            # or healpix
    crval : 170.0, 52.0
    cdelt : 0.01666, 0.01666
    shape : 480, 480
    nside : 4096          # healpix only
    galactic : false

Calibrator filelists get the reference's overrides (offset 250,
threshold 1, ``run_destriper.py:142-144``). Maps are written per band:
FITS image (WCS) or partial-sky HEALPix FITS.
"""

from __future__ import annotations

import logging
import os
import sys
import time

import numpy as np

logger = logging.getLogger("comapreduce_tpu")

from comapreduce_tpu.mapmaking.destriper import destripe_jit
from comapreduce_tpu.mapmaking.fits_io import (write_fits_image,
                                               write_healpix_map)
from comapreduce_tpu.mapmaking.leveldata import read_comap_data
from comapreduce_tpu.mapmaking.wcs import WCS
from comapreduce_tpu.pipeline.config import IniConfig

__all__ = ["main", "make_band_map", "make_band_maps_joint",
           "parse_destriper_section", "solve_band",
           "solve_band_checkpointed", "write_band_map"]


def _aslist(v):
    return v if isinstance(v, list) else [v]


# plan + compiled-solver memo: a multi-band run shares one pointing (pixels
# come from pointing alone; the band only selects tod/weights), so bands
# 1..3 reuse band 0's host plan build AND its XLA compilation. Keyed on a
# content digest — ~10x cheaper than the argsort plan build it avoids.
_PLAN_MEMO: dict = {}


def _memoized(tag: str, pixels: np.ndarray, extra_key: tuple, build):
    """Digest-keyed memo: the key hashes the pixel vector's content
    (~10x cheaper than the plan build it avoids). One slot PER TAG —
    'single' and 'sharded' solvers against the same pointing coexist
    (alternating them must not thrash the memo and recompile)."""
    import hashlib

    pixels = np.ascontiguousarray(pixels)
    key = (pixels.shape, str(pixels.dtype), extra_key,
           hashlib.sha1(pixels.tobytes()).hexdigest())
    slot = _PLAN_MEMO.get(tag)
    if slot is None or slot[0] != key:
        _PLAN_MEMO[tag] = slot = (key, build(pixels))
    return slot[1]


def _planned_solver(pixels: np.ndarray, npix: int, offset_length: int,
                    n_iter: int, threshold: float, n_groups: int = 0,
                    compact: bool = False, precond: str = "jacobi",
                    pair_batch: int | None = None, mg_smooth: int = 1,
                    kernels: str = "auto", cg_dot: str = "f32",
                    trace_iters: int = 0):
    import functools

    import jax

    from comapreduce_tpu.mapmaking.destriper import destripe_planned
    from comapreduce_tpu.mapmaking.pointing_plan import build_pointing_plan

    def build(pix):
        plan = build_pointing_plan(pix, npix, offset_length,
                                   pair_batch=pair_batch)
        fn = jax.jit(functools.partial(destripe_planned, plan=plan,
                                       n_iter=n_iter,
                                       threshold=threshold,
                                       n_groups=n_groups,
                                       dense_maps=not compact,
                                       mg_smooth=mg_smooth,
                                       precond=precond,
                                       kernels=kernels,
                                       cg_dot=cg_dot,
                                       trace_iters=trace_iters))
        if compact:
            return fn, np.asarray(plan.uniq_pixels)
        return fn

    # ground and plain solvers get separate slots: alternating them on
    # one pointing must not thrash the per-tag memo
    tag = "single-ground" if n_groups else "single"
    if compact:
        # compact (hit-pixel) maps, expanded on host by the caller: the
        # multi-RHS joint solve must never hold (n_bands, npix) dense
        # products on device (3x the per-band peak; ~10 GB at nside 4096
        # x 4 bands would OOM a 16 GB chip)
        tag += "-compact"
    return _memoized(tag, pixels,
                     (int(npix), int(offset_length), int(n_iter),
                      float(threshold), int(n_groups), str(precond),
                      pair_batch, int(mg_smooth), str(kernels),
                      str(cg_dot), int(trace_iters)), build)


def _sharded_planned_solver(mesh, pixels: np.ndarray, npix: int,
                            offset_length: int, n_iter: int,
                            threshold: float, n_bands: int = 0,
                            n_groups: int = 0,
                            with_coarse: bool = False,
                            with_mg: bool = False, mg_smooth: int = 1,
                            mg_omega: float = 2.0 / 3.0,
                            with_banded: bool = False,
                            precond: str = "jacobi",
                            pair_batch: int | None = None,
                            kernels: str = "auto",
                            cg_dot: str = "f32",
                            trace_iters: int = 0):
    """Memoized sharded solver (plans + ONE compiled shard_map program
    per pointing — bands share both). ``n_bands > 0`` builds the
    multi-RHS program (all bands in one CG); ``n_groups > 0`` the joint
    ground program; ``with_coarse`` the two-level-preconditioned one;
    ``with_mg`` the multigrid V-cycle one (hierarchy passed at call
    time); ``with_banded`` the measured-noise banded-weighted one."""
    from comapreduce_tpu.mapmaking.pointing_plan import build_sharded_plans
    from comapreduce_tpu.parallel.sharded import (
        make_destripe_sharded_planned)

    n_shards = len(mesh.devices.ravel())

    def build(pix):
        plans = build_sharded_plans(pix, npix, offset_length, n_shards,
                                    pair_batch=pair_batch)
        run = make_destripe_sharded_planned(mesh, plans, n_iter=n_iter,
                                            threshold=threshold,
                                            n_bands=n_bands,
                                            n_groups=n_groups,
                                            with_coarse=with_coarse,
                                            with_mg=with_mg,
                                            mg_smooth=mg_smooth,
                                            mg_omega=mg_omega,
                                            with_banded=with_banded,
                                            precond=precond,
                                            kernels=kernels,
                                            cg_dot=cg_dot,
                                            trace_iters=trace_iters)
        return run, np.asarray(plans[0].uniq_global)

    return _memoized(f"sharded{n_bands}-g{n_groups}-c{int(with_coarse)}"
                     f"-m{int(with_mg)}-b{int(with_banded)}",
                     pixels,
                     (n_shards, int(npix), int(offset_length), int(n_iter),
                      float(threshold), int(n_groups),
                      bool(with_coarse), bool(with_mg), int(mg_smooth),
                      float(mg_omega), bool(with_banded), str(precond),
                      pair_batch, str(kernels), str(cg_dot),
                      int(trace_iters)), build)


def _shard_quantum(mesh, offset_length: int) -> int:
    """Padding quantum of the sharded solvers: every shard gets whole
    offsets."""
    return len(mesh.devices.ravel()) * offset_length


def _pad_pixels(pix: np.ndarray, n_pad: int, npix: int) -> np.ndarray:
    """Host-side shard padding of the pixel stream: the out-of-range
    ``npix`` sentinel carries zero weight downstream. ONE home for the
    sentinel rule — the single-band and joint sharded paths must never
    drift apart."""
    if not n_pad:
        return pix
    return np.concatenate([pix, np.full(n_pad, npix, pix.dtype)])


def _expand_compact(uniq: np.ndarray, npix: int, compact) -> np.ndarray:
    """Compact (hit-pixel) map over ``uniq`` -> the band's full pixel
    space (shared by both sharded paths)."""
    full = np.zeros(npix, np.float32)
    full[uniq] = np.asarray(compact)[: uniq.size]
    return full


def _expand_joint_results(res, uniq: np.ndarray, npix: int, nb: int):
    """Split one compact multi-RHS result into per-band dense results:
    host-expand each band's destriped/naive/weight products (the hit map
    depends on pointing alone and is shared). ONE home for the rule —
    the sharded and single-process joint paths must never drift."""
    hit_full = _expand_compact(uniq, npix, res.hit_map)
    div = np.asarray(res.diverged)
    return [res._replace(
        offsets=res.offsets[i],
        destriped_map=_expand_compact(uniq, npix, res.destriped_map[i]),
        naive_map=_expand_compact(uniq, npix, res.naive_map[i]),
        weight_map=_expand_compact(uniq, npix, res.weight_map[i]),
        hit_map=hit_full,
        residual=res.residual[i],
        diverged=div[i] if div.ndim else div) for i in range(nb)]


def _attach_dict(data, result):
    """Stamp the seen-pixel dictionary onto a host-level result
    (compacted solves only): ``DestriperResult.sky_pixels`` lets the
    writers/coadd scatter compact map values to the sky at write time
    without the ``DestriperData`` side channel. No-op for dense
    solves (the field stays None)."""
    space = getattr(data, "pixel_space", None)
    if space is not None and space.compacted:
        return result._replace(sky_pixels=space.pixels)
    return result


def parse_destriper_section(destr: dict, coarse_default: int = 0):
    """``[Destriper]`` knobs ->
    ``(precond, coarse_block, pair_batch, mg, kernels, noise_weight)``
    (docs/OPERATIONS.md §3):

    - ``preconditioner = none | jacobi | twolevel | multigrid`` — CG
      preconditioner selection; ``twolevel`` = Jacobi + the coarse
      correction (block from ``coarse_block``, default 8);
      ``multigrid`` = the V-cycle over the offset-block ladder
      (``mg_levels`` levels x8 apart from ``mg_block``, ``mg_smooth``
      damped-Jacobi sweeps per level — ``mg`` comes back as the config
      dict for ``build_multigrid_hierarchy``, else None). Absent, the
      legacy ``[Inputs] coarse_precond`` default (``coarse_default``)
      stands.
    - ``pair_batch = N | auto`` — one-hot binning chunks merged per MXU
      matmul in the planned matvec (auto = HBM-planner sized).
    - ``kernels = auto | xla | pallas | interpret`` — the planned
      matvec's binning/gather implementation (PR 11): ``auto``
      (default) resolves at trace time to the Mosaic kernels on TPU and
      the XLA paths everywhere else; ``interpret`` runs the kernels
      under the Pallas interpreter (CPU parity/debug — slow).
    - ``checkpoint_every = N`` — validated here (>= 0; 0 = off) but
      returned separately by the caller: every N CG iterations the
      chunked solve durably snapshots ``(x, iter, residual history,
      preconditioner id)`` so a killed solve resumes instead of
      restarting (:func:`solve_band_checkpointed`,
      docs/OPERATIONS.md §11).

    A typo'd or contradictory knob raises instead of silently running
    the default (the ``[Resilience]`` section's rule)."""
    from comapreduce_tpu.mapmaking.destriper import (CONFIG_KERNELS,
                                                     CONFIG_PRECONDITIONERS)

    coarse_block = int(coarse_default)
    mg = None
    pname = str(destr.get("preconditioner", "")).strip().lower()
    if pname not in ("",) + CONFIG_PRECONDITIONERS:
        raise ValueError(
            f"[Destriper] preconditioner must be "
            f"{'|'.join(CONFIG_PRECONDITIONERS)}, got {pname!r}")
    if "coarse_block" in destr and pname != "twolevel":
        # the knob only exists under twolevel; accepting-and-ignoring it
        # (or letting the legacy [Inputs] default override it) would be
        # the silent-drop this section's rule forbids
        raise ValueError(
            "[Destriper] coarse_block only applies under preconditioner"
            f"=twolevel (preconditioner is {pname or 'absent'!r}); remove "
            "the knob or select twolevel")
    mg_knobs = [k for k in ("mg_levels", "mg_smooth", "mg_block")
                if k in destr]
    if mg_knobs and pname != "multigrid":
        raise ValueError(
            f"[Destriper] {'/'.join(mg_knobs)} only apply under "
            f"preconditioner=multigrid (preconditioner is "
            f"{pname or 'absent'!r}); remove the knob(s) or select "
            "multigrid")
    precond = "none" if pname == "none" else "jacobi"
    if pname == "none":
        coarse_block = 0
    elif pname == "jacobi":
        coarse_block = 0
    elif pname == "multigrid":
        coarse_block = 0
        mg = {"levels": int(destr.get("mg_levels", 2)),
              "smooth": int(destr.get("mg_smooth", 1)),
              "block": int(destr.get("mg_block", 8))}
        if mg["levels"] < 1 or mg["smooth"] < 1 or mg["block"] < 2:
            raise ValueError(
                f"[Destriper] multigrid knobs out of range (mg_levels "
                f">= 1, mg_smooth >= 1, mg_block >= 2): {mg}")
    elif pname == "twolevel":
        if "coarse_block" in destr:
            coarse_block = int(destr["coarse_block"])
            if coarse_block < 1:
                # 0 means "coarse disabled" everywhere else ([Inputs]
                # coarse_precond : 0) — contradicting twolevel; raise
                # like any other bad knob instead of silently running
                # the default block
                raise ValueError(
                    "[Destriper] coarse_block must be >= 1 under "
                    f"preconditioner=twolevel, got {coarse_block}")
        else:
            coarse_block = coarse_block or 8
    pb_raw = destr.get("pair_batch", "auto")
    pair_batch = (None if str(pb_raw).strip().lower() in ("auto", "")
                  else int(pb_raw))
    if pair_batch is not None and pair_batch < 1:
        raise ValueError(f"[Destriper] pair_batch must be >= 1 or auto, "
                         f"got {pb_raw!r}")
    if int(destr.get("checkpoint_every", 0) or 0) < 0:
        raise ValueError(
            f"[Destriper] checkpoint_every must be >= 0 (0 = off), got "
            f"{destr.get('checkpoint_every')!r}")
    kernels = str(destr.get("kernels", "auto")).strip().lower() or "auto"
    if kernels not in CONFIG_KERNELS:
        raise ValueError(f"[Destriper] kernels must be "
                         f"{'|'.join(CONFIG_KERNELS)}, got "
                         f"{destr.get('kernels')!r}")
    nw_raw = str(destr.get("noise_weight", "white")).strip().lower()
    if nw_raw not in ("", "white", "banded"):
        raise ValueError(f"[Destriper] noise_weight must be white|banded, "
                         f"got {destr.get('noise_weight')!r}")
    if "noise_bandwidth" in destr and nw_raw != "banded":
        # same silent-drop rule as coarse_block/mg_* above
        raise ValueError(
            "[Destriper] noise_bandwidth only applies under noise_weight"
            f"=banded (noise_weight is {nw_raw or 'absent'!r}); remove "
            "the knob or select banded")
    noise_weight = None
    if nw_raw == "banded":
        noise_weight = {"bandwidth": int(destr.get("noise_bandwidth", 4))}
        if noise_weight["bandwidth"] < 1:
            raise ValueError(
                f"[Destriper] noise_bandwidth must be >= 1, got "
                f"{destr.get('noise_bandwidth')!r}")
    return precond, coarse_block, pair_batch, mg, kernels, noise_weight


def make_band_map(filenames, band, wcs=None, nside=None, galactic=False,
                  offset_length=50, n_iter=100, threshold=1e-6,
                  use_ground=False, use_calibration=True, sharded=False,
                  medfilt_window=400, tod_variant="auto",
                  coarse_block=0, prefetch=0, cache=None,
                  resilience=None, precond="jacobi", pair_batch=None,
                  mg=None, compact="auto", kernels="auto",
                  tod_dtype="f32", cg_dot="f32", noise_weight=None,
                  quality=None):
    """Read one band and destripe it. Returns (DestriperData, result).

    The scatter-free planned destriper (``destripe_planned``, >10x per CG
    iteration at production shape) is the default — including joint
    ground-template solves when the groups align to offsets (the data
    layer guarantees it; misaligned geometries and sharded ground solves
    fall back to the general scatter path). ``prefetch``/``cache`` are
    the streaming-ingest knobs (docs/ingest.md): reads overlap the
    per-file host prep, and a cache shared across per-band calls skips
    re-decoding the filelist for bands past the first. ``compact``
    selects seen-pixel compaction (``read_comap_data``; auto = HEALPix
    on, WCS off) — every device map vector is then coverage-sized.
    ``mg`` is the ``[Destriper] preconditioner = multigrid`` config
    dict (``parse_destriper_section``)."""
    data = read_comap_data(filenames, band=band, wcs=wcs, nside=nside,
                           galactic=galactic, offset_length=offset_length,
                           use_calibration=use_calibration,
                           medfilt_window=medfilt_window,
                           tod_variant=tod_variant,
                           prefetch=prefetch, cache=cache,
                           resilience=resilience, compact=compact,
                           tod_dtype=tod_dtype)
    return data, solve_band(data, offset_length=offset_length,
                            n_iter=n_iter, threshold=threshold,
                            use_ground=use_ground, sharded=sharded,
                            coarse_block=coarse_block,
                            watchdog=getattr(resilience, "watchdog",
                                             None),
                            unit=f"band{band}", precond=precond,
                            pair_batch=pair_batch, mg=mg, kernels=kernels,
                            cg_dot=cg_dot, noise_weight=noise_weight,
                            quality=quality, band=band)


def _build_banded(data, noise_weight, quality, band, offset_length,
                  n_offsets, n_shards, unit=""):
    """Assemble the measured-noise banded offset prior for one band's
    solve (``[Destriper] noise_weight = banded``) and ledger every white
    fallback — the operator must be able to answer "which files kept
    white weighting, and why" from the log alone. Returns the
    ``(c0, cs)`` pair, or None when the knob is off or EVERY group fell
    back (callers then omit the kwarg — byte-identical white program)."""
    if not noise_weight:
        return None
    from comapreduce_tpu.mapmaking.noise_weight import build_banded_weight

    banded, report = build_banded_weight(
        getattr(data, "groups", None) or [], quality or [], n_offsets,
        offset_length, band=band,
        bandwidth=int(noise_weight.get("bandwidth", 4)),
        n_shards=n_shards)
    if report["fallbacks"]:
        detail = ", ".join(f"{f['file']}/feed{f['feed']}:{f['reason']}"
                           for f in report["fallbacks"][:8])
        more = len(report["fallbacks"]) - 8
        logger.warning(
            "noise_weight=banded %s: %d group(s) kept white weighting "
            "(%s%s)", unit or "<band>", report["white"], detail,
            f", +{more} more" if more > 0 else "")
    if banded is None:
        logger.warning(
            "noise_weight=banded %s: every group fell back to white — "
            "running the white-weight program (exact parity)",
            unit or "<band>")
    else:
        logger.info("noise_weight=banded %s: %d/%d group(s) weighted "
                    "from measured fits", unit or "<band>",
                    report["banded"], report["banded"] + report["white"])
    return banded


def _watched_cg(solve, watchdog, unit: str):
    """Run ``solve`` under the ``mapmaking.cg_solve`` wall budget and
    translate a blown hard deadline into the operator warning — ONE
    wrapper for the per-band and joint solve paths, so the default
    (joint multi-RHS) route is watched exactly like the fallback."""
    from comapreduce_tpu.mapmaking.destriper import watched_solve

    result, state = watched_solve(solve, watchdog, unit=unit)
    if state is not None and state.hard_expired:
        logger.warning(
            "CG solve %s blew its wall budget (%.1f s > hard "
            "%.1f s); the map below is LATE, not wrong — raise the "
            "[Resilience] deadlines budget for mapmaking.cg_solve "
            "or investigate the stall (tools/watchdog_report.py)",
            unit or "<band>", state.elapsed_s, state.hard_s)
    return result


def solve_band(data, offset_length=50, n_iter=100, threshold=1e-6,
               use_ground=False, sharded=False, coarse_block=0,
               watchdog=None, unit="", precond="jacobi",
               pair_batch=None, mg=None, x0=None, kernels="auto",
               cg_dot="f32", noise_weight=None, quality=None, band=0,
               trace_iters=None, trace_base=0):
    """Destripe one already-read band (the solve half of
    :func:`make_band_map` — callers holding ``DestriperData`` reuse it
    without re-reading the filelist).

    ``coarse_block > 0`` enables the two-level preconditioner on the
    planned paths — non-sharded AND sharded
    (``destriper.build_coarse_preconditioner`` — reaches the
    threshold-1e-6 spec where Jacobi stalls; the coarse system is built
    per (pointing, weights) on host). The scatter fallbacks and the
    sharded ground program keep Jacobi, with a warning.

    ``watchdog`` puts the whole solve under the ``mapmaking.cg_solve``
    wall budget (``destriper.watched_solve``): device compute cannot be
    cancelled, so the soft deadline warns/ledgers a stall and a blown
    hard deadline flags the late result through the same operator
    signal path as a tripped divergence monitor.

    ``precond``/``pair_batch``/``mg`` are the ``[Destriper]`` section's
    knobs (docs/OPERATIONS.md §3): CG preconditioner selection
    ('jacobi'|'none'; the two-level upgrade rides ``coarse_block``, the
    multigrid V-cycle the ``mg`` config dict) and the merged one-hot
    binning batch (None = HBM-planner auto). Multigrid runs on BOTH
    planned offsets-only paths — non-sharded AND sharded (the hierarchy
    is built host-side from the padded global pointing/weights and the
    V-cycle's level-0 restriction is psum-assembled under shard_map) —
    plus the non-sharded offset-aligned ground solve; the scatter
    fallbacks and the sharded ground program keep Jacobi like they do
    for ``coarse_block``.

    ``noise_weight``/``quality``/``band`` enable the measured-noise
    banded offset weighting (``[Destriper] noise_weight = banded``,
    docs/OPERATIONS.md §3): the quality ledger's per-(file, feed, band)
    ``white_sigma/fknee_hz/alpha`` fits become a banded inverse-
    covariance prior on the offset amplitudes, applied inside the CG
    matvec on both planned paths. Groups without a usable fit keep
    white weighting, ledgered per file; the joint ground solve always
    keeps white (the prior composes with offsets-only solves).

    ``x0`` warm-starts the CG from a prior iterate (the solver-
    checkpoint resume, :func:`solve_band_checkpointed`) — non-sharded
    offsets-only planned path only; ground/sharded solves ignore it
    with a warning and start cold.

    ``trace_iters`` controls the per-iteration solver trace
    (``telemetry.solver_trace``, docs/OPERATIONS.md §17): ``None`` (the
    default) auto-enables depth-``n_iter`` tracing on the non-sharded
    planned paths whenever telemetry is on, ``0`` forces it off, and an
    explicit positive depth caps the history. Traced solves append
    iteration + summary records to ``solver.rank{r}.jsonl`` under the
    telemetry log dir; ``trace_base`` offsets the recorded global
    iteration numbers (the checkpointed chunk loop passes its running
    ``done`` count so chunked traces continue one global axis)."""
    from comapreduce_tpu.mapmaking.destriper import _check_precond
    from comapreduce_tpu.telemetry import solver_trace

    _check_precond(precond, coarse=coarse_block or None, mg=mg)
    if trace_iters is None:
        # the planned paths — non-sharded AND sharded (the shard_map
        # programs thread trace_iters and return replicated histories) —
        # ride the telemetry switch; the scatter fallbacks stay untraced
        trace_iters = int(n_iter) if solver_trace.trace_enabled() else 0
    if noise_weight and use_ground:
        # the banded prior composes with the offsets-only normal
        # operator; the joint ground solve keeps the white-weight system
        # (destripe_planned raises on the combination) — loud, ledgered
        logger.warning("noise_weight=banded: the joint ground solve "
                       "keeps white weighting")
        noise_weight = None
    if x0 is not None and (sharded or use_ground):
        # destripe_planned's x0 is offsets-only by construction (the
        # joint ground solve raises on it) and the sharded programs
        # take no warm start — drop it loudly rather than crash a
        # resume that would otherwise just cost iterations
        logger.warning("solver warm start x0 ignored: only the "
                       "non-sharded offsets-only planned solve "
                       "supports it")
        x0 = None
    if watchdog is not None:
        return _watched_cg(
            lambda: solve_band(data, offset_length=offset_length,
                               n_iter=n_iter, threshold=threshold,
                               use_ground=use_ground, sharded=sharded,
                               coarse_block=coarse_block, unit=unit,
                               precond=precond, pair_batch=pair_batch,
                               mg=mg, x0=x0, kernels=kernels,
                               cg_dot=cg_dot, noise_weight=noise_weight,
                               quality=quality, band=band,
                               trace_iters=trace_iters,
                               trace_base=trace_base),
            watchdog, unit)
    # the applied-preconditioner label + solve configuration the trace
    # records carry (solver_report groups convergence by it) — shared by
    # the sharded and non-sharded planned paths below
    precision_id = f"tod={getattr(data.tod, 'dtype', 'f32')}" \
                   f"|cgdot={cg_dot}"

    # the shape-bucket stamp the per-bucket solver policy groups by
    # (ISSUE 20): offset length + flat sample count — the two axes the
    # solve's conditioning and cost actually follow
    bucket_id = f"L={offset_length}|N={int(np.size(data.tod))}"

    def _record_trace(res, label):
        if getattr(res, "trace", None) is None:
            return
        solver_trace.record_solve(
            res, band=unit or "band", base=trace_base,
            precond_id=f"{label}|L{offset_length}",
            precision_id=precision_id, threshold=threshold,
            bucket=bucket_id)

    if sharded:
        import jax

        from comapreduce_tpu.parallel.sharded import destripe_sharded
        from jax.sharding import Mesh

        # LOCAL devices: multi-host destriping is data parallel over
        # filelist shards (each process destripes its own files)
        mesh = Mesh(np.array(jax.local_devices()), ("time",))
        # ONE padding quantum for everything below: gid_off, pixels,
        # tod/weights and az must all agree on the padded offset count
        n_pad = (-data.tod.size) % _shard_quantum(mesh, offset_length)
        gid_off = None
        if use_ground:
            from comapreduce_tpu.mapmaking.destriper import (
                ground_ids_per_offset)

            gids = np.asarray(data.ground_ids)
            if n_pad:   # padding adds whole zero-weight offsets: park
                # them in the last group (their weight is zero anyway)
                fill = gids[-1] if gids.size else 0
                gids = np.concatenate(
                    [gids, np.full(n_pad, fill, gids.dtype)])
            try:
                gid_off = ground_ids_per_offset(gids, offset_length)
            except ValueError:
                gid_off = None   # misaligned: scatter fallback below
        if use_ground and gid_off is None:
            if coarse_block or mg:
                logger.warning("%s active but the ground groups are not "
                               "offset-aligned; sharded scatter "
                               "fallback runs Jacobi only",
                               "multigrid" if mg else
                               "coarse_precond (default 8 for field "
                               "runs)")
            result = destripe_sharded(
                mesh, data.tod, data.pixels, data.weights, data.npix,
                offset_length=offset_length, n_iter=n_iter,
                threshold=threshold, ground_ids=data.ground_ids,
                az=data.az, n_groups=data.n_groups, precond=precond,
                cg_dot=cg_dot)
        else:
            import jax.numpy as jnp

            # pad on host: the pixel vector is consumed by the host plan
            # build only — routing it through pad_for_shards would cost a
            # full H2D+D2H round trip of several GB at production scale
            pix_host = _pad_pixels(np.asarray(data.pixels), n_pad,
                                   data.npix)
            tod, weights = data.tod, data.weights
            if n_pad:
                tod = jnp.concatenate(
                    [jnp.asarray(tod), jnp.zeros(n_pad, jnp.float32)])
                weights = jnp.concatenate(
                    [jnp.asarray(weights), jnp.zeros(n_pad, jnp.float32)])
            use_coarse = bool(coarse_block) and gid_off is None
            use_mg = mg is not None and gid_off is None
            # the coarse/multigrid systems and the banded prior are all
            # built host-side from the GLOBAL padded pointing/weights
            # (padding samples carry zero weight, so they contribute
            # nothing — same idiom for every operator-shaping input)
            w_host = None
            if use_coarse or use_mg:
                w_host = np.zeros(pix_host.size, np.float32)
                w_host[:data.tod.size] = np.asarray(data.weights)
            mg_hier = None
            if use_mg:
                from comapreduce_tpu.mapmaking.destriper import (
                    MultigridUnavailable, build_multigrid_hierarchy)

                try:
                    mg_hier = build_multigrid_hierarchy(
                        pix_host, w_host, data.npix, offset_length,
                        block=mg["block"], levels=mg["levels"])
                except MultigridUnavailable as exc:
                    # same degenerate-geometry fallback as the
                    # non-sharded branch below
                    logger.warning("multigrid unavailable for this "
                                   "geometry (%s); running Jacobi", exc)
                    use_mg = False
            banded = None
            if gid_off is None:
                banded = _build_banded(
                    data, noise_weight, quality, band, offset_length,
                    pix_host.size // offset_length,
                    len(mesh.devices.ravel()), unit=unit)
            run, uniq = _sharded_planned_solver(
                mesh, pix_host, data.npix, offset_length, n_iter,
                threshold,
                n_groups=data.n_groups if gid_off is not None else 0,
                with_coarse=use_coarse, with_mg=use_mg,
                mg_smooth=mg["smooth"] if use_mg else 1,
                with_banded=banded is not None, precond=precond,
                pair_batch=pair_batch, kernels=kernels, cg_dot=cg_dot,
                trace_iters=trace_iters)
            if gid_off is not None:
                if coarse_block or mg:
                    logger.warning("%s: the sharded ground program "
                                   "keeps Jacobi",
                                   "multigrid" if mg else
                                   "coarse_precond")
                az = np.asarray(data.az, np.float32)
                if n_pad:
                    az = np.concatenate([az, np.zeros(n_pad, np.float32)])
                result = run(tod, weights, ground_off=gid_off, az=az)
                _record_trace(result, f"{precond}-sharded")
            else:
                kw_run = {}
                if use_coarse:
                    from comapreduce_tpu.mapmaking.destriper import (
                        build_coarse_preconditioner)

                    kw_run["coarse"] = build_coarse_preconditioner(
                        pix_host, w_host, data.npix, offset_length,
                        block=int(coarse_block))
                elif use_mg:
                    kw_run["mg"] = mg_hier
                if banded is not None:
                    kw_run["banded"] = banded
                result = run(tod, weights, **kw_run)
                label = ("multigrid" if use_mg else
                         "twolevel" if use_coarse else precond)
                if banded is not None:
                    label += "|nw=banded"
                _record_trace(result, f"{label}-sharded")
            result = result._replace(
                destriped_map=_expand_compact(uniq, data.npix,
                                              result.destriped_map),
                naive_map=_expand_compact(uniq, data.npix,
                                          result.naive_map),
                weight_map=_expand_compact(uniq, data.npix,
                                           result.weight_map),
                hit_map=_expand_compact(uniq, data.npix, result.hit_map))
    else:
        import jax.numpy as jnp

        n = (data.tod.size // offset_length) * offset_length
        gid_off = None
        if use_ground:
            from comapreduce_tpu.mapmaking.destriper import (
                ground_ids_per_offset)

            try:
                gid_off = ground_ids_per_offset(
                    np.asarray(data.ground_ids[:n]), offset_length)
            except ValueError:
                # groups not offset-aligned (unusual geometry):
                # the scatter path handles per-sample group ids
                gid_off = None
            if gid_off is None:
                if coarse_block or mg:
                    logger.warning(
                        "%s active but the ground groups are not "
                        "offset-aligned; scatter fallback runs "
                        "Jacobi only",
                        "multigrid" if mg else
                        "coarse_precond (default 8 for field runs)")
                return _attach_dict(data, destripe_jit(
                    data.tod[:n], data.pixels[:n],
                    data.weights[:n], data.npix,
                    offset_length=offset_length,
                    n_iter=n_iter, threshold=threshold,
                    ground_ids=data.ground_ids[:n],
                    az=data.az[:n],
                    n_groups=data.n_groups,
                    precond=precond, kernels=kernels, cg_dot=cg_dot))
        kwargs = {}
        if coarse_block:
            from comapreduce_tpu.mapmaking.destriper import (
                build_coarse_preconditioner)

            grp, aci = build_coarse_preconditioner(
                np.asarray(data.pixels[:n]), np.asarray(data.weights[:n]),
                data.npix, offset_length, block=int(coarse_block))
            kwargs["coarse"] = (jnp.asarray(grp), jnp.asarray(aci))
        elif mg is not None:
            from comapreduce_tpu.mapmaking.destriper import (
                MultigridUnavailable, build_multigrid_hierarchy)

            try:
                kwargs["mg"] = build_multigrid_hierarchy(
                    np.asarray(data.pixels[:n]),
                    np.asarray(data.weights[:n]), data.npix,
                    offset_length, block=mg["block"],
                    levels=mg["levels"])
            except MultigridUnavailable as exc:
                # geometry too small for any >= 2-unknown level: a
                # 1-block coarse system is pure null mode and would
                # diverge by construction — Jacobi instead, loudly
                logger.warning("multigrid unavailable for this "
                               "geometry (%s); running Jacobi", exc)
                mg = None
        mg_smooth = mg["smooth"] if mg is not None else 1
        banded = None
        if not use_ground:
            banded = _build_banded(data, noise_weight, quality, band,
                                   offset_length, n // offset_length, 1,
                                   unit=unit)
            if banded is not None:
                kwargs["banded"] = (jnp.asarray(banded[0]),
                                    jnp.asarray(banded[1]))
        # the banded prior is part of the linear SYSTEM (A + B), not
        # the preconditioner: every re-solve below must keep it
        sys_kw = ({"banded": kwargs["banded"]} if "banded" in kwargs
                  else {})
        precond_used = ("multigrid" if kwargs.get("mg") is not None
                        else "twolevel" if kwargs.get("coarse") is not None
                        else precond)
        if banded is not None:
            precond_used += "|nw=banded"
        if use_ground:
            fn = _planned_solver(np.asarray(data.pixels[:n]), data.npix,
                                 offset_length, n_iter, threshold,
                                 n_groups=data.n_groups, precond=precond,
                                 pair_batch=pair_batch,
                                 mg_smooth=mg_smooth, kernels=kernels,
                                 cg_dot=cg_dot, trace_iters=trace_iters)
            result = fn(jnp.asarray(data.tod[:n]),
                        jnp.asarray(data.weights[:n]),
                        ground_off=jnp.asarray(gid_off),
                        az=jnp.asarray(data.az[:n]), **kwargs)
        else:
            fn = _planned_solver(np.asarray(data.pixels[:n]), data.npix,
                                 offset_length, n_iter, threshold,
                                 precond=precond, pair_batch=pair_batch,
                                 mg_smooth=mg_smooth, kernels=kernels,
                                 cg_dot=cg_dot, trace_iters=trace_iters)
            if x0 is not None:
                kwargs["x0"] = jnp.asarray(x0)
            result = fn(jnp.asarray(data.tod[:n]),
                        jnp.asarray(data.weights[:n]), **kwargs)
        if (kwargs.get("coarse") is not None
                or kwargs.get("mg") is not None) and \
                bool(np.any(np.asarray(result.diverged))):
            # CG divergence tripwire fired under the two-level/multigrid
            # preconditioner (an ill-assembled coarse inverse can lose
            # SPD in f32): re-solve under plain Jacobi — warm-started
            # from the monitored solve's best iterate on the
            # offsets-only path; the joint ground solve restarts cold
            # (x0 is offsets-only by construction). Slower but safe —
            # and recorded, not silent (docs/OPERATIONS.md §7).
            which = "multigrid" if "mg" in kwargs else "coarse"
            # the diverged attempt's trace is recorded too — the decay
            # that tripped the monitor is exactly what the operator
            # opens solver_report for
            _record_trace(result, precond_used)
            precond_used = ("jacobi-fallback" if banded is None
                            else "jacobi-fallback|nw=banded")
            if use_ground:
                logger.warning(
                    "CG diverged under the %s preconditioner "
                    "(diverged=%s); re-solving ground solve with "
                    "Jacobi from a cold start", which,
                    np.asarray(result.diverged))
                result = fn(jnp.asarray(data.tod[:n]),
                            jnp.asarray(data.weights[:n]),
                            ground_off=jnp.asarray(gid_off),
                            az=jnp.asarray(data.az[:n]))
            else:
                logger.warning(
                    "CG diverged under the %s preconditioner "
                    "(diverged=%s); re-solving with Jacobi from the "
                    "best iterate", which, np.asarray(result.diverged))
                result = fn(jnp.asarray(data.tod[:n]),
                            jnp.asarray(data.weights[:n]),
                            x0=result.offsets, **sys_kw)
        _record_trace(result, precond_used)
    if sharded and bool(np.any(np.asarray(result.diverged))):
        # the sharded programs are memoized per-(geometry, coarse) pair;
        # flag the divergence for the operator instead of compiling a
        # second program mid-run
        logger.warning("sharded CG solve flagged divergence "
                       "(diverged=%s); re-run with [Inputs] "
                       "coarse_precond : 0 to force Jacobi",
                       np.asarray(result.diverged))
    return _attach_dict(data, result)


def solve_band_checkpointed(data, checkpoint_path, checkpoint_every,
                            offset_length=50, n_iter=100,
                            threshold=1e-6, watchdog=None, unit="",
                            x0=None, precond_tag="", **kw):
    """:func:`solve_band` in durable checkpoint/resume chunks
    (``[Destriper] checkpoint_every``, docs/OPERATIONS.md §11).

    A jitted CG solve cannot snapshot mid-program, so checkpointing
    happens at the host level: the band solves in chunks of
    ``checkpoint_every`` iterations, each warm-started from the last
    iterate through ``solve_band``'s ``x0``, and after every chunk the
    running state ``(x, iterations done, residual history,
    preconditioner id)`` is durably written to ``checkpoint_path``
    (``destriper.save_solver_checkpoint`` — tmp + fsync + atomic
    replace, so a SIGKILL mid-write leaves the previous snapshot, never
    a torn one). A relaunch loads the snapshot and pays only the
    REMAINING iterations; a torn/alien/stale snapshot (schema or
    preconditioner-id mismatch) is discarded and the solve starts cold.
    The snapshot is deleted once the solve completes — it protects a
    solve in flight, not a finished map.

    ``x0`` is an INITIAL warm start (the map server hands the previous
    epoch's offsets here) used only when no snapshot resumes — a
    snapshot is always further along. ``precond_tag`` is appended to
    the preconditioner id; callers whose linear system changes in ways
    the built-in id cannot see (the serving census, which grows while
    keeping ``trimmed_sample_count``-compatible shapes) bake their own
    discriminator in so a stale snapshot refuses to load.

    Falls back to one plain un-checkpointed ``solve_band`` when
    ``checkpoint_every <= 0`` or on the sharded/ground paths (no
    ``x0`` warm start there — resuming would silently restart cold
    every chunk and pay full price anyway)."""
    from comapreduce_tpu.mapmaking.destriper import (
        load_solver_checkpoint, save_solver_checkpoint)

    chunk = int(checkpoint_every)
    if chunk <= 0 or kw.get("sharded") or kw.get("use_ground"):
        if chunk > 0:
            logger.warning(
                "checkpoint_every=%d ignored: the sharded/ground solve "
                "paths have no x0 warm start, so a resumed chunk would "
                "restart cold and checkpointing would only add I/O",
                chunk)
        return solve_band(data, offset_length=offset_length,
                          n_iter=n_iter, threshold=threshold,
                          watchdog=watchdog, unit=unit, x0=x0, **kw)
    # the snapshot is only valid against the SAME linear system and
    # preconditioner: bake the solve configuration and the trimmed
    # sample count into an id the loader refuses to cross
    mg = kw.get("mg") or {}
    precond_id = "|".join(str(v) for v in (
        kw.get("precond", "jacobi"), int(kw.get("coarse_block", 0) or 0),
        int(mg.get("block", 0) or 0), offset_length, threshold,
        (int(data.tod.size) // offset_length) * offset_length))
    if kw.get("cg_dot", "f32") != "f32":
        # a compensated-dot solve follows a different iterate path —
        # refuse to resume it from (or leave behind) an f32 snapshot.
        # Appended only when NON-default so snapshots written before
        # this knob existed keep loading byte-identically.
        precond_id = f"{precond_id}|cgdot={kw['cg_dot']}"
    if kw.get("noise_weight"):
        # the banded prior changes the linear system itself — a white
        # snapshot must never resume into a banded solve (or vice
        # versa). Non-default-only append, same rule as cg_dot.
        precond_id = f"{precond_id}|nw=banded"
    if precond_tag:
        precond_id = f"{precond_id}|{precond_tag}"
    snap = load_solver_checkpoint(checkpoint_path, precond_id=precond_id)
    x0 = None if x0 is None else np.asarray(x0, np.float32)
    done, residuals = 0, []
    if snap is not None:
        x0 = np.asarray(snap["offsets"])
        done = int(snap["n_done"])
        residuals = list(snap["residuals"])
        logger.info("solver checkpoint %s: resuming %s at iteration %d "
                    "of %d", checkpoint_path, unit or "<band>", done,
                    n_iter)
    from comapreduce_tpu.telemetry import TELEMETRY

    result = None
    while True:
        step = max(min(chunk, n_iter - done), 1)
        t_chunk = time.perf_counter()
        # trace_base=done: a chunked trace continues the SAME global
        # iteration axis across chunks and resumes (solver_trace)
        result = solve_band(data, offset_length=offset_length,
                            n_iter=step, threshold=threshold,
                            watchdog=watchdog, unit=unit, x0=x0,
                            trace_base=done, **kw)
        ran = int(np.asarray(result.n_iter))
        done += ran
        residual = float(np.asarray(result.residual))
        residuals.append(residual)
        x0 = np.asarray(result.offsets)
        save_solver_checkpoint(checkpoint_path, x0, done, residuals,
                               precond_id)
        # per-chunk CG observability: iterations actually run, the
        # running residual and the preconditioner id — the destriper's
        # convergence trajectory as spans on the campaign timeline
        TELEMETRY.event_span("destriper.cg_chunk",
                             time.perf_counter() - t_chunk,
                             unit=unit or "<band>", iters=ran,
                             n_done=done, residual=residual,
                             precond_id=precond_id)
        # ran < step means the chunk converged (or was already converged
        # on entry, ran == 0) before exhausting its budget — done either
        # way; the budget and threshold exits mirror the plain solve's
        if done >= n_iter or residual <= threshold or ran < step:
            break
    try:
        os.unlink(checkpoint_path)
    except OSError:
        pass
    # solve_band already stamped sky_pixels; report the CUMULATIVE
    # iteration count, not the last chunk's
    return result._replace(n_iter=np.int32(done),
                           residual=np.float32(residuals[-1]))


def make_band_maps_joint(filenames, bands, wcs=None, nside=None,
                         galactic=False, offset_length=50, n_iter=100,
                         threshold=1e-6, use_calibration=True,
                         medfilt_window=400, sharded=False,
                         tod_variant="auto", coarse_block=0,
                         prefetch=0, cache=None, resilience=None,
                         watchdog=None, precond="jacobi",
                         pair_batch=None, mg=None, compact="auto",
                         kernels="auto", tod_dtype="f32", cg_dot="f32",
                         noise_weight=None, quality=None):
    """ALL bands in one multi-RHS planned solve.

    The per-band loop's pixel stream comes from pointing alone, so when
    every band reads the same sample set the bands are independent RHS
    against one pointing plan: stack (n_bands, N) tod/weights and let
    ``destripe_planned`` run per-band CGs in a single program — each
    CG iteration's one-hot binning is built once and contracted against
    every band (MXU batching), and per-iteration gathers/dispatch are
    paid once instead of n_bands times.

    Returns ``(datas, results)``: the per-band ``DestriperData`` list
    plus the per-band result list — or ``(datas, None)`` when the bands'
    sample streams differ (e.g. a feed dead in one band only); the
    caller then falls back to per-band ``solve_band`` calls on the SAME
    ``datas`` (the reads are never repeated). ``watchdog`` puts every
    joint CG solve under the same ``mapmaking.cg_solve`` wall budget
    as ``solve_band`` (``_watched_cg``) — the DEFAULT multi-band path
    must not escape the deadline the fallback path honours.
    """
    import jax.numpy as jnp

    # one shared BlockCache across the per-band reads: bands 1..n decode
    # nothing — the pixel/weight extraction reuses band 0's decoded
    # stores (the multi-pass workload the ingest cache exists for)
    datas = [read_comap_data(filenames, band=b, wcs=wcs, nside=nside,
                             galactic=galactic,
                             offset_length=offset_length,
                             use_calibration=use_calibration,
                             medfilt_window=medfilt_window,
                             tod_variant=tod_variant,
                             prefetch=prefetch, cache=cache,
                             resilience=resilience, compact=compact,
                             tod_dtype=tod_dtype)
             for b in bands]
    pix0 = np.asarray(datas[0].pixels)
    for d in datas[1:]:
        if d.tod.size != datas[0].tod.size \
                or not np.array_equal(np.asarray(d.pixels), pix0):
            return datas, None
    npix = datas[0].npix
    nb = len(bands)
    if sharded:
        import jax
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.local_devices()), ("time",))
        N = datas[0].tod.size
        n_pad = (-N) % _shard_quantum(mesh, offset_length)
        pix_host = _pad_pixels(pix0, n_pad, npix)
        # ONE preallocated stack per input (no per-band concatenate
        # temporaries on top of the datas already in memory)
        tod = np.zeros((nb, N + n_pad), np.float32)
        wgt = np.zeros((nb, N + n_pad), np.float32)
        for i, d in enumerate(datas):
            tod[i, :N] = d.tod
            wgt[i, :N] = d.weights
        kw_run = {}
        if coarse_block:
            from comapreduce_tpu.mapmaking.destriper import (
                build_coarse_preconditioner, coarse_pattern)

            pat = coarse_pattern(pix_host, npix, offset_length,
                                 block=int(coarse_block))
            pre = [build_coarse_preconditioner(pix_host, wgt[i], npix,
                                               offset_length,
                                               block=int(coarse_block),
                                               pattern=pat)
                   for i in range(nb)]
            kw_run["coarse"] = (pre[0][0],
                                np.stack([p[1] for p in pre]))
        elif mg is not None:
            from comapreduce_tpu.mapmaking.destriper import (
                MultigridUnavailable, build_multigrid_hierarchy,
                multigrid_patterns, stack_multigrid)

            # same build as the non-sharded joint branch below, run on
            # the PADDED global pointing/weights (the sharded-operator
            # idiom: padding carries zero weight everywhere)
            try:
                pats = multigrid_patterns(pix_host, npix, offset_length,
                                          block=mg["block"],
                                          levels=mg["levels"])
                kw_run["mg"] = stack_multigrid(
                    [build_multigrid_hierarchy(pix_host, wgt[i], npix,
                                               offset_length,
                                               patterns=pats)
                     for i in range(nb)])
            except MultigridUnavailable as exc:
                logger.warning("multigrid unavailable for this "
                               "geometry (%s); running Jacobi", exc)
                mg = None
        if noise_weight:
            from comapreduce_tpu.mapmaking.noise_weight import (
                stack_banded)

            banded = stack_banded(
                [_build_banded(datas[i], noise_weight, quality, b,
                               offset_length,
                               pix_host.size // offset_length,
                               len(mesh.devices.ravel()),
                               unit=f"band{b}(joint)")
                 for i, b in enumerate(bands)])
            if banded is not None:
                kw_run["banded"] = banded
        run, uniq = _sharded_planned_solver(
            mesh, pix_host, npix, offset_length, n_iter, threshold,
            n_bands=nb, with_coarse=bool(coarse_block),
            with_mg="mg" in kw_run,
            mg_smooth=mg["smooth"] if mg is not None else 1,
            with_banded="banded" in kw_run, precond=precond,
            pair_batch=pair_batch, kernels=kernels, cg_dot=cg_dot)
        res = _watched_cg(
            lambda: run(jnp.asarray(tod), jnp.asarray(wgt), **kw_run),
            watchdog, "joint(sharded)")
        if bool(np.any(np.asarray(res.diverged))):
            # same operator contract as solve_band's sharded branch:
            # the memoized program is not recompiled mid-run, but a
            # diverged (best-iterate, non-converged) map must never
            # ship silently
            logger.warning("sharded joint CG solve flagged divergence "
                           "(diverged=%s); re-run with [Inputs] "
                           "coarse_precond : 0 to force Jacobi",
                           np.asarray(res.diverged))
        return datas, [_attach_dict(d, r) for d, r in
                       zip(datas, _expand_joint_results(res, uniq, npix,
                                                        nb))]
    n = (datas[0].tod.size // offset_length) * offset_length
    tod = np.stack([np.asarray(d.tod)[:n] for d in datas])
    wgt = np.stack([np.asarray(d.weights)[:n] for d in datas])
    kwargs = {}
    if coarse_block:
        from comapreduce_tpu.mapmaking.destriper import (
            build_coarse_preconditioner, coarse_pattern)

        pat = coarse_pattern(pix0[:n], npix, offset_length,
                             block=int(coarse_block))
        pre = [build_coarse_preconditioner(pix0[:n], wgt[i], npix,
                                           offset_length,
                                           block=int(coarse_block),
                                           pattern=pat)
               for i in range(nb)]
        kwargs["coarse"] = (jnp.asarray(pre[0][0]),
                            jnp.stack([jnp.asarray(p[1]) for p in pre]))
    elif mg is not None:
        from comapreduce_tpu.mapmaking.destriper import (
            MultigridUnavailable, build_multigrid_hierarchy,
            multigrid_patterns, stack_multigrid)

        # one pattern set (pixels are band-invariant), per-band weight
        # aggregates, stacked into the single multi-RHS hierarchy
        try:
            pats = multigrid_patterns(pix0[:n], npix, offset_length,
                                      block=mg["block"],
                                      levels=mg["levels"])
            kwargs["mg"] = stack_multigrid(
                [build_multigrid_hierarchy(pix0[:n], wgt[i], npix,
                                           offset_length, patterns=pats)
                 for i in range(nb)])
        except MultigridUnavailable as exc:
            # same degenerate-geometry fallback as solve_band
            logger.warning("multigrid unavailable for this geometry "
                           "(%s); running Jacobi", exc)
            mg = None
    if noise_weight:
        from comapreduce_tpu.mapmaking.noise_weight import stack_banded

        banded = stack_banded(
            [_build_banded(datas[i], noise_weight, quality, b,
                           offset_length, n // offset_length, 1,
                           unit=f"band{b}(joint)")
             for i, b in enumerate(bands)])
        if banded is not None:
            kwargs["banded"] = (jnp.asarray(banded[0]),
                                jnp.asarray(banded[1]))
    # the banded prior is part of the linear system, not the
    # preconditioner — the divergence fallback re-solve keeps it
    sys_kw = {"banded": kwargs["banded"]} if "banded" in kwargs else {}
    # compact solve + host expansion (same shape handling as the sharded
    # branch above): the joint program only ever holds (nb, n_rank)
    # compact products on device, never (nb, npix) dense maps
    fn, uniq = _planned_solver(pix0[:n], npix, offset_length, n_iter,
                               threshold, compact=True, precond=precond,
                               pair_batch=pair_batch,
                               mg_smooth=mg["smooth"] if mg else 1,
                               kernels=kernels, cg_dot=cg_dot)
    res = _watched_cg(
        lambda: fn(jnp.asarray(tod), jnp.asarray(wgt), **kwargs),
        watchdog, "joint")
    if (kwargs.get("coarse") is not None
            or kwargs.get("mg") is not None) and \
            bool(np.any(np.asarray(res.diverged))):
        # same divergence fallback as solve_band: drop to Jacobi, warm-
        # started per band from the monitored solve's best iterates
        logger.warning(
            "joint CG diverged under the %s preconditioner "
            "(diverged=%s); re-solving with Jacobi from the best "
            "iterates", "multigrid" if "mg" in kwargs else "coarse",
            np.asarray(res.diverged))
        res = _watched_cg(
            lambda: fn(jnp.asarray(tod), jnp.asarray(wgt),
                       x0=res.offsets, **sys_kw),
            watchdog, "joint(fallback)")
    return datas, [_attach_dict(d, r) for d, r in
                   zip(datas, _expand_joint_results(res, uniq, npix, nb))]


def band_map_writer(path, data, result):
    """Materialise the (small) output maps and return a zero-arg writer
    over them. The async writeback path submits THIS closure — it
    captures only the maps plus the wcs/pixel geometry, never the
    band's full ``data`` (GB-scale TOD/pointing arrays must not stay
    alive on the write queue while later bands load theirs).

    The seen-pixel dictionary comes from ``result.sky_pixels`` when the
    solve attached one (``_attach_dict``) — the RESULT is authoritative
    for the index space its map values live in; ``data`` supplies the
    fallback for results produced outside the CLI solvers.

    Written products are ALWAYS f32, whatever the ``[Precision]``
    policy did upstream (OPERATIONS.md §15): the FITS BITPIX tables
    and the tile blob format (``CMTL1`` is little-endian f32 by spec —
    a narrower map would silently change every tile hash) both assume
    it, so the cast is forced and asserted here rather than trusted."""
    maps = {
        "DESTRIPED": np.asarray(result.destriped_map, np.float32),
        "NAIVE": np.asarray(result.naive_map, np.float32),
        "WEIGHTS": np.asarray(result.weight_map, np.float32),
        "HITS": np.asarray(result.hit_map, np.float32),
    }
    assert all(v.dtype == np.float32 for v in maps.values()), \
        "map products must be f32 regardless of the precision policy"
    wcs, sky_pixels, nside = data.wcs, data.sky_pixels, data.nside
    space = getattr(data, "pixel_space", None)
    if getattr(result, "sky_pixels", None) is not None:
        from comapreduce_tpu.mapmaking import healpix as hp
        from comapreduce_tpu.mapmaking.pixel_space import PixelSpace

        npix_sky = wcs.npix if wcs is not None else hp.nside2npix(nside)
        space = PixelSpace.from_dictionary(
            np.asarray(result.sky_pixels), npix_sky)
        sky_pixels = space.pixels

    def write() -> None:
        if wcs is not None:
            # compacted WCS solves scatter to the field HERE — the one
            # write-time expansion (PixelSpace.expand); dense solves
            # pass through
            vals = maps if space is None or not space.compacted else \
                {k: space.expand(v) for k, v in maps.items()}
            shaped = {k: v.reshape(wcs.ny, wcs.nx)
                      for k, v in vals.items()}
            write_fits_image(path, shaped, header=dict(wcs.header_cards()))
        elif sky_pixels is not None:
            # compacted HEALPix: partial map over the dictionary — the
            # full sky is never materialised, not even on host
            write_healpix_map(path, maps, sky_pixels, nside)
        else:
            # dense (compact=false) HEALPix: every sky pixel explicit
            write_healpix_map(path, maps,
                              np.arange(maps["WEIGHTS"].shape[-1],
                                        dtype=np.int64), nside)

    return write


def write_band_map(path, data, result):
    """Write destriped/naive/weight/hit maps (``run_destriper.py:19-77``)."""
    band_map_writer(path, data, result)()


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    retry_quarantined = "--retry-quarantined" in argv
    argv = [a for a in argv if a != "--retry-quarantined"]
    live_port = None
    for a in list(argv):
        if a.startswith("--live-port="):
            # live observability sidecar (docs/OPERATIONS.md §16)
            live_port = int(a.split("=", 1)[1])
            argv.remove(a)
    if len(argv) != 1:
        print("usage: python -m comapreduce_tpu.cli.run_destriper "
              "[--retry-quarantined] [--live-port=N] parameters.ini",
              file=sys.stderr)
        return 2
    from comapreduce_tpu.parallel.multihost import rank_info

    ini = IniConfig(argv[0])
    inputs = ini.get("Inputs", {})
    pixel = ini.get("Pixelization", {})
    from comapreduce_tpu.pipeline.config import read_filelist

    filelist = read_filelist(inputs["filelist"])
    # multi-process launch: initialise the distributed runtime; the
    # round-robin filelist shard (same split as the Runner; the
    # reference instead slices contiguous blocks,
    # run_destriper.py:131-138) is taken AFTER the straggler barrier
    # below — each process writes its own partial maps
    rank, n_ranks = rank_info()
    out_dir = inputs.get("output_dir", ".")
    os.makedirs(out_dir, exist_ok=True)
    prefix = inputs.get("prefix", "map")
    bands = [int(b) for b in _aslist(inputs.get("bands", [0, 1, 2, 3]))]
    offset_length = int(inputs.get("offset_length", 50))
    n_iter = int(inputs.get("niter", 100))
    threshold = float(inputs.get("threshold", 1e-6))
    calibrator = bool(inputs.get("calibrator", False))
    if calibrator:  # reference overrides, run_destriper.py:142-144
        offset_length = int(inputs.get("offset_length", 250))
        threshold = 1.0

    wcs = nside = None
    if str(pixel.get("type", "wcs")).lower() == "healpix":
        nside = int(pixel.get("nside", 512))
    else:
        crval = [float(x) for x in _aslist(pixel.get("crval", [0.0, 0.0]))]
        cdelt = [float(x) for x in _aslist(pixel.get(
            "cdelt", [1.0 / 60.0, 1.0 / 60.0]))]
        shape = [int(x) for x in _aslist(pixel.get("shape", [480, 480]))]
        wcs = WCS.from_field(tuple(crval), tuple(cdelt), tuple(shape))

    use_ground = bool(inputs.get("ground", False))
    use_cal = bool(inputs.get("calibration", True))
    sharded = bool(inputs.get("sharded", False))
    galactic = bool(pixel.get("galactic", False))
    # which Level-2 TOD product to map (COMAPData.py:255-258 role);
    # "frequency_binned" maps the plain no-gain-correction reduction
    tod_variant = str(inputs.get("tod_variant", "auto"))
    # two-level destriper preconditioner block (0 = Jacobi only): the
    # threshold-1e-6 spec is unreachable under Jacobi on production-like
    # pointings (stalls ~3e-5); 8-32 reaches it. Default ON (block 8)
    # for field runs since the on-chip A/B (SWEEP_r05: spec reached in
    # 213 iters / 3.27 s where Jacobi stalls at 2.6e-6 in 400 / 5.23 s);
    # calibrator runs (threshold 1) converge in a few iterations and
    # would only pay the host-side build. `coarse_precond : 0` disables.
    coarse_block = int(inputs.get("coarse_precond",
                                  0 if calibrator else 8))
    destr_sec = ini.get("Destriper", {})
    precond, coarse_block, pair_batch, mg, kernels, noise_weight = \
        parse_destriper_section(destr_sec, coarse_block)
    # CG solve checkpointing (docs/OPERATIONS.md §11): validated by
    # parse_destriper_section above, consumed here (its return tuple is
    # pinned) — 0 = off
    checkpoint_every = int(destr_sec.get("checkpoint_every", 0) or 0)
    # seen-pixel compaction ([Pixelization] compact : auto|true|false;
    # docs/OPERATIONS.md §3): auto = HEALPix compacted (the survey
    # regime), WCS dense. Compacted, every device map vector is
    # coverage-sized and the writers scatter to the sky at write time.
    # Validated HERE, at config load — a typo'd knob must fail before
    # the campaign-scale ingest starts (the [Destriper] section's rule)
    compact = str(pixel.get("compact", "auto")).strip().lower()
    if compact not in ("auto", "true", "false"):
        raise ValueError(f"[Pixelization] compact must be "
                         f"auto|true|false, got {compact!r}")
    # [Precision] (docs/OPERATIONS.md §15): bf16 TOD streaming +
    # compensated CG dots. coerce raises on a typo'd knob — the same
    # fail-at-config-load contract as [Destriper]/[Resilience] above
    from comapreduce_tpu.ops.precision import PrecisionPolicy

    prec = PrecisionPolicy.coerce(dict(ini.get("Precision", {})) or None)
    if prec.tod_dtype == "bf16" and nside is not None \
            and compact == "false":
        # the one combination that can never pay for itself: a dense
        # HEALPix map vector (12*nside^2 per band) dominates device
        # memory, so halving TOD bytes buys ~nothing while the solve
        # still eats the bf16 rounding. Refuse at config load rather
        # than rounding a campaign for no memory win.
        raise ValueError(
            "[Precision] tod_dtype = bf16 with [Pixelization] "
            "compact = false on a HEALPix grid: dense map vectors "
            "dominate memory, so narrowed TOD buys nothing here — "
            "set compact : auto/true, or tod_dtype : f32")
    # streaming ingest (docs/ingest.md): `[Inputs] prefetch : N` reads
    # ahead on a background thread; `cache_mb : M` caches decoded files
    # so every band after the first skips the HDF5 decode entirely
    from comapreduce_tpu.ingest import IngestConfig

    ingest_cfg = IngestConfig.from_mapping(inputs)  # normalises knobs
    prefetch = ingest_cfg.prefetch
    cache = ingest_cfg.make_cache()
    if ingest_cfg.compile_cache_dir:
        # persistent XLA compile cache (docs/OPERATIONS.md §9): repeat
        # destriper runs (new bands, reruns after quarantine lifts)
        # skip the CG program compiles entirely
        from comapreduce_tpu.pipeline.campaign import enable_compile_cache

        enable_compile_cache(ingest_cfg.compile_cache_dir)

    # resilience layer (docs/OPERATIONS.md §7): `[Resilience]` section
    # tunes the quarantine ledger / retry policy / chaos injection; ONE
    # runtime (one ledger) is shared across every band's read
    from comapreduce_tpu.resilience import ResilienceConfig

    # coerce, not from_mapping: a typo'd knob in the dedicated section
    # must raise, not silently run with the default; campaign surface,
    # so elastic claiming defaults ON (lease_ttl_s = 0 opts out)
    res_cfg = ResilienceConfig.coerce_campaign(
        dict(ini.get("Resilience", {})))
    if retry_quarantined:
        import dataclasses

        res_cfg = dataclasses.replace(res_cfg, retry_quarantined=True)
    # run state (heartbeats, leases, queue manifest, solver snapshots)
    # routes under `[Inputs] log_dir`, default <output_dir>/logs — same
    # layout as the Runner's (docs/OPERATIONS.md §11)
    state_dir = str(inputs.get("log_dir", "") or
                    os.path.join(out_dir, "logs"))
    os.makedirs(state_dir, exist_ok=True)
    # [Telemetry] (docs/OPERATIONS.md §13): per-rank event streams in
    # the same state dir as the leases/heartbeats — the CG-chunk spans
    # of solve_band_checkpointed merge with any reduction campaign's
    from comapreduce_tpu.telemetry import TELEMETRY, TelemetryConfig
    tcfg = TelemetryConfig.coerce(dict(ini.get("Telemetry", {})) or None)
    if tcfg.enabled and not TELEMETRY.enabled:
        TELEMETRY.configure(state_dir, rank=rank, flush_s=tcfg.flush_s,
                            jax_profiler=tcfg.jax_profiler)
    resilience = res_cfg.make_runtime(out_dir, rank=rank,
                                      n_ranks=n_ranks,
                                      state_dir=state_dir)
    live = None
    if live_port is not None and rank == 0:
        # one sidecar per campaign (rank 0): the plane reads every
        # rank's state off disk (docs/OPERATIONS.md §16)
        from comapreduce_tpu.telemetry.live import LiveServer

        live = LiveServer(state_dir, port=live_port,
                          stale_s=res_cfg.lease_ttl_s or 60.0,
                          n_ranks=n_ranks).start()
        print(f"live plane: http://{live.host}:{live.port}/metrics")
    # [Slo] exclude_flagged (docs/OPERATIONS.md §16, default OFF): drop
    # files whose latest quality record violated an SLO rule, the same
    # way quarantined files drop out — the reduction campaign ledgered
    # the evidence, this is the one knob that acts on it
    from comapreduce_tpu.telemetry.quality import (SloConfig,
                                                   flagged_files)

    slo_cfg = SloConfig.coerce(dict(ini.get("Slo", {})) or None)
    if slo_cfg.exclude_flagged:
        bad = flagged_files(state_dir)
        kept = [f for f in filelist
                if os.path.basename(f) not in bad]
        if len(kept) < len(filelist):
            logger.warning(
                "[Slo] exclude_flagged: dropping %d of %d file(s) "
                "with flagged quality records",
                len(filelist) - len(kept), len(filelist))
        filelist = kept
    # [Tuning] (docs/OPERATIONS.md §21, default OFF): the shape-bucket
    # autotuner's winners cache. Enabled, every auto-sized knob
    # downstream — build_pointing_plan's pair_batch, the stage HBM
    # planner's feed_batch, and the solver policy's mg_block — consults
    # measured winners from <log_dir>/tuning.jsonl; device_hbm_mb
    # declares accelerator memory for backends that cannot report it.
    # Absent section = byte-identical untuned pipeline.
    from comapreduce_tpu.tuning import TUNING, TuningConfig, \
        solver_bucket

    tuning_cfg = TuningConfig.coerce(dict(ini.get("Tuning", {}))
                                     or None)
    if tuning_cfg.enabled:
        TUNING.configure(state_dir, tuning_cfg)
        win = TUNING.winner("solver", solver_bucket(offset_length))
        if win:
            # apply measured destriper winners by re-parsing an
            # overridden copy of [Destriper] (the solver_policy
            # discipline below) — and only where the operator left the
            # knob to auto: an explicit config value always wins over
            # a measurement
            destr_tuned = dict(destr_sec)
            applied = []
            if mg is not None:
                for knob, val in (("mg_block", win.get("mg_block")),
                                  ("mg_smooth",
                                   win.get("mg_smooth"))):
                    if val and knob not in destr_sec:
                        destr_tuned[knob] = int(val)
                        applied.append(f"{knob}={int(val)}")
            if win.get("kernels") and "kernels" not in destr_sec:
                destr_tuned["kernels"] = str(win["kernels"])
                applied.append(f"kernels={win['kernels']}")
            if applied:
                logger.warning("[Tuning] applying measured winners "
                               "for bucket L=%d: %s", offset_length,
                               ", ".join(applied))
                precond, coarse_block, pair_batch, mg, kernels, \
                    noise_weight = parse_destriper_section(
                        destr_tuned,
                        int(inputs.get("coarse_precond",
                                       0 if calibrator else 8)))
    # [Control] solver_policy (docs/OPERATIONS.md §19, default OFF):
    # re-pick preconditioner/mg_block/pair_batch from evidence — this
    # state dir's solver traces, the run-registry iteration delta, and
    # the XLA program cost model — instead of trusting the static
    # [Destriper] knobs for every shape the campaign will see. Every
    # override is an auditable control.decision event; no evidence
    # leaves the static config byte-for-byte. Rung evidence is folded
    # PER SHAPE BUCKET (ISSUE 20): only solves stamped with this run's
    # offset-length bucket argue its rungs.
    from comapreduce_tpu.control.config import ControlConfig

    control_cfg = ControlConfig.coerce(dict(ini.get("Control", {}))
                                       or None)
    if control_cfg.solver_policy:
        from comapreduce_tpu.control.policy import choose_solver
        from comapreduce_tpu.telemetry.registry import \
            default_registry_path

        # the effective rung the decisions are measured against (the
        # parse collapses twolevel/multigrid into flags)
        rung = ("multigrid" if mg is not None
                else "twolevel" if coarse_block > 0
                else precond)
        choice = choose_solver(
            state_dir,
            static={"preconditioner": rung,
                    "mg_block": mg["block"] if mg else None,
                    "pair_batch": pair_batch,
                    "offset_length": offset_length},
            registry_path=default_registry_path(),
            bucket=f"L={offset_length}")
        for reason in choice.get("reasons", ()):
            logger.warning("[Control] solver_policy: %s", reason)
        overrides = {k: v for k, v in choice.items() if k != "reasons"}
        if overrides:
            # apply by re-parsing an overridden copy of [Destriper] so
            # every existing knob validation (mg ranges, coarse_block
            # gating) governs the policy's picks too
            destr_over = dict(destr_sec)
            new_rung = str(overrides.get("preconditioner", rung))
            destr_over["preconditioner"] = new_rung
            if new_rung != "twolevel":
                destr_over.pop("coarse_block", None)
            if new_rung != "multigrid":
                for k in ("mg_levels", "mg_smooth", "mg_block"):
                    destr_over.pop(k, None)
            elif "mg_block" in overrides:
                destr_over["mg_block"] = int(overrides["mg_block"])
            if "pair_batch" in overrides:
                destr_over["pair_batch"] = int(overrides["pair_batch"])
            precond, coarse_block, pair_batch, mg, kernels, \
                noise_weight = parse_destriper_section(
                    destr_over, int(inputs.get("coarse_precond",
                                               0 if calibrator else 8)))
    writeback = None
    if ingest_cfg.writeback >= 1:
        # async map writeback (docs/OPERATIONS.md §9): band N+1's CG
        # solve overlaps band N's FITS write on the background writer;
        # the flush barrier below surfaces any write error before exit
        from comapreduce_tpu.data.writeback import Writeback

        writeback = Writeback(depth=ingest_cfg.writeback,
                              watchdog=resilience.watchdog,
                              chaos=resilience.chaos,
                              name="map-writeback")
    if resilience.heartbeat is not None:
        # per-rank liveness for the whole mapping run (read by sibling
        # ranks' straggler barriers and tools/watchdog_report.py)
        resilience.heartbeat.start()
    sched = None
    if res_cfg.lease_ttl_s > 0:
        # elastic campaign (docs/OPERATIONS.md §11): claim this run's
        # file set under heartbeat-fenced leases up front — a dead
        # rank's expired leases are stolen here, a rank joining
        # mid-campaign simply starts claiming — then destripe the
        # claimed set and commit the leases only after the maps flush.
        # Sorted: the per-band reads concatenate in filelist order, so
        # the map over a stolen-and-redone set is byte-identical to a
        # clean run over the same files.
        from comapreduce_tpu.pipeline.scheduler import Scheduler

        # leases live in a destriper-owned SUBDIRECTORY: the reduction
        # campaign's leases in state_dir share the same basenames, and
        # a server tailing state_dir for committed Level-2 units must
        # never mistake a destriper commit for a reduction commit (and
        # the destriper must never see the reduction's done leases as
        # its own finished work). Heartbeats stay in state_dir — rank
        # liveness is one signal for the whole run
        sched = Scheduler(list(filelist),
                          os.path.join(state_dir, "destriper"),
                          heartbeat_dir=state_dir, rank=rank,
                          n_ranks=n_ranks,
                          lease_ttl_s=res_cfg.lease_ttl_s,
                          steal_after_s=res_cfg.steal_after_s,
                          ledger=resilience.ledger,
                          chaos=resilience.chaos,
                          heartbeat=resilience.heartbeat)
        filelist = sorted(sched.claim_iter())
    elif n_ranks > 1:
        if resilience.straggler_timeout_s > 0 \
                and resilience.heartbeat is not None:
            from comapreduce_tpu.parallel.multihost import \
                straggler_barrier

            # advisory only on the static-shard path: dead ranks are
            # named in the log; their shards wait for the next launch
            # (elastic claiming, the default, finishes them this run)
            straggler_barrier(
                state_dir, rank, n_ranks,
                timeout_s=resilience.straggler_timeout_s,
                heartbeat=resilience.heartbeat)
        filelist = filelist[rank::n_ranks]

    quality = None
    if noise_weight:
        # [Destriper] noise_weight = banded: the measured per-(file,
        # feed, band) noise fits come from the quality ledger in the
        # SAME state dir the reduction campaign wrote (latest-wins,
        # seal-checked). An empty/absent ledger is not an error — every
        # group then falls back to white, ledgered per file downstream.
        from comapreduce_tpu.telemetry.quality import read_quality

        quality = read_quality(state_dir)
        if not quality:
            logger.warning(
                "noise_weight=banded: no quality records under %s — "
                "all groups will keep white weighting", state_dir)
    if checkpoint_every > 0 and (sharded or use_ground):
        # solve_band has no x0 warm start on these paths — a "resumed"
        # chunk would restart cold every time and only pay snapshot I/O
        logger.warning(
            "[Destriper] checkpoint_every=%d disabled: the "
            "sharded/ground solve paths have no warm-start resume",
            checkpoint_every)
        checkpoint_every = 0
    # shared-pointing bands solve as ONE multi-RHS CG (joint one-hot
    # binning per iteration); ground solves keep their own path.
    # `[Inputs] joint : false` forces per-band solves (measurement
    # escape hatch until the on-chip joint-vs-serial numbers land)
    use_joint = bool(inputs.get("joint", True))
    if checkpoint_every > 0 and use_joint and len(bands) > 1:
        # snapshots are per-band (one CG state each); the multi-RHS
        # joint program solves all bands inside one jit and cannot
        # checkpoint per band — trade the MXU batching for resumability
        logger.info("checkpoint_every=%d: per-band checkpointed solves "
                    "(joint multi-RHS path disabled for this run)",
                    checkpoint_every)
        use_joint = False
    joint_datas = joint_results = None
    if use_joint and len(bands) > 1 and not use_ground:
        joint_datas, joint_results = make_band_maps_joint(
            filelist, bands, wcs=wcs, nside=nside, galactic=galactic,
            offset_length=offset_length, n_iter=n_iter,
            threshold=threshold, use_calibration=use_cal,
            sharded=sharded, tod_variant=tod_variant,
            coarse_block=coarse_block, prefetch=prefetch, cache=cache,
            resilience=resilience, watchdog=resilience.watchdog,
            precond=precond, pair_batch=pair_batch, mg=mg,
            compact=compact, kernels=kernels,
            tod_dtype=prec.tod_dtype, cg_dot=prec.cg_dot,
            noise_weight=noise_weight, quality=quality)
        if joint_results is None:
            print("bands read different sample sets; falling back to "
                  "per-band solves (reusing the reads)")

    for i, band in enumerate(bands):
        if joint_results is not None:
            data, result = joint_datas[i], joint_results[i]
        elif joint_datas is not None:
            data = joint_datas[i]
            result = solve_band(data, offset_length=offset_length,
                                n_iter=n_iter, threshold=threshold,
                                sharded=sharded,
                                coarse_block=coarse_block,
                                watchdog=resilience.watchdog,
                                unit=f"band{band}", precond=precond,
                                pair_batch=pair_batch, mg=mg,
                                kernels=kernels, cg_dot=prec.cg_dot,
                                noise_weight=noise_weight,
                                quality=quality, band=band)
        elif checkpoint_every > 0:
            # same read as make_band_map, solve split into durable
            # checkpoint/resume chunks — a relaunch mid-CG pays only
            # the remaining iterations (docs/OPERATIONS.md §11)
            data = read_comap_data(
                filelist, band=band, wcs=wcs, nside=nside,
                galactic=galactic, offset_length=offset_length,
                use_calibration=use_cal, medfilt_window=400,
                tod_variant=tod_variant, prefetch=prefetch,
                cache=cache, resilience=resilience, compact=compact,
                tod_dtype=prec.tod_dtype)
            ckpt = os.path.join(
                state_dir,
                f"solver.{prefix}.band{band}.rank{rank}.npz")
            result = solve_band_checkpointed(
                data, ckpt, checkpoint_every,
                offset_length=offset_length, n_iter=n_iter,
                threshold=threshold, watchdog=resilience.watchdog,
                unit=f"band{band}", coarse_block=coarse_block,
                precond=precond, pair_batch=pair_batch, mg=mg,
                kernels=kernels, cg_dot=prec.cg_dot,
                noise_weight=noise_weight, quality=quality, band=band)
        else:
            data, result = make_band_map(
                filelist, band, wcs=wcs, nside=nside, galactic=galactic,
                offset_length=offset_length, n_iter=n_iter,
                threshold=threshold, use_ground=use_ground,
                use_calibration=use_cal, sharded=sharded,
                tod_variant=tod_variant, coarse_block=coarse_block,
                prefetch=prefetch, cache=cache, resilience=resilience,
                precond=precond, pair_batch=pair_batch, mg=mg,
                compact=compact, kernels=kernels,
                tod_dtype=prec.tod_dtype, cg_dot=prec.cg_dot,
                noise_weight=noise_weight, quality=quality)
        tag = f"_rank{rank}" if n_ranks > 1 else ""
        path = os.path.join(out_dir, f"{prefix}_band{band}{tag}.fits")
        if writeback is None:
            write_band_map(path, data, result)
        else:
            writeback.submit(path, band_map_writer(path, data, result))
        print(f"band {band}: {len(data.files)} files, "
              f"{data.tod.size} samples, {int(result.n_iter)} CG iters, "
              f"residual {float(result.residual):.2e} -> {path}")
        if float(result.residual) > threshold:
            # an unconverged solve leaves real large-scale stripes in
            # the map (measured: ~1.7x the converged map error) — say so
            # instead of letting the residual line scroll past
            logger.warning(
                "band %d did NOT reach threshold %.0e (residual %.2e "
                "after %d iterations)%s", band, threshold,
                float(result.residual), int(result.n_iter),
                " — coarse_precond active: if a 'Jacobi only' fallback "
                "warning appeared above it did not apply; otherwise "
                "raise niter (or the coarse block size)"
                if coarse_block
                else " — consider [Inputs] coarse_precond : 8 "
                "(two-level preconditioner; docs/OPERATIONS.md §3)")
    if writeback is not None:
        # the exit barrier: every queued map committed (or this run
        # fails loudly) before the CLI reports success
        try:
            writeback.flush()
        finally:
            writeback.close()
    if sched is not None:
        # commit only AFTER the maps are durably flushed: a lease
        # committed against an unwritten map would let a crash between
        # solve and write lose the files forever (no survivor would
        # re-claim a "done" lease)
        for f in filelist:
            if not sched.commit(f):
                logger.warning(
                    "lease commit fence-rejected for %s: this rank's "
                    "lease was stolen (stale heartbeat?) and the file "
                    "redone elsewhere; its partial products here are "
                    "superseded", f)
        logger.info("elastic campaign rank %d: %s", rank, sched.stats)
        leftover = sched.release_held()
        if leftover:
            logger.warning("released %d uncommitted lease(s)", leftover)
    if resilience.ledger is not None and resilience.ledger.entries:
        print(f"quarantine ledger {resilience.ledger.path}: "
              f"{resilience.ledger.summary()}")
    if resilience.heartbeat is not None:
        resilience.heartbeat.stop(final_stage="run_destriper.done")
    if live is not None:
        live.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
