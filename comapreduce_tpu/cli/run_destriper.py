"""Destriping map-maker driver: ``python -m comapreduce_tpu.cli.
run_destriper parameters.ini`` (reference ``MapMaking/run_destriper.py``).

INI layout (legacy ``ParserClass`` syntax, ``MapMaking/parameters.ini``)::

    [Inputs]
    filelist : filelist.txt
    output_dir : maps
    prefix : co2
    bands : 0, 1, 2, 3
    offset_length : 50
    niter : 100
    threshold : 1e-6
    calibration : true

    [Pixelization]
    type : wcs            # or healpix
    crval : 170.0, 52.0
    cdelt : 0.01666, 0.01666
    shape : 480, 480
    nside : 4096          # healpix only
    galactic : false

Calibrator filelists get the reference's overrides (offset 250,
threshold 1, ``run_destriper.py:142-144``). Maps are written per band:
FITS image (WCS) or partial-sky HEALPix FITS.
"""

from __future__ import annotations

import os
import sys

import numpy as np

from comapreduce_tpu.mapmaking.destriper import destripe_jit
from comapreduce_tpu.mapmaking.fits_io import (write_fits_image,
                                               write_healpix_map)
from comapreduce_tpu.mapmaking.leveldata import read_comap_data
from comapreduce_tpu.mapmaking.wcs import WCS
from comapreduce_tpu.pipeline.config import IniConfig

__all__ = ["main", "make_band_map", "write_band_map"]


def _aslist(v):
    return v if isinstance(v, list) else [v]


def make_band_map(filenames, band, wcs=None, nside=None, galactic=False,
                  offset_length=50, n_iter=100, threshold=1e-6,
                  use_ground=False, use_calibration=True, sharded=False,
                  medfilt_window=400):
    """Read one band and destripe it. Returns (DestriperData, result)."""
    data = read_comap_data(filenames, band=band, wcs=wcs, nside=nside,
                           galactic=galactic, offset_length=offset_length,
                           use_calibration=use_calibration,
                           medfilt_window=medfilt_window)
    if sharded:
        import jax

        from comapreduce_tpu.parallel.sharded import destripe_sharded
        from jax.sharding import Mesh

        kw = dict(ground_ids=data.ground_ids, az=data.az,
                  n_groups=data.n_groups) if use_ground else {}
        # LOCAL devices: multi-host destriping is data parallel over
        # filelist shards (each process destripes its own files)
        mesh = Mesh(np.array(jax.local_devices()), ("time",))
        result = destripe_sharded(mesh, data.tod, data.pixels, data.weights,
                                  data.npix, offset_length=offset_length,
                                  n_iter=n_iter, threshold=threshold, **kw)
    else:
        n = (data.tod.size // offset_length) * offset_length
        kw = dict(ground_ids=data.ground_ids[:n], az=data.az[:n],
                  n_groups=data.n_groups) if use_ground else {}
        result = destripe_jit(data.tod[:n], data.pixels[:n],
                              data.weights[:n], data.npix,
                              offset_length=offset_length, n_iter=n_iter,
                              threshold=threshold, **kw)
    return data, result


def write_band_map(path, data, result):
    """Write destriped/naive/weight/hit maps (``run_destriper.py:19-77``)."""
    maps = {
        "DESTRIPED": np.asarray(result.destriped_map),
        "NAIVE": np.asarray(result.naive_map),
        "WEIGHTS": np.asarray(result.weight_map),
        "HITS": np.asarray(result.hit_map),
    }
    if data.wcs is not None:
        shaped = {k: v.reshape(data.wcs.ny, data.wcs.nx)
                  for k, v in maps.items()}
        write_fits_image(path, shaped,
                         header=dict(data.wcs.header_cards()))
    else:
        write_healpix_map(path, maps, data.sky_pixels, data.nside)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m comapreduce_tpu.cli.run_destriper "
              "parameters.ini", file=sys.stderr)
        return 2
    from comapreduce_tpu.parallel.multihost import rank_info

    ini = IniConfig(argv[0])
    inputs = ini.get("Inputs", {})
    pixel = ini.get("Pixelization", {})
    with open(inputs["filelist"]) as f:
        filelist = [ln.strip() for ln in f
                    if ln.strip() and not ln.startswith("#")]
    # multi-process launch: initialise the distributed runtime and take
    # this process's round-robin filelist shard (same split as the
    # Runner; the reference instead slices contiguous blocks,
    # run_destriper.py:131-138); each process writes its own partial maps
    rank, n_ranks = rank_info()
    if n_ranks > 1:
        filelist = filelist[rank::n_ranks]
    out_dir = inputs.get("output_dir", ".")
    os.makedirs(out_dir, exist_ok=True)
    prefix = inputs.get("prefix", "map")
    bands = [int(b) for b in _aslist(inputs.get("bands", [0, 1, 2, 3]))]
    offset_length = int(inputs.get("offset_length", 50))
    n_iter = int(inputs.get("niter", 100))
    threshold = float(inputs.get("threshold", 1e-6))
    calibrator = bool(inputs.get("calibrator", False))
    if calibrator:  # reference overrides, run_destriper.py:142-144
        offset_length = int(inputs.get("offset_length", 250))
        threshold = 1.0

    wcs = nside = None
    if str(pixel.get("type", "wcs")).lower() == "healpix":
        nside = int(pixel.get("nside", 512))
    else:
        crval = [float(x) for x in _aslist(pixel.get("crval", [0.0, 0.0]))]
        cdelt = [float(x) for x in _aslist(pixel.get(
            "cdelt", [1.0 / 60.0, 1.0 / 60.0]))]
        shape = [int(x) for x in _aslist(pixel.get("shape", [480, 480]))]
        wcs = WCS.from_field(tuple(crval), tuple(cdelt), tuple(shape))

    for band in bands:
        data, result = make_band_map(
            filelist, band, wcs=wcs, nside=nside,
            galactic=bool(pixel.get("galactic", False)),
            offset_length=offset_length, n_iter=n_iter, threshold=threshold,
            use_ground=bool(inputs.get("ground", False)),
            use_calibration=bool(inputs.get("calibration", True)),
            sharded=bool(inputs.get("sharded", False)))
        tag = f"_rank{rank}" if n_ranks > 1 else ""
        path = os.path.join(out_dir, f"{prefix}_band{band}{tag}.fits")
        write_band_map(path, data, result)
        print(f"band {band}: {len(data.files)} files, "
              f"{data.tod.size} samples, {int(result.n_iter)} CG iters, "
              f"residual {float(result.residual):.2e} -> {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
