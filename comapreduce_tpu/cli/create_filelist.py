"""Filelist curation driver: ``python -m comapreduce_tpu.cli.
create_filelist [options] <level2 files or @filelist>``.

The reference's ``scripts/io/createFileList.py`` +
``MapMaking/CreateFilelist.py`` role: split Level-2 files into good /
rejected lists by the white-noise cut (default σ_f < 4 mK,
``CreateFilelist.py:17``), optionally filtered to one source via the
observation database.
"""

from __future__ import annotations

import argparse
import os

from comapreduce_tpu.mapmaking.filelist import create_filelist, write_filelist
from comapreduce_tpu.pipeline.config import read_filelist

__all__ = ["main"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="create_filelist",
        description="Split Level-2 files into good/rejected filelists by "
                    "the white-noise cut.")
    ap.add_argument("files", nargs="+",
                    help="Level-2 paths, or @listfile to read a filelist")
    ap.add_argument("--noise-cut-mk", type=float, default=4.0,
                    help="white-noise cut in mK (default 4.0)")
    ap.add_argument("--band", type=int, default=0,
                    help="band whose noise level is tested (default 0)")
    ap.add_argument("--source", default="",
                    help="keep only observations of this source "
                         "(obs database query)")
    ap.add_argument("--database", default="",
                    help="obs database for --source (required with it)")
    ap.add_argument("--output", default="filelist.txt")
    ap.add_argument("--rejected", default="rejected.txt")
    ap.add_argument("--prefetch", type=int, default=0,
                    help="read-ahead queue depth (0 = serial reads; "
                         "see docs/ingest.md)")
    args = ap.parse_args(argv)
    if args.band < 0:
        ap.error("--band must be >= 0")

    files: list[str] = []
    for f in args.files:
        files.extend(read_filelist(f[1:]) if f.startswith("@") else [f])

    if args.source:
        if not args.database:
            ap.error("--source requires --database")
        from comapreduce_tpu.database import ObsDatabase

        # the database stores abspath-normalized level2_path entries
        keep = {os.path.abspath(p)
                for p in ObsDatabase(args.database).query_source(
                    args.source)}
        files = [f for f in files if os.path.abspath(f) in keep]

    good, rejected = create_filelist(files, band=args.band,
                                     sigma_cut_mk=args.noise_cut_mk,
                                     prefetch=max(args.prefetch, 0))
    write_filelist(args.output, good)
    write_filelist(args.rejected, rejected)
    print(f"{len(good)} good -> {args.output}; "
          f"{len(rejected)} rejected -> {args.rejected}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
