"""Co-add per-rank partial maps into one map file.

Usage::

    python -m comapreduce_tpu.cli.coadd_maps OUTPUT.fits RANK1.fits ...
    python -m comapreduce_tpu.cli.coadd_maps OUTPUT.fits --glob \
        'maps/co2_band0_rank*.fits'
    python -m comapreduce_tpu.cli.coadd_maps OUTPUT.fits \
        serving/epochs/epoch-000004 other-field/epochs

An input that is a serving EPOCH (an ``epoch-NNNNNN`` dir, a
``manifest.json``, or an epochs root — the root resolves ``current``)
expands to the map products its manifest lists: "co-add everything in
epoch N" without globbing, and immune to a concurrent publish.

Role parity: the reference's in-MPI map Allreduce
(``MapMaking/Destriper.py:61-75``) — here an offline inverse-variance
co-add over the rank files a sharded ``run_destriper`` launch writes.
"""

from __future__ import annotations

import sys

__all__ = ["main"]


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    usage = ("usage: python -m comapreduce_tpu.cli.coadd_maps "
             "OUTPUT.fits (RANK.fits ... | --glob PATTERN)")
    if argv and argv[0] in ("-h", "--help"):
        print(usage)
        return 0
    if len(argv) < 2:
        print(usage, file=sys.stderr)
        return 2
    output, rest = argv[0], argv[1:]
    if rest[0] == "--glob":
        import glob as _glob

        if len(rest) != 2:
            print(usage, file=sys.stderr)
            return 2
        inputs = sorted(_glob.glob(rest[1]))
    else:
        inputs = rest
    if not inputs:
        print("coadd_maps: no input files", file=sys.stderr)
        return 1
    from comapreduce_tpu.mapmaking.coadd import coadd_fits_files

    out = coadd_fits_files(inputs, output)
    hits = out.get("HITS")
    print(f"{output}: {len(inputs)} rank maps"
          + (f", {int((hits > 0).sum())} hit pixels"
             if hits is not None else ""))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
