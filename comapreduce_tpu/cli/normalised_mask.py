"""Build fleet date-range channel masks in the observation database.

Role parity: ``COMAPDatabase/assign_normalised_mask.py`` (channel masks
applied uniformly over operator-defined date ranges, consumed by the
next reduction level through the Tsys flags). Usage::

    python -m comapreduce_tpu.cli.normalised_mask DB.hd5 CUTS.dat \\
        [--filelist LEVEL2_LIST.txt] [--threshold 0.25] \\
        [--feed-cuts N:FILE ...]

``CUTS.dat``: two columns ``start_obsid end_obsid`` (inclusive),
``#`` comments. ``--filelist`` harvests per-channel evidence from the
named Level-2 files first (otherwise the evidence already in the
database is reused). ``--feed-cuts N:FILE`` overrides the global cuts
for feed index N (the reference's per-feed ``datecuts/FeedNN_cuts.dat``).
"""

from __future__ import annotations

import sys

__all__ = ["main"]


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    usage = ("usage: python -m comapreduce_tpu.cli.normalised_mask "
             "DB.hd5 CUTS.dat [--filelist L2LIST] [--threshold 0.25] "
             "[--feed-cuts N:FILE ...]")
    if argv and argv[0] in ("-h", "--help"):
        print(usage)
        return 0
    if len(argv) < 2:
        print(usage, file=sys.stderr)
        return 2
    from comapreduce_tpu.database.normalised_mask import (
        build_normalised_masks, harvest_channel_flags, read_date_cuts)
    from comapreduce_tpu.database.obsdb import ObsDatabase
    from comapreduce_tpu.pipeline.config import read_filelist

    db_path, cuts_path = argv[0], argv[1]
    threshold = 0.25
    filelist = None
    feed_cuts = {}
    rest = argv[2:]
    i = 0
    while i < len(rest):
        if rest[i] == "--threshold" and i + 1 < len(rest):
            threshold = float(rest[i + 1])
            i += 2
        elif rest[i] == "--filelist" and i + 1 < len(rest):
            filelist = rest[i + 1]
            i += 2
        elif rest[i] == "--feed-cuts" and i + 1 < len(rest):
            feed, path = rest[i + 1].split(":", 1)
            feed_cuts[int(feed)] = read_date_cuts(path)
            i += 2
        else:
            print(f"unknown argument {rest[i]!r}\n{usage}",
                  file=sys.stderr)
            return 2

    db = ObsDatabase(db_path)
    if filelist is not None:
        n = harvest_channel_flags(db, read_filelist(filelist))
        print(f"harvested channel evidence from {n} Level-2 files")
    cuts = read_date_cuts(cuts_path)
    n = build_normalised_masks(db, cuts, feed_cuts=feed_cuts or None,
                               threshold=threshold)
    db.save()
    print(f"{db_path}: normalised masks for {n} observations")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
