"""Merge per-rank gains timeline shards into one fleet product.

Role parity: ``Summary/CalibrationFactors.py:19-165`` builds the single
fleet-wide ``gains.hd5``; a multi-process ``Level2Timelines`` run here
leaves one ``{base}_rank{r}{ext}`` shard per rank instead (disjoint
filelist shards — ``pipeline/stages.py``). Usage::

    python -m comapreduce_tpu.cli.merge_gains gains.hd5 [shard1 shard2 ...]

With no shard arguments, ``{base}_rank*{ext}`` next to the output are
discovered automatically.
"""

from __future__ import annotations

import sys

__all__ = ["main"]


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    usage = ("usage: python -m comapreduce_tpu.cli.merge_gains "
             "OUTPUT.hd5 [RANK_SHARD.hd5 ...]")
    if argv and argv[0] in ("-h", "--help"):
        print(usage)
        return 0
    if not argv:
        print(usage, file=sys.stderr)
        return 2
    from comapreduce_tpu.summary import merge_gains

    output, inputs = argv[0], (argv[1:] or None)
    try:
        merged = merge_gains(output, inputs)
    except FileNotFoundError as exc:
        print(str(exc), file=sys.stderr)
        return 1
    print(f"{output}: {len(merged['obsid'])} observations")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
