"""Synthetic campaign driver: ``python -m comapreduce_tpu.cli.
run_synthetic <command>`` (docs/OPERATIONS.md §18).

Three commands over the ISSUE 16 synthetic engine
(``comapreduce_tpu/synthetic/``)::

    # stream a scenario's Level-1 files to disk (+ its ground truth)
    run_synthetic generate scenario.toml --out-dir level1/

    # end-to-end transfer-function closure: generate -> inject ->
    # reduce -> destripe -> map -> compare vs the injected truth
    run_synthetic transfer --workdir xfer/ --seed 0 [--check]

    # the scale drill: a synth:// campaign through elastic ranks +
    # map server + tile tier with a mid-run rank kill/rejoin
    run_synthetic drill --workdir drill/ --n-files 200

``generate`` writes byte-identical files for identical
``([scenario], seed)`` — regenerating a campaign is always safe.
``transfer`` writes the ``transfer.json`` artifact; with ``--check``
it also runs the machine-independent closure gate (non-zero exit on a
broken criterion — the same gate ``tools/check_perf.py`` wires into
CI). ``drill`` prints the evidence line ``tools/check_resilience.py
--synthetic-only`` gates.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

__all__ = ["main"]


def _cmd_generate(args) -> int:
    from comapreduce_tpu.synthetic.generator import (campaign_truth,
                                                     write_campaign)
    from comapreduce_tpu.synthetic.scenario import load_scenario

    cfg = load_scenario(args.scenario)
    paths = write_campaign(cfg, args.out_dir)
    truth_path = os.path.join(args.out_dir, "campaign_truth.json")
    with open(truth_path, "w", encoding="utf-8") as f:
        json.dump(campaign_truth(cfg), f, indent=1, sort_keys=True)
    print(json.dumps({"scenario": cfg.name, "seed": cfg.seed,
                      "n_files": len(paths), "out_dir": args.out_dir,
                      "truth": truth_path}))
    return 0


def _cmd_transfer(args) -> int:
    from comapreduce_tpu.synthetic.transfer import (check_transfer,
                                                    run_transfer)

    artifact = run_transfer(args.workdir, seed=args.seed,
                            n_bins=args.n_bins)
    summary = {
        "artifact": os.path.join(args.workdir, "transfer.json"),
        "seed": args.seed,
        "map_gain": [b.get("map_gain") for b in artifact["bands"]],
        "low_k_transfer": [list(b.get("transfer", [])[:2])
                           for b in artifact["bands"]],
        "quality": artifact.get("quality"),
    }
    if args.check:
        try:
            check_transfer(artifact)
        except AssertionError as exc:
            print(json.dumps({"ok": False, "criterion": str(exc),
                              **summary}))
            return 1
        summary["ok"] = True
    print(json.dumps(summary))
    return 0


def _cmd_drill(args) -> int:
    from comapreduce_tpu.synthetic.loadgen import run_synthetic_drill

    try:
        evidence = run_synthetic_drill(args.workdir, seed=args.seed,
                                       n_files=args.n_files,
                                       ttl_s=args.ttl)
    except AssertionError as exc:
        print(json.dumps({"ok": False, "criterion": str(exc)}))
        return 1
    print(json.dumps({"ok": True, **evidence}))
    return 0


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(prog="run_synthetic",
                                 description=__doc__)
    sub = ap.add_subparsers(dest="command", required=True)

    g = sub.add_parser("generate",
                       help="stream a scenario's Level-1 files to disk")
    g.add_argument("scenario", help="[scenario] TOML path")
    g.add_argument("--out-dir", required=True)
    g.set_defaults(fn=_cmd_generate)

    t = sub.add_parser("transfer",
                       help="end-to-end transfer-function closure")
    t.add_argument("--workdir", required=True)
    t.add_argument("--seed", type=int, default=0)
    t.add_argument("--n-bins", type=int, default=6)
    t.add_argument("--check", action="store_true",
                   help="also run the closure gate (non-zero exit on "
                        "a broken criterion)")
    t.set_defaults(fn=_cmd_transfer)

    d = sub.add_parser("drill", help="the synthetic scale drill")
    d.add_argument("--workdir", required=True)
    d.add_argument("--seed", type=int, default=0)
    d.add_argument("--n-files", type=int, default=200)
    d.add_argument("--ttl", type=float, default=2.0,
                   help="lease TTL (s) for the elastic ranks")
    d.set_defaults(fn=_cmd_drill)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
