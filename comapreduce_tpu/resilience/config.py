"""Resilience configuration + the runtime bundle threaded through.

Mirrors :class:`~comapreduce_tpu.ingest.config.IngestConfig`: one value
object owning the knob names so the TOML ``[resilience]`` table, the
INI ``[Resilience]`` section and the CLI flags cannot drift apart, plus
:class:`Resilience` — the built runtime (ledger + retry policy + chaos
monkey) that ``Runner``/``read_comap_data`` actually consume.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, replace

from comapreduce_tpu.resilience.chaos import (ChaosMonkey,
                                              parse_inject_spec)
from comapreduce_tpu.resilience.heartbeat import Heartbeat
from comapreduce_tpu.resilience.ledger import QuarantineLedger
from comapreduce_tpu.resilience.retry import RetryPolicy
from comapreduce_tpu.resilience.watchdog import Watchdog, parse_deadlines

__all__ = ["ResilienceConfig", "Resilience", "DEFAULT_LEASE_TTL_S"]

#: campaign-surface default lease TTL (seconds): the config entry
#: points (``Runner.from_config`` / ``from_legacy_config``, the
#: destriper CLI) turn elastic claiming ON at this TTL when the config
#: does not mention ``lease_ttl_s`` itself (docs/OPERATIONS.md §11).
#: An explicit ``lease_ttl_s = 0`` opts back into static shards. The
#: DATACLASS default stays 0 so programmatic ``ResilienceConfig(...)``
#: construction keeps the static-shard behaviour it always had.
DEFAULT_LEASE_TTL_S = 60.0


@dataclass(frozen=True)
class ResilienceConfig:
    """Knobs for the resilience subsystem.

    quarantine:
        Ledger path. ``"auto"`` (default) puts ``quarantine.jsonl``
        next to the run's outputs; an explicit path is used verbatim;
        ``"off"``/``"none"``/empty disables the ledger (failures fall
        back to the plain ``BAD FILE`` log line).
    max_retries / retry_base_s / retry_max_s / retry_jitter:
        :class:`~comapreduce_tpu.resilience.retry.RetryPolicy` fields —
        bounded exponential backoff for transient (I/O-class) failures.
    retry_quarantined:
        Re-admit every currently-quarantined unit at startup (the
        ``--retry-quarantined`` CLI flag lands here). Each re-admission
        is itself a ledger event.
    inject / inject_seed:
        Chaos spec (``chaos.parse_inject_spec`` syntax) + seed. Empty
        spec = no injection (production default).
    deadlines:
        Watchdog spec, ``"name=soft/hard,*=soft/hard"`` in seconds
        (``watchdog.parse_deadlines`` syntax). Empty (default) watches
        nothing; operations with no entry (and no ``*``) are never
        deadline-cancelled. Typical production value:
        ``"ingest.read=60/300,*=120/1800"``.
    deadline_scale / deadline_min_s:
        Adaptive rule: once an operation has enough recorded durations
        (``Runner.timings`` + the watchdog's own history), each
        CONFIGURED deadline side grows to the measured estimate — hard
        to ``max(configured hard, p95 x deadline_scale)`` — so the
        config is a floor and adaptive budgets only ever extend it; a
        side the config left empty is never invented, and estimates
        below ``deadline_min_s`` are ignored (cache-hit histories must
        not drive budgets).
    hang_grace_s:
        Cancellation latency allowance on top of a hard deadline (the
        drill asserts cancels land within ``hard + grace``).
    heartbeat_s:
        Per-rank ``heartbeat.rank{r}.json`` ticker period (written into
        the run's output dir next to the quarantine ledger); 0
        disables. The ticker starts with the run (``Runner.run_tod`` /
        the destriper CLI), not at config build.
    straggler_timeout_s:
        Multi-host pre-shard barrier budget: how long a rank waits for
        every sibling's fresh heartbeat before declaring the laggards
        dead and entering degraded mode
        (``parallel.multihost.straggler_barrier``); 0 disables the
        barrier.
    lease_ttl_s:
        ELASTIC campaigns (``pipeline.scheduler``): > 0 replaces the
        static ``rank::n_ranks`` shard with lease-based claiming —
        each rank claims files under a heartbeat-fenced lease, and a
        lease whose owner's heartbeat is older than this TTL is
        stealable by any survivor. 0 (the dataclass default) keeps the
        static shard — but the campaign config ENTRY POINTS default to
        ``DEFAULT_LEASE_TTL_S`` when the config does not set this knob
        (:meth:`coerce_campaign`); write ``lease_ttl_s = 0`` to opt
        back into static shards. Requires ``heartbeat_s > 0`` (the TTL
        is judged against the owner's heartbeat file).
    steal_after_s:
        Minimum age of the lease FILE itself before it may be stolen
        (a freshly-claimed lease whose owner has not beaten yet must
        not be stolen instantly); 0 (default) = same as
        ``lease_ttl_s``.
    """

    quarantine: str = "auto"
    max_retries: int = 2
    retry_base_s: float = 0.5
    retry_max_s: float = 30.0
    retry_jitter: float = 0.25
    retry_quarantined: bool = False
    inject: str = ""
    inject_seed: int = 0
    deadlines: str = ""
    deadline_scale: float = 4.0
    deadline_min_s: float = 30.0
    hang_grace_s: float = 0.5
    heartbeat_s: float = 10.0
    straggler_timeout_s: float = 120.0
    lease_ttl_s: float = 0.0
    steal_after_s: float = 0.0

    def __post_init__(self):
        # normalise INI-coerced values (None from 'none'/'', bools,
        # numbers-as-strings) once, here — same contract as IngestConfig
        q = self.quarantine
        if q is None or str(q).strip().lower() in ("off", "none", "false",
                                                   ""):
            q = ""
        elif q is True or str(q).strip().lower() in ("auto", "true"):
            q = "auto"
        object.__setattr__(self, "quarantine", str(q))
        object.__setattr__(self, "max_retries",
                           max(int(self.max_retries or 0), 0))
        object.__setattr__(self, "retry_base_s",
                           max(float(self.retry_base_s or 0.0), 0.0))
        object.__setattr__(self, "retry_max_s",
                           max(float(self.retry_max_s or 0.0), 0.0))
        object.__setattr__(self, "retry_jitter",
                           max(float(self.retry_jitter or 0.0), 0.0))
        object.__setattr__(self, "retry_quarantined",
                           bool(self.retry_quarantined))
        # INI coercion splits a comma value into a LIST (the documented
        # multi-fault spec `inject : read_error:0.05,nan_burst:0.05`
        # arrives as ['read_error:0.05', 'nan_burst:0.05']) — rejoin it;
        # then parse eagerly so a typo'd spec fails at config load, not
        # mid-run
        inj = self.inject
        if isinstance(inj, (list, tuple)):
            inj = ",".join(str(v).strip() for v in inj)
        inj = str(inj or "")
        parse_inject_spec(inj)
        object.__setattr__(self, "inject", inj)
        object.__setattr__(self, "inject_seed",
                           int(self.inject_seed or 0))
        # deadlines: rejoin INI list-coercion like inject, parse eagerly
        # so a typo'd spec fails at config load, not mid-run
        dl = self.deadlines
        if isinstance(dl, (list, tuple)):
            dl = ",".join(str(v).strip() for v in dl)
        dl = str(dl or "")
        parse_deadlines(dl)
        object.__setattr__(self, "deadlines", dl)
        object.__setattr__(self, "deadline_scale",
                           max(float(self.deadline_scale or 0.0), 1.0))
        object.__setattr__(self, "deadline_min_s",
                           max(float(self.deadline_min_s or 0.0), 0.0))
        object.__setattr__(self, "hang_grace_s",
                           max(float(self.hang_grace_s or 0.0), 0.0))
        object.__setattr__(self, "heartbeat_s",
                           max(float(self.heartbeat_s or 0.0), 0.0))
        object.__setattr__(self, "straggler_timeout_s",
                           max(float(self.straggler_timeout_s or 0.0),
                               0.0))
        object.__setattr__(self, "lease_ttl_s",
                           max(float(self.lease_ttl_s or 0.0), 0.0))
        object.__setattr__(self, "steal_after_s",
                           max(float(self.steal_after_s or 0.0), 0.0))
        if self.lease_ttl_s > 0 and self.heartbeat_s <= 0:
            raise ValueError(
                "lease_ttl_s > 0 (elastic campaigns) requires "
                "heartbeat_s > 0: lease expiry is judged against the "
                "owner's heartbeat file")

    KNOBS = ("quarantine", "max_retries", "retry_base_s", "retry_max_s",
             "retry_jitter", "retry_quarantined", "inject", "inject_seed",
             "deadlines", "deadline_scale", "deadline_min_s",
             "hang_grace_s", "heartbeat_s", "straggler_timeout_s",
             "lease_ttl_s", "steal_after_s")

    @classmethod
    def from_mapping(cls, mapping) -> "ResilienceConfig":
        """Pick the resilience knobs out of a wider MIXED mapping (an
        ``[Inputs]``-style section holding other subsystems' keys too),
        ignoring unrelated keys. A dedicated ``[Resilience]``/TOML
        ``[resilience]`` section must go through :meth:`coerce`, which
        REJECTS unknown keys — a typo'd knob silently falling back to
        its default is exactly the failure a dedicated section can
        catch."""
        return cls(**{k: mapping[k] for k in cls.KNOBS if k in mapping})

    @classmethod
    def coerce(cls, value) -> "ResilienceConfig":
        """Build from None / dict / ResilienceConfig."""
        if value is None:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            known = {k: value[k] for k in cls.KNOBS if k in value}
            unknown = set(value) - set(known)
            if unknown:
                raise ValueError(
                    f"unknown resilience keys: {sorted(unknown)}")
            return cls(**known)
        raise TypeError(f"cannot build ResilienceConfig from {type(value)}")

    @classmethod
    def coerce_campaign(cls, value) -> "ResilienceConfig":
        """:meth:`coerce` plus the campaign-entry-point default:
        elastic claiming ON (``lease_ttl_s = DEFAULT_LEASE_TTL_S``)
        when the config mapping does not mention ``lease_ttl_s``.

        An explicit ``lease_ttl_s = 0`` keeps the static
        ``rank::n_ranks`` shard, and the default also stays off when
        heartbeats are disabled — lease expiry is judged against the
        owner's heartbeat file, so elastic claiming without heartbeats
        could never fence a dead rank. An already-built
        ``ResilienceConfig`` passes through untouched (programmatic
        construction chose its own value)."""
        mentioned = (isinstance(value, cls)
                     or (isinstance(value, dict)
                         and "lease_ttl_s" in value))
        cfg = cls.coerce(value)
        if not mentioned and cfg.lease_ttl_s <= 0 and cfg.heartbeat_s > 0:
            cfg = replace(cfg, lease_ttl_s=DEFAULT_LEASE_TTL_S)
        return cfg

    def ledger_path(self, output_dir: str = ".", rank: int = 0,
                    n_ranks: int = 1) -> str:
        """Resolved ledger path ('' when disabled).

        Multi-rank runs get per-rank auto paths: JSONL appends are only
        atomic single-writer (NFS interleaving would garble lines), and
        the round-robin filelist shard is stable across runs, so each
        rank owning its shard's failures in its own file keeps both the
        append and the resume-skip correct."""
        if not self.quarantine:
            return ""
        if self.quarantine == "auto":
            name = ("quarantine.jsonl" if n_ranks <= 1
                    else f"quarantine.rank{rank}.jsonl")
            return os.path.join(output_dir or ".", name)
        return self.quarantine

    def make_runtime(self, output_dir: str = ".", rank: int = 0,
                     n_ranks: int = 1,
                     state_dir: str = "") -> "Resilience":
        """Build the runtime bundle this config describes.

        ``state_dir`` is where run-state files (heartbeats, and the
        scheduler's leases/queue manifest) live; '' keeps them in
        ``output_dir`` (historic behaviour — the CLIs pass ``[Global]
        log_dir`` so science products and run state stay separate)."""
        import logging

        path = self.ledger_path(output_dir, rank=rank, n_ranks=n_ranks)
        if path and self.quarantine == "auto":
            # fold in every sibling auto ledger read-only: a run with a
            # DIFFERENT rank count than the one that recorded a failure
            # must still see it (writes stay single-file, single-writer)
            import glob as _glob

            siblings = sorted(_glob.glob(os.path.join(
                os.path.dirname(path) or ".", "quarantine*.jsonl")))
            ledger = QuarantineLedger(path, read_paths=tuple(siblings))
        elif path:
            ledger = QuarantineLedger(path)
        else:
            ledger = None
        retry = RetryPolicy(max_retries=self.max_retries,
                            base_s=self.retry_base_s,
                            max_s=self.retry_max_s,
                            jitter=self.retry_jitter,
                            seed=self.inject_seed)
        heartbeat = (Heartbeat(state_dir or output_dir or ".", rank=rank,
                               period_s=self.heartbeat_s)
                     if self.heartbeat_s > 0 else None)
        # the watchdog exists whenever deadlines are configured; with an
        # empty spec every name is unwatched and no supervisor threads
        # are ever spawned, so None keeps call sites one-branch cheap
        watchdog = (Watchdog(deadlines=parse_deadlines(self.deadlines),
                             ledger=ledger, scale=self.deadline_scale,
                             min_s=self.deadline_min_s,
                             grace_s=self.hang_grace_s,
                             heartbeat=heartbeat)
                    if self.deadlines else None)
        chaos = (ChaosMonkey(self.inject, seed=self.inject_seed)
                 if self.inject else None)
        if chaos is not None:
            # loud on purpose: injected faults go through the REAL
            # quarantine path (that is the drill's point), so running a
            # drill against a production ledger would durably skip
            # healthy files — point drills at a scratch output_dir
            logging.getLogger("comapreduce_tpu").warning(
                "chaos injection ACTIVE (inject=%r, seed=%d): injected "
                "failures will be ledgered and may QUARANTINE files in "
                "%s — use a scratch output dir for drills",
                self.inject, self.inject_seed, path or "<no ledger>")
        return Resilience(ledger=ledger, retry=retry, chaos=chaos,
                          retry_quarantined=self.retry_quarantined,
                          watchdog=watchdog, heartbeat=heartbeat,
                          straggler_timeout_s=self.straggler_timeout_s,
                          lease_ttl_s=self.lease_ttl_s,
                          steal_after_s=self.steal_after_s,
                          state_dir=state_dir or output_dir or ".")


@dataclass
class Resilience:
    """The built runtime bundle consumers thread through.

    Any field may be None (that capability is off); the helpers below
    keep the call sites free of ``if ... is not None`` noise.
    """

    ledger: QuarantineLedger | None = None
    retry: RetryPolicy | None = None
    chaos: ChaosMonkey | None = None
    retry_quarantined: bool = False
    watchdog: Watchdog | None = None
    heartbeat: Heartbeat | None = None
    straggler_timeout_s: float = 0.0
    # elastic campaigns (pipeline.scheduler): lease_ttl_s > 0 turns on
    # lease-based claiming; state_dir is where leases + queue.json live
    lease_ttl_s: float = 0.0
    steal_after_s: float = 0.0
    state_dir: str = ""
    _readmitted: set = field(default_factory=set)
    # quarantine snapshot, frozen at the first admit() of this runtime:
    # a file quarantined MID-run must not change which files the rest of
    # the same run covers (per-band destriper maps over one shared
    # runtime would otherwise cover different observation sets); the
    # next run's fresh runtime picks the new entries up
    _admit_snapshot: set | None = field(default=None, repr=False)

    def admit(self, filename: str) -> bool:
        """Quarantine gate for one file: True = process it.

        With ``retry_quarantined`` a quarantined file is re-admitted
        (ledger event, once per run) and processed; otherwise it is
        skipped cheaply — no read, no decode. The quarantined set is
        snapshotted at this runtime's first admit() call (see field
        comment)."""
        if self.ledger is None:
            return True
        if self._admit_snapshot is None:
            self._admit_snapshot = self.ledger.quarantined_files()
        if filename not in self._admit_snapshot:
            return True
        if self.retry_quarantined:
            if filename not in self._readmitted:
                self._readmitted.add(filename)
                self.ledger.readmit(filename)
            return True
        return False

    def record_failure(self, filename: str, error: BaseException,
                       stage: str, may_quarantine: bool = True,
                       **unit) -> None:
        """Ledger a failed unit. Classification and retry count come
        off the annotations ``retry_call`` leaves.

        Disposition triage: only failures that indict the FILE itself
        quarantine (skip on future runs) — exhausted-transient I/O
        errors, from a READ of that file, that are not mere lock
        contention. Everything else is ``rejected``: recorded for
        audit, re-attempted next run. A permanent error often encodes
        the CONFIG, not the data (a wrong ``tod_variant`` raises
        KeyError on every file); lock contention means another writer,
        not a bad file; a ``hang`` (a deadline-cancelled read) indicts
        the ENVIRONMENT — a stalled mount, a dying disk — so it lands
        ``rejected`` too, never durably skipped; and callers reporting
        failures from OUTSIDE the file's own read
        (``may_quarantine=False`` — e.g. a stage chain whose checkpoint
        WRITE hit a full output disk) must never durably skip the input
        over an environment problem.

        A ``corrupt`` failure (checksum-proven damage,
        :class:`~comapreduce_tpu.resilience.integrity.
        CorruptArtifactError`) gets its own first-class disposition
        regardless of ``may_quarantine``: the artifact's bytes are
        wrong no matter who reports it, the unit must be skipped until
        repaired, and the entry carries the digest evidence in the
        message."""
        if self.ledger is None:
            return
        from comapreduce_tpu.resilience.retry import (classify_error,
                                                      is_lock_error)

        failure_class = getattr(error, "_failure_class",
                                classify_error(error))
        if failure_class == "corrupt":
            self.ledger.record(
                filename, error=error, failure_class="corrupt",
                retries=getattr(error, "_retries", 0),
                disposition="corrupt", stage=stage, **unit)
            return
        quarantine = (may_quarantine and failure_class == "transient"
                      and not is_lock_error(error))
        self.ledger.record(
            filename, error=error,
            failure_class=failure_class,
            retries=getattr(error, "_retries", 0),
            disposition="quarantined" if quarantine else "rejected",
            stage=stage, **unit)

    def record_hang(self, filename: str, stage: str,
                    message: str = "") -> None:
        """Ledger a hang with no live exception in hand (the prefetch
        worker that never returned, a dead rank's shard) — same
        ``hang``/``rejected`` triage the :class:`HangError` path takes
        through :meth:`record_failure`."""
        if self.ledger is None:
            return
        self.ledger.record(
            filename, failure_class="hang", disposition="rejected",
            stage=stage,
            message=message or "operation never returned (hang)")

    def record_recovered(self, filename: str, retries: int,
                         stage: str) -> None:
        """Ledger a retry-saved read (bookkeeping only, never skipped)."""
        if self.ledger is None or not retries:
            return
        self.ledger.record(filename, retries=retries,
                           failure_class="transient",
                           disposition="recovered", stage=stage)

    def record_masked(self, filename: str, n_masked: int, stage: str,
                      **unit) -> None:
        """Ledger a numerical-tripwire event (unit stays live).

        Deduplicated: re-reading the same poisoned unit (a second band
        pass, a campaign re-run) must not re-append — and re-fsync —
        an identical line every time; only a CHANGED mask size is a new
        event worth recording."""
        if self.ledger is None or n_masked <= 0:
            return
        message = f"{n_masked} non-finite sample(s) zero-weighted"
        prev = self.ledger.latest(filename, feed=unit.get("feed"),
                                  band=unit.get("band"),
                                  scan=unit.get("scan"))
        if prev is not None and prev.disposition == "masked" \
                and prev.message == message:
            return
        self.ledger.record(
            filename, failure_class="numerical", disposition="masked",
            stage=stage, message=message, **unit)
