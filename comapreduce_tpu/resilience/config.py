"""Resilience configuration + the runtime bundle threaded through.

Mirrors :class:`~comapreduce_tpu.ingest.config.IngestConfig`: one value
object owning the knob names so the TOML ``[resilience]`` table, the
INI ``[Resilience]`` section and the CLI flags cannot drift apart, plus
:class:`Resilience` — the built runtime (ledger + retry policy + chaos
monkey) that ``Runner``/``read_comap_data`` actually consume.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from comapreduce_tpu.resilience.chaos import (ChaosMonkey,
                                              parse_inject_spec)
from comapreduce_tpu.resilience.ledger import QuarantineLedger
from comapreduce_tpu.resilience.retry import RetryPolicy

__all__ = ["ResilienceConfig", "Resilience"]


@dataclass(frozen=True)
class ResilienceConfig:
    """Knobs for the resilience subsystem.

    quarantine:
        Ledger path. ``"auto"`` (default) puts ``quarantine.jsonl``
        next to the run's outputs; an explicit path is used verbatim;
        ``"off"``/``"none"``/empty disables the ledger (failures fall
        back to the plain ``BAD FILE`` log line).
    max_retries / retry_base_s / retry_max_s / retry_jitter:
        :class:`~comapreduce_tpu.resilience.retry.RetryPolicy` fields —
        bounded exponential backoff for transient (I/O-class) failures.
    retry_quarantined:
        Re-admit every currently-quarantined unit at startup (the
        ``--retry-quarantined`` CLI flag lands here). Each re-admission
        is itself a ledger event.
    inject / inject_seed:
        Chaos spec (``chaos.parse_inject_spec`` syntax) + seed. Empty
        spec = no injection (production default).
    """

    quarantine: str = "auto"
    max_retries: int = 2
    retry_base_s: float = 0.5
    retry_max_s: float = 30.0
    retry_jitter: float = 0.25
    retry_quarantined: bool = False
    inject: str = ""
    inject_seed: int = 0

    def __post_init__(self):
        # normalise INI-coerced values (None from 'none'/'', bools,
        # numbers-as-strings) once, here — same contract as IngestConfig
        q = self.quarantine
        if q is None or str(q).strip().lower() in ("off", "none", "false",
                                                   ""):
            q = ""
        elif q is True or str(q).strip().lower() in ("auto", "true"):
            q = "auto"
        object.__setattr__(self, "quarantine", str(q))
        object.__setattr__(self, "max_retries",
                           max(int(self.max_retries or 0), 0))
        object.__setattr__(self, "retry_base_s",
                           max(float(self.retry_base_s or 0.0), 0.0))
        object.__setattr__(self, "retry_max_s",
                           max(float(self.retry_max_s or 0.0), 0.0))
        object.__setattr__(self, "retry_jitter",
                           max(float(self.retry_jitter or 0.0), 0.0))
        object.__setattr__(self, "retry_quarantined",
                           bool(self.retry_quarantined))
        # INI coercion splits a comma value into a LIST (the documented
        # multi-fault spec `inject : read_error:0.05,nan_burst:0.05`
        # arrives as ['read_error:0.05', 'nan_burst:0.05']) — rejoin it;
        # then parse eagerly so a typo'd spec fails at config load, not
        # mid-run
        inj = self.inject
        if isinstance(inj, (list, tuple)):
            inj = ",".join(str(v).strip() for v in inj)
        inj = str(inj or "")
        parse_inject_spec(inj)
        object.__setattr__(self, "inject", inj)
        object.__setattr__(self, "inject_seed",
                           int(self.inject_seed or 0))

    KNOBS = ("quarantine", "max_retries", "retry_base_s", "retry_max_s",
             "retry_jitter", "retry_quarantined", "inject", "inject_seed")

    @classmethod
    def from_mapping(cls, mapping) -> "ResilienceConfig":
        """Pick the resilience knobs out of a wider MIXED mapping (an
        ``[Inputs]``-style section holding other subsystems' keys too),
        ignoring unrelated keys. A dedicated ``[Resilience]``/TOML
        ``[resilience]`` section must go through :meth:`coerce`, which
        REJECTS unknown keys — a typo'd knob silently falling back to
        its default is exactly the failure a dedicated section can
        catch."""
        return cls(**{k: mapping[k] for k in cls.KNOBS if k in mapping})

    @classmethod
    def coerce(cls, value) -> "ResilienceConfig":
        """Build from None / dict / ResilienceConfig."""
        if value is None:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            known = {k: value[k] for k in cls.KNOBS if k in value}
            unknown = set(value) - set(known)
            if unknown:
                raise ValueError(
                    f"unknown resilience keys: {sorted(unknown)}")
            return cls(**known)
        raise TypeError(f"cannot build ResilienceConfig from {type(value)}")

    def ledger_path(self, output_dir: str = ".", rank: int = 0,
                    n_ranks: int = 1) -> str:
        """Resolved ledger path ('' when disabled).

        Multi-rank runs get per-rank auto paths: JSONL appends are only
        atomic single-writer (NFS interleaving would garble lines), and
        the round-robin filelist shard is stable across runs, so each
        rank owning its shard's failures in its own file keeps both the
        append and the resume-skip correct."""
        if not self.quarantine:
            return ""
        if self.quarantine == "auto":
            name = ("quarantine.jsonl" if n_ranks <= 1
                    else f"quarantine.rank{rank}.jsonl")
            return os.path.join(output_dir or ".", name)
        return self.quarantine

    def make_runtime(self, output_dir: str = ".", rank: int = 0,
                     n_ranks: int = 1) -> "Resilience":
        """Build the runtime bundle this config describes."""
        import logging

        path = self.ledger_path(output_dir, rank=rank, n_ranks=n_ranks)
        if path and self.quarantine == "auto":
            # fold in every sibling auto ledger read-only: a run with a
            # DIFFERENT rank count than the one that recorded a failure
            # must still see it (writes stay single-file, single-writer)
            import glob as _glob

            siblings = sorted(_glob.glob(os.path.join(
                os.path.dirname(path) or ".", "quarantine*.jsonl")))
            ledger = QuarantineLedger(path, read_paths=tuple(siblings))
        elif path:
            ledger = QuarantineLedger(path)
        else:
            ledger = None
        retry = RetryPolicy(max_retries=self.max_retries,
                            base_s=self.retry_base_s,
                            max_s=self.retry_max_s,
                            jitter=self.retry_jitter,
                            seed=self.inject_seed)
        chaos = (ChaosMonkey(self.inject, seed=self.inject_seed)
                 if self.inject else None)
        if chaos is not None:
            # loud on purpose: injected faults go through the REAL
            # quarantine path (that is the drill's point), so running a
            # drill against a production ledger would durably skip
            # healthy files — point drills at a scratch output_dir
            logging.getLogger("comapreduce_tpu").warning(
                "chaos injection ACTIVE (inject=%r, seed=%d): injected "
                "failures will be ledgered and may QUARANTINE files in "
                "%s — use a scratch output dir for drills",
                self.inject, self.inject_seed, path or "<no ledger>")
        return Resilience(ledger=ledger, retry=retry, chaos=chaos,
                          retry_quarantined=self.retry_quarantined)


@dataclass
class Resilience:
    """The built runtime bundle consumers thread through.

    Any field may be None (that capability is off); the helpers below
    keep the call sites free of ``if ... is not None`` noise.
    """

    ledger: QuarantineLedger | None = None
    retry: RetryPolicy | None = None
    chaos: ChaosMonkey | None = None
    retry_quarantined: bool = False
    _readmitted: set = field(default_factory=set)
    # quarantine snapshot, frozen at the first admit() of this runtime:
    # a file quarantined MID-run must not change which files the rest of
    # the same run covers (per-band destriper maps over one shared
    # runtime would otherwise cover different observation sets); the
    # next run's fresh runtime picks the new entries up
    _admit_snapshot: set | None = field(default=None, repr=False)

    def admit(self, filename: str) -> bool:
        """Quarantine gate for one file: True = process it.

        With ``retry_quarantined`` a quarantined file is re-admitted
        (ledger event, once per run) and processed; otherwise it is
        skipped cheaply — no read, no decode. The quarantined set is
        snapshotted at this runtime's first admit() call (see field
        comment)."""
        if self.ledger is None:
            return True
        if self._admit_snapshot is None:
            self._admit_snapshot = self.ledger.quarantined_files()
        if filename not in self._admit_snapshot:
            return True
        if self.retry_quarantined:
            if filename not in self._readmitted:
                self._readmitted.add(filename)
                self.ledger.readmit(filename)
            return True
        return False

    def record_failure(self, filename: str, error: BaseException,
                       stage: str, may_quarantine: bool = True,
                       **unit) -> None:
        """Ledger a failed unit. Classification and retry count come
        off the annotations ``retry_call`` leaves.

        Disposition triage: only failures that indict the FILE itself
        quarantine (skip on future runs) — exhausted-transient I/O
        errors, from a READ of that file, that are not mere lock
        contention. Everything else is ``rejected``: recorded for
        audit, re-attempted next run. A permanent error often encodes
        the CONFIG, not the data (a wrong ``tod_variant`` raises
        KeyError on every file); lock contention means another writer,
        not a bad file; and callers reporting failures from OUTSIDE the
        file's own read (``may_quarantine=False`` — e.g. a stage chain
        whose checkpoint WRITE hit a full output disk) must never
        durably skip the input over an environment problem."""
        if self.ledger is None:
            return
        from comapreduce_tpu.resilience.retry import (classify_error,
                                                      is_lock_error)

        failure_class = getattr(error, "_failure_class",
                                classify_error(error))
        quarantine = (may_quarantine and failure_class == "transient"
                      and not is_lock_error(error))
        self.ledger.record(
            filename, error=error,
            failure_class=failure_class,
            retries=getattr(error, "_retries", 0),
            disposition="quarantined" if quarantine else "rejected",
            stage=stage, **unit)

    def record_recovered(self, filename: str, retries: int,
                         stage: str) -> None:
        """Ledger a retry-saved read (bookkeeping only, never skipped)."""
        if self.ledger is None or not retries:
            return
        self.ledger.record(filename, retries=retries,
                           failure_class="transient",
                           disposition="recovered", stage=stage)

    def record_masked(self, filename: str, n_masked: int, stage: str,
                      **unit) -> None:
        """Ledger a numerical-tripwire event (unit stays live).

        Deduplicated: re-reading the same poisoned unit (a second band
        pass, a campaign re-run) must not re-append — and re-fsync —
        an identical line every time; only a CHANGED mask size is a new
        event worth recording."""
        if self.ledger is None or n_masked <= 0:
            return
        message = f"{n_masked} non-finite sample(s) zero-weighted"
        prev = self.ledger.latest(filename, feed=unit.get("feed"),
                                  band=unit.get("band"),
                                  scan=unit.get("scan"))
        if prev is not None and prev.disposition == "masked" \
                and prev.message == message:
            return
        self.ledger.record(
            filename, failure_class="numerical", disposition="masked",
            stage=stage, message=message, **unit)
