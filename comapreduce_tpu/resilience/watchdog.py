"""Watchdog: soft/hard wall-clock deadlines over named operations.

PR 2's resilience layer handles operations that *fail*; nothing in the
pipeline handled operations that *hang* — a stalled NFS read, a loader
stuck inside the HDF5 C library, one slow rank holding a whole
multi-host campaign. Production map-making frameworks treat wall-clock
budgets and per-rank progress as first-class operational signals
(MAPPRAISER, arXiv:2112.03370; COMAP ES III, arXiv:2111.05929); this
module is that signal source.

Two supervision modes, one deadline table:

- :meth:`Watchdog.call` — run ``fn`` on a disposable worker thread.
  At the **soft** deadline a structured ``stalled`` warning is logged
  and ledgered (the unit stays live); at the **hard** deadline the
  operation is CANCELLED: the worker thread is abandoned (a thread
  stuck in C code cannot be killed, but it can be orphaned — it is a
  daemon and its eventual result is discarded) and :class:`HangError`
  is raised to the caller. Use for reads and anything else whose
  side effects tolerate abandonment.
- :meth:`Watchdog.watch` — a context manager that monitors a block it
  cannot cancel (a jitted CG solve, a stage chain driving device
  compute). The soft deadline warns + ledgers identically; the hard
  deadline sets ``WatchState.hard_expired`` so the caller can route
  the late result through an operator signal path (the destriper
  treats it like a tripped divergence monitor: warn, never silent).

Deadlines come from two sources, merged per name:

- **static** — the ``[resilience] deadlines`` spec
  (``"name=soft/hard,*=soft/hard"``, seconds; either side may be
  empty). A name with no static entry (and no ``*`` default) is
  UNWATCHED — the watchdog never invents a deadline for an operation
  nobody budgeted.
- **adaptive** — once an operation has ``history_min`` recorded
  durations (the watchdog's own completions plus any external
  ``timings`` dict, e.g. ``Runner.timings``), each CONFIGURED side
  grows to the measured estimate: hard becomes
  ``max(static hard, p95 × scale)``, soft
  ``max(static soft, p95 × scale / 2)``. Adaptive deadlines only
  ever *extend* budgets the config set (a soft-only spec never grows
  a hard deadline — measurement must not overrule a never-cancel
  decision), and estimates below ``min_s`` are ignored outright (a
  history of near-zero cache hits must not drive budgets). A
  genuinely slow stage earns a longer leash; a tight static budget
  on a fast machine never produces false cancellations.

``HangError`` is a new failure class ``"hang"`` in the retry/ledger
triage: hangs are retried like transients (the NFS server may come
back) but on exhaustion they are ledgered ``rejected`` — re-attempted
next run, never durably quarantined, because a hang indicts the
ENVIRONMENT (a mount, a rank, a disk), not the file.

Everything here is host-side wall clock; nothing touches jit.
"""

from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

__all__ = ["Deadline", "HangError", "WatchState", "Watchdog",
           "parse_deadlines", "percentile"]

logger = logging.getLogger("comapreduce_tpu")

# durations remembered per operation name for the adaptive percentile;
# bounded so a campaign-length run cannot grow without limit
_HISTORY_CAP = 512


class HangError(OSError):
    """An operation exceeded its hard deadline and was cancelled.

    Subclasses ``OSError`` so every existing per-file I/O net
    (``except (OSError, KeyError)``) catches it, but
    ``retry.classify_error`` checks this type FIRST and classifies it
    ``"hang"`` — retried like a transient, ledgered ``rejected`` (not
    quarantined) when retries run out.
    """

    def __init__(self, op: str, unit: str, hard_s: float,
                 elapsed_s: float):
        super().__init__(
            f"{op}: {unit or '<anonymous>'} exceeded its hard deadline "
            f"({elapsed_s:.2f} s > {hard_s:.2f} s); operation cancelled")
        self.op = op
        self.unit = unit
        self.hard_s = float(hard_s)
        self.elapsed_s = float(elapsed_s)


@dataclass(frozen=True)
class Deadline:
    """Soft/hard wall budget for one operation name (``None`` = no
    limit on that side)."""

    soft_s: float | None = None
    hard_s: float | None = None

    def __post_init__(self):
        for name in ("soft_s", "hard_s"):
            v = getattr(self, name)
            if v is not None and not v > 0:
                raise ValueError(f"deadline {name} must be > 0, got {v}")
        if self.soft_s is not None and self.hard_s is not None \
                and self.hard_s < self.soft_s:
            raise ValueError(
                f"hard deadline ({self.hard_s}) must be >= soft "
                f"({self.soft_s})")


def parse_deadlines(spec: str) -> dict:
    """``"ingest.read=30/120,stage=60/,*=/600"`` ->
    ``{name: Deadline}``. ``soft/hard`` in seconds; either side may be
    empty (no limit on that side); a bare number is the hard deadline.
    ``*`` is the default for any watched-by-name lookup that has no
    exact entry. Empty spec -> ``{}``. Malformed entries raise (config
    load is the place to find a typo, not mid-run)."""
    out: dict[str, Deadline] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, budget = part.partition("=")
        name = name.strip()
        if not sep or not name:
            raise ValueError(f"deadline entry {part!r} is not "
                             "'name=soft/hard'")
        soft_s, sep2, hard_s = budget.partition("/")
        if not sep2:          # bare number = hard deadline
            soft_s, hard_s = "", soft_s
        soft = float(soft_s) if soft_s.strip() else None
        hard = float(hard_s) if hard_s.strip() else None
        if soft is None and hard is None:
            raise ValueError(f"deadline entry {part!r} sets neither a "
                             "soft nor a hard budget")
        out[name] = Deadline(soft_s=soft, hard_s=hard)
    return out


def percentile(samples, q: float) -> float:
    """Plain nearest-rank percentile (no numpy: this runs on the read
    hot path's supervision side)."""
    xs = sorted(float(s) for s in samples)
    if not xs:
        raise ValueError("percentile of no samples")
    k = max(0, min(len(xs) - 1, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[k]


@dataclass
class WatchState:
    """Live state of one supervised operation (yielded by
    :meth:`Watchdog.watch`, recorded into :attr:`Watchdog.events`)."""

    name: str
    unit: str = ""
    soft_s: float | None = None
    hard_s: float | None = None
    stalled: bool = False        # soft deadline fired
    hard_expired: bool = False   # hard deadline fired (uncancellable op)
    elapsed_s: float = 0.0
    _done: threading.Event = field(default_factory=threading.Event,
                                   repr=False)


class Watchdog:
    """Deadline supervisor for named operations.

    Parameters
    ----------
    deadlines:
        ``{name: Deadline}`` static table (see :func:`parse_deadlines`).
        Names without an entry (and no ``"*"`` default) are unwatched.
    ledger:
        Optional :class:`~comapreduce_tpu.resilience.ledger
        .QuarantineLedger`; soft stalls are recorded as
        ``hang``/``stalled`` events (informational — never skipped).
    timings:
        Optional external ``{name: [seconds]}`` durations dict
        (``Runner.timings``) folded into the adaptive percentile.
    scale / min_s / history_min:
        Adaptive rule: with ``history_min`` samples for a name, hard
        becomes ``max(p95 × scale, static hard or min_s)`` and soft
        ``max(p95 × scale/2, static soft)``. Config is a floor —
        adaptive only extends, never tightens.
    grace_s:
        Cancellation latency allowance on top of the hard deadline —
        the drill/CI contract asserts cancels land within
        ``hard + grace``.

    ``events`` is the audit trail: ``(kind, name, unit, elapsed_s)``
    with kind in ``stalled`` / ``hang`` / ``hard_expired``. Thread-safe
    (reads run on prefetcher worker threads).
    """

    def __init__(self, deadlines: dict | None = None, ledger=None,
                 timings: dict | None = None, scale: float = 4.0,
                 min_s: float = 30.0, grace_s: float = 0.5,
                 history_min: int = 8, heartbeat=None,
                 clock=time.monotonic):
        self.static = dict(deadlines or {})
        self.ledger = ledger
        self.timings = timings if timings is not None else {}
        self.scale = float(scale)
        self.min_s = float(min_s)
        self.grace_s = float(grace_s)
        self.history_min = int(history_min)
        self.heartbeat = heartbeat
        self.clock = clock
        self.history: dict[str, list] = {}
        self.events: list[tuple] = []
        self._lock = threading.Lock()

    # -- deadline resolution ------------------------------------------------
    def record(self, name: str, elapsed_s: float) -> None:
        """Remember a completed operation's duration (adaptive input)."""
        with self._lock:
            hist = self.history.setdefault(name, [])
            hist.append(float(elapsed_s))
            if len(hist) > _HISTORY_CAP:
                del hist[: len(hist) - _HISTORY_CAP]

    def _samples(self, name: str) -> list:
        with self._lock:
            own = list(self.history.get(name, ()))
        try:
            # a spans-backed StageTimings knows which entries are
            # skip-path placeholders (errored reads, resumed files)
            # and excludes them here — a mostly-resumed campaign must
            # not drag the adaptive p95 (and with it every deadline
            # budget) toward zero; a plain dict has no skip tracking
            # and contributes everything, as before
            sample = getattr(self.timings, "samples", None)
            ext = list(sample(name)) if sample is not None \
                else list(self.timings.get(name, ()))
        except AttributeError:
            ext = []
        return own + [float(v) for v in ext]

    def deadline_for(self, name: str) -> Deadline | None:
        """The effective deadline for ``name`` right now (static merged
        with adaptive; ``None`` = unwatched).

        Adaptive budgets only ever EXTEND sides the config budgeted: a
        soft-only spec (``name=60/``) never grows a hard deadline — the
        operator said never-cancel, and measurement must not overrule
        that — and a hard-only spec never grows a soft one. Adaptive
        estimates below ``min_s`` are ignored entirely (a history of
        near-zero cache-hit reads must not drive budgets)."""
        static = self.static.get(name) or self.static.get("*")
        if static is None:
            return None
        soft, hard = static.soft_s, static.hard_s
        samples = self._samples(name)
        if len(samples) >= self.history_min:
            estimate = percentile(samples, 95.0) * self.scale
            if estimate >= self.min_s:
                if hard is not None:
                    hard = max(hard, estimate)
                if soft is not None:
                    soft = max(soft, estimate / 2.0)
        return Deadline(soft_s=soft, hard_s=hard)

    # -- event plumbing -----------------------------------------------------
    def _event(self, kind: str, name: str, unit: str,
               elapsed_s: float) -> None:
        with self._lock:
            self.events.append((kind, name, unit, round(elapsed_s, 4)))
        if self.heartbeat is not None:
            try:
                self.heartbeat.note(deadline={
                    "name": name, "state": kind,
                    "elapsed_s": round(elapsed_s, 3)})
            except Exception:  # pragma: no cover - advisory only
                logger.exception("heartbeat note failed")

    def _stall(self, st: WatchState) -> None:
        st.stalled = True
        elapsed = self.clock() - st._t0
        logger.warning(
            "watchdog: %s (%s) STALLED: %.2f s elapsed > soft deadline "
            "%.2f s (hard %s)", st.name, st.unit or "<anonymous>",
            elapsed, st.soft_s,
            f"{st.hard_s:.2f} s" if st.hard_s else "none")
        self._event("stalled", st.name, st.unit, elapsed)
        if self.ledger is not None:
            self.ledger.record(
                st.unit or st.name, failure_class="hang",
                disposition="stalled", stage=st.name,
                message=f"stalled {elapsed:.2f} s > soft "
                        f"{st.soft_s:.2f} s")

    def _begin(self, name: str, unit: str) -> None:
        if self.heartbeat is not None:
            try:
                self.heartbeat.note(stage=name, unit=unit)
            except Exception:  # pragma: no cover - advisory only
                logger.exception("heartbeat note failed")

    # -- supervision --------------------------------------------------------
    @contextmanager
    def watch(self, name: str, unit: str = ""):
        """Monitor a block this thread runs itself (UNCANCELLABLE: a
        jitted solve, a stage chain). Soft -> stall warning + ledger;
        hard -> ``WatchState.hard_expired`` for the caller to act on.
        Completed durations feed the adaptive history."""
        dl = self.deadline_for(name)
        st = WatchState(name=name, unit=unit,
                        soft_s=dl.soft_s if dl else None,
                        hard_s=dl.hard_s if dl else None)
        st._t0 = self.clock()
        self._begin(name, unit)
        monitor = None
        if st.soft_s is not None or st.hard_s is not None:
            monitor = threading.Thread(
                target=self._monitor, args=(st,),
                name=f"watchdog:{name}", daemon=True)
            monitor.start()
        try:
            yield st
        finally:
            st.elapsed_s = self.clock() - st._t0
            st._done.set()
            if monitor is not None:
                monitor.join(timeout=1.0)
            if not st.hard_expired:
                self.record(name, st.elapsed_s)

    def _monitor(self, st: WatchState) -> None:
        if st.soft_s is not None:
            if st._done.wait(timeout=st.soft_s):
                return
            self._stall(st)
        if st.hard_s is None:
            return
        remaining = st.hard_s - (self.clock() - st._t0)
        if remaining > 0 and st._done.wait(timeout=remaining):
            return
        if st._done.is_set():
            return
        st.hard_expired = True
        elapsed = self.clock() - st._t0
        logger.error(
            "watchdog: %s (%s) exceeded its HARD deadline (%.2f s > "
            "%.2f s) and cannot be cancelled in place; flagging for the "
            "caller", st.name, st.unit or "<anonymous>", elapsed,
            st.hard_s)
        self._event("hard_expired", st.name, st.unit, elapsed)

    def call(self, fn, name: str, unit: str = "", args: tuple = ()):
        """Run ``fn(*args)`` under ``name``'s deadline, CANCELLABLY.

        With a hard deadline the call runs on a disposable daemon
        worker; past the deadline the worker is abandoned (its eventual
        result/exception is discarded) and :class:`HangError` raises —
        a stuck HDF5/NFS read in C code is orphaned, not joined
        forever. Unwatched names call straight through (no thread).
        Soft-only names run inline under :meth:`watch`.
        """
        dl = self.deadline_for(name)
        if dl is None:
            return fn(*args)
        if dl.hard_s is None:
            with self.watch(name, unit=unit):
                return fn(*args)
        st = WatchState(name=name, unit=unit, soft_s=dl.soft_s,
                        hard_s=dl.hard_s)
        st._t0 = self.clock()
        self._begin(name, unit)
        box: dict = {}
        done = threading.Event()
        abandoned = threading.Event()

        def run():
            try:
                box["value"] = fn(*args)
            except BaseException as exc:  # noqa: BLE001 — relayed below
                box["error"] = exc
                if abandoned.is_set():
                    # nobody will re-raise this; keep the log trail
                    logger.warning(
                        "watchdog: abandoned %s worker for %s finally "
                        "failed: %s: %s", name, unit or "<anonymous>",
                        type(exc).__name__, exc)
            finally:
                done.set()

        worker = threading.Thread(target=run, daemon=True,
                                  name=f"watchdog-call:{name}")
        worker.start()
        budget = dl.hard_s
        if dl.soft_s is not None:
            if not done.wait(timeout=dl.soft_s):
                self._stall(st)
            budget = dl.hard_s - (self.clock() - st._t0)
        if not done.wait(timeout=max(budget, 0.0)):
            abandoned.set()
            elapsed = self.clock() - st._t0
            self._event("hang", name, unit, elapsed)
            logger.error(
                "watchdog: %s (%s) HUNG: %.2f s > hard deadline %.2f s; "
                "abandoning the worker thread and cancelling the "
                "operation", name, unit or "<anonymous>", elapsed,
                dl.hard_s)
            raise HangError(name, unit, dl.hard_s, elapsed)
        if "error" in box:
            raise box["error"]
        self.record(name, self.clock() - st._t0)
        return box["value"]
