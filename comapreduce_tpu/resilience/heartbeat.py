"""Per-rank heartbeat files: liveness + progress for multi-host runs.

Each rank writes ``heartbeat.rank{r}.json`` into the run's output
directory — atomically (tmp + ``os.replace``), so a reader never sees a
torn file — carrying what an operator (or a sibling rank's straggler
detector, ``parallel.multihost``) needs to tell a slow rank from a dead
one:

```json
{"rank": 0, "pid": 12345, "host": "vm", "seq": 42,
 "stage": "ingest.read", "unit": "/data/comap-0001.hd5",
 "progress": {"files_done": 3, "files_failed": 1},
 "deadline": {"name": "ingest.read", "state": "stalled",
              "elapsed_s": 31.2},
 "t_wall": "2026-08-04T07:00:00Z", "t_wall_unix": 1785913200.0,
 "t_mono": 12345.6}
```

``seq`` increments on every write (progress is "seq advanced", even
when the wall clock of two hosts disagrees); ``t_mono`` is the writer's
monotonic clock (meaningful only within one host — stale-ness across
hosts is judged by ``t_wall_unix``/file mtime); ``deadline`` mirrors
the watchdog's last event for the rank so a stall is visible without
grepping logs. Writes are advisory and NOT fsynced — a lost heartbeat
costs one tick, never data.

A background ticker (:meth:`Heartbeat.start`) rewrites the file every
``period_s`` even when the rank is stuck inside one long operation —
that is exactly when liveness information matters most; the watchdog
additionally :meth:`note`\\ s stage transitions and deadline events
through immediately. ``tools/watchdog_report.py`` renders these files
plus the quarantine ledger into the operator stall report.
"""

from __future__ import annotations

import glob as _glob
import json
import logging
import os
import re
import socket
import tempfile
import threading
import time

__all__ = ["Heartbeat", "HeartbeatWatch", "heartbeat_age_s",
           "heartbeat_path", "heartbeat_signature", "heartbeat_stale",
           "read_heartbeats", "stale_age"]

logger = logging.getLogger("comapreduce_tpu")

_NAME_RE = re.compile(r"heartbeat\.rank(\d+)\.json$")


def heartbeat_path(directory: str, rank: int) -> str:
    return os.path.join(directory or ".", f"heartbeat.rank{rank}.json")


def read_heartbeats(directory: str) -> dict:
    """``{rank: parsed_heartbeat}`` for every readable
    ``heartbeat.rank*.json`` in ``directory``. A torn/foreign file is
    skipped with a warning, never fatal (the writer replaces
    atomically, but NFS caching or a partial copy can still serve
    garbage). Each entry gains ``_mtime`` (the file's mtime) for
    local-clock staleness checks."""
    out: dict[int, dict] = {}
    for path in sorted(_glob.glob(
            os.path.join(directory or ".", "heartbeat.rank*.json"))):
        m = _NAME_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path, "r", encoding="utf-8") as f:
                hb = json.load(f)
        except (OSError, ValueError) as exc:
            logger.warning("unreadable heartbeat %s (%s: %s)", path,
                           type(exc).__name__, exc)
            continue
        from comapreduce_tpu.resilience.integrity import check_json

        hb, verdict = check_json(hb)
        if verdict is False:
            # a rotted heartbeat is as unreadable as a torn one: skip
            # it (the rank looks silent, which is the honest signal)
            logger.warning("heartbeat %s fails its _sha256 seal; "
                           "skipped", path)
            continue
        try:
            hb["_mtime"] = os.stat(path).st_mtime
        except OSError:
            continue
        out[int(m.group(1))] = hb
    return out


def heartbeat_age_s(hb: dict, now: float | None = None) -> float:
    """Heartbeat age against the local clock: the freshest NON-NEGATIVE
    of the wall timestamp inside the file and the file's own mtime (two
    hosts' wall clocks may disagree; mtime is assigned by the
    filesystem). A timestamp in the FUTURE is no evidence of life — a
    dead rank whose clock ran ahead must not read fresh for the whole
    skew window — so when every component is in the future the
    (negative) age is returned as-is for the caller's out-of-range
    test. ONE home for the rule: ``tools/watchdog_report`` staleness
    and any freshness heuristic must not drift apart."""
    now = time.time() if now is None else now
    ages = [now - float(hb.get("t_wall_unix", 0.0)),
            now - float(hb.get("_mtime", 0.0))]
    valid = [a for a in ages if a >= 0.0]
    return min(valid) if valid else min(ages)


def stale_age(age: float, ttl: float) -> bool:
    """The ONE out-of-range predicate applied to a heartbeat age: too
    old is dead, and a NEGATIVE age (future clock, see
    :func:`heartbeat_age_s`) is a skewed host with no live evidence —
    stale on either side. Every consumer of the rule — the operator
    report, the lease scheduler's ``expired()``, the serving watcher's
    freshness view and the live ``/healthz`` probe — must route through
    here (or :func:`heartbeat_stale`) so the definitions cannot
    drift."""
    return not 0.0 <= age <= ttl


def heartbeat_stale(hb: dict | None, now: float | None = None,
                    ttl: float = 60.0) -> bool:
    """``True`` when ``hb`` shows no evidence of life within ``ttl``
    seconds: missing heartbeat, or :func:`heartbeat_age_s` out of the
    ``[0, ttl]`` band (:func:`stale_age`)."""
    if hb is None:
        return True
    return stale_age(heartbeat_age_s(hb, now), ttl)


def heartbeat_signature(hb: dict | None) -> tuple | None:
    """The change-detection identity of one heartbeat: ``(seq,
    t_wall_unix, _mtime)``. Two reads with the same signature carry no
    evidence the writer lived between them; ANY component moving does.
    One home for the tuple — the straggler barrier
    (``parallel.multihost``) and the control-plane supervisor
    (``control.supervisor``) must judge liveness by the same rule."""
    if hb is None:
        return None
    return (hb.get("seq"), hb.get("t_wall_unix"), hb.get("_mtime"))


class HeartbeatWatch:
    """CHANGE-based liveness over a fleet of heartbeats.

    A rank counts as ALIVE only when its heartbeat is *observed to
    change* (a new :func:`heartbeat_signature` — advancing ``seq``,
    fresh stamp or mtime — or a file appearing after the watch began)
    within the trailing ``ttl_s`` window. A file already on disk at the
    first :meth:`observe` proves nothing: it may be a crashed rank's
    final beat, written milliseconds before the SIGKILL and fresh by
    every timestamp — the exact artefact that must never read alive to
    an autoscaler deciding whether to spawn a replacement. The rule is
    also immune to cross-host clock skew: a future-stamped heartbeat
    from a dead rank never changes, so it goes ``dead`` like any other
    frozen file, while a skewed-but-beating rank still proves itself by
    advancing ``seq``.

    Verdicts per rank: ``"alive"`` (change observed within ``ttl_s``),
    ``"unknown"`` (watched for less than ``ttl_s`` with no change yet —
    the proving window of a fleet the watch just started over), and
    ``"dead"`` (no change for at least ``ttl_s``; a rank never seen at
    all is also ``dead``). The price of change-based proof is latency —
    ``ttl_s`` must comfortably exceed the fleet's ``heartbeat_s``
    ticker period or healthy ranks flap through ``dead`` between beats.

    Both the pre-shard straggler barrier and the control plane's
    supervisor poll through one instance of this class; the inline
    baseline/signature logic they would otherwise each re-derive lives
    here and nowhere else.
    """

    ALIVE = "alive"
    UNKNOWN = "unknown"
    DEAD = "dead"

    def __init__(self, ttl_s: float, clock=time.monotonic):
        self.ttl_s = float(ttl_s)
        self.clock = clock
        self._started: float | None = None
        # rank -> [signature, t_ref, ever_changed]; t_ref is the last
        # observed change (or first sighting while unchanged)
        self._tracks: dict[int, list] = {}

    def observe(self, heartbeats: dict) -> dict:
        """Fold one ``read_heartbeats`` snapshot in; returns
        ``{rank: verdict}`` for every rank ever seen."""
        t = self.clock()
        if self._started is None:
            self._started = t
        for rank, hb in heartbeats.items():
            sig = heartbeat_signature(hb)
            tr = self._tracks.get(rank)
            if tr is None:
                # first sighting: at the baseline scan (the watch's
                # very first observe) the file proves nothing; a file
                # APPEARING after the watch began is itself a change
                self._tracks[rank] = [sig, t, t > self._started]
            elif sig != tr[0]:
                tr[0], tr[1], tr[2] = sig, t, True
        return {rank: self.verdict(rank, now=t) for rank in self._tracks}

    def verdict(self, rank: int, now: float | None = None) -> str:
        """This rank's current liveness verdict (see class docstring)."""
        tr = self._tracks.get(rank)
        if tr is None:
            return self.DEAD
        t = self.clock() if now is None else now
        sig, t_ref, changed = tr
        if t - t_ref > self.ttl_s:
            return self.DEAD
        return self.ALIVE if changed else self.UNKNOWN

    def alive_ranks(self) -> list:
        return sorted(r for r in self._tracks
                      if self.verdict(r) == self.ALIVE)

    def dead_ranks(self, expected=()) -> list:
        """Ranks with a ``dead`` verdict; ``expected`` ranks never seen
        at all (no heartbeat file was ever observed) count too."""
        seen = set(self._tracks)
        dead = {r for r in seen if self.verdict(r) == self.DEAD}
        dead |= {int(r) for r in expected} - seen
        return sorted(dead)


class Heartbeat:
    """Atomic per-rank heartbeat writer with a background ticker.

    Thread-safe: the ticker, the watchdog (from prefetcher worker
    threads) and the consumer all write through one lock. ``start`` /
    ``stop`` are idempotent and re-startable (``run_tod`` followed by
    ``run_astro_cal`` reuses one instance).
    """

    def __init__(self, directory: str, rank: int = 0,
                 period_s: float = 10.0, clock=time.monotonic):
        self.directory = directory or "."
        self.rank = int(rank)
        self.path = heartbeat_path(directory, rank)
        self.period_s = float(period_s)
        self.clock = clock
        self._lock = threading.Lock()
        # commit order lock: snapshot + tmp-write + replace must be one
        # unit, or two racing writers can land their replaces out of
        # order and seq would REGRESS on disk — the straggler barrier's
        # seq-advance liveness check must never see a healthy rank go
        # backwards. (_lock alone guards state and is never held across
        # I/O; heartbeat payloads are ~300 B, so holding _io_lock
        # through the write is cheap.)
        self._io_lock = threading.Lock()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._paused = False
        self._thread: threading.Thread | None = None
        self._state = {
            "rank": self.rank,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "seq": 0,
            "stage": "",
            "unit": "",
            "progress": {},
            "deadline": None,
        }

    # -- state --------------------------------------------------------------
    def _publish(self) -> None:
        """Get the updated state onto disk WITHOUT blocking the caller
        on heartbeat I/O when the ticker runs: the watchdog calls
        :meth:`note` from the very paths it supervises, and a stalled
        output mount must not wedge the hang supervisor inside its own
        liveness write — the ticker thread (whose whole job is this
        I/O) is woken to write instead. With no live ticker (period 0,
        or not started) the write happens inline."""
        if self._thread is not None and self._thread.is_alive():
            self._wake.set()
        else:
            self.write()

    def note(self, stage: str | None = None, unit: str | None = None,
             deadline: dict | None = None) -> None:
        """Update the current position (and/or last deadline event) and
        publish (see :meth:`_publish`)."""
        with self._lock:
            if stage is not None:
                self._state["stage"] = stage
            if unit is not None:
                self._state["unit"] = unit
            if deadline is not None:
                self._state["deadline"] = dict(deadline)
        self._publish()

    def advance(self, **counters) -> None:
        """Increment progress counters (``files_done=1, ...``) and
        publish."""
        with self._lock:
            prog = self._state["progress"]
            for k, v in counters.items():
                prog[k] = prog.get(k, 0) + int(v)
        self._publish()

    # -- persistence --------------------------------------------------------
    def write(self) -> None:
        """One atomic heartbeat write (never torn; advisory, so I/O
        failures are logged and swallowed — a full disk must not kill
        the run through its liveness channel). Commits are serialised
        (see ``_io_lock``) so ``seq`` on disk is monotonic."""
        with self._io_lock:
            with self._lock:
                if self._paused:
                    return  # zombie mode: no beat may reach disk
                self._state["seq"] += 1
                snap = dict(self._state,
                            progress=dict(self._state["progress"]),
                            t_mono=self.clock(),
                            t_wall_unix=time.time(),
                            t_wall=time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                 time.gmtime()))
            try:
                os.makedirs(self.directory, exist_ok=True)
                fd, tmp = tempfile.mkstemp(
                    prefix=".heartbeat.", suffix=".tmp",
                    dir=self.directory)
                try:
                    with os.fdopen(fd, "w", encoding="utf-8") as f:
                        from comapreduce_tpu.resilience.integrity import (
                            seal_json)

                        json.dump(seal_json(snap), f)
                    os.replace(tmp, self.path)
                except BaseException:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
                    raise
            except OSError as exc:
                logger.warning("heartbeat write failed (%s: %s)",
                               type(exc).__name__, exc)

    # -- ticker -------------------------------------------------------------
    def start(self) -> "Heartbeat":
        """Start (or restart) the background ticker; writes one beat
        immediately so the file exists before any barrier reads it."""
        if self.period_s <= 0:
            return self
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._wake.clear()
        with self._lock:
            self._paused = False  # a restarted rank beats again
        self.write()
        self._thread = threading.Thread(
            target=self._tick, name=f"heartbeat.rank{self.rank}",
            daemon=True)
        self._thread.start()
        return self

    def _tick(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=self.period_s)
            self._wake.clear()
            if self._stop.is_set():
                break  # stop() writes the final beat itself
            self.write()

    def pause(self) -> None:
        """Freeze the heartbeat WITHOUT stopping the rank — every
        subsequent write (ticker, notes, even :meth:`stop`'s final
        beat) is suppressed until :meth:`start` is called again. This
        is the chaos ``rank_pause`` zombie: to every observer the rank
        is dead (its lease becomes stealable), yet it keeps computing
        and will try to commit. Also the clean half of the LEAVE
        runbook: pause, finish the unit in flight, exit."""
        with self._lock:
            self._paused = True
        logger.warning("heartbeat rank %d: paused — no further beats "
                       "will be written", self.rank)

    def stop(self, final_stage: str = "") -> None:
        """Stop the ticker and write one final beat (so the last state
        on disk says where the rank ended, not where the ticker
        happened to catch it)."""
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=max(self.period_s, 1.0))
            self._thread = None
        if final_stage:
            with self._lock:
                self._state["stage"] = final_stage
        self.write()

    def __enter__(self) -> "Heartbeat":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
