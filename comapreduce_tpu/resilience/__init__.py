"""Resilience layer: quarantine ledger, retry policy, tripwires, chaos.

The reference pipeline's whole answer to bad data is a broad
``try/except`` that logs ``BAD FILE`` and drops the observation
(``COMAPData.py:169-173``); nothing records *what* failed, *why*, or
whether a retry could have saved it, and a re-run pays the full read
cost of every known-bad file again. Real reductions are dominated by
data-quality rejection (COMAP ESIII, arXiv:2111.05929) and large
map-making runs must survive detector-level failures without
restarting the solve (MAPPRAISER, arXiv:2112.03370). This subsystem
gives the framework the same property:

- :class:`QuarantineLedger` (``ledger``) — a persistent JSONL record of
  every failed/suspect unit (file, feed, band, scan) with failure
  class, traceback digest, retry count and disposition. Consulted on
  resume: known-bad files are skipped without a read, and re-admitted
  only on explicit ``--retry-quarantined``.
- :class:`RetryPolicy` (``retry``) — bounded retries with exponential
  backoff + deterministic jitter, driven by transient-vs-permanent
  error classification (``OSError``/truncated-HDF5 reads are worth a
  retry; shape/validation errors never are).
- ``tripwires`` — cheap jitted finite-fraction checks that mask NaN/Inf
  TOD samples into zero weight before they can poison a CG solve, plus
  the host-side scrub bookkeeping. The destriper's CG loop carries the
  matching divergence monitor (``destriper._cg_loop``).
- :class:`ChaosMonkey` (``chaos``) — deterministic fault injection
  (read errors, NaN bursts, truncated files, slow reads, hangs,
  first-attempt flakes) by seed, so every path above is exercised in CI
  (``tools/check_resilience.py``) instead of discovered in production.
- :class:`Watchdog` (``watchdog``) — soft/hard wall-clock deadlines
  over named operations: soft fires a structured ``stalled``
  warning + ledger event, hard CANCELS (reads run on a disposable
  worker thread, so a call stuck in HDF5/NFS C code is abandoned, not
  joined forever) and raises :class:`HangError` — a new ``hang``
  failure class that is retried like a transient and ledgered
  ``rejected`` on exhaustion. Deadlines are static from config plus
  adaptive from recorded stage durations (p95 x scale, floored by
  config).
- ``integrity`` — end-to-end artifact integrity: every durable commit
  carries a sha256 sidecar or embedded seal, every load boundary
  verifies before trusting bytes, and a mismatch raises
  :class:`CorruptArtifactError` → the non-retryable ``corrupt``
  failure class → per-artifact-class triage (unlink-and-rebuild vs
  quarantine-with-evidence). Audited offline by
  ``tools/campaign_fsck.py`` (docs/OPERATIONS.md §20).
- :class:`Heartbeat` (``heartbeat``) — atomic per-rank
  ``heartbeat.rank{r}.json`` (stage, unit, progress counters, last
  deadline state, monotonic + wall clocks) on a background ticker;
  read by ``parallel.multihost``'s straggler barrier and rendered by
  ``tools/watchdog_report.py``.
- :class:`LeaseBoard` (``lease``) — heartbeat-fenced per-unit work
  leases (claim / steal / generation-fenced commit over plain files),
  the primitive under ``pipeline.scheduler``'s elastic campaigns: a
  dead or zombie rank's units are stolen by survivors, its late
  commits rejected at the generation fence (docs/OPERATIONS.md §11).

Config surface: :class:`ResilienceConfig` (TOML ``[resilience]`` table,
INI ``[Resilience]`` section) -> :meth:`ResilienceConfig.make_runtime`
-> a :class:`Resilience` bundle threaded through ``pipeline.Runner``,
``ingest`` streams and ``mapmaking.leveldata``. See
``docs/OPERATIONS.md`` §7.
"""

from comapreduce_tpu.resilience.chaos import ChaosMonkey  # noqa: F401
from comapreduce_tpu.resilience.integrity import (  # noqa: F401
    CorruptArtifactError,
    committed_replace,
    seal_json,
    check_json,
    verify_file,
    verify_enabled,
    write_sidecar,
    read_sidecar,
    sha256_path,
)
from comapreduce_tpu.resilience.config import (  # noqa: F401
    DEFAULT_LEASE_TTL_S,
    Resilience,
    ResilienceConfig,
)
from comapreduce_tpu.resilience.ledger import (  # noqa: F401
    LedgerEntry,
    QuarantineLedger,
)
from comapreduce_tpu.resilience.retry import (  # noqa: F401
    RetryPolicy,
    classify_error,
    retry_call,
)
from comapreduce_tpu.resilience.heartbeat import (  # noqa: F401
    Heartbeat,
    read_heartbeats,
)
from comapreduce_tpu.resilience.lease import (  # noqa: F401
    Lease,
    LeaseBoard,
    lease_key,
    lease_path,
    read_lease,
)
from comapreduce_tpu.resilience.tripwires import (  # noqa: F401
    finite_fraction,
    scrub_tod,
    scrub_tod_host,
)
from comapreduce_tpu.resilience.watchdog import (  # noqa: F401
    Deadline,
    HangError,
    Watchdog,
    parse_deadlines,
)
