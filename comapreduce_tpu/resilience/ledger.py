"""The quarantine ledger: a persistent JSONL record of failed units.

One line per event, append-only — the format a kill can't corrupt
beyond its own last line (and :meth:`QuarantineLedger.load` tolerates
exactly that: a truncated trailing line is dropped, never fatal). The
*latest* entry for a unit wins, so re-admission and recovery are new
appended events, not in-place edits; the full failure history of a
campaign stays greppable.

Entry schema (one JSON object per line)::

    {"t": "2026-08-04T07:00:00Z",      # UTC timestamp
     "unit": {"file": "...", "feed": null, "band": null, "scan": null},
     "failure_class": "transient" | "permanent" | "numerical" | "hang",
     "error": "OSError",               # exception type name ('' if n/a)
     "message": "...",                 # str(exc), truncated
     "digest": "1f2e3d4c5b6a",         # sha1 of the traceback, 12 hex
     "retries": 2,                     # attempts burned before giving up
     "disposition": "quarantined" | "readmitted" | "recovered"
                    | "masked",
     "stage": "ingest.read"}           # where it was caught

Dispositions: ``quarantined`` — the unit is skipped on future runs
until re-admitted; ``readmitted`` — an operator ran
``--retry-quarantined`` and the unit is live again; ``recovered`` — a
retry succeeded (bookkeeping only, never skipped); ``masked`` — a
numerical tripwire zero-weighted part of the unit (the rest of the
unit still flows; never skipped); ``rejected`` — the unit failed this
run but is re-attempted on the next one (never skipped: used for
failures that may be config-dependent — a ``KeyError`` from a wrong
``tod_variant`` must not poison the ledger against the corrected
re-run — for lock contention, where the file itself is fine, and for
``hang``-class failures, which indict the environment — an NFS mount,
a dead rank — rather than the data); ``stalled`` — a watchdog soft
deadline fired mid-operation (informational; never skipped — the
operation itself may still have succeeded); ``stolen`` — an elastic
campaign survivor reclaimed this unit's expired lease from a dead or
zombie rank (``pipeline.scheduler``; never skipped — the unit is
being redone right now), paired with a later ``recovered`` once the
thief commits it; ``deferred`` — the control plane's admission gate
shed this (quality-flagged) unit under SLO pressure
(``control.admission``; never skipped — the unit stays in the queue
and is paired with a later ``readmitted`` when pressure clears or
the rest of the queue drains: shed, never dropped); ``corrupt`` — a
committed artifact for this unit failed sha256 verification
(``resilience.integrity``): skipped like ``quarantined``, with the
digest evidence in the message. Re-derivable artifacts (Level-2
checkpoints, spill, snapshots, tiles) are unlinked and rebuilt from
source, which appends the lifting ``recovered``; non-derivable
Level-1 inputs stay corrupt until an operator re-stages the data and
``--retry-quarantined``s the unit.

Every line appended since the integrity plane landed carries an
embedded ``_sha256`` seal (``resilience.integrity.seal_line``); a
line whose seal fails verification is dropped-and-counted on load
exactly like a torn line (``tools/campaign_fsck.py --repair`` rewrites
the file without them). Pre-integrity lines have no seal and load
unverified — the scheme is additive.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
import traceback
from dataclasses import asdict, dataclass, field

from comapreduce_tpu.resilience.integrity import check_line, seal_line

__all__ = ["LedgerEntry", "QuarantineLedger", "traceback_digest"]

logger = logging.getLogger("comapreduce_tpu")

# dispositions that make a unit skippable on the next run
_SKIPPING = ("quarantined", "corrupt")
_MSG_LIMIT = 500


def traceback_digest(exc: BaseException | None) -> str:
    """12-hex sha1 of the exception's formatted traceback — stable
    across runs for 'the same failure', unlike the message (which may
    embed retry counts or tmp paths)."""
    if exc is None:
        return ""
    tb = "".join(traceback.format_exception(type(exc), exc,
                                            exc.__traceback__))
    return hashlib.sha1(tb.encode()).hexdigest()[:12]


@dataclass(frozen=True)
class LedgerEntry:
    """One ledger line (see the module docstring for field semantics)."""

    unit: dict
    failure_class: str = ""
    error: str = ""
    message: str = ""
    digest: str = ""
    retries: int = 0
    disposition: str = "quarantined"
    stage: str = ""
    t: str = ""
    # sub-second companion to ``t``: cross-rank latest-wins must order
    # a defer and its re-admission correctly even within one second
    # (0.0 on pre-control ledger lines — they sort first in their tie)
    t_unix: float = 0.0

    @property
    def key(self) -> tuple:
        """Identity of the unit this entry is about."""
        u = self.unit
        return (u.get("file"), u.get("feed"), u.get("band"),
                u.get("scan"))


def _unit(file: str, feed=None, band=None, scan=None) -> dict:
    return {"file": file, "feed": feed, "band": band, "scan": scan}


class QuarantineLedger:
    """Append-only JSONL quarantine ledger.

    Thread-safe (the ingest prefetcher's worker thread records read
    failures concurrently with the consumer). Every :meth:`record`
    appends one line and flushes, so a kill right after a failure still
    leaves that failure on disk for the next run to skip.
    """

    def __init__(self, path: str, read_paths: tuple = ()):
        """``path`` is the file this process APPENDS to (single-writer:
        JSONL appends only interleave safely with one writer per file);
        ``read_paths`` are sibling ledgers folded into the in-memory
        state read-only — how a run with a different rank count still
        sees every rank's quarantines (the auto path is per-rank on
        multi-rank runs)."""
        self.path = path
        self.read_paths = tuple(p for p in read_paths if p != path)
        self._lock = threading.Lock()
        self._latest: dict[tuple, LedgerEntry] = {}
        self.entries: list[LedgerEntry] = []
        # seal-failing lines dropped across load()s — surfaced by the
        # watchdog report so silent rot in the ledger itself is loud
        self.corrupt_lines = 0
        self.load()

    # -- persistence -------------------------------------------------------
    def _read_file(self, path: str) -> list[LedgerEntry]:
        if not path or not os.path.exists(path):
            return []
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
        dropped = 0
        corrupt = 0
        out = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            raw, verdict = check_line(line)
            if raw is None:
                # unparseable (torn by a kill) or failed its seal
                # (rotted in place) — either way one line is dropped,
                # never the ledger
                try:
                    json.loads(line)
                    corrupt += 1  # parsed fine: the seal failed
                except ValueError:
                    dropped += 1
                continue
            try:
                out.append(LedgerEntry(
                    **{k: raw[k] for k in
                       LedgerEntry.__dataclass_fields__ if k in raw}))
            except (ValueError, TypeError):
                dropped += 1
        if dropped:
            logger.warning("quarantine ledger %s: dropped %d unparseable "
                           "line(s) (truncated by a kill?)", path,
                           dropped)
        if corrupt:
            logger.warning("quarantine ledger %s: dropped %d line(s) "
                           "failing their _sha256 seal (bit rot? run "
                           "tools/campaign_fsck.py --repair)", path,
                           corrupt)
        self.corrupt_lines += corrupt
        return out

    def load(self) -> int:
        """(Re)read the ledger (own file + read-only siblings); returns
        the number of valid lines.

        A truncated/garbled trailing line (the signature of a kill
        mid-append) is dropped with a warning; a garbled line in the
        *middle* of a file is dropped too — one corrupt event must not
        cost the whole ledger. Cross-file ordering for latest-wins is
        by timestamp — ISO second first, then the sub-second ``t_unix``
        (admission control defers and re-admits within one second) —
        stable with the OWN file's entries read last so they win exact
        ties."""
        self.entries = []
        self._latest = {}
        self.corrupt_lines = 0
        merged = []
        for p in self.read_paths:
            merged.extend(self._read_file(p))
        merged.extend(self._read_file(self.path))
        # stable: own-file exact ties win
        merged.sort(key=lambda e: (e.t, e.t_unix))
        for entry in merged:
            self._remember(entry)
        return len(self.entries)

    def _remember(self, entry: LedgerEntry) -> None:
        self.entries.append(entry)
        self._latest[entry.key] = entry

    def _append(self, entry: LedgerEntry) -> None:
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        # a kill mid-append can leave the file without its trailing
        # newline — gluing the next record onto that stump would corrupt
        # BOTH lines, so terminate the stump first
        needs_nl = False
        try:
            with open(self.path, "rb") as f:
                f.seek(-1, os.SEEK_END)
                needs_nl = f.read(1) != b"\n"
        except OSError:
            pass
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(("\n" if needs_nl else "")
                    + seal_line(asdict(entry)) + "\n")
            f.flush()
            os.fsync(f.fileno())

    # -- recording ---------------------------------------------------------
    def record(self, file: str, error: BaseException | None = None,
               failure_class: str = "", retries: int = 0,
               disposition: str = "quarantined", stage: str = "",
               feed=None, band=None, scan=None,
               message: str = "") -> LedgerEntry:
        """Append one event; returns the entry. ``error`` fills the
        type/message/digest fields; ``message`` overrides the text."""
        entry = LedgerEntry(
            unit=_unit(file, feed, band, scan),
            failure_class=failure_class,
            error=type(error).__name__ if error is not None else "",
            message=(message or (str(error) if error is not None
                                 else ""))[:_MSG_LIMIT],
            digest=traceback_digest(error),
            retries=int(retries),
            disposition=disposition,
            stage=stage,
            t=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            t_unix=time.time())
        with self._lock:
            self._append(entry)
            self._remember(entry)
        return entry

    def readmit(self, file: str, stage: str = "readmit") -> None:
        """Mark every quarantined unit of ``file`` live again (the
        ``--retry-quarantined`` action)."""
        with self._lock:
            keys = [k for k, e in self._latest.items()
                    if k[0] == file and e.disposition in _SKIPPING]
        for key in keys:
            self.record(file, feed=key[1], band=key[2], scan=key[3],
                        disposition="readmitted", stage=stage)

    # -- queries -----------------------------------------------------------
    def latest(self, file: str, feed=None, band=None,
               scan=None) -> LedgerEntry | None:
        """The winning (most recent) entry for this exact unit."""
        with self._lock:
            return self._latest.get((file, feed, band, scan))

    def is_quarantined(self, file: str, feed=None, band=None,
                       scan=None) -> bool:
        """True when the latest entry for this exact unit says skip."""
        with self._lock:
            e = self._latest.get((file, feed, band, scan))
        return e is not None and e.disposition in _SKIPPING

    def quarantined_files(self) -> set:
        """Files whose file-level unit is currently quarantined."""
        with self._lock:
            return {k[0] for k, e in self._latest.items()
                    if e.disposition in _SKIPPING}

    def summary(self) -> dict:
        """Counts by (failure_class, disposition) over the LATEST entry
        per unit — the current state, for the run-report line. (The
        full history stays in ``entries``: a campaign-old quarantine
        that was later re-admitted must not read as a rejection in
        today's report.)"""
        out: dict[str, int] = {}
        with self._lock:
            for e in self._latest.values():
                key = f"{e.failure_class or 'n/a'}:{e.disposition}"
                out[key] = out.get(key, 0) + 1
        return out
