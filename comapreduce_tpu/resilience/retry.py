"""Retry policy: bounded backoff + jitter, transient/permanent triage.

The split that matters operationally (``safe_hdf5_open`` already knew
it for lock contention): an ``OSError`` — an NFS hiccup, a file still
being copied in, a truncated read racing a writer — may succeed on the
next attempt, while a shape/validation error (``ValueError``,
``KeyError``: wrong schema, missing group) is the same data every time
and retrying it only burns wall time. h5py raises plain ``OSError``
for both unreadable *and* truncated files, which is exactly the
retry-worthy class (a genuinely corrupt file fails every attempt and
then lands in the quarantine ledger with its retry count).

Jitter is deterministic by ``(seed, key, attempt)`` — fleet ranks
hammering one NFS server desynchronise, while a re-run of the same
rank reproduces the same schedule (CI requirement: the chaos drills
assert on timing-independent outcomes).
"""

from __future__ import annotations

import logging
import random
import time
from dataclasses import dataclass

__all__ = ["RetryPolicy", "classify_error", "is_lock_error",
           "retry_call", "TRANSIENT_ERRORS", "PERMANENT_ERRORS"]

logger = logging.getLogger("comapreduce_tpu")

# OSError covers BlockingIOError / TimeoutError / ConnectionError and
# every h5py read failure (unable to open, truncated file, bad symbol
# table) — the I/O class worth a second attempt.
TRANSIENT_ERRORS = (OSError,)
# data/shape/schema problems: deterministic, never retried
PERMANENT_ERRORS = (ValueError, TypeError, KeyError, IndexError,
                    AttributeError, ArithmeticError, AssertionError)


def is_lock_error(exc: BaseException) -> bool:
    """True for HDF5/NFS lock contention (``safe_hdf5_open``'s own
    heuristic): the FILE is fine, another writer holds it — worth a
    retry, but never worth a durable quarantine."""
    if not isinstance(exc, OSError):
        return False
    msg = str(exc).lower()
    return (isinstance(exc, BlockingIOError) or "lock" in msg
            or "resource temporarily unavailable" in msg)


def classify_error(exc: BaseException) -> str:
    """``"transient"`` (worth retrying), ``"hang"`` (a cancelled
    deadline — retried like a transient, but ledgered ``rejected``
    rather than quarantined: a hang indicts the environment, not the
    file), ``"corrupt"`` (a committed artifact failed checksum
    verification — deterministic damage, never retried, triaged per
    artifact class: see ``resilience.integrity``) or ``"permanent"``
    (never retried).

    ``HangError`` and ``CorruptArtifactError`` subclass ``OSError`` so
    existing per-file nets catch them; both must therefore be checked
    BEFORE the transient class. Unknown exception types classify
    permanent: retrying a failure mode nobody has triaged just delays
    the quarantine entry that gets it triaged."""
    from comapreduce_tpu.resilience.integrity import CorruptArtifactError
    from comapreduce_tpu.resilience.watchdog import HangError

    if isinstance(exc, CorruptArtifactError):
        return "corrupt"
    if isinstance(exc, HangError):
        return "hang"
    if isinstance(exc, TRANSIENT_ERRORS):
        return "transient"
    if isinstance(exc, PERMANENT_ERRORS):
        return "permanent"
    return "permanent"


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff with deterministic jitter.

    ``max_retries`` is the number of *re*-attempts after the first try
    (0 disables retrying while keeping the classification/ledger
    plumbing). Delay before re-attempt ``a`` (1-based) is
    ``min(base_s * 2**(a-1), max_s) * (1 + jitter * u)`` with
    ``u ~ U[0, 1)`` seeded by ``(seed, key, a)``.
    """

    max_retries: int = 2
    base_s: float = 0.5
    max_s: float = 30.0
    jitter: float = 0.25
    seed: int = 0

    def delay_s(self, attempt: int, key: str = "") -> float:
        base = min(self.base_s * (2.0 ** max(attempt - 1, 0)), self.max_s)
        u = random.Random(f"{self.seed}:{key}:{attempt}").random()
        return base * (1.0 + self.jitter * u)


def retry_call(fn, policy: RetryPolicy | None = None, key: str = "",
               classify=classify_error, sleep=time.sleep,
               label: str = ""):
    """Call ``fn()`` under ``policy``; returns ``(result, retries)``.

    Retries only failures ``classify`` deems ``transient`` or ``hang``
    (a cancelled deadline may be a recovered NFS server — each retry
    gets a fresh deadline of its own). When attempts
    run out (or the failure is permanent) the ORIGINAL exception
    propagates, annotated with ``_retries`` (attempts burned) and
    ``_failure_class`` so the caller's ledger entry can report both
    without re-deriving them.

    ``sleep`` returning TRUTHY aborts the remaining schedule and
    re-raises immediately — pass a stop event's ``wait`` so a shutting-
    down consumer cancels the retries instead of burning them back-to-
    back against a dying filesystem (``time.sleep`` returns None, so
    the default never aborts).
    """
    policy = policy or RetryPolicy()
    attempt = 0
    while True:
        try:
            return fn(), attempt
        except Exception as exc:  # noqa: BLE001 — triaged via classify
            kind = classify(exc)
            exc._retries = attempt          # type: ignore[attr-defined]
            exc._failure_class = kind       # type: ignore[attr-defined]
            if kind not in ("transient", "hang") \
                    or attempt >= policy.max_retries:
                raise
            attempt += 1
            d = policy.delay_s(attempt, key=key)
            logger.warning("%s: %s %s (%s); retry %d/%d in %.2f s",
                           label or key or "retry_call", kind,
                           type(exc).__name__, exc, attempt,
                           policy.max_retries, d)
            if d > 0 and sleep(d):
                # the sleeper says stop (consumer shutting down):
                # abort the schedule, don't accelerate it
                raise
