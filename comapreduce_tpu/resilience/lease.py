"""Heartbeat-fenced file leases: the elastic campaign's claim primitive.

One lease file per work unit — ``lease.<key>.json`` in the run's state
directory — makes the filesystem itself the work queue: no coordinator
service, no locks a dead rank can hold forever. Three operations, each
built on a primitive the filesystem makes atomic:

- **claim** — publish the lease by hard-linking a fully-written,
  fsynced temp file onto the lease name (``os.link`` fails with
  ``EEXIST`` when the name is taken, so exactly one rank wins; the
  loser never sees a torn file because the content was complete and
  durable *before* the name existed). Same durability discipline as
  ``data/durable.py``: fsync data blocks first, then the directory.
- **steal** — reclaim an EXPIRED lease by ``os.rename``-ing it to a
  unique tombstone (POSIX guarantees exactly one racing renamer
  succeeds; the loser gets ``ENOENT``), then re-publishing with the
  generation bumped. Expiry is judged by the owner's heartbeat through
  :func:`~comapreduce_tpu.resilience.heartbeat.heartbeat_age_s` — the
  ONE staleness rule (``tools/watchdog_report`` and the straggler
  barrier use the same one) — so a paused-but-running zombie rank and
  a SIGKILLed rank look identical: no fresh beat, lease reclaimable.
- **commit** — fence-checked done marker. The committer rename-takes
  the current lease file, verifies it still carries ITS owner and
  generation, and only then publishes ``state: "done"``. A zombie
  whose lease was stolen finds a higher generation (or the thief's
  done marker) under the name and is REJECTED — the same monotonic-
  generation gate as ``data.writeback.Writeback``'s late-commit skip,
  applied to the work queue: a stolen-and-redone file can never be
  double-counted or clobbered by its original owner limping back.

Generations are monotonic per key: every claim/steal scans the key's
tombstones (a stealer that crashed mid-reclaim leaves its tombstone
behind, preserving the counter) and publishes ``max(seen) + 1``. A
torn lease file (a partial NFS copy — the claim path itself can never
tear one) parses as None and NEVER acts as a valid claim: it is
reclaimable once old enough, like any expired lease.
"""

from __future__ import annotations

import glob as _glob
import itertools
import json
import logging
import os
import re
import socket
import tempfile
import time
from typing import NamedTuple

from comapreduce_tpu.data.durable import durable_replace, fsync_path
from comapreduce_tpu.resilience.heartbeat import (heartbeat_stale,
                                                  read_heartbeats)

__all__ = ["Lease", "LeaseBoard", "lease_key", "lease_path", "read_lease"]

logger = logging.getLogger("comapreduce_tpu")

_KEY_RE = re.compile(r"[^A-Za-z0-9._-]+")


def lease_key(filename: str) -> str:
    """Stable slug for one work unit (its basename, sanitised)."""
    return _KEY_RE.sub("-", os.path.basename(filename)) or "unit"


def lease_path(directory: str, key: str) -> str:
    return os.path.join(directory or ".", f"lease.{key}.json")


def read_lease(path: str) -> dict | None:
    """Parse one lease/tombstone file; None for missing OR torn (a torn
    lease must never be treated as a live claim)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class Lease(NamedTuple):
    """One held claim (the token the committer's fence checks)."""

    key: str
    file: str
    owner: int
    generation: int
    path: str
    stolen_from: int | None = None


class LeaseBoard:
    """The per-run lease table: claim / steal / commit over one
    directory of ``lease.*.json`` files.

    ``heartbeat_dir`` is where the fleet's ``heartbeat.rank*.json``
    files live (defaults to ``directory``); ``lease_ttl_s`` is the
    owner-heartbeat age beyond which a lease is expired;
    ``steal_after_s`` additionally requires the lease FILE itself to be
    at least that old (0 = same as the TTL) — a fresh claim whose
    owner simply has not beaten yet must not be stolen instantly.
    """

    def __init__(self, directory: str, rank: int = 0,
                 heartbeat_dir: str | None = None,
                 lease_ttl_s: float = 60.0, steal_after_s: float = 0.0,
                 now=time.time):
        self.directory = directory or "."
        os.makedirs(self.directory, exist_ok=True)
        self.rank = int(rank)
        self.heartbeat_dir = heartbeat_dir or self.directory
        self.lease_ttl_s = float(lease_ttl_s)
        self.steal_after_s = float(steal_after_s) or self.lease_ttl_s
        self.now = now
        self.fence_rejects = 0
        self._nonce = itertools.count()

    # -- readers -------------------------------------------------------------
    def path_for(self, filename: str) -> str:
        return lease_path(self.directory, lease_key(filename))

    def state(self, filename: str) -> dict | None:
        return read_lease(self.path_for(filename))

    def is_done(self, filename: str) -> bool:
        st = self.state(filename)
        return st is not None and st.get("state") == "done"

    def expired(self, filename: str, now: float | None = None) -> bool:
        """True when the lease exists, is not done, and its owner shows
        no live heartbeat — the steal precondition. The rule is
        ``heartbeat_age_s`` out of ``[0, lease_ttl_s]`` (a FUTURE
        timestamp is no evidence of life, same as the stale-rank rule
        everywhere else), plus the lease file itself being at least
        ``steal_after_s`` old by local mtime."""
        path = self.path_for(filename)
        try:
            mtime = os.stat(path).st_mtime
        except OSError:
            return False  # no lease: claimable, not stealable
        now = self.now() if now is None else now
        if now - mtime < self.steal_after_s:
            return False
        st = read_lease(path)
        if st is None:
            # torn lease (partial copy): no valid owner to be alive —
            # reclaimable once past the age gate above
            return True
        if st.get("state") == "done":
            return False
        hb = read_heartbeats(self.heartbeat_dir).get(int(st.get("owner",
                                                                -1)))
        if heartbeat_stale(hb, now, self.lease_ttl_s):
            return True
        # the rank beats, but is it the CLAIMANT beating? A fresh pulse
        # from a different process (a same-rank restart — the rejoined
        # rank shadows its dead predecessor's heartbeat file) is no
        # evidence the claimant lives; without this, a claim leaked by
        # a killed rank is pinned un-stealable the moment its successor
        # starts beating. A split-brain claimant that somehow still
        # runs is fenced by the commit generation as usual.
        pid, host = st.get("pid"), st.get("host")
        if pid is not None and hb.get("pid") is not None:
            if int(hb["pid"]) != int(pid):
                return True
            if host and hb.get("host") and hb["host"] != host:
                return True
        return False

    # -- writers -------------------------------------------------------------
    def claim(self, filename: str) -> Lease | None:
        """Claim an unleased unit; None when the name is already taken
        (done, live, torn or mid-steal — the caller retries through
        :meth:`steal` once :meth:`expired` says so)."""
        key = lease_key(filename)
        path = lease_path(self.directory, key)
        if os.path.exists(path):
            return None
        gen = self._next_generation(path)
        payload = self._payload(key, filename, gen, state="claimed")
        if not self._publish(path, payload):
            return None  # lost the create race
        return Lease(key, filename, self.rank, gen, path)

    def steal(self, filename: str) -> Lease | None:
        """Reclaim an expired lease; exactly one racing stealer wins
        (the rename-take). None when not expired, raced away, or the
        owner committed first."""
        if not self.expired(filename):
            return None
        key = lease_key(filename)
        path = lease_path(self.directory, key)
        tomb = f"{path}.t{self.rank}.{os.getpid()}.{next(self._nonce)}"
        try:
            os.rename(path, tomb)  # atomic take: one winner per inode
        except OSError:
            return None
        old = read_lease(tomb)
        if old is not None and old.get("state") == "done":
            # raced a just-in-time commit: the work is done, put the
            # marker back (exclusive, in case a third party republished)
            self._restore(tomb, path)
            return None
        gen = max(int((old or {}).get("generation", 0)),
                  self._next_generation(path) - 1) + 1
        owner = None if old is None else old.get("owner")
        payload = self._payload(key, filename, gen, state="claimed",
                                stolen_from=owner)
        if self._publish(path, payload):
            os.unlink(tomb)
            logger.warning("lease %s: stolen from rank %s (gen %d -> %d)",
                           key, owner, gen - 1, gen)
            return Lease(key, filename, self.rank, gen, path,
                         stolen_from=owner)
        os.unlink(tomb)  # a racer re-published first; its generation
        # already accounted for ours through the tombstone scan
        return None

    def commit(self, lease: Lease) -> bool:
        """Publish the done marker iff the on-disk lease still carries
        ``lease``'s owner and generation — the zombie fence. False
        (and ``fence_rejects`` incremented) when the unit was stolen:
        the thief's work stands, ours is discarded."""
        path = lease.path
        tomb = f"{path}.c{self.rank}.{os.getpid()}.{next(self._nonce)}"
        try:
            os.rename(path, tomb)  # take the name to check-and-set
        except OSError:
            self.fence_rejects += 1  # vanished: a steal is in flight
            return False
        st = read_lease(tomb)
        if (st is None or st.get("state") != "claimed"
                or int(st.get("owner", -1)) != lease.owner
                or int(st.get("generation", -1)) != lease.generation):
            # not our claim any more (stolen — possibly already redone
            # and committed by the thief): restore whatever was there
            self._restore(tomb, path)
            self.fence_rejects += 1
            logger.warning(
                "lease %s: commit REJECTED at the generation fence "
                "(held gen %d, found %s gen %s) — the unit was stolen "
                "and this rank's late result is discarded", lease.key,
                lease.generation, (st or {}).get("state", "torn"),
                (st or {}).get("generation"))
            return False
        payload = dict(st, state="done", done_by=self.rank,
                       t_done_unix=self.now())
        if self._publish(path, payload):
            os.unlink(tomb)
            return True
        # a fresh claim landed in the take window: its generation scan
        # saw our tombstone, so it supersedes us — reject ourselves
        os.unlink(tomb)
        self.fence_rejects += 1
        return False

    def release(self, lease: Lease) -> bool:
        """Give a claim back (clean shutdown with unprocessed claims):
        the lease file is removed iff it is still ours."""
        tomb = f"{lease.path}.r{self.rank}.{os.getpid()}." \
               f"{next(self._nonce)}"
        try:
            os.rename(lease.path, tomb)
        except OSError:
            return False
        st = read_lease(tomb)
        if (st is None or int(st.get("owner", -1)) != lease.owner
                or int(st.get("generation", -1)) != lease.generation):
            self._restore(tomb, lease.path)
            return False
        os.unlink(tomb)
        return True

    # -- internals -----------------------------------------------------------
    def _payload(self, key, filename, gen, state, stolen_from=None):
        return {"key": key, "file": filename, "owner": self.rank,
                "generation": int(gen), "state": state,
                "pid": os.getpid(), "host": socket.gethostname(),
                "stolen_from": stolen_from,
                "t_claim_unix": self.now(),
                "t_wall": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime())}

    def _publish(self, path: str, payload: dict) -> bool:
        """Exclusive durable publication: write + fsync a temp file,
        then hard-link it onto the lease name (fails if taken), then
        fsync the directory — the name never exists before its content
        is complete and durable, so a reader can never see a torn
        claim of OUR making."""
        fd, tmp = tempfile.mkstemp(prefix=".lease.", suffix=".tmp",
                                   dir=self.directory)
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            fsync_path(tmp)
            try:
                os.link(tmp, path)
            except FileExistsError:
                return False
            except OSError:
                # no hard links (exotic FS): degrade to replace — the
                # durable data fsync above still prevents torn content,
                # at the cost of last-writer-wins on a true tie
                durable_replace(tmp, path)
                tmp = ""
                return True
            self._fsync_dir()
            return True
        finally:
            if tmp:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass

    def _restore(self, tomb: str, path: str) -> None:
        """Put a taken lease file back under its name (exclusive — a
        republished name wins over the restore) and drop the tombstone."""
        try:
            os.link(tomb, path)
        except OSError:
            pass
        try:
            os.unlink(tomb)
        except OSError:
            pass

    def _next_generation(self, path: str) -> int:
        """1 + the highest generation among the key's tombstones (a
        crashed stealer's tombstone preserves the counter; the live
        lease itself, when present, is handled by the caller)."""
        gen = 0
        for t in _glob.glob(path + ".*"):
            st = read_lease(t)
            if st is not None:
                gen = max(gen, int(st.get("generation", 0)))
        return gen + 1

    def _fsync_dir(self) -> None:
        flags = os.O_RDONLY | getattr(os, "O_DIRECTORY", 0)
        try:
            fd = os.open(self.directory, flags)
        except OSError:
            return
        try:
            os.fsync(fd)
        except OSError:
            pass
        finally:
            os.close(fd)
