"""Numerical tripwires: NaN/Inf containment before the CG solve.

One NaN sample entering the destriper poisons every inner product of
the CG within an iteration (the breakdown guard then freezes the whole
system — the *map* survives but that band's solve is dead). The
destriper's own convention already has the answer: a zero-weight
sample contributes nothing anywhere, *provided its value is finite*
(``0 * inf`` is NaN). So the tripwire masks every non-finite TOD or
weight sample to ``value 0, weight 0`` — exactly equivalent to the
clean solve with those samples zero-weighted, which is what the chaos
drill (``tools/check_resilience.py``) asserts byte-for-byte.

``scrub_tod`` is pure ``jnp`` elementwise work (one fused pass under
jit, negligible next to a single CG iteration) and is applied at the
entry of both ``destripe`` and ``destripe_planned`` — defense in
depth behind the host-side scrub in ``leveldata.read_comap_data``
(which also *records* the event in the quarantine ledger; a jitted
trace cannot).
"""

from __future__ import annotations

import numpy as np

__all__ = ["scrub_tod", "scrub_tod_host", "finite_fraction"]


def scrub_tod(tod, weights):
    """Mask non-finite samples to (0, 0): returns ``(tod', weights')``.

    jnp in, jnp out; shapes preserved; safe under jit/shard_map (pure
    elementwise). A sample is bad when its TOD *or* its weight is
    non-finite — a NaN weight silently zeroes nothing and poisons
    ``sum_w`` otherwise.
    """
    import jax.numpy as jnp

    ok = jnp.isfinite(tod) & jnp.isfinite(weights)
    return jnp.where(ok, tod, 0.0), jnp.where(ok, weights, 0.0)


def scrub_tod_host(tod: np.ndarray, weights: np.ndarray):
    """Host (numpy) twin of :func:`scrub_tod`: returns
    ``(tod', weights', n_masked)`` so the caller can ledger-record the
    event with a count. Copies only when something is actually bad."""
    ok = np.isfinite(tod) & np.isfinite(weights)
    n_bad = int(ok.size - np.count_nonzero(ok))
    if n_bad == 0:
        return tod, weights, 0
    return (np.where(ok, tod, 0.0).astype(tod.dtype, copy=False),
            np.where(ok, weights, 0.0).astype(weights.dtype, copy=False),
            n_bad)


def finite_fraction(x) -> float:
    """Fraction of finite samples (host scalar) — the cheap health
    check logged per file/band."""
    x = np.asarray(x)
    if x.size == 0:
        return 1.0
    return float(np.count_nonzero(np.isfinite(x))) / x.size
