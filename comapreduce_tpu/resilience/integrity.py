"""End-to-end artifact integrity: checksums, sidecars, verify-on-read.

Every durable artifact the campaign writes — Level-2 HDF5 checkpoints,
BlockCache disk spill, solver npz snapshots, epoch FITS products, tile
blobs, and the JSONL/JSON control state — is committed with a sha256
manifest, and every load boundary re-verifies before trusting the
bytes.  Atomicity (``data.durable``) guarantees the *rename* is
all-or-nothing; this module guarantees the *content* under the name is
the content that was committed.  A mismatch raises
:class:`CorruptArtifactError`, which :func:`resilience.retry.
classify_error` maps to the non-retryable ``corrupt`` class so the
per-file safety nets triage it (unlink-and-rebuild for re-derivable
state, quarantine-with-evidence for Level-1 inputs) instead of
retrying a deterministic failure.

Two manifest shapes cover every artifact:

**Sidecar** (``<name>.s256``) — for opaque binary payloads (HDF5, npz,
pickle spill).  A small JSON document next to the artifact::

    {"schema": 1, "kind": "checkpoint", "algo": "sha256",
     "digests": ["<newest>", "<previous>", ...], "size": 12345}

``digests`` keeps a short history (newest first, capped at
:data:`HISTORY`): the sidecar is committed *before* the payload rename
inside :func:`committed_replace`, so a crash between the two renames
leaves the OLD payload under a NEW sidecar — the old digest is still
in the history, and verification passes.  Old-or-new, never
unverifiable.

**Embedded** — for JSON/JSONL state the pipeline already parses.  A
``_sha256`` key holding the digest of the canonical serialisation of
the document *without* that key (``json.dumps(..., sort_keys=True,
separators=(",", ":"))``).  :func:`seal_json` adds it,
:func:`check_json` verifies and strips it.  Documents written before
this scheme existed have no ``_sha256`` and verify as *unverified*
(``None``), never as corrupt — the scheme is additive.

Verification is pure host-side hashing (hashlib over file bytes):
it adds zero jax dispatches, so a clean campaign's compile profile is
byte-identical with verification on or off.  The
``COMAP_VERIFY_READS`` environment knob (default on) exists for
forensics — turning it off makes readers trust bytes again, e.g. to
copy a corrupt artifact out of a run dir for inspection.

Offline, ``tools/campaign_fsck.py`` walks a whole run directory
through these same primitives.  Runbook: docs/OPERATIONS.md §20.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os

from ..data import durable as _durable
from ..telemetry.core import TELEMETRY

logger = logging.getLogger(__name__)

__all__ = [
    "CorruptArtifactError", "verify_enabled", "sha256_path",
    "sidecar_path", "read_sidecar", "write_sidecar",
    "committed_replace", "refresh_sidecar", "drop_sidecar",
    "verify_file", "seal_json", "check_json", "seal_line",
    "check_line",
]

#: suffix of sidecar manifests (``map_band0.fits`` → ``map_band0.fits.s256``)
SIDECAR_SUFFIX = ".s256"

#: digest generations kept in a sidecar.  One would satisfy a clean
#: commit; the history absorbs the sidecar-first commit window (crash
#: after sidecar rename, before payload rename → old payload must
#: still verify) and repeated crashed commits in a row.
HISTORY = 4

_CHUNK = 1 << 20  # 1 MiB read chunks for hashing

#: embedded-checksum key for JSON documents / JSONL lines
SEAL_KEY = "_sha256"


class CorruptArtifactError(OSError):
    """Committed artifact whose bytes no longer match their manifest.

    An :class:`OSError` subclass so it rides the existing per-file
    safety nets (``TRANSIENT_ERRORS`` catch arcs), but
    ``classify_error`` recognises it FIRST and returns ``"corrupt"``:
    deterministic damage, never retried.  Carries the evidence the
    ledger records (expected vs actual digest)."""

    def __init__(self, path: str, kind: str = "",
                 expected: str = "", actual: str = "",
                 detail: str = ""):
        self.path = path
        self.kind = kind
        self.expected = expected
        self.actual = actual
        msg = f"corrupt artifact {path!r}"
        if kind:
            msg += f" (kind={kind})"
        if expected or actual:
            msg += (f": sha256 {actual[:12] or '?'} != committed "
                    f"{expected[:12] or '?'}")
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


def verify_enabled() -> bool:
    """Verify-on-read master switch: ``COMAP_VERIFY_READS`` (default
    on; ``0``/``false``/``off``/``no`` disable)."""
    v = os.environ.get("COMAP_VERIFY_READS", "1").strip().lower()
    return v not in ("0", "false", "off", "no")


def sha256_path(path: str) -> str:
    """Hex sha256 of a file's bytes, chunked (constant memory)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(_CHUNK)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def sidecar_path(path: str) -> str:
    return os.fspath(path) + SIDECAR_SUFFIX


def read_sidecar(path: str) -> dict | None:
    """The sidecar manifest for artifact ``path``, or None when
    absent/torn/foreign-schema (an unreadable sidecar means the
    artifact is *unverified*, not corrupt — sidecars are advisory
    metadata; the payload's own commit protocol guarantees its
    atomicity)."""
    sc = sidecar_path(path)
    try:
        with open(sc, "r") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("schema") != 1:
        return None
    digs = doc.get("digests")
    if not isinstance(digs, list) or not all(
            isinstance(d, str) for d in digs):
        return None
    return doc


def write_sidecar(payload: str, dst: str, kind: str,
                  durable: bool = True) -> dict:
    """Commit a sidecar for artifact ``dst`` recording the digest of
    ``payload`` (usually the tmp file about to be renamed onto
    ``dst``).  Merges the existing sidecar's digest history so the
    sidecar-first commit window keeps the previous generation
    verifiable.  Atomic + durable like every other commit."""
    digest = sha256_path(payload)
    prev = read_sidecar(dst)
    history = [digest]
    if prev:
        for d in prev.get("digests", []):
            if d not in history:
                history.append(d)
    doc = {"schema": 1, "kind": kind, "algo": "sha256",
           "digests": history[:HISTORY],
           "size": os.path.getsize(payload)}
    sc = sidecar_path(dst)
    tmp = sc + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True)
    try:
        # module attribute, not a from-import: fault-injection tests
        # patch data.durable.durable_replace and the sidecar commit
        # must honour the same fault as the payload commit
        _durable.durable_replace(tmp, sc, durable=durable)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return doc


def committed_replace(tmp: str, dst: str, kind: str,
                      durable: bool = True, chaos=None) -> None:
    """The integrity-aware commit: sidecar first, then the payload's
    fsync-before-rename.  Ordering is the crash-safety argument —
    whatever point a SIGKILL lands, the payload under ``dst`` has its
    digest in the sidecar's history (old payload + old sidecar, old
    payload + new sidecar via history, or new payload + new sidecar).
    ``chaos`` (a ``ChaosMonkey`` or None) gets a post-commit
    ``maybe_bit_rot(dst)`` shot so injected rot is always *detectable*
    rot (flipped after hashing, like real media decay)."""
    write_sidecar(tmp, dst, kind, durable=durable)
    _durable.durable_replace(tmp, dst, durable=durable)
    if chaos is not None:
        chaos.maybe_bit_rot(dst)


def refresh_sidecar(dst: str, kind: str = "",
                    durable: bool = False) -> None:
    """Re-seal an artifact that was (legitimately) mutated in place —
    e.g. ``HDF5Store.write(atomic=False)`` appending groups to an
    existing checkpoint.  Only rewrites when a sidecar already exists
    (in-place writers of never-sealed files stay sidecar-less), so a
    stale manifest can never condemn honestly-updated bytes."""
    prev = read_sidecar(dst)
    if prev is None:
        return
    write_sidecar(dst, dst, kind or str(prev.get("kind", "")),
                  durable=durable)


def drop_sidecar(path: str) -> None:
    """Remove the sidecar alongside a condemned/unlinked artifact."""
    try:
        os.unlink(sidecar_path(path))
    except OSError:
        pass


def verify_file(path: str, kind: str = "",
                required: bool = False) -> bool | None:
    """Verify artifact ``path`` against its sidecar.

    Returns True (digest in the committed history), None (no usable
    sidecar — unverified; unless ``required``), or raises
    :class:`CorruptArtifactError` on mismatch (counting an
    ``integrity.violations`` telemetry tick first, so /metrics shows
    ``comap_integrity_violations_total`` moving).  Honors
    :func:`verify_enabled` — disabled verification reads as
    unverified, never as OK."""
    if not verify_enabled():
        return None
    doc = read_sidecar(path)
    if doc is None:
        if required:
            raise CorruptArtifactError(
                path, kind=kind, detail="required sidecar missing")
        return None
    actual = sha256_path(path)
    digests = doc.get("digests", [])
    if actual in digests:
        return True
    TELEMETRY.counter("integrity.violations",
                      kind=str(doc.get("kind", kind) or kind))
    raise CorruptArtifactError(
        path, kind=str(doc.get("kind", "")) or kind,
        expected=digests[0] if digests else "", actual=actual)


# ---------------------------------------------------------------- JSON

def _canonical(doc: dict) -> bytes:
    body = {k: v for k, v in doc.items() if k != SEAL_KEY}
    return json.dumps(body, sort_keys=True, default=str,
                      separators=(",", ":")).encode("utf-8")


def seal_json(doc: dict) -> dict:
    """Return ``doc`` with an embedded ``_sha256`` over its canonical
    serialisation (sorted keys, tight separators, minus the seal key
    itself).  Idempotent; does not mutate the input."""
    out = dict(doc)
    out[SEAL_KEY] = hashlib.sha256(_canonical(out)).hexdigest()
    return out


def check_json(doc: dict) -> tuple[dict, bool | None]:
    """Verify an embedded-seal document.  Returns ``(body, verdict)``
    where ``body`` is the document WITHOUT the seal key and
    ``verdict`` is True (seal matches), None (no seal — legacy
    document, unverified), or False (mismatch — the caller decides
    whether that's a drop, a None, or a raise; a tick is counted
    here either way)."""
    if SEAL_KEY not in doc:
        return doc, None
    body = {k: v for k, v in doc.items() if k != SEAL_KEY}
    if not verify_enabled():
        return body, None
    want = doc.get(SEAL_KEY)
    got = hashlib.sha256(_canonical(doc)).hexdigest()
    if got == want:
        return body, True
    TELEMETRY.counter("integrity.violations", kind="json")
    return body, False


def seal_line(doc: dict) -> str:
    """One sealed JSONL line (no trailing newline)."""
    return json.dumps(seal_json(doc), default=str,
                      separators=(",", ":"))


def check_line(raw: str) -> tuple[dict | None, bool | None]:
    """Parse + verify one JSONL line.  ``(None, False)`` when the line
    is unparseable or fails its seal; otherwise ``(body, verdict)``
    as :func:`check_json`."""
    try:
        doc = json.loads(raw)
    except ValueError:
        return None, False
    if not isinstance(doc, dict):
        return None, False
    body, verdict = check_json(doc)
    if verdict is False:
        return None, False
    return body, verdict
