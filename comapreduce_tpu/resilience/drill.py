"""The chaos drill: inject every fault class, assert the promises hold.

This is the executable form of the resilience layer's contract
(ISSUE 2 + ISSUE 3 acceptance criteria), run by
``tools/check_resilience.py`` and ``bench.py --config resilience``:

1. a chaos run (read error + truncated file + NaN burst + slow read +
   first-attempt flake + HANGING read injected over a synthetic
   fixture set) completes with no unhandled exception;
2. every injected fault appears in the quarantine ledger with the
   correct classification (read error/truncate -> ``transient``
   quarantines, NaN burst -> ``numerical``/``masked``, flake ->
   ``transient``/``recovered``, hang -> ``hang``/``rejected`` after a
   ``hang``/``stalled`` soft warning);
3. the destriped map from the chaos run is byte-identical to the
   clean run's map with the faulted units zero-weighted (dead and
   hung files dropped, NaN-touched samples at weight 0);
4. a second pass consults the ledger: quarantined files are skipped
   without a read, the HUNG file is re-attempted (rejected, not
   quarantined — a hang indicts the environment), and
   ``retry_quarantined`` re-admits exactly the quarantined set;
5. the watchdog honoured its deadline budget: each hung read was
   cancelled within ``hard + grace`` seconds (every retry gets its
   own fresh budget), and the run never joined a stuck read;
6. the async writeback path (ISSUE 5): a ``write_stall`` fault on the
   background writer thread is cancelled by the ``writeback.write``
   hard deadline within ``hard + grace``, ledgered ``hang``/
   ``rejected`` (environment, never the file), the flush barrier
   surfaces the failure, committed checkpoints are never dropped or
   reordered (the surviving file holds its LAST submitted generation,
   complete), and the abandoned writer's late commit is skipped at the
   generation gate.

Everything is deterministic by seed (chaos decisions, jitter, synthetic
data), so a CI failure reproduces locally bit-for-bit. (Deadline
checks bound wall time from ABOVE only — cancels must not be late;
nothing asserts a minimum, so fast machines stay green.)
"""

from __future__ import annotations

import logging
import os
import time

import numpy as np

__all__ = ["run_drill"]

logger = logging.getLogger("comapreduce_tpu")


def _write_level2(path: str, seed: int, F: int = 2, T: int = 600) -> None:
    """Minimal single-band Level-2 store the destriper reader accepts
    (same schema as the pipeline's checkpoint output)."""
    from comapreduce_tpu.data.hdf5io import HDF5Store

    rng = np.random.default_rng(seed)
    store = HDF5Store(name="l2")
    tod = (rng.normal(size=(F, 1, T))
           + np.sin(np.arange(T) / 37.0)).astype(np.float32)
    store["averaged_tod/tod"] = tod
    store["averaged_tod/weights"] = np.ones((F, 1, T), np.float32)
    store["averaged_tod/scan_edges"] = np.array([[0, T]], np.int64)
    ra = 170.0 + 0.5 * rng.random((F, T))
    dec = 52.0 + 0.5 * rng.random((F, T))
    store["spectrometer/pixel_pointing/pixel_ra"] = ra
    store["spectrometer/pixel_pointing/pixel_dec"] = dec
    store["spectrometer/pixel_pointing/pixel_az"] = ra
    store["spectrometer/pixel_pointing/pixel_el"] = dec
    store.set_attrs("comap", "source", "co2,sky")
    store.set_attrs("comap", "obsid", seed)
    store.write(path)


def _read(files, wcs, resilience=None, prefetch: int = 0):
    from comapreduce_tpu.mapmaking.leveldata import read_comap_data

    return read_comap_data(files, band=0, wcs=wcs, offset_length=50,
                           medfilt_window=51, use_calibration=False,
                           prefetch=prefetch, resilience=resilience)


def _solve(data):
    from comapreduce_tpu.cli.run_destriper import solve_band

    return solve_band(data, offset_length=50, n_iter=50, threshold=1e-5)


def run_drill(workdir: str, seed: int = 0, n_files: int = 7,
              prefetch: int = 2, hard_deadline_s: float = 0.4,
              soft_deadline_s: float = 0.1,
              grace_s: float = 1.0) -> dict:
    """Run the full drill in ``workdir``; returns the evidence dict.

    Raises ``AssertionError`` (with a named criterion) on any broken
    promise — the CI contract is 'exit 0 means all five held'.
    """
    from comapreduce_tpu.mapmaking.wcs import WCS
    from comapreduce_tpu.resilience import (QuarantineLedger, Resilience,
                                            Watchdog, parse_deadlines)
    from comapreduce_tpu.resilience.chaos import ChaosMonkey
    from comapreduce_tpu.resilience.retry import RetryPolicy

    t0 = time.perf_counter()
    os.makedirs(workdir, exist_ok=True)
    files = []
    for i in range(n_files):
        path = os.path.join(workdir, f"Level2_comap-{i:04d}.hd5")
        if not os.path.exists(path):
            _write_level2(path, seed=1000 + seed * 10 + i)
        files.append(path)
    wcs = WCS.from_field((170.25, 52.25), (1.0 / 60, 1.0 / 60), (64, 64))

    # one fault of every class, each aimed at a known file; the hang
    # blocks far past the hard deadline (abandoned workers are released
    # in the finally below so they die promptly, not after hang_s)
    spec = ("read_error@0001,truncate@0002,flaky@0003,"
            "nan_burst@0004,slow_read@0000,hang@0005")
    monkey = ChaosMonkey(spec, seed=seed, slow_s=0.01, burst_frac=0.1,
                         hang_s=60.0)
    ledger_path = os.path.join(workdir, "quarantine.jsonl")
    if os.path.exists(ledger_path):
        os.unlink(ledger_path)
    ledger = QuarantineLedger(ledger_path)
    watchdog = Watchdog(
        deadlines=parse_deadlines(
            f"ingest.read={soft_deadline_s}/{hard_deadline_s}"),
        ledger=ledger, grace_s=grace_s)
    res = Resilience(ledger=ledger,
                     retry=RetryPolicy(max_retries=1, base_s=0.0,
                                       seed=seed),
                     chaos=monkey, watchdog=watchdog)

    try:
        return _run_drill_criteria(
            workdir, files, wcs, res, monkey, ledger_path, watchdog,
            hard_deadline_s, grace_s, prefetch, n_files, t0, seed=seed)
    finally:
        monkey.release()


def _run_drill_criteria(workdir, files, wcs, res, monkey, ledger_path,
                        watchdog, hard_deadline_s, grace_s, prefetch,
                        n_files, t0, seed=0) -> dict:
    from comapreduce_tpu.resilience import QuarantineLedger, Resilience

    # -- 1. chaos run completes ------------------------------------------
    data_chaos = _read(files, wcs, resilience=res, prefetch=prefetch)
    result_chaos = _solve(data_chaos)
    assert np.isfinite(
        np.asarray(result_chaos.destriped_map)).all(), \
        "criterion 1: chaos-run map contains non-finite pixels"

    dead = [files[1], files[2]]          # read_error, truncate
    hung = [files[5]]                    # hang (cancelled, rejected)
    survivors = [f for f in files if f not in dead and f not in hung]
    assert data_chaos.files == survivors, \
        f"criterion 1: expected survivors {survivors}, " \
        f"got {data_chaos.files}"

    # -- 2. every injected fault is ledgered, correctly classified ------
    ledger = QuarantineLedger(ledger_path)  # re-read from disk
    by_file = {}
    for e in ledger.entries:
        by_file.setdefault(os.path.basename(e.unit["file"]), []).append(e)

    def _has(fname, failure_class, disposition):
        return any(e.failure_class == failure_class
                   and e.disposition == disposition
                   for e in by_file.get(os.path.basename(fname), []))

    assert _has(files[1], "transient", "quarantined"), \
        "criterion 2: injected read_error not quarantined as transient"
    assert _has(files[2], "transient", "quarantined"), \
        "criterion 2: injected truncate not quarantined as transient"
    assert _has(files[3], "transient", "recovered"), \
        "criterion 2: flaky read not recorded as recovered-by-retry"
    assert _has(files[4], "numerical", "masked"), \
        "criterion 2: NaN burst not recorded as numerical/masked"
    assert _has(files[5], "hang", "stalled"), \
        "criterion 2: hung read fired no soft-deadline 'stalled' event"
    assert _has(files[5], "hang", "rejected"), \
        "criterion 2: cancelled hang not ledgered as hang/rejected " \
        "(a hang indicts the environment — it must never quarantine)"
    injected_kinds = {k for _, k in monkey.injected}
    assert injected_kinds >= {"read_error", "truncate", "flaky",
                              "nan_burst", "slow_read", "hang"}, \
        f"chaos harness fired only {sorted(injected_kinds)}"

    # -- 3. chaos map == clean map with faulted units zero-weighted -----
    # The reference run reads clean copies of the SURVIVING files with
    # the burst unit (file 4's (feed, start, n), reconstructed from the
    # monkey's own deterministic placement) zero-weighted at the source:
    # value 0, weight 0 — exactly what the tripwire turns the NaNs into,
    # so every downstream operator (median filter included) sees
    # identical inputs and the maps must agree to the last byte.
    import h5py
    import shutil

    ref_dir = os.path.join(workdir, "ref")
    os.makedirs(ref_dir, exist_ok=True)
    ref_files = []
    n_masked = 0
    for f in survivors:
        dst = os.path.join(ref_dir, os.path.basename(f))
        shutil.copy2(f, dst)
        if f == files[4]:
            with h5py.File(dst, "a") as h:
                shape = h["averaged_tod/tod"].shape    # (F, B, T)
                feed, start, n = monkey.burst_coords(f, shape)
                h["averaged_tod/tod"][feed, ..., start:start + n] = 0.0
                h["averaged_tod/weights"][feed, ...,
                                          start:start + n] = 0.0
                n_masked = n
        ref_files.append(dst)
    assert n_masked > 0, "criterion 3: NaN burst masked no samples"
    data_ref = _read(ref_files, wcs)
    assert data_ref.tod.size == data_chaos.tod.size, \
        "criterion 3: chaos run changed the sample stream shape"
    result_ref = _solve(data_ref)
    identical = np.array_equal(np.asarray(result_chaos.destriped_map),
                               np.asarray(result_ref.destriped_map))
    assert identical, \
        "criterion 3: chaos map != clean map with faulted units " \
        "zero-weighted"

    # -- 4. resume consults the ledger; retry_quarantined re-admits -----
    # the HUNG file is rejected, not quarantined: resume re-attempts it
    expected_admit = [f for f in files if f not in dead]
    res2 = Resilience(ledger=QuarantineLedger(ledger_path))
    admitted = [f for f in files if res2.admit(f)]
    assert admitted == expected_admit, \
        f"criterion 4: resume admitted {admitted}, " \
        f"expected {expected_admit}"
    res3 = Resilience(ledger=QuarantineLedger(ledger_path),
                      retry_quarantined=True)
    readmitted = [f for f in files if res3.admit(f)]
    assert readmitted == files, \
        "criterion 4: retry_quarantined did not re-admit the " \
        "quarantined set"
    # ... and exactly the quarantined set was re-admitted
    assert sorted(res3._readmitted) == sorted(dead), \
        f"criterion 4: re-admitted {sorted(res3._readmitted)}, " \
        f"expected {sorted(dead)}"

    # -- 5. deadline budget honoured -------------------------------------
    # every cancelled attempt must land within hard + grace of its own
    # start (the watchdog's audit trail records per-event elapsed); one
    # event per attempt (retry = a fresh budget, so 2 with max_retries=1)
    hangs = [e for e in watchdog.events if e[0] == "hang"]
    assert len(hangs) == 2, \
        f"criterion 5: expected 2 cancelled hang attempts (1 retry), " \
        f"saw {len(hangs)}: {hangs}"
    late = [e for e in hangs if e[3] > hard_deadline_s + grace_s]
    assert not late, \
        f"criterion 5: cancel latency exceeded hard deadline " \
        f"{hard_deadline_s} s + grace {grace_s} s: {late}"

    # -- 6. async writeback: stalled writer cancelled, ordering kept ----
    wb_evidence = _writeback_drill(workdir, res, seed=seed, soft_s=0.1,
                                   hard_s=hard_deadline_s,
                                   grace_s=grace_s)

    return {
        **wb_evidence,
        "n_files": n_files,
        "injected": sorted({(os.path.basename(f), k)
                            for f, k in monkey.injected}),
        "quarantined": sorted(os.path.basename(f)
                              for f in ledger.quarantined_files()),
        "ledger_summary": ledger.summary(),
        "n_masked_samples": n_masked,
        "map_byte_identical": bool(identical),
        "cg_iters_chaos": int(result_chaos.n_iter),
        "hang_cancel_s": [round(e[3], 4) for e in hangs],
        "hard_deadline_s": hard_deadline_s,
        "hang_grace_s": grace_s,
        "watchdog_events": [list(e) for e in watchdog.events][:50],
        "wall_s": round(time.perf_counter() - t0, 3),
    }


def _writeback_drill(workdir, res, seed, soft_s, hard_s, grace_s) -> dict:
    """Criterion 6: async writeback under a ``write_stall`` fault.

    A stalled background writer must be cancelled by the
    ``writeback.write`` hard deadline (within ``hard + grace``),
    ledgered ``hang``/``rejected``, must never drop or reorder a
    committed checkpoint, and its abandoned late commit must be skipped
    at the generation gate. Returns the evidence fields merged into the
    drill record."""
    import h5py

    from comapreduce_tpu.data.hdf5io import HDF5Store
    from comapreduce_tpu.data.writeback import Writeback
    from comapreduce_tpu.resilience.chaos import ChaosMonkey
    from comapreduce_tpu.resilience.watchdog import (HangError, Watchdog,
                                                     parse_deadlines)

    wb_dir = os.path.join(workdir, "writeback")
    os.makedirs(wb_dir, exist_ok=True)
    ok = os.path.join(wb_dir, "Level2_ok.hd5")
    victim = os.path.join(wb_dir, "Level2_stall.hd5")
    for p in (ok, victim):
        if os.path.exists(p):
            os.unlink(p)

    def payload(gen: int) -> dict:
        store = HDF5Store(name="wb-drill")
        store["averaged_tod/tod"] = np.full((2, 64), float(gen),
                                            np.float32)
        store["meta/gen"] = np.array([gen])
        return store.export_payload()

    monkey = ChaosMonkey("write_stall@stall", seed=seed, hang_s=60.0)
    watchdog = Watchdog(
        deadlines=parse_deadlines(f"writeback.write={soft_s}/{hard_s}"),
        ledger=res.ledger, grace_s=grace_s)
    wb = Writeback(depth=4, watchdog=watchdog, chaos=monkey)
    try:
        # ordering: three generations for the healthy file, committed
        # in submission order; the survivor must hold the LAST one
        for gen in (1, 2, 3):
            wb.submit_store(ok, payload(gen))
        wb.flush(ok)
        with h5py.File(ok, "r") as h:
            got = int(h["meta/gen"][0])
            torn = not (h["averaged_tod/tod"][...] == float(got)).all()
        assert got == 3 and not torn, \
            f"criterion 6: committed checkpoint dropped/reordered " \
            f"(gen {got}, torn={torn})"

        err = None
        try:
            wb.submit_store(victim, payload(1))
            wb.flush(victim)
        except OSError as exc:    # HangError is an OSError subclass
            err = exc
        assert isinstance(err, HangError), \
            "criterion 6: stalled writeback was not cancelled by the " \
            "watchdog hard deadline"
        res.record_failure(victim, err, stage="writeback.write",
                           may_quarantine=False)
        hangs = [e for e in watchdog.events if e[0] == "hang"]
        late = [e for e in hangs if e[3] > hard_s + grace_s]
        assert hangs and not late, \
            f"criterion 6: writeback cancel latency exceeded " \
            f"{hard_s} + {grace_s} s: {late}"
        assert not os.path.exists(victim), \
            "criterion 6: a cancelled write must not commit"
        entries = [e for e in res.ledger.entries
                   if e.unit["file"] == victim]
        assert any(e.failure_class == "hang" and
                   e.disposition == "rejected" for e in entries), \
            "criterion 6: stalled write not ledgered hang/rejected"

        # the abandoned writer, released, must SKIP its late commit
        monkey.release()
        deadline = time.perf_counter() + 10.0
        while wb.stats["late_skips"] < 1 and \
                time.perf_counter() < deadline:
            time.sleep(0.02)
        assert wb.stats["late_skips"] >= 1, \
            "criterion 6: abandoned writer's late commit not skipped"
        assert not os.path.exists(victim), \
            "criterion 6: late commit landed after cancellation"
        return {
            "writeback_hang_cancel_s": [round(e[3], 4) for e in hangs],
            "writeback_writes": wb.stats["writes"],
            "writeback_late_skips": wb.stats["late_skips"],
        }
    finally:
        monkey.release()
        wb.close()
