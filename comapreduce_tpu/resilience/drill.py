"""The chaos drill: inject every fault class, assert the promises hold.

This is the executable form of the resilience layer's contract
(ISSUE 2 acceptance criteria), run by ``tools/check_resilience.py``
and ``bench.py --config resilience``:

1. a chaos run (read error + truncated file + NaN burst + slow read +
   first-attempt flake injected over a synthetic fixture set) completes
   with no unhandled exception;
2. every injected fault appears in the quarantine ledger with the
   correct classification (read error/truncate -> ``transient``
   quarantines, NaN burst -> ``numerical``/``masked``, flake ->
   ``transient``/``recovered``);
3. the destriped map from the chaos run is byte-identical to the
   clean run's map with the faulted units zero-weighted (dead files
   dropped, NaN-touched samples at weight 0);
4. a second pass consults the ledger: quarantined files are skipped
   without a read, and ``retry_quarantined`` re-admits exactly the
   quarantined set.

Everything is deterministic by seed (chaos decisions, jitter, synthetic
data), so a CI failure reproduces locally bit-for-bit.
"""

from __future__ import annotations

import logging
import os
import time

import numpy as np

__all__ = ["run_drill"]

logger = logging.getLogger("comapreduce_tpu")


def _write_level2(path: str, seed: int, F: int = 2, T: int = 600) -> None:
    """Minimal single-band Level-2 store the destriper reader accepts
    (same schema as the pipeline's checkpoint output)."""
    from comapreduce_tpu.data.hdf5io import HDF5Store

    rng = np.random.default_rng(seed)
    store = HDF5Store(name="l2")
    tod = (rng.normal(size=(F, 1, T))
           + np.sin(np.arange(T) / 37.0)).astype(np.float32)
    store["averaged_tod/tod"] = tod
    store["averaged_tod/weights"] = np.ones((F, 1, T), np.float32)
    store["averaged_tod/scan_edges"] = np.array([[0, T]], np.int64)
    ra = 170.0 + 0.5 * rng.random((F, T))
    dec = 52.0 + 0.5 * rng.random((F, T))
    store["spectrometer/pixel_pointing/pixel_ra"] = ra
    store["spectrometer/pixel_pointing/pixel_dec"] = dec
    store["spectrometer/pixel_pointing/pixel_az"] = ra
    store["spectrometer/pixel_pointing/pixel_el"] = dec
    store.set_attrs("comap", "source", "co2,sky")
    store.set_attrs("comap", "obsid", seed)
    store.write(path)


def _read(files, wcs, resilience=None, prefetch: int = 0):
    from comapreduce_tpu.mapmaking.leveldata import read_comap_data

    return read_comap_data(files, band=0, wcs=wcs, offset_length=50,
                           medfilt_window=51, use_calibration=False,
                           prefetch=prefetch, resilience=resilience)


def _solve(data):
    from comapreduce_tpu.cli.run_destriper import solve_band

    return solve_band(data, offset_length=50, n_iter=50, threshold=1e-5)


def run_drill(workdir: str, seed: int = 0, n_files: int = 6,
              prefetch: int = 2) -> dict:
    """Run the full drill in ``workdir``; returns the evidence dict.

    Raises ``AssertionError`` (with a named criterion) on any broken
    promise — the CI contract is 'exit 0 means all four held'.
    """
    from comapreduce_tpu.mapmaking.wcs import WCS
    from comapreduce_tpu.resilience import QuarantineLedger, Resilience
    from comapreduce_tpu.resilience.chaos import ChaosMonkey
    from comapreduce_tpu.resilience.retry import RetryPolicy

    t0 = time.perf_counter()
    os.makedirs(workdir, exist_ok=True)
    files = []
    for i in range(n_files):
        path = os.path.join(workdir, f"Level2_comap-{i:04d}.hd5")
        if not os.path.exists(path):
            _write_level2(path, seed=1000 + seed * 10 + i)
        files.append(path)
    wcs = WCS.from_field((170.25, 52.25), (1.0 / 60, 1.0 / 60), (64, 64))

    # one fault of every class, each aimed at a known file
    spec = ("read_error@0001,truncate@0002,flaky@0003,"
            "nan_burst@0004,slow_read@0000")
    monkey = ChaosMonkey(spec, seed=seed, slow_s=0.01, burst_frac=0.1)
    ledger_path = os.path.join(workdir, "quarantine.jsonl")
    if os.path.exists(ledger_path):
        os.unlink(ledger_path)
    res = Resilience(ledger=QuarantineLedger(ledger_path),
                     retry=RetryPolicy(max_retries=1, base_s=0.0,
                                       seed=seed),
                     chaos=monkey)

    # -- 1. chaos run completes ------------------------------------------
    data_chaos = _read(files, wcs, resilience=res, prefetch=prefetch)
    result_chaos = _solve(data_chaos)
    assert np.isfinite(
        np.asarray(result_chaos.destriped_map)).all(), \
        "criterion 1: chaos-run map contains non-finite pixels"

    dead = [files[1], files[2]]          # read_error, truncate
    survivors = [f for f in files if f not in dead]
    assert data_chaos.files == survivors, \
        f"criterion 1: expected survivors {survivors}, " \
        f"got {data_chaos.files}"

    # -- 2. every injected fault is ledgered, correctly classified ------
    ledger = QuarantineLedger(ledger_path)  # re-read from disk
    by_file = {}
    for e in ledger.entries:
        by_file.setdefault(os.path.basename(e.unit["file"]), []).append(e)

    def _has(fname, failure_class, disposition):
        return any(e.failure_class == failure_class
                   and e.disposition == disposition
                   for e in by_file.get(os.path.basename(fname), []))

    assert _has(files[1], "transient", "quarantined"), \
        "criterion 2: injected read_error not quarantined as transient"
    assert _has(files[2], "transient", "quarantined"), \
        "criterion 2: injected truncate not quarantined as transient"
    assert _has(files[3], "transient", "recovered"), \
        "criterion 2: flaky read not recorded as recovered-by-retry"
    assert _has(files[4], "numerical", "masked"), \
        "criterion 2: NaN burst not recorded as numerical/masked"
    injected_kinds = {k for _, k in monkey.injected}
    assert injected_kinds >= {"read_error", "truncate", "flaky",
                              "nan_burst", "slow_read"}, \
        f"chaos harness fired only {sorted(injected_kinds)}"

    # -- 3. chaos map == clean map with faulted units zero-weighted -----
    # The reference run reads clean copies of the SURVIVING files with
    # the burst unit (file 4's (feed, start, n), reconstructed from the
    # monkey's own deterministic placement) zero-weighted at the source:
    # value 0, weight 0 — exactly what the tripwire turns the NaNs into,
    # so every downstream operator (median filter included) sees
    # identical inputs and the maps must agree to the last byte.
    import h5py
    import shutil

    ref_dir = os.path.join(workdir, "ref")
    os.makedirs(ref_dir, exist_ok=True)
    ref_files = []
    n_masked = 0
    for f in survivors:
        dst = os.path.join(ref_dir, os.path.basename(f))
        shutil.copy2(f, dst)
        if f == files[4]:
            with h5py.File(dst, "a") as h:
                shape = h["averaged_tod/tod"].shape    # (F, B, T)
                feed, start, n = monkey.burst_coords(f, shape)
                h["averaged_tod/tod"][feed, ..., start:start + n] = 0.0
                h["averaged_tod/weights"][feed, ...,
                                          start:start + n] = 0.0
                n_masked = n
        ref_files.append(dst)
    assert n_masked > 0, "criterion 3: NaN burst masked no samples"
    data_ref = _read(ref_files, wcs)
    assert data_ref.tod.size == data_chaos.tod.size, \
        "criterion 3: chaos run changed the sample stream shape"
    result_ref = _solve(data_ref)
    identical = np.array_equal(np.asarray(result_chaos.destriped_map),
                               np.asarray(result_ref.destriped_map))
    assert identical, \
        "criterion 3: chaos map != clean map with faulted units " \
        "zero-weighted"

    # -- 4. resume consults the ledger; retry_quarantined re-admits -----
    res2 = Resilience(ledger=QuarantineLedger(ledger_path))
    admitted = [f for f in files if res2.admit(f)]
    assert admitted == survivors, \
        f"criterion 4: resume admitted {admitted}, expected {survivors}"
    res3 = Resilience(ledger=QuarantineLedger(ledger_path),
                      retry_quarantined=True)
    readmitted = [f for f in files if res3.admit(f)]
    assert readmitted == files, \
        "criterion 4: retry_quarantined did not re-admit the " \
        "quarantined set"
    # ... and exactly the quarantined set was re-admitted
    assert sorted(res3._readmitted) == sorted(dead), \
        f"criterion 4: re-admitted {sorted(res3._readmitted)}, " \
        f"expected {sorted(dead)}"

    return {
        "n_files": n_files,
        "injected": sorted({(os.path.basename(f), k)
                            for f, k in monkey.injected}),
        "quarantined": sorted(os.path.basename(f)
                              for f in ledger.quarantined_files()),
        "ledger_summary": ledger.summary(),
        "n_masked_samples": n_masked,
        "map_byte_identical": bool(identical),
        "cg_iters_chaos": int(result_chaos.n_iter),
        "wall_s": round(time.perf_counter() - t0, 3),
    }
