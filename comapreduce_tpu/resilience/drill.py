"""The chaos drill: inject every fault class, assert the promises hold.

This is the executable form of the resilience layer's contract
(ISSUE 2 + ISSUE 3 acceptance criteria), run by
``tools/check_resilience.py`` and ``bench.py --config resilience``:

1. a chaos run (read error + truncated file + NaN burst + slow read +
   first-attempt flake + HANGING read injected over a synthetic
   fixture set) completes with no unhandled exception;
2. every injected fault appears in the quarantine ledger with the
   correct classification (read error/truncate -> ``transient``
   quarantines, NaN burst -> ``numerical``/``masked``, flake ->
   ``transient``/``recovered``, hang -> ``hang``/``rejected`` after a
   ``hang``/``stalled`` soft warning);
3. the destriped map from the chaos run is byte-identical to the
   clean run's map with the faulted units zero-weighted (dead and
   hung files dropped, NaN-touched samples at weight 0);
4. a second pass consults the ledger: quarantined files are skipped
   without a read, the HUNG file is re-attempted (rejected, not
   quarantined — a hang indicts the environment), and
   ``retry_quarantined`` re-admits exactly the quarantined set;
5. the watchdog honoured its deadline budget: each hung read was
   cancelled within ``hard + grace`` seconds (every retry gets its
   own fresh budget), and the run never joined a stuck read;
6. the async writeback path (ISSUE 5): a ``write_stall`` fault on the
   background writer thread is cancelled by the ``writeback.write``
   hard deadline within ``hard + grace``, ledgered ``hang``/
   ``rejected`` (environment, never the file), the flush barrier
   surfaces the failure, committed checkpoints are never dropped or
   reordered (the surviving file holds its LAST submitted generation,
   complete), and the abandoned writer's late commit is skipped at the
   generation gate;
7. the elastic campaign (ISSUE 8, ``run_elastic_drill``): three REAL
   processes share one lease-file queue; a ``rank_kill`` rank is
   SIGKILLed mid-lease and a ``rank_pause`` zombie stops heartbeating
   but keeps working. The survivor steals both expired leases
   (ledgered ``stolen`` then ``recovered``), every file is committed
   EXACTLY once, the zombie's late commit is rejected at the
   generation fence, and the map over the committed set is
   byte-identical to a clean run over the same filelist.

8. incremental map serving (ISSUE 9, ``run_serving_drill``): a
   ``serving.MapServer`` folds committed waves into versioned epochs.
   Asserts: every committed file lands in EXACTLY one epoch's
   ``new_files`` (exactly-once folding); a ``kill_mid_publish``
   SIGKILL never moves ``current`` off a complete epoch; a killed and
   resumed server's epochs are byte-identical to an uninterrupted
   twin's (map FITS and offsets compared byte-for-byte); a cold
   one-shot serving epoch is byte-identical to a batch
   read+solve over the same census (incremental assembly parity); and
   the warm-started final epoch needs STRICTLY fewer CG iterations
   than the cold one-shot while agreeing with it modulo the offset
   null mode (a global constant — docs/OPERATIONS.md §12).

9. the map tile read tier (ISSUE 12, ``run_tiles_drill``): served
   epochs are cut into content-addressed tiles behind an HTTP front.
   Asserts: a SIGKILL between the tile object writes and the tile
   manifest rename leaves the tile tier serving the PREVIOUS complete
   epoch whole (old-or-new, never torn) while the epoch itself stands;
   the CLI backfill repairs the gap and a fresh-root re-tile yields
   byte-identical tile hashes (deterministic blob encoding), making
   the published delta the exact manifest diff; an HTTP cutout is
   bit-identical to slicing the expanded epoch FITS and revalidates
   (304) across an atomic ``/v1/current`` rollback; every serving
   process lands on its own auto-incremented telemetry lane (rank >=
   1000); and ``MapServer.evict`` publishes a ``downdated`` epoch
   whose tiles are byte-identical to the pre-eviction epoch's
   (content addressing across history), with the retracted file never
   re-admitted by the commit scan.

10. the integrity plane (docs/OPERATIONS.md §20,
   ``run_integrity_drill``): one byte is flipped in a committed
   artifact of EVERY class — Level-2 checkpoint, spill entry, solver
   snapshot, epoch FITS, tile object, ledger line. Asserts 100%
   detection by ``tools/campaign_fsck.py``, the correct per-class
   triage at each read boundary (checkpoint -> ``corrupt`` ledger
   disposition, spill -> cache miss + unlink, snapshot -> cold solve,
   epoch -> ``verify_epoch`` problem, tile ->
   ``CorruptArtifactError`` + unlink, ledger line ->
   dropped-and-counted), that chaos ``bit_rot`` rots only
   post-commit (always detectable) at most once per basename, and
   that ``--repair`` + re-derivation yields a final map
   byte-identical to the clean run's.

Everything is deterministic by seed (chaos decisions, jitter, synthetic
data), so a CI failure reproduces locally bit-for-bit. (Deadline
checks bound wall time from ABOVE only — cancels must not be late;
nothing asserts a minimum, so fast machines stay green.)
"""

from __future__ import annotations

import logging
import os
import time

import numpy as np

__all__ = ["run_drill", "run_elastic_drill", "run_integrity_drill",
           "run_live_drill", "run_serving_drill", "run_tiles_drill"]

logger = logging.getLogger("comapreduce_tpu")


def _child_env(**extra) -> dict:
    """Environment for drill subprocesses: CPU jax, and the repo root on
    PYTHONPATH so ``python -m comapreduce_tpu...`` resolves regardless of
    the caller's cwd (the package need not be installed)."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ, JAX_PLATFORMS="cpu", **extra)
    parts = [root] + [p for p in
                      env.get("PYTHONPATH", "").split(os.pathsep) if p]
    env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(parts))
    return env


def _write_level2(path: str, seed: int, F: int = 2, T: int = 600,
                  drift: float = 0.0, rw: float = 0.0,
                  raster: bool = False) -> None:
    """Minimal single-band Level-2 store the destriper reader accepts
    (same schema as the pipeline's checkpoint output).

    ``drift`` adds slow per-feed sinusoids and ``rw`` a random walk —
    the 1/f structure destriping exists to remove, which the SERVING
    drill needs so a warm-started epoch has real offset structure to
    reuse (white noise has none and warm starts save nothing).
    ``raster`` swaps the random per-sample pointing for a smooth
    boustrophedon sweep (scan-like pixel coupling)."""
    from comapreduce_tpu.data.hdf5io import HDF5Store

    rng = np.random.default_rng(seed)
    store = HDF5Store(name="l2")
    t = np.arange(T)
    tod = (rng.normal(size=(F, 1, T))
           + np.sin(t / 37.0)).astype(np.float32)
    if drift:
        for f in range(F):
            ph = rng.uniform(0.0, 2.0 * np.pi, size=3)
            tod[f, 0] += drift * (
                np.sin(2 * np.pi * t / 401.0 + ph[0])
                + 0.5 * np.sin(2 * np.pi * t / 173.0 + ph[1])
                + 0.25 * np.sin(2 * np.pi * t / 83.0 + ph[2])
            ).astype(np.float32)
    if rw:
        tod += (rw * np.cumsum(rng.normal(size=(F, 1, T)),
                               axis=-1)).astype(np.float32)
    store["averaged_tod/tod"] = tod
    store["averaged_tod/weights"] = np.ones((F, 1, T), np.float32)
    store["averaged_tod/scan_edges"] = np.array([[0, T]], np.int64)
    if raster:
        ph = rng.uniform(0.0, 2.0 * np.pi, size=(F, 1))
        ra = (170.0 + 0.25 * (1 + np.sin(2 * np.pi * t / 97.0 + ph))
              ) * np.ones((F, T))
        dec = (52.0 + 0.5 * ((t[None, :] / T + rng.random((F, 1)))
                             % 1.0)) * np.ones((F, T))
    else:
        ra = 170.0 + 0.5 * rng.random((F, T))
        dec = 52.0 + 0.5 * rng.random((F, T))
    store["spectrometer/pixel_pointing/pixel_ra"] = ra
    store["spectrometer/pixel_pointing/pixel_dec"] = dec
    store["spectrometer/pixel_pointing/pixel_az"] = ra
    store["spectrometer/pixel_pointing/pixel_el"] = dec
    store.set_attrs("comap", "source", "co2,sky")
    store.set_attrs("comap", "obsid", seed)
    store.write(path)


def _read(files, wcs, resilience=None, prefetch: int = 0):
    from comapreduce_tpu.mapmaking.leveldata import read_comap_data

    return read_comap_data(files, band=0, wcs=wcs, offset_length=50,
                           medfilt_window=51, use_calibration=False,
                           prefetch=prefetch, resilience=resilience)


def _solve(data):
    from comapreduce_tpu.cli.run_destriper import solve_band

    return solve_band(data, offset_length=50, n_iter=50, threshold=1e-5)


def run_drill(workdir: str, seed: int = 0, n_files: int = 7,
              prefetch: int = 2, hard_deadline_s: float = 0.4,
              soft_deadline_s: float = 0.1,
              grace_s: float = 1.0) -> dict:
    """Run the full drill in ``workdir``; returns the evidence dict.

    Raises ``AssertionError`` (with a named criterion) on any broken
    promise — the CI contract is 'exit 0 means all five held'.
    """
    from comapreduce_tpu.mapmaking.wcs import WCS
    from comapreduce_tpu.resilience import (QuarantineLedger, Resilience,
                                            Watchdog, parse_deadlines)
    from comapreduce_tpu.resilience.chaos import ChaosMonkey
    from comapreduce_tpu.resilience.retry import RetryPolicy

    t0 = time.perf_counter()
    os.makedirs(workdir, exist_ok=True)
    files = []
    for i in range(n_files):
        path = os.path.join(workdir, f"Level2_comap-{i:04d}.hd5")
        if not os.path.exists(path):
            _write_level2(path, seed=1000 + seed * 10 + i)
        files.append(path)
    wcs = WCS.from_field((170.25, 52.25), (1.0 / 60, 1.0 / 60), (64, 64))

    # one fault of every class, each aimed at a known file; the hang
    # blocks far past the hard deadline (abandoned workers are released
    # in the finally below so they die promptly, not after hang_s)
    spec = ("read_error@0001,truncate@0002,flaky@0003,"
            "nan_burst@0004,slow_read@0000,hang@0005")
    monkey = ChaosMonkey(spec, seed=seed, slow_s=0.01, burst_frac=0.1,
                         hang_s=60.0)
    ledger_path = os.path.join(workdir, "quarantine.jsonl")
    if os.path.exists(ledger_path):
        os.unlink(ledger_path)
    ledger = QuarantineLedger(ledger_path)
    watchdog = Watchdog(
        deadlines=parse_deadlines(
            f"ingest.read={soft_deadline_s}/{hard_deadline_s}"),
        ledger=ledger, grace_s=grace_s)
    res = Resilience(ledger=ledger,
                     retry=RetryPolicy(max_retries=1, base_s=0.0,
                                       seed=seed),
                     chaos=monkey, watchdog=watchdog)

    try:
        return _run_drill_criteria(
            workdir, files, wcs, res, monkey, ledger_path, watchdog,
            hard_deadline_s, grace_s, prefetch, n_files, t0, seed=seed)
    finally:
        monkey.release()


def _run_drill_criteria(workdir, files, wcs, res, monkey, ledger_path,
                        watchdog, hard_deadline_s, grace_s, prefetch,
                        n_files, t0, seed=0) -> dict:
    from comapreduce_tpu.resilience import QuarantineLedger, Resilience

    # -- 1. chaos run completes ------------------------------------------
    data_chaos = _read(files, wcs, resilience=res, prefetch=prefetch)
    result_chaos = _solve(data_chaos)
    assert np.isfinite(
        np.asarray(result_chaos.destriped_map)).all(), \
        "criterion 1: chaos-run map contains non-finite pixels"

    dead = [files[1], files[2]]          # read_error, truncate
    hung = [files[5]]                    # hang (cancelled, rejected)
    survivors = [f for f in files if f not in dead and f not in hung]
    assert data_chaos.files == survivors, \
        f"criterion 1: expected survivors {survivors}, " \
        f"got {data_chaos.files}"

    # -- 2. every injected fault is ledgered, correctly classified ------
    ledger = QuarantineLedger(ledger_path)  # re-read from disk
    by_file = {}
    for e in ledger.entries:
        by_file.setdefault(os.path.basename(e.unit["file"]), []).append(e)

    def _has(fname, failure_class, disposition):
        return any(e.failure_class == failure_class
                   and e.disposition == disposition
                   for e in by_file.get(os.path.basename(fname), []))

    assert _has(files[1], "transient", "quarantined"), \
        "criterion 2: injected read_error not quarantined as transient"
    assert _has(files[2], "transient", "quarantined"), \
        "criterion 2: injected truncate not quarantined as transient"
    assert _has(files[3], "transient", "recovered"), \
        "criterion 2: flaky read not recorded as recovered-by-retry"
    assert _has(files[4], "numerical", "masked"), \
        "criterion 2: NaN burst not recorded as numerical/masked"
    assert _has(files[5], "hang", "stalled"), \
        "criterion 2: hung read fired no soft-deadline 'stalled' event"
    assert _has(files[5], "hang", "rejected"), \
        "criterion 2: cancelled hang not ledgered as hang/rejected " \
        "(a hang indicts the environment — it must never quarantine)"
    injected_kinds = {k for _, k in monkey.injected}
    assert injected_kinds >= {"read_error", "truncate", "flaky",
                              "nan_burst", "slow_read", "hang"}, \
        f"chaos harness fired only {sorted(injected_kinds)}"

    # -- 3. chaos map == clean map with faulted units zero-weighted -----
    # The reference run reads clean copies of the SURVIVING files with
    # the burst unit (file 4's (feed, start, n), reconstructed from the
    # monkey's own deterministic placement) zero-weighted at the source:
    # value 0, weight 0 — exactly what the tripwire turns the NaNs into,
    # so every downstream operator (median filter included) sees
    # identical inputs and the maps must agree to the last byte.
    import h5py
    import shutil

    ref_dir = os.path.join(workdir, "ref")
    os.makedirs(ref_dir, exist_ok=True)
    ref_files = []
    n_masked = 0
    for f in survivors:
        dst = os.path.join(ref_dir, os.path.basename(f))
        shutil.copy2(f, dst)
        if f == files[4]:
            with h5py.File(dst, "a") as h:
                shape = h["averaged_tod/tod"].shape    # (F, B, T)
                feed, start, n = monkey.burst_coords(f, shape)
                h["averaged_tod/tod"][feed, ..., start:start + n] = 0.0
                h["averaged_tod/weights"][feed, ...,
                                          start:start + n] = 0.0
                n_masked = n
        ref_files.append(dst)
    assert n_masked > 0, "criterion 3: NaN burst masked no samples"
    data_ref = _read(ref_files, wcs)
    assert data_ref.tod.size == data_chaos.tod.size, \
        "criterion 3: chaos run changed the sample stream shape"
    result_ref = _solve(data_ref)
    identical = np.array_equal(np.asarray(result_chaos.destriped_map),
                               np.asarray(result_ref.destriped_map))
    assert identical, \
        "criterion 3: chaos map != clean map with faulted units " \
        "zero-weighted"

    # -- 4. resume consults the ledger; retry_quarantined re-admits -----
    # the HUNG file is rejected, not quarantined: resume re-attempts it
    expected_admit = [f for f in files if f not in dead]
    res2 = Resilience(ledger=QuarantineLedger(ledger_path))
    admitted = [f for f in files if res2.admit(f)]
    assert admitted == expected_admit, \
        f"criterion 4: resume admitted {admitted}, " \
        f"expected {expected_admit}"
    res3 = Resilience(ledger=QuarantineLedger(ledger_path),
                      retry_quarantined=True)
    readmitted = [f for f in files if res3.admit(f)]
    assert readmitted == files, \
        "criterion 4: retry_quarantined did not re-admit the " \
        "quarantined set"
    # ... and exactly the quarantined set was re-admitted
    assert sorted(res3._readmitted) == sorted(dead), \
        f"criterion 4: re-admitted {sorted(res3._readmitted)}, " \
        f"expected {sorted(dead)}"

    # -- 5. deadline budget honoured -------------------------------------
    # every cancelled attempt must land within hard + grace of its own
    # start (the watchdog's audit trail records per-event elapsed); one
    # event per attempt (retry = a fresh budget, so 2 with max_retries=1)
    hangs = [e for e in watchdog.events if e[0] == "hang"]
    assert len(hangs) == 2, \
        f"criterion 5: expected 2 cancelled hang attempts (1 retry), " \
        f"saw {len(hangs)}: {hangs}"
    late = [e for e in hangs if e[3] > hard_deadline_s + grace_s]
    assert not late, \
        f"criterion 5: cancel latency exceeded hard deadline " \
        f"{hard_deadline_s} s + grace {grace_s} s: {late}"

    # -- 6. async writeback: stalled writer cancelled, ordering kept ----
    wb_evidence = _writeback_drill(workdir, res, seed=seed, soft_s=0.1,
                                   hard_s=hard_deadline_s,
                                   grace_s=grace_s)

    return {
        **wb_evidence,
        "n_files": n_files,
        "injected": sorted({(os.path.basename(f), k)
                            for f, k in monkey.injected}),
        "quarantined": sorted(os.path.basename(f)
                              for f in ledger.quarantined_files()),
        "ledger_summary": ledger.summary(),
        "n_masked_samples": n_masked,
        "map_byte_identical": bool(identical),
        "cg_iters_chaos": int(result_chaos.n_iter),
        "hang_cancel_s": [round(e[3], 4) for e in hangs],
        "hard_deadline_s": hard_deadline_s,
        "hang_grace_s": grace_s,
        "watchdog_events": [list(e) for e in watchdog.events][:50],
        "wall_s": round(time.perf_counter() - t0, 3),
    }


def _writeback_drill(workdir, res, seed, soft_s, hard_s, grace_s) -> dict:
    """Criterion 6: async writeback under a ``write_stall`` fault.

    A stalled background writer must be cancelled by the
    ``writeback.write`` hard deadline (within ``hard + grace``),
    ledgered ``hang``/``rejected``, must never drop or reorder a
    committed checkpoint, and its abandoned late commit must be skipped
    at the generation gate. Returns the evidence fields merged into the
    drill record."""
    import h5py

    from comapreduce_tpu.data.hdf5io import HDF5Store
    from comapreduce_tpu.data.writeback import Writeback
    from comapreduce_tpu.resilience.chaos import ChaosMonkey
    from comapreduce_tpu.resilience.watchdog import (HangError, Watchdog,
                                                     parse_deadlines)

    wb_dir = os.path.join(workdir, "writeback")
    os.makedirs(wb_dir, exist_ok=True)
    ok = os.path.join(wb_dir, "Level2_ok.hd5")
    victim = os.path.join(wb_dir, "Level2_stall.hd5")
    for p in (ok, victim):
        if os.path.exists(p):
            os.unlink(p)

    def payload(gen: int) -> dict:
        store = HDF5Store(name="wb-drill")
        store["averaged_tod/tod"] = np.full((2, 64), float(gen),
                                            np.float32)
        store["meta/gen"] = np.array([gen])
        return store.export_payload()

    monkey = ChaosMonkey("write_stall@stall", seed=seed, hang_s=60.0)
    watchdog = Watchdog(
        deadlines=parse_deadlines(f"writeback.write={soft_s}/{hard_s}"),
        ledger=res.ledger, grace_s=grace_s)
    wb = Writeback(depth=4, watchdog=watchdog, chaos=monkey)
    try:
        # ordering: three generations for the healthy file, committed
        # in submission order; the survivor must hold the LAST one
        for gen in (1, 2, 3):
            wb.submit_store(ok, payload(gen))
        wb.flush(ok)
        with h5py.File(ok, "r") as h:
            got = int(h["meta/gen"][0])
            torn = not (h["averaged_tod/tod"][...] == float(got)).all()
        assert got == 3 and not torn, \
            f"criterion 6: committed checkpoint dropped/reordered " \
            f"(gen {got}, torn={torn})"

        err = None
        try:
            wb.submit_store(victim, payload(1))
            wb.flush(victim)
        except OSError as exc:    # HangError is an OSError subclass
            err = exc
        assert isinstance(err, HangError), \
            "criterion 6: stalled writeback was not cancelled by the " \
            "watchdog hard deadline"
        res.record_failure(victim, err, stage="writeback.write",
                           may_quarantine=False)
        hangs = [e for e in watchdog.events if e[0] == "hang"]
        late = [e for e in hangs if e[3] > hard_s + grace_s]
        assert hangs and not late, \
            f"criterion 6: writeback cancel latency exceeded " \
            f"{hard_s} + {grace_s} s: {late}"
        assert not os.path.exists(victim), \
            "criterion 6: a cancelled write must not commit"
        entries = [e for e in res.ledger.entries
                   if e.unit["file"] == victim]
        assert any(e.failure_class == "hang" and
                   e.disposition == "rejected" for e in entries), \
            "criterion 6: stalled write not ledgered hang/rejected"

        # the abandoned writer, released, must SKIP its late commit
        monkey.release()
        deadline = time.perf_counter() + 10.0
        while wb.stats["late_skips"] < 1 and \
                time.perf_counter() < deadline:
            time.sleep(0.02)
        assert wb.stats["late_skips"] >= 1, \
            "criterion 6: abandoned writer's late commit not skipped"
        assert not os.path.exists(victim), \
            "criterion 6: late commit landed after cancellation"
        return {
            "writeback_hang_cancel_s": [round(e[3], 4) for e in hangs],
            "writeback_writes": wb.stats["writes"],
            "writeback_late_skips": wb.stats["late_skips"],
        }
    finally:
        monkey.release()
        wb.close()


def run_elastic_drill(workdir: str, seed: int = 0, n_files: int = 7,
                      ttl_s: float = 1.0, hold_s: float = 10.0,
                      timeout_s: float = 180.0) -> dict:
    """Criterion 7: the elastic campaign under ``rank_kill`` +
    ``rank_pause``, with REAL processes (a SIGKILL cannot be faked
    in-process).

    Three worker ranks (``python -m comapreduce_tpu.resilience.drill``)
    share one lease directory over the same ``n_files`` fixtures:

    - rank 1 draws ``rank_kill`` on its first rotation unit — SIGKILLed
      the instant the lease is claimed, leaking it;
    - rank 2 draws ``rank_pause`` on its first unit — the zombie: its
      heartbeat freezes but it keeps "working" for ``hold_s`` (far past
      the TTL) and then tries to commit;
    - rank 0, the survivor, waits for both targets' leases to exist
      (so the faults deterministically land on their ranks) and then
      drains the whole queue, stealing both expired leases.

    Asserts: the killed rank died by SIGKILL and wrote nothing; every
    file was committed exactly once (by the survivor); both steals are
    ledgered ``stolen`` then ``recovered``; the zombie's late commit
    was fence-rejected exactly once; every lease file ends ``done`` by
    the survivor; and the destriped map over the committed set is
    byte-identical to a clean run over the same filelist.
    """
    import json
    import shutil
    import subprocess
    import sys

    from comapreduce_tpu.mapmaking.wcs import WCS
    from comapreduce_tpu.resilience import QuarantineLedger
    from comapreduce_tpu.resilience.lease import (lease_key, lease_path,
                                                  read_lease)

    t0 = time.perf_counter()
    os.makedirs(workdir, exist_ok=True)
    files = []
    for i in range(n_files):
        path = os.path.join(workdir, f"Level2_comap-{i:04d}.hd5")
        if not os.path.exists(path):
            _write_level2(path, seed=1000 + seed * 10 + i)
        files.append(os.path.abspath(path))
    state = os.path.join(workdir, "elastic")
    shutil.rmtree(state, ignore_errors=True)
    os.makedirs(state)
    flist = os.path.join(state, "filelist.txt")
    with open(flist, "w", encoding="utf-8") as f:
        f.write("\n".join(files) + "\n")
    # each fault targets its rank's FIRST rotation unit, so the rank
    # dies/pauses before doing anything else — the worst case for the
    # queue (nothing of its shard completed)
    kill_target = os.path.basename(files[1])
    pause_target = os.path.basename(files[2])
    env = _child_env()

    def spawn(rank: int, **kw):
        cmd = [sys.executable, "-m", "comapreduce_tpu.resilience.drill",
               f"--rank={rank}", "--n-ranks=3", f"--state-dir={state}",
               f"--filelist={flist}", f"--ttl={ttl_s}",
               f"--seed={seed}"]
        cmd += [f"--{k.replace('_', '-')}={v}" for k, v in kw.items()]
        return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)

    procs = {
        "killer": spawn(1, chaos=f"rank_kill@{kill_target}"),
        "zombie": spawn(2, chaos=f"rank_pause@{pause_target}",
                        hold_s=hold_s, max_files=1),
        "survivor": spawn(0, wait_for=f"{kill_target},{pause_target}"),
    }
    rc, out = {}, {}
    for name, pr in procs.items():
        try:
            stdout, _ = pr.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            pr.kill()
            stdout, _ = pr.communicate()
        rc[name] = pr.returncode
        out[name] = (stdout or b"").decode(errors="replace")

    assert rc["killer"] == -9, \
        f"criterion 7: rank_kill rank exited {rc['killer']}, expected " \
        f"SIGKILL (-9):\n{out['killer']}"
    assert rc["zombie"] == 0, \
        f"criterion 7: zombie rank failed ({rc['zombie']}):\n" \
        f"{out['zombie']}"
    assert rc["survivor"] == 0, \
        f"criterion 7: survivor rank failed ({rc['survivor']}):\n" \
        f"{out['survivor']}"
    assert not os.path.exists(os.path.join(state, "result.rank1.json")), \
        "criterion 7: the SIGKILLed rank wrote a result"

    def result(rank: int) -> dict:
        with open(os.path.join(state, f"result.rank{rank}.json"),
                  encoding="utf-8") as f:
            return json.load(f)

    surv, zomb = result(0), result(2)
    names = sorted(os.path.basename(f) for f in files)
    committed = sorted(surv["committed"] + zomb["committed"])
    # exactly once: equality of sorted MULTISETS catches both a lost
    # unit and a double commit
    assert committed == names, \
        f"criterion 7: committed {committed} != filelist {names} " \
        f"(unit lost or committed twice)"
    assert zomb["committed"] == [] \
        and zomb["stats"]["fence_rejects"] == 1, \
        f"criterion 7: zombie's late commit was not fence-rejected " \
        f"exactly once: {zomb}"
    assert pause_target in zomb["processed"], \
        f"criterion 7: zombie never claimed its pause target: {zomb}"
    assert surv["stats"]["stolen"] == 2 \
        and surv["stats"]["recovered"] == 2, \
        f"criterion 7: survivor should steal AND recover exactly the " \
        f"2 faulted units: {surv['stats']}"
    ledger = QuarantineLedger(os.path.join(state,
                                           "quarantine.rank0.jsonl"))
    stolen = sorted({os.path.basename(e.unit["file"])
                     for e in ledger.entries
                     if e.disposition == "stolen"})
    recovered = sorted({os.path.basename(e.unit["file"])
                        for e in ledger.entries
                        if e.disposition == "recovered"})
    assert stolen == recovered == sorted([kill_target, pause_target]), \
        f"criterion 7: ledger stole {stolen} / recovered {recovered}, " \
        f"expected {sorted([kill_target, pause_target])}"
    for f in files:
        st = read_lease(lease_path(state, lease_key(f)))
        assert st is not None and st.get("state") == "done", \
            f"criterion 7: lease for {os.path.basename(f)} not done: {st}"
        assert int(st.get("done_by", -1)) == 0, \
            f"criterion 7: {os.path.basename(f)} finished by rank " \
            f"{st.get('done_by')}, expected the survivor (0)"

    # the map over the committed set must match a clean static run over
    # the same filelist to the last byte — stealing moved units between
    # ranks, it must not change WHAT gets reduced
    wcs = WCS.from_field((170.25, 52.25), (1.0 / 60, 1.0 / 60), (64, 64))
    by_name = {os.path.basename(f): f for f in files}
    map_elastic = np.asarray(_solve(_read(
        [by_name[n] for n in committed], wcs)).destriped_map)
    map_clean = np.asarray(_solve(_read(
        sorted(files), wcs)).destriped_map)
    identical = bool(np.array_equal(map_elastic, map_clean))
    assert identical, \
        "criterion 7: elastic-campaign map != clean run over the " \
        "same filelist"

    return {
        "elastic_returncodes": dict(rc),
        "elastic_committed": {"survivor": surv["committed"],
                              "zombie": zomb["committed"]},
        "elastic_stats": {"survivor": surv["stats"],
                          "zombie": zomb["stats"]},
        "elastic_stolen": stolen,
        "elastic_recovered": recovered,
        "elastic_fence_rejects": zomb["stats"]["fence_rejects"],
        "elastic_map_byte_identical": identical,
        "elastic_wall_s": round(time.perf_counter() - t0, 3),
    }


def run_live_drill(workdir: str, seed: int = 0, n_files: int = 6,
                   ttl_s: float = 2.0, timeout_s: float = 180.0) -> dict:
    """Criterion 8: the live observability plane over a real elastic
    campaign with a real SIGKILL (docs/OPERATIONS.md §16).

    Two worker ranks share one lease directory; rank 1 draws
    ``rank_kill`` on its first unit and dies mid-claim; rank 0 (the
    survivor) steals the leaked lease and drains the queue; rank 1 is
    then RESTARTED (a restarted rank beats again and finds every unit
    done elsewhere). A :class:`telemetry.live.LiveServer` watches the
    state dir throughout.

    Asserts: ``/healthz`` flips to 503 within one heartbeat TTL of the
    SIGKILL and back to 200 after the steal + restart (clean ``.done``
    heartbeats probe healthy); the ``/metrics`` Prometheus page parses
    line-by-line and its ``comap_scheduler_committed_total`` summed
    across ranks equals the scheduler's own commit count EXACTLY (one
    counter event per commit — the live file-done count is trustworthy);
    and ``/v1/campaign`` serves the schema-2 report.
    """
    import json
    import re as _re
    import shutil
    import subprocess
    import sys
    from urllib.error import URLError
    from urllib.request import urlopen

    from comapreduce_tpu.telemetry.live import LiveServer

    t0 = time.perf_counter()
    os.makedirs(workdir, exist_ok=True)
    files = []
    for i in range(n_files):
        path = os.path.join(workdir, f"Level2_comap-{i:04d}.hd5")
        if not os.path.exists(path):
            _write_level2(path, seed=1000 + seed * 10 + i)
        files.append(os.path.abspath(path))
    state = os.path.join(workdir, "live")
    shutil.rmtree(state, ignore_errors=True)
    os.makedirs(state)
    flist = os.path.join(state, "filelist.txt")
    with open(flist, "w", encoding="utf-8") as f:
        f.write("\n".join(files) + "\n")
    kill_target = os.path.basename(files[1])
    env = _child_env()
    srv = LiveServer(state, port=0, stale_s=ttl_s, n_ranks=2).start()

    def spawn(rank: int, **kw):
        cmd = [sys.executable, "-m", "comapreduce_tpu.resilience.drill",
               f"--rank={rank}", "--n-ranks=2", f"--state-dir={state}",
               f"--filelist={flist}", f"--ttl={ttl_s}",
               f"--seed={seed}", "--telemetry"]
        cmd += [f"--{k.replace('_', '-')}={v}" for k, v in kw.items()]
        return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)

    def wait(pr):
        try:
            stdout, _ = pr.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            pr.kill()
            stdout, _ = pr.communicate()
        return pr.returncode, (stdout or b"").decode(errors="replace")

    def probe() -> int:
        try:
            with urlopen(f"http://{srv.host}:{srv.port}/healthz",
                         timeout=10) as r:
                return r.status
        except URLError as exc:
            code = getattr(exc, "code", None)
            if code is not None:
                return int(code)  # urlopen raises on 503
            raise

    def poll_until(status: int, deadline_s: float, what: str) -> float:
        t_start = time.monotonic()
        while True:
            if probe() == status:
                return time.monotonic() - t_start
            if time.monotonic() - t_start > deadline_s:
                raise AssertionError(
                    f"criterion 8: /healthz never reached {status} "
                    f"within {deadline_s:.1f} s ({what})")
            time.sleep(0.05)

    try:
        killer = spawn(1, chaos=f"rank_kill@{kill_target}")
        survivor = spawn(0, wait_for=kill_target)
        rc_kill, out_kill = wait(killer)
        t_kill = time.monotonic()
        assert rc_kill == -9, \
            f"criterion 8: rank_kill rank exited {rc_kill}, expected " \
            f"SIGKILL (-9):\n{out_kill}"
        # the dead rank's heartbeat freezes mid-stage: the probe must
        # flip unhealthy within one TTL of the kill (plus poll slack)
        poll_until(503, ttl_s + 2.0, "after SIGKILL")
        t_503 = time.monotonic() - t_kill
        rc_surv, out_surv = wait(survivor)
        assert rc_surv == 0, \
            f"criterion 8: survivor failed ({rc_surv}):\n{out_surv}"
        # the survivor finished cleanly (.done) but the dead rank's
        # stale beat still pins the probe at 503 — only a restart (or
        # operator retirement of the rank) clears it
        assert probe() == 503, \
            "criterion 8: /healthz went 200 while the killed rank's " \
            "stale heartbeat was still unresolved"
        rc_again, out_again = wait(spawn(1))
        assert rc_again == 0, \
            f"criterion 8: restarted rank failed ({rc_again}):" \
            f"\n{out_again}"
        poll_until(200, 10.0, "after steal + restart")

        with urlopen(f"http://{srv.host}:{srv.port}/metrics",
                     timeout=10) as r:
            assert r.status == 200
            prom = r.read().decode("utf-8")
        line_re = _re.compile(
            r"^[A-Za-z_:][A-Za-z0-9_:]*(\{[^{}]*\})? \S+$")
        bad = [ln for ln in prom.splitlines()
               if ln and not ln.startswith("#")
               and not line_re.match(ln)]
        assert not bad, \
            f"criterion 8: unparseable /metrics line(s): {bad[:3]}"
        committed_metric = 0.0
        for ln in prom.splitlines():
            if ln.startswith("comap_scheduler_committed_total{"):
                committed_metric += float(ln.rsplit(" ", 1)[1])
        results = {}
        for rank in (0, 1):
            with open(os.path.join(state, f"result.rank{rank}.json"),
                      encoding="utf-8") as f:
                results[rank] = json.load(f)
        committed_true = sum(r["stats"]["committed"]
                             for r in results.values())
        # EXACT: every scheduler commit emitted exactly one counter
        # event and the tail absorbed every one of them
        assert committed_metric == committed_true == n_files, \
            f"criterion 8: /metrics committed {committed_metric} != " \
            f"scheduler committed {committed_true} (n_files {n_files})"
        assert "comap_live_healthy 1" in prom, \
            "criterion 8: /metrics lacks comap_live_healthy 1"
        with urlopen(f"http://{srv.host}:{srv.port}/v1/campaign",
                     timeout=10) as r:
            rep = json.load(r)
        assert rep.get("schema") == 2 and not rep.get("n_stale"), \
            f"criterion 8: /v1/campaign unhealthy after recovery: " \
            f"{ {k: rep.get(k) for k in ('schema', 'n_stale')} }"
    finally:
        srv.stop()

    return {
        "live_t_503_after_kill_s": round(t_503, 3),
        "live_ttl_s": ttl_s,
        "live_committed_metric": committed_metric,
        "live_committed_true": committed_true,
        "live_metrics_lines": len(prom.splitlines()),
        "live_requests": srv.stats["n_requests"],
        "live_wall_s": round(time.perf_counter() - t0, 3),
    }


def _elastic_worker_main(argv=None) -> int:
    """One elastic-drill rank (the ``python -m`` entry): heartbeat +
    scheduler over the shared state dir, committing every claimed unit.
    The chaos spec (``rank_kill``/``rank_pause``) makes this rank the
    drill's victim; ``--wait-for`` makes it the survivor (it defers
    claiming until the victims' leases exist, so the faults land
    deterministically). Results land in ``result.rank<r>.json``."""
    import argparse
    import json

    from comapreduce_tpu.pipeline.scheduler import Scheduler
    from comapreduce_tpu.resilience.chaos import ChaosMonkey
    from comapreduce_tpu.resilience.heartbeat import Heartbeat
    from comapreduce_tpu.resilience.ledger import QuarantineLedger
    from comapreduce_tpu.resilience.lease import lease_key, lease_path

    p = argparse.ArgumentParser(prog="drill-elastic-worker")
    p.add_argument("--rank", type=int, required=True)
    p.add_argument("--n-ranks", type=int, required=True)
    p.add_argument("--state-dir", required=True)
    p.add_argument("--filelist", required=True)
    p.add_argument("--ttl", type=float, default=1.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--chaos", default="")
    p.add_argument("--wait-for", default="")
    p.add_argument("--hold-s", type=float, default=0.0)
    p.add_argument("--max-files", type=int, default=0)
    p.add_argument("--telemetry", action="store_true")
    a = p.parse_args(argv)
    with open(a.filelist, encoding="utf-8") as f:
        files = [ln.strip() for ln in f if ln.strip()]
    if a.telemetry:
        # the live drill scrapes this rank's counter stream off disk
        # while it runs — flush fast so commits land within a poll
        from comapreduce_tpu.telemetry import TELEMETRY

        TELEMETRY.configure(a.state_dir, rank=a.rank, flush_s=0.2)
    hb = Heartbeat(a.state_dir, rank=a.rank,
                   period_s=max(a.ttl / 5.0, 0.05))
    hb.start()
    monkey = ChaosMonkey(a.chaos, seed=a.seed) if a.chaos else None
    ledger = QuarantineLedger(os.path.join(
        a.state_dir, f"quarantine.rank{a.rank}.jsonl"))
    sched = Scheduler(files, a.state_dir, rank=a.rank,
                      n_ranks=a.n_ranks, lease_ttl_s=a.ttl,
                      poll_s=min(a.ttl / 5.0, 0.25), ledger=ledger,
                      chaos=monkey, heartbeat=hb)
    if a.wait_for:
        want = [lease_path(a.state_dir, lease_key(k))
                for k in a.wait_for.split(",") if k]
        deadline = time.monotonic() + 60.0
        while not all(os.path.exists(w) for w in want):
            if time.monotonic() > deadline:
                raise RuntimeError(f"peer leases never appeared: {want}")
            time.sleep(0.05)
    processed, committed = [], []
    for f in sched.claim_iter():
        processed.append(os.path.basename(f))
        if a.hold_s and getattr(hb, "_paused", False):
            # the zombie: keep "working" far past the TTL so the
            # survivor steals and redoes the unit before this commit
            time.sleep(a.hold_s)
        if sched.commit(f):
            committed.append(os.path.basename(f))
        if a.max_files and len(processed) >= a.max_files:
            break
    out = {"rank": a.rank, "processed": processed,
           "committed": committed, "stats": sched.stats}
    tmp = os.path.join(a.state_dir, f".result.rank{a.rank}.tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(out, f)
    os.replace(tmp, os.path.join(a.state_dir,
                                 f"result.rank{a.rank}.json"))
    if a.telemetry:
        from comapreduce_tpu.telemetry import TELEMETRY

        TELEMETRY.close()  # drain the counter buffer before exit
    hb.stop(final_stage="drill.elastic.done")
    return 0


def _commit_done(state_dir: str, files) -> None:
    """Mark ``files`` committed in ``state_dir``'s lease layout — the
    drill's stand-in for a campaign's reduce+commit of each unit."""
    from comapreduce_tpu.resilience.lease import LeaseBoard

    board = LeaseBoard(state_dir, rank=0, lease_ttl_s=60.0)
    for f in files:
        lease = board.claim(f)
        assert lease is not None, f"drill setup: could not claim {f}"
        assert board.commit(lease), f"drill setup: could not commit {f}"


def _epoch_products(epochs_dir: str, n: int) -> dict:
    """Byte-compare material for epoch ``n``: raw map FITS bytes plus
    the published offsets vector."""
    from comapreduce_tpu.serving.epochs import EpochStore
    from comapreduce_tpu.serving.server import load_epoch_offsets

    d = EpochStore(epochs_dir).epoch_dir(n)
    with open(os.path.join(d, "map_band0.fits"), "rb") as f:
        fits = f.read()
    off = load_epoch_offsets(os.path.join(d, "solver_band0.npz"))
    return {"fits": fits, "offsets": off["offsets"]}


def _read_epoch_map(epochs_dir: str, n: int, name: str = "DESTRIPED"):
    from comapreduce_tpu.mapmaking.fits_io import read_fits_image
    from comapreduce_tpu.serving.epochs import EpochStore

    d = EpochStore(epochs_dir).epoch_dir(n)
    for hname, _, arr in read_fits_image(os.path.join(d,
                                                      "map_band0.fits")):
        if hname.upper() == name:
            return np.asarray(arr)
    raise AssertionError(f"epoch {n} map has no {name} HDU")


def run_serving_drill(workdir: str, seed: int = 0, n_files: int = 8,
                      timeout_s: float = 300.0) -> dict:
    """Criterion 8: the incremental map server, with REAL processes for
    the mid-publish SIGKILL (docstring item 8 for the full contract).

    Three waves of committed files drive four server invocations
    (``python -m comapreduce_tpu.resilience.drill --serving``, one
    epoch attempt each):

    - wave 1 (``n_files - 2`` files) publishes ``epoch-000001``
      cleanly;
    - wave 2 (1 file) is solved but the publisher draws
      ``kill_mid_publish`` — SIGKILLed after writing its temp epoch
      dir, before the atomic rename;
    - wave 3 (1 file) resumes the server: temp garbage is swept and
      all pending files publish as ``epoch-000002``.

    An uninterrupted TWIN run over the same waves and a COLD one-shot
    run over the full census provide the byte-identity references.
    The fixtures carry drift + random-walk noise over raster pointing
    (``_write_level2``) so offsets have real 1/f structure — that is
    what makes the warm-started epoch's CG converge in strictly fewer
    iterations than the cold one-shot.
    """
    import json
    import shutil
    import subprocess
    import sys

    from comapreduce_tpu.cli.run_destriper import solve_band
    from comapreduce_tpu.mapmaking.leveldata import read_comap_data
    from comapreduce_tpu.mapmaking.wcs import WCS
    from comapreduce_tpu.serving.epochs import EpochStore
    from comapreduce_tpu.serving.ledger import ServedLedger

    t0 = time.perf_counter()
    os.makedirs(workdir, exist_ok=True)
    files = []
    for i in range(n_files):
        path = os.path.join(workdir, f"Level2_serving-{i:04d}.hd5")
        if not os.path.exists(path):
            _write_level2(path, seed=1000 + seed * 10 + i,
                          drift=6.0, rw=0.3, raster=True)
        files.append(os.path.abspath(path))
    names = sorted(os.path.basename(f) for f in files)
    wave1, wave2, wave3 = files[:-2], files[-2:-1], files[-1:]

    dirs = {k: os.path.join(workdir, f"serving-{k}")
            for k in ("state", "epochs", "twin-state", "twin",
                      "cold-epochs")}
    for d in dirs.values():
        shutil.rmtree(d, ignore_errors=True)
        os.makedirs(d)
    env = _child_env()

    def run_server(state_dir, epochs_dir, chaos=""):
        cmd = [sys.executable, "-m",
               "comapreduce_tpu.resilience.drill", "--serving",
               f"--state-dir={state_dir}", f"--epochs-dir={epochs_dir}",
               f"--seed={seed}"]
        if chaos:
            cmd.append(f"--chaos={chaos}")
        pr = subprocess.run(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, timeout=timeout_s)
        return pr.returncode, (pr.stdout or b"").decode(errors="replace")

    # ---- the drilled run: publish, die mid-publish, resume ----
    _commit_done(dirs["state"], wave1)
    rc, out = run_server(dirs["state"], dirs["epochs"])
    assert rc == 0, f"criterion 8: epoch-1 publish failed ({rc}):\n{out}"
    store = EpochStore(dirs["epochs"])
    assert store.current() == 1 and \
        store.census(1) == {os.path.basename(f) for f in wave1}, \
        f"criterion 8: epoch-1 wrong: current={store.current()} " \
        f"census={store.census(1)}"

    _commit_done(dirs["state"], wave2)
    rc, out = run_server(dirs["state"], dirs["epochs"],
                         chaos="kill_mid_publish@epoch-000002")
    assert rc == -9, \
        f"criterion 8: mid-publish rank exited {rc}, expected SIGKILL " \
        f"(-9):\n{out}"
    # the reader-facing promise: a publisher SIGKILLed mid-publish
    # leaves `current` on a COMPLETE epoch and no epoch-2 directory
    assert store.current() == 1 and store.manifest(1) is not None, \
        f"criterion 8: current torn after mid-publish kill: " \
        f"{store.current()}"
    assert store.latest() == 1 and not os.path.isdir(store.epoch_dir(2)), \
        "criterion 8: a half-published epoch-2 is visible"
    tmp_left = [x for x in os.listdir(dirs["epochs"])
                if x.startswith(".tmp-epoch.")]
    assert tmp_left, \
        "criterion 8: kill_mid_publish fired after the rename " \
        "(drill aimed it before)"

    _commit_done(dirs["state"], wave3)
    rc, out = run_server(dirs["state"], dirs["epochs"])
    assert rc == 0, f"criterion 8: resume failed ({rc}):\n{out}"
    assert store.current() == 2 and store.census(2) == set(names), \
        f"criterion 8: resumed epoch wrong: current={store.current()} " \
        f"census={store.census(2)}"
    assert not [x for x in os.listdir(dirs["epochs"])
                if x.startswith(".tmp-epoch.")], \
        "criterion 8: resume left dead .tmp-epoch.* garbage"

    # exactly-once folding: the epochs' new_files partition the census,
    # and the admission ledger holds each file exactly once
    folded = []
    for n in store.list_epochs():
        folded += list(store.manifest(n).get("new_files", []))
    assert sorted(folded) == names, \
        f"criterion 8: files folded {sorted(folded)} != committed " \
        f"{names} (lost or double-folded)"
    ledger = ServedLedger(os.path.join(dirs["epochs"], "served.jsonl"))
    assert sorted(ledger.files) == names and len(ledger) == len(names), \
        f"criterion 8: admission ledger {sorted(ledger.files)} != " \
        f"{names}"

    # ---- the uninterrupted twin: same waves, no chaos ----
    _commit_done(dirs["twin-state"], wave1)
    rc, out = run_server(dirs["twin-state"], dirs["twin"])
    assert rc == 0, f"criterion 8: twin epoch-1 failed ({rc}):\n{out}"
    _commit_done(dirs["twin-state"], wave2 + wave3)
    rc, out = run_server(dirs["twin-state"], dirs["twin"])
    assert rc == 0, f"criterion 8: twin epoch-2 failed ({rc}):\n{out}"
    for n in (1, 2):
        got = _epoch_products(dirs["epochs"], n)
        want = _epoch_products(dirs["twin"], n)
        assert got["fits"] == want["fits"] and \
            np.array_equal(got["offsets"], want["offsets"]), \
            f"criterion 8: killed+resumed epoch-{n} differs from the " \
            f"uninterrupted twin's"

    # ---- cold one-shot over the full census: assembly parity ----
    rc, out = run_server(dirs["state"], dirs["cold-epochs"])
    assert rc == 0, f"criterion 8: cold one-shot failed ({rc}):\n{out}"
    cold_store = EpochStore(dirs["cold-epochs"])
    assert cold_store.current() == 1 and \
        cold_store.census(1) == set(names), \
        "criterion 8: cold one-shot census wrong"
    wcs = WCS.from_field((170.25, 52.25), (1.0 / 60, 1.0 / 60), (64, 64))
    batch_data = read_comap_data(sorted(files), band=0, wcs=wcs,
                                 offset_length=50, medfilt_window=201,
                                 use_calibration=False)
    batch = solve_band(batch_data, offset_length=50, n_iter=300,
                       threshold=1e-8)
    batch_map = np.asarray(batch.destriped_map).reshape(64, 64)
    cold_map = _read_epoch_map(dirs["cold-epochs"], 1)
    parity = bool(np.array_equal(cold_map, batch_map, equal_nan=True))
    assert parity, \
        "criterion 8: cold serving epoch != batch read+solve over the " \
        "same census (incremental assembly broke parity)"

    # ---- warm vs cold: fewer iterations, equal modulo the null mode
    warm_cg = store.manifest(2)["cg"]
    cold_cg = cold_store.manifest(1)["cg"]
    assert warm_cg["x0"] == "epoch-000001" and cold_cg["x0"] == "cold", \
        f"criterion 8: warm-start provenance wrong: {warm_cg} {cold_cg}"
    assert warm_cg["n_iter"] < cold_cg["n_iter"], \
        f"criterion 8: warm epoch used {warm_cg['n_iter']} CG " \
        f"iterations, cold used {cold_cg['n_iter']} — warm start " \
        f"saved nothing"
    warm_map = _read_epoch_map(dirs["epochs"], 2)
    wmap = _read_epoch_map(dirs["epochs"], 2, "WEIGHTS")
    hit = wmap > 0
    diff = warm_map[hit] - cold_map[hit]
    null_mode = float(np.sum(diff * wmap[hit]) / np.sum(wmap[hit]))
    resid = float(np.max(np.abs(diff - null_mode)))
    assert resid < 1e-4, \
        f"criterion 8: warm and cold maps disagree beyond the null " \
        f"mode (max {resid:.2e} after removing the {null_mode:.2e} " \
        f"constant)"

    return {
        "serving_epochs": store.list_epochs(),
        "serving_folded": sorted(folded),
        "serving_kill_rc": -9,
        "serving_twin_byte_identical": True,
        "serving_cold_parity": parity,
        "serving_warm_iters": int(warm_cg["n_iter"]),
        "serving_cold_iters": int(cold_cg["n_iter"]),
        "serving_null_mode_resid": resid,
        "serving_freshness_s": float(
            store.manifest(2).get("freshness_s", 0.0)),
        "serving_wall_s": round(time.perf_counter() - t0, 3),
    }


def run_tiles_drill(workdir: str, seed: int = 0, n_files: int = 4,
                    timeout_s: float = 300.0) -> dict:
    """Criterion 9: the map tile read tier end-to-end (ISSUE 12).

    Real server subprocesses reduce committed waves into epochs and
    cut them into a content-addressed tiles root; a real
    ``tools/tile_server.py serve`` process fronts it over HTTP.
    Asserts, in order:

    - wave 1 publishes ``epoch-000001`` and tiles it (the map
      server's publish hook); the tiles ``CURRENT`` points at it;
    - wave 2's publisher draws ``kill_mid_publish@tiles-epoch-000002``
      — SIGKILLed after the epoch publish, after the tile OBJECTS are
      written, before the tile manifest lands. The epoch stands, the
      tile tier still serves epoch 1 whole (old-or-new, never torn);
    - the CLI backfill (``tile_server.py tile``) repairs the gap
      idempotently, and a full re-tile of epoch 2 into a FRESH root
      yields byte-identical tile hashes (deterministic encoding), so
      the published delta is exactly the full-retile diff;
    - an HTTP cutout of epoch 2 is bit-identical to slicing the
      expanded epoch FITS; conditional requests 304; a tiles rollback
      moves ``/v1/current`` atomically while the epoch-addressed URLs
      keep validating (a pinned reader's cache stays warm);
    - each serving process landed on its OWN telemetry lane
      (auto-incremented rank >= 1000 streams in the state dir);
    - ``MapServer.evict`` retracts a served file: the downdated epoch
      passes the (relaxed) fence with the SHRUNKEN census, its tiles
      are byte-identical to epoch 1's (content addressing across
      history), and the admission scan does NOT re-admit the
      retracted file.
    """
    import json
    import shutil
    import subprocess
    import sys
    import urllib.error
    import urllib.request

    from comapreduce_tpu.mapmaking.fits_io import read_fits_image
    from comapreduce_tpu.serving.epochs import EpochStore
    from comapreduce_tpu.serving.ledger import ServedLedger
    from comapreduce_tpu.tiles.blob import decode_tile
    from comapreduce_tpu.tiles.tiler import TileSet, tile_epoch

    t0 = time.perf_counter()
    os.makedirs(workdir, exist_ok=True)
    files = []
    for i in range(n_files):
        path = os.path.join(workdir, f"Level2_tiles-{i:04d}.hd5")
        if not os.path.exists(path):
            _write_level2(path, seed=2000 + seed * 10 + i,
                          drift=6.0, rw=0.3, raster=True)
        files.append(os.path.abspath(path))
    names = sorted(os.path.basename(f) for f in files)
    wave1, wave2 = files[:-1], files[-1:]

    dirs = {k: os.path.join(workdir, f"tiles-{k}")
            for k in ("state", "epochs", "root", "retile")}
    for d in dirs.values():
        shutil.rmtree(d, ignore_errors=True)
        os.makedirs(d)
    env = _child_env()
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "tools")

    def run_server(chaos=""):
        cmd = [sys.executable, "-m",
               "comapreduce_tpu.resilience.drill", "--serving",
               f"--state-dir={dirs['state']}",
               f"--epochs-dir={dirs['epochs']}",
               f"--tiles-dir={dirs['root']}", "--tile-px=16",
               "--telemetry", f"--seed={seed}"]
        if chaos:
            cmd.append(f"--chaos={chaos}")
        pr = subprocess.run(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, timeout=timeout_s)
        return pr.returncode, (pr.stdout or b"").decode(errors="replace")

    # ---- wave 1: publish + tile ----
    _commit_done(dirs["state"], wave1)
    rc, out = run_server()
    assert rc == 0, f"criterion 9: epoch-1 publish failed ({rc}):\n{out}"
    store = EpochStore(dirs["epochs"])
    ts = TileSet(dirs["root"])
    man1 = ts.manifest(1)
    assert store.current() == 1 and ts.current() == 1 and man1, \
        f"criterion 9: epoch-1 not tiled (tiles CURRENT={ts.current()})"
    assert man1["n_tiles"] > 1, \
        f"criterion 9: {man1['n_tiles']} tile(s) — the 16px grid " \
        "should cut the 64x64 field into several"

    # ---- wave 2: SIGKILL between the epoch publish and the tile
    # manifest write (the widest tile-tier window) ----
    _commit_done(dirs["state"], wave2)
    rc, out = run_server(chaos="kill_mid_publish@tiles-epoch-000002")
    assert rc == -9, \
        f"criterion 9: mid-tile-publish rank exited {rc}, expected " \
        f"SIGKILL (-9):\n{out}"
    assert store.current() == 2, \
        "criterion 9: the EPOCH publish should have completed before " \
        f"the tile kill (current={store.current()})"
    ts = TileSet(dirs["root"])
    assert ts.latest() == 1 and ts.current() == 1 and \
        ts.manifest(2) is None, \
        "criterion 9: tile tier torn after mid-tile-publish kill " \
        f"(latest={ts.latest()} current={ts.current()})"

    # ---- CLI backfill repairs the gap ----
    pr = subprocess.run(
        [sys.executable, os.path.join(tools, "tile_server.py"), "tile",
         f"--epochs-dir={dirs['epochs']}", f"--tiles-dir={dirs['root']}",
         "--tile-px=16"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        timeout=timeout_s)
    assert pr.returncode == 0, \
        f"criterion 9: tile backfill failed:\n{pr.stdout.decode()}"
    man2 = ts.manifest(2)
    assert man2 is not None and ts.current() == 2, \
        "criterion 9: backfill did not publish the epoch-2 tile set"

    # ---- delta == full-retile diff; hashes byte-stable across roots
    retile = tile_epoch(store.epoch_dir(2), dirs["retile"], tile_px=16)
    assert retile["tiles"] == man2["tiles"], \
        "criterion 9: re-tiling epoch 2 into a fresh root changed " \
        "tile hashes — the blob encoding is not deterministic"
    delta = ts.delta(2)
    want_changed = {k for k, v in man2["tiles"].items()
                    if (man1["tiles"].get(k) or [None])[0] != v[0]}
    want_removed = sorted(k for k in man1["tiles"]
                          if k not in man2["tiles"])
    assert set(delta["changed"]) == want_changed and \
        delta["removed"] == want_removed, \
        f"criterion 9: delta ({delta['n_changed']} changed, " \
        f"{delta['n_removed']} removed) is not the exact manifest diff"
    n_unchanged = sum(1 for k, v in man2["tiles"].items()
                      if (man1["tiles"].get(k) or [None])[0] == v[0])
    assert delta["n_unchanged"] == n_unchanged, \
        "criterion 9: delta n_unchanged miscounts byte-stable tiles"

    # ---- HTTP: cutout bit-identity, 304s, rollback ----
    srv = subprocess.Popen(
        [sys.executable, os.path.join(tools, "tile_server.py"), "serve",
         f"--tiles-dir={dirs['root']}", "--port=0",
         f"--epochs-dir={dirs['epochs']}",
         f"--telemetry-dir={dirs['state']}",
         f"--max-wall-s={timeout_s}"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        line = srv.stdout.readline().decode()
        assert "listening on http://" in line, \
            f"criterion 9: tile server did not start: {line}"
        base = line.split("listening on ")[1].split("/ ")[0]

        def fetch(url, etag=None):
            rq = urllib.request.Request(base + url)
            if etag:
                rq.add_header("If-None-Match", etag)
            try:
                with urllib.request.urlopen(rq, timeout=10) as r:
                    return r.status, dict(r.headers), r.read()
            except urllib.error.HTTPError as e:
                return e.code, dict(e.headers), b""

        st, _, body = fetch("/v1/current")
        assert st == 200 and json.loads(body)["epoch"] == 2, \
            f"criterion 9: /v1/current wrong: {st} {body!r}"
        x0, y0, w, h = 5, 9, 37, 21   # crosses 16px tile boundaries
        st, hdrs, blob = fetch(
            f"/v1/epochs/2/cutout?x0={x0}&y0={y0}&w={w}&h={h}")
        assert st == 200 and "immutable" in hdrs.get("Cache-Control", ""),\
            f"criterion 9: cutout fetch failed ({st})"
        cut = decode_tile(blob)["products"]
        full = {nm: np.asarray(arr, np.float32) for nm, _, arr in
                read_fits_image(os.path.join(store.epoch_dir(2),
                                             "map_band0.fits"))}
        for nm, ref in full.items():
            got = cut[nm]
            assert np.array_equal(got, ref[y0:y0 + h, x0:x0 + w]), \
                f"criterion 9: HTTP cutout {nm} != expanded FITS slice"
        etag = hdrs["ETag"]
        st, _, _ = fetch(
            f"/v1/epochs/2/cutout?x0={x0}&y0={y0}&w={w}&h={h}", etag)
        assert st == 304, f"criterion 9: cutout revalidation got {st}"
        st, mh, _ = fetch("/v1/epochs/2/manifest.json")
        man_etag = mh["ETag"]
        # rollback: /v1/current swaps atomically; epoch-addressed URLs
        # keep validating (the pinned reader's cache stays intact)
        ts.set_current(1, force=True)
        st, _, body = fetch("/v1/current")
        assert st == 200 and json.loads(body)["epoch"] == 1, \
            "criterion 9: /v1/current did not follow the rollback"
        st, _, _ = fetch("/v1/epochs/2/manifest.json", man_etag)
        assert st == 304, \
            "criterion 9: epoch-2 manifest ETag broke across rollback"
        ts.set_current(2)
        st, _, body = fetch("/v1/status")
        assert st == 200 and json.loads(body)["current"] == 2
    finally:
        srv.kill()
        srv.wait(timeout=30)

    # ---- telemetry: every serving process on its own lane ----
    lanes = sorted(int(f.split("rank")[1].split(".")[0])
                   for f in os.listdir(dirs["state"])
                   if f.startswith("events.rank")
                   and int(f.split("rank")[1].split(".")[0]) >= 1000)
    assert len(lanes) >= 3 and len(set(lanes)) == len(lanes), \
        f"criterion 9: serving-lane ranks collided: {lanes} (two map " \
        "server runs + the tile server must each get a fresh stream)"

    # ---- evict: downdated epoch past the fence, byte-stable tiles --
    from comapreduce_tpu.mapmaking.wcs import WCS
    from comapreduce_tpu.serving.server import MapServer

    wcs = WCS.from_field((170.25, 52.25), (1.0 / 60, 1.0 / 60), (64, 64))
    server = MapServer(
        dirs["state"], dirs["epochs"], wcs=wcs, band=0,
        offset_length=50, n_iter=300, threshold=1e-8,
        medfilt_window=201, use_calibration=False, warm_start=False,
        tiles_root=dirs["root"], tile_px=16)
    evicted = os.path.basename(wave2[0])
    n3 = server.evict(evicted)
    assert n3 == 3, f"criterion 9: evict published {n3}, expected 3"
    man_e = store.manifest(3)
    assert man_e.get("downdated") is True and \
        man_e.get("evicted") == [evicted] and \
        store.census(3) == {os.path.basename(f) for f in wave1}, \
        "criterion 9: downdated epoch census/flags wrong"
    man3 = TileSet(dirs["root"]).manifest(3)
    assert man3 is not None and man3["tiles"] == man1["tiles"], \
        "criterion 9: evicting back to epoch-1's census did not " \
        "reproduce epoch-1's tile hashes (content addressing broke)"
    # the watcher still lists the evicted commit; admission must skip
    assert server.admit_new() == [] and evicted not in server.ledger, \
        "criterion 9: the admission scan re-admitted an evicted file"
    led = ServedLedger(os.path.join(dirs["epochs"], "served.jsonl"))
    assert evicted in led.retracted and evicted not in led, \
        "criterion 9: retraction did not survive a ledger reload"

    return {
        "tiles_epochs": ts.list_tiled(),
        "tiles_n_tiles": [man1["n_tiles"], man2["n_tiles"],
                          man3["n_tiles"]],
        "tiles_kill_rc": -9,
        "tiles_delta_changed": int(delta["n_changed"]),
        "tiles_delta_unchanged": int(delta["n_unchanged"]),
        "tiles_retile_byte_identical": True,
        "tiles_cutout_bit_identical": True,
        "tiles_serving_lanes": lanes,
        "tiles_evict_epoch": int(n3),
        "tiles_census": names,
        "tiles_wall_s": round(time.perf_counter() - t0, 3),
    }


def run_integrity_drill(workdir: str, seed: int = 0,
                        n_files: int = 4) -> dict:
    """Criterion 10 (the integrity plane, docs/OPERATIONS.md §20): one
    byte flipped per durable artifact class — Level-2 checkpoint,
    BlockCache spill, solver snapshot, epoch FITS, tile object, ledger
    line — asserting 100% detection by the offline fsck, the correct
    per-class triage at every read boundary (``corrupt`` ledger
    disposition + skip for the checkpoint; cache-miss + unlink for the
    spill; cold solve for the snapshot; ``verify_epoch`` problems for
    the FITS; ``CorruptArtifactError`` + unlink for the tile;
    dropped-and-counted for the ledger line), that chaos ``bit_rot``
    fires post-commit (always detectable) at most once per basename,
    and that after ``campaign_fsck --repair`` + re-derivation the
    final map is byte-identical to the clean run's."""
    import json as _json
    import subprocess
    import sys as _sys

    from comapreduce_tpu.ingest.cache import BlockCache
    from comapreduce_tpu.mapmaking.destriper import (
        load_solver_checkpoint, save_solver_checkpoint)
    from comapreduce_tpu.mapmaking.wcs import WCS
    from comapreduce_tpu.resilience import QuarantineLedger, Resilience
    from comapreduce_tpu.resilience.chaos import ChaosMonkey, flip_byte
    from comapreduce_tpu.resilience.integrity import (
        CorruptArtifactError, seal_json, verify_file, write_sidecar)
    from comapreduce_tpu.resilience.retry import RetryPolicy
    from comapreduce_tpu.serving.epochs import (EpochStore, verify_epoch,
                                                verify_epoch_product)
    from comapreduce_tpu.tiles.store import TileStore

    t0 = time.perf_counter()
    workdir = os.path.abspath(workdir)
    os.makedirs(workdir, exist_ok=True)

    def _fixture(i: int) -> None:
        path = os.path.join(workdir, f"Level2_comap-{i:04d}.hd5")
        if os.path.exists(path):
            os.unlink(path)  # HDF5Store.write appends into rotted files
        _write_level2(path, seed=3000 + seed * 10 + i)
        write_sidecar(path, path, kind="checkpoint")
        return path

    files = [_fixture(i) for i in range(n_files)]
    wcs = WCS.from_field((170.25, 52.25), (1.0 / 60, 1.0 / 60), (64, 64))
    clean_map = np.asarray(_solve(_read(files, wcs)).destriped_map
                           ).tobytes()

    # -- one committed artifact of every other class --------------------
    spill_dir = os.path.join(workdir, "spill")
    cache = BlockCache(max_bytes=64, spill_dir=spill_dir)
    spill_payload = np.arange(4096, dtype=np.float32)
    cache.put(files[0], spill_payload)   # oversized -> straight to disk
    spill_file = [os.path.join(spill_dir, n)
                  for n in sorted(os.listdir(spill_dir))
                  if not n.endswith(".s256")][0]

    sck = os.path.join(workdir, "solver_band0.npz")
    save_solver_checkpoint(sck, np.ones(32, np.float32), 7,
                           [1e-3, 1e-4], "precond-drill")

    epochs_dir = os.path.join(workdir, "epochs")
    es = EpochStore(epochs_dir)

    def _products(tmpdir: str) -> dict:
        with open(os.path.join(tmpdir, "map_band0.fits"), "wb") as f:
            f.write(b"SIMPLE  =                    T" + b"\x07" * 256)
        return {"maps": ["map_band0.fits"]}

    n_epoch = es.publish([os.path.basename(f) for f in files],
                         _products)
    epoch_dir = es.epoch_dir(n_epoch)
    fits_path = os.path.join(epoch_dir, "map_band0.fits")

    tiles_root = os.path.join(workdir, "tiles")
    tstore = TileStore(tiles_root)
    tile_blob = bytes(range(256)) * 3
    digest, _ = tstore.put(tile_blob)
    os.makedirs(os.path.join(tiles_root, "manifests"), exist_ok=True)
    with open(os.path.join(tiles_root, "manifests",
                           "epoch-000001.json"), "w",
              encoding="utf-8") as f:
        _json.dump(seal_json({"schema": 1, "kind": "tiles", "epoch": 1,
                              "tiles": {"b0/0": [digest, len(tile_blob),
                                                 256]}}), f)

    ledger_path = os.path.join(workdir, "quarantine.jsonl")
    led = QuarantineLedger(ledger_path)
    for f in (files[2], files[3]):
        led.record(f, failure_class="transient",
                   disposition="recovered", stage="drill",
                   message="integrity-drill warmup")

    # -- chaos bit_rot: post-commit, once per basename, detectable ------
    monkey = ChaosMonkey("bit_rot", seed=seed)
    assert monkey.maybe_bit_rot(files[2]), \
        "criterion 10: bit_rot did not fire on a committed checkpoint"
    assert not monkey.maybe_bit_rot(files[2]), \
        "criterion 10: bit_rot re-rotted the same basename (repairs " \
        "could never converge)"
    try:
        verify_file(files[2], kind="checkpoint")
        raise AssertionError(
            "criterion 10: post-commit bit_rot escaped verify_file — "
            "the sidecar did not hash the honest bytes")
    except CorruptArtifactError:
        pass
    _fixture(2)  # re-derive: same seed, same data, fresh sidecar

    # -- flip one byte per artifact class -------------------------------
    victims = {"checkpoint": files[1], "spill": spill_file,
               "solver": sck, "epoch": fits_path,
               "tile": tstore.path(digest)}
    for path in victims.values():
        flip_byte(path, seed=seed + 1)
    with open(ledger_path, "r", encoding="utf-8") as f:
        lines = f.read().splitlines()
    doc = _json.loads(lines[0])
    doc["disposition"] = "quarantined"  # body edited, seal left stale
    lines[0] = _json.dumps(doc, separators=(",", ":"), default=str)
    with open(ledger_path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")

    # -- 100% detection: the offline fsck sees every class --------------
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    fsck = os.path.join(root, "tools", "campaign_fsck.py")

    def _fsck(*extra) -> tuple:
        proc = subprocess.run(
            [_sys.executable, fsck, workdir, "--json", *extra],
            capture_output=True, text=True, env=_child_env())
        assert proc.stdout, \
            f"criterion 10: fsck produced no report: {proc.stderr}"
        return proc.returncode, _json.loads(proc.stdout)

    rc, rep = _fsck()
    corrupt_paths = {p["path"] for p in rep["problems"]
                     if p["problem"] == "corrupt"}
    missed = {cls for cls, path in victims.items()
              if path not in corrupt_paths}
    assert not missed, \
        f"criterion 10: fsck missed corrupt class(es) {sorted(missed)}"
    assert ledger_path in corrupt_paths, \
        "criterion 10: fsck missed the corrupt ledger line"
    assert rc == 1, "criterion 10: fsck exited 0 over corruption"

    # -- per-class runtime triage ---------------------------------------
    triage_path = os.path.join(workdir, "quarantine-triage.jsonl")
    res = Resilience(ledger=QuarantineLedger(triage_path),
                     retry=RetryPolicy(max_retries=1, base_s=0.0,
                                       seed=seed))
    data_tri = _read(files, wcs, resilience=res)
    assert files[1] not in data_tri.files, \
        "criterion 10: a corrupt checkpoint fed the solve"
    tri = QuarantineLedger(triage_path)
    assert any(e.failure_class == "corrupt"
               and e.disposition == "corrupt"
               and e.unit.get("file") == files[1]
               for e in tri.entries), \
        "criterion 10: corrupt checkpoint not ledgered corrupt/corrupt"
    assert not any(e.disposition == "quarantined" for e in tri.entries), \
        "criterion 10: corruption mis-triaged as a quarantine"

    assert cache.get(files[0]) is None, \
        "criterion 10: a rotted spill entry was served"
    assert not os.path.exists(spill_file), \
        "criterion 10: rotted spill entry not unlinked"
    cache.put(files[0], spill_payload)
    assert np.array_equal(cache.get(files[0]), spill_payload), \
        "criterion 10: re-spilled entry unreadable"

    assert load_solver_checkpoint(sck, "precond-drill") is None, \
        "criterion 10: a rotted solver snapshot warm-started a solve"
    assert not os.path.exists(sck), \
        "criterion 10: rotted solver snapshot not unlinked"
    save_solver_checkpoint(sck, np.ones(32, np.float32), 7,
                           [1e-3, 1e-4], "precond-drill")
    assert load_solver_checkpoint(sck, "precond-drill")["n_done"] == 7

    nok, problems = verify_epoch(epoch_dir)
    assert [p[0] for p in problems] == ["map_band0.fits"], \
        f"criterion 10: verify_epoch reported {problems}"
    assert verify_epoch_product(epoch_dir, "map_band0.fits") is False, \
        "criterion 10: rotted epoch product verified True/None"

    try:
        tstore.get(digest)
        raise AssertionError("criterion 10: a rotted tile object was "
                             "served (CAS name no longer matches "
                             "content)")
    except CorruptArtifactError:
        pass
    assert not os.path.exists(tstore.path(digest)), \
        "criterion 10: rotted tile object not unlinked"
    d2, renewed = tstore.put(tile_blob)
    assert d2 == digest and renewed and tstore.get(digest) == tile_blob, \
        "criterion 10: tile re-put did not repair the object"

    led2 = QuarantineLedger(ledger_path)
    assert led2.corrupt_lines == 1, \
        f"criterion 10: expected 1 dropped ledger line, counted " \
        f"{led2.corrupt_lines}"
    assert len(led2.entries) == 1, \
        "criterion 10: the intact ledger line did not survive the drop"

    # -- fsck --repair + re-derivation -> byte-identical map ------------
    rc, rep = _fsck("--repair")
    assert rc == 0 and rep["ok"], \
        f"criterion 10: fsck --repair did not converge: " \
        f"{rep['problems']}"
    assert not os.path.exists(files[1]), \
        "criterion 10: repair kept a corrupt re-derivable checkpoint"
    assert not os.path.exists(epoch_dir), \
        "criterion 10: repair kept a corrupt epoch"
    _fixture(1)  # the re-reduction the runner would perform
    n2 = EpochStore(epochs_dir).publish(
        [os.path.basename(f) for f in files], _products)
    assert not verify_epoch(es.epoch_dir(n2))[1], \
        "criterion 10: republished epoch failed verification"
    final_map = np.asarray(_solve(_read(files, wcs)).destriped_map
                           ).tobytes()
    assert final_map == clean_map, \
        "criterion 10: repaired campaign's map != clean run's map"

    return {
        "criterion": "10-integrity",
        "n_classes": 6,
        "n_detected": 6,
        "corrupt_paths": sorted(os.path.basename(p)
                                for p in corrupt_paths),
        "ledger_lines_dropped": led2.corrupt_lines,
        "map_identical": True,
        "integrity_wall_s": round(time.perf_counter() - t0, 3),
    }


def _serving_worker_main(argv=None) -> int:
    """One serving-drill server invocation (``python -m ... --serving``):
    build a ``MapServer`` over the shared state dir and attempt exactly
    one epoch (``poll_once(force=True)``) — resume recovery
    (tmp sweep + orphan adoption) runs in the constructor, so a
    restarted invocation IS the resumed server."""
    import argparse

    from comapreduce_tpu.mapmaking.wcs import WCS
    from comapreduce_tpu.resilience.chaos import ChaosMonkey
    from comapreduce_tpu.serving.server import MapServer

    p = argparse.ArgumentParser(prog="drill-serving-worker")
    p.add_argument("--state-dir", required=True)
    p.add_argument("--epochs-dir", required=True)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--chaos", default="")
    p.add_argument("--no-warm-start", action="store_true")
    p.add_argument("--tiles-dir", default="",
                   help="also cut each published epoch into this tiles "
                   "root (the tiles drill)")
    p.add_argument("--tile-px", type=int, default=16)
    p.add_argument("--telemetry", action="store_true",
                   help="configure the serving telemetry lane (auto "
                   "rank >= 1000) in the state dir")
    a = p.parse_args(argv)
    if a.telemetry:
        from comapreduce_tpu.telemetry import (TELEMETRY,
                                               serving_lane_rank)

        TELEMETRY.configure(a.state_dir,
                            rank=serving_lane_rank(a.state_dir))
    wcs = WCS.from_field((170.25, 52.25), (1.0 / 60, 1.0 / 60), (64, 64))
    monkey = ChaosMonkey(a.chaos, seed=a.seed) if a.chaos else None
    server = MapServer(
        a.state_dir, a.epochs_dir, wcs=wcs, band=0, offset_length=50,
        n_iter=300, threshold=1e-8, medfilt_window=201,
        use_calibration=False, warm_start=not a.no_warm_start,
        tiles_root=a.tiles_dir, tile_px=a.tile_px, chaos=monkey)
    n = server.poll_once(force=True)
    print(f"serving-worker: published {n}")
    return 0


if __name__ == "__main__":
    import sys as _sys

    _argv = _sys.argv[1:]
    if "--serving" in _argv:
        _argv.remove("--serving")
        raise SystemExit(_serving_worker_main(_argv))
    raise SystemExit(_elastic_worker_main(_argv))
