"""Campaign status as data: the schema-2 watchdog report, as a library.

Relocated from ``tools/watchdog_report.py`` so the live observability
plane (:mod:`comapreduce_tpu.telemetry.live` — the ``/v1/campaign``
endpoint and the ``/healthz`` probe) and the CLI report render the SAME
report from the SAME rules; the tool is now a thin wrapper. Staleness
is judged exclusively through
:func:`comapreduce_tpu.resilience.heartbeat.heartbeat_stale` /
:func:`~comapreduce_tpu.resilience.heartbeat.stale_age` — one home for
the out-of-range predicate, shared with the lease scheduler's
``expired()``.

``build_report`` reads every ``heartbeat.rank*.json``,
``quarantine*.jsonl``, ``lease.*.json`` and the ``queue.json`` manifest
in the run's state directory and answers the on-call questions in one
dict: which ranks are alive, where each one is, which operations
stalled or hung, which units the run deferred or durably skipped, and —
for elastic campaigns (docs/OPERATIONS.md §11) — who holds which lease
at what generation and whether any expired lease sits unreclaimed.

Probe policy (the exit-code / ``/healthz`` rule): a campaign is
UNHEALTHY when any expected rank's heartbeat is stale OR any lease is
expired-but-unreclaimed — :func:`report_healthy`.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import time

from comapreduce_tpu.resilience.heartbeat import (heartbeat_age_s,
                                                  heartbeat_stale,
                                                  read_heartbeats,
                                                  stale_age)
from comapreduce_tpu.resilience.ledger import QuarantineLedger
from comapreduce_tpu.resilience.lease import read_lease

__all__ = ["build_report", "report_healthy", "resolve_state_dir"]


def resolve_state_dir(output_dir: str) -> str:
    """The directory actually holding the run state: ``output_dir``
    itself, else its ``logs/`` child (the default ``[Global] log_dir``
    routing) when only that one has state files."""

    def has_state(d: str) -> bool:
        return any(_glob.glob(os.path.join(d, pat))
                   for pat in ("heartbeat.rank*.json", "lease.*.json",
                               "queue.json", "quarantine*.jsonl"))

    logs = os.path.join(output_dir, "logs")
    if not has_state(output_dir) and os.path.isdir(logs) \
            and has_state(logs):
        return logs
    return output_dir


def build_report(output_dir: str, stale_s: float = 60.0,
                 n_ranks: int = 0) -> dict:
    """The report as data (rendering and exit policy live with the
    callers — ``tools/watchdog_report.py`` and the live plane)."""
    now = time.time()
    output_dir = resolve_state_dir(output_dir)
    beats = read_heartbeats(output_dir)
    expected = range(n_ranks) if n_ranks > 0 else sorted(beats)
    ranks = []
    for r in expected:
        hb = beats.get(r)
        if hb is None:
            ranks.append({"rank": r, "present": False, "stale": True})
            continue
        age = heartbeat_age_s(hb, now)
        # a rank that wrote its terminal beat ("<phase>.done" final
        # stage) exited cleanly and is not expected to beat again — a
        # finished campaign must probe healthy, not rot into 503/exit-1
        # once the TTL passes its last beat
        done = str(hb.get("stage", "")).endswith(".done")
        ranks.append({
            "rank": r, "present": True, "done": done,
            "age_s": round(age, 1),
            # out-of-range on EITHER side is stale: too old is dead,
            # and a negative age (future clock) is a skewed host with
            # no live evidence — exit-1 material for the cron probe
            "stale": not done and stale_age(age, stale_s),
            "stage": hb.get("stage", ""),
            "unit": hb.get("unit", ""),
            "seq": hb.get("seq", 0),
            "pid": hb.get("pid"),
            "host": hb.get("host", ""),
            "progress": hb.get("progress", {}),
            "deadline": hb.get("deadline"),
        })

    # one merged read-only view over every rank's ledger file
    ledgers = sorted(_glob.glob(os.path.join(output_dir,
                                             "quarantine*.jsonl")))
    entries = []
    summary: dict = {}
    stalls, hangs, corruption = [], [], []
    corrupt_lines = 0
    if ledgers:
        led = QuarantineLedger(ledgers[0],
                               read_paths=tuple(ledgers[1:]))
        entries = led.entries
        summary = led.summary()
        corrupt_lines = led.corrupt_lines
        for e in entries:
            if e.disposition == "corrupt":
                corruption.append({
                    "t": e.t, "unit": e.unit.get("file", ""),
                    "stage": e.stage, "message": e.message,
                    "disposition": e.disposition})
            if e.failure_class != "hang":
                continue
            row = {"t": e.t, "unit": e.unit.get("file", ""),
                   "stage": e.stage, "message": e.message,
                   "disposition": e.disposition}
            (stalls if e.disposition == "stalled" else hangs).append(row)

    queue, leases = _queue_report(output_dir, beats, stale_s, now)
    supervisor = _supervisor_report(output_dir, now)
    return {
        # schema 3 adds the "supervisor" block — ONLY when a control
        # plane actually ran here (supervisor.json exists); runs with
        # no supervisor stay byte-for-byte schema 2
        "schema": 3 if supervisor is not None else 2,
        **({"supervisor": supervisor}
           if supervisor is not None else {}),
        "output_dir": output_dir,
        "stale_s": stale_s,
        "ranks": ranks,
        "n_stale": sum(1 for r in ranks if r["stale"]),
        "ledger_files": [os.path.basename(p) for p in ledgers],
        "ledger_summary": summary,
        "n_ledger_events": len(entries),
        "n_stolen": sum(1 for e in entries
                        if e.disposition == "stolen"),
        "stalls": stalls[-20:],
        "hangs": hangs[-20:],
        # integrity plane (docs/OPERATIONS.md §20): artifacts whose
        # checksum verification failed, plus ledger lines dropped for
        # failing their own embedded seal
        "corruption": corruption[-20:],
        "n_corrupt": len(corruption),
        "n_corrupt_ledger_lines": corrupt_lines,
        "queue": queue,
        "leases": leases,
        "n_expired_leases": sum(1 for l in leases if l["expired"]),
    }


def report_healthy(rep: dict) -> bool:
    """The probe rule shared by the CLI exit code and ``/healthz``: an
    expired-but-unreclaimed lease means work nobody will finish —
    fail it like a stale rank. Schema 3 adds: a supervisor that
    stopped republishing mid-campaign is a dead control loop — the
    autoscaler will never replace the NEXT dead rank."""
    stuck = bool((rep.get("supervisor") or {}).get("stuck"))
    return not (rep["n_stale"] or rep["n_expired_leases"] or stuck)


def _supervisor_report(state_dir: str, now: float) -> dict | None:
    """The control-plane block of the schema-3 report: the latest
    ``supervisor.json`` snapshot plus the stuck verdict and the last
    recorded ``control.decision``; None (stay schema 2) when no
    supervisor ever published here."""
    from comapreduce_tpu.control.supervisor import (read_supervisor,
                                                    supervisor_stuck)

    snap = read_supervisor(state_dir)
    if snap is None:
        return None
    return {
        "t_unix": snap.get("t_unix"),
        "age_s": round(now - float(snap.get("t_unix") or 0.0), 1),
        "desired_ranks": snap.get("desired_ranks"),
        "live_ranks": snap.get("live_ranks", []),
        "dead_ranks": snap.get("dead_ranks", []),
        "backlog": snap.get("backlog"),
        "shed_backlog": snap.get("shed_backlog"),
        "files_per_hour": snap.get("files_per_hour"),
        "eta_s": snap.get("eta_s"),
        "drained": bool(snap.get("drained")),
        "n_decisions": snap.get("n_decisions", 0),
        "last_decision": snap.get("last_decision"),
        "stuck": supervisor_stuck(snap, now),
    }


def _queue_report(state_dir: str, beats: dict, stale_s: float,
                  now: float) -> tuple:
    """Elastic-campaign state: the ``queue.json`` manifest summary and
    one row per ``lease.*.json``. ``expired`` marks a lease whose
    owner shows no live heartbeat within ``stale_s`` yet which no
    survivor has reclaimed — the signal that a campaign is wedged
    (no rank left to steal)."""
    leases = []
    for p in sorted(_glob.glob(os.path.join(state_dir, "lease.*.json"))):
        try:
            age = now - os.stat(p).st_mtime
        except OSError:
            continue  # vanished mid-scan (a commit or steal in flight)
        st = read_lease(p)
        if st is None:
            # torn lease: no valid owner to be alive — reclaimable
            # (and 'expired' for the probe) once past the TTL
            leases.append({"key": os.path.basename(p), "state": "torn",
                           "owner": None, "generation": None,
                           "age_s": round(age, 1),
                           "expired": age > stale_s})
            continue
        row = {"key": st.get("key", os.path.basename(p)),
               "state": st.get("state", "?"),
               "owner": st.get("owner"),
               "generation": st.get("generation"),
               "stolen_from": st.get("stolen_from"),
               "done_by": st.get("done_by"),
               "age_s": round(age, 1), "expired": False}
        if row["state"] == "claimed" and age > stale_s:
            hb = beats.get(int(st.get("owner", -1)))
            row["expired"] = heartbeat_stale(hb, now, stale_s)
        leases.append(row)

    queue = None
    qpath = os.path.join(state_dir, "queue.json")
    try:
        with open(qpath, "r", encoding="utf-8") as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        manifest = None
    if manifest is not None or leases:
        n_files = len((manifest or {}).get("files", [])) or len(leases)
        n_done = sum(1 for l in leases if l["state"] == "done")
        n_claimed = sum(1 for l in leases if l["state"] == "claimed")
        queue = {"n_files": n_files, "n_done": n_done,
                 "n_claimed": n_claimed,
                 "n_pending": max(n_files - len(leases), 0),
                 "n_torn": sum(1 for l in leases
                               if l["state"] == "torn")}
    return queue, leases
