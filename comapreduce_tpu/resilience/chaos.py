"""Fault injection: deterministic chaos for the resilience layer.

Every failure path the resilience layer promises to survive — read
errors, truncated files, NaN bursts, slow reads, first-attempt flakes
— can be injected here *deterministically by seed*, so CI drills
(``tools/check_resilience.py``, ``bench.py --config resilience``)
exercise them on every run instead of production discovering them.

Config knob: ``[resilience] inject = "read_error:0.25,nan_burst:0.25"``
(TOML) / ``inject : read_error:0.25,nan_burst:0.25`` (INI) — a comma
list of ``kind[@substr][:rate]`` with rate in [0, 1] (default 1);
``@substr`` limits the fault to files whose basename contains
``substr`` (how the drills aim one fault at one file). Kinds:

- ``read_error`` — the loader raises ``OSError`` (every attempt);
- ``truncate``   — ``OSError`` worded like h5py's truncated-file error
  (same class as read_error on purpose: both are the retryable kind);
- ``flaky``      — ``OSError`` on the FIRST attempt only; a retry
  succeeds (the recovered-by-retry path);
- ``nan_burst``  — the decoded payload's TOD gets a NaN burst in one
  feed (copy-on-poison: a shared cache payload is never mutated);
- ``slow_read``  — the read sleeps ``slow_s`` first (exercises the
  prefetch queue under a lagging producer);
- ``hang``       — the read BLOCKS (up to ``hang_s``, or until
  :meth:`ChaosMonkey.release`) on EVERY attempt — the stuck-NFS/
  stuck-in-C-code failure the watchdog's hard deadline exists to
  cancel. Unlike ``slow_read`` the block outlasts any sane deadline;
  the drill asserts the read is abandoned at the hard deadline and the
  unit ledgered as a ``hang``. Call ``release()`` when a drill ends so
  abandoned worker threads exit promptly instead of sleeping out
  ``hang_s``.
- ``write_stall`` — the OUTPUT-side ``hang``: a ``data.writeback``
  commit for a matching target path blocks (same release/``hang_s``
  semantics) on the background writer thread. The drill asserts the
  writeback watchdog cancels it at the hard deadline, the unit is
  ledgered ``hang``/``rejected``, and the abandoned writer's late
  commit is skipped (committed checkpoints are never dropped or
  reordered).
- ``rank_kill``   — the whole PROCESS dies (SIGKILL to self) the
  moment a matching file is claimed from the elastic queue
  (``pipeline.scheduler``) — the preempted-node case: the lease file
  leaks, the heartbeat goes silent, and a survivor must steal the
  unit. Fired at most once per monkey (the process is gone anyway in
  real runs; the cap keeps in-process tests sane).
- ``rank_pause``  — the ZOMBIE case: the rank's heartbeat is frozen
  (``Heartbeat.pause``) when a matching file is claimed, but the rank
  keeps running and will try to commit late. The drill asserts the
  stolen-and-redone unit's generation fence rejects that commit.
- ``late_file``   — a matching file's ARRIVAL is delayed: the serving
  drill/bench replay asks :meth:`ChaosMonkey.arrival_delay` for each
  file's extra commit latency, so freshness metrics and the incremental
  fold see a straggler (``slow_s`` seconds; deterministic by seed).
- ``kill_mid_publish`` — the epoch-publication ``rank_kill``: SIGKILL
  to self between writing an epoch's temp dir and the atomic rename
  (``serving.epochs.EpochStore.publish``). The drill asserts the
  ``current`` pointer still resolves to a COMPLETE epoch and that a
  restarted server republishes the lost epoch. Matches on the epoch
  name (``@epoch-000002`` aims it), fires at most once per monkey.
- ``load_spike`` — a deterministic BURST of extra queued files lands
  mid-run: when a matching file is committed, the elastic scheduler
  asks :meth:`ChaosMonkey.maybe_spike` and appends the monkey's
  ``spike_files`` (set by the drill harness) to the shared
  ``queue.json`` manifest, exactly as a late observing session being
  dropped into a live campaign would. Fires at most once per monkey —
  the drill for admission control (``control.admission``), the same
  way ``rank_kill`` drills the autoscaler.
- ``bit_rot`` — media decay: one byte of a COMMITTED artifact is
  flipped in place (deterministic offset and xor mask by
  ``(seed, kind, basename)``), AFTER the integrity sidecar recorded
  the honest digest — so the rot is always detectable, exactly like
  real rot under a real checksum. Invoked post-commit by the integrity
  plane (:func:`resilience.integrity.committed_replace`) and directly
  by drills; fires at most once per matching basename so a repaired
  artifact stays repaired.

Whether a given file draws a given fault depends only on
``(seed, kind, basename)`` — stable across runs, across iteration
order, and across serial-vs-prefetched paths.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time

import numpy as np

__all__ = ["ChaosMonkey", "parse_inject_spec", "CHAOS_KINDS",
           "flip_byte"]

logger = logging.getLogger("comapreduce_tpu")

CHAOS_KINDS = ("read_error", "truncate", "flaky", "nan_burst",
               "slow_read", "hang", "write_stall", "rank_kill",
               "rank_pause", "late_file", "kill_mid_publish",
               "load_spike", "bit_rot")

# TOD datasets a NaN burst can poison, by payload schema
_POISON_KEYS = ("spectrometer/tod", "averaged_tod/tod",
                "frequency_binned/tod")


def flip_byte(path: str, seed: int = 0) -> tuple[int, int]:
    """Flip one byte of ``path`` in place — deterministic offset and
    (never-zero) xor mask from ``(seed, basename)``. Returns
    ``(offset, mask)`` so drills/tests can assert or undo the exact
    damage. Empty files are left alone (nothing to rot)."""
    size = os.path.getsize(path)
    if size <= 0:
        return (-1, 0)
    rng = random.Random(f"{seed}:bit_rot_at:{os.path.basename(path)}")
    offset = rng.randrange(size)
    mask = 1 + rng.randrange(255)  # never 0: the flip always flips
    with open(path, "r+b") as f:
        f.seek(offset)
        byte = f.read(1)
        f.seek(offset)
        f.write(bytes([byte[0] ^ mask]))
    return (offset, mask)


def parse_inject_spec(spec: str) -> list:
    """``"read_error@0003:0.5,nan_burst"`` -> ``[(kind, substr, rate)]``
    (``substr`` '' = every file). Empty spec -> ``[]``. Unknown kinds
    and rates outside [0, 1] raise."""
    out: list[tuple[str, str, float]] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        head, _, rate_s = part.partition(":")
        kind, _, substr = head.partition("@")
        kind = kind.strip()
        if kind not in CHAOS_KINDS:
            raise ValueError(f"unknown chaos kind {kind!r} "
                             f"(know {CHAOS_KINDS})")
        rate = float(rate_s) if rate_s.strip() else 1.0
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"chaos rate for {kind!r} must be in "
                             f"[0, 1], got {rate}")
        out.append((kind, substr.strip(), rate))
    return out


class ChaosMonkey:
    """Deterministic fault injector wrapping an ingest loader.

    ``injected`` logs every fault actually fired as
    ``(filename, kind)`` — the drill's ground truth when asserting the
    quarantine ledger caught everything.
    """

    def __init__(self, spec: str | list, seed: int = 0,
                 slow_s: float = 0.05, burst_frac: float = 0.05,
                 hang_s: float = 60.0):
        self.entries = (list(spec) if isinstance(spec, list)
                        else parse_inject_spec(spec))
        self.seed = int(seed)
        self.slow_s = float(slow_s)
        self.burst_frac = float(burst_frac)
        self.hang_s = float(hang_s)
        self.injected: list[tuple[str, str]] = []
        # the burst a ``load_spike`` releases (maybe_spike): the drill
        # harness fills this with the spike's filenames before the run
        self.spike_files: list[str] = []
        self._attempts: dict[str, int] = {}
        self._lock = threading.Lock()
        self._release = threading.Event()

    def release(self) -> None:
        """Unblock every in-flight (abandoned) ``hang`` read — drills
        call this on exit so orphaned worker threads die promptly."""
        self._release.set()

    def decide(self, filename: str) -> list:
        """Kinds that fire for this file — a pure function of
        ``(seed, kind, basename)`` (and the spec's ``@substr``
        targeting)."""
        base = os.path.basename(filename)
        fired = []
        for kind, substr, rate in self.entries:
            if kind in fired or rate <= 0.0:
                continue
            if substr and substr not in base:
                continue
            if random.Random(f"{self.seed}:{kind}:{base}").random() < rate:
                fired.append(kind)
        return fired

    def _note(self, filename: str, kind: str) -> None:
        with self._lock:
            self.injected.append((filename, kind))
        logger.info("chaos: injected %s into %s", kind, filename)

    def maybe_kill(self, filename: str) -> None:
        """SIGKILL the whole process (kind ``rank_kill``) — called by
        the scheduler at claim time, so the lease is already on disk
        and LEAKS exactly like a preempted node's would. No cleanup
        handlers run: that is the point."""
        if "rank_kill" not in self.decide(filename):
            return
        with self._lock:
            if any(k == "rank_kill" for _, k in self.injected):
                return  # at most once (a real kill never returns)
            self.injected.append((filename, "rank_kill"))
        logger.warning("chaos: rank_kill — SIGKILLing pid %d at claim "
                       "of %s", os.getpid(), filename)
        os.kill(os.getpid(), 9)  # signal.SIGKILL; never returns
        time.sleep(60.0)  # pathological platform: at least stall

    def maybe_pause(self, filename: str) -> bool:
        """True once when ``rank_pause`` fires for this file — the
        caller freezes the rank's heartbeat (``Heartbeat.pause``) but
        keeps working: the zombie whose stolen unit's late commit the
        lease generation fence must reject."""
        if "rank_pause" not in self.decide(filename):
            return False
        with self._lock:
            if any(k == "rank_pause" for _, k in self.injected):
                return False  # already a zombie
            self.injected.append((filename, "rank_pause"))
        logger.warning("chaos: rank_pause — freezing heartbeat at "
                       "claim of %s (zombie mode)", filename)
        return True

    def maybe_spike(self, filename: str) -> list:
        """The burst of extra queued files a ``load_spike`` releases
        when ``filename``'s commit matches — the elastic scheduler
        appends these to the shared ``queue.json`` manifest mid-run
        (``pipeline.scheduler.extend_manifest``). Empty when the kind
        does not fire, the burst list is empty, or the spike already
        fired (at most once per monkey: one spike with a known file
        set keeps the drill's exactly-once audit exact)."""
        if not self.spike_files or \
                "load_spike" not in self.decide(filename):
            return []
        with self._lock:
            if any(k == "load_spike" for _, k in self.injected):
                return []
            self.injected.append((filename, "load_spike"))
        logger.warning("chaos: load_spike — %d extra file(s) queued at "
                       "commit of %s", len(self.spike_files), filename)
        return list(self.spike_files)

    def arrival_delay(self, filename: str) -> float:
        """Extra seconds before ``filename``'s commit becomes visible
        (kind ``late_file``) — the serving drill/bench replay adds this
        to its arrival schedule so the incremental fold and the
        freshness metrics see a straggler. 0.0 when the kind does not
        fire; deterministic by ``(seed, kind, basename)``."""
        if "late_file" not in self.decide(filename):
            return 0.0
        self._note(filename, "late_file")
        return self.slow_s

    def maybe_kill_publish(self, epoch: str) -> None:
        """SIGKILL the whole process (kind ``kill_mid_publish``) —
        called by ``EpochStore.publish`` after the temp epoch dir is
        fully written and fsynced but BEFORE the atomic rename, the
        widest window a crashing publisher can leave garbage in. At
        most once per monkey (a real kill never returns)."""
        if "kill_mid_publish" not in self.decide(epoch):
            return
        with self._lock:
            if any(k == "kill_mid_publish" for _, k in self.injected):
                return
            self.injected.append((epoch, "kill_mid_publish"))
        logger.warning("chaos: kill_mid_publish — SIGKILLing pid %d "
                       "before the rename of %s", os.getpid(), epoch)
        os.kill(os.getpid(), 9)  # signal.SIGKILL; never returns
        time.sleep(60.0)  # pathological platform: at least stall

    def maybe_bit_rot(self, path: str) -> bool:
        """Flip one byte of the committed artifact at ``path`` (kind
        ``bit_rot``) — called post-commit by the integrity plane's
        :func:`~comapreduce_tpu.resilience.integrity.committed_replace`
        (i.e. AFTER the sidecar hashed the honest bytes, so injected
        rot is always detectable rot). At most once per basename: a
        rebuilt/repaired artifact is not re-rotted, so the recovery
        the drill asserts can actually converge. True when it fired."""
        if "bit_rot" not in self.decide(path):
            return False
        base = os.path.basename(path)
        with self._lock:
            if any(k == "bit_rot" and os.path.basename(f) == base
                   for f, k in self.injected):
                return False
            self.injected.append((path, "bit_rot"))
        try:
            offset, mask = flip_byte(path, self.seed)
        except OSError as exc:  # artifact raced away: nothing to rot
            logger.warning("chaos: bit_rot skipped for %s (%s)",
                           path, exc)
            return False
        logger.warning("chaos: bit_rot — flipped byte %d (xor 0x%02x) "
                       "of committed %s", offset, mask, path)
        return True

    def stall_write(self, path: str) -> None:
        """Block a writeback commit for ``path`` (kind ``write_stall``)
        until :meth:`release` or ``hang_s`` — invoked by
        ``data.writeback.Writeback`` inside its watchdog-supervised
        region, so the ``writeback.write`` hard deadline must cancel it
        exactly like a real stuck-in-C-code write."""
        if "write_stall" in self.decide(path):
            self._note(path, "write_stall")
            self._release.wait(self.hang_s)

    def wrap_loader(self, loader):
        """``loader(path) -> payload`` with faults injected around it."""

        def chaotic(path):
            kinds = self.decide(path)
            if "hang" in kinds:
                # blocks EVERY attempt (a retried hang hangs again)
                # until release() or hang_s — then falls through to the
                # real read, so an abandoned watchdog worker finishes
                # harmlessly (its result is discarded)
                self._note(path, "hang")
                self._release.wait(self.hang_s)
            if "slow_read" in kinds:
                self._note(path, "slow_read")
                time.sleep(self.slow_s)
            if "flaky" in kinds:
                with self._lock:
                    n = self._attempts[path] = \
                        self._attempts.get(path, 0) + 1
                if n == 1:
                    self._note(path, "flaky")
                    raise OSError(f"chaos: flaky read of {path} "
                                  "(succeeds on retry)")
            if "read_error" in kinds:
                self._note(path, "read_error")
                raise OSError(f"chaos: injected read error for {path}")
            if "truncate" in kinds:
                self._note(path, "truncate")
                # h5py's wording for a file cut short mid-copy
                raise OSError(f"chaos: unable to open file {path} "
                              "(truncated file, injected)")
            payload = loader(path)
            if "nan_burst" in kinds:
                payload = self._poison(path, payload)
            return payload

        return chaotic

    # -- NaN bursts --------------------------------------------------------
    def burst_coords(self, path: str, shape: tuple):
        """Deterministic burst placement for an array of ``shape``:
        ``(feed | None, start, n)`` — shared by the injector and by the
        drill, which reconstructs the exact faulted unit to build its
        zero-weighted reference run."""
        rng = random.Random(f"{self.seed}:burst:{os.path.basename(path)}")
        t_axis = int(shape[-1])
        n = max(1, int(t_axis * self.burst_frac))
        start = rng.randrange(max(t_axis - n, 1))
        feed = rng.randrange(shape[0]) if len(shape) > 1 else None
        return feed, start, n

    def _poison(self, path: str, payload):
        """NaN-burst one feed of the payload's TOD (copy-on-poison)."""
        data = payload.get("data") if isinstance(payload, dict) else None
        if data is None and hasattr(payload, "materialise") \
                and hasattr(payload, "__setitem__"):
            data = payload  # live store: item assignment replaces the
            # array, the store's own copy semantics apply
        if data is None:
            return payload
        for key in _POISON_KEYS:
            if key in data:
                arr = data[key]
                if hasattr(payload, "materialise") and data is payload:
                    arr = payload.materialise(key)
                arr = np.array(arr, copy=True)  # never poison a shared
                # cache payload in place
                feed, start, n = self.burst_coords(path, arr.shape)
                if feed is None:
                    arr[start:start + n] = np.nan
                else:
                    arr[feed, ..., start:start + n] = np.nan
                data[key] = arr
                self._note(path, "nan_burst")
                break
        return payload
