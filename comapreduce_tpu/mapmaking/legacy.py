"""Legacy Level-2 ("fg-survey") read path.

The reference's older map-making generation reads per-channel Level-2
files — ``level2/averaged_tod`` of shape (F, 4, 64, T) plus per-scan
statistics — and cleans each channel with stored coefficients before
averaging channels into one stream per feed
(``MapMaking/Types.py:550-623`` ``DataLevel2AverageHPX.getTOD``,
``MapMaking/DataReader.py:32-449`` ``ReadDataLevel2``). Per sample:

1. subtract the stored per-scan median-filter template scaled by the
   channel's ``filter_coefficients``;
2. subtract the atmosphere/ground model: the per-(band, scan) ``atmos``
   value stretched over the scan by the airmass (1/sin el) and scaled by
   the channel's ``atmos_coefficients``;
3. subtract the channel's scan median;
4. calibrate by the per-channel calibration factor;
5. average unmasked channels weighted by ``1/wnoise_auto^2``; the sample
   weight is the summed inverse variance.

Scans are truncated to offset multiples (``countDataSize`` semantics) and
concatenated across files into flat destriper vectors. The upstream class
is bit-rotted at HEAD (its ``AtmosGroundModel`` import no longer exists);
this is the working equivalent, kept numpy/h5py host-side — it is an IO
path, not device math.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

import numpy as np

from comapreduce_tpu.data.hdf5io import safe_hdf5_open

__all__ = ["LegacyLevel2Data", "read_legacy_level2"]

logger = logging.getLogger("comapreduce_tpu")


@dataclass
class LegacyLevel2Data:
    """Flat destriper vectors from legacy Level-2 files."""

    tod: np.ndarray        # f32[N]
    weights: np.ndarray    # f32[N]
    az: np.ndarray         # f32[N]
    el: np.ndarray         # f32[N]
    file_ids: np.ndarray   # i32[N]
    files: list


def _clean_feed_scan(tod, medfilt, medfilt_coef, atmos_val, atmos_coef,
                     el, cal_factors, channel_mask, wnoise):
    """Clean one (feed, scan) block (B, C, N) -> (avg(N), weight(N)).

    Vectorised over channels (the reference loops band x channel in
    Python, ``Types.py:592-599``).
    """
    B, C, N = tod.shape
    airmass = 1.0 / np.clip(np.sin(np.radians(el)), 0.05, None)
    # (B, C, N) models
    mdl = (medfilt[:, None, :N] * medfilt_coef[..., None]
           + (atmos_val[:, None, None] * airmass[None, None, :])
           * atmos_coef[..., None])
    cleaned = tod - mdl
    cleaned = cleaned - np.nanmedian(cleaned, axis=-1)[..., None]
    cal = np.where(cal_factors > 0, cal_factors, 1.0)
    cleaned = cleaned / cal[..., None]

    good = (channel_mask
            & np.isfinite(cleaned).all(axis=-1)
            & np.isfinite(wnoise)
            & (wnoise > 0))
    ivar = np.where(good, 1.0 / np.maximum(wnoise, 1e-30) ** 2, 0.0)
    bot = ivar.sum()
    if bot <= 0:
        return np.zeros(N), np.zeros(N)
    top = np.einsum("bcn,bc->n", np.where(good[..., None], cleaned, 0.0),
                    ivar)
    return top / bot, np.full(N, bot)


def read_legacy_level2(filenames, feeds=None, offset_length: int = 50,
                       channel_mask: np.ndarray | None = None,
                       cal_factors: np.ndarray | None = None):
    """Read legacy-format Level-2 files into flat destriper vectors.

    Expected schema (``Types.py:550-623``): ``level2/averaged_tod``
    (F, B, C, T), ``level2/Statistics/{scan_edges, filter_coefficients
    (F,B,C,S,1), atmos (F,B,S), atmos_coefficients (F,B,C,S,1),
    wnoise_auto (F,B,C,S... trailing 1), FilterTod_ScanXX (F,B,N)}``, and
    ``level1/spectrometer/pixel_pointing/pixel_{az,el}`` (F, T).

    ``feeds``: feed indices to use (default: all); ``channel_mask``: bool
    (F, B, C), True = use channel (the reference stores the inverse
    "masked" sense; pass usable-channel True here); ``cal_factors``:
    (F, B, C) calibration divisors (default 1).
    """
    tods, weis, azs, els, fids = [], [], [], [], []
    used = []
    for fid, filename in enumerate(filenames):
        try:
            with safe_hdf5_open(filename, "r") as h:
                tod_d = h["level2/averaged_tod"]
                F, B, C, T = tod_d.shape
                sel = list(range(F)) if feeds is None else list(feeds)
                edges = h["level2/Statistics/scan_edges"][...]
                mf_coef = h["level2/Statistics/filter_coefficients"][...]
                atmos = h["level2/Statistics/atmos"][...]
                at_coef = h["level2/Statistics/atmos_coefficients"][...]
                wn = h["level2/Statistics/wnoise_auto"][...]
                az_d = h["level1/spectrometer/pixel_pointing/pixel_az"]
                el_d = h["level1/spectrometer/pixel_pointing/pixel_el"]
                cmask = (np.ones((F, B, C), bool) if channel_mask is None
                         else np.asarray(channel_mask, bool))
                cal = (np.ones((F, B, C)) if cal_factors is None
                       else np.asarray(cal_factors, np.float64))
                for ifeed in sel:
                    tod_f = tod_d[ifeed].astype(np.float64)
                    az_f = az_d[ifeed].astype(np.float64)
                    el_f = el_d[ifeed].astype(np.float64)
                    for iscan, (start, end) in enumerate(edges):
                        start, end = int(start), int(end)
                        n = (end - start) // offset_length * offset_length
                        if n <= 0:
                            continue
                        end = start + n
                        medfilt = h["level2/Statistics/"
                                    f"FilterTod_Scan{iscan:02d}"][ifeed]
                        avg, w = _clean_feed_scan(
                            tod_f[..., start:end], medfilt,
                            mf_coef[ifeed, ..., iscan, 0],
                            atmos[ifeed, :, iscan],
                            at_coef[ifeed, ..., iscan, 0],
                            el_f[start:end], cal[ifeed],
                            cmask[ifeed],
                            wn[ifeed, ..., iscan, 0]
                            if wn.ndim == 5 else wn[ifeed, ..., iscan])
                        tods.append(avg.astype(np.float32))
                        weis.append(w.astype(np.float32))
                        azs.append(az_f[start:end].astype(np.float32))
                        els.append(el_f[start:end].astype(np.float32))
                        fids.append(np.full(n, fid, np.int32))
            used.append(filename)
        except (OSError, KeyError) as err:
            logger.warning("BAD FILE %s (%s)", filename, err)
    if not tods:
        z = np.zeros(0, np.float32)
        return LegacyLevel2Data(z, z, z, z, np.zeros(0, np.int32), [])
    return LegacyLevel2Data(
        np.concatenate(tods), np.concatenate(weis), np.concatenate(azs),
        np.concatenate(els), np.concatenate(fids), used)
