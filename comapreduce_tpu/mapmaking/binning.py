"""TOD <-> map binning: the pointing matrix as gather / segment_sum.

The reference's Cython scatter-add kernels ``Tools/binFuncs.pyx``
(``binValues`` :7-32, ``binValues2Map`` :35-46) are the innermost map-making
ops. On TPU they are one primitive each:

- ``P^T w d`` (TOD -> map accumulate) = ``jax.ops.segment_sum``;
- ``P m`` (map -> TOD sample)         = ``m[pixels]`` gather.

Invalid samples are encoded as pixel id ``npix`` and dropped by
``mode="drop"``-equivalent masking (the reference masks with a separate
array, ``binFuncs.pyx:20-23``). All functions are jittable; inside
``shard_map`` pass ``axis_name`` so shard-local maps are ``psum``-reduced
(the reference's MPI ``Gather+sum+Bcast``, ``Destriper.py:183-204``).

``npix`` may be a plain segment count or a
:class:`~comapreduce_tpu.mapmaking.pixel_space.PixelSpace`: a compacted
space sizes every map vector here to ``n_compact`` (hit pixels), never
the sky — the caller remaps the pointing once per plan
(``PixelSpace.remap``) and scatters back to the sky only at write time.
Each public entry sanitizes the pixel stream ONCE and shares it across
its internal segment sums (``bin_map`` -> weights -> hits used to
re-sanitize per product — pure waste on every matvec).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from comapreduce_tpu.mapmaking.pixel_space import PixelSpace, resolve_npix

__all__ = ["bin_map", "bin_offset_map", "sample_map", "accumulate_weights",
           "naive_map", "PixelSpace", "resolve_npix"]


def _psum(x, axis_name):
    return jax.lax.psum(x, axis_name) if axis_name is not None else x


def _sanitize(pixels: jax.Array, npix: int) -> jax.Array:
    """Map every invalid id (negative — e.g. WCS.ang2pix's -1 — or >= npix)
    to the drop slot ``npix`` so P and P^T agree on validity."""
    return jnp.where((pixels < 0) | (pixels >= npix), npix, pixels)


def _segment(values: jax.Array, pixels_sane: jax.Array, npix: int,
             axis_name) -> jax.Array:
    """One psum-reduced segment_sum over an ALREADY-sanitized stream —
    the shared inner op, so multi-product entry points sanitize once."""
    return _psum(jax.ops.segment_sum(
        values, pixels_sane, num_segments=npix, indices_are_sorted=False),
        axis_name)


def accumulate_weights(pixels: jax.Array, weights: jax.Array, npix,
                       axis_name: str | None = None) -> jax.Array:
    """``sum_w[p] = sum_{t: pix_t=p} w_t`` — the map-domain weight vector."""
    n = resolve_npix(npix)
    return _segment(weights, _sanitize(pixels, n), n, axis_name)


def bin_map(tod: jax.Array, pixels: jax.Array, weights: jax.Array, npix,
            sum_w: jax.Array | None = None,
            axis_name: str | None = None) -> jax.Array:
    """Weighted naive map: ``m = (P^T W d) / (P^T W 1)``.

    ``pixels`` is i32[N]; invalid samples (negative or >= npix) drop out of
    the segment_sum. Returns f32[npix]; unhit pixels are 0 (the reference
    leaves NaN after dividing by a zero hit count; masks compose better).
    """
    n = resolve_npix(npix)
    return _bin_map_sane(tod, _sanitize(pixels, n), weights, n,
                         sum_w=sum_w, axis_name=axis_name)


def _bin_map_sane(tod, pixels_sane, weights, npix: int, sum_w, axis_name):
    wsum = _segment(tod * weights, pixels_sane, npix, axis_name)
    if sum_w is None:
        sum_w = _segment(weights, pixels_sane, npix, axis_name)
    return jnp.where(sum_w > 0, wsum / jnp.maximum(sum_w, 1e-30), 0.0)


def bin_offset_map(offsets: jax.Array, pixels: jax.Array, weights: jax.Array,
                   npix, offset_length: int,
                   sum_w: jax.Array | None = None,
                   axis_name: str | None = None) -> jax.Array:
    """Map of the stretched offset vector (``binValues2Map`` analogue).

    ``offsets``: f32[n_offsets]; sample t belongs to offset ``t // L``
    (``OffsetTypes.py:11-54``). Computed as ``bin_map(repeat(offsets, L))``;
    XLA fuses the repeat into the scatter, so it is never a separate buffer.
    """
    n = pixels.shape[0]
    tod = jnp.repeat(offsets, offset_length, total_repeat_length=n)
    return bin_map(tod, pixels, weights, npix, sum_w=sum_w,
                   axis_name=axis_name)


def sample_map(m: jax.Array, pixels: jax.Array) -> jax.Array:
    """``(P m)_t = m[pix_t]`` with invalid pixels reading 0."""
    npix = m.shape[-1]
    valid = (pixels >= 0) & (pixels < npix)
    safe = jnp.clip(pixels, 0, npix - 1)
    return jnp.where(valid, m[..., safe], 0.0)


def naive_map(tod: jax.Array, pixels: jax.Array, weights: jax.Array,
              npix, axis_name: str | None = None,
              sum_w: jax.Array | None = None):
    """(signal, weight, hit) maps in one pass — the reference's
    ``destriper_iteration`` products (``Destriper.py:402-453``).

    The pixel stream is sanitized ONCE and shared by all three segment
    sums (weights, signal, hits)."""
    n = resolve_npix(npix)
    pixels = _sanitize(pixels, n)
    if sum_w is None:
        sum_w = _segment(weights, pixels, n, axis_name)
    m = _bin_map_sane(tod, pixels, weights, n, sum_w=sum_w,
                      axis_name=axis_name)
    hits = _segment(jnp.ones_like(weights), pixels, n, axis_name)
    return m, sum_w, hits
