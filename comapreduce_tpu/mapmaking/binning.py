"""TOD <-> map binning: the pointing matrix as gather / segment_sum.

The reference's Cython scatter-add kernels ``Tools/binFuncs.pyx``
(``binValues`` :7-32, ``binValues2Map`` :35-46) are the innermost map-making
ops. On TPU they are one primitive each:

- ``P^T w d`` (TOD -> map accumulate) = ``jax.ops.segment_sum``;
- ``P m`` (map -> TOD sample)         = ``m[pixels]`` gather.

Invalid samples are encoded as pixel id ``npix`` and dropped by
``mode="drop"``-equivalent masking (the reference masks with a separate
array, ``binFuncs.pyx:20-23``). All functions are jittable; inside
``shard_map`` pass ``axis_name`` so shard-local maps are ``psum``-reduced
(the reference's MPI ``Gather+sum+Bcast``, ``Destriper.py:183-204``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["bin_map", "bin_offset_map", "sample_map", "accumulate_weights",
           "naive_map"]


def _psum(x, axis_name):
    return jax.lax.psum(x, axis_name) if axis_name is not None else x


def _sanitize(pixels: jax.Array, npix: int) -> jax.Array:
    """Map every invalid id (negative — e.g. WCS.ang2pix's -1 — or >= npix)
    to the drop slot ``npix`` so P and P^T agree on validity."""
    return jnp.where((pixels < 0) | (pixels >= npix), npix, pixels)


def accumulate_weights(pixels: jax.Array, weights: jax.Array, npix: int,
                       axis_name: str | None = None) -> jax.Array:
    """``sum_w[p] = sum_{t: pix_t=p} w_t`` — the map-domain weight vector."""
    pixels = _sanitize(pixels, npix)
    return _psum(jax.ops.segment_sum(
        weights, pixels, num_segments=npix, indices_are_sorted=False), axis_name)


def bin_map(tod: jax.Array, pixels: jax.Array, weights: jax.Array, npix: int,
            sum_w: jax.Array | None = None,
            axis_name: str | None = None) -> jax.Array:
    """Weighted naive map: ``m = (P^T W d) / (P^T W 1)``.

    ``pixels`` is i32[N]; invalid samples (negative or >= npix) drop out of
    the segment_sum. Returns f32[npix]; unhit pixels are 0 (the reference
    leaves NaN after dividing by a zero hit count; masks compose better).
    """
    pixels = _sanitize(pixels, npix)
    wsum = jax.ops.segment_sum(tod * weights, pixels, num_segments=npix)
    wsum = _psum(wsum, axis_name)
    if sum_w is None:
        sum_w = accumulate_weights(pixels, weights, npix, axis_name)
    return jnp.where(sum_w > 0, wsum / jnp.maximum(sum_w, 1e-30), 0.0)


def bin_offset_map(offsets: jax.Array, pixels: jax.Array, weights: jax.Array,
                   npix: int, offset_length: int,
                   sum_w: jax.Array | None = None,
                   axis_name: str | None = None) -> jax.Array:
    """Map of the stretched offset vector (``binValues2Map`` analogue).

    ``offsets``: f32[n_offsets]; sample t belongs to offset ``t // L``
    (``OffsetTypes.py:11-54``). Computed as ``bin_map(repeat(offsets, L))``;
    XLA fuses the repeat into the scatter, so it is never a separate buffer.
    """
    n = pixels.shape[0]
    tod = jnp.repeat(offsets, offset_length, total_repeat_length=n)
    return bin_map(tod, pixels, weights, npix, sum_w=sum_w,
                   axis_name=axis_name)


def sample_map(m: jax.Array, pixels: jax.Array) -> jax.Array:
    """``(P m)_t = m[pix_t]`` with invalid pixels reading 0."""
    npix = m.shape[-1]
    valid = (pixels >= 0) & (pixels < npix)
    safe = jnp.clip(pixels, 0, npix - 1)
    return jnp.where(valid, m[..., safe], 0.0)


def naive_map(tod: jax.Array, pixels: jax.Array, weights: jax.Array,
              npix: int, axis_name: str | None = None,
              sum_w: jax.Array | None = None):
    """(signal, weight, hit) maps in one pass — the reference's
    ``destriper_iteration`` products (``Destriper.py:402-453``)."""
    if sum_w is None:
        sum_w = accumulate_weights(pixels, weights, npix, axis_name)
    m = bin_map(tod, pixels, weights, npix, sum_w=sum_w, axis_name=axis_name)
    hits = _psum(jax.ops.segment_sum(jnp.ones_like(weights),
                                     _sanitize(pixels, npix),
                                     num_segments=npix), axis_name)
    return m, sum_w, hits
