"""Polarized (I, Q, U) destriping.

Parity target: the reference's polarization self-test path
(``MapMaking/Destriper.py:617-753`` ``testpol``), where each sample
carries a ``special_weight`` pair (cos 2chi, sin 2chi) and the map solve
becomes a per-pixel 3x3 system:

    d_t = I[p_t] + Q[p_t] cos(2 psi_t) + U[p_t] sin(2 psi_t) + (F a)_t + n_t

TPU-native formulation: the six unique entries of ``A_p = sum_t w s s^T``
(``s = [1, cos 2psi, sin 2psi]``) and the three of ``b_p = sum_t w d s``
are nine ``segment_sum``s; the per-pixel solves are one batched 3x3
``linalg.solve`` (MXU-friendly). The destriper CG is the same operator
chain as the unpolarized solver with ``Z`` replaced by its polarized
version; offsets remain per-sample scalars.

Pixels with insufficient angle diversity are rank-deficient; they get a
Tikhonov floor and are masked in the returned condition map.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from comapreduce_tpu.mapmaking.binning import _sanitize

__all__ = ["PolMapState", "pol_map_solve", "destripe_pol",
           "PolDestriperResult"]


class PolMapState(NamedTuple):
    """Per-pixel normal-equation pieces for the IQU solve."""

    ata: jax.Array   # f32[npix, 3, 3]
    hits: jax.Array  # f32[npix]
    rcond_ok: jax.Array  # bool[npix] — pixel solvable


class PolDestriperResult(NamedTuple):
    offsets: jax.Array        # f32[n_offsets]
    iqu_destriped: jax.Array  # f32[npix, 3]
    iqu_naive: jax.Array      # f32[npix, 3]
    hit_map: jax.Array        # f32[npix]
    solvable: jax.Array       # bool[npix]
    n_iter: jax.Array
    residual: jax.Array


def _stokes_basis(c2, s2):
    """s_t = [1, cos 2psi, sin 2psi] stacked (N, 3)."""
    one = jnp.ones_like(c2)
    return jnp.stack([one, c2, s2], axis=-1)


def _pol_accumulate(pixels, weights, c2, s2, npix, axis_name):
    s = _stokes_basis(c2, s2)                       # (N, 3)
    outer = s[:, :, None] * s[:, None, :]           # (N, 3, 3)
    w_outer = outer * weights[:, None, None]
    pix = _sanitize(pixels, npix)
    ata = jax.ops.segment_sum(w_outer, pix, num_segments=npix)
    hits = jax.ops.segment_sum(jnp.ones_like(weights) * (weights > 0),
                               pix, num_segments=npix)
    if axis_name is not None:
        ata = jax.lax.psum(ata, axis_name)
        hits = jax.lax.psum(hits, axis_name)
    # solvable: enough angle diversity that A is well conditioned.
    # Normalise by the trace BEFORE the determinant — weights can be huge
    # (1/sigma^2) and det(A) ~ w^3 overflows f32.
    trace = jnp.trace(ata, axis1=-2, axis2=-1)
    scale = jnp.maximum(trace / 3.0, 1e-30)
    det_n = jnp.linalg.det(ata / scale[:, None, None])
    rcond_ok = (hits >= 3) & (det_n > 1e-6)
    return PolMapState(ata, hits, rcond_ok)


def pol_map_solve(d, pixels, weights, c2, s2, npix, state: PolMapState,
                  axis_name=None):
    """Weighted IQU map: solve ``A_p m_p = b_p`` per pixel. f32[npix, 3]."""
    s = _stokes_basis(c2, s2)
    wd = (weights * d)[:, None] * s                 # (N, 3)
    pix = _sanitize(pixels, npix)
    b = jax.ops.segment_sum(wd, pix, num_segments=npix)
    if axis_name is not None:
        b = jax.lax.psum(b, axis_name)
    eye = jnp.eye(3, dtype=d.dtype)
    # Tikhonov floor scaled to each pixel's weight magnitude
    scale = jnp.maximum(jnp.trace(state.ata, axis1=-2, axis2=-1) / 3.0,
                        1e-30)
    a_reg = state.ata + (1e-6 * scale)[:, None, None] * eye
    m = jnp.linalg.solve(a_reg, b[..., None])[..., 0]
    return jnp.where(state.rcond_ok[:, None], m, 0.0)


def destripe_pol(tod, pixels, weights, psi, npix: int,
                 offset_length: int = 50, n_iter: int = 100,
                 threshold: float = 1e-6, axis_name: str | None = None
                 ) -> PolDestriperResult:
    """Destripe a polarized TOD. ``psi``: f32[N] polarization/parallactic
    angle [rad]. Same contract as :func:`destriper.destripe` otherwise."""
    n = tod.shape[0]
    n_offsets = n // offset_length
    c2 = jnp.cos(2.0 * psi)
    s2 = jnp.sin(2.0 * psi)
    state = _pol_accumulate(pixels, weights, c2, s2, npix, axis_name)
    s_basis = _stokes_basis(c2, s2)

    def sample_iqu(m):
        safe = jnp.clip(pixels, 0, npix - 1)
        valid = ((pixels >= 0) & (pixels < npix)
                 & state.rcond_ok[safe])
        proj = jnp.sum(m[safe] * s_basis, axis=-1)
        return jnp.where(valid, proj, 0.0)

    def Z(d):
        m = pol_map_solve(d, pixels, weights, c2, s2, npix, state,
                          axis_name)
        return weights * (d - sample_iqu(m))

    def FT(wr):
        return jnp.sum(wr.reshape(n_offsets, offset_length), axis=1)

    def matvec(a):
        d = jnp.repeat(a, offset_length, total_repeat_length=n)
        return FT(Z(d))

    def dot(x, y):
        v = jnp.sum(x * y)
        return jax.lax.psum(v, axis_name) if axis_name is not None else v

    b = FT(Z(tod))
    b_norm = dot(b, b)

    def cond(st):
        _, _, _, rz, k, done = st
        return ((k < n_iter) & ~done
                & (rz > threshold**2 * jnp.maximum(b_norm, 1e-30)))

    def body(st):
        x, r, p, rz, k, _ = st
        q = matvec(p)
        pq = dot(p, q)
        ok = jnp.isfinite(pq) & (pq > 0)
        alpha = jnp.where(ok, rz / jnp.where(ok, pq, 1.0), 0.0)
        x = jnp.where(ok, x + alpha * p, x)
        r_new = r - alpha * q
        rz_new = dot(r_new, r_new)
        ok = ok & jnp.isfinite(rz_new)
        beta = jnp.where(ok, rz_new / jnp.maximum(rz, 1e-30), 0.0)
        r = jnp.where(ok, r_new, r)
        p = jnp.where(ok, r + beta * p, p)
        rz = jnp.where(ok, rz_new, rz)
        return x, r, p, rz, k + 1, ~ok

    st0 = (jnp.zeros(n_offsets, tod.dtype), b, b, b_norm,
           jnp.asarray(0, jnp.int32), jnp.asarray(False))
    a, _, _, rz, k, _ = jax.lax.while_loop(cond, body, st0)

    # A constant offset vector is (near-)degenerate with the I map — the
    # Tikhonov floor in the map solve tips the balance so CG parks the
    # global mean in the offsets. Pin the offsets to zero mean (the
    # reference's maps carry the same convention: destriped maps are
    # defined up to a constant).
    tot = jnp.sum(a)
    cnt = jnp.asarray(n_offsets, tod.dtype)
    if axis_name is not None:
        tot = jax.lax.psum(tot, axis_name)
        cnt = jax.lax.psum(cnt, axis_name)
    a = a - tot / cnt

    template = jnp.repeat(a, offset_length, total_repeat_length=n)
    iqu_naive = pol_map_solve(tod, pixels, weights, c2, s2, npix, state,
                              axis_name)
    iqu_destriped = pol_map_solve(tod - template, pixels, weights, c2, s2,
                                  npix, state, axis_name)
    residual = jnp.sqrt(rz / jnp.maximum(b_norm, 1e-30))
    return PolDestriperResult(a, iqu_destriped, iqu_naive, state.hits,
                              state.rcond_ok, k, residual)


destripe_pol_jit = jax.jit(
    destripe_pol,
    static_argnames=("npix", "offset_length", "n_iter", "threshold",
                     "axis_name"))
